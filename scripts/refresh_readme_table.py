"""Regenerate the README bench table from bench_secondary.json.

Keeps the README's numbers artifact-backed by construction: the table
between the BENCH-TABLE markers is produced from the artifact, never
hand-edited. Also enforces the floor-or-lever discipline (ISSUE 7):
every rendered row must carry a ``floor`` block (or explicitly lack one,
``floor: {"na": ...}`` — the dpoverhead delta row); a record with NO
floor key predates the floor engine and is flagged as stale so the next
capture re-derives it. The trend column (ISSUE 15) renders ▲/▼/≈ with
% vs the previous same-backend capture from ``runs/perf_ledger.jsonl``,
tolerant of a missing or partial ledger (em-dash). Run after a bench
capture:
    python scripts/refresh_readme_table.py
"""

import importlib.util
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# one byte formatter shared with scripts/mem_report.py, loaded by FILE
# path: obs/memory.py is standalone-importable by design, so this script
# stays runnable without jax (the full package import would pull it in)
_spec = importlib.util.spec_from_file_location(
    "_dl4j_obs_memory_standalone",
    REPO / "deeplearning4j_tpu" / "obs" / "memory.py")
_mem = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mem)
_fmt_bytes = _mem.format_bytes
# trend cells (ISSUE 15) come from the perf ledger via obs/trend.py —
# same standalone-by-file-path discipline, tolerant of a missing ledger
_tspec = importlib.util.spec_from_file_location(
    "_dl4j_obs_trend_standalone",
    REPO / "deeplearning4j_tpu" / "obs" / "trend.py")
_trend = importlib.util.module_from_spec(_tspec)
_tspec.loader.exec_module(_trend)
BEGIN = "<!-- BENCH-TABLE BEGIN (scripts/refresh_readme_table.py) -->"
END = "<!-- BENCH-TABLE END -->"

_floor_warnings = []

# the ledger is read once; every cell filters it (missing/partial
# ledger → every cell is an em-dash, the table still renders)
_LEDGER = _trend.load_ledger()


def trend_col(name, rec):
    """▲/▼/≈ with % vs the previous same-backend capture of this row
    (ISSUE 15). Em-dash when the ledger is missing or holds fewer than
    two comparable captures."""
    backend = rec.get("backend") if isinstance(rec, dict) else None
    return _trend.trend_cell(name, backend, _LEDGER)


def floor_cell(label, rec):
    """'% of floor' column + stale-row flagging. Three cases:
    floor block with pct → the number (the row explains itself);
    explicit na → em-dash (the record SAYS why it has no floor);
    no floor key at all → pre-floor capture, flagged for re-capture."""
    fl = rec.get("floor") if isinstance(rec, dict) else None
    if fl is None:
        if "floor" not in rec:
            _floor_warnings.append(
                f"row {label!r}: pre-floor record (captured before the "
                "floor engine) — re-capture to get its roofline account")
            return "— *(pre-floor)*"
        return "—"
    if "pct_of_floor" in fl:
        res = {"compute": "MXU", "memory": "HBM"}.get(
            fl.get("binding_resource"), "?")
        return f"{100 * fl['pct_of_floor']:.0f}% ({res})"
    if "na" in fl:
        return "—"
    return "—"


def fmt_value(rec):
    v = rec.get("value")
    unit = rec.get("unit", "")
    if v is None:
        return "—"
    if "tokens" in unit:
        return f"{v / 1e3:,.0f}k tokens/s" if v < 1e6 else \
            f"{v / 1e6:.1f}M tokens/s"
    if "samples" in unit or "seq" in unit:
        return f"{v:,.0f} {'img' if 'ResNet' in rec.get('metric', '') else 'samples' if 'samples' in unit else 'seq'}/s"
    return f"{v:,.0f} {unit}"


def row(label, rec, extra="", name=None):
    if not isinstance(rec, dict) or rec.get("value") is None:
        return None
    mfu = rec.get("mfu")
    mfu_s = f"{mfu:.2f}" if isinstance(mfu, (int, float)) else "—"
    if rec.get("unstable"):
        extra += f" *(unstable: median of {rec.get('median_of_k')})*"
    if rec.get("bimodal") and rec.get("cluster_medians_ms"):
        lo, hi = rec["cluster_medians_ms"]
        extra += f" *(bimodal: {lo}/{hi} ms modes)*"
    return (f"| {label} | {fmt_value(rec)}{extra} | {mfu_s} "
            f"| {floor_cell(label, rec)} "
            f"| {trend_col(name, rec) if name else '—'} |")


INFERENCE_LABELS = {
    "inference_decode": "Transformer-LM decode (KV-cache, 8 slots, T=1024)",
    "inference_ttft_1024": "Time-to-first-token, T=1024 prefill",
    "inference_ttft_4096": "Time-to-first-token, T=4096 prefill",
    "inference_prefix_shared": "Warm TTFT, 64 req × shared 1024-token "
                               "prefix (CoW cache)",
    "inference_fleet": "Fleet goodput, Poisson burst, autoscaled "
                       "replicas",
    "inference_quant_kv": "int8 KV pages vs bf16, fidelity-gated "
                          "promotion race",
    "inference_spec_decode": "Speculative decode (draft-verify) vs "
                             "plain greedy",
    "inference_scoring": "SCORE workload: prefill-only per-token "
                         "logprobs, 8 × 512-token prompts",
    "inference_beam": "BEAM workload: width-4 beam search, CoW "
                      "page-shared beams",
    "inference_resnet_b1": "ResNet-50 batch-1 latency (ParallelInference)",
    "inference_bert_b1": "BERT-base batch-1 latency (ParallelInference)",
}


def waste_cell(rec):
    """The KV-waste column (ISSUE 14): measured waste ratio plus, for a
    block-paged serve, the concurrency bought back at the dense byte
    budget — the before (dense 96%) / after (page tails only) of the
    paged-KV PR, straight from the one-sha artifact."""
    m = rec.get("memory") if isinstance(rec, dict) else None
    if not isinstance(m, dict) or m.get("kv_waste_ratio") is None:
        return "—"
    cell = f"{100 * m['kv_waste_ratio']:.0f}%"
    paged = m.get("paged")
    if isinstance(paged, dict):
        cell += (f" (paged; {paged['concurrency_x']}× slots "
                 f"@ equal bytes)")
    else:
        cell += " (dense)"
    return cell


def mem_cell(rec):
    """The serving memory column (ISSUE 12): bytes per resident token
    from a real mixed-length serve, or peak bytes for rows without a
    KV cache. A record with no `memory` block predates the memory
    plane — em-dash, the floor-column precedent."""
    m = rec.get("memory") if isinstance(rec, dict) else None
    if not isinstance(m, dict) or "na" in m:
        return "—"
    parts = []
    if m.get("bytes_per_resident_token") is not None:
        parts.append(f"{_fmt_bytes(m['bytes_per_resident_token'])}/tok")
    if not parts and m.get("peak_bytes") is not None:
        # only an allocator-backed number is a measured PEAK; the
        # pytree fallback is a static lower bound (params only — no
        # activations/workspace) and must say so
        if m.get("source") == "memory_stats":
            parts.append(f"peak {_fmt_bytes(m['peak_bytes'])}")
        else:
            parts.append(f"≥{_fmt_bytes(m['peak_bytes'])} (pytree)")
    return "; ".join(parts) or "—"


def inference_row(name, rec):
    """One serving-plane table row: value + the row's own detail column
    (best-batch throughput for the latency rows, p99 where measured),
    the memory column (ISSUE 12), and an explicit capture flag — a
    CPU-derived value must SAY so in the README, the same contract the
    floor tables follow."""
    if not isinstance(rec, dict) or rec.get("value") is None:
        return None
    label = INFERENCE_LABELS.get(name, name)
    unit = rec.get("unit", "")
    if "bytes/token" in unit:
        val = f"{rec['value']:,.2f}× fewer KV bytes/token"
    elif "tokens" in unit:
        val = f"{rec['value']:,.1f} tokens/s"
    elif "goodput" in unit:
        val = f"{rec['value']:,.1f}% goodput"
    else:
        val = f"{rec['value']:,.1f} ms"
    details = []
    if rec.get("p99_ms") is not None:
        details.append(f"p99 {rec['p99_ms']:.1f} ms")
    if rec.get("best_batch") is not None:
        details.append(f"best batch {rec['best_batch']}: "
                       f"{rec['best_batch_throughput']:,.1f} samples/s")
    if rec.get("slots") is not None:
        details.append(f"{rec['slots']} decode slots")
    ab = rec.get("paged_kernel_ab")
    if isinstance(ab, dict) and ab.get("verdict"):
        # the paged-attention kernel-vs-gather race (ISSUE 17): the
        # verdict and measured ratio, so the README never implies the
        # kernel is live where the fidelity gate said otherwise
        sp = ab.get("speedup_kernel_over_gather")
        details.append(f"pallas paged-attn A/B: {ab['verdict']}"
                       + (f" ({sp}× vs gather)" if sp else ""))
    if rec.get("replicas_max") is not None:
        # the fleet row (ISSUE 18): p99s at target + the autoscaler's
        # replica span under the burst, straight from the episode dump
        slo = rec.get("slo") or {}
        if slo.get("ttft_p99_ms") is not None:
            details.append(f"p99 TTFT {slo['ttft_p99_ms']:,.0f} ms / "
                           f"ITL {slo.get('itl_p99_ms', 0):,.1f} ms")
        details.append(f"replicas {rec.get('replicas_min')}→"
                       f"{rec['replicas_max']} "
                       f"({rec.get('scale_ups', 0)} up, "
                       f"{rec.get('scale_downs', 0)} down)")
    if rec.get("kv_bytes_per_token") is not None:
        # the quant row (ISSUE 19): what each pool pays per token plus
        # the race's speed verdict — a CPU fallback_slower is recorded,
        # not hidden
        bpt = rec["kv_bytes_per_token"]
        details.append(f"{bpt['int8']} vs {bpt['bf16']} B/tok")
        details.append(f"int8 race: {rec.get('verdict')}"
                       + (f" ({rec['speedup_int8_over_bf16']}× vs bf16)"
                          if rec.get("speedup_int8_over_bf16") else ""))
        w = rec.get("weights")
        if isinstance(w, dict) and w.get("verdict"):
            details.append(f"int8 weights: {w['verdict']}")
    spec = rec.get("spec")
    if isinstance(spec, dict) and spec.get("accepted_per_step") is not None:
        # the spec-decode row (ISSUE 19): tokens per verify dispatch +
        # bit-identity, the --min-accept gate's own numbers
        details.append(f"{spec['accepted_per_step']:.2f} accepted/step "
                       f"(k={rec.get('k')})")
        if rec.get("speedup_vs_plain"):
            details.append(f"{rec['speedup_vs_plain']}× vs plain "
                           f"({rec.get('best_arm')} draft)")
        details.append("greedy bit-identical"
                       if spec.get("bit_identical")
                       else "⚠ greedy divergence")
    if rec.get("perplexity_head") is not None:
        # the SCORE row (ISSUE 20): prefill-only scoring retires at the
        # final chunk — surface the wave count so the number reads as a
        # pipeline throughput, not a single pass
        details.append(f"{rec.get('requests')} × "
                       f"{rec.get('prompt_tokens')} tok/wave, "
                       f"{rec.get('reps')} waves")
    if rec.get("beam_gain_nats") is not None:
        # the BEAM row (ISSUE 20): the search-quality gain over greedy
        # and the page census proving the beams share the prompt
        details.append(f"+{rec['beam_gain_nats']:.3f} nats vs greedy "
                       f"(width {rec.get('beam_width')})")
        if rec.get("census_shared_pages") is not None:
            details.append(f"{rec['census_shared_pages']} shared / "
                           f"{rec['census_mapped_pages']} mapped pages")
    if rec.get("ttft_speedup_x") is not None:
        # the CoW prefix-cache row (ISSUE 16): warm-vs-cold TTFT and
        # tokens each user actually keeps resident when the prefix is
        # counted once
        details.append(f"{rec['ttft_speedup_x']}× vs no sharing "
                       f"(cold {rec['ttft_no_sharing_ms']:,.0f} ms)")
        if rec.get("tokens_resident_per_user_shared") is not None:
            details.append(
                f"{rec['tokens_resident_per_user_shared']:,.0f} "
                f"tok/user resident vs "
                f"{rec['tokens_resident_per_user_dense']:,.0f} unshared")
    captured = ("on-chip" if rec.get("backend") == "tpu"
                else "⏳ CPU-derived, on-chip TODO")
    return (f"| {label} | {val} | {'; '.join(details) or '—'} "
            f"| {waste_cell(rec)} | {mem_cell(rec)} "
            f"| {trend_col(name, rec)} | {captured} |")


def inference_lines(inf):
    """Render the artifact's `inference` section (ISSUE 10). Absent
    section → no serving table (pre-serving artifact)."""
    rows = [inference_row(n, inf.get(n)) for n in INFERENCE_LABELS]
    rows = [r for r in rows if r]
    if not rows:
        return []
    return ["",
            "**Serving / inference** (`inference` section of the same "
            "artifact; rows marked ⏳ await their on-chip capture — "
            "`bench.py --refresh inference_decode,...`). CPU-derived "
            "values drift with host performance between sessions "
            "(sandbox CPU is not a stable reference) — compare them "
            "only against their own floor/memory evidence, not across "
            "captures:",
            "",
            "| config | value | detail | KV waste | memory | trend "
            "| captured |",
            "|---|---|---|---|---|---|---|"] + rows


def main():
    art = json.loads((REPO / "bench_secondary.json").read_text())
    head = art.get("headline", {})
    sec = art.get("secondary", {})
    if head.get("backend_unavailable") or not head.get("value"):
        print("headline missing/unavailable — README left untouched")
        return 1
    sha = head.get("git_sha", "?")
    date = str(head.get("captured_at", ""))[:10]
    # The provenance sentence must be true by construction: if any row was
    # captured at a different sha than the headline, say so.
    shas = {r.get("git_sha") for r in sec.values()
            if isinstance(r, dict) and r.get("git_sha")} | {sha}
    sha_note = f"`{sha}`" if len(shas) == 1 else \
        "shas " + ", ".join(f"`{s}`" for s in sorted(shas)) + \
        " (per-row `git_sha` in the artifact)"
    lines = [BEGIN,
             f"Current single-chip (v5e) numbers — captured {date} on the "
             f"real chip at {sha_note}; every row is generated from "
             "`bench_secondary.json` by `scripts/refresh_readme_table.py` "
             "(each record carries `captured_at` + `git_sha` + "
             "`backend: tpu`):",
             "",
             "| config | throughput | MFU | % of floor | trend |",
             "|---|---|---|---|---|"]
    vsb = head.get("vs_baseline")
    rows = [
        row("ResNet-50 **real `fit(DataSetIterator)`**, bf16, batch 128",
            head, extra=f" ({vsb}× the 360 img/s V100 baseline)"
            if vsb else "", name="resnet50"),
        row("ResNet-50 `fit_scanned` (one dispatch/epoch)",
            sec.get("resnet50_fitscan"), name="resnet50_fitscan"),
        row("ResNet-50 raw train step", sec.get("resnet50_rawstep"),
            name="resnet50_rawstep"),
        row("BERT-base fine-tune, T=128", sec.get("bert"), name="bert"),
        row("Transformer-LM 120M, T=1024 (flash + save-attn remat, b32)",
            sec.get("transformer"), name="transformer"),
        row("Transformer-LM long context, T=4096 (flash attention)",
            sec.get("transformer_long"), name="transformer_long"),
        row("Transformer-LM extra-long context, T=8192 (flash, remat-off)",
            sec.get("transformer_xlong"), name="transformer_xlong"),
        row("GravesLSTM char-RNN, bf16", sec.get("charnn"),
            name="charnn"),
        row("GravesLSTM char-RNN, f32 (delta record)",
            sec.get("charnn_f32"), name="charnn_f32"),
        row("LeNet MNIST, bf16", sec.get("lenet"), name="lenet"),
        row("LeNet MNIST, `fit_scanned` (scan-dispatch)",
            sec.get("lenet_scan"), name="lenet_scan"),
    ]
    lines += [r for r in rows if r]
    dp = sec.get("dpoverhead", {})
    if isinstance(dp, dict) and dp.get("value") is not None:
        lines.append(f"| dp-8 ParallelWrapper overhead (virtual CPU mesh) "
                     f"| +{dp['value']:.1f} ms/step at equal global batch "
                     f"| — | {floor_cell('dpoverhead', dp)} "
                     f"| {trend_col('dpoverhead', dp)} |")
    lines += inference_lines(art.get("inference", {}))
    if _floor_warnings:
        lines.append("")
        lines.append("*(rows marked pre-floor predate the roofline "
                     "accounting — re-capture to fill the floor column)*")
    lines.append(END)

    readme = REPO / "README.md"
    t = readme.read_text()
    if BEGIN in t:
        pre = t[:t.index(BEGIN)]
        post = t[t.index(END) + len(END):]
        t = pre + "\n".join(lines) + post
    else:
        print("no BENCH-TABLE markers in README — add them first")
        return 1
    readme.write_text(t)
    for w in _floor_warnings:
        print(f"WARNING: {w}", file=sys.stderr)
    print(f"README table refreshed from artifact at {sha}"
          + (f" ({len(_floor_warnings)} pre-floor row(s) flagged)"
             if _floor_warnings else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
