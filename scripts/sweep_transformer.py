"""Transformer-LM perf sweep on the real chip (VERDICT r2 item 2 runbook).

Usage: python scripts/sweep_transformer.py [phase]
  phase 1 — fused-loss on/off + remat policies at T=1024 (find best base)
  phase 2 — batch sweep on the best base config
  phase 3 — flash-vs-XLA attention crossover table over T
Each record is MFU-audited via the bench harness. Writes
scripts/sweep_transformer_out.json (appending per phase).
"""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax.numpy as jnp  # noqa: E402

import bench  # noqa: E402
from deeplearning4j_tpu.zoo import transformer as tfm  # noqa: E402

OUT = pathlib.Path(__file__).with_name("sweep_transformer_out.json")


def run(tag, cfg, batch, steps=11):
    run_chain, flops = bench.build_transformer(batch, cfg)
    timing = bench.measure_marginal(run_chain, n1=3, n2=steps)
    rec = bench._record(tag, "tokens/sec/chip", batch * cfg.max_seq, timing,
                        flops, batch=batch, seq=cfg.max_seq)
    print(tag, "->", rec["value"], "tok/s  mfu", rec["mfu"],
          "step", rec["step_time_ms"], flush=True)
    results = json.loads(OUT.read_text()) if OUT.exists() else []
    results.append(rec)
    OUT.write_text(json.dumps(results, indent=2))
    return rec


def base_cfg(**kw):
    d = dict(vocab_size=32000, d_model=512, n_heads=8, n_layers=8,
             d_ff=2048, max_seq=1024, dtype=jnp.bfloat16, remat=False,
             fused_loss=False)
    d.update(kw)
    return tfm.TransformerConfig(**d)


def phase1():
    run("t1024 b16 naive-loss remat-off", base_cfg(), 16)
    run("t1024 b16 fused-loss remat-off", base_cfg(fused_loss=True), 16)
    run("t1024 b16 fused-loss chunk2048",
        base_cfg(fused_loss=True, loss_chunk=2048), 16)
    run("t1024 b16 fused-loss bf16-scores",
        base_cfg(fused_loss=True, attn_scores_bf16=True), 16)
    run("t1024 b16 fused-loss flash-forced",
        base_cfg(fused_loss=True, use_flash_attention=True), 16)
    run("t1024 b16 fused-loss remat-dots",
        base_cfg(fused_loss=True, remat=True, remat_policy="dots"), 16)
    run("t1024 b16 fused-loss remat-full",
        base_cfg(fused_loss=True, remat=True, remat_policy="full"), 16)


def phase2():
    for b in (8, 24, 32):
        run(f"t1024 b{b} fused-loss", base_cfg(fused_loss=True), b)


def phase3():
    for t in (1024, 2048, 4096):
        toks = 16 * 1024
        b = max(1, toks // t)
        for attn, tag in ((False, "xla"), (True, "flash")):
            try:
                run(f"t{t} b{b} fused {tag}-attn",
                    base_cfg(max_seq=t, fused_loss=True,
                             use_flash_attention=attn,
                             remat=(t >= 4096), remat_policy="dots"), b)
            except Exception as e:  # noqa: BLE001 — record and continue
                print(f"t{t} {tag}: FAILED {type(e).__name__}: {e}",
                      flush=True)


def phase4():
    """r4: combine the two phase-1/3 winners (remat-full 0.3046, bf16-scores
    0.2527) and settle the T=4096 long-context config with an XLA-vs-flash
    comparison under the same remat policy."""
    best = dict(fused_loss=True, remat=True, remat_policy="full",
                attn_scores_bf16=True)
    run("t1024 b16 remat-full+bf16-scores", base_cfg(**best), 16)
    run("t1024 b16 remat-full+bf16-scores chunk2048",
        base_cfg(**best, loss_chunk=2048), 16)
    for b in (32, 64):
        try:
            run(f"t1024 b{b} remat-full+bf16-scores", base_cfg(**best), b)
        except Exception as e:  # noqa: BLE001
            print(f"b{b}: FAILED {type(e).__name__}: {e}", flush=True)
    # r5 NOTE: the r4 version of this comparison left use_flash_attention
    # at its "auto" default (flash_min_seq=2048), so at T=4096 ALL THREE
    # tags ran the flash kernel — the 0.0575≈0.0568 "tie" the r4 verdict
    # flagged was the same program measured twice. Force the path OFF for
    # the xla/bf16-scores tags so the comparison is real.
    for tag, kw in (("xla", {"use_flash_attention": False,
                             "attn_scores_bf16": False}),
                    ("bf16-scores", {"use_flash_attention": False,
                                     "attn_scores_bf16": True}),
                    ("flash", {"use_flash_attention": True})):
        try:
            run(f"t4096 b4 remat-full {tag}",
                base_cfg(max_seq=4096, fused_loss=True, remat=True,
                         remat_policy="full", **kw), 4)
        except Exception as e:  # noqa: BLE001
            print(f"t4096 {tag}: FAILED {type(e).__name__}: {e}",
                  flush=True)


if __name__ == "__main__":
    phase = sys.argv[1] if len(sys.argv) > 1 else "1"
    ok, detail = bench.wait_for_backend(max_wait_s=120)
    if not ok:
        print(json.dumps({"backend_unavailable": True, "detail": detail}))
        sys.exit(0)
    {"1": phase1, "2": phase2, "3": phase3, "4": phase4}[phase]()
