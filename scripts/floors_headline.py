#!/usr/bin/env python
"""Derive the four headline-model roofline floors on CPU (ISSUE 7).

Builds each headline bench config at its REAL benched shapes, derives
HLO flops/bytes via the floor engine (XLA cost_analysis on the CPU
lowering; estimator fallback), and combines them with the v5e peak table
plus the last on-chip measured step time from bench_secondary.json into
the floor tables docs/PERF.md quotes. Writes
``scripts/floors_headline_out.json``.

Caveats recorded in the output (and PERF.md):
- flops/bytes come from the CPU lowering: XLA:TPU fuses differently, so
  the HBM-floor is an upper bound on the chip's true traffic (the
  ResNet case measured ~12% below it — docs/PERF.md roofline section).
- the transformer configs run flash attention ON CHIP only; the CPU
  lowering takes the XLA attention path, so attention bytes here
  reflect the XLA path while the benched program streams scores through
  VMEM. On-chip cost_analysis (TODO next capture) replaces both.

Run: JAX_PLATFORMS=cpu python scripts/floors_headline.py  (~minutes:
ResNet-50 b128 + two 120M-param compiles on CPU)
"""

import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def headline_configs():
    import jax.numpy as jnp

    import bench
    from deeplearning4j_tpu.zoo import transformer as tfm

    def resnet():
        return bench.build_resnet50(128)[0]

    def transformer():
        cfg = tfm.TransformerConfig(
            vocab_size=32000, d_model=512, n_heads=8, n_layers=8,
            d_ff=2048, max_seq=1024, dtype=jnp.bfloat16, fused_loss=True,
            remat=True, remat_policy="save_attn", attn_scores_bf16=True)
        return bench.build_transformer(32, cfg)[0]

    def bert():
        cfg = tfm.BertConfig(max_seq=128, remat=True, attn_scores_bf16=True)
        return bench.build_bert(128, cfg)[0]

    def charnn():
        return bench.build_charnn(256)[0]

    return {                       # name -> (builder, dtype, artifact row)
        "resnet50": (resnet, "bf16", "headline"),
        "transformer": (transformer, "bf16", "transformer"),
        "bert": (bert, "bf16", "bert"),
        "charnn": (charnn, "bf16", "charnn"),
    }


def measured_step_ms(artifact, row):
    if row == "headline":
        rec = artifact.get("headline", {})
    else:
        rec = artifact.get("secondary", {}).get(row, {})
    if isinstance(rec, dict) and rec.get("backend") == "tpu" and \
            rec.get("timing_valid", True):
        return rec.get("step_time_ms"), rec.get("git_sha")
    return None, None


def main():
    from deeplearning4j_tpu.obs import floors
    artifact = json.loads((REPO / "bench_secondary.json").read_text())
    out = {"derived_on": "cpu lowering (see module docstring caveats)",
           "peaks": floors.PEAKS["tpu"], "configs": {}}
    for name, (build, dtype, row) in headline_configs().items():
        t0 = time.perf_counter()
        print(f"[floors] {name}: building + compiling on CPU...",
              file=sys.stderr, flush=True)
        try:
            run_chain = build()
            costs = run_chain.floor_probe()
            step_ms, sha = measured_step_ms(artifact, row)
            block = floors.floor_block(costs, step_ms=step_ms,
                                       dtype=dtype, backend="tpu")
            block["measured_step_ms_onchip"] = step_ms
            block["measured_sha"] = sha
            out["configs"][name] = block
            print(f"[floors] {name}: {block.get('floor_ms')} ms floor "
                  f"({block.get('binding_resource')}-bound, "
                  f"{time.perf_counter() - t0:.0f}s)", file=sys.stderr,
                  flush=True)
        except Exception as e:  # noqa: BLE001 — record, keep going
            out["configs"][name] = {"na": f"{type(e).__name__}: {e}"[:300]}
            print(f"[floors] {name} FAILED: {e}", file=sys.stderr,
                  flush=True)
    path = REPO / "scripts" / "floors_headline_out.json"
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
