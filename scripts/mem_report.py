#!/usr/bin/env python
"""Render a memory attribution / KV-waste table from a serving
flight-recorder JSONL (ISSUE 12 tooling — the offline half of
``GET /debug/memory``).

A flight-recorder dump now carries three memory-plane record kinds:

- ``memcensus`` — component attribution at dump time (params /
  kv_cache / ... bytes, plus the allocator view where the backend had
  ``memory_stats``);
- ``snapshot`` — the per-step KV residency timeline
  (``kv_allocated_bytes`` / ``kv_resident_bytes`` / ``kv_waste_ratio``
  beside the slot map every step already recorded);
- ``reqtrace`` — per-request timelines whose ``finish`` event carries
  ``residency_ratio`` (how much of its fixed slot the request ever
  used).

This script aggregates all three into a per-replica table: attribution,
mean/max resident bytes, mean waste ratio, bytes-per-resident-token,
and the final-residency distribution — the numbers that size the
paged-KV PR (ROADMAP item 1) and prove the ZeRO memory drop (item 4).
Torn trailing lines are tolerated (``load_spans`` discipline).

    python scripts/mem_report.py runs/serving_blackbox.jsonl
    python scripts/mem_report.py dump.jsonl --max-waste 0.9 --json

Exit code: 0, or 1 when ``--max-waste`` is given and any replica's mean
KV waste ratio exceeds it — a post-run gate, like slo_report's. The
mean is byte-weighted (1 - Σresident/Σallocated over the snapshot
window) so it gates correctly on BOTH layouts: dense slotting, where
allocated bytes are the static ``slots × max_len`` pool, and the
block-paged pool (ISSUE 14), where allocated bytes are the MAPPED
pages of each snapshot and the only reservable waste is unfilled page
tails (paged snapshots carry ``kv_mapped_pages`` / ``kv_page_len`` /
``kv_pool_bytes`` alongside).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from deeplearning4j_tpu.obs import load_flight_records  # noqa: E402
from deeplearning4j_tpu.obs.memory import format_bytes as _fmt_bytes  # noqa: E402


def _fmt_pct(v) -> str:
    return "-" if v is None else f"{100 * float(v):.1f}%"


def build_report(records) -> dict:
    """Replica -> aggregated memory evidence from one dump's records."""
    out: dict = {}

    def rep(replica):
        return out.setdefault(str(replica), {
            "census": None, "snapshots": 0, "kv_allocated_bytes": None,
            "kv_token_bytes": None, "resident_sum": 0.0,
            "resident_max": 0, "alloc_sum": 0.0, "alloc_max": 0,
            "paged": False, "kv_page_len": None, "kv_pool_bytes": None,
            "mapped_pages_max": 0,
            "prefix": False, "shared_pages_max": 0, "cached_pages_max": 0,
            "prefix_hits": 0, "prefix_hit_tokens": 0, "cow_copies": 0,
            "final_residency": [], "requests": 0})

    def _better_census(old, new):
        """A serving census (it carries kv_cache) beats a training one
        for a serving postmortem; within one source, newest ts wins —
        dump file order is alphabetical by (source, replica), not
        chronological, so it must not decide."""
        if old is None:
            return new
        old_s = old.get("source") == "serving"
        new_s = new.get("source") == "serving"
        if old_s != new_s:
            return new if new_s else old
        return new if new.get("ts", 0) >= old.get("ts", 0) else old

    for r in records:
        kind = r.get("kind")
        if kind == "memcensus":
            d = rep(r.get("replica", "0"))
            d["census"] = _better_census(d["census"], r)
        elif kind == "snapshot" and "kv_resident_bytes" in r:
            d = rep(r.get("replica", "0"))
            d["snapshots"] += 1
            d["kv_allocated_bytes"] = r.get("kv_allocated_bytes")
            d["kv_token_bytes"] = r.get("kv_token_bytes")
            res = float(r.get("kv_resident_bytes", 0))
            d["resident_sum"] += res
            d["resident_max"] = max(d["resident_max"], res)
            # allocated bytes are STATIC under dense slotting but track
            # the mapped pages under paging (ISSUE 14) — accumulate per
            # snapshot so mean waste can be byte-weighted
            alloc = float(r.get("kv_allocated_bytes") or 0)
            d["alloc_sum"] += alloc
            d["alloc_max"] = max(d["alloc_max"], alloc)
            if "kv_mapped_pages" in r:          # paged-pool snapshot
                d["paged"] = True
                d["kv_page_len"] = r.get("kv_page_len")
                d["kv_pool_bytes"] = r.get("kv_pool_bytes")
                d["mapped_pages_max"] = max(
                    d["mapped_pages_max"], int(r["kv_mapped_pages"] or 0))
            if "kv_shared_pages" in r:      # CoW prefix census (ISSUE 16)
                d["prefix"] = True
                d["shared_pages_max"] = max(
                    d["shared_pages_max"], int(r["kv_shared_pages"] or 0))
                d["cached_pages_max"] = max(
                    d["cached_pages_max"], int(r["kv_cached_pages"] or 0))
                # counters are monotonic; max() tolerates out-of-order
                # dump lines the same way the torn-tail discipline does
                d["prefix_hits"] = max(
                    d["prefix_hits"], int(r.get("kv_prefix_hits_total")
                                          or 0))
                d["prefix_hit_tokens"] = max(
                    d["prefix_hit_tokens"],
                    int(r.get("kv_prefix_hit_tokens_total") or 0))
                d["cow_copies"] = max(
                    d["cow_copies"], int(r.get("kv_cow_copies_total")
                                         or 0))
        elif kind == "reqtrace":
            d = rep(r.get("replica", "0"))
            d["requests"] += 1
            for name, _, attrs in reversed(r.get("events") or []):
                if name == "finish" and "residency_ratio" in attrs:
                    d["final_residency"].append(
                        float(attrs["residency_ratio"]))
                    break
    for d in out.values():
        # pop the accumulators unconditionally: a zero-snapshot replica
        # (pre-memory-plane dump) must not leak them into the report
        n = d.pop("snapshots")
        resident_sum = d.pop("resident_sum")
        resident_max = d.pop("resident_max")
        alloc_sum = d.pop("alloc_sum")
        alloc_max = d.pop("alloc_max")
        d["n_snapshots"] = n
        d["resident_bytes_mean"] = resident_sum / n if n else None
        d["resident_bytes_max"] = resident_max if n else None
        d["allocated_bytes_mean"] = alloc_sum / n if n else None
        d["allocated_bytes_max"] = alloc_max if n else None
        # mean waste is byte-weighted: 1 - Σresident/Σallocated. Under
        # dense slotting (allocated constant) this equals the old
        # mean-of-ratios; under paging (ISSUE 14: allocated = mapped
        # pages, varies per snapshot) it weights each snapshot by the
        # bytes it actually reserved — idle zero-alloc snapshots can no
        # longer dilute (or a transient spike dominate) the --max-waste
        # gate
        d["waste_ratio_mean"] = (1.0 - resident_sum / alloc_sum
                                 if alloc_sum else None) if n else None
        fr = d.pop("final_residency")
        d["final_residency_mean"] = sum(fr) / len(fr) if fr else None
        d["final_residency_n"] = len(fr)
        census = d["census"]
        total = None
        if census:
            # total footprint: the allocator's peak where the backend
            # had one, else the census pytree total
            peak = (census.get("device") or {}).get("peak_bytes_in_use")
            total = peak or census.get("component_bytes", {}).get("total")
        d["total_bytes"] = total
        # bytes the pool pays per mean resident token — the efficiency
        # number paged KV / quantized caches must push down
        d["bytes_per_resident_token"] = None
        if total and d["resident_bytes_mean"] and d["kv_token_bytes"]:
            tokens = d["resident_bytes_mean"] / d["kv_token_bytes"]
            if tokens:
                d["bytes_per_resident_token"] = round(total / tokens, 1)
    return out


def render(report) -> str:
    lines = []
    for replica, d in sorted(report.items()):
        lines.append(f"replica {replica}:")
        census = d.get("census")
        if census:
            lines.append(f"  attribution (census, "
                         f"source={census.get('source')}, "
                         f"device={census.get('device_source')}):")
            for comp, nbytes in sorted(
                    census.get("component_bytes", {}).items()):
                lines.append(f"    {comp:<12} {_fmt_bytes(nbytes):>12}")
            dev = census.get("device")
            if dev:
                lines.append(
                    f"    device: in_use={_fmt_bytes(dev.get('bytes_in_use'))} "
                    f"peak={_fmt_bytes(dev.get('peak_bytes_in_use'))} "
                    f"limit={_fmt_bytes(dev.get('bytes_limit'))}")
        else:
            lines.append("  (no census record in dump)")
        if d.get("n_snapshots"):
            if d.get("paged"):
                alloc_txt = (
                    f"allocated (mapped pages) mean "
                    f"{_fmt_bytes(d['allocated_bytes_mean'])} / max "
                    f"{_fmt_bytes(d['allocated_bytes_max'])} of a "
                    f"{_fmt_bytes(d['kv_pool_bytes'])} pool "
                    f"(page_len={d['kv_page_len']}, "
                    f"mapped max {d['mapped_pages_max']} pages)")
            else:
                alloc_txt = f"allocated {_fmt_bytes(d['kv_allocated_bytes'])}"
            lines.append(
                f"  KV residency over {d['n_snapshots']} snapshots: "
                f"{alloc_txt}, "
                f"resident mean {_fmt_bytes(d['resident_bytes_mean'])} "
                f"/ max {_fmt_bytes(d['resident_bytes_max'])}, "
                f"waste mean {_fmt_pct(d['waste_ratio_mean'])}")
            if d.get("prefix"):
                lines.append(
                    f"  prefix sharing: shared max "
                    f"{d['shared_pages_max']} / cached max "
                    f"{d['cached_pages_max']} pages, "
                    f"{d['prefix_hits']} hits "
                    f"({d['prefix_hit_tokens']} prompt tokens skipped), "
                    f"{d['cow_copies']} CoW copies")
            if d.get("bytes_per_resident_token"):
                lines.append(
                    f"  bytes per resident token: "
                    f"{_fmt_bytes(d['bytes_per_resident_token'])} "
                    f"(total {_fmt_bytes(d['total_bytes'])} over mean "
                    "residency)")
        else:
            lines.append("  (no KV residency snapshots in dump)")
        if d.get("final_residency_n"):
            denom = "mapped pages" if d.get("paged") else "max_len"
            lines.append(
                f"  requests: {d['requests']} traced, "
                f"{d['final_residency_n']} finished — final residency "
                f"mean {_fmt_pct(d['final_residency_mean'])} of {denom}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="memory attribution / KV-waste table from a "
                    "flight-recorder JSONL")
    ap.add_argument("dump", help="flight-recorder JSONL path")
    ap.add_argument("--max-waste", type=float, default=None,
                    help="gate: exit 1 if any replica's mean KV waste "
                         "ratio exceeds this")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report dict as JSON")
    args = ap.parse_args(argv)

    records = load_flight_records(args.dump)
    if not records:
        print(f"mem_report: no flight-recorder records in {args.dump}",
              file=sys.stderr)
        return 1
    report = build_report(records)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    if args.max_waste is not None:
        for replica, d in report.items():
            w = d.get("waste_ratio_mean")
            if w is not None and w > args.max_waste:
                print(f"mem_report: replica {replica} mean KV waste "
                      f"{w:.3f} > gate {args.max_waste}",
                      file=sys.stderr)
                return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
