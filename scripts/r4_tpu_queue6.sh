#!/bin/bash
cd "$(dirname "$0")/.." || exit 1
while pgrep -f "sweep_transformer.py 3" > /dev/null; do sleep 20; done
: > /tmp/r4_queue6.log
for i in 1 2 3; do
  echo "=== [charnnAB] attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue6.log
  if python scripts/diag_charnn.py >> /tmp/r4_queue6.log 2>&1 \
      && ! grep -q backend_unavailable /tmp/r4_queue6.log; then
    break
  fi
  sed -i 's/backend_unavailable/backend_was_unavailable/g' /tmp/r4_queue6.log
  sleep 90
done
echo "=== queue6 done $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue6.log
