#!/bin/bash
# Final r4 capture: waits for the perf queues, then runs the FULL bench
# (headline fit() + all secondaries) with the r4-tuned configs.
cd "$(dirname "$0")/.." || exit 1
while pgrep -f "sweep_transformer.py 3" > /dev/null; do sleep 30; done
while pgrep -f "diag_charnn.py" > /dev/null; do sleep 30; done
: > /tmp/r4_final.log
for i in 1 2 3 4; do
  echo "=== [fullbench] attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/r4_final.log
  python bench.py >> /tmp/r4_final.log 2>&1
  rc=$?
  if [ $rc -eq 0 ] && ! grep -q backend_unavailable /tmp/r4_final.log; then
    break
  fi
  sed -i 's/backend_unavailable/backend_was_unavailable/g' /tmp/r4_final.log
  sleep 180
done
echo "=== final done rc=$rc $(date -u +%H:%M:%S) ===" >> /tmp/r4_final.log
