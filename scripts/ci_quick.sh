#!/usr/bin/env bash
# Quick observability gate (ISSUE 7): metric-name + doc lint, then the
# telemetry-plane and roofline-floor suites. One command, <2 min on CPU;
# run before touching instrumentation, bench schema, or docs examples.
#
#   bash scripts/ci_quick.sh
#
# The full tier-1 suite is ROADMAP.md's verify line; this is the fast
# inner loop for the obs/bench surface only.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== metric-name + doc lint =="
python scripts/check_metric_names.py

echo "== obs + floors suites =="
JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py tests/test_floors.py \
    -q -m 'not slow' -p no:cacheprovider -p no:randomly

echo "ci_quick: all green"
