#!/usr/bin/env bash
# Quick gate (ISSUE 7 + 8): metric-name + doc lint, then the
# telemetry-plane, roofline-floor, and elastic-scaleout fast suites.
# One command, <2 min on CPU; run before touching instrumentation,
# bench schema, docs examples, or the scaleout plane.
#
#   bash scripts/ci_quick.sh
#
# The full tier-1 suite is ROADMAP.md's verify line; this is the fast
# inner loop for the obs/bench/scaleout surface only.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== metric-name + doc lint =="
python scripts/check_metric_names.py

echo "== obs + floors + scaleout-fast suites =="
JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py tests/test_floors.py \
    tests/test_scaleout_fast.py \
    -q -m 'not slow' -p no:cacheprovider -p no:randomly

echo "ci_quick: all green"
