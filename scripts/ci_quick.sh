#!/usr/bin/env bash
# Quick gate (ISSUE 7 + 8 + 10 + 11 + 12 + 13 + 14 + 15 + 16 + 17 +
# 18 + 19 + 20): metric-name/label + doc lint, the offline perf-regression
# gate over the bench ledger, then the telemetry-plane, roofline-floor,
# elastic-scaleout, serving-plane, paged-KV/chunked-prefill,
# prefix-cache/CoW, SLO-plane, memory/compile-plane,
# numerics/fidelity-plane, perf-trend, fleet-fabric, quantization, and
# speculative-decoding fast suites.
# One command, <4 min on CPU; run before touching instrumentation,
# bench schema, docs examples, the scaleout plane, the serving
# engine/scheduler, the paged KV pool / page table, the prefix cache /
# session API, the SLO/flight-recorder plane, the memory census /
# retrace sentinel, the numerics sentinel / drift audit / fidelity
# probes, the perf ledger / trend verdicts, the fleet
# router/autoscaler, or the quant/spec plane.
#
#   bash scripts/ci_quick.sh
#
# The full tier-1 suite is ROADMAP.md's verify line; this is the fast
# inner loop for the obs/bench/scaleout/serving surface only.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== metric-name + doc lint =="
python scripts/check_metric_names.py

echo "== perf regression gate (offline replay of runs/perf_ledger.jsonl) =="
python scripts/perf_gate.py --offline

echo "== obs + floors + scaleout-fast + serving + paged-kv + prefix-cache + slo + memplane + numerics + trend + fleet + quant + spec + workloads suites =="
JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py tests/test_floors.py \
    tests/test_scaleout_fast.py tests/test_serving.py \
    tests/test_paged_kv.py tests/test_prefix_cache.py \
    tests/test_paged_attention.py \
    tests/test_slo.py \
    tests/test_memplane.py tests/test_numerics.py \
    tests/test_trend.py tests/test_fleet_fast.py \
    tests/test_quant.py tests/test_spec_decode.py \
    tests/test_workloads.py \
    -q -m 'not slow' -p no:cacheprovider -p no:randomly

echo "== autotune harness round-trip (record -> sha-bump -> invalidate + re-measure) =="
JAX_PLATFORMS=cpu python - <<'EOF'
import tempfile
from pathlib import Path
import jax.numpy as jnp
from deeplearning4j_tpu.kernels import autotune as at

at._CACHE_PATH = Path(tempfile.mkdtemp()) / "autotune.json"
at._memory_cache.clear()
at.put("roundtrip:check", (1,), meta={"best_s": 1.0}, sha="aaaa")
assert at.choice("roundtrip:check", sha="aaaa") == (1,)
# sha bump: stale record dropped, lookup misses
assert at.lookup("roundtrip:check", sha="bbbb") is None
assert at.records(kind="roundtrip") == {}
# and the autotune() path re-measures instead of serving the old verdict
timed = []
def make_run(cand):
    def run():
        timed.append(cand)
        return jnp.zeros((1,))
    return run
got = at.autotune("roundtrip:check", [(1,), (2,)], make_run, sha="bbbb")
assert timed, "re-measure path not taken after sha bump"
assert at.records()["roundtrip:check"]["sha"] == "bbbb"
print("autotune harness round-trip OK")
EOF

echo "ci_quick: all green"
