#!/bin/bash
# Persistent round-4 TPU queue: block until the tunnel is healthy (up to
# ~4h, one gentle probe per 5 min), then run remat sweep -> flash
# crossover -> charnn A/B -> full bench. No timeout wrappers around the
# TPU jobs themselves (killing a TPU-attached process wedges the relay).
cd "$(dirname "$0")/.." || exit 1
LOG=/tmp/r4_queue8.log
: > "$LOG"
note() { echo "=== $1 $(date -u +%H:%M:%S) ===" >> "$LOG"; }

note "waiting for tunnel"
healthy=0
for i in $(seq 1 48); do
  if python - >> "$LOG" 2>&1 <<'PY'
import sys
sys.path.insert(0, ".")
import bench
ok, detail = bench.wait_for_backend(max_wait_s=100)
sys.exit(0 if ok else 1)
PY
  then healthy=1; break; fi
  sleep 300
done
if [ "$healthy" != 1 ]; then note "gave up waiting"; exit 1; fi
note "tunnel healthy"

run_step() {
  name=$1; shift
  for i in 1 2 3; do
    note "[$name] attempt $i"
    "$@" >> "$LOG" 2>&1
    if ! tail -5 "$LOG" | grep -q backend_unavailable; then
      note "[$name] done"; return 0
    fi
    sleep 240
  done
  note "[$name] gave up"
  return 1
}

run_step remat   python scripts/diag_resnet.py G H
run_step flash   python scripts/diag_flash.py bwd
run_step charnn  python scripts/diag_charnn.py
note "[bench] full capture"
python bench.py > /tmp/r4_bench_stdout.json 2>> "$LOG"
cat /tmp/r4_bench_stdout.json >> "$LOG"
note "queue8 done"
