#!/bin/bash
cd "$(dirname "$0")/.." || exit 1
: > /tmp/r4_queue4.log
for i in 1 2 3; do
  echo "=== [diagF] attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue4.log
  if python scripts/diag_resnet.py F >> /tmp/r4_queue4.log 2>&1 \
      && ! grep -q backend_unavailable /tmp/r4_queue4.log; then
    break
  fi
  sed -i 's/backend_unavailable/backend_was_unavailable/g' /tmp/r4_queue4.log
  sleep 90
done
echo "=== queue4 done $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue4.log
