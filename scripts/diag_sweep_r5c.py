"""Post-adjudication batch sweeps on the real chip (r5 session 2).

The scan-path charnn and the b128 BERT winner were adopted at the batch
sizes tuned for their PREDECESSOR configs — sweep one step further:
  - charnn bf16 scan at b512 / b1024 (b256 was tuned for the fused kernel)
  - BERT remat-full+bf16s at b256 (b128 was the sweep edge, 0.61 and rising)
  - T=8192 b2 flash save-attn at the benched-config settings (candidate
    extra-long-context README row; r5b measured 106.9k tokens/s)

Writes scripts/diag_sweep_r5c_out.json. One arm per process when the
result would decide a config (the shared-process bias lesson): this
script takes the arm name as argv.
"""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import bench  # noqa: E402

OUT = pathlib.Path(__file__).with_name("diag_sweep_r5c_out.json")


def emit(tag, **kw):
    rec = bench._stamp({"tag": tag, **kw})
    try:
        results = json.loads(OUT.read_text())
    except Exception:  # noqa: BLE001
        results = []
    results.append(rec)
    print(json.dumps(rec), flush=True)
    OUT.write_text(json.dumps(results, indent=2))


def charnn(batch):
    rec = bench.bench_charnn(batch, 25)
    emit(rec.pop("metric") + f" b{batch}", **rec)


def bert(batch):
    rec = bench.bench_bert(batch, 13)
    emit(rec.pop("metric") + f" b{batch}", **rec)


def t8192(batch):
    # one source of truth: measure the EXACT benched config
    rec = bench.bench_transformer_xlong(batch, 9)
    emit(rec.pop("metric") + f" b{batch}", **rec)


ARMS = {
    "charnn512": lambda: charnn(512),
    "charnn1024": lambda: charnn(1024),
    "bert256": lambda: bert(256),
    "t8192b2": lambda: t8192(2),
}

if __name__ == "__main__":
    ok, detail = bench.wait_for_backend(max_wait_s=120)
    if not ok:
        print(json.dumps({"backend_unavailable": True, "detail": detail}))
        sys.exit(0)
    for arm in sys.argv[1:]:
        ARMS[arm]()
