#!/bin/bash
cd "$(dirname "$0")/.." || exit 1
: > /tmp/r4_queue3.log
for i in 1 2 3; do
  echo "=== [sweep4] attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue3.log
  if python scripts/sweep_transformer.py 4 >> /tmp/r4_queue3.log 2>&1 \
      && ! grep -q backend_unavailable /tmp/r4_queue3.log; then
    break
  fi
  sed -i 's/backend_unavailable/backend_was_unavailable/g' /tmp/r4_queue3.log
  sleep 90
done
echo "=== queue3 done $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue3.log
