#!/bin/bash
# Serial TPU job queue for r4 perf work: survives tunnel flaps by letting
# each python job do its own wait_for_backend, and retries a job that
# reports backend_unavailable. One job at a time — the tunnel serves one
# client session well.
cd "$(dirname "$0")/.." || exit 1
run_retry() {  # run_retry <tag> <cmd...>
  tag=$1; shift
  for i in 1 2 3 4 5 6; do
    echo "=== [$tag] attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue.log
    if "$@" >> /tmp/r4_queue.log 2>&1 \
        && ! grep -q backend_unavailable /tmp/r4_queue.log; then
      return 0
    fi
    echo "=== [$tag] attempt $i failed (rc or backend) ===" >> /tmp/r4_queue.log
    sed -i 's/backend_unavailable/backend_was_unavailable/g' /tmp/r4_queue.log
    sleep 120
  done
  echo "=== [$tag] EXHAUSTED ===" >> /tmp/r4_queue.log
  return 1
}
: > /tmp/r4_queue.log
run_retry diagABD python scripts/diag_resnet.py A B D
run_retry sweep1 python scripts/sweep_transformer.py 1
run_retry sweep3 python scripts/sweep_transformer.py 3
run_retry sweep2 python scripts/sweep_transformer.py 2
echo "=== queue done $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue.log
