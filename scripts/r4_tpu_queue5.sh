#!/bin/bash
# waits for queue4, then re-measures long-context attention with the
# bf16-operand flash kernel (autotune re-runs under the flash2 key)
cd "$(dirname "$0")/.." || exit 1
while pgrep -f "diag_resnet.py F" > /dev/null; do sleep 20; done
: > /tmp/r4_queue5.log
for i in 1 2 3; do
  echo "=== [sweep3b] attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue5.log
  if python scripts/sweep_transformer.py 3 >> /tmp/r4_queue5.log 2>&1 \
      && ! grep -q backend_unavailable /tmp/r4_queue5.log; then
    break
  fi
  sed -i 's/backend_unavailable/backend_was_unavailable/g' /tmp/r4_queue5.log
  sleep 90
done
echo "=== queue5 done $(date -u +%H:%M:%S) ===" >> /tmp/r4_queue5.log
