"""BERT fine-tune composition sweep on the real chip (VERDICT r4 → r5 item 5).

The transformer-LM sweep's two HBM cuts (remat-full, bf16 score
materialization) applied to the BERT-base T=128 fine-tune step, which
last measured MFU 0.40 WITHOUT either. At T=128 the score tensor is
small (B32·H12·128² bf16 ≈ 12 MB/layer) so bf16-scores should matter
less than at T=1024 — the sweep says which levers pay here, and whether
remat frees enough HBM for a larger batch to win.

Writes scripts/diag_bert_out.json; if a composition beats the 0.40
record, flip bench.bench_bert's config to the winner and re-capture.
"""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import bench  # noqa: E402

OUT = pathlib.Path(__file__).with_name("diag_bert_out.json")
RESULTS = []


def emit(tag, **kw):
    rec = bench._stamp({"tag": tag, **kw})
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)
    OUT.write_text(json.dumps(RESULTS, indent=2))


def run(tag, batch, **cfg_kw):
    from deeplearning4j_tpu.zoo import transformer as tfm
    cfg = tfm.BertConfig(max_seq=128, **cfg_kw)
    try:
        run_chain, flops = bench.build_bert(batch, cfg)
        timing = bench.measure_marginal(run_chain, n1=3, n2=11)
        rec = bench._record(tag, "seq/sec/chip", batch, timing, flops,
                            batch=batch, seq=cfg.max_seq)
        emit(rec.pop("metric"), **rec)
    except Exception as e:  # noqa: BLE001
        emit(tag, error=f"{type(e).__name__}: {e}"[:300])


def main():
    run("bert b32 base (r4 record config)", 32)
    run("bert b32 bf16-scores", 32, attn_scores_bf16=True)
    run("bert b32 remat-full", 32, remat=True)
    run("bert b32 remat-full+bf16s", 32, remat=True, attn_scores_bf16=True)
    run("bert b32 remat-dots+bf16s", 32, remat=True, remat_policy="dots",
        attn_scores_bf16=True)
    run("bert b64 base", 64)
    run("bert b64 remat-full+bf16s", 64, remat=True, attn_scores_bf16=True)
    run("bert b128 remat-full+bf16s", 128, remat=True, attn_scores_bf16=True)


if __name__ == "__main__":
    ok, detail = bench.wait_for_backend(max_wait_s=120)
    if not ok:
        print(json.dumps({"backend_unavailable": True, "detail": detail}))
        sys.exit(0)
    main()
