#!/usr/bin/env python
"""Lint the telemetry instrumentation sites (ISSUE 6 tooling).

Greps every ``.counter("...") / .gauge("...") / .histogram("...")`` call
in the instrumented trees and fails on:

- metric names outside the registered ``dl4j_`` namespace,
- counter names not ending in ``_total`` (Prometheus convention the
  registry also enforces at runtime),
- names with invalid characters,
- duplicate registrations: the same name used as two different
  instrument kinds anywhere in the tree (the runtime raises on the
  second registration — this catches it statically, before a rarely-
  exercised code path does),
- label cardinality (ISSUE 11): label NAMES must come from the small
  ``ALLOWED_LABELS`` allowlist (extend it deliberately, in review —
  every new label multiplies series), and nothing that smells like a
  request/trace/span id may appear as a label name or be fed as a
  label value (``replica=req.id`` style) — per-request identity
  belongs in spans and flight-recorder records, not the registry.

Also lints the DOCS (ISSUE 7): every ``dl4j_``-prefixed token in
docs/*.md + README.md must be a name some instrumentation site actually
registers (wildcards like ``dl4j_bench_*`` must match ≥1 registered
name; Prometheus exposition suffixes ``_bucket/_sum/_count`` resolve to
their histogram) — so a doc example can never promise a metric the
registry doesn't serve.

Wired into the test suite as a fast unit test (tests/test_obs.py), so a
stray name fails CI, not a Grafana query. Run standalone:
``python scripts/check_metric_names.py``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

REPO = Path(__file__).resolve().parent.parent

# instrumented trees: the package + the bench/diag entry points.
# tests/ excluded on purpose — they register deliberately-bad names to
# assert the runtime rejects them.
SCAN = ["deeplearning4j_tpu", "bench.py", "scripts"]

# docs whose dl4j_ mentions must resolve to registered metric names
DOCS = ["docs", "README.md"]

# dl4j_-prefixed doc tokens that are NOT metrics (library/namespace
# mentions) — keep this list short and literal
DOC_NON_METRIC_TOKENS = {"dl4j_", "dl4j_*", "dl4j_tpu_native"}

_SITE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([^\"']+)[\"']")
_DOC_TOKEN = re.compile(r"dl4j_[a-zA-Z0-9_]*\*?")
_EXPO_SUFFIX = re.compile(r"_(bucket|sum|count)$")
_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
NAMESPACE = "dl4j_"

# -------- label-cardinality lint (ISSUE 11) --------
# Every label NAME any instrumentation site registers. Extending this
# is a deliberate act: each new label multiplies time series, and an
# unbounded one (request id, trace id) melts the registry.
ALLOWED_LABELS = {"backend", "component", "config", "direction", "kernel",
                  "kind", "layer", "level", "mode", "reason", "replica",
                  "row", "stat", "unit", "verdict"}
# per-prefix restriction (ISSUE 12/13): each observability plane may
# label ONLY from its own small fixed vocabulary — component names,
# stat kinds and probe-pair kinds are bounded sets, never per-request
# identity. A dl4j_mem_* gauge with a `reason` label (or a
# dl4j_fidelity_* gauge labeled by layer AND reason) is a design smell
# this catches before it ships.
PLANE_LABELS = {
    "dl4j_mem_": {"component", "replica"},
    "dl4j_kv_": {"component", "replica"},
    "dl4j_compile_": {"component", "replica"},
    # numerics & fidelity plane (ISSUE 13): layer/kind/replica only
    "dl4j_num_": {"kind", "layer", "replica"},
    "dl4j_fidelity_": {"kind", "layer", "replica"},
    "dl4j_replica_": {"replica"},
    # autotune harness (ISSUE 17): cache level, kernel kind, promotion
    # verdict, invalidation reason — all small fixed enums; the shape
    # bucket and sha stay in the cost-record key, never in a label
    "dl4j_autotune_": {"kernel", "level", "reason", "verdict"},
    # perf trend plane (ISSUE 15): the ledger key (row, backend) plus
    # the verdict enum — bench row names are a small fixed set; never
    # a sha, host fingerprint or capture id (those live in the ledger
    # records themselves)
    "dl4j_trend_": {"backend", "row", "verdict"},
    # fleet fabric (ISSUE 18): routing reason and scale direction are
    # tiny fixed enums; replica ids (r0, r1, ...) stay out of fleet
    # metric labels — per-replica series already exist on the
    # dl4j_serving_*/dl4j_slo_* planes under {replica=}
    "dl4j_fleet_": {"direction", "reason"},
    # quantization & speculation plane (ISSUE 19): storage/draft mode,
    # kernel kind and promotion verdict — all tiny fixed enums; shape
    # buckets and shas live in the autotune cost-record keys
    "dl4j_quant_": {"kernel", "mode", "verdict"},
    "dl4j_spec_": {"kernel", "mode", "verdict"},
    # multi-workload request plane (ISSUE 20): the RequestKind value
    # is the ONLY label — five fixed kinds, never per-request identity
    "dl4j_workload_": {"kind"},
}
# label names that smell like per-request/per-trace identity — never
# allowed even if someone adds them to the allowlist above by mistake
_ID_LABEL = re.compile(
    r"(^|_)(id|ids|uuid|request|requests|trace|span|session)(_|$)")
_LABELNAMES = re.compile(
    r"labelnames\s*=\s*[\(\[]\s*([^\)\]]*?)\s*[,\s]*[\)\]]")
_LABEL_LIT = re.compile(r"[\"']([^\"']+)[\"']")
# observation calls whose kwargs are label values: .inc/.set/.observe
_OBS_CALL = re.compile(r"\.(inc|set|observe)\(")
# a label VALUE expression that smuggles a request/trace id into the
# registry, e.g. `replica=req.id` / `reason=trace_id`
_ID_VALUE = re.compile(
    r"\b[a-z_]+\s*=\s*(?:str\(|f[\"'])?[^,()]*"
    r"\b(?:req(?:uest)?\.id|request_id|trace_id|span_id|\.trace_id\(\))")


def _files() -> List[Path]:
    out: List[Path] = []
    for entry in SCAN:
        p = REPO / entry
        if p.is_file():
            out.append(p)
        else:
            out.extend(sorted(f for f in p.rglob("*.py")
                              if "__pycache__" not in f.parts))
    return out


def _call_text(text: str, open_idx: int) -> str:
    """The argument text of a call: from the ``(`` at ``open_idx`` to
    its matching close paren. String-naive — adequate because help
    strings at these sites keep their parens balanced; a truncated
    match only makes the label lint conservative."""
    depth = 0
    for i in range(open_idx, len(text)):
        c = text[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return text[open_idx:i + 1]
    return text[open_idx:open_idx + 400]


def check(files=None) -> List[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    errors: List[str] = []
    kinds: Dict[str, Set[str]] = {}
    sites: Dict[str, List[str]] = {}
    for f in files or _files():
        if f.name == "check_metric_names.py":
            continue
        text = f.read_text()
        for m in _SITE.finditer(text):
            kind, name = m.group(1), m.group(2)
            try:
                shown = f.relative_to(REPO)
            except ValueError:   # explicit file list outside the repo
                shown = f
            where = f"{shown}:{text[:m.start()].count(chr(10)) + 1}"
            kinds.setdefault(name, set()).add(kind)
            sites.setdefault(name, []).append(where)
            if not _NAME_OK.match(name):
                errors.append(f"{where}: invalid metric name {name!r}")
            if not name.startswith(NAMESPACE):
                errors.append(f"{where}: {name!r} outside the registered "
                              f"{NAMESPACE} namespace")
            if kind == "counter" and not name.endswith("_total"):
                errors.append(f"{where}: counter {name!r} must end in "
                              "'_total'")
            args = _call_text(text, text.find("(", m.start()))
            lm = _LABELNAMES.search(args)
            for lab in (_LABEL_LIT.findall(lm.group(1)) if lm else ()):
                if _ID_LABEL.search(lab):
                    errors.append(
                        f"{where}: label {lab!r} on {name!r} looks like "
                        "a request/trace id — per-request identity "
                        "belongs in spans / flight-recorder records, "
                        "not metric labels")
                elif lab not in ALLOWED_LABELS:
                    errors.append(
                        f"{where}: label {lab!r} on {name!r} not in the "
                        f"allowlist {sorted(ALLOWED_LABELS)} — extend "
                        "ALLOWED_LABELS deliberately if this is a real "
                        "low-cardinality label")
                else:
                    for prefix, allowed in PLANE_LABELS.items():
                        if name.startswith(prefix) and lab not in allowed:
                            errors.append(
                                f"{where}: label {lab!r} on {name!r} — "
                                f"the {prefix}* plane restricts labels "
                                f"to {sorted(allowed)}")
        # label VALUES: an id smuggled into .inc/.set/.observe kwargs
        for m in _OBS_CALL.finditer(text):
            args = _call_text(text, text.find("(", m.start()))
            v = _ID_VALUE.search(args)
            if v:
                where = f"{f.relative_to(REPO) if f.is_relative_to(REPO) else f}" \
                        f":{text[:m.start()].count(chr(10)) + 1}"
                errors.append(
                    f"{where}: {v.group(0).strip()!r} feeds a "
                    "request/trace id as a metric label value — "
                    "unbounded cardinality; put it in a span or "
                    "flight-recorder record instead")
    for name, ks in sorted(kinds.items()):
        if len(ks) > 1:
            errors.append(
                f"duplicate registration of {name!r} as {sorted(ks)} "
                f"at {', '.join(sites[name])}")
    if files is None:     # full-tree run: docs must match the registry
        errors.extend(check_docs(set(kinds)))
    return errors


def _doc_files() -> List[Path]:
    out: List[Path] = []
    for entry in DOCS:
        p = REPO / entry
        if p.is_file():
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(p.glob("*.md")))
    return out


def check_docs(known: Set[str], doc_files=None) -> List[str]:
    """Every dl4j_ token a doc promises must resolve to a registered
    instrumentation-site name (wildcard prefix / exposition suffix
    aware). Returns human-readable violations."""
    errors: List[str] = []
    for f in doc_files or _doc_files():
        text = f.read_text()
        for m in _DOC_TOKEN.finditer(text):
            tok = m.group(0)
            if tok in DOC_NON_METRIC_TOKENS:
                continue
            where = f"{f.relative_to(REPO) if f.is_relative_to(REPO) else f}" \
                    f":{text[:m.start()].count(chr(10)) + 1}"
            if tok.endswith("*"):
                prefix = tok[:-1]
                if not any(n.startswith(prefix) for n in known):
                    errors.append(
                        f"{where}: doc wildcard {tok!r} matches no "
                        "registered metric")
                continue
            base = _EXPO_SUFFIX.sub("", tok)
            if tok not in known and base not in known:
                errors.append(
                    f"{where}: doc mentions unregistered metric {tok!r} "
                    "(no .counter/.gauge/.histogram site registers it)")
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(e, file=sys.stderr)
    n_names = len({m.group(2) for f in _files()
                   if f.name != "check_metric_names.py"
                   for m in _SITE.finditer(f.read_text())})
    n_doc = sum(len(_DOC_TOKEN.findall(f.read_text()))
                for f in _doc_files())
    print(f"check_metric_names: {n_names} metric names scanned, "
          f"{n_doc} doc mention(s) checked, {len(errors)} violation(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
