#!/usr/bin/env python
"""Lint the telemetry instrumentation sites (ISSUE 6 tooling).

Greps every ``.counter("...") / .gauge("...") / .histogram("...")`` call
in the instrumented trees and fails on:

- metric names outside the registered ``dl4j_`` namespace,
- counter names not ending in ``_total`` (Prometheus convention the
  registry also enforces at runtime),
- names with invalid characters,
- duplicate registrations: the same name used as two different
  instrument kinds anywhere in the tree (the runtime raises on the
  second registration — this catches it statically, before a rarely-
  exercised code path does).

Wired into the test suite as a fast unit test (tests/test_obs.py), so a
stray name fails CI, not a Grafana query. Run standalone:
``python scripts/check_metric_names.py``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

REPO = Path(__file__).resolve().parent.parent

# instrumented trees: the package + the bench/diag entry points.
# tests/ excluded on purpose — they register deliberately-bad names to
# assert the runtime rejects them.
SCAN = ["deeplearning4j_tpu", "bench.py", "scripts"]

_SITE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([^\"']+)[\"']")
_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
NAMESPACE = "dl4j_"


def _files() -> List[Path]:
    out: List[Path] = []
    for entry in SCAN:
        p = REPO / entry
        if p.is_file():
            out.append(p)
        else:
            out.extend(sorted(f for f in p.rglob("*.py")
                              if "__pycache__" not in f.parts))
    return out


def check(files=None) -> List[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    errors: List[str] = []
    kinds: Dict[str, Set[str]] = {}
    sites: Dict[str, List[str]] = {}
    for f in files or _files():
        if f.name == "check_metric_names.py":
            continue
        text = f.read_text()
        for m in _SITE.finditer(text):
            kind, name = m.group(1), m.group(2)
            try:
                shown = f.relative_to(REPO)
            except ValueError:   # explicit file list outside the repo
                shown = f
            where = f"{shown}:{text[:m.start()].count(chr(10)) + 1}"
            kinds.setdefault(name, set()).add(kind)
            sites.setdefault(name, []).append(where)
            if not _NAME_OK.match(name):
                errors.append(f"{where}: invalid metric name {name!r}")
            if not name.startswith(NAMESPACE):
                errors.append(f"{where}: {name!r} outside the registered "
                              f"{NAMESPACE} namespace")
            if kind == "counter" and not name.endswith("_total"):
                errors.append(f"{where}: counter {name!r} must end in "
                              "'_total'")
    for name, ks in sorted(kinds.items()):
        if len(ks) > 1:
            errors.append(
                f"duplicate registration of {name!r} as {sorted(ks)} "
                f"at {', '.join(sites[name])}")
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(e, file=sys.stderr)
    n_names = len({m.group(2) for f in _files()
                   if f.name != "check_metric_names.py"
                   for m in _SITE.finditer(f.read_text())})
    print(f"check_metric_names: {n_names} metric names scanned, "
          f"{len(errors)} violation(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
