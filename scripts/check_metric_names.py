#!/usr/bin/env python
"""Lint the telemetry instrumentation sites (ISSUE 6 tooling).

Greps every ``.counter("...") / .gauge("...") / .histogram("...")`` call
in the instrumented trees and fails on:

- metric names outside the registered ``dl4j_`` namespace,
- counter names not ending in ``_total`` (Prometheus convention the
  registry also enforces at runtime),
- names with invalid characters,
- duplicate registrations: the same name used as two different
  instrument kinds anywhere in the tree (the runtime raises on the
  second registration — this catches it statically, before a rarely-
  exercised code path does).

Also lints the DOCS (ISSUE 7): every ``dl4j_``-prefixed token in
docs/*.md + README.md must be a name some instrumentation site actually
registers (wildcards like ``dl4j_bench_*`` must match ≥1 registered
name; Prometheus exposition suffixes ``_bucket/_sum/_count`` resolve to
their histogram) — so a doc example can never promise a metric the
registry doesn't serve.

Wired into the test suite as a fast unit test (tests/test_obs.py), so a
stray name fails CI, not a Grafana query. Run standalone:
``python scripts/check_metric_names.py``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

REPO = Path(__file__).resolve().parent.parent

# instrumented trees: the package + the bench/diag entry points.
# tests/ excluded on purpose — they register deliberately-bad names to
# assert the runtime rejects them.
SCAN = ["deeplearning4j_tpu", "bench.py", "scripts"]

# docs whose dl4j_ mentions must resolve to registered metric names
DOCS = ["docs", "README.md"]

# dl4j_-prefixed doc tokens that are NOT metrics (library/namespace
# mentions) — keep this list short and literal
DOC_NON_METRIC_TOKENS = {"dl4j_", "dl4j_*", "dl4j_tpu_native"}

_SITE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([^\"']+)[\"']")
_DOC_TOKEN = re.compile(r"dl4j_[a-zA-Z0-9_]*\*?")
_EXPO_SUFFIX = re.compile(r"_(bucket|sum|count)$")
_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
NAMESPACE = "dl4j_"


def _files() -> List[Path]:
    out: List[Path] = []
    for entry in SCAN:
        p = REPO / entry
        if p.is_file():
            out.append(p)
        else:
            out.extend(sorted(f for f in p.rglob("*.py")
                              if "__pycache__" not in f.parts))
    return out


def check(files=None) -> List[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    errors: List[str] = []
    kinds: Dict[str, Set[str]] = {}
    sites: Dict[str, List[str]] = {}
    for f in files or _files():
        if f.name == "check_metric_names.py":
            continue
        text = f.read_text()
        for m in _SITE.finditer(text):
            kind, name = m.group(1), m.group(2)
            try:
                shown = f.relative_to(REPO)
            except ValueError:   # explicit file list outside the repo
                shown = f
            where = f"{shown}:{text[:m.start()].count(chr(10)) + 1}"
            kinds.setdefault(name, set()).add(kind)
            sites.setdefault(name, []).append(where)
            if not _NAME_OK.match(name):
                errors.append(f"{where}: invalid metric name {name!r}")
            if not name.startswith(NAMESPACE):
                errors.append(f"{where}: {name!r} outside the registered "
                              f"{NAMESPACE} namespace")
            if kind == "counter" and not name.endswith("_total"):
                errors.append(f"{where}: counter {name!r} must end in "
                              "'_total'")
    for name, ks in sorted(kinds.items()):
        if len(ks) > 1:
            errors.append(
                f"duplicate registration of {name!r} as {sorted(ks)} "
                f"at {', '.join(sites[name])}")
    if files is None:     # full-tree run: docs must match the registry
        errors.extend(check_docs(set(kinds)))
    return errors


def _doc_files() -> List[Path]:
    out: List[Path] = []
    for entry in DOCS:
        p = REPO / entry
        if p.is_file():
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(p.glob("*.md")))
    return out


def check_docs(known: Set[str], doc_files=None) -> List[str]:
    """Every dl4j_ token a doc promises must resolve to a registered
    instrumentation-site name (wildcard prefix / exposition suffix
    aware). Returns human-readable violations."""
    errors: List[str] = []
    for f in doc_files or _doc_files():
        text = f.read_text()
        for m in _DOC_TOKEN.finditer(text):
            tok = m.group(0)
            if tok in DOC_NON_METRIC_TOKENS:
                continue
            where = f"{f.relative_to(REPO) if f.is_relative_to(REPO) else f}" \
                    f":{text[:m.start()].count(chr(10)) + 1}"
            if tok.endswith("*"):
                prefix = tok[:-1]
                if not any(n.startswith(prefix) for n in known):
                    errors.append(
                        f"{where}: doc wildcard {tok!r} matches no "
                        "registered metric")
                continue
            base = _EXPO_SUFFIX.sub("", tok)
            if tok not in known and base not in known:
                errors.append(
                    f"{where}: doc mentions unregistered metric {tok!r} "
                    "(no .counter/.gauge/.histogram site registers it)")
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(e, file=sys.stderr)
    n_names = len({m.group(2) for f in _files()
                   if f.name != "check_metric_names.py"
                   for m in _SITE.finditer(f.read_text())})
    n_doc = sum(len(_DOC_TOKEN.findall(f.read_text()))
                for f in _doc_files())
    print(f"check_metric_names: {n_names} metric names scanned, "
          f"{n_doc} doc mention(s) checked, {len(errors)} violation(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
