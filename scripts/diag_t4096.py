"""T=4096 perf-cliff diagnosis on the real chip (VERDICT r4 → r5 item 2).

The mystery: `t4096 b4 remat-full` runs 5.17 TFLOP/step in ~462 ms
(MFU 0.057) while `t1024 b16` runs MORE flops (6.27 TFLOP) in ~86 ms
(MFU 0.37) — same tokens/step, and the number is identical across the
xla / bf16-scores / flash attention paths, so the attention *kernel*
is not the differentiator. This script decomposes the step:

  A. full train step at t1024 b16 and t4096 b4 (benched baselines)
  B. same steps with attention REPLACED BY IDENTITY — everything-but-
     attention (embeddings, ffn, norms, loss head, optimizer, remat
     recompute of all of those). If B(t4096) ≈ B(t1024), the cliff is
     inside attention despite "all paths equal"; if B alone shows the
     cliff, attention was never the problem.
  C. forward-only loss (no grad/optimizer) — backward-specific cost.
  D. remat policy variants at t4096 (full / dots / dots_no_batch / off)
     — is it the *recompute* of the T² scores in backward (remat-full
     recomputes attention once per grad pass) rather than attention
     itself?
  E. XLA's own opinion: compiled cost_analysis (flops, bytes accessed)
     and memory_analysis (peak HBM) for both configs — if bytes/step
     explains 462 ms at 819 GB/s, it's traffic; if not, serialization.

Writes scripts/diag_t4096_out.json incrementally.
"""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import bench  # noqa: E402

OUT = pathlib.Path(__file__).with_name("diag_t4096_out.json")
RESULTS = []


def emit(tag, **kw):
    rec = bench._stamp({"tag": tag, **kw})
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)
    OUT.write_text(json.dumps(RESULTS, indent=2))


def cfg_for(seq, **kw):
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo import transformer as tfm
    d = dict(vocab_size=32000, d_model=512, n_heads=8, n_layers=8,
             d_ff=2048, max_seq=seq, dtype=jnp.bfloat16, fused_loss=True,
             remat=True, remat_policy="full", attn_scores_bf16=True)
    d.update(kw)
    return tfm.TransformerConfig(**d)


def step_time(tag, cfg, batch, steps=9):
    run_chain, flops = bench.build_transformer(batch, cfg)
    timing = bench.measure_marginal(run_chain, n1=3, n2=steps)
    rec = bench._record(tag, "tokens/sec/chip", batch * cfg.max_seq,
                        timing, flops, batch=batch, seq=cfg.max_seq)
    emit(rec.pop("metric"), **rec)
    return rec


def no_attention(tag, cfg, batch):
    """Full train step with _attention monkeypatched to identity."""
    from deeplearning4j_tpu.zoo import transformer as tfm
    real = tfm._attention

    def identity_attn(cfg_, q, k, v, mask_bias=None):
        return q

    tfm._attention = identity_attn
    try:
        step_time(tag, cfg, batch)
    finally:
        tfm._attention = real


def forward_only(tag, cfg, batch):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.utils.tracing import total_flops
    from deeplearning4j_tpu.zoo import transformer as tfm

    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))

    def fwd(params, bump):
        return tfm.lm_loss(params, cfg, ids, tgt) + bump

    jf = jax.jit(fwd)
    flops = total_flops(fwd, params, 0.0)

    def step_once(bump):
        loss = jf(params, bump)
        return (loss * 0.0,), loss

    run_chain = bench.chain_runner(step_once, [jnp.float32(0.0)])
    timing = bench.measure_marginal(run_chain, n1=3, n2=9)
    rec = bench._record(tag, "tokens/sec/chip", batch * cfg.max_seq,
                        timing, flops, batch=batch, seq=cfg.max_seq)
    emit(rec.pop("metric"), **rec)


def xla_opinion(tag, cfg, batch):
    """Compiled cost_analysis + memory_analysis for the full train step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from deeplearning4j_tpu.zoo import transformer as tfm

    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    raw_step = tfm.make_train_step(cfg, opt)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    out = {}
    try:
        compiled = jax.jit(raw_step, donate_argnums=(0, 1)).lower(
            params, opt_state, ids, tgt).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for k in ("flops", "bytes accessed", "optimal_seconds",
                  "bytes accessed output", "bytes accessed operand 0 {}"):
            if ca and k in ca:
                out[k.replace(" ", "_")] = float(ca[k])
        if ca:
            ba = float(ca.get("bytes accessed", 0.0))
            out["hbm_floor_ms_at_819GBs"] = round(ba / 819e9 * 1e3, 2)
        try:
            ma = compiled.memory_analysis()
            for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                         "output_size_in_bytes", "generated_code_size_in_bytes"):
                v = getattr(ma, attr, None)
                if v is not None:
                    out[attr] = int(v)
        except Exception as e:  # noqa: BLE001
            out["memory_analysis_error"] = str(e)[:200]
    except Exception as e:  # noqa: BLE001
        out["error"] = f"{type(e).__name__}: {e}"[:300]
    emit(tag, **out)


def block_sweep(tag_prefix, t, b, h=8, d=64):
    """Phase F: attention-only fwd+bwd time vs flash block size — the
    direct test of the grid-overhead theory (steps = (B·H)(T/bq)(T/bk);
    if per-step overhead dominates, time ~ 1/(bq·bk) until VMEM/MXU
    effects take over)."""
    import time

    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.kernels.flash_attention import (
        _flash_attention_pallas)

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, h, t, d), jnp.bfloat16)
    for bq, bk in ((128, 128), (256, 256), (512, 512), (1024, 512),
                   (512, 1024), (1024, 1024), (2048, 1024), (1024, 2048),
                   (2048, 2048), (512, 4096), (1024, 4096), (4096, 1024)):
        if t % bq or t % bk:
            continue
        try:
            def loss(q_, k_, v_, _bq=bq, _bk=bk):
                return jnp.sum(_flash_attention_pallas(
                    q_, k_, v_, None, True, _bq, _bk, False
                ).astype(jnp.float32))

            jfn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            out = jfn(q, q, q)
            float(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])
            n1, n2 = 2, 8
            t0 = time.perf_counter()
            for _ in range(n1):
                out = jfn(q, q, q)
            float(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])
            t1 = time.perf_counter()
            for _ in range(n2):
                out = jfn(q, q, q)
            float(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])
            t2 = time.perf_counter()
            dt = ((t2 - t1) - (t1 - t0)) / (n2 - n1)
            steps = (b * h) * (t // bq) * (t // bk)
            emit(f"{tag_prefix} flash bq{bq} bk{bk}",
                 ms=round(dt * 1e3, 3), grid_steps=steps,
                 us_per_step=round(dt * 1e6 / steps, 3))
        except Exception as e:  # noqa: BLE001 — VMEM overflow etc.
            emit(f"{tag_prefix} flash bq{bq} bk{bk}",
                 error=f"{type(e).__name__}: {e}"[:200])


def main():
    phases = sys.argv[1:] or ["A", "B", "C", "D", "E", "F"]
    if "A" in phases:
        step_time("A full t1024 b16 remat-full bf16s", cfg_for(1024), 16)
        step_time("A full t4096 b4 remat-full (auto->flash on TPU)",
                  cfg_for(4096), 4)
    if "B" in phases:
        no_attention("B no-attn t1024 b16", cfg_for(1024), 16)
        no_attention("B no-attn t4096 b4", cfg_for(4096), 4)
    if "C" in phases:
        forward_only("C fwd-only t1024 b16", cfg_for(1024), 16)
        forward_only("C fwd-only t4096 b4", cfg_for(4096), 4)
    if "D" in phases:
        step_time("D t4096 b4 remat-dots", cfg_for(4096, remat_policy="dots"), 4)
        step_time("D t4096 b4 remat-dots-nobatch",
                  cfg_for(4096, remat_policy="dots_no_batch"), 4)
        try:
            step_time("D t4096 b4 remat-off", cfg_for(4096, remat=False), 4)
        except Exception as e:  # noqa: BLE001
            emit("D t4096 b4 remat-off", error=f"{type(e).__name__}: {e}"[:300])
        step_time("D t4096 b4 flash-forced",
                  cfg_for(4096, use_flash_attention=True), 4)
        # THE comparison the r4 sweep never actually ran: flash OFF at
        # T=4096 (the "auto" default silently engaged flash in every r4
        # "xla"-tagged t4096 run — see sweep_transformer.py phase4 note).
        # The tunnel's remote compiler may reject these; record that too.
        for tag, kw in (("xla-true", dict(use_flash_attention=False,
                                          attn_scores_bf16=False)),
                        ("bf16s-true", dict(use_flash_attention=False,
                                            attn_scores_bf16=True))):
            try:
                step_time(f"D t4096 b4 remat-full {tag}",
                          cfg_for(4096, **kw), 4)
            except Exception as e:  # noqa: BLE001
                emit(f"D t4096 b4 remat-full {tag}",
                     error=f"{type(e).__name__}: {e}"[:300])
        try:
            step_time("D t4096 b8 remat-full", cfg_for(4096), 8)
        except Exception as e:  # noqa: BLE001
            emit("D t4096 b8 remat-full", error=f"{type(e).__name__}: {e}"[:300])
    if "E" in phases:
        xla_opinion("E cost t1024 b16", cfg_for(1024), 16)
        xla_opinion("E cost t4096 b4", cfg_for(4096), 4)
    if "F" in phases:
        block_sweep("F t4096 b4", 4096, 4)
        block_sweep("F t1024 b16", 1024, 16)


if __name__ == "__main__":
    ok, detail = bench.wait_for_backend(max_wait_s=120)
    if not ok:
        print(json.dumps({"backend_unavailable": True, "detail": detail}))
        sys.exit(0)
    main()
