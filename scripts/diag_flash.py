"""Flash-attention kernel diagnostic: attention-ONLY fwd+bwd timing vs the
XLA paths, per sequence length, on the real chip.

The transformer sweep showed flash ~tying XLA at T=4096 (MFU 0.11) — this
isolates the attention op to find where the kernel loses. Reports achieved
TFLOP/s counting LIVE flops only (causal ≈ half the rectangle), so an
efficient causal kernel should show ~flat achieved TFLOP/s across T while
the materializing XLA path degrades.

Usage: python scripts/diag_flash.py [fwd bwd ...]   (default: bwd = train path)
Writes scripts/diag_flash_out.json.
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import bench  # noqa: E402

OUT = pathlib.Path(__file__).with_name("diag_flash_out.json")
RESULTS = []


def emit(tag, **kw):
    rec = bench._stamp({"tag": tag, **kw})
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)
    OUT.write_text(json.dumps(RESULTS, indent=2))


def attention_flops(b, h, t, d, causal, train, impl):
    """MXU flops for fwd(+bwd). Matmul counts differ per implementation:
    flash recomputes s in BOTH backward passes (fwd 2 + dq pass s/dp/dq 3 +
    dkv pass s/dv/dp/dk 4 = 9); the XLA paths keep p from the forward
    (fwd 2 + bwd dv/dp/ds->dq/ds->dk 4 = 6, with softmax vjp on the VPU).
    Reported achieved_tflops is thus per-impl WORK done, not a common
    denominator — compare impls on `ms`, not on achieved_tflops."""
    per_matmul = 2.0 * b * h * t * t * d
    if causal:
        per_matmul *= 0.5
    n_matmuls = (9 if impl == "flash" else 6) if train else 2
    return per_matmul * n_matmuls


def _timeit(fn, *args):
    import jax
    out = jax.block_until_ready(fn(*args))
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(leaf.reshape(-1)[0])  # host fetch sync (tunnel-safe)
    n1, n2 = 2, 8
    t0 = time.perf_counter()
    for _ in range(n1):
        out = fn(*args)
    float(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])
    t1 = time.perf_counter()
    for _ in range(n2):
        out = fn(*args)
    float(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])
    t2 = time.perf_counter()
    return ((t2 - t1) - (t1 - t0)) / (n2 - n1)


def run(train=True):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.kernels.flash_attention import (
        flash_attention_ntc, mha_reference)

    h, d = 8, 64      # matches the benched TransformerConfig (d_model 512)
    causal = True
    for t, b in ((1024, 16), (2048, 8), (4096, 4), (8192, 2)):
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (b, t, h, d), jnp.bfloat16)
        qh = q.transpose(0, 2, 1, 3)

        def xla_fn(q, k, v):
            return mha_reference(q, k, v, None, causal)

        def xla_bf16_fn(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) * (d ** -0.5)
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v)

        def flash_fn(q, k, v):
            return flash_attention_ntc(q, k, v, causal=causal)

        for name, fn, arg in (("xla", xla_fn, qh),
                              ("xla-bf16p", xla_bf16_fn, qh),
                              ("flash", flash_fn, q)):
            try:
                if train:
                    def loss(q_, k_, v_, _fn=fn):
                        return jnp.sum(_fn(q_, k_, v_).astype(jnp.float32))
                    jfn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                else:
                    jfn = jax.jit(fn)
                dt = _timeit(jfn, arg, arg, arg)
                fl = attention_flops(b, h, t, d, causal, train,
                                     "flash" if name == "flash" else "xla")
                emit(f"{name} t{t} b{b} {'bwd' if train else 'fwd'}",
                     ms=round(dt * 1e3, 3),
                     achieved_tflops=round(fl / dt / 1e12, 2),
                     live_flops=fl)
            except Exception as e:  # noqa: BLE001
                emit(f"{name} t{t} {'bwd' if train else 'fwd'}",
                     error=f"{type(e).__name__}: {e}"[:300])


if __name__ == "__main__":
    which = sys.argv[1:] or ["bwd"]
    ok, detail = bench.wait_for_backend(max_wait_s=120)
    if not ok:
        print(json.dumps({"backend_unavailable": True, "detail": detail}))
        sys.exit(0)
    for w in which:
        run(train=(w == "bwd"))
