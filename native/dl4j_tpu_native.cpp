// dl4j_tpu_native — host-side native runtime for the TPU framework.
//
// Reference counterpart: libnd4j's C++ host runtime. The TPU compute path is
// XLA; what remains native here is what stays on the host in the reference
// too: the async data-pipeline ring buffer (DL4J AsyncDataSetIterator's
// queue + pinned staging), the threshold-encoding gradient codec
// (EncodedGradientsAccumulator / threshold compression used by gradient
// sharing over DCN), and fast CSV/float parsing for the ETL layer.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

extern "C" {

// ---------------------------------------------------------------------------
// SPSC ring buffer of fixed-size slots (lock-free; producer thread = Python
// worker filling batches, consumer = training loop). Slots are raw bytes —
// the Python side memcpy's numpy batch payloads in and out without the GIL
// (ctypes releases it during the call).
// ---------------------------------------------------------------------------

struct Ring {
    uint8_t*  data;
    uint64_t  slot_size;
    uint64_t  n_slots;
    std::atomic<uint64_t> head;   // next slot to write
    std::atomic<uint64_t> tail;   // next slot to read
    uint64_t* sizes;              // payload size per slot
};

Ring* ring_create(uint64_t slot_size, uint64_t n_slots) {
    Ring* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->data = static_cast<uint8_t*>(std::malloc(slot_size * n_slots));
    r->sizes = static_cast<uint64_t*>(std::calloc(n_slots, sizeof(uint64_t)));
    if (!r->data || !r->sizes) {
        std::free(r->data);
        std::free(r->sizes);
        delete r;
        return nullptr;
    }
    r->slot_size = slot_size;
    r->n_slots = n_slots;
    r->head.store(0);
    r->tail.store(0);
    return r;
}

void ring_destroy(Ring* r) {
    if (!r) return;
    std::free(r->data);
    std::free(r->sizes);
    delete r;
}

// returns 1 on success, 0 if full
int ring_push(Ring* r, const uint8_t* payload, uint64_t size) {
    if (size > r->slot_size) return -1;
    uint64_t head = r->head.load(std::memory_order_relaxed);
    uint64_t tail = r->tail.load(std::memory_order_acquire);
    if (head - tail >= r->n_slots) return 0;  // full
    uint64_t slot = head % r->n_slots;
    std::memcpy(r->data + slot * r->slot_size, payload, size);
    r->sizes[slot] = size;
    r->head.store(head + 1, std::memory_order_release);
    return 1;
}

// returns payload size on success, 0 if empty, -1 if out_cap too small
int64_t ring_pop(Ring* r, uint8_t* out, uint64_t out_cap) {
    uint64_t tail = r->tail.load(std::memory_order_relaxed);
    uint64_t head = r->head.load(std::memory_order_acquire);
    if (tail == head) return 0;  // empty
    uint64_t slot = tail % r->n_slots;
    uint64_t size = r->sizes[slot];
    if (size > out_cap) return -1;
    std::memcpy(out, r->data + slot * r->slot_size, size);
    r->tail.store(tail + 1, std::memory_order_release);
    return static_cast<int64_t>(size);
}

uint64_t ring_size(Ring* r) {
    return r->head.load(std::memory_order_acquire)
         - r->tail.load(std::memory_order_acquire);
}

// ---------------------------------------------------------------------------
// Threshold-encoding gradient codec (gradient sharing / DCN compression).
// Encoding: for |g[i]| >= threshold emit int32 token (i<<1 | sign) and
// subtract ±threshold into the residual (error feedback). Matches the
// reference's semantics: quantize-to-±threshold sparse updates.
// ---------------------------------------------------------------------------

// returns number of encoded tokens (<= max_out); residual updated in place.
// tokens are int64 (i<<1 | sign) so vectors beyond 2^30 params don't overflow
int64_t threshold_encode(const float* grad, float* residual, int64_t n,
                         float threshold, int64_t* out_idx, int64_t max_out) {
    int64_t count = 0;
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i] + residual[i];
        if (g >= threshold) {
            if (count < max_out) {
                out_idx[count++] = i << 1;
                residual[i] = g - threshold;
            } else {
                residual[i] = g;  // buffer full: keep in residual
            }
        } else if (g <= -threshold) {
            if (count < max_out) {
                out_idx[count++] = (i << 1) | 1;
                residual[i] = g + threshold;
            } else {
                residual[i] = g;
            }
        } else {
            residual[i] = g;
        }
    }
    return count;
}

// decode tokens into dense accumulator: out[i] += ±threshold
void threshold_decode(const int64_t* tokens, int64_t count, float threshold,
                      float* out, int64_t n) {
    for (int64_t t = 0; t < count; ++t) {
        int64_t tok = tokens[t];
        int64_t i = tok >> 1;
        if (i < 0 || i >= n) continue;
        out[i] += (tok & 1) ? -threshold : threshold;
    }
}

// ---------------------------------------------------------------------------
// Fast float CSV parser: parses `text` (len bytes) of comma/space-separated
// floats with newlines into out (row-major), returns count parsed.
// ---------------------------------------------------------------------------

int64_t parse_csv_floats(const char* text, int64_t len, float* out,
                         int64_t max_out) {
    int64_t count = 0;
    const char* p = text;
    const char* end = text + len;
    while (p < end && count < max_out) {
        // skip separators
        while (p < end && (*p == ',' || *p == ' ' || *p == '\n' ||
                           *p == '\r' || *p == '\t' || *p == ';')) ++p;
        if (p >= end) break;
        char* next = nullptr;
        float v = std::strtof(p, &next);
        if (next == p) { ++p; continue; }  // unparseable char; skip
        out[count++] = v;
        p = next;
    }
    return count;
}

// elementwise f32 → bf16 (round-to-nearest-even) staging conversion
void f32_to_bf16(const float* in, uint16_t* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        std::memcpy(&bits, &in[i], 4);
        uint32_t lsb = (bits >> 16) & 1;
        uint32_t rounded = bits + 0x7FFF + lsb;
        out[i] = static_cast<uint16_t>(rounded >> 16);
    }
}

}  // extern "C"
