// dl4j_tpu_native — host-side native runtime for the TPU framework.
//
// Reference counterpart: libnd4j's C++ host runtime. The TPU compute path is
// XLA; what remains native here is what stays on the host in the reference
// too: the async data-pipeline ring buffer (DL4J AsyncDataSetIterator's
// queue + pinned staging), the threshold-encoding gradient codec
// (EncodedGradientsAccumulator / threshold compression used by gradient
// sharing over DCN), and fast CSV/float parsing for the ETL layer.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <new>
#include <string>

extern "C" {

// ---------------------------------------------------------------------------
// SPSC ring buffer of fixed-size slots (lock-free; producer thread = Python
// worker filling batches, consumer = training loop). Slots are raw bytes —
// the Python side memcpy's numpy batch payloads in and out without the GIL
// (ctypes releases it during the call).
// ---------------------------------------------------------------------------

struct Ring {
    uint8_t*  data;
    uint64_t  slot_size;
    uint64_t  n_slots;
    std::atomic<uint64_t> head;   // next slot to write
    std::atomic<uint64_t> tail;   // next slot to read
    uint64_t* sizes;              // payload size per slot
};

Ring* ring_create(uint64_t slot_size, uint64_t n_slots) {
    Ring* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->data = static_cast<uint8_t*>(std::malloc(slot_size * n_slots));
    r->sizes = static_cast<uint64_t*>(std::calloc(n_slots, sizeof(uint64_t)));
    if (!r->data || !r->sizes) {
        std::free(r->data);
        std::free(r->sizes);
        delete r;
        return nullptr;
    }
    r->slot_size = slot_size;
    r->n_slots = n_slots;
    r->head.store(0);
    r->tail.store(0);
    return r;
}

void ring_destroy(Ring* r) {
    if (!r) return;
    std::free(r->data);
    std::free(r->sizes);
    delete r;
}

// returns 1 on success, 0 if full
int ring_push(Ring* r, const uint8_t* payload, uint64_t size) {
    if (size > r->slot_size) return -1;
    uint64_t head = r->head.load(std::memory_order_relaxed);
    uint64_t tail = r->tail.load(std::memory_order_acquire);
    if (head - tail >= r->n_slots) return 0;  // full
    uint64_t slot = head % r->n_slots;
    std::memcpy(r->data + slot * r->slot_size, payload, size);
    r->sizes[slot] = size;
    r->head.store(head + 1, std::memory_order_release);
    return 1;
}

// returns payload size on success, 0 if empty, -1 if out_cap too small
int64_t ring_pop(Ring* r, uint8_t* out, uint64_t out_cap) {
    uint64_t tail = r->tail.load(std::memory_order_relaxed);
    uint64_t head = r->head.load(std::memory_order_acquire);
    if (tail == head) return 0;  // empty
    uint64_t slot = tail % r->n_slots;
    uint64_t size = r->sizes[slot];
    if (size > out_cap) return -1;
    std::memcpy(out, r->data + slot * r->slot_size, size);
    r->tail.store(tail + 1, std::memory_order_release);
    return static_cast<int64_t>(size);
}

uint64_t ring_size(Ring* r) {
    return r->head.load(std::memory_order_acquire)
         - r->tail.load(std::memory_order_acquire);
}

// ---------------------------------------------------------------------------
// Threshold-encoding gradient codec (gradient sharing / DCN compression).
// Encoding: for |g[i]| >= threshold emit int32 token (i<<1 | sign) and
// subtract ±threshold into the residual (error feedback). Matches the
// reference's semantics: quantize-to-±threshold sparse updates.
// ---------------------------------------------------------------------------

// returns number of encoded tokens (<= max_out); residual updated in place.
// tokens are int64 (i<<1 | sign) so vectors beyond 2^30 params don't overflow
int64_t threshold_encode(const float* grad, float* residual, int64_t n,
                         float threshold, int64_t* out_idx, int64_t max_out) {
    int64_t count = 0;
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i] + residual[i];
        if (g >= threshold) {
            if (count < max_out) {
                out_idx[count++] = i << 1;
                residual[i] = g - threshold;
            } else {
                residual[i] = g;  // buffer full: keep in residual
            }
        } else if (g <= -threshold) {
            if (count < max_out) {
                out_idx[count++] = (i << 1) | 1;
                residual[i] = g + threshold;
            } else {
                residual[i] = g;
            }
        } else {
            residual[i] = g;
        }
    }
    return count;
}

// decode tokens into dense accumulator: out[i] += ±threshold
void threshold_decode(const int64_t* tokens, int64_t count, float threshold,
                      float* out, int64_t n) {
    for (int64_t t = 0; t < count; ++t) {
        int64_t tok = tokens[t];
        int64_t i = tok >> 1;
        if (i < 0 || i >= n) continue;
        out[i] += (tok & 1) ? -threshold : threshold;
    }
}

// ---------------------------------------------------------------------------
// Fast float CSV parser: parses `text` (len bytes) of comma/space-separated
// floats with newlines into out (row-major), returns count parsed.
// ---------------------------------------------------------------------------

int64_t parse_csv_floats(const char* text, int64_t len, float* out,
                         int64_t max_out) {
    int64_t count = 0;
    const char* p = text;
    const char* end = text + len;
    while (p < end && count < max_out) {
        // skip separators
        while (p < end && (*p == ',' || *p == ' ' || *p == '\n' ||
                           *p == '\r' || *p == '\t' || *p == ';')) ++p;
        if (p >= end) break;
        char* next = nullptr;
        float v = std::strtof(p, &next);
        if (next == p) { ++p; continue; }  // unparseable char; skip
        out[count++] = v;
        p = next;
    }
    return count;
}

// elementwise f32 → bf16 (round-to-nearest-even) staging conversion
void f32_to_bf16(const float* in, uint16_t* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        std::memcpy(&bits, &in[i], 4);
        uint32_t lsb = (bits >> 16) & 1;
        uint32_t rounded = bits + 0x7FFF + lsb;
        out[i] = static_cast<uint16_t>(rounded >> 16);
    }
}

// ---------------------------------------------------------------------------
// Staging arena — the pinned-host allocator analogue (reference: libnd4j's
// memory workspaces + cudaHostAlloc staging for H2D copies). TPU hosts have
// no cudaHostAlloc; what matters is (a) page-aligned long-lived buffers the
// runtime can DMA from without bounce copies, (b) zero malloc/free churn in
// the steady-state input pipeline, (c) first-touch NUMA locality (pages land
// on the socket of the worker thread that first writes them — we touch them
// from the allocating thread at creation). Fixed-size blocks + LIFO freelist.
// ---------------------------------------------------------------------------

// Freelist is mutex-guarded: borrow/release happen per BATCH (thousands of
// times slower cadence than the per-payload SPSC ring above, which stays
// lock-free), so correctness beats lock-freedom here. The bitmap rejects
// double-free and misaligned/foreign pointers outright.

struct Arena {
    uint8_t*  base;        // one aligned slab: block_size * n_blocks
    uint64_t  block_size;
    uint64_t  n_blocks;
    int64_t*  freelist;    // stack of free block indices
    uint8_t*  allocated;   // per-block allocation bitmap
    int64_t   top;         // freelist top (count of free blocks)
    uint64_t  in_use;
    uint64_t  peak;
    std::mutex lock;
};

Arena* arena_create(uint64_t block_size, uint64_t n_blocks) {
    // round block size up to 4 KiB pages so every block is page-aligned
    const uint64_t page = 4096;
    block_size = (block_size + page - 1) / page * page;
    Arena* a = new (std::nothrow) Arena();
    if (!a) return nullptr;
    void* mem = nullptr;
    if (posix_memalign(&mem, page, block_size * n_blocks) != 0) {
        delete a;
        return nullptr;
    }
    a->base = static_cast<uint8_t*>(mem);
    a->freelist = static_cast<int64_t*>(std::malloc(n_blocks * sizeof(int64_t)));
    a->allocated = static_cast<uint8_t*>(std::calloc(n_blocks, 1));
    if (!a->freelist || !a->allocated) {
        std::free(mem);
        std::free(a->freelist);
        std::free(a->allocated);
        delete a;
        return nullptr;
    }
    // first-touch every page from THIS thread so NUMA placement follows the
    // pipeline worker that owns the arena; also warms the TLB.
    std::memset(a->base, 0, block_size * n_blocks);
    a->block_size = block_size;
    a->n_blocks = n_blocks;
    for (uint64_t i = 0; i < n_blocks; ++i)
        a->freelist[i] = static_cast<int64_t>(n_blocks - 1 - i);
    a->top = static_cast<int64_t>(n_blocks);
    a->in_use = 0;
    a->peak = 0;
    return a;
}

void arena_destroy(Arena* a) {
    if (!a) return;
    std::free(a->base);
    std::free(a->freelist);
    std::free(a->allocated);
    delete a;
}

// returns block pointer or nullptr if exhausted (caller falls back to malloc)
uint8_t* arena_alloc(Arena* a) {
    std::lock_guard<std::mutex> g(a->lock);
    if (a->top <= 0) return nullptr;
    int64_t idx = a->freelist[--a->top];
    a->allocated[idx] = 1;
    ++a->in_use;
    if (a->in_use > a->peak) a->peak = a->in_use;
    return a->base + idx * a->block_size;
}

// returns 1 on success; 0 for foreign, misaligned or double-freed pointers
int arena_free(Arena* a, uint8_t* p) {
    if (p < a->base || p >= a->base + a->block_size * a->n_blocks) return 0;
    if ((p - a->base) % static_cast<int64_t>(a->block_size) != 0) return 0;
    int64_t idx = (p - a->base) / a->block_size;
    std::lock_guard<std::mutex> g(a->lock);
    if (!a->allocated[idx]) return 0;  // double free
    a->allocated[idx] = 0;
    a->freelist[a->top++] = idx;
    --a->in_use;
    return 1;
}

uint64_t arena_block_size(Arena* a) { return a->block_size; }
uint64_t arena_in_use(Arena* a) {
    std::lock_guard<std::mutex> g(a->lock);
    return a->in_use;
}
uint64_t arena_peak(Arena* a) {
    std::lock_guard<std::mutex> g(a->lock);
    return a->peak;
}

// ---------------------------------------------------------------------------
// NPY header parser (v1.0/2.0) — fast path for DataVec-lite record storage:
// parse shape/dtype/offset without Python, then the caller mmaps or memcpys
// the payload straight into a staging block.
// Returns 0 on success, negative error code otherwise.
// ---------------------------------------------------------------------------

int npy_parse_header(const uint8_t* buf, int64_t len,
                     int64_t* shape_out /*cap 8*/, int32_t* ndim_out,
                     char* dtype_char_out, int32_t* itemsize_out,
                     int64_t* data_offset_out, int32_t* fortran_out) {
    if (len < 10 || std::memcmp(buf, "\x93NUMPY", 6) != 0) return -1;
    uint8_t major = buf[6];
    uint64_t hlen, hstart;
    if (major == 1) {
        hlen = buf[8] | (uint64_t(buf[9]) << 8);
        hstart = 10;
    } else if (major == 2) {
        if (len < 12) return -1;
        hlen = buf[8] | (uint64_t(buf[9]) << 8) |
               (uint64_t(buf[10]) << 16) | (uint64_t(buf[11]) << 24);
        hstart = 12;
    } else {
        return -2;
    }
    if (hstart + hlen > static_cast<uint64_t>(len)) return -3;
    // Copy the header into a NUL-terminated local buffer: the str* scanners
    // below must never run past the caller's (ptr, len) region — the C API
    // contract cannot rely on callers passing NUL-terminated memory.
    std::string hbuf(reinterpret_cast<const char*>(buf + hstart), hlen);
    const char* h = hbuf.c_str();
    const char* hend = h + hlen;
    // descr: find "'descr':" then the quoted dtype like '<f4'
    const char* d = std::strstr(h, "descr");
    if (!d || d >= hend) return -4;
    d = std::strchr(d, ':');
    if (!d) return -4;
    ++d;
    while (d < hend && *d == ' ') ++d;
    if (d < hend && *d == '[') return -7;  // structured dtype: caller falls
                                           // back to numpy's own parser
    while (d < hend && *d != '\'' && *d != '"') ++d;
    if (d >= hend) return -4;
    ++d;                       // inside quote: e.g. <f4, |u1, <i8
    char endian = *d;
    if (endian == '<' || endian == '>' || endian == '|' || endian == '=') ++d;
    if (endian == '>') return -5;  // big-endian unsupported on TPU hosts
    *dtype_char_out = *d;
    *itemsize_out = std::atoi(d + 1);
    // fortran_order
    const char* f = std::strstr(h, "fortran_order");
    *fortran_out = (f && std::strstr(f, "True") &&
                    std::strstr(f, "True") < hend) ? 1 : 0;
    // shape tuple
    const char* s = std::strstr(h, "shape");
    if (!s || s >= hend) return -6;
    s = std::strchr(s, '(');
    if (!s) return -6;
    ++s;
    int32_t nd = 0;
    while (s < hend && *s != ')' && nd < 8) {
        while (s < hend && (*s == ' ' || *s == ',')) ++s;
        if (*s == ')') break;
        char* next = nullptr;
        long long v = std::strtoll(s, &next, 10);
        if (next == s) break;
        shape_out[nd++] = v;
        s = next;
    }
    *ndim_out = nd;
    *data_offset_out = static_cast<int64_t>(hstart + hlen);
    return 0;
}

// ---------------------------------------------------------------------------
// CSV matrix parser: text → row-major f32 with a fixed column count.
// Rows with a different column count are skipped (header lines, blanks).
// Returns rows parsed (<= max_rows).
// ---------------------------------------------------------------------------

int64_t parse_csv_matrix(const char* text, int64_t len, int64_t n_cols,
                         float* out, int64_t max_rows) {
    const char* p = text;
    const char* end = text + len;
    int64_t rows = 0;
    float* rowbuf = static_cast<float*>(std::malloc(n_cols * sizeof(float)));
    if (!rowbuf) return 0;
    std::string linebuf;  // NUL-terminated line copy: strtof must never scan
                          // past the caller's (ptr, len) region
    while (p < end && rows < max_rows) {
        const char* raw_end = static_cast<const char*>(
            std::memchr(p, '\n', end - p));
        if (!raw_end) raw_end = end;
        linebuf.assign(p, raw_end - p);
        const char* q = linebuf.c_str();
        const char* line_end = q + linebuf.size();
        int64_t c = 0;
        while (q < line_end && c <= n_cols) {
            while (q < line_end && (*q == ',' || *q == ' ' || *q == '\t' ||
                                    *q == ';' || *q == '\r')) ++q;
            if (q >= line_end) break;
            char* next = nullptr;
            float v = std::strtof(q, &next);
            if (next == q || next > line_end) { c = -1; break; }  // non-numeric
            if (c < n_cols) rowbuf[c] = v;
            ++c;
            q = next;
        }
        if (c == n_cols) {
            std::memcpy(out + rows * n_cols, rowbuf, n_cols * sizeof(float));
            ++rows;
        }
        p = raw_end + 1;
    }
    std::free(rowbuf);
    return rows;
}

}  // extern "C"
