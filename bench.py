"""Headline bench: ResNet-50 ImageNet fit() samples/sec/chip (BASELINE.json).

Runs on the real TPU chip (axon). Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}

vs_baseline divides by the DL4J V100 cuDNN reference (360 img/s — see
BASELINE.md). Synthetic ImageNet-shaped data (zero-egress sandbox); bf16
NHWC convs (MXU accumulates in f32 on TPU); steady-state timing excludes
compile.

Secondary configs (SURVEY.md §6): `python bench.py --model lenet|charnn|
bert|transformer [batch] [steps]` — each prints its own single JSON line
(no vs_baseline; the published reference numbers cover ResNet-50 only).
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_SAMPLES_PER_SEC = 360.0  # DL4J ResNet-50 V100 cuDNN (BASELINE.md)


def bench_lenet(batch, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.zoo import LeNet

    net = LeNet(num_classes=10).init()
    rng = np.random.default_rng(0)
    x = rng.random((batch, 28, 28, 1), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    it = ListDataSetIterator([DataSet(x, y)])
    net.fit(it, epochs=1)  # compile + warmup
    t0 = time.perf_counter()
    net.fit(ListDataSetIterator([DataSet(x, y)] * steps), epochs=1)
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0
    return {"metric": "LeNet MNIST fit() samples/sec/chip",
            "value": round(batch * steps / dt, 2), "unit": "samples/sec/chip"}


def bench_charnn(batch, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.zoo import TextGenerationLSTM

    seq, vocab = 60, 77
    net = TextGenerationLSTM(num_classes=vocab, input_shape=(seq, vocab)).init()
    rng = np.random.default_rng(0)
    x = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (batch, seq))]
    y = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (batch, seq))]
    net.fit(ListDataSetIterator([DataSet(x, y)]), epochs=1)
    t0 = time.perf_counter()
    net.fit(ListDataSetIterator([DataSet(x, y)] * steps), epochs=1)
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0
    return {"metric": "GravesLSTM char-RNN fit() tokens/sec/chip",
            "value": round(batch * seq * steps / dt, 2),
            "unit": "tokens/sec/chip"}


def bench_bert(batch, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from deeplearning4j_tpu.zoo import transformer as tfm

    cfg = tfm.BertConfig(max_seq=128)
    key = jax.random.PRNGKey(0)
    params = tfm.bert_init(key, cfg)
    opt = optax.adamw(2e-5)
    opt_state = opt.init(params)

    def step(params, opt_state, ids, labels):
        loss, grads = jax.value_and_grad(tfm.bert_classifier_loss)(
            params, cfg, ids, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    labels = jnp.asarray(rng.integers(0, cfg.num_labels, batch))
    params, opt_state, loss = jstep(params, opt_state, ids, labels)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = jstep(params, opt_state, ids, labels)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return {"metric": "BERT-base fine-tune seq/sec/chip (T=128)",
            "value": round(batch * steps / dt, 2), "unit": "seq/sec/chip"}


def bench_transformer(batch, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from deeplearning4j_tpu.zoo import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=32000, d_model=512, n_heads=8,
                                n_layers=8, d_ff=2048, max_seq=1024,
                                dtype=jnp.bfloat16)
    key = jax.random.PRNGKey(0)
    params = tfm.init_params(key, cfg)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    jstep = jax.jit(tfm.make_train_step(cfg, opt), donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    params, opt_state, loss = jstep(params, opt_state, ids, tgt)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = jstep(params, opt_state, ids, tgt)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return {"metric": "Transformer-LM (120M, T=1024, flash-attn) tokens/sec/chip",
            "value": round(batch * cfg.max_seq * steps / dt, 2),
            "unit": "tokens/sec/chip"}


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    argv = list(sys.argv[1:])
    model = "resnet50"
    if argv and argv[0] == "--model":
        model = argv[1]
        argv = argv[2:]
    if model != "resnet50":
        fn = {"lenet": bench_lenet, "charnn": bench_charnn,
              "bert": bench_bert, "transformer": bench_transformer}[model]
        batch = int(argv[0]) if argv else 32
        steps = int(argv[1]) if len(argv) > 1 else 10
        print(json.dumps(fn(batch, steps)))
        return

    batch = int(argv[0]) if argv else 128
    steps = int(argv[1]) if len(argv) > 1 else 20

    from deeplearning4j_tpu.zoo.resnet import ResNet50
    net = ResNet50(num_classes=1000, compute_dtype=jnp.bfloat16).init()

    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(net.params)

    def train_step(params, states, opt_state, x, y):
        def loss_fn(p, s):
            acts, pre, new_s = net._forward(p, s, {"in": x}, train=True, rng=None,
                                            stop_at_output_preact=True)
            out_layer = net.conf.nodes["out"].op
            loss = out_layer.compute_loss(p["out"], pre["out"], y)
            return loss, new_s

        (loss, new_states), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, states)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_states, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((batch, 224, 224, 3), np.float32), jnp.bfloat16)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])

    params, states, ostate = net.params, net.states, opt_state
    # warmup / compile
    params, states, ostate, loss = step(params, states, ostate, x, y)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, states, ostate, loss = step(params, states, ostate, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    sps = batch * steps / dt
    print(json.dumps({
        "metric": "MultiLayerNetwork.fit() samples/sec/chip (ResNet-50 ImageNet)",
        "value": round(sps, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps / BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
