"""Benchmarks: ResNet-50 headline + SURVEY §6 secondary configs, MFU-audited.

Prints ONE JSON line on stdout (the headline, BASELINE.json contract):
  {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N,
   "flops_per_step": ..., "derived_tflops": ..., "mfu": ..., ...}

The headline routes through the REAL user entry point —
``ComputationGraph.fit(DataSetIterator)`` (VERDICT r2 item 1): iterator
protocol, async-wrap policy, optimizer build, donated jitted step and
listener plumbing all engaged. Batches are pre-staged on device (DataSet
keeps jax Arrays device-resident, like the reference's INDArray-backed
DataSet) because the axon tunnel's host link is a network relay, not a
TPU host's PCIe path. `resnet50_rawstep` keeps the hand-built-step
variant for comparison.

Methodology (why this is trustworthy on the axon tunnel):
- `jax.block_until_ready` does NOT synchronize through the tunnel (measured:
  a chained 4096^2 matmul loop "finishes" at 6972 TFLOP/s, 35x over the v5e
  bf16 peak of ~197 TFLOP/s). Only a real device->host fetch syncs. Every
  timed region here ends in a scalar host fetch.
- A single fetch carries a fixed ~65ms tunnel round-trip, so throughput is
  computed from the MARGINAL step time between two chained-step counts
  (t(n2)-t(n1))/(n2-n1), which cancels the constant.
- Steps are data-dependent (params/opt-state carried through), so the chain
  cannot be reordered or elided.
- Every record carries analytic FLOPs/step (jaxpr walk, MXU ops only — see
  utils/tracing.py), derived TFLOP/s, and MFU vs the v5e bf16 peak. An MFU
  > 1 is physically impossible and flags the record `timing_valid: false`.

Secondary configs (LeNet bf16, char-RNN, BERT fine-tune, Transformer-LM,
dp-8 overhead) run after the headline and are written to `bench_secondary.json`
(stderr progress only, stdout stays one line). `--model NAME [batch steps]`
runs a single config and prints its record alone.

Serving-plane configs (ISSUE 10: KV-cache decode tokens/s, TTFT at
T=1024/4096 prefill, ResNet/BERT batch-1 p50/p99 + best-batch throughput
through ParallelInference) run last into the artifact's `inference`
section; rows captured off-TPU carry `on_chip_todo` until a chip
re-capture (`bench.py --refresh inference_decode,...`).

Reference parity: DL4J's published ResNet-50 V100 cuDNN number (~360 img/s)
is the `vs_baseline` denominator — see BASELINE.md.

Longitudinal trend plane (ISSUE 15): every captured row also appends a
keyed record to `runs/perf_ledger.jsonl` (atomic single-write line; see
`deeplearning4j_tpu/obs/trend.py`). `scripts/perf_gate.py` replays the
ledger into per-row trend verdicts (stable/improved/regressed/unstable/
bimodal) and gates CI on out-of-band regressions vs a pinned baseline.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_SAMPLES_PER_SEC = 360.0  # DL4J ResNet-50 V100 cuDNN (BASELINE.md)

# Activation-remat policy for the ResNet configs (None = off; int = number
# of jax.checkpoint segments). Set from the diag_resnet G/H sweep when the
# measured winner beats the monolithic forward on-chip.
RESNET_REMAT = None


def _git_sha():
    import subprocess
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=10, cwd=__import__("os").path.dirname(
                __import__("os").path.abspath(__file__)),
        ).stdout.strip() or None
    except Exception:  # noqa: BLE001 — provenance stamp, never fatal
        return None


def _stamp(rec):
    """Provenance: every record carries capture time + repo SHA + backend, so
    a stale artifact can never masquerade as current (the r3 failure mode)."""
    import datetime
    rec.setdefault("captured_at",
                   datetime.datetime.now(datetime.timezone.utc).isoformat(
                       timespec="seconds"))
    rec.setdefault("git_sha", _git_sha())
    if "backend" not in rec:
        # only touch jax when the caller did NOT pre-set the backend:
        # setdefault would evaluate jax.default_backend() eagerly, and on
        # an unavailable-backend record that call INITIALIZES the wedged
        # backend in-process and hangs the very record reporting it
        # (observed: rc=124 instead of the clean unavailable line)
        try:
            import jax
            rec["backend"] = jax.default_backend()
        except Exception:  # noqa: BLE001
            rec["backend"] = "unavailable"
    return rec


def wait_for_backend(max_wait_s=300.0, attempt_timeout_s=90.0):
    """Retry backend init with backoff. The axon tunnel flaps: a single
    UNAVAILABLE at t=0 (the r3 round-end crash) does not mean it is down for
    good. Each probe runs in a SUBPROCESS with its own timeout: a wedged
    relay makes jax.devices() hang 100s+ in-process, which would blow past
    max_wait_s and also poison this process's backend state. Returns
    (ok, detail). Never raises."""
    import subprocess
    delay, detail = 5.0, ""
    t0 = time.perf_counter()
    while True:
        waited = time.perf_counter() - t0
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices()[0]; "
                 "print(d.platform + ' ' + str(d))"],
                capture_output=True, text=True,
                timeout=min(attempt_timeout_s, max(max_wait_s - waited, 10)))
            found = proc.stdout.strip()
            if proc.returncode == 0 and found:
                platform = found.split()[0]
                # A silent CPU fallback must NOT pass the gate: an rc=0
                # headline measured on host CPU would masquerade as a TPU
                # number. Opt out with BENCH_ALLOW_CPU=1 for local runs.
                import os
                if platform == "tpu" or os.environ.get("BENCH_ALLOW_CPU"):
                    return True, found
                detail = f"non-TPU backend found: {found}"
            else:
                detail = (proc.stderr or proc.stdout)[-300:]
        except subprocess.TimeoutExpired:
            detail = f"probe hang >{attempt_timeout_s:.0f}s (relay wedged?)"
        except Exception as e:  # noqa: BLE001 — backend probe
            detail = f"{type(e).__name__}: {e}"[:300]
        waited = time.perf_counter() - t0
        if waited >= max_wait_s:
            return False, detail
        print(f"[bench] backend unavailable, retrying in {delay:.0f}s "
              f"({waited:.0f}/{max_wait_s:.0f}s elapsed)", file=sys.stderr,
              flush=True)
        time.sleep(delay)
        delay = min(delay * 2, 60.0)
V5E_BF16_PEAK = 197e12  # TPU v5 lite bf16 peak FLOP/s (public spec)
DPOVERHEAD_METRIC = "dp-8 per-step overhead vs single device (virtual CPU mesh)"


def _peak_flops(dtype="bf16"):
    """Attainable peak for the config's compute dtype: f32 matmuls run at
    roughly half the bf16 MXU rate, so auditing an f32 config against the
    bf16 peak would make the impossibility gate ~2x too lenient."""
    import jax
    if jax.default_backend() != "tpu":
        return None
    return V5E_BF16_PEAK if dtype == "bf16" else V5E_BF16_PEAK / 2


def _fetch(x):
    """Force a real device->host sync (block_until_ready lies on the tunnel)."""
    import jax.numpy as jnp
    return float(jnp.asarray(x).reshape(-1)[0])


def measure_marginal(run_chain, n1=5, n2=25, repeats=2):
    """Marginal per-step seconds of `run_chain(n) -> fetchable`, best of
    `repeats` at each count (cancels the fixed tunnel round-trip)."""
    n2 = max(n2, n1 + 2)
    _fetch(run_chain(2))  # compile + warmup
    t_at = {}
    for n in (n1, n2):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            _fetch(run_chain(n))
            best = min(best, time.perf_counter() - t0)
        t_at[n] = best
    per_step = (t_at[n2] - t_at[n1]) / (n2 - n1)
    # A non-positive marginal means the measurement is garbage (noise beat
    # the signal): report it as invalid rather than a clamped huge number.
    return max(per_step, 1e-9), per_step > 0


SUB_MS_S = 1e-3      # below this, single captures moved 6x intra-day
STABILITY_K = 5      # median-of-k pair captures for sub-ms rows
UNSTABLE_REL_IQR = 0.25   # IQR/median above this flags the row


def measure_stable(run_chain, n1=5, n2=25, repeats=2, k=STABILITY_K):
    """measure_marginal + stability discipline for sub-millisecond rows
    (the lenet row moved 6x intra-day on tunnel jitter — docs/PERF.md):
    when the first marginal estimate lands under 1 ms, capture k
    independent (n1, n2) pairs, quote the MEDIAN, and flag the row
    ``unstable`` when the relative IQR exceeds 25% — so floors quote
    against a stable denominator or say loudly that none exists.
    Returns (per_step_s, valid, stability_dict_or_None)."""
    per_step, valid = measure_marginal(run_chain, n1, n2, repeats)
    if not valid or per_step >= SUB_MS_S or k <= 1:
        return per_step, valid, None
    samples = [per_step]
    for _ in range(k - 1):
        s, ok = measure_marginal(run_chain, n1, n2, repeats=1)
        if ok:
            samples.append(s)
    samples.sort()
    import statistics
    med = float(statistics.median(samples))
    n = len(samples)
    q25 = samples[max(0, round(0.25 * (n - 1)))]
    q75 = samples[min(n - 1, round(0.75 * (n - 1)))]
    iqr_rel = (q75 - q25) / med if med > 0 else float("inf")
    stability = {
        "median_of_k": n,
        "step_time_ms_samples": [round(s * 1e3, 4) for s in samples],
        "iqr_rel": round(iqr_rel, 4),
        "unstable": bool(iqr_rel > UNSTABLE_REL_IQR),
    }
    # bimodality verdict inline (ISSUE 15): when the retained samples
    # split into two tight modes, the median above is NOT a stable
    # denominator — record the per-cluster medians beside it (the
    # machine form of the T=4096 "82–152k across sessions" prose).
    # min_cluster=2: within one capture a mode must RECUR — a lone
    # tunnel-jitter outlier among k samples is the `unstable`/median
    # discipline's problem, not a second mode
    try:
        from deeplearning4j_tpu.obs import trend
        split = trend.split_clusters(samples, min_cluster=2)
        stability["bimodal"] = split is not None
        if split is not None:
            stability["cluster_medians_ms"] = [
                round(split["lo_median"] * 1e3, 4),
                round(split["hi_median"] * 1e3, 4)]
    except Exception:  # noqa: BLE001 — the verdict is decoration
        pass
    return med, True, stability


def chain_runner(step_once, carry):
    """Chained-step closure shared by every config: `step_once(*carry) ->
    (new_carry, loss)`. Steps are data-dependent through `carry`, and because
    the jitted steps donate their state args, `carry` is updated in place so
    no call ever re-reads a donated buffer."""

    def run_chain(n):
        c, loss = tuple(carry), None
        for _ in range(n):
            c, loss = step_once(*c)
        carry[:] = c
        return loss

    return run_chain


def _record(metric, unit, samples_per_step, timing, flops_per_step,
            dtype="bf16", probe=None, **extra):
    per_step_s, valid = timing[0], timing[1]
    stability = timing[2] if len(timing) > 2 else None
    peak = _peak_flops(dtype)
    tflops = flops_per_step / per_step_s / 1e12
    rec = {
        "metric": metric,
        "value": round(samples_per_step / per_step_s, 2),
        "unit": unit,
        "step_time_ms": round(per_step_s * 1e3, 3),
        "flops_per_step": int(flops_per_step),
        "derived_tflops": round(tflops, 2),
        "compute_dtype": dtype,
        "peak_tflops_assumed": None if peak is None else peak / 1e12,
        "mfu": None if peak is None else round(flops_per_step / per_step_s / peak, 4),
        "timing": "marginal chained steps, host-fetch synced",
    }
    if stability is not None:
        rec.update(stability)   # median_of_k / samples / iqr_rel / unstable
    if not valid or (rec["mfu"] is not None and rec["mfu"] > 1.0):
        rec["timing_valid"] = False
    rec.update(extra)
    _emit_row_metrics(rec)
    _attach_floor(rec, probe, dtype,
                  per_step_s if rec.get("timing_valid", True) else None)
    return _stamp(rec)


def _attach_floor(rec, probe, dtype, per_step_s):
    """Roofline floor block (ISSUE 7): derive HLO flops/bytes for the
    row's jitted step via the probe the builder attached to its
    run_chain (``floor_probe``: cost_analysis with estimator fallback,
    lowered from shape structs so donation can't bite), combine with the
    per-backend peak table and record floor_ms / pct_of_floor /
    binding_resource / lever-or-ok verdict beside the row. Never fatal —
    a floor failure must not cost a captured row."""
    fp = getattr(probe, "floor_probe", None)
    if fp is None:
        return
    try:
        from deeplearning4j_tpu.obs import floors
        costs = fp()
        step_ms = None if per_step_s is None else per_step_s * 1e3
        block = floors.floor_block(costs, step_ms=step_ms, dtype=dtype)
        rec["floor"] = block
        try:
            m = floors.emit_floor_metrics(rec["metric"], block)
            if m and isinstance(rec.get("metrics"), dict):
                rec["metrics"].update(m)
        except Exception:  # noqa: BLE001 — gauge mirror is decoration
            pass
    except Exception as e:  # noqa: BLE001 — the row survives floorless
        rec["floor"] = {"na": f"floor derivation failed: "
                              f"{type(e).__name__}: {e}"[:300]}


def _emit_row_metrics(rec):
    """Telemetry-plane mirror of a bench row: observe the row into the
    process-wide dl4j_ registry AND embed the same schema beside the
    record, so the floor table (ROADMAP item 5) and a live /metrics
    scrape read identical names. Never fatal — a telemetry failure must
    not cost a captured row."""
    try:
        from deeplearning4j_tpu.obs import get_registry
        reg = get_registry()
        config = rec["metric"]
        step_s = rec["step_time_ms"] / 1e3
        reg.histogram("dl4j_bench_step_seconds",
                      "Measured marginal step time per bench row",
                      labelnames=("config",)).observe(step_s, config=config)
        reg.gauge("dl4j_bench_throughput",
                  "Bench row value in the row's own unit",
                  labelnames=("config", "unit")).set(
            rec["value"], config=config, unit=rec["unit"])
        metrics = {"dl4j_bench_step_seconds": step_s,
                   "dl4j_bench_throughput": rec["value"]}
        if rec.get("mfu") is not None:
            reg.gauge("dl4j_bench_mfu",
                      "Bench row model-flops utilization",
                      labelnames=("config",)).set(rec["mfu"], config=config)
            metrics["dl4j_bench_mfu"] = rec["mfu"]
        rec["metrics"] = metrics
    except Exception:  # noqa: BLE001 — decoration only
        pass


def _mln_chain(net, x, y):
    """Chained-train-step runner for a MultiLayerNetwork + its analytic FLOPs."""
    import jax
    from deeplearning4j_tpu.utils.tracing import total_flops

    net._build_optimizer(1)
    step = net._get_train_step()
    rng = jax.random.PRNGKey(0)
    flops = total_flops(
        lambda p, s, o: step.__wrapped__(p, s, o, x, y, rng, None, None)[:3],
        net.params, net.states, net._opt_state)

    def step_once(p, s, o, k):
        p, s, o, loss, _, k = step(p, s, o, x, y, k, None, None)
        return (p, s, o, k), loss

    run_chain = chain_runner(step_once, [net.params, net.states,
                                         net._opt_state, rng])
    run_chain.floor_probe = _make_floor_probe(
        step, net.params, net.states, net._opt_state, x, y, rng, None, None)
    return run_chain, flops


def _make_floor_probe(jitted_step, *args, extra_flops=0):
    """Zero-arg closure returning {flops, bytes, source} for one step.
    Shapes are captured NOW (ShapeDtypeStructs) because the chain will
    donate these very buffers; lowering needs avals, not data.
    ``extra_flops`` tops up work invisible to both cost_analysis and the
    jaxpr estimator (pallas kernels)."""
    from deeplearning4j_tpu.obs import floors
    shapes = floors.shape_probe(args)

    def probe():
        costs = floors.hlo_costs(jitted_step, *shapes)
        if extra_flops and "flops" in costs:
            costs["flops"] += extra_flops
        return costs

    return probe


def build_lenet(batch, compute_dtype="bf16"):
    """(run_chain, flops) for the LeNet config — importable by tests so the
    bench code path compiles in CI, not only at round end. Runs the mixed
    bf16 policy by default (params f32, compute bf16 — the framework's
    recommended TPU config); pass compute_dtype=None for the pure-f32
    DL4J-default comparison."""
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.zoo import LeNet

    cd = jnp.bfloat16 if compute_dtype == "bf16" else None
    net = LeNet(num_classes=10, compute_dtype=cd).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((batch, 28, 28, 1), np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    return _mln_chain(net, x, y)


def build_lenet_scan(batch, compute_dtype="bf16"):
    """(run_chain, flops) for the SCANNED LeNet fit: fit_scanned runs the
    epoch as one lax.scan dispatch, so the marginal per-step time is pure
    device compute — the dispatch overhead that dominates a ~1 ms model
    through the tunnel is paid once per chain call. Same step math as
    fit() (bit-identical trajectory, tests/test_fit_scanned.py)."""
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.utils.tracing import total_flops
    from deeplearning4j_tpu.zoo import LeNet

    cd = jnp.bfloat16 if compute_dtype == "bf16" else None
    net = LeNet(num_classes=10, compute_dtype=cd).init()
    rng = np.random.default_rng(0)
    # a few distinct device-resident batches, reused cyclically
    dss = [DataSet(jnp.asarray(rng.random((batch, 28, 28, 1), np.float32)),
                   jnp.asarray(np.eye(10, dtype=np.float32)[
                       rng.integers(0, 10, batch)]))
           for _ in range(4)]
    net._build_optimizer(1)
    step = net._get_train_step()
    rng0 = __import__("jax").random.PRNGKey(0)
    flops = total_flops(
        lambda p, s, o: step.__wrapped__(
            p, s, o, dss[0].features, dss[0].labels, rng0, None, None)[:3],
        net.params, net.states, net._opt_state)

    def run_chain(n):
        return net.fit_scanned([dss[i % len(dss)] for i in range(n)])

    # floor of the per-step work (the scan dispatches K of these)
    run_chain.floor_probe = _make_floor_probe(
        step, net.params, net.states, net._opt_state,
        dss[0].features, dss[0].labels, rng0, None, None)
    return run_chain, flops


def bench_lenet_scan(batch, steps):
    run_chain, flops = build_lenet_scan(batch, compute_dtype="bf16")
    timing = measure_stable(run_chain, n1=5, n2=steps)
    return _record(
        "LeNet MNIST fit_scanned samples/sec/chip (bf16, scan-dispatch)",
        "samples/sec/chip", batch, timing, flops, dtype="bf16",
        probe=run_chain, batch=batch)


def bench_lenet(batch, steps):
    run_chain, flops = build_lenet(batch, compute_dtype="bf16")
    timing = measure_stable(run_chain, n1=5, n2=steps)
    return _record("LeNet MNIST train-step samples/sec/chip (bf16)",
                   "samples/sec/chip", batch, timing, flops, dtype="bf16",
                   probe=run_chain, batch=batch)


def build_charnn(batch, seq=60, vocab=77, compute_dtype="bf16"):
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.zoo import TextGenerationLSTM

    cd = jnp.bfloat16 if compute_dtype == "bf16" else None
    net = TextGenerationLSTM(num_classes=vocab, input_shape=(seq, vocab),
                             compute_dtype=cd).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, seq))])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, seq))])
    return _mln_chain(net, x, y)


def bench_charnn(batch, steps, compute_dtype="bf16"):
    seq = 60
    run_chain, flops = build_charnn(batch, seq=seq,
                                    compute_dtype=compute_dtype)
    timing = measure_stable(run_chain, n1=5, n2=steps)
    return _record(
        f"GravesLSTM char-RNN train-step tokens/sec/chip ({compute_dtype})",
        "tokens/sec/chip", batch * seq, timing, flops,
        dtype=compute_dtype, probe=run_chain, batch=batch, seq=seq)


def bench_charnn_f32(batch, steps):
    """Pure-f32 variant kept for the bf16-vs-f32 delta record."""
    return bench_charnn(batch, steps, compute_dtype="f32")


def build_bert(batch, cfg):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from deeplearning4j_tpu.utils.tracing import total_flops
    from deeplearning4j_tpu.zoo import transformer as tfm

    key = jax.random.PRNGKey(0)
    params = tfm.bert_init(key, cfg)
    opt = optax.adamw(2e-5)
    opt_state = opt.init(params)

    def step(params, opt_state, ids, labels):
        loss, grads = jax.value_and_grad(tfm.bert_classifier_loss)(
            params, cfg, ids, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    labels = jnp.asarray(rng.integers(0, cfg.num_labels, batch))
    flops = total_flops(step, params, opt_state, ids, labels)

    def step_once(p, o):
        p, o, loss = jstep(p, o, ids, labels)
        return (p, o), loss

    run_chain = chain_runner(step_once, [params, opt_state])
    run_chain.floor_probe = _make_floor_probe(jstep, params, opt_state,
                                              ids, labels)
    return run_chain, flops


def bench_bert(batch, steps):
    from deeplearning4j_tpu.zoo import transformer as tfm
    # r5 composition sweep (scripts/diag_bert_out.json): remat-full +
    # bf16-scores frees enough HBM for b128, MFU 0.40 -> 0.61 (b32 base
    # 0.40; b32 remat+bf16s 0.49; b64 0.59; b128 0.61)
    cfg = tfm.BertConfig(max_seq=128, remat=True, attn_scores_bf16=True)
    run_chain, flops = build_bert(batch, cfg)
    timing = measure_stable(run_chain, n1=3, n2=steps)
    return _record(
        "BERT-base fine-tune seq/sec/chip (T=128, remat-full bf16-scores)",
        "seq/sec/chip", batch, timing, flops, probe=run_chain,
        batch=batch, seq=cfg.max_seq)


def build_transformer(batch, cfg):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from deeplearning4j_tpu.utils.tracing import total_flops
    from deeplearning4j_tpu.zoo import transformer as tfm

    key = jax.random.PRNGKey(0)
    params = tfm.init_params(key, cfg)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    raw_step = tfm.make_train_step(cfg, opt)
    jstep = jax.jit(raw_step, donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq)))
    flops = total_flops(raw_step, params, opt_state, ids, tgt)
    # total_flops counts jaxpr dots — it cannot see inside a pallas_call,
    # so when the flash kernel engages the attention matmuls are missing
    # from the trace and every flash config at equal tokens/step traces
    # the same count. Add the kernel's analytic train-path flops (fwd 2 +
    # dq pass 3 + dkv pass 4 = 9 matmuls of 2*B*H*T*T*D, halved causal)
    # so flash-row MFU counts the T^2 work actually done. The engagement
    # test is the model's own gate (tfm.flash_engages), not a copy.
    # Known asymmetry (ADVICE r5 #2): under remat the pallas fwd re-runs
    # to rebuild vjp residuals (~2 extra matmuls/layer for save_attn and
    # full alike), which this top-up does NOT count — while the XLA
    # path's remat recompute IS in the jaxpr and counted. Flash rows'
    # MFU is therefore slightly UNDERstated relative to XLA rows when
    # cfg.remat is on; left uncounted deliberately (conservative skew —
    # the flash wins in PERF.md survive the handicap).
    t = cfg.max_seq
    flash_flops = 0
    if tfm.flash_engages(cfg, t):
        per_matmul = 0.5 * 2.0 * batch * cfg.n_heads * t * t * cfg.head_dim
        flash_flops = 9 * per_matmul * cfg.n_layers
        flops += flash_flops

    def step_once(p, o):
        p, o, loss = jstep(p, o, ids, tgt)
        return (p, o), loss

    run_chain = chain_runner(step_once, [params, opt_state])
    # the pallas flash kernel is opaque to cost_analysis AND the jaxpr
    # estimator — top the floor's flops up by the same analytic count
    # the MFU audit uses, so floor and MFU quote one flops accounting
    run_chain.floor_probe = _make_floor_probe(
        jstep, params, opt_state, ids, tgt, extra_flops=flash_flops)
    return run_chain, flops


def bench_transformer(batch, steps):
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo import transformer as tfm
    # r5 winner (scripts/diag_attn_r5_out.json): flash attention at the
    # grad-tuned flash5 blocks + remat that pins the attention outputs
    # ("save_attn") + fused chunked CE. At T=1024 b16 this measured 221k
    # tokens/s vs 187k for the r4 bf16-scores XLA config; b32 flash was
    # 223k. attn_scores_bf16 stays True for the off-TPU/multichip
    # fallback path (flash is single-chip TPU only).
    cfg = tfm.TransformerConfig(vocab_size=32000, d_model=512, n_heads=8,
                                n_layers=8, d_ff=2048, max_seq=1024,
                                dtype=jnp.bfloat16, fused_loss=True,
                                remat=True, remat_policy="save_attn",
                                attn_scores_bf16=True)
    run_chain, flops = build_transformer(batch, cfg)
    timing = measure_stable(run_chain, n1=3, n2=steps)
    return _record(
        "Transformer-LM (120M, T=1024, flash save-attn remat) tokens/sec/chip",
        "tokens/sec/chip", batch * cfg.max_seq, timing, flops,
        probe=run_chain, batch=batch, seq=cfg.max_seq)


def bench_transformer_long(batch, steps):
    """Long-context config: T=4096 at the same tokens/step as the T=1024
    config. This is the regime the pallas flash kernel exists for — the
    (B,H,T,T) score tensor the XLA path materializes is 1.6 GB bf16 per
    layer here, while the flash kernel streams it through VMEM. The r4
    0.057-MFU cliff was the fwd-only autotuner picking 128×128 blocks
    (34 ms/layer fwd+bwd vs 6.1 ms at 1024×1024 — diag_t4096 phase F);
    with grad-tuned flash5 blocks the r5 sweep measured 160k tokens/s
    remat-OFF (activations fit HBM at b4 once scores stay in VMEM) vs
    150k save-attn, 147k remat-full, 87k best-XLA
    (scripts/diag_attn_r5_out.json)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32000, d_model=512, n_heads=8,
                                n_layers=8, d_ff=2048, max_seq=4096,
                                dtype=jnp.bfloat16, remat=False)
    run_chain, flops = build_transformer(batch, cfg)
    timing = measure_stable(run_chain, n1=3, n2=steps)
    return _record(
        "Transformer-LM long-context (120M, T=4096, flash attn) tokens/sec/chip",
        "tokens/sec/chip", batch * cfg.max_seq, timing, flops,
        probe=run_chain, batch=batch, seq=cfg.max_seq)


def bench_transformer_xlong(batch, steps):
    """Extra-long context: T=8192 (double transformer_long's T at the same
    model). Pure flash-kernel territory — the XLA path's per-layer score
    tensor would be 4 GB bf16 and measured 2.4x slower (43.7k tokens/s,
    scripts/diag_attn_r5_out.json). Same lesson as T=4096: with scores
    streamed through VMEM the activations fit HBM without remat — b4
    remat-off measured 112.2k tokens/s vs 107k for b2 save_attn."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32000, d_model=512, n_heads=8,
                                n_layers=8, d_ff=2048, max_seq=8192,
                                dtype=jnp.bfloat16, remat=False)
    run_chain, flops = build_transformer(batch, cfg)
    timing = measure_stable(run_chain, n1=3, n2=steps)
    return _record(
        "Transformer-LM extra-long context (120M, T=8192, flash attn)"
        " tokens/sec/chip",
        "tokens/sec/chip", batch * cfg.max_seq, timing, flops,
        probe=run_chain, batch=batch, seq=cfg.max_seq)


def bench_dpoverhead(batch, steps):
    """Per-step wall-clock overhead of the dp-8 path vs single-device at the
    SAME global batch (8-device virtual CPU mesh).

    Unlike a "scaling efficiency" number — meaningless when 8 virtual
    devices share one host's cores — this isolates a real quantity: the
    extra per-step latency added by the ParallelWrapper machinery (sharding,
    psum collectives, multi-device dispatch) at equal total compute. ICI
    scaling itself is validated by the loss-equivalence tests in
    tests/test_parallel.py.

    Runs in a subprocess with a CPU-forced env (same reason as
    __graft_entry__.dryrun_multichip): the calling process may hold the TPU.
    """
    import os
    import re
    import subprocess

    from deeplearning4j_tpu.utils.subproc import cpu_forced_env

    env, preamble = cpu_forced_env(8)
    code = (
        preamble + "import bench; import json;"
        f"print('DPOVERHEAD ' + json.dumps("
        f"bench._dpoverhead_impl({batch}, {steps})))"
    )
    repo = os.path.dirname(os.path.abspath(__file__))
    metric = DPOVERHEAD_METRIC
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              cwd=repo, capture_output=True, text=True,
                              timeout=900)
    except subprocess.TimeoutExpired as e:
        return {"metric": metric, "error": f"timeout after {e.timeout}s"}
    m = re.search(r"DPOVERHEAD (\{.*\})", proc.stdout)
    if proc.returncode != 0 or not m:
        return {"metric": metric,
                "error": (proc.stdout + proc.stderr)[-500:]}
    # stamp in the PARENT (the CPU-forced subprocess has no session
    # identity): the row keys trend history by the capture session's
    # backend/sha like every other row — without it the ledger files
    # this capture under backend "unknown", disconnected from the
    # BENCH_r* tail history (ISSUE 15 backfill found exactly that)
    return _stamp(json.loads(m.group(1)))


def _dpoverhead_impl(batch, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.nn import (DenseLayer, MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
    from deeplearning4j_tpu.train import Adam

    def build():
        conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=256, n_out=512, activation="relu"))
                .layer(DenseLayer(n_out=512, activation="relu"))
                .layer(OutputLayer(n_out=10, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init((256,))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((batch, 256), np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])

    def per_step_ms(fit_once):
        fit_once()  # compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                fit_once()
            best = min(best, time.perf_counter() - t0)
        return best / steps * 1e3

    from deeplearning4j_tpu.data.dataset import DataSet
    ds = DataSet(x, y)
    net1 = build()
    t1 = per_step_ms(lambda: net1.fit(ds))
    net8 = build()
    pw = ParallelWrapper(net8, mesh=make_mesh(jax.devices()[:8], dp=8))
    t8 = per_step_ms(lambda: pw.fit([ds]))
    # scanned-dp: K batches per dispatch — the per-step dispatch share of
    # the dp overhead amortizes to ~1/K (r4-s2 ParallelWrapper.fit_scanned)
    k = max(4, steps)
    dss = [ds] * k
    net8s = build()
    pws = ParallelWrapper(net8s, mesh=make_mesh(jax.devices()[:8], dp=8))
    pws.fit_scanned(dss)   # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        pws.fit_scanned(dss)
        best = min(best, time.perf_counter() - t0)
    t8s = best / k * 1e3
    return {"metric": DPOVERHEAD_METRIC,
            # explicit floor-lack: this row is an overhead DELTA between
            # two configs, not a throughput with a single-step roofline
            # (refresh_readme_table flags rows with NO floor key at all)
            "floor": {"na": "overhead-delta row; no single-step roofline"},
            "value": round(t8 - t1, 3), "unit": "ms/step",
            "single_ms": round(t1, 3), "dp8_ms": round(t8, 3),
            "dp8_scanned_ms": round(t8s, 3),
            "scanned_batches_per_dispatch": k,
            "global_batch": batch,
            "note": "equal global batch, equal total compute; the delta is "
                    "the sharding/collective/dispatch cost of the dp path "
                    "(dp8_scanned_ms = same step inside one lax.scan "
                    "dispatch per epoch). ICI scaling equivalence: "
                    "tests/test_parallel.py"}


def build_resnet50_fit(batch, num_classes=1000, n_distinct=8,
                       return_parts=False):
    """(run_fit(n)->last_loss, flops) through the REAL user entry point:
    ``ComputationGraph.fit(iterator)`` — iterator protocol, async-wrap
    check, optimizer build, jitted donated step, listener plumbing all
    engaged. Batches are PRE-STAGED on device: the axon tunnel's
    host->device link (a network relay) is orders of magnitude slower than
    a real TPU host's PCIe/DMA path, so streaming fresh host batches would
    measure the tunnel, not the framework; `n_distinct` staged batches
    cycle so no single-buffer reuse artifact exists on device."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.train import Momentum
    from deeplearning4j_tpu.utils.tracing import total_flops
    from deeplearning4j_tpu.zoo.resnet import ResNet50

    net = ResNet50(num_classes=num_classes, compute_dtype=jnp.bfloat16,
                   updater=Momentum(0.1, 0.9),
                   remat_segments=RESNET_REMAT).init()
    rng = np.random.default_rng(0)
    dss = []
    for i in range(n_distinct):
        x = jnp.asarray(rng.random((batch, 224, 224, 3), np.float32),
                        jnp.bfloat16)
        y = jnp.asarray(np.eye(num_classes, dtype=np.float32)[
            rng.integers(0, num_classes, batch)])
        dss.append(DataSet(x, y))

    net._build_optimizer(1)
    step = net._get_train_step()
    flops = total_flops(
        lambda p, s, o: step.__wrapped__(
            p, s, o, {"in": dss[0].features}, {"out": dss[0].labels},
            jax.random.PRNGKey(0), None, None)[:3],
        net.params, net.states, net._opt_state)

    def run_fit(n):
        batches = [dss[i % n_distinct] for i in range(n)]
        return net.fit(batches)   # float(last loss) = the host-fetch sync

    run_fit.floor_probe = _make_floor_probe(
        step, net.params, net.states, net._opt_state,
        {"in": dss[0].features}, {"out": dss[0].labels},
        jax.random.PRNGKey(0), None, None)
    if return_parts:
        return run_fit, flops, net, dss
    return run_fit, flops


def bench_resnet50_fitscan(batch, steps):
    """fit_scanned variant of the headline: the SAME ComputationGraph
    train step scanned over the epoch's batches in one dispatch
    (bit-identical trajectory to fit(); tests/test_fit_scanned.py). The
    delta vs the fit() record is the per-batch dispatch overhead a user
    recovers by switching entry points."""
    run_fit, flops, net, dss = build_resnet50_fit(batch, return_parts=True)

    def run_scan(n):
        return net.fit_scanned([dss[i % len(dss)] for i in range(n)])

    run_scan.floor_probe = run_fit.floor_probe   # same per-step work
    timing = measure_stable(run_scan, n1=3, n2=steps)
    rec = _record(
        "ComputationGraph.fit_scanned samples/sec/chip "
        "(ResNet-50, scan-dispatch)",
        "samples/sec/chip", batch, timing, flops, probe=run_scan,
        batch=batch)
    rec["vs_baseline"] = round(rec["value"] / BASELINE_SAMPLES_PER_SEC, 3)
    return rec


def bench_resnet50_fit(batch, steps):
    run_fit, flops = build_resnet50_fit(batch)
    timing = measure_stable(run_fit, n1=3, n2=steps)
    rec = _record(
        "ComputationGraph.fit(DataSetIterator) samples/sec/chip "
        "(ResNet-50 ImageNet)",
        "samples/sec/chip", batch, timing, flops, probe=run_fit,
        batch=batch,
        data_path="pre-staged device batches (tunnel host link not "
                  "representative; fit loop fully engaged)")
    rec["vs_baseline"] = round(rec["value"] / BASELINE_SAMPLES_PER_SEC, 3)
    return rec


def build_resnet50(batch, num_classes=1000):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from deeplearning4j_tpu.utils.tracing import total_flops
    from deeplearning4j_tpu.zoo.resnet import ResNet50

    net = ResNet50(num_classes=num_classes, compute_dtype=jnp.bfloat16,
                   remat_segments=RESNET_REMAT).init()
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(net.params)

    def train_step(params, states, opt_state, x, y):
        def loss_fn(p, s):
            acts, pre, new_s = net._forward(p, s, {"in": x}, train=True,
                                            rng=None,
                                            stop_at_output_preact=True)
            out_layer = net.conf.nodes["out"].op
            loss = out_layer.compute_loss(p["out"], pre["out"], y)
            return loss, new_s

        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, states)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_states, opt_state, loss

    jstep = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((batch, 224, 224, 3), np.float32),
                    jnp.bfloat16)
    y = jnp.asarray(np.eye(num_classes, dtype=np.float32)[
        rng.integers(0, num_classes, batch)])
    flops = total_flops(train_step, net.params, net.states, opt_state, x, y)

    def step_once(p, s, o):
        p, s, o, loss = jstep(p, s, o, x, y)
        return (p, s, o), loss

    run_chain = chain_runner(step_once, [net.params, net.states, opt_state])
    run_chain.floor_probe = _make_floor_probe(
        jstep, net.params, net.states, opt_state, x, y)
    return run_chain, flops


def bench_resnet50(batch, steps):
    run_chain, flops = build_resnet50(batch)
    timing = measure_stable(run_chain, n1=3, n2=steps)
    rec = _record(
        "MultiLayerNetwork.fit() samples/sec/chip (ResNet-50 ImageNet)",
        "samples/sec/chip", batch, timing, flops, probe=run_chain,
        batch=batch)
    rec["vs_baseline"] = round(rec["value"] / BASELINE_SAMPLES_PER_SEC, 3)
    return rec


# ------------------------------------------------------------ inference
# Serving-plane rows (ISSUE 10) — written to the `inference` section of
# bench_secondary.json. Captured wherever they run; a row captured off-TPU
# is flagged `on_chip_todo` the same way the floor tables flag CPU-derived
# flops (the schema and code path are proven now, the chip re-capture is
# `bench.py --refresh inference_...`).

def _flag_on_chip(rec):
    if rec.get("backend") != "tpu":
        rec["on_chip"] = False
        rec["on_chip_todo"] = ("CPU-derived row — re-capture on the real "
                               "chip via bench.py --refresh")
    return rec


def _serving_engine(max_seq):
    """Flagship 120M Transformer-LM generation engine at context max_seq.
    remat off: generation is forward-only, there are no residuals to
    trade; flash/bf16-scores gating is the model's own (prefill runs the
    same _attention the training forward does)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.serving import GenerationEngine
    from deeplearning4j_tpu.zoo import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32000, d_model=512, n_heads=8,
                                n_layers=8, d_ff=2048, max_seq=max_seq,
                                dtype=jnp.bfloat16, remat=False)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    return GenerationEngine(cfg, params), cfg


def _slo_compact(report):
    """The compact `slo` block a bench row embeds: goodput + ITL/TTFT
    p99 beside the throughput number, so the decode-slot sweep (ROADMAP
    item 1) optimizes goodput at target, not raw tokens/s. The full
    targets ride along — the row self-describes its verdict."""
    if report.get("goodput") is None:
        return {"na": "no SLO-eligible requests"}
    t = report["targets"]
    ms = lambda v: None if v is None else round(v * 1e3, 2)  # noqa: E731
    out = {
        "goodput": round(report["goodput"], 4),
        "ttft_p99_ms": ms(report.get("ttft", {}).get("p99_s")),
        "itl_p99_ms": ms(report.get("itl", {}).get("p99_s")),
        "error_rate": round(report["error_rate"], 4),
        "burn_rate": round(report["burn_rate"], 3),
        "met": report["met"],
        "requests": report["window"]["requests"],
        "targets": {"ttft_ms": ms(t["ttft_s"]), "itl_ms": ms(t["itl_s"]),
                    "quantile": t["quantile"]},
    }
    if report.get("itl", {}).get("samples") is not None:
        out["itl_samples"] = report["itl"]["samples"]
    return out


def _mem_peak(pytree_total):
    """(peak_bytes, source): the allocator's peak where the backend has
    memory_stats (TPU/GPU), else the pytree census total — the CPU
    tier-1 path still gets a number (ISSUE 12)."""
    from deeplearning4j_tpu.obs import device_memory_stats
    stats = device_memory_stats()
    if stats and stats.get("peak_bytes_in_use"):
        return int(stats["peak_bytes_in_use"]), "memory_stats"
    return int(pytree_total), "pytree"


def _mem_basic(params_tree, kv_pool_bytes=None, **kv_fields):
    """Memory block builder — the ONE place the row schema lives
    (peak/source/params_bytes core + optional kv_* fields), so the
    decode, TTFT, and batch-1 rows can't drift apart. For a paged pool
    (ISSUE 14) ``kv_pool_bytes`` is the device's actual KV reservation
    (allocated_bytes tracks MAPPED pages, which undercounts the pytree
    footprint). Never fatal."""
    try:
        from deeplearning4j_tpu.obs import tree_bytes
        pb = tree_bytes(params_tree)
        kv_dev = kv_pool_bytes if kv_pool_bytes is not None \
            else kv_fields.get("kv_allocated_bytes", 0)
        peak, src = _mem_peak(pb + (kv_dev or 0))
        return {"peak_bytes": peak, "source": src, "params_bytes": pb,
                **kv_fields}
    except Exception as e:  # noqa: BLE001 — the row survives block-less
        return {"na": f"memory block failed: "
                      f"{type(e).__name__}: {e}"[:300]}


def _fid_compact(report):
    """The compact per-pair fidelity evidence a bench row embeds."""
    r = lambda v, n=8: round(float(v), n)  # noqa: E731
    return {"max_abs_err": r(report["max_abs_err"]),
            "mean_abs_err": r(report["mean_abs_err"]),
            "kl_mean": r(report["kl_mean"], 9),
            "kl_max": r(report["kl_max"], 9),
            "topk_agreement": r(report["topk_agreement"], 4),
            "greedy_match_frac": r(report["greedy_match_frac"], 4),
            "greedy_prefix_len": report["greedy_prefix_len"]}


def _fidelity_block(eng, probe_tokens=128):
    """Fidelity evidence beside the floor/slo/memory blocks (ISSUE 13):
    the row's engine forward run over the SAME probe prompt through
    three attention/dtype paths, compared by ``obs.fidelity``:

    - ``flash_vs_xla``: pallas flash kernel (interpret mode off-TPU —
      the same numerics CI covers) vs the row's XLA attention path,
      same compute dtype;
    - ``bf16_vs_fp32``: the row's deployed path (bf16 activations +
      bf16-scores gating as configured) vs an exact-f32 reference.

    These are the measured logit-error baselines the quantized-KV and
    spec-decode rows (ROADMAP 3) will be judged against — a candidate
    that beats the floor but drifts past today's flash/bf16 envelope
    is a different model, not a faster one. Never fatal."""
    import dataclasses
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.obs.fidelity import FidelityProbe
    from deeplearning4j_tpu.zoo import transformer as tfm

    cfg = eng.cfg
    t = int(min(probe_tokens, cfg.max_seq))
    ids = jnp.asarray(np.random.default_rng(7).integers(
        0, cfg.vocab_size, (1, t)), jnp.int32)

    def logits(**over):
        c = dataclasses.replace(cfg, **over) if over else cfg
        return np.asarray(tfm.forward(eng.params, c, ids)[0], np.float32)

    # the row's deployed XLA path at its own dtype/score gating — also
    # the bf16 candidate (flash's auto-gate never engages at the probe
    # length, so this IS what the row serves off-flash)
    xla = logits(use_flash_attention=False)
    flash = logits(use_flash_attention=True)
    f32 = logits(use_flash_attention=False, dtype=jnp.float32,
                 attn_scores_bf16=False)
    return {
        "probe_tokens": t,
        "flash_vs_xla": _fid_compact(
            FidelityProbe("flash_vs_xla").compare(xla, flash)),
        "bf16_vs_fp32": _fid_compact(
            FidelityProbe("bf16_vs_fp32").compare(f32, xla)),
    }


def _attach_fidelity(rec, eng):
    try:
        rec["fidelity"] = _fidelity_block(eng)
    except Exception as e:  # noqa: BLE001 — the row survives block-less
        rec["fidelity"] = {"na": f"fidelity probe failed: "
                                 f"{type(e).__name__}: {e}"[:300]}
    return rec


def _paged_kernel_ab(eng, slots=4, floor_ms=None):
    """Kernel-vs-XLA A/B for the decode row (ISSUE 17): run the
    fidelity-gated promotion race over probe paged caches of the row's
    own geometry (dense byte budget re-cut into DEFAULT_PAGE_LEN
    pages) and report both arms — tokens/s, pct_of_floor against the
    row's roofline floor, the fidelity kl_max that gated promotion,
    and the verdict that landed as a sha-stamped cost record. Off-TPU
    the kernel arm runs in pallas interpret mode, so its timing is a
    plumbing check (the verdict records ``fallback_slower`` — the
    baseline is NOT re-pinned on it); on-chip the same block is the
    promotion's citable evidence."""
    from deeplearning4j_tpu.kernels.paged_attention import race
    from deeplearning4j_tpu.serving import kvcache

    plen = kvcache.DEFAULT_PAGE_LEN
    n_pages = slots * (-(-eng.max_len // plen))
    cache = eng.init_paged_cache(slots, n_pages, plen)
    res = race(eng, cache)

    def arm(step_s):
        if step_s is None:
            return None
        return {"step_time_ms": round(step_s * 1e3, 3),
                "tokens_per_s": round(slots / step_s, 2),
                "pct_of_floor": (None if not floor_ms or step_s <= 0
                                 else round(floor_ms / (step_s * 1e3), 4))}

    rep = eng.compile_report()
    return {
        "slots": slots, "page_len": plen, "n_pages": n_pages,
        "verdict": res["verdict"],
        "promoted": res["choice"] == "kernel",
        "gather": arm(res["gather_s"]),
        "kernel": arm(res["kernel_s"]),
        "speedup_kernel_over_gather": res["speedup"],
        "fidelity_kl_max": res["fidelity"]["kl_max"],
        "greedy_match_frac": res["fidelity"]["greedy_match_frac"],
        "cost_record": res["key"],
        # one compile per arm, both pre-warmed by the race itself — the
        # dispatch decision never costs the serve loop a retrace
        "kernel_compiles": rep["decode_paged_kernel"]["compiles"],
    }


def _serve_blocks(eng, slots, n_requests=None, new_tokens=8,
                  prompt_len=64, paged=False, concurrency_x=3):
    """(slo, memory) evidence from ONE real continuous-batching serve
    over the row's engine: submit a mixed-length wave through the
    scheduler with per-request ITL tracing + KV residency accounting
    on, report the rolling-window SLO verdict beside the memory
    attribution (ISSUE 11 + 12). The warm-up request keeps compile time
    out of the steady-state verdict (the same discipline every timed
    row uses); prompt lengths step down across the wave so the
    kv_waste_ratio is measured under genuinely mixed traffic.

    ``paged=True`` (ISSUE 14) serves the SAME wave shape through the
    block-paged pool at the SAME KV byte budget as the dense baseline
    (``slots × max_len`` rows re-cut into DEFAULT_PAGE_LEN pages) but
    ``concurrency_x × slots`` decode lanes — the measured
    ``peak_concurrent`` vs the dense slot count is the
    concurrency-at-equal-bytes claim, and ``kv_waste_ratio`` drops from
    the dense 0.96 to page-tail-only waste. Never fatal — the row
    survives block-less."""
    import numpy as np
    from deeplearning4j_tpu.obs import SLOConfig, SLOTracker
    from deeplearning4j_tpu.serving import (ContinuousBatchingScheduler,
                                            DEFAULT_PAGE_LEN)

    if paged:
        # equal KV byte budget: the dense pool's slots × max_len rows,
        # re-cut into pages shared by concurrency_x× as many lanes
        n_pages = slots * eng.max_len // DEFAULT_PAGE_LEN
        sched = ContinuousBatchingScheduler(
            eng, n_slots=slots * concurrency_x,
            page_len=DEFAULT_PAGE_LEN, n_pages=n_pages)
    else:
        sched = ContinuousBatchingScheduler(eng, n_slots=slots)
    n_requests = n_requests or 2 * sched.n_slots
    rng = np.random.default_rng(1)
    warm = sched.submit(rng.integers(0, eng.cfg.vocab_size, (prompt_len,)),
                        max_new_tokens=2)
    sched.run_until_idle()
    warm.result(timeout=600)
    eng.mark_warm()    # any compile past here is a warned retrace
    sched.slo = SLOTracker(SLOConfig())   # measured window starts here
    sched.reset_kv_window()   # memory evidence covers the SAME window
    lstep = max(1, prompt_len // 16)
    futs = [sched.submit(
        rng.integers(0, eng.cfg.vocab_size,
                     (max(1, prompt_len - (i % 8) * lstep),)),
        max_new_tokens=new_tokens + (i % 3)) for i in range(n_requests)]
    sched.run_until_idle()
    for f in futs:
        f.result(timeout=600)
    kv = sched.kv_report()
    mem = _mem_basic(
        eng.params,
        kv_pool_bytes=kv["pool_bytes"] if paged else None,
        kv_allocated_bytes=(kv["allocated_bytes_mean"] if paged
                            else kv["allocated_bytes"]),
        kv_token_bytes=kv["token_bytes"],
        kv_waste_ratio=kv["waste_ratio_mean"],
        final_residency_mean=kv["final_residency_mean"],
        retraces_after_warm=sum(s["retraces_after_warm"]
                                for s in eng.compile_report().values()))
    if paged:
        # the ISSUE 14 claim, measured: lanes actually served
        # concurrently from the dense baseline's byte budget
        mem["paged"] = {
            **kv["paged"],
            "pool_bytes": kv["pool_bytes"],
            "dense_equiv_slots": slots,
            "peak_concurrent": kv["peak_concurrent"],
            "concurrency_x": round(kv["peak_concurrent"] / slots, 2),
        }
    # HBM bytes the pool pays per token actually resident (mean over
    # the serve) — the serving-efficiency number paged KV and quantized
    # caches (ROADMAP items 1, 3) must push down
    res_tokens = (kv["resident_bytes_mean"] / kv["token_bytes"]
                  if kv["token_bytes"] else 0.0)
    if "peak_bytes" in mem:
        mem["bytes_per_resident_token"] = \
            round(mem["peak_bytes"] / res_tokens, 1) if res_tokens else None
    return _slo_compact(sched.slo.report()), mem


def _chunked_admission_itl(eng, seq, dense_stall_ms=None, slots=8,
                           baseline_sweeps=24, short_len=32,
                           chunk_len=16):
    """The ISSUE 14 ITL claim, measured: decode-sweep wall (= the
    active requests' ITL) for a paged pool of ``slots`` short decoding
    requests, with vs without a T=``seq`` prompt chunk-prefilling in.
    Under chunked admission each step is one chunk + one sweep, so the
    p99 must hold ≤2× the no-admission baseline — where the dense path
    stalls every slot for the WHOLE prefill (``dense_stall_ms``: the
    row's own TTFT median, the before number).

    ``chunk_len`` is the ITL-bound side of the knob trade: one chunk's
    cost must stay well under one decode sweep's (measured on the CPU
    capture: a chunk has a ~0.8 s floor at ctx=4096 — the full-width
    page gather — plus ~10 ms/token, so 128-token chunks cost ~2.5
    2-slot sweeps → 3.5× p99; 16-token chunks ride just above the
    floor). The TTFT-amortization side picks larger chunks — that is
    the ``serving_prefill_chunk`` autotune record's verdict; this
    block records both sides. ``slots`` sizes the
    baseline pool the admission disturbs: the sweep cost scales with
    occupancy while the chunk cost is constant, so the claim is judged
    at a realistically busy pool (the decode row's 8 lanes), not an
    idle one a single chunk would dominate."""
    import numpy as np
    from deeplearning4j_tpu.serving import (ContinuousBatchingScheduler,
                                            DEFAULT_PAGE_LEN,
                                            GenerationEngine)

    if chunk_len != eng.chunk_len:
        # chunk size is engine geometry (it fixes the chunk buckets):
        # a dedicated engine over the SAME params serves the experiment
        eng = GenerationEngine(eng.cfg, eng.params, max_len=eng.max_len,
                               prefill_chunk=chunk_len)
    # the admission prompt: T=seq less the decode budget that keeps it
    # resident through the steady window (stays inside max_len)
    long_len = min(seq, eng.max_len - baseline_sweeps - 1)
    chunks = -(-long_len // eng.chunk_len)
    rng = np.random.default_rng(3)
    budget = 2 * baseline_sweeps + chunks + 12
    # pages for the full working set: the long admission + every short
    # request's whole prompt+budget — page PRESSURE preemptions would
    # contaminate the ITL measurement
    per_short = -(-(short_len + budget) // DEFAULT_PAGE_LEN)
    n_pages = -(-seq // DEFAULT_PAGE_LEN) + slots * per_short + 4
    sched = ContinuousBatchingScheduler(eng, n_slots=slots + 1,
                                        page_len=DEFAULT_PAGE_LEN,
                                        n_pages=n_pages)
    # warm every shape this experiment touches: a chunk_len-long prompt
    # (the long admission's bucket), a short_len prompt, decode, sample
    for warm_len in (eng.chunk_len, short_len):
        w = sched.submit(rng.integers(0, eng.cfg.vocab_size, (warm_len,)),
                         max_new_tokens=2)
        sched.run_until_idle()
        w.result(timeout=600)
    shorts = [sched.submit(
        rng.integers(0, eng.cfg.vocab_size, (short_len,)),
        max_new_tokens=budget) for _ in range(slots)]
    for _ in range(2):
        sched.step()                    # admit; exclude ramp-up steps
    base = []
    for _ in range(baseline_sweeps):
        t0 = time.perf_counter()
        sched.step()
        base.append(time.perf_counter() - t0)
    # budget > 1 keeps the long request DECODING (pages mapped) after
    # its prefill, so the steady window below sees the same working set
    long_fut = sched.submit(
        rng.integers(0, eng.cfg.vocab_size, (long_len,)),
        max_new_tokens=baseline_sweeps + 2)

    def _prefilling():
        return any(r is not None and r.pending is not None
                   for r in sched.slots)

    adm = []
    while len(adm) < 4 * chunks + 8:
        t0 = time.perf_counter()
        sched.step()       # first iteration admits the long request
        adm.append(time.perf_counter() - t0)
        if not _prefilling():
            break
    # steady-state baseline at EQUAL residency: the T=seq context is
    # resident and decoding, no admission in progress — sweeps here pay
    # the same KV bytes the admission-window sweeps paid, so the ratio
    # isolates the admission MECHANICS (the chunk interleave) from the
    # permanent cost of holding seq more resident tokens, which any
    # admission policy pays
    steady = []
    for _ in range(baseline_sweeps):
        t0 = time.perf_counter()
        sched.step()
        steady.append(time.perf_counter() - t0)
    sched.run_until_idle()
    for f in shorts:
        f.result(timeout=600)
    long_res = long_fut.result(timeout=600)
    p99 = lambda xs: sorted(xs)[min(len(xs) - 1,  # noqa: E731
                                    int(round(0.99 * (len(xs) - 1))))]
    base_p99, adm_p99, steady_p99 = p99(base), p99(adm), p99(steady)
    ratio_resident = round(adm_p99 / steady_p99, 3) if steady_p99 else None
    ratio_idle = round(adm_p99 / base_p99, 3) if base_p99 else None
    return {
        "page_len": DEFAULT_PAGE_LEN, "chunk_len": eng.chunk_len,
        "chunks": chunks, "long_prompt_tokens": long_len,
        "decode_slots": slots,
        "baseline_itl_p99_ms": round(steady_p99 * 1e3, 2),
        "pre_admission_itl_p99_ms": round(base_p99 * 1e3, 2),
        "admission_itl_p99_ms": round(adm_p99 * 1e3, 2),
        "admission_over_baseline": ratio_resident,
        "admission_over_pre_admission": ratio_idle,
        "met_2x": ratio_resident is not None and ratio_resident <= 2.0,
        "dense_admission_stall_ms": dense_stall_ms,
        "long_ttft_ms": round(long_res.ttft_s * 1e3, 1),
        "note": "per-sweep wall of the decoding pool while the T="
                f"{seq} prompt chunks in. Baseline = steady-state "
                "sweeps at EQUAL residency (the prompt resident and "
                "decoding, no admission running): paged KV reads "
                "scale with resident bytes, so pre-admission sweeps "
                "(pre_admission_itl_p99_ms) are structurally cheaper "
                "in a way any admission policy would forfeit. Dense "
                "admission stalls every slot for the whole prefill "
                "(the row's TTFT median)",
    }


def bench_inference_decode(batch, steps):
    """Decode tokens/sec/chip: one jitted donated-cache decode_step +
    greedy sample per sweep over a `batch`-slot pool (the serving hot
    path, T=1024 cache). Marginal chained-step timing like every other
    row; flops from the traced decode step (attention against the full
    static cache length — the work actually dispatched)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.utils.tracing import total_flops

    eng, cfg = _serving_engine(1024)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, 64)))
    cache = eng.init_cache(batch)
    logits, cache = eng.prefill(cache, prompt)
    tokens = jnp.argmax(logits, -1).astype(jnp.int32)
    flops = total_flops(eng._decode_raw, eng.params, cache, tokens)

    def step_once(cache, tokens):
        logits, cache = eng.decode_step(cache, tokens)
        toks = jnp.argmax(logits, -1).astype(jnp.int32)
        return (cache, toks), toks

    run_chain = chain_runner(step_once, [cache, tokens])
    run_chain.floor_probe = _make_floor_probe(eng._decode, eng.params,
                                              cache, tokens)
    timing = measure_stable(run_chain, n1=5, n2=steps)
    rec = _record(
        "Serving decode tokens/sec/chip (Transformer-LM 120M, KV-cache "
        "T=1024, greedy)",
        "tokens/sec/chip", batch, timing, flops, probe=run_chain,
        slots=batch, prefill_tokens=64,
        note="one continuous-batching decode sweep = one token per slot; "
             "scheduler occupancy metrics: dl4j_serving_*")
    # the SLO + memory verdicts beside the floor block (ISSUE 11 + 12 +
    # 14): goodput at target AND kv waste from ONE real mixed-length
    # scheduler serve — now through the block-paged pool at the dense
    # baseline's byte budget (slots × max_len re-cut into pages,
    # concurrency_x× the lanes): memory.paged carries the measured
    # peak_concurrent / concurrency_x, and kv_waste_ratio is page-tail
    # waste, not the dense 0.96
    try:
        rec["slo"], rec["memory"] = _serve_blocks(eng, slots=batch,
                                                  paged=True)
    except Exception as e:  # noqa: BLE001 — the row survives block-less
        rec["slo"] = {"na": f"slo serve failed: "
                            f"{type(e).__name__}: {e}"[:300]}
        rec["memory"] = {"na": "see slo"}
    # fidelity evidence (ISSUE 13): flash-vs-XLA + bf16-vs-fp32 logit
    # error over the row's own engine — the measured numerics envelope
    # the quantized-KV / spec-decode rows must stay inside
    _attach_fidelity(rec, eng)
    # paged-decode kernel-vs-XLA A/B (ISSUE 17): the promotion race's
    # verdict + both arms' tokens/s beside the row, and the race's own
    # fidelity probe joins the fidelity block so fidelity_report.py
    # gates the kernel capture like every other pair
    try:
        floor_ms = (rec.get("floor") or {}).get("floor_ms")
        rec["paged_kernel_ab"] = _paged_kernel_ab(eng, slots=4,
                                                  floor_ms=floor_ms)
        if isinstance(rec.get("fidelity"), dict) \
                and "na" not in rec["fidelity"]:
            from deeplearning4j_tpu.kernels import autotune as _at
            meta = _at.measurement_meta(
                rec["paged_kernel_ab"]["cost_record"]) or {}
            fid = meta.get("fidelity")
            if fid:
                rec["fidelity"]["paged_kernel_vs_xla"] = _fid_compact(fid)
    except Exception as e:  # noqa: BLE001 — the row survives block-less
        rec["paged_kernel_ab"] = {"na": f"kernel A/B failed: "
                                        f"{type(e).__name__}: {e}"[:300]}
    return _flag_on_chip(rec)


def _ttft_row(seq, reps, chunked_admission=False):
    """Time-to-first-token at a `seq`-token prompt: wall-clock of one
    jitted prefill + greedy sample + host fetch (compile excluded,
    median of `reps`). This is the latency a request pays before its
    decode slot starts streaming. ``chunked_admission`` additionally
    measures the ISSUE 14 interleave claim: a paged pool's decode ITL
    p99 while this row's prompt chunk-prefills in, vs no admission."""
    import jax.numpy as jnp
    import numpy as np
    import statistics

    eng, cfg = _serving_engine(seq)
    rng = np.random.default_rng(0)
    prompt = np.asarray(rng.integers(0, cfg.vocab_size, (seq,)), np.int32)
    # caches pre-allocated outside the timed region (prefill donates its
    # cache arg; a served slot reuses pool HBM, it doesn't re-alloc)
    caches = [eng.init_cache(1) for _ in range(reps + 1)]
    samples = []
    for i, cache in enumerate(caches):
        t0 = time.perf_counter()
        logits, cache = eng.prefill_slot(cache, prompt, 0)
        tok = int(np.asarray(jnp.argmax(logits)))
        dt = time.perf_counter() - t0
        if i:                      # first call pays compile — excluded
            samples.append(dt)
    med = float(statistics.median(samples))
    try:
        from deeplearning4j_tpu.obs import get_registry
        get_registry().histogram(
            "dl4j_serving_ttft_seconds",
            "Time from submit to first generated token").observe(med)
    except Exception:  # noqa: BLE001 — telemetry mirror is decoration
        pass
    rec = {
        "metric": f"Serving time-to-first-token, T={seq} prefill "
                  "(Transformer-LM 120M)",
        "value": round(med * 1e3, 1), "unit": "ms",
        "prefill_tokens": seq, "reps": len(samples),
        "ttft_ms_samples": [round(s * 1e3, 1) for s in samples],
        "first_token": tok,
        "timing": "wall-clock prefill_slot + greedy sample + host fetch, "
                  "compile excluded, median of reps",
        "metrics": {"dl4j_serving_ttft_seconds": med},
    }
    # offline SLO verdict over the same samples (each rep is one
    # 1-token request): TTFT attainment/goodput at the default target
    try:
        from deeplearning4j_tpu.obs import SLOConfig, SLOTracker
        slo = SLOTracker(SLOConfig(), registry=False)
        for s in samples:
            slo.observe_summary({"status": "finish", "ttft_s": s,
                                 "itl_s": []})
        rec["slo"] = _slo_compact(slo.report())
    except Exception as e:  # noqa: BLE001 — the row survives SLO-less
        rec["slo"] = {"na": f"slo derivation failed: "
                            f"{type(e).__name__}: {e}"[:300]}
    if chunked_admission:
        # the chunked-prefill ITL verdict (ISSUE 14) rides this row's
        # slo block: its prompt length is the admission under test
        try:
            rec["slo"]["chunked_admission"] = _chunked_admission_itl(
                eng, seq, dense_stall_ms=rec["value"])
        except Exception as e:  # noqa: BLE001 — row survives block-less
            rec["slo"]["chunked_admission"] = {
                "na": f"admission experiment failed: "
                      f"{type(e).__name__}: {e}"[:300]}
    # memory attribution for the prefill path (ISSUE 12): one slot
    # filled to its prompt length — waste is the tail of max_len the
    # fixed slot preallocates past the prompt
    try:
        from deeplearning4j_tpu.serving import cache_nbytes, token_nbytes
        rec["memory"] = _mem_basic(
            eng.params,
            kv_allocated_bytes=cache_nbytes(cache),
            kv_token_bytes=token_nbytes(cache),
            kv_waste_ratio=round(1.0 - seq / eng.max_len, 6))
        if "peak_bytes" in rec["memory"]:
            rec["memory"]["bytes_per_resident_token"] = \
                round(rec["memory"]["peak_bytes"] / seq, 1)
    except Exception as e:  # noqa: BLE001 — the row survives block-less
        rec["memory"] = {"na": f"memory block failed: "
                               f"{type(e).__name__}: {e}"[:300]}
    # fidelity evidence (ISSUE 13) beside the slo/memory blocks
    _attach_fidelity(rec, eng)
    return _flag_on_chip(_stamp(rec))


def bench_inference_ttft_1024(batch, steps):
    return _ttft_row(1024, reps=max(steps, 2))


def bench_inference_ttft_4096(batch, steps):
    # the T=4096 admission is the ISSUE 14 worst case: measure the
    # chunked-prefill ITL interleave beside the raw prefill latency
    return _ttft_row(4096, reps=max(steps, 2), chunked_admission=True)


def bench_inference_prefix_shared(batch, steps):
    """CoW prefix cache row (ISSUE 16): `batch` requests share a
    1024-token common prefix (the system-prompt shape) with mixed
    random tails. Three phases against the same page budget:

    - sharing ON, sequential: a cold leader pays the full prefill,
      then every follower admits against the cached prefix and
      chunk-prefills only its tail — warm TTFT median is the row value;
    - sharing ON, concurrent: `slots` requests decode together while
      the page table is sampled — tokens-resident-per-user with the
      prefix counted ONCE (used pages) vs per-slot (mapped pages, what
      a no-sharing pool holds);
    - sharing OFF, same prompts: measured cold TTFT AND a greedy
      bit-equivalence check against the sharing-on outputs.
    """
    import numpy as np
    import statistics
    from deeplearning4j_tpu.serving import (ContinuousBatchingScheduler,
                                            DEFAULT_PAGE_LEN)

    prefix_len, slots = 1024, 8
    n_req = max(batch, 2)
    new_tokens = max(steps, 2)
    eng, cfg = _serving_engine(prefix_len + 128)
    pages_per_slot = -(-cfg.max_seq // DEFAULT_PAGE_LEN)
    n_pages = slots * pages_per_slot     # the dense-equivalent budget
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, (prefix_len,)).astype(
        np.int32)
    prompts = [np.concatenate([prefix, rng.integers(
        0, cfg.vocab_size, (int(rng.integers(8, 65)),)).astype(np.int32)])
        for _ in range(n_req)]

    sched = ContinuousBatchingScheduler(eng, n_slots=slots,
                                        page_len=DEFAULT_PAGE_LEN,
                                        n_pages=n_pages,
                                        prefix_cache=True)
    # cold leader: full-prefix prefill, pages cached at retirement
    leader = sched.submit(prompts[0], max_new_tokens=new_tokens)
    sched.run_until_idle()
    ttft_cold = leader.result(timeout=1200).ttft_s
    # warm followers, sequential (queue-free TTFT): tail-only prefill
    warm_samples, on_tokens = [], {}
    for i, p in enumerate(prompts[1:], start=1):
        f = sched.submit(p, max_new_tokens=new_tokens)
        sched.run_until_idle()
        res = f.result(timeout=1200)
        warm_samples.append(res.ttft_s)
        on_tokens[i] = res.tokens.tolist()
    warm_med = float(statistics.median(warm_samples))
    # concurrent wave: residency per user while `slots` decode
    # together. Generation long enough to span several sweeps — the
    # page table is sampled AFTER each step, and a too-short wave
    # retires inside the first one, leaving nothing to observe
    wave = [sched.submit(p, max_new_tokens=max(new_tokens, 8))
            for p in prompts[:slots]]
    best = (0, 0, 0, 0)                 # (active, used, mapped, shared)
    while sched.step():
        with sched._lock:
            active = sum(1 for s in sched.slots if s is not None)
            if active >= best[0]:
                best = (active, sched._pages.used_pages,
                        sched._pages.mapped_pages,
                        sched._pages.shared_pages)
    for f in wave:
        f.result(timeout=1200)
    assert sched.check_pages()
    prefix_rep = sched.kv_report()["prefix"]
    active, used, mapped, shared = best
    per_user_shared = (used * DEFAULT_PAGE_LEN / active) if active else None
    per_user_dense = (mapped * DEFAULT_PAGE_LEN / active) if active else None

    # sharing OFF: measured cold TTFT over a subset of the SAME
    # prompts + greedy bit-equivalence vs the sharing-on outputs
    sched_off = ContinuousBatchingScheduler(eng, n_slots=slots,
                                            page_len=DEFAULT_PAGE_LEN,
                                            n_pages=n_pages)
    off_samples, mismatches = [], 0
    n_off = min(4, n_req - 1)
    for i in range(1, 1 + n_off):
        f = sched_off.submit(prompts[i], max_new_tokens=new_tokens)
        sched_off.run_until_idle()
        res = f.result(timeout=1200)
        off_samples.append(res.ttft_s)
        if res.tokens.tolist() != on_tokens[i]:
            mismatches += 1
    off_med = float(statistics.median(off_samples))

    rec = {
        "metric": f"Serving TTFT under a shared {prefix_len}-token "
                  f"prefix, {n_req} requests, CoW prefix cache "
                  "(Transformer-LM 120M)",
        "value": round(warm_med * 1e3, 1), "unit": "ms",
        "requests": n_req, "prefix_tokens": prefix_len,
        "decode_slots": slots, "n_pages": n_pages,
        "ttft_ms_samples": [round(s * 1e3, 1) for s in warm_samples],
        "ttft_cold_ms": round(ttft_cold * 1e3, 1),
        "ttft_no_sharing_ms": round(off_med * 1e3, 1),
        "ttft_speedup_x": round(off_med / warm_med, 2) if warm_med else None,
        "tokens_resident_per_user_shared": round(per_user_shared, 1)
        if per_user_shared else None,
        "tokens_resident_per_user_dense": round(per_user_dense, 1)
        if per_user_dense else None,
        "residency_sample_active_users": active,
        "shared_pages_sampled": shared,
        "prefix_hits": prefix_rep["prefix_hits"],
        "prefix_hit_tokens": prefix_rep["prefix_hit_tokens"],
        "cow_copies": prefix_rep["cow_copies"],
        "greedy_bitmatch_vs_no_sharing": mismatches == 0,
        "no_sharing_reps": n_off,
        "timing": "wall submit→first-token through the scheduler, "
                  "sequential (queue-free); value = warm (prefix-hit) "
                  "median, vs measured no-sharing cold median over "
                  f"{n_off} of the same prompts",
    }
    assert mismatches == 0, (
        f"{mismatches}/{n_off} prompts decoded differently with the "
        "prefix cache on — sharing broke greedy bit-equivalence")
    return _flag_on_chip(_stamp(rec))


def bench_inference_scoring(batch, steps):
    """SCORE workload row (ISSUE 20): prefill-only per-token logprob
    scoring through the scheduler — `batch` prompts of ~512 tokens
    each, `steps` timed waves. A SCORE request retires at its final
    prefill chunk (no decode sweeps), so the row measures the chunked
    prefill pipeline's SCORING throughput: prompt tokens scored per
    second. Each wave's perplexities are cross-checked for finiteness
    and the first wave's logprob count must be exactly prompt-1 per
    request (the oracle contract tests pin the values on CPU)."""
    import time as _time
    import numpy as np
    from deeplearning4j_tpu.serving import (ContinuousBatchingScheduler,
                                            DEFAULT_PAGE_LEN)

    n_req = max(batch, 1)
    reps = max(steps, 1)
    prompt_len, slots = 512, 8
    eng, cfg = _serving_engine(prompt_len + 16)
    pages_per_slot = -(-cfg.max_seq // DEFAULT_PAGE_LEN)
    sched = ContinuousBatchingScheduler(eng, n_slots=slots,
                                        page_len=DEFAULT_PAGE_LEN,
                                        n_pages=slots * pages_per_slot)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(
        np.int32) for _ in range(n_req)]
    # warm the chunk buckets once (compile excluded from timing)
    sched.submit(prompts[0], kind="score")
    sched.run_until_idle()
    wave_tps, ppl0 = [], None
    for _ in range(reps):
        futs = [sched.submit(p, kind="score") for p in prompts]
        t0 = _time.perf_counter()
        sched.run_until_idle()
        dt = _time.perf_counter() - t0
        results = [f.result(timeout=1200) for f in futs]
        assert all(np.isfinite(r.perplexity) for r in results)
        assert all(len(r.logprobs) == prompt_len - 1 for r in results)
        if ppl0 is None:
            ppl0 = [round(float(r.perplexity), 2) for r in results[:4]]
        wave_tps.append(n_req * prompt_len / dt)
    tps = max(wave_tps)
    rec = {"metric": "Serving SCORE throughput: prefill-only per-token "
                     f"logprobs, {n_req} x {prompt_len}-token prompts "
                     "(Transformer-LM 120M)",
           "value": round(tps, 1), "unit": "tokens/sec/chip",
           "requests": n_req, "prompt_tokens": prompt_len,
           "decode_slots": slots, "reps": reps,
           "wave_tokens_per_s": [round(t, 1) for t in wave_tps],
           "perplexity_head": ppl0,
           "timing": "wall submit→all-retired per wave through the "
                     "scheduler, warm buckets (compile excluded); "
                     "value = best wave"}
    return _flag_on_chip(_stamp(rec))


def bench_inference_beam(batch, steps):
    """BEAM workload row (ISSUE 20): width-`batch` beam search through
    the scheduler's paged pool, `steps` new tokens. The beams
    ``map_shared`` the prompt's pages and CoW-split only where they
    diverge, so the row reports BOTH the lane throughput (beams advance
    in one decode sweep — width-k costs one sweep, not k) and the page
    census (shared vs mapped) that proves the sharing, plus the search
    quality signal: beam gain = best beam total logprob − greedy total
    logprob over the same horizon (greedy continuation re-scored
    through a SCORE request; ≥ 0 up to fp tolerance by construction,
    fidelity_report.py --min-beam-gain gates it)."""
    import time as _time
    import statistics
    import numpy as np
    from deeplearning4j_tpu.serving import (ContinuousBatchingScheduler,
                                            DEFAULT_PAGE_LEN)

    width = max(batch, 2)
    new_tokens = max(steps, 4)
    prompt_len = 256
    slots = max(width, 8)
    eng, cfg = _serving_engine(prompt_len + new_tokens + 16)
    pages_per_slot = -(-cfg.max_seq // DEFAULT_PAGE_LEN)
    sched = ContinuousBatchingScheduler(eng, n_slots=slots,
                                        page_len=DEFAULT_PAGE_LEN,
                                        n_pages=slots * pages_per_slot)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(
        np.int32) for _ in range(3)]
    # warm: one narrow beam + one greedy + one score (compile excluded)
    sched.submit(prompts[0], max_new_tokens=2, kind="beam",
                 beam_width=width)
    sched.submit(prompts[0], max_new_tokens=2)
    sched.submit(prompts[0], kind="score")
    sched.run_until_idle()

    gains, lane_tps, census = [], [], (0, 0, 0)
    for p in prompts:
        fb = sched.submit(p, max_new_tokens=new_tokens, kind="beam",
                          beam_width=width)
        t0 = _time.perf_counter()
        while sched.step():
            with sched._lock:
                active = sum(1 for s in sched.slots if s is not None)
                if active >= census[0]:
                    census = (active, sched._pages.shared_pages,
                              sched._pages.mapped_pages)
        dt = _time.perf_counter() - t0
        br = fb.result(timeout=1200)
        assert sched.check_pages()
        lane_tps.append(len(br.sequences[0]) * width / dt)
        # greedy baseline over the same horizon, scored exactly
        fg = sched.submit(p, max_new_tokens=new_tokens)
        sched.run_until_idle()
        greedy = fg.result(timeout=1200).tokens
        fs = sched.submit(np.concatenate([p, greedy]), kind="score")
        sched.run_until_idle()
        lps = fs.result(timeout=1200).logprobs
        greedy_lp = float(np.sum(lps[p.size - 1:]))
        gains.append(br.best_logprob - greedy_lp)
    gain_med = float(statistics.median(gains))
    active, shared, mapped = census
    rec = {"metric": f"Serving width-{width} beam search, "
                     f"{prompt_len}-token prompt + {new_tokens} new "
                     "tokens, CoW page-shared beams "
                     "(Transformer-LM 120M)",
           "value": round(float(statistics.median(lane_tps)), 1),
           "unit": "tokens/sec/chip",
           "beam_width": width, "new_tokens": new_tokens,
           "prompt_tokens": prompt_len, "n_prompts": len(prompts),
           "beam_gain_nats": round(gain_med, 4),
           "beam_gain_samples": [round(g, 4) for g in gains],
           "census_active_lanes": active,
           "census_shared_pages": shared,
           "census_mapped_pages": mapped,
           "timing": "wall submit→finish per beam request, warm "
                     "buckets (compile excluded); value = median lane "
                     "tokens/s (width x generated / wall)"}
    assert gain_med >= -1e-3, (
        f"beam best ({gain_med:+.4f} nats vs greedy) lost to greedy — "
        "the joint ranking is broken")
    return _flag_on_chip(_stamp(rec))


def bench_inference_fleet(batch, steps):
    """Fleet serving fabric row (ISSUE 18): a seeded open-loop Poisson
    trace with a burst window drives a ``FleetRouter`` that autoscales
    between 1 and 3 replicas on sustained SLO burn. The row value is
    FLEET goodput (every replica's requests replayed through ONE
    offline tracker — the same aggregation `scripts/slo_report.py
    --fleet` renders), with p99 TTFT/ITL, the replica min→max span and
    the scale-event counts riding along.

    ``batch`` = decode slots per replica, ``steps`` = decode tokens per
    request. The burst deliberately overloads one replica so the
    autoscaler has something to do; goodput below 100% during the burst
    is the signal this row trends, not a failure.
    """
    import importlib.util
    import tempfile
    import numpy as np
    from pathlib import Path
    from deeplearning4j_tpu.obs import load_flight_records
    from deeplearning4j_tpu.obs.slo import SLOConfig
    from deeplearning4j_tpu.serving import (AutoscalerConfig,
                                            ContinuousBatchingScheduler,
                                            FleetRouter, TrafficConfig,
                                            run_episode)

    slots = max(batch, 2)
    new_tokens = max(steps, 2)
    eng, cfg = _serving_engine(256)
    # episode SLO: ITL generous (one CPU decode sweep is tens of ms),
    # TTFT tight enough that burst queue-wait registers as burn — the
    # autoscale signal. The offline replay judges against the SAME
    # targets.
    slo = SLOConfig(ttft_s=5.0, itl_s=2.0, window_s=4.0)
    prompt_lens = (8, 16, 32)
    # warm the shared engine OUTSIDE the fleet: the compile storm must
    # not appear in the episode's flight record. Same slot count + the
    # same prompt-length set → the jitted shapes every replica will hit
    # (replicas share the engine; its jitted fns are cache-stateless).
    rng = np.random.default_rng(0)
    warm = ContinuousBatchingScheduler(eng, n_slots=slots)
    for plen in prompt_lens:
        warm.submit(rng.integers(1, cfg.vocab_size, (plen,)).astype(
            np.int32), max_new_tokens=2)
    warm.run_until_idle()

    router = FleetRouter(
        eng, n_replicas=1, n_slots=slots, slo=slo,
        autoscaler=AutoscalerConfig(min_replicas=1, max_replicas=3,
                                    high_burn=1.0, low_burn=0.5,
                                    high_queue=3.0, patience=2,
                                    cooldown=3),
        autoscale_every=4)
    # base rate below one warm replica's service rate (so the tail is
    # calm enough to earn the scale-down), burst far above it (so the
    # autoscaler has to act); the long tail lets the burn window clear
    traffic = TrafficConfig(rate_rps=1.0, duration_s=30.0,
                            prompt_lens=prompt_lens,
                            max_new_tokens=(new_tokens, new_tokens + 2),
                            vocab=cfg.vocab_size,
                            burst_start_s=1.0, burst_end_s=3.5,
                            # seed picked by enumerating the (seeded)
                            # trace: the piecewise draw can step clean
                            # over the burst window from a pre-burst
                            # gap (seeds 0/4 do); seed 1 lands 26 of
                            # 54 arrivals inside it, leaving a ~26s
                            # calm tail for the scale-down
                            burst_mult=10.0, seed=1)
    with tempfile.TemporaryDirectory() as td:
        dump = Path(td) / "fleet_episode.jsonl"
        ep = run_episode(router, traffic, dump_path=dump,
                         max_wall_s=1500.0)
        records = load_flight_records(dump)

    # offline replay through the slo_report aggregation — one
    # semantics for the bench row and the operator tool
    spec = importlib.util.spec_from_file_location(
        "dl4j_bench_slo_report",
        Path(__file__).resolve().parent / "scripts" / "slo_report.py")
    slo_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(slo_report)
    reports = slo_report.build_reports(records, slo, fleet=True)
    fleet_rep = reports["FLEET"]
    rng_rep = slo_report.replica_range(records)
    evs = slo_report.scale_events(records)
    ups = sum(1 for e in evs if e["scale_event"] == "up")
    downs = sum(1 for e in evs if e["scale_event"] == "down")
    goodput = fleet_rep.get("goodput")

    rec = {
        "metric": "Fleet goodput under a Poisson burst trace, "
                  "SLO-autoscaled 1→3 replicas (Transformer-LM 120M)",
        "value": None if goodput is None else round(100.0 * goodput, 1),
        "unit": "% goodput",
        "decode_slots": slots, "decode_tokens": new_tokens,
        "requests": ep.submitted, "completed": ep.completed,
        "failed": ep.failed, "episode_wall_s": ep.wall_s,
        "replicas_min": rng_rep[0] if rng_rep else None,
        "replicas_max": rng_rep[1] if rng_rep else None,
        "scale_ups": ups, "scale_downs": downs,
        "reprefills": ep.fleet.get("reprefills"),
        "ghost_results": ep.fleet.get("ghost_results"),
        "goodput_per_replica": {
            r: round(rep["goodput"], 4)
            for r, rep in sorted(reports.items())
            if r != "FLEET" and rep.get("goodput") is not None},
        "traffic": {"rate_rps": traffic.rate_rps,
                    "duration_s": traffic.duration_s,
                    "burst_s": [traffic.burst_start_s,
                                traffic.burst_end_s],
                    "burst_mult": traffic.burst_mult,
                    "seed": traffic.seed},
        "slo": _slo_compact(fleet_rep),
        "timing": "wall-clock open-loop episode (arrivals paced against "
                  "the clock, independent of completions); value = FLEET "
                  "goodput from the offline replay of the episode dump "
                  "at the live targets",
    }
    assert ep.failed == 0, (
        f"{ep.failed}/{ep.submitted} fleet futures failed — the "
        "never-hang contract resolved them with exceptions")
    return _flag_on_chip(_stamp(rec))


def bench_inference_quant_kv(batch, steps):
    """Quantized-KV row (ISSUE 19): run the fidelity-gated int8-vs-bf16
    promotion races (``quant.race_kv`` over one paged-pool geometry,
    ``quant.race_weights`` over the block stack) and report both arms —
    decode tokens/s, the KV bytes-per-resident-token each pool pays,
    the kl_max that gated promotion, and the verdicts that landed as
    sha-stamped cost records. The row VALUE is the byte-shrink factor
    (bf16 / int8 KV bytes per token) — the claim that holds on any
    backend; the speed verdict is the chip's to make (CPU dequant
    overhead records ``fallback_slower`` without re-pinning anything,
    exactly the paged-kernel A/B discipline). The races' own fidelity
    probes land in the ``fidelity`` block so ``fidelity_report.py
    --max-kl`` gates this capture like every other pair.

    ``batch`` = probe decode slots, ``steps`` unused (the race times
    marginal chained sweeps itself)."""
    from deeplearning4j_tpu.serving import kvcache
    from deeplearning4j_tpu.serving.quant import race_kv, race_weights

    slots = max(batch, 2)
    eng, cfg = _serving_engine(512)
    plen = kvcache.DEFAULT_PAGE_LEN
    n_pages = slots * (-(-eng.max_len // plen))
    kv = race_kv(eng, slots, n_pages, plen)
    bpt = kv["bytes_per_token"]

    def arm(step_s):
        if step_s is None:
            return None
        return {"step_time_ms": round(step_s * 1e3, 3),
                "tokens_per_s": round(slots / step_s, 2)}

    rec = {
        "metric": "KV-cache bytes/token shrink from int8 page storage, "
                  "fidelity-gated (Transformer-LM 120M, paged pool)",
        "value": round(bpt["bf16"] / bpt["int8"], 2), "unit": "x fewer "
                 "KV bytes/token (int8+scales vs bf16)",
        "slots": slots, "page_len": plen, "n_pages": n_pages,
        "kv_bytes_per_token": bpt,
        "verdict": kv["verdict"],
        "promoted": kv["choice"] == "int8",
        "bf16": arm(kv["bf16_s"]), "int8": arm(kv["int8_s"]),
        "speedup_int8_over_bf16": kv["speedup"],
        "fidelity_kl_max": kv["fidelity"]["kl_max"],
        "cost_record": kv["key"],
        "timing": "marginal chained decode sweeps per arm (the race's "
                  "own autotune timing); identical probe content both "
                  "pools — the fidelity diff is quantization error and "
                  "nothing else",
    }
    rec["fidelity"] = {"quant_kv_vs_bf16": kv["fidelity"]}
    # int8 weights ride along: same race shape over the decode matvecs
    try:
        w = race_weights(eng)
        rec["weights"] = {
            "verdict": w["verdict"], "promoted": w["choice"] == "int8",
            "bf16_s": w["bf16_s"], "int8_s": w["int8_s"],
            "speedup": w["speedup"], "cost_record": w["key"]}
        rec["fidelity"]["quant_w_vs_bf16"] = w["fidelity"]
    except Exception as e:  # noqa: BLE001 — the row survives block-less
        rec["weights"] = {"na": f"weight race failed: "
                                f"{type(e).__name__}: {e}"[:300]}
    return _flag_on_chip(_stamp(rec))


def bench_inference_spec_decode(batch, steps):
    """Speculative-decoding row (ISSUE 19): race draft arms (prompt-
    lookup ``NgramDraft`` + self-draft ``EngineDraft``) against the
    plain paged greedy decode on one prompt via ``spec.race_spec``.
    The row VALUE is the best arm's tokens/s with the baseline riding
    along; ``accepted_per_step`` (tokens per verify dispatch — the
    ``fidelity_report.py --min-accept`` gate input) and the per-arm
    bit-identity + promotion verdicts land beside it. An arm that
    loses falls back silently (counted in
    ``dl4j_autotune_promotions_total``) — the row still captures, the
    verdict is the evidence.

    ``batch`` = draft window k, ``steps`` = decode tokens per rep."""
    import numpy as np
    from deeplearning4j_tpu.serving import EngineDraft, NgramDraft
    from deeplearning4j_tpu.serving.spec import SpeculativeDecoder, \
        race_spec

    k = max(batch, 2)
    new_tokens = max(steps, 16)
    eng, cfg = _serving_engine(256)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (32,)).astype(np.int32)
    # warm every jitted shape the race will hit OUTSIDE its timed reps:
    # plain decode + chunked prefill, the verify chunk, and the engine
    # draft's dense prefill_slot/decode_step
    warm = SpeculativeDecoder(eng, NgramDraft(), k=k)
    warm.generate(prompt, k + 2)
    warm.release()
    d = EngineDraft(eng)
    d.propose([int(t) for t in prompt] + [0], 2)
    d.reset()

    res = race_spec(eng, {"ngram": NgramDraft(), "engine": EngineDraft(eng)},
                    prompt, new_tokens, k=k)
    base_tps = res["tokens"] / res["base_s"] if res["base_s"] else None
    # best arm by wall time whether or not it promoted — the row trends
    # the measured number; the verdict says what dispatches
    best_name = min(res["arms"], key=lambda n: res["arms"][n]["spec_s"])
    best = res["arms"][best_name]

    rec = {
        "metric": f"Speculative decode tokens/s, draft-verify k={k} "
                  "vs plain greedy (Transformer-LM 120M, paged pool)",
        "value": round(res["tokens"] / best["spec_s"], 2)
        if best["spec_s"] else None,
        "unit": "tokens/sec (best draft arm)",
        "k": k, "decode_tokens": res["tokens"],
        "baseline_tokens_per_s": round(base_tps, 2) if base_tps else None,
        "choice": res["choice"],
        "best_arm": best_name,
        "speedup_vs_plain": best["speedup"],
        "arms": {
            name: {kk: a[kk] for kk in ("verdict", "spec_s", "speedup",
                                        "accepted_per_step",
                                        "bit_identical")}
            for name, a in res["arms"].items()},
        "spec": {                       # the --min-accept gate's input
            "accepted_per_step": best["accepted_per_step"],
            "bit_identical": best["bit_identical"],
            "rounds": (best["stats"] or {}).get("rounds"),
            "rollback_pages": (best["stats"] or {}).get("rollback_pages"),
        },
        # greedy bit-identity IS the fidelity evidence here (token
        # space, not logits) — the pair rides the fidelity block so the
        # report renders it beside the kl pairs
        "fidelity": {"spec_vs_plain": {
            "greedy_match_frac": 1.0 if best["bit_identical"] else 0.0,
            "greedy_prefix_len": res["tokens"]
            if best["bit_identical"] else 0}},
        "timing": "median wall of full generates per arm (prefill + "
                  "rounds), identical prompt and token budget; baseline "
                  "= plain chunked-prefill + per-token decode over an "
                  "identical private paged pool",
    }
    return _flag_on_chip(_stamp(rec))


def _latency_sweep(pi, make_batch, iters, batches=(1, 8, 32)):
    """batch-1 p50/p99 + best-batch throughput through a LIVE
    ParallelInference (jit dispatch, padding, host round-trip included —
    the quantity a serving SLO is written against)."""
    import numpy as np
    x1 = make_batch(1)
    pi.output(x1)                       # compile
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        pi.output(x1)
        lat.append(time.perf_counter() - t0)
    lat.sort()
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))]
    sweep, best = {}, (None, 0.0)
    for b in batches:
        xb = make_batch(b)
        pi.output(xb)                   # compile this batch shape
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            pi.output(xb)
            times.append(time.perf_counter() - t0)
        thr = b / min(times)
        sweep[str(b)] = round(thr, 2)
        if thr > best[1]:
            best = (b, thr)
    return {"p50_ms": round(p50 * 1e3, 2), "p99_ms": round(p99 * 1e3, 2),
            "iters": iters, "best_batch": best[0],
            "best_batch_throughput": round(best[1], 2),
            "batch_sweep_samples_per_s": sweep}


def bench_inference_resnet_b1(batch, steps):
    """ResNet-50 online-serving latency through ParallelInference."""
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.parallel import ParallelInference
    from deeplearning4j_tpu.zoo.resnet import ResNet50

    net = ResNet50(num_classes=1000, compute_dtype=jnp.bfloat16).init()
    pi = ParallelInference(net, max_batch=64)
    rng = np.random.default_rng(0)

    def make_batch(b):
        return rng.random((b, 224, 224, 3), np.float32)

    stats = _latency_sweep(pi, make_batch, iters=max(steps, 5))
    rec = {"metric": "ResNet-50 batch-1 serving latency via "
                     "ParallelInference (bf16)",
           "value": stats["p50_ms"], "unit": "ms p50 (batch 1)",
           "best_batch_unit": "samples/sec", **stats,
           "memory": _mem_basic(net.params),
           "timing": "wall-clock ParallelInference.output round-trips, "
                     "compile excluded"}
    return _flag_on_chip(_stamp(rec))


def bench_inference_bert_b1(batch, steps):
    """BERT-base (T=128) serving latency: the functional encoder served
    through ParallelInference via serving.FunctionalInferenceModel."""
    import jax
    import numpy as np
    from deeplearning4j_tpu.parallel import ParallelInference
    from deeplearning4j_tpu.serving import FunctionalInferenceModel
    from deeplearning4j_tpu.zoo import transformer as tfm

    cfg = tfm.BertConfig(max_seq=128)
    params = tfm.bert_init(jax.random.PRNGKey(0), cfg)
    model = FunctionalInferenceModel(
        params, lambda p, ids: tfm.bert_forward(p, cfg, ids)[0])
    pi = ParallelInference(model, max_batch=64)
    rng = np.random.default_rng(0)

    def make_batch(b):
        return rng.integers(0, cfg.vocab_size, (b, cfg.max_seq)).astype(
            np.int32)

    stats = _latency_sweep(pi, make_batch, iters=max(steps, 5),
                           batches=(1, 8, 16))
    rec = {"metric": "BERT-base batch-1 serving latency via "
                     "ParallelInference (T=128)",
           "value": stats["p50_ms"], "unit": "ms p50 (batch 1)",
           "best_batch_unit": "samples/sec", **stats,
           "memory": _mem_basic(params),
           "timing": "wall-clock ParallelInference.output round-trips, "
                     "compile excluded"}
    return _flag_on_chip(_stamp(rec))


INFERENCE_ROWS = ("inference_decode", "inference_ttft_1024",
                  "inference_ttft_4096", "inference_prefix_shared",
                  "inference_fleet", "inference_quant_kv",
                  "inference_spec_decode", "inference_scoring",
                  "inference_beam",
                  "inference_resnet_b1", "inference_bert_b1")

CONFIGS = {
    "resnet50": bench_resnet50_fit,   # headline: the REAL fit() entry point
    "resnet50_rawstep": bench_resnet50,
    "resnet50_fitscan": bench_resnet50_fitscan,
    "lenet": bench_lenet,
    "lenet_scan": bench_lenet_scan,
    "charnn": bench_charnn,
    "charnn_f32": bench_charnn_f32,
    "bert": bench_bert,
    "transformer": bench_transformer,
    "transformer_long": bench_transformer_long,
    "transformer_xlong": bench_transformer_xlong,
    "dpoverhead": bench_dpoverhead,
    "inference_decode": bench_inference_decode,
    "inference_ttft_1024": bench_inference_ttft_1024,
    "inference_ttft_4096": bench_inference_ttft_4096,
    "inference_prefix_shared": bench_inference_prefix_shared,
    "inference_fleet": bench_inference_fleet,
    "inference_quant_kv": bench_inference_quant_kv,
    "inference_spec_decode": bench_inference_spec_decode,
    "inference_scoring": bench_inference_scoring,
    "inference_beam": bench_inference_beam,
    "inference_resnet_b1": bench_inference_resnet_b1,
    "inference_bert_b1": bench_inference_bert_b1,
}

DEFAULTS = {  # (batch, steps) — batch swept on the real chip (r2): charnn
    # peaks at 256. r5: charnn runs the lax.scan LSTM path (the fused
    # pallas kernel measured slower in both dtypes — see
    # nn/layers/recurrent.py `fused` and scripts/diag_attn_r5_out.json)
    "resnet50": (128, 13),
    "resnet50_rawstep": (128, 13),
    "resnet50_fitscan": (128, 13),
    "lenet": (512, 25),
    "lenet_scan": (512, 25),
    "charnn": (256, 25),
    "charnn_f32": (256, 25),
    # bert: r5 composition sweep — remat-full + bf16-scores frees HBM for
    # b128 (MFU 0.61 vs 0.40 at the r4 b32 base config)
    "bert": (128, 13),
    # transformer: b32 composes the two measured r5 winners (b16
    # flash+save_attn 221.4k, b32 flash remat-full 223.3k tok/s); the
    # composed cell is captured by the official bench run itself
    "transformer": (32, 13),
    "transformer_long": (4, 9),   # 16k tokens/step (T=1024 runs 32k at b32)
    "transformer_xlong": (4, 9),  # T=8192 b4 remat-off — 32k tokens/step
    "dpoverhead": (1024, 20),
    # serving rows: batch = decode slots / fixed 1; steps = chain length
    # (decode) or timed reps (latency rows)
    "inference_decode": (8, 25),
    "inference_ttft_1024": (1, 3),
    "inference_ttft_4096": (1, 2),   # T=4096 prefill is minutes on CPU
    # prefix row: batch = requests sharing the 1024-token prefix, steps
    # = decode tokens per request; one cold prefill + batch-1 warm tails
    "inference_prefix_shared": (64, 4),
    # fleet row: batch = decode slots per replica, steps = decode tokens
    # per request; the burst trace + autoscaler window are fixed in-row
    "inference_fleet": (4, 6),
    # quant row: batch = probe decode slots; spec row: batch = draft
    # window k, steps = decode tokens per rep
    "inference_quant_kv": (4, 8),
    "inference_spec_decode": (8, 48),
    # scoring row: batch = prompts per wave, steps = timed waves;
    # beam row: batch = beam width, steps = new tokens per request
    "inference_scoring": (8, 3),
    "inference_beam": (4, 24),
    "inference_resnet_b1": (1, 15),
    "inference_bert_b1": (1, 12),
}


def _write_secondary(headline, secondary, inference=None):
    """Atomic write (temp + rename) after EVERY config, so a crash mid-run
    can never leave a stale artifact claiming to be current (the r3 failure:
    bench_secondary.json on disk was still the r2 output).

    A backend-unavailable run must not ERASE verified numbers either (the
    complementary failure, hit in r4 when the tunnel died for hours): when
    this run has no timings but the artifact on disk holds a real capture,
    that capture is preserved under `last_verified` — explicitly stamped
    with its own sha/timestamp, never masquerading as current.

    ``inference`` (ISSUE 10 serving rows) defaults to whatever the
    artifact on disk already holds — a training-only capture must not
    silently drop the serving section."""
    import os
    path = _artifact_path()
    if inference is None:
        try:
            inference = json.loads(path.read_text()).get("inference")
        except Exception:  # noqa: BLE001 — absent/corrupt previous artifact
            inference = None
    out = {"headline": headline, "secondary": secondary}
    if inference:
        out["inference"] = inference
    this_run_failed = (isinstance(headline, dict)
                       and headline.get("value") is None)
    if this_run_failed:
        try:
            prev = json.loads(path.read_text())
            prev_head = prev.get("headline", {})
            if prev_head.get("value") is not None:
                out["last_verified"] = prev
            elif "last_verified" in prev:
                out["last_verified"] = prev["last_verified"]
        except Exception:  # noqa: BLE001 — absent/corrupt previous artifact
            pass
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(out, indent=2) + "\n")
    os.replace(tmp, path)


def _artifact_path():
    import os
    import pathlib
    return pathlib.Path(os.environ.get(
        "DL4J_TPU_BENCH_ARTIFACT",
        pathlib.Path(__file__).with_name("bench_secondary.json")))


def _ledger_append(row, rec):
    """Feed the perf trend ledger (ISSUE 15): one keyed record per
    captured row into runs/perf_ledger.jsonl — the longitudinal
    history scripts/perf_gate.py replays for regression verdicts.
    Called from the PARENT process only (main() for the headline,
    _run_row_subprocess for every other row) so a `--model` subprocess
    can never double-append its own capture. Self-timed; the <2%-of-a-
    row-capture budget is pinned in tests/test_trend.py. Never fatal —
    a ledger failure must not cost a captured row."""
    try:
        from deeplearning4j_tpu.obs import trend
        entry = trend.ledger_record(row, rec)
        if entry is None:
            return
        dt = trend.append_record(entry)
        print(f"[bench] trend ledger += {row} "
              f"({dt * 1e3:.2f} ms)", file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 — decoration only
        print(f"[bench] trend ledger append failed for {row}: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)


def _run_row_subprocess(name):
    """One secondary row in a fresh interpreter (isolation: residual
    allocator/compile state measurably depresses shared-process configs).
    Returns the row's record dict, or {"error": ...} on any failure.
    Serving rows get a longer leash: a CPU-derived T=4096 prefill is
    minutes per rep (wall-clock row, not a marginal chain)."""
    import os
    import subprocess
    script = os.path.abspath(__file__)
    timeout = 1800 if name in INFERENCE_ROWS else 900
    try:
        proc = subprocess.run([sys.executable, script, "--model", name],
                              capture_output=True, text=True,
                              timeout=timeout, cwd=os.path.dirname(script))
        if proc.returncode == 0 and proc.stdout.strip():
            rec = json.loads(proc.stdout.strip().splitlines()[-1])
            if not isinstance(rec, dict):
                # a stray print can make the last stdout line parse to a
                # non-dict JSON value; callers rec.get() — never hand one
                # back (ADVICE r5 #3: it aborted the remaining rows)
                return {"error": f"non-dict record: {rec!r:.200}"}
            _ledger_append(name, rec)
            return rec
        return {"error": (proc.stdout + proc.stderr)[-500:]}
    except Exception as e:  # noqa: BLE001 — callers keep other rows' records
        return {"error": f"{type(e).__name__}: {e}"[:500]}


def _refresh_rows(names):
    """Re-capture the named secondary rows into the existing artifact —
    the tool-supported way to redo a contaminated row (e.g. a CPU-mesh
    measurement taken while the host was loaded) without hand-editing
    bench_secondary.json or paying for a full re-capture. The headline
    and untouched rows keep their records; a row whose re-capture FAILS
    also keeps its previous record (the error goes to stderr only —
    never overwrite a verified capture with an error entry)."""
    art = json.loads(_artifact_path().read_text())
    headline = art.get("headline", {})
    secondary = art.get("secondary", {})
    inference = art.get("inference", {})
    if headline.get("value") is None:
        print("no headline in artifact; run a full capture first",
              file=sys.stderr)
        return
    secondary.pop("_incomplete", None)  # a crashed full run may have left it
    for name in names:
        if name == "resnet50":
            print("resnet50 is the headline row — run a full capture "
                  "(python bench.py) to refresh it", file=sys.stderr)
            continue
        if name not in CONFIGS:
            print(f"unknown row {name!r}", file=sys.stderr)
            continue
        # serving rows live in the `inference` section, everything else
        # in `secondary` — one refresh path serves both
        section = inference if name in INFERENCE_ROWS else secondary
        rec = _run_row_subprocess(name)
        if rec.get("value") is None and name in section \
                and isinstance(section[name], dict) \
                and section[name].get("value") is not None:
            print(f"[bench] {name}: refresh FAILED "
                  f"({rec.get('error', rec)!s:.200}); previous record kept",
                  file=sys.stderr, flush=True)
            continue
        section[name] = rec
        print(f"[bench] {name}: {rec.get('value', rec)}",
              file=sys.stderr, flush=True)
        # write per row (crash safety)
        _write_secondary(headline, secondary, inference)


def main():
    argv = list(sys.argv[1:])
    model = None
    if argv and argv[0] == "--refresh":
        if len(argv) < 2 or not argv[1]:
            print("usage: bench.py --refresh row1[,row2,...]   rows: "
                  + ",".join(sorted(CONFIGS)), file=sys.stderr)
            return
        _refresh_rows(argv[1].split(","))
        return
    if argv and argv[0] == "--model":
        model = argv[1]
        argv = argv[2:]
    if model is not None:
        b, s = DEFAULTS[model]
        batch = int(argv[0]) if argv else b
        steps = int(argv[1]) if len(argv) > 1 else s
        print(json.dumps(CONFIGS[model](batch, steps)))
        return

    batch, steps = DEFAULTS["resnet50"]
    if argv:
        batch = int(argv[0])
    if len(argv) > 1:
        steps = int(argv[1])

    ok, detail = wait_for_backend()
    if not ok:
        # One JSON line, rc=0: an explicit unavailability record beats a
        # traceback — the driver archives stdout either way, and rc=1 left
        # round 3 with no artifact at all.
        unavail = _stamp({
            "metric": "ComputationGraph.fit(DataSetIterator) samples/sec/chip"
                      " (ResNet-50 ImageNet)",
            "backend_unavailable": True,
            # Pre-set so _stamp's setdefault never touches
            # jax.default_backend() here — that would init the wedged
            # backend in-process and hang the very record that reports it.
            "backend": "unavailable",
            "error": detail,
            "note": "axon TPU backend unreachable after retries; no timing "
                    "captured this run. Last verified numbers live in the "
                    "previous BENCH_r*.json artifacts.",
        })
        print(json.dumps(unavail), flush=True)
        # Overwrite the secondary artifact too: leaving last round's numbers
        # on disk unmarked is the r3 stale-artifact failure mode.
        _write_secondary(unavail, {"backend_unavailable": True})
        return

    # Mark the artifact incomplete BEFORE the headline runs: a crash during
    # the headline (the actual r3 failure was mid-run, post-init) must not
    # leave last round's numbers on disk unmarked.
    _write_secondary({"_incomplete": "headline in progress"}, {})
    headline = bench_resnet50_fit(batch, steps)
    print(json.dumps(headline), flush=True)
    _ledger_append("resnet50", headline)
    _write_secondary(headline, {"_incomplete": "run in progress"})

    # Secondary configs (SURVEY §6) -> bench_secondary.json; never stdout.
    # Each runs in a FRESH subprocess: residual allocator/compilation state
    # from the headline (and from each other) measurably depresses the
    # later configs when they share a process (observed: charnn 2.9M vs
    # 4.7M tokens/s isolated).
    t_start = time.perf_counter()
    secondary = {}
    # transformer_xlong runs LAST: its T=8192 compile+run took ~10.5 min
    # in the first capture — against the 1500 s budget it must not be able
    # to starve the established rows of their slots.
    for name in ("lenet", "lenet_scan", "charnn", "bert", "transformer",
                 "transformer_long", "dpoverhead",
                 "resnet50_rawstep", "resnet50_fitscan",
                 "charnn_f32", "transformer_xlong"):
        if time.perf_counter() - t_start > 1500:
            secondary[name] = {"skipped": "time budget"}
        else:
            secondary[name] = _run_row_subprocess(name)
        print(f"[bench] {name}: "
              f"{secondary[name].get('value', secondary[name])}",
              file=sys.stderr, flush=True)
        secondary["_incomplete"] = "run in progress"
        _write_secondary(headline, secondary)
    secondary.pop("_incomplete", None)
    _write_secondary(headline, secondary)

    # Serving-plane rows (ISSUE 10) -> `inference` section. Own time
    # budget so a slow training capture can't permanently starve the
    # serving numbers (and vice versa); same per-row subprocess
    # isolation. Prior rows are preserved on per-row failure by
    # _write_secondary's read-back only when this loop never runs.
    t_inf = time.perf_counter()
    inference = {}
    for name in INFERENCE_ROWS:
        if time.perf_counter() - t_inf > 1200:
            inference[name] = {"skipped": "time budget"}
        else:
            inference[name] = _run_row_subprocess(name)
        print(f"[bench] {name}: "
              f"{inference[name].get('value', inference[name])}",
              file=sys.stderr, flush=True)
        _write_secondary(headline, secondary, inference)


if __name__ == "__main__":
    main()
