"""Headline bench: ResNet-50 ImageNet fit() samples/sec/chip (BASELINE.json).

Runs on the real TPU chip (axon). Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}

vs_baseline divides by the DL4J V100 cuDNN reference (360 img/s — see
BASELINE.md). Synthetic ImageNet-shaped data (zero-egress sandbox); bf16
NHWC convs (MXU accumulates in f32 on TPU); steady-state timing excludes
compile.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_SAMPLES_PER_SEC = 360.0  # DL4J ResNet-50 V100 cuDNN (BASELINE.md)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20

    from deeplearning4j_tpu.zoo.resnet import ResNet50
    net = ResNet50(num_classes=1000, compute_dtype=jnp.bfloat16).init()

    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(net.params)

    def train_step(params, states, opt_state, x, y):
        def loss_fn(p, s):
            acts, pre, new_s = net._forward(p, s, {"in": x}, train=True, rng=None,
                                            stop_at_output_preact=True)
            out_layer = net.conf.nodes["out"].op
            loss = out_layer.compute_loss(p["out"], pre["out"], y)
            return loss, new_s

        (loss, new_states), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, states)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_states, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((batch, 224, 224, 3), np.float32), jnp.bfloat16)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])

    params, states, ostate = net.params, net.states, opt_state
    # warmup / compile
    params, states, ostate, loss = step(params, states, ostate, x, y)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, states, ostate, loss = step(params, states, ostate, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    sps = batch * steps / dt
    print(json.dumps({
        "metric": "MultiLayerNetwork.fit() samples/sec/chip (ResNet-50 ImageNet)",
        "value": round(sps, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps / BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
