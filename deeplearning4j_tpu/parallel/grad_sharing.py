"""Gradient sharing — threshold-encoded sparse gradient exchange.

Reference parity: ``org.deeplearning4j.optimize.solvers.accumulation.
EncodedGradientsAccumulator`` + the Aeron-based gradient-sharing trainer:
each worker quantizes gradients to ±threshold sparse updates with residual
error feedback, shares the encoded stream, and applies the decoded sum.

TPU-first positioning: WITHIN a pod, dense psum over ICI (ParallelWrapper)
beats sparse encoding — that path never uses this module. The codec matters
for the reference's own regime: slow interconnect (DCN between distant pods,
or host-driven federation). The encode/decode hot loops are native C++
(`native/dl4j_tpu_native.cpp`), with adaptive-threshold control matching the
reference's ``AdaptiveThresholdAlgorithm``.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import numpy as np

from ..utils.native import threshold_decode, threshold_encode


class AdaptiveThreshold:
    """Adjust threshold toward a target encoded-sparsity (reference
    AdaptiveThresholdAlgorithm: keep ~1e-3 of entries encoded)."""

    def __init__(self, initial: float = 1e-3, target_sparsity: float = 1e-3,
                 decay: float = 1.2, min_threshold: float = 1e-6,
                 max_threshold: float = 1.0):
        self.threshold = initial
        self.target = target_sparsity
        self.decay = decay
        self.min = min_threshold
        self.max = max_threshold

    def update(self, encoded: int, total: int):
        frac = encoded / max(total, 1)
        if frac > 4 * self.target:
            self.threshold = min(self.threshold * self.decay, self.max)
        elif frac < self.target / 4:
            self.threshold = max(self.threshold / self.decay, self.min)
        return self.threshold


class GradientSharingAccumulator:
    """N-worker accumulator: encode each worker's flat gradient, exchange
    (here: in-process; transport pluggable), decode-sum, apply residuals.

    `transport` is a callable List[np.ndarray(int32)] → List[np.ndarray] that
    delivers every worker's tokens to every worker (default: local all-gather,
    standing in for the reference's Aeron UDP multicast).
    """

    def __init__(self, n_params: int, n_workers: int, threshold: float = 1e-3,
                 adaptive: bool = True,
                 transport: Optional[Callable] = None):
        self.n_params = n_params
        self.n_workers = n_workers
        self.residuals = [np.zeros(n_params, np.float32) for _ in range(n_workers)]
        self.adaptive = AdaptiveThreshold(threshold) if adaptive else None
        self.threshold = threshold
        self.transport = transport or (lambda msgs: msgs)

    def step(self, worker_grads: List[np.ndarray]) -> np.ndarray:
        """One sharing round → the dense summed update every worker applies."""
        assert len(worker_grads) == self.n_workers
        msgs = []
        encoded_total = 0
        for w, g in enumerate(worker_grads):
            tokens = threshold_encode(np.asarray(g, np.float32).ravel(),
                                      self.residuals[w], self.threshold)
            encoded_total += tokens.size
            msgs.append(tokens)
        delivered = self.transport(msgs)
        update = np.zeros(self.n_params, np.float32)
        for tokens in delivered:
            update += threshold_decode(tokens, self.threshold, self.n_params)
        if self.adaptive is not None:
            self.threshold = self.adaptive.update(
                encoded_total, self.n_params * self.n_workers)
        return update / self.n_workers

    def residual_norm(self, worker: int) -> float:
        return float(np.linalg.norm(self.residuals[worker]))
