"""Generic pipeline parallelism — partition ANY sequential layer stack
(MultiLayerNetwork) into GPipe stages over the mesh 'pp' axis.

Reference counterpart: none in DL4J (data-parallel only); VERDICT r2 item 4
asked for a stage partitioner beyond the transformer-only pipeline in
``pipeline.py``. TPU-native design: stages are contiguous layer runs
balanced by parameter count; inside ``shard_map`` a fill-drain loop streams
M microbatches around the ring with ``lax.ppermute`` (neighbor hop = ICI),
and each device runs its own stage via ``lax.cond``-free ``lax.switch`` on
its 'pp' coordinate. Heterogeneous boundary activations are flattened and
zero-padded to one common buffer width so every stage exchanges the same
static shape — the price of generality XLA demands (the homogeneous
transformer pipeline in pipeline.py avoids the pad by stacking its
identical blocks instead).

Scope v2: stateful layers (BatchNorm running stats) ARE supported — the
states pytree rides the fill-drain loop as a carry; each stage updates its
own layers' stats per microbatch (GPipe semantics: BN batch statistics are
per-MICROBATCH, like upstream GPipe), and after the drain an
ownership-masked psum over 'pp' (+ pmean over dp axes) reassembles one
consistent tree. Dropout/weight-noise: pass ``rng`` to the loss/step —
masks are drawn per MICROBATCH (fold_in(microbatch, layer); GPipe
semantics, like the per-microbatch BN stats — NOT bit-equal to a
single-device full-batch mask). Single input/output still.

Memory: ``shard_params_pp`` lays params out 1/pp per device AT REST
(ZeRO-3 over the 'pp' axis) — params, Adam moments, and every optimizer
buffer scale with the stage count; the step transiently regathers (XLA
inserts the all-gather at the shard_map boundary). The homogeneous-stack
variant in pipeline.py partitions the transient too by stacking identical
blocks.

``jax.grad`` differentiates straight through the fill-drain loop
(ppermute's transpose is the reverse permute), so one program serves
forward and backward.
"""

from __future__ import annotations

import math
from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import optax
from jax import lax

from .._jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.layers.base import Ctx
from ..nn.layers.core import LossLayer, OutputLayer
from ..nn.multi_layer_network import unwrap


def partition_layers(net, n_stages: int) -> List[List[int]]:
    """Contiguous stages balanced by parameter count (the final loss/output
    layer rides with the last stage). Greedy: close a stage once it holds
    its fair share of the remaining parameters."""
    sizes = []
    for i in range(len(net.layers)):
        p = net.params[f"layer_{i}"]
        sizes.append(sum(x.size for x in jax.tree_util.tree_leaves(p)))
    n = len(sizes)
    if n_stages > n:
        raise ValueError(f"{n_stages} stages > {n} layers")
    stages, start, remaining = [], 0, sum(sizes)
    for s in range(n_stages):
        stages_left = n_stages - s
        target = remaining / stages_left
        end, acc = start, 0
        # must leave >= 1 layer per remaining stage
        max_end = n - (stages_left - 1)
        while end < max_end and (acc < target or end == start):
            acc += sizes[end]
            end += 1
        stages.append(list(range(start, end)))
        remaining -= acc
        start = end
    return stages


def _boundary_shapes(net, stages, batch: int):
    """Per-stage input shapes (with batch dim) via abstract evaluation."""
    in_shape = (batch,) + tuple(net._init_input_shape)
    shapes = [in_shape]
    x = jax.ShapeDtypeStruct(in_shape, jnp.float32)

    def run_stage(idx_list, drop_output):
        def f(params, x):
            h = x
            for i in idx_list:
                layer = net.layers[i]
                if drop_output and i == len(net.layers) - 1 and isinstance(
                        unwrap(layer), (OutputLayer, LossLayer)):
                    break
                if i in net._preprocessors:
                    h = net._preprocessors[i](h)
                h, _ = layer.apply(params[f"layer_{i}"],
                                   net.states[f"layer_{i}"], h,
                                   Ctx(train=True, rng=None))
            return h
        return f

    for s, idx_list in enumerate(stages):
        x = jax.eval_shape(run_stage(idx_list, drop_output=True),
                           net.params, x)
        shapes.append(tuple(x.shape))
        x = jax.ShapeDtypeStruct(tuple(x.shape), jnp.float32)
    return shapes


def shard_params_pp(mesh: Mesh, params, min_size: int = 2 ** 12):
    """ZeRO-3-over-'pp' at-rest layout: shard each large leaf's first
    divisible axis over 'pp'. Apply to params BEFORE optimizer init so the
    Adam moments inherit the layout — at-rest model+optimizer memory then
    scales 1/pp; the pipelined step transiently regathers at the shard_map
    boundary (XLA inserts the all-gather)."""
    n = mesh.shape["pp"]

    def sh(leaf):
        if not hasattr(leaf, "shape") or leaf.size < min_size:
            return jax.device_put(leaf, NamedSharding(mesh, P()))
        for d, dim in enumerate(leaf.shape):
            if dim % n == 0:
                spec = [None] * leaf.ndim
                spec[d] = "pp"
                return jax.device_put(leaf, NamedSharding(mesh, P(*spec)))
        return jax.device_put(leaf, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(sh, params)


def make_mln_pipeline_loss(mesh: Mesh, net, microbatch: int):
    """Pipelined loss for a sequential net over mesh axes ('pp' required,
    'dp' optional). Stateless nets: ``loss = fn(params, x_mb, y_mb)``.
    Stateful nets (BatchNorm): ``(loss, new_states) = fn(params, states,
    x_mb, y_mb)`` — per-microbatch batch stats (GPipe semantics), final
    states reassembled from each stage's owner. At dp=1 the loss equals the
    single-device microbatched loop exactly (proven in
    tests/test_parallel.py); under dp>1 a BN layer normalizes each dp
    shard's mb/dp samples separately (standard sharded-BN semantics; stats
    are pmean'd), so BN values differ from single-device by the shard-local
    normalization, like every dp framework without SyncBN."""
    n_stages = mesh.shape["pp"]
    stateful = any(bool(s) for s in net.states.values())
    stages = partition_layers(net, n_stages)
    stage_of = {}
    for s, idx_list in enumerate(stages):
        for i in idx_list:
            stage_of[i] = s
    out_layer = unwrap(net.layers[-1])
    if not isinstance(out_layer, (OutputLayer, LossLayer)):
        raise ValueError("last layer must be an OutputLayer/LossLayer")
    last_i = len(net.layers) - 1
    shapes = _boundary_shapes(net, stages, microbatch)
    flat_sizes = [math.prod(s[1:]) for s in shapes]
    fmax = max(flat_sizes)

    from ..nn.weightnoise import maybe_apply_weight_noise
    needs_rng = any(getattr(l, "dropout", 0.0) > 0.0
                    or getattr(l, "weight_noise", None) is not None
                    for l in net.layers)

    def stage_fn(s):
        idx_list = stages[s]
        is_loss_stage = s == n_stages - 1

        def f(params, states, flat, tgt, mb_rng):
            # leading dim comes from the LOCAL array: under a dp axis,
            # shard_map hands each device its microbatch shard
            h = flat[:, :flat_sizes[s]].reshape(
                (flat.shape[0],) + shapes[s][1:])
            new_states = dict(states)
            for i in idx_list:
                layer = net.layers[i]
                if i == last_i and isinstance(unwrap(layer),
                                              (OutputLayer, LossLayer)):
                    break   # the loss computation below consumes h
                if i in net._preprocessors:
                    h = net._preprocessors[i](h)
                lrng = None if mb_rng is None else \
                    jax.random.fold_in(mb_rng, i)
                if getattr(layer, "dropout", 0.0) > 0.0 and lrng is not None:
                    # per-MICROBATCH masks (GPipe semantics, like the
                    # per-microbatch BN stats above)
                    keep = 1.0 - layer.dropout
                    m = jax.random.bernoulli(
                        jax.random.fold_in(lrng, 997), keep, h.shape)
                    h = jnp.where(m, h / keep, 0.0).astype(h.dtype)
                p_i = maybe_apply_weight_noise(
                    layer, params[f"layer_{i}"], lrng, True)
                h, s_new = layer.apply(p_i,
                                       states[f"layer_{i}"], h,
                                       Ctx(train=True, rng=lrng))
                new_states[f"layer_{i}"] = s_new
            out = h.reshape(h.shape[0], -1)
            pad = fmax - out.shape[1]
            if pad:
                out = jnp.pad(out, ((0, 0), (0, pad)))
            # loss lives INSIDE the last stage's branch so the other
            # stages never pay the output-head FLOPs (lax.switch executes
            # only the selected branch)
            if not is_loss_stage:
                return out, jnp.zeros((), jnp.float32), new_states
            hl = h
            if last_i in net._preprocessors:
                hl = net._preprocessors[last_i](hl)
            if isinstance(out_layer, OutputLayer):
                mb_loss = out_layer.compute_loss(
                    params[f"layer_{last_i}"], hl, tgt)
            else:
                mb_loss = out_layer.compute_loss(hl, tgt)
            return out, mb_loss.astype(jnp.float32), new_states
        return f

    fns = [stage_fn(s) for s in range(n_stages)]
    other_axes = tuple(a for a in mesh.axis_names
                       if a != "pp" and mesh.shape[a] > 1)

    def device_loss(params, states, x_mb, y_mb, rng=None):
        stage = lax.axis_index("pp")
        n_mb = x_mb.shape[0]
        mb_local = x_mb.shape[1]   # microbatch / dp under a dp axis
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        buf = jnp.zeros((mb_local, fmax), jnp.float32)
        total = jnp.zeros((), jnp.float32)
        is_first = stage == 0
        is_last = stage == n_stages - 1
        for tick in range(n_mb + n_stages - 1):
            # the microbatch THIS stage works on at this tick (stage s gets
            # live microbatch tick - s) — keys its dropout/weight-noise rng
            my_mb = jnp.clip(tick - stage, 0, n_mb - 1)
            if rng is None:
                mb_rng = None
            else:
                mb_rng = jax.random.fold_in(rng, my_mb)
                # de-correlate masks across DATA-sharding axes only ('dp'
                # is the sole axis data_spec shards over): without this
                # every dp device would draw the SAME per-position mask
                # for its shard. Non-data axes (tp/fsdp) hold replicated
                # activations and MUST keep identical masks or their
                # "replicated" values silently diverge.
                if "dp" in mesh.axis_names and mesh.shape["dp"] > 1:
                    mb_rng = jax.random.fold_in(mb_rng,
                                                lax.axis_index("dp"))
            mb_idx = jnp.clip(tick, 0, n_mb - 1)
            fresh = x_mb[mb_idx].reshape(mb_local, -1)
            if fresh.shape[1] < fmax:
                fresh = jnp.pad(fresh,
                                ((0, 0), (0, fmax - fresh.shape[1])))
            x = jnp.where(is_first & (tick < n_mb), fresh, buf)
            out_idx = tick - (n_stages - 1)
            tgt = y_mb[jnp.clip(out_idx, 0, n_mb - 1)]
            y, mb_loss, new_states = lax.switch(stage, fns, params, states,
                                                x, tgt, mb_rng)
            # only ticks carrying a real microbatch may advance the stats:
            # stage s sees live data at ticks [s, s + n_mb); outside that
            # (fill/drain) it re-ran a clipped mb whose stats must be
            # discarded
            if stateful:
                live = (tick >= stage) & (tick - stage < n_mb)
                states = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(live, new, old),
                    new_states, states)
            if out_idx >= 0:
                use = is_last & (out_idx < n_mb)
                total = total + jnp.where(use, mb_loss, 0.0)
            buf = lax.ppermute(y, "pp", perm)
        total = lax.psum(jnp.where(is_last, total, 0.0), "pp") / n_mb
        for ax in other_axes:
            total = lax.pmean(total, ax)
        if not stateful:
            return total, states
        # reassemble: each layer's state is authoritative on its OWNING
        # stage; masked psum over 'pp' broadcasts it to everyone, pmean
        # over dp axes averages the per-shard batch stats (all-float)
        merged = {}
        for i in range(len(net.layers)):
            key = f"layer_{i}"
            own = (stage == stage_of[i]).astype(jnp.float32)

            def pick(leaf, own=own):
                v = lax.psum(leaf.astype(jnp.float32) * own, "pp")
                for ax in other_axes:
                    v = lax.pmean(v, ax)
                return v.astype(leaf.dtype)

            merged[key] = jax.tree_util.tree_map(pick, states[key])
        return total, merged

    rep = jax.tree_util.tree_map(lambda _: P(), net.params)
    rep_states = jax.tree_util.tree_map(lambda _: P(), net.states)
    dp = "dp" if "dp" in mesh.axis_names else None

    def data_spec(arr_ndim):
        return P(*((None, dp) + (None,) * (arr_ndim - 2)))

    def loss_with_states(params, states, x_mb, y_mb, rng=None):
        if not needs_rng:
            rng = None   # dropout-free net: skip the whole rng machinery
        if rng is None:
            fn = shard_map(
                lambda p, s, x, y: device_loss(p, s, x, y, None),
                mesh=mesh,
                in_specs=(rep, rep_states, data_spec(x_mb.ndim),
                          data_spec(y_mb.ndim)),
                out_specs=(P(), rep_states), check_vma=False)
            return fn(params, states, x_mb, y_mb)
        fn = shard_map(device_loss, mesh=mesh,
                       in_specs=(rep, rep_states, data_spec(x_mb.ndim),
                                 data_spec(y_mb.ndim), P()),
                       out_specs=(P(), rep_states), check_vma=False)
        return fn(params, states, x_mb, y_mb, rng)

    if stateful:
        return loss_with_states

    def loss(params, x_mb, y_mb, rng=None):
        return loss_with_states(params, net.states, x_mb, y_mb, rng)[0]

    return loss


def make_mln_pipeline_train_step(mesh: Mesh, net, optimizer,
                                 microbatch: int):
    """Jitted pipelined train step for any sequential net. Stateless:
    (params, opt_state, x_mb, y_mb) → (params, opt_state, loss).
    Stateful (BatchNorm): (params, states, opt_state, x_mb, y_mb) →
    (params, states, opt_state, loss)."""
    loss_fn = make_mln_pipeline_loss(mesh, net, microbatch)
    stateful = any(bool(s) for s in net.states.values())

    if stateful:
        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def step_s(params, states, opt_state, x_mb, y_mb, rng=None):
            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, states, x_mb, y_mb, rng)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_states, opt_state, loss

        return step_s

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x_mb, y_mb, rng=None):
        loss, grads = jax.value_and_grad(loss_fn)(params, x_mb, y_mb, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


class _SequentialView:
    """MLN-shaped facade over a linear-chain ComputationGraph so the
    generic pipeline machinery applies unchanged. Params/states are
    re-keyed node-name → 'layer_i'; ``to_graph``/``from_graph`` convert."""

    def __init__(self, cg):
        from ..nn.layers.base import Layer as _Layer
        order = [n for n in cg.conf.topo_order if n not in cg.conf.inputs]
        for k, name in enumerate(order):
            node = cg.conf.nodes[name]
            if not isinstance(node.op, _Layer):
                raise ValueError(
                    f"CG pipeline needs a pure layer chain; '{name}' is a "
                    f"{type(node.op).__name__} vertex")
            expect = cg.conf.inputs[0] if k == 0 else order[k - 1]
            if list(node.inputs) != [expect]:
                raise ValueError(
                    f"CG pipeline needs a linear chain; '{name}' consumes "
                    f"{list(node.inputs)} (expected ['{expect}'])")
        self.names = order
        self.layers = [cg.conf.nodes[n].op for n in order]
        self.params = {f"layer_{i}": cg.params[n]
                       for i, n in enumerate(order)}
        self.states = {f"layer_{i}": cg.states[n]
                       for i, n in enumerate(order)}
        self._preprocessors = {i: cg._preprocessors[n]
                               for i, n in enumerate(order)
                               if n in cg._preprocessors}
        self._init_input_shape = tuple(cg._init_shapes[0])

    def to_graph(self, params):
        return {n: params[f"layer_{i}"] for i, n in enumerate(self.names)}

    def from_graph(self, params):
        return {f"layer_{i}": params[n] for i, n in enumerate(self.names)}


def make_cg_pipeline_train_step(mesh: Mesh, cg, optimizer, microbatch: int):
    """Pipeline a linear-chain ComputationGraph: returns (step, view) where
    ``view.params``/``view.states`` are the 'layer_i'-keyed starting pytree
    (use ``view.to_graph`` to map results back onto the graph)."""
    view = _SequentialView(cg)
    return make_mln_pipeline_train_step(mesh, view, optimizer,
                                        microbatch), view


def microbatches(x, y, microbatch: int):
    """Host-side reshape: (B, ...) → (M, mb, ...); B must divide evenly."""
    import numpy as np
    x, y = np.asarray(x), np.asarray(y)
    if x.shape[0] % microbatch:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"microbatch {microbatch}")
    m = x.shape[0] // microbatch
    return (x.reshape((m, microbatch) + x.shape[1:]),
            y.reshape((m, microbatch) + y.shape[1:]))
