"""ParallelWrapper / ParallelInference — data-parallel training & inference.

Reference parity: ``org.deeplearning4j.parallelism.ParallelWrapper``
(replicate model over N devices, split each batch, average gradients) and
``ParallelInference`` (round-robin batched inference workers).

TPU-first redesign: no worker threads, no averaging step, no parameter
server. The SAME jitted train step as single-device, compiled over a mesh:
params replicated (or fsdp-sharded), batch sharded over dp. XLA inserts the
gradient all-reduce over ICI where the reference moved gradients over PCIe/
Aeron. `fit()` is a drop-in for MultiLayerNetwork/ComputationGraph fit.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import get_registry
from .mesh import data_parallel_mesh, shard_params_fsdp


def _unpack_batch(ds):
    """DataSet or MultiDataSet -> (x, y, fmask, lmask). Multi-arm features/
    labels become tuples (CG._as_input_dict zips them with conf.inputs /
    conf.outputs); MultiDataSet masks (plural attrs) collapse to the single
    mask the network applies, or raise if there are several."""
    feats = ds.features
    labs = ds.labels
    if isinstance(feats, (list, tuple)) or isinstance(labs, (list, tuple)):
        def one(ms, what):
            if ms is None:
                return None
            ms = [m for m in ms if m is not None]
            if len(ms) > 1:
                raise NotImplementedError(
                    f"ParallelWrapper supports at most one {what} mask per "
                    "MultiDataSet (the network applies a single mask)")
            return ms[0] if ms else None
        return (tuple(feats) if isinstance(feats, (list, tuple)) else feats,
                tuple(labs) if isinstance(labs, (list, tuple)) else labs,
                one(getattr(ds, "features_masks", None), "features"),
                one(getattr(ds, "labels_masks", None), "labels"))
    return feats, labs, getattr(ds, "features_mask", None), \
        getattr(ds, "labels_mask", None)


def _padder(pad, zero=False):
    """Pad `pad` rows onto axis 0: repeat the last row (batch arrays) or
    zeros (masks, so padded rows drop out of the loss)."""
    def f(a):
        a = np.asarray(a)
        tail = (np.zeros((pad,) + a.shape[1:], a.dtype) if zero
                else np.repeat(a[-1:], pad, 0))
        return np.concatenate([a, tail])
    return f


class ParallelWrapper:
    """Data-parallel trainer over a mesh's 'dp' (and optional 'fsdp') axis."""

    def __init__(self, net, mesh: Optional[Mesh] = None, use_fsdp: bool = False,
                 prefetch_buffer: int = 2, drift_audit: bool = True):
        if not net.initialized:
            raise ValueError("initialize the network first (net.init(...))")
        self.net = net
        self.mesh = mesh or data_parallel_mesh()
        self.use_fsdp = use_fsdp and "fsdp" in self.mesh.axis_names
        # ISSUE 13: checksum the per-device param replicas at the end of
        # each fit call (dl4j_replica_* — the dp lockstep audit)
        self.drift_audit = bool(drift_audit)
        self._step = None
        self._rep = NamedSharding(self.mesh, P())
        batch_axes = tuple(a for a in ("dp", "fsdp") if a in self.mesh.axis_names)
        self._batch_sh = NamedSharding(self.mesh, P(batch_axes or None))
        # batches divide only the axes they are SHARDED over — padding to
        # mesh.size on a dp×tp mesh would add unmasked duplicate rows
        self._batch_div = int(np.prod([self.mesh.shape[a]
                                       for a in batch_axes])) if batch_axes \
            else 1
        if "tp" in self.mesh.axis_names:
            # tensor parallel: layers that declare param_pspecs (tp.py's
            # Column/RowParallelDense, ShardedSelfAttention) get their
            # Megatron sharding; GSPMD inserts the psums when the step
            # compiles. With use_fsdp, params the tp resolver left
            # replicated get the fsdp layout instead (the two compose).
            from .tp import network_param_shardings
            self._param_sh = network_param_shardings(self.mesh, net)
            if self.use_fsdp:
                fsdp_sh = shard_params_fsdp(self.mesh, net.params)
                self._param_sh = jax.tree_util.tree_map(
                    lambda t, f: f if t.spec == P() else t,
                    self._param_sh, fsdp_sh)
        elif self.use_fsdp:
            self._param_sh = shard_params_fsdp(self.mesh, net.params)
        else:
            self._param_sh = jax.tree_util.tree_map(lambda _: self._rep, net.params)
        # place params/states once
        net.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), net.params, self._param_sh)
        net.states = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self._rep), net.states)

    @property
    def workers(self) -> int:
        return self.mesh.size

    def _build_step(self):
        if self.net._optimizer is None:
            self.net._build_optimizer(1)
            # re-place fresh opt state
            self.net._opt_state = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, self._rep), self.net._opt_state)
        optimizer = self.net._optimizer
        net = self.net
        with_stats = getattr(net, "_anomaly_detector", None) is not None
        # numerics sentinel (ISSUE 13) — see MLN._get_train_step
        gate = with_stats and getattr(net._anomaly_detector,
                                      "gate_updates", True)
        self._step_with_stats = (with_stats, gate)
        # the compiled step traced net._loss, which routes on the net's
        # remat policy — record it so a later toggle forces a rebuild
        self._built_remat = getattr(net, "remat_segments", None)

        def step(params, states, opt_state, x, y, rng, fmask, lmask):
            # split inside jit; next key rides the outputs (no separate
            # host-side split dispatch per batch — see MLN._get_train_step)
            use_rng, next_rng = jax.random.split(rng)
            (loss, new_states), grads = jax.value_and_grad(
                net._loss, has_aux=True)(params, states, x, y, use_rng,
                                         fmask, lmask)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            stats = None
            if with_stats:  # same failure-detection path as single-device fit
                from ..train.anomaly import maybe_stats_and_gate
                stats, new_params, new_opt_state, new_states = \
                    maybe_stats_and_gate(
                        gate, grads, params, new_params, opt_state,
                        new_opt_state, states, new_states)
            return new_params, new_states, new_opt_state, loss, stats, next_rng

        self._step_raw = step    # unjitted: fit_scanned scans over it
        from ..obs.compiles import CompileSentinel
        self._step = CompileSentinel("pw_train_step", jax.jit(
            step, donate_argnums=(0, 1, 2),
            in_shardings=(self._param_sh,
                          jax.tree_util.tree_map(lambda _: self._rep, net.states),
                          None,  # opt state: let the compiler propagate
                          self._batch_sh, self._batch_sh, self._rep,
                          self._batch_sh, self._batch_sh),
            ))
        return self._step

    def fit(self, iterator, *, epochs: int = 1):
        net = self.net
        want_stats = getattr(net, "_anomaly_detector", None) is not None
        want = (want_stats, want_stats and getattr(
            net._anomaly_detector, "gate_updates", True))
        if self._step is not None and getattr(self, "_step_with_stats", None) != want:
            self._step = None  # detector/gate toggled since compile — rebuild
            self._scan_epoch = None  # scans over _step_raw — same staleness
        if self._step is not None and getattr(self, "_built_remat", None) != \
                getattr(net, "remat_segments", None):
            self._step = None            # remat policy toggled — retrace
            self._scan_epoch = None
        step_fn = self._step or self._build_step()
        m_batches = get_registry().counter(
            "dl4j_parallel_fit_batches_total",
            "Batches stepped through ParallelWrapper.fit")
        # memory census (ISSUE 12), per replica: an fsdp-sharded param
        # tree reports what EACH device holds — the gauge the ZeRO
        # update-sharding PR (ROADMAP item 4) reads for its per-chip
        # memory-drop proof. Once per fit call, off the batch loop.
        try:
            from ..obs import memory as obs_memory
            components = {"params": net.params}
            if getattr(net, "_opt_state", None) is not None:
                components["optimizer"] = net._opt_state
            if getattr(net, "states", None) is not None:
                components["states"] = net.states
            obs_memory.emit_census(components, source="parallel_fit",
                                   per_replica=True)
        except Exception:  # noqa: BLE001 — census is decoration
            pass
        last = None
        n = self._batch_div
        anomaly_check = None
        if getattr(net, "_anomaly_detector", None) is not None:
            from ..train.anomaly import DelayedAnomalyCheck
            anomaly_check = DelayedAnomalyCheck(net._anomaly_detector)
        for _ in range(epochs):
            for ds in iterator:
                x, y, fmask, lmask = _unpack_batch(ds)
                multi = isinstance(x, tuple)
                rows = (x[0] if multi else x).shape[0]
                if rows % n:     # pad final partial batch to divide mesh
                    # padding is host work — device-resident arrays fetch
                    # once here (partial final batch only); full batches
                    # pass straight through without a host bounce
                    pad = n - rows % n
                    x = jax.tree_util.tree_map(_padder(pad), x)
                    y = jax.tree_util.tree_map(_padder(pad), y)
                    if fmask is not None:  # padded rows masked out entirely
                        fmask = jax.tree_util.tree_map(_padder(pad, zero=True),
                                                       fmask)
                    if lmask is not None:
                        lmask = jax.tree_util.tree_map(_padder(pad, zero=True),
                                                       lmask)
                net._last_batch_size = rows  # telemetry: pre-pad rows
                as_dev = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
                (net.params, net.states, net._opt_state, loss, gstats,
                 net._host_key) = step_fn(
                    net.params, net.states, net._opt_state,
                    as_dev(x), as_dev(y), net._host_key,
                    None if fmask is None else as_dev(fmask),
                    None if lmask is None else as_dev(lmask))
                net._step_count += 1
                m_batches.inc()
                if anomaly_check is not None and gstats is not None:
                    anomaly_check.push(gstats, net._step_count)
                last = loss
                if net.listeners:
                    lv = float(loss)
                    for listener in net.listeners:
                        listener.iteration_done(net, net._step_count, net.epoch_count, lv)
            net.epoch_count += 1
            if hasattr(iterator, "reset"):
                iterator.reset()
        if anomaly_check is not None:
            anomaly_check.flush()
        # drift audit (ISSUE 13): per-device checksums over the
        # replicated params at the end of every fit call — the dp
        # replicas hold COPIES of the same logical array and must be
        # bit-identical; zero drift here is the lockstep proof the
        # ZeRO update-sharding equivalence case (ROADMAP 4) cites.
        # Once per fit (not per batch): the audit fetches every
        # replica's copy to host. Decoration — never takes down a fit.
        if self.drift_audit and self.workers > 1:
            try:
                self.audit_drift()
            except Exception:  # noqa: BLE001 — audit is decoration
                pass
        return None if last is None else float(last)

    def audit_drift(self):
        """Checksum every device's copy of the replicated params NOW
        (``obs.numerics.audit_params``) and return the verdict:
        ``{round, replicas, max_drift, bit_identical}``. fsdp/tp-sharded
        leaves are skipped — each device holds a different slice, there
        is no cross-replica copy to compare."""
        from ..obs import numerics as obs_numerics
        return obs_numerics.audit_params(self.net.params,
                                         source="parallel_fit")

    def fit_scanned(self, data, *, epochs: int = 1):
        """One jit dispatch per EPOCH across the dp mesh: the epoch's
        equally-shaped minibatches stack to (K, B, ...) sharded over the
        batch axes, and the dp train step runs as a ``lax.scan`` over K.
        Composes the two throughput levers — data-parallel sharding and
        the scanned epoch loop (net.fit_scanned) — so per-step dispatch
        overhead (the quantity `bench.py dpoverhead` measures) is paid
        once per epoch. Same restrictions as net.fit_scanned: no masks,
        no anomaly gating, deferred-score listeners only; single-arm
        DataSet batches (MultiDataSet: use fit())."""
        net = self.net
        batches = [data] if not isinstance(data, (list, tuple)) else list(data)
        if not batches:
            return None
        if any(isinstance(b.features, (list, tuple)) for b in batches):
            raise ValueError("fit_scanned supports single-arm DataSet "
                             "batches; use fit() for MultiDataSet")
        if any(getattr(b, "features_mask", None) is not None
               or getattr(b, "labels_mask", None) is not None
               for b in batches):
            raise ValueError("fit_scanned does not support masked batches; "
                             "use fit()")
        shapes = {(np.shape(b.features), np.shape(b.labels))
                  for b in batches}
        if len(shapes) > 1:
            raise ValueError(f"fit_scanned needs equally-shaped batches, "
                             f"got {sorted(shapes)}; use fit()")
        if batches[0].features.shape[0] % self._batch_div:
            raise ValueError(
                f"batch size {batches[0].features.shape[0]} must divide the "
                f"mesh batch axes ({self._batch_div}) — fit_scanned does "
                "not pad")
        from ..nn._scan_common import check_scan_listeners
        check_scan_listeners(net)
        if epochs <= 0:
            return None
        if self._step is not None and (
                getattr(self, "_built_remat", None) !=
                getattr(net, "remat_segments", None)
                or (getattr(self, "_step_with_stats", None)
                    or (False,))[0]):
            # remat policy toggled, or the cached step was compiled with
            # anomaly-stats gating (detector since disabled) — retrace
            self._step = None
            self._scan_epoch = None
        if self._step is None:
            self._build_step()
        step_raw = self._step_raw
        xs = jnp.stack([jnp.asarray(b.features) for b in batches])
        ys = jnp.stack([jnp.asarray(b.labels) for b in batches])
        if getattr(self, "_scan_epoch", None) is None:
            def scan_epoch(params, states, opt_state, rng, xs, ys):
                def body(carry, xy):
                    p, s, o, k = carry
                    x, y = xy
                    p, s, o, loss, _, k = step_raw(p, s, o, x, y, k,
                                                   None, None)
                    return (p, s, o, k), loss
                (params, states, opt_state, rng), losses = lax.scan(
                    body, (params, states, opt_state, rng), (xs, ys))
                return params, states, opt_state, rng, losses

            # stacked batches: leading K axis replicated, batch axes sharded
            stacked_sh = NamedSharding(self.mesh,
                                       P(None, *self._batch_sh.spec))
            self._scan_epoch = jax.jit(
                scan_epoch, donate_argnums=(0, 1, 2),
                in_shardings=(self._param_sh,
                              jax.tree_util.tree_map(lambda _: self._rep,
                                                     net.states),
                              None, self._rep, stacked_sh, stacked_sh))
        losses = None
        for _ in range(epochs):
            (net.params, net.states, net._opt_state, net._host_key,
             losses) = self._scan_epoch(net.params, net.states,
                                        net._opt_state, net._host_key,
                                        xs, ys)
            net._step_count += len(batches)
            net.epoch_count += 1
            from ..nn._scan_common import replay_scan_listeners
            replay_scan_listeners(net, losses, len(batches))
        return float(np.asarray(losses)[-1])


class ParallelInference:
    """Sharded batched inference (reference ParallelInference).

    Splits incoming batches over the dp axis; with `dynamic_batching`,
    requests accumulate to `max_batch` before one device sweep. With
    ``max_wait_ms`` set, a partial batch is flushed by a deadline timer
    once its OLDEST request has waited that long — a trickle of traffic
    below `max_batch` no longer waits forever for a flush it can't
    trigger. Every ``submit`` returns a Future for that request's rows,
    resolved at whichever flush carries them (size threshold, deadline,
    or an explicit ``flush()``).
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, max_batch: int = 64,
                 max_wait_ms: Optional[float] = None):
        self.net = net
        self.mesh = mesh or data_parallel_mesh()
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self._rep = NamedSharding(self.mesh, P())
        batch_axes = tuple(a for a in ("dp",) if a in self.mesh.axis_names)
        self._batch_sh = NamedSharding(self.mesh, P(batch_axes or None))
        self._batch_div = (self.mesh.shape["dp"]
                           if "dp" in self.mesh.axis_names else 1)
        # Keep a LOCAL placed copy of params/states on THIS mesh: a net
        # trained under a different mesh (e.g. dp×tp ParallelWrapper) hands
        # us arrays from a foreign mesh, and mutating the net would break
        # the trainer's compiled step. Layers that declare tp pspecs stay
        # sharded when this mesh has a tp axis; everything else (including
        # tp shards when the axis is absent) gathers to replicated.
        from .tp import network_param_shardings
        self._param_sh = network_param_shardings(self.mesh, net)
        self._params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), net.params, self._param_sh)
        self._states = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self._rep), net.states)
        self._infer = None
        self._pending = []
        self._pending_ts = []  # enqueue time per request (queue-wait metric)
        self._pending_futures = []   # one Future per submitted request
        self._lock = threading.RLock()
        self._timer: Optional[threading.Timer] = None
        if max_wait_ms is not None:
            # a deadline timer firing DURING interpreter shutdown
            # dispatches into a jax runtime that is mid-teardown and
            # aborts the process (std::terminate). atexit runs before
            # jax's own exit hooks (LIFO; jax registered at import), so
            # cancel-or-drain the timer while the runtime is still up.
            import atexit
            import weakref
            ref = weakref.ref(self)
            atexit.register(lambda: (lambda s: s and s._drain_timer())(
                ref()))

    def _drain_timer(self):
        """Cancel a pending deadline timer; if its callback is already
        mid-flush, wait for it to finish (process-exit path)."""
        with self._lock:
            t, self._timer = self._timer, None
        if t is not None:
            t.cancel()
            if t.is_alive():
                t.join(timeout=30)

    def refresh(self):
        """Re-copy the net's current params (e.g. after more training)."""
        self._params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), self.net.params,
            self._param_sh)
        self._states = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self._rep), self.net.states)
        return self

    def _build(self):
        net = self.net
        from ..nn.computation_graph import ComputationGraph

        if isinstance(net, ComputationGraph):
            def infer(params, states, x):
                acts, _, _ = net._forward(params, states, x, train=False,
                                          rng=None)
                outs = [acts[o] for o in net.conf.outputs]
                return outs[0] if len(outs) == 1 else outs
        else:
            def infer(params, states, x):
                y, _ = net._forward(params, states, x, train=False, rng=None)
                return y

        self._infer = jax.jit(infer, in_shardings=(
            self._param_sh,
            jax.tree_util.tree_map(lambda _: self._rep, self._states),
            self._batch_sh))
        return self._infer

    def output(self, x):
        fn = self._infer or self._build()
        multi = isinstance(x, (list, tuple))   # multi-input ComputationGraph
        xs = [np.asarray(a) for a in x] if multi else [np.asarray(x)]
        n = self._batch_div
        orig = xs[0].shape[0]
        if orig % n:
            pad_fn = _padder(n - orig % n)
            xs = [pad_fn(a) for a in xs]
        arg = tuple(jnp.asarray(a) for a in xs) if multi else jnp.asarray(xs[0])
        out = fn(self._params, self._states, arg)
        if isinstance(out, (list, tuple)):   # multi-output ComputationGraph
            return [np.asarray(o)[:orig] for o in out]
        return np.asarray(out)[:orig]

    def submit(self, x):
        """Dynamic batching: queue a request. Flushes inline (and returns
        the whole batch's parts, legacy contract) when the size threshold
        is met; otherwise returns this request's Future, which resolves
        at the flush that carries it — the deadline timer's flush when
        ``max_wait_ms`` is set, or an explicit ``flush()``."""
        x = np.asarray(x)
        with self._lock:
            if self._pending and x.shape[1:] != self._pending[0].shape[1:]:
                raise ValueError(
                    f"mixed-shape submission: request rows have shape "
                    f"{x.shape[1:]} but the pending dynamic batch holds "
                    f"{self._pending[0].shape[1:]} — flush() concatenates "
                    "on axis 0, so per-request trailing dims must match "
                    "(flush or use a separate ParallelInference per shape)")
            fut: Future = Future()
            self._pending.append(x)
            self._pending_ts.append(time.perf_counter())
            self._pending_futures.append(fut)
            get_registry().counter(
                "dl4j_inference_requests_total",
                "Requests submitted to dynamic batching").inc()
            if sum(p.shape[0] for p in self._pending) >= self.max_batch:
                return self._flush_locked()
            if self.max_wait_ms is not None and self._timer is None:
                t = threading.Timer(self.max_wait_ms / 1e3,
                                    lambda: self._deadline_flush(t))
                t.daemon = True
                t.start()
                self._timer = t
        return fut

    def _deadline_flush(self, timer):
        """Timer callback: the oldest pending request hit max_wait_ms —
        sweep whatever is queued. Results reach callers via the Futures
        submit returned. ``timer`` identity-guards the race where a
        fired-but-lock-blocked timer outlives the flush that retired it:
        a stale callback must neither flush the NEXT batch early nor
        orphan that batch's live timer handle."""
        with self._lock:
            if self._timer is not timer:
                return
            self._timer = None
            if self._pending:
                get_registry().counter(
                    "dl4j_inference_deadline_flushes_total",
                    "Dynamic batches flushed by the max_wait_ms deadline "
                    "rather than the size threshold").inc()
                self._flush_locked()

    def flush(self):
        with self._lock:
            return self._flush_locked()

    def _flush_locked(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return []
        sizes = [p.shape[0] for p in self._pending]
        batch = np.concatenate(self._pending)
        # serving-plane telemetry: how full each device sweep runs under
        # the offered traffic, and how long requests waited to board it —
        # the two dials continuous batching tunes (μ-cuDNN occupancy
        # analysis; ROADMAP item 1 inherits these for free)
        reg = get_registry()
        now = time.perf_counter()
        wait_h = reg.histogram(
            "dl4j_inference_queue_wait_seconds",
            "Time a request waited in the dynamic-batching queue")
        for ts in self._pending_ts:
            wait_h.observe(now - ts)
        reg.gauge(
            "dl4j_inference_batch_occupancy",
            "Rows in the last dynamic batch / max_batch").set(
            batch.shape[0] / max(self.max_batch, 1))
        reg.counter("dl4j_inference_batches_total",
                    "Dynamic batches swept through the device").inc()
        futures = self._pending_futures
        self._pending = []
        self._pending_ts = []
        self._pending_futures = []
        try:
            out = self.output(batch)
        except Exception as e:
            for f in futures:       # a deadline-flush caller only has the
                try:                # Future to learn of the failure from
                    f.set_exception(e)
                except InvalidStateError:
                    pass            # caller cancelled while queued
            raise
        parts, off = [], 0
        for s, f in zip(sizes, futures):
            parts.append(out[off:off + s])
            try:
                f.set_result(out[off:off + s])
            except InvalidStateError:
                pass   # this caller cancelled; its rows still ship in
                       # the parts list, the OTHER futures must resolve
            off += s
        return parts
