"""Partition lease table — the elastic re-partitioning half of the
scaleout plane (ROADMAP item 4).

The reference Spark ``ParameterAveragingTrainingMaster`` never loses a
partition: a failed executor's split is re-provisioned onto a
replacement and re-run. The drop-only driver this replaces lost the
dead worker's partition silently (the survivors just averaged over less
data). Here the master holds a table of work items — one per
``(epoch, shard)`` pair, epoch-major — and workers *lease* items one at
a time over the wire instead of receiving a static partition at spawn:

- **affinity**: item ``i`` prefers the worker slot
  ``(i % n_shards) % n_workers``, which reproduces the old round-robin
  static partitioning exactly while every worker is alive (so the
  freq-1 averaging-equivalence anchor still holds bit-for-bit);
- **reassignment**: when a worker dies (or never shows up), its leases
  return to the pool and its *affinity slot* becomes stealable — a
  survivor or rejoiner picks the items up, so job output covers every
  partition regardless of the failure schedule;
- **exactly-once accounting**: each item is completed at most once in
  the table (stale completions from a dropped worker's ghost are
  ignored unless the item is still unclaimed), and the job is done when
  ``all_done()``;
- **resume**: ``snapshot()``/``restore()`` round-trip the completed set
  through the between-round checkpoint (``leases.json``), so a
  restarted master re-runs only the unfinished items.

At-least-once caveat: an item completed after the last checkpoint but
before a master crash, or in flight when its worker died, is re-run.
Parameter averaging tolerates the duplicated fit; the table's
``completed`` set still counts each item once.

The table is self-locking (leaf lock — it never calls out), so the hub
may use it from any handler thread and the fast unit suite can exercise
the invariants without sockets.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Iterable, List, Optional, Tuple

AVAILABLE, LEASED, DONE = 0, 1, 2

#: grant statuses returned by :meth:`LeaseTable.acquire`
GRANT_NONE = 0      # nothing for this worker, now or later — wrap up
GRANT_OK = 1        # payload carries the granted item id
GRANT_RETRY = 2     # provisioning window: items exist whose affine
#                     owner has not registered yet — ask again shortly


class LeaseTable:
    """Lease table over ``n_shards * epochs`` work items."""

    def __init__(self, n_shards: int, epochs: int = 1, n_workers: int = 1,
                 completed: Iterable[int] = ()):
        if n_shards < 1 or epochs < 1 or n_workers < 1:
            raise ValueError("n_shards, epochs, n_workers must be >= 1")
        self.n_shards = n_shards
        self.epochs = epochs
        self.n_workers = n_workers
        self.n_items = n_shards * epochs
        self._lock = threading.Lock()
        self._state = [AVAILABLE] * self.n_items
        self._owner: List[Optional[int]] = [None] * self.n_items
        self._prev: List[Optional[int]] = [None] * self.n_items
        self.reassigned = 0     # grants to a non-affine worker
        for i in completed:
            i = int(i)
            if not 0 <= i < self.n_items:
                raise ValueError(f"completed item {i} out of range "
                                 f"[0, {self.n_items})")
            self._state[i] = DONE

    # ------------------------------------------------------------ geometry
    def shard_of(self, item: int) -> int:
        return item % self.n_shards

    def epoch_of(self, item: int) -> int:
        return item // self.n_shards

    def affinity_of(self, item: int) -> int:
        """The worker *slot* (wid mod n_workers) this item prefers —
        matches the old static round-robin ``parts[i % n_workers]``."""
        return (item % self.n_shards) % self.n_workers

    # ------------------------------------------------------------ leasing
    def acquire(self, wid: int,
                stealable_slots: Iterable[int] = (),
                unsettled_slots: Iterable[int] = ()) -> Tuple[int, int]:
        """Try to lease an item for worker ``wid``.

        ``stealable_slots``: affinity slots whose owner is known absent
        (dead or departed) — their items may be reassigned.
        ``unsettled_slots``: slots whose owner has not registered *yet*
        (the provisioning window) — their items are held back and the
        caller is told to retry rather than steal prematurely.

        Returns ``(status, item)`` with status one of GRANT_OK /
        GRANT_NONE / GRANT_RETRY (item is only meaningful for GRANT_OK).
        Item ids are granted in ascending order, i.e. epoch-major FIFO.
        """
        aff = wid % self.n_workers
        steal = set(stealable_slots)
        unsettled = set(unsettled_slots)
        with self._lock:
            steal_pick = None
            saw_unsettled = False
            for i, st in enumerate(self._state):
                if st != AVAILABLE:
                    continue
                slot = self.affinity_of(i)
                if slot == aff:
                    return self._grant_locked(i, wid)
                if steal_pick is None and slot in steal:
                    steal_pick = i          # keep scanning for an affine one
                elif slot in unsettled:
                    saw_unsettled = True
            if steal_pick is not None:
                return self._grant_locked(steal_pick, wid)
            if saw_unsettled:
                return GRANT_RETRY, -1
            return GRANT_NONE, -1

    def _grant_locked(self, item: int, wid: int) -> Tuple[int, int]:
        self._state[item] = LEASED
        if self.affinity_of(item) != wid % self.n_workers or \
                self._prev[item] not in (None, wid):
            self.reassigned += 1
        self._owner[item] = wid
        return GRANT_OK, item

    def complete(self, wid: int, item: int) -> bool:
        """Mark ``item`` done by ``wid``. Stale completions (the item was
        released and re-leased to someone else, or already done) are
        ignored — each item counts DONE exactly once."""
        if not 0 <= item < self.n_items:
            return False
        with self._lock:
            st = self._state[item]
            if st == LEASED and self._owner[item] == wid:
                self._state[item] = DONE
                self._owner[item] = None
                return True
            if st == AVAILABLE and self._prev[item] == wid:
                # the worker was dropped (lease released) but its DONE
                # arrived anyway — accept, sparing a re-run
                self._state[item] = DONE
                return True
            return False

    def release_worker(self, wid: int) -> List[int]:
        """Return all of ``wid``'s unfinished leases to the pool."""
        out = []
        with self._lock:
            for i, st in enumerate(self._state):
                if st == LEASED and self._owner[i] == wid:
                    self._state[i] = AVAILABLE
                    self._owner[i] = None
                    self._prev[i] = wid
                    out.append(i)
        return out

    # ------------------------------------------------------------ queries
    def all_done(self) -> bool:
        with self._lock:
            return all(st == DONE for st in self._state)

    @property
    def completed(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(i for i, st in enumerate(self._state)
                         if st == DONE)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {"available": self._state.count(AVAILABLE),
                    "leased": self._state.count(LEASED),
                    "done": self._state.count(DONE),
                    "reassigned": self.reassigned}

    # ------------------------------------------------------------ resume
    def snapshot(self) -> str:
        """JSON snapshot for the between-round checkpoint."""
        return json.dumps({"n_shards": self.n_shards,
                           "epochs": self.epochs,
                           "completed": list(self.completed)})

    @staticmethod
    def restore(snapshot: str, n_shards: int, epochs: int,
                n_workers: int) -> Optional["LeaseTable"]:
        """Rebuild a table from ``snapshot`` if its geometry matches the
        (n_shards, epochs) of the new job; None = start fresh (the
        checkpoint belongs to a different job shape)."""
        try:
            d = json.loads(snapshot)
            if int(d["n_shards"]) != n_shards or int(d["epochs"]) != epochs:
                return None
            return LeaseTable(n_shards, epochs, n_workers,
                              completed=[int(i) for i in d["completed"]])
        except (ValueError, KeyError, TypeError):
            return None


class RequestLeaseTable:
    """Lease table over an UNBOUNDED request stream — the serving-fleet
    sibling of :class:`LeaseTable` (ISSUE 18).

    The training table's geometry is fixed at construction (``n_shards *
    epochs`` items, affinity by slot arithmetic); a serving fleet sees an
    open-ended arrival stream and routes by burn-rate/session affinity
    *outside* the table. What carries over unchanged is the completion
    contract: every item is completed **exactly once** no matter how many
    replicas die holding it, stale completions from a replica whose lease
    was released-and-re-granted are ignored, and ``release_replica``
    returns the dead replica's in-flight items so the router can re-lease
    them on survivors. Same state constants, same leaf-lock discipline.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Dict[int, int] = {}
        self._owner: Dict[int, Optional[int]] = {}
        self._prev: Dict[int, Optional[int]] = {}
        self._next = 0
        self.reassigned = 0      # leases granted after a release

    def add(self) -> int:
        """Register a new work item; returns its id (monotonic)."""
        with self._lock:
            item = self._next
            self._next += 1
            self._state[item] = AVAILABLE
            self._owner[item] = None
            self._prev[item] = None
            return item

    def lease(self, item: int, replica: int) -> bool:
        """Grant ``item`` to ``replica``. False if unknown / already
        leased or done (the router must release before re-leasing)."""
        with self._lock:
            if self._state.get(item) != AVAILABLE:
                return False
            self._state[item] = LEASED
            if self._prev[item] is not None:
                self.reassigned += 1
            self._owner[item] = replica
            return True

    def owner_of(self, item: int) -> Optional[int]:
        with self._lock:
            return self._owner.get(item)

    def complete(self, replica: int, item: int) -> bool:
        """Exactly-once completion: True iff ``replica`` currently holds
        the lease (or held it when the item was released and no one has
        re-leased it since — the late-DONE-from-a-ghost case). A result
        arriving from a presumed-dead replica AFTER the item was re-leased
        elsewhere returns False and must be dropped by the caller."""
        with self._lock:
            st = self._state.get(item)
            if st == LEASED and self._owner[item] == replica:
                self._state[item] = DONE
                self._owner[item] = None
                return True
            if st == AVAILABLE and self._prev[item] == replica:
                self._state[item] = DONE
                return True
            return False

    def release_replica(self, replica: int) -> List[int]:
        """Return all of ``replica``'s unfinished leases to the pool (in
        item order) so they can be re-leased on survivors."""
        out = []
        with self._lock:
            for item in sorted(self._state):
                if self._state[item] == LEASED and \
                        self._owner[item] == replica:
                    self._state[item] = AVAILABLE
                    self._owner[item] = None
                    self._prev[item] = replica
                    out.append(item)
        return out

    def all_done(self) -> bool:
        with self._lock:
            return all(st == DONE for st in self._state.values())

    def counts(self) -> Dict[str, int]:
        with self._lock:
            vals = list(self._state.values())
            return {"available": vals.count(AVAILABLE),
                    "leased": vals.count(LEASED),
                    "done": vals.count(DONE),
                    "reassigned": self.reassigned}
