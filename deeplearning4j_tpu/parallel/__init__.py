"""Parallelism — reference `deeplearning4j-scaleout` rethought for TPU:
one mesh + named shardings + XLA collectives instead of replicated workers
over NCCL/Aeron. See SURVEY.md §2.8."""

from .grad_sharing import AdaptiveThreshold, GradientSharingAccumulator
from .transport import (DistributedGradientWorker, GradientExchangeServer,
                        SocketGradientTransport)
from .mesh import (MeshSpec, batch_sharding, bootstrap_distributed,
                   data_parallel_mesh, hybrid_mesh_2d, make_mesh, replicated,
                   shard_params_fsdp)
from .pipeline import (make_pipeline_loss, make_pipeline_train_step,
                       place_params_for_pipeline)
from .pipeline_generic import (make_cg_pipeline_train_step,  # noqa: F401
                               shard_params_pp,
                               make_mln_pipeline_loss,
                               make_mln_pipeline_train_step, microbatches,
                               partition_layers)
from .tp import (ChannelShardedConvolution, ColumnParallelDense,
                 ColumnParallelOutputLayer, InputChannelShardedConvolution,
                 RowParallelDense, RowShardedEmbedding,
                 RowShardedEmbeddingSequence, ShardedSelfAttention,
                 network_param_shardings)
from .ring_attention import (ring_attention, ring_attention_inner,
                             ring_attention_sharded)
from .param_avg import ParameterAveragingTrainer
from .leases import LeaseTable, RequestLeaseTable
from .scaleout import (MasterDiedError, ParamAveragingHub,
                       ParameterAveragingTrainingMaster,
                       SparkComputationGraph, SparkDl4jMultiLayer,
                       TrainingMaster, WorkerClient, read_resume_state,
                       worker_main)
from .wrapper import ParallelInference, ParallelWrapper

__all__ = [
    "AdaptiveThreshold", "GradientSharingAccumulator", "MeshSpec",
    "batch_sharding", "bootstrap_distributed", "data_parallel_mesh",
    "hybrid_mesh_2d", "make_mesh", "replicated", "shard_params_fsdp",
    "make_pipeline_loss", "make_pipeline_train_step",
    "place_params_for_pipeline", "ring_attention", "ring_attention_inner",
    "ring_attention_sharded", "ParallelInference", "ParallelWrapper",
    "ParameterAveragingTrainer",
    "ColumnParallelDense", "ColumnParallelOutputLayer", "RowParallelDense",
    "RowShardedEmbedding", "RowShardedEmbeddingSequence",
    "ChannelShardedConvolution", "InputChannelShardedConvolution",
    "ShardedSelfAttention", "network_param_shardings",
    "make_mln_pipeline_loss", "make_mln_pipeline_train_step",
    "shard_params_pp", "make_cg_pipeline_train_step",
    "microbatches", "partition_layers",
    "DistributedGradientWorker", "GradientExchangeServer",
    "SocketGradientTransport",
    "TrainingMaster", "ParameterAveragingTrainingMaster",
    "SparkDl4jMultiLayer", "SparkComputationGraph", "ParamAveragingHub",
    "WorkerClient", "worker_main", "LeaseTable", "RequestLeaseTable",
    "MasterDiedError", "read_resume_state",
]
