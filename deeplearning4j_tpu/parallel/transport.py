"""Socket transport for threshold-encoded gradient sharing — the process-
boundary half of the reference's gradient-sharing regime.

Reference parity: ``org.deeplearning4j.optimize.solvers.accumulation.
EncodedGradientsAccumulator`` + Aeron UDP transport (deeplearning4j-
scaleout ``gradientsharing``): workers exchange ±threshold sparse token
streams over the wire, every worker applies the same decoded aggregate,
residual error feedback keeps the compression lossless over time. (Here
``DistributedGradientWorker.step`` returns the decoded MEAN — divide-by-
workers — so the learning rate keeps its single-worker meaning; the
upstream accumulator applies the raw sum and expects lr scaled
accordingly.)

TPU-first positioning (same as grad_sharing.py): within a pod, dense psum
over ICI always wins — this transport is for the slow-interconnect regime
(DCN between pods, host federation) the reference built Aeron for. Design:
a tiny hub (``GradientExchangeServer``) stands in for Aeron multicast —
each round it gathers one length-prefixed frame per worker and broadcasts
the full set back. Frames carry the SENDER's threshold so adaptive
thresholds may drift per worker without corrupting decode. TCP and Unix
domain sockets supported (``address=("127.0.0.1", port)`` or a filesystem
path).

Wire format per frame:
  uint32  payload byte length (tokens only)
  float32 sender threshold
  int64[] tokens (threshold_encode output)
Broadcast reply: uint32 worker count, then the workers' frames in order.

This module also owns the SCALEOUT frame protocol (kind-tagged frames
used by ``parallel/scaleout.py``'s parameter-averaging hub) — both wire
formats live here so every socket-facing byte layout is in one file.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import List, Optional, Tuple, Union

import numpy as np

from ..utils.native import threshold_decode, threshold_encode
from .grad_sharing import AdaptiveThreshold

Address = Union[str, Tuple[str, int]]

_HDR = struct.Struct("<If")  # payload bytes, sender threshold


# ---------------------------------------------------------------------------
# Scaleout frame protocol (parameter-averaging hub <-> worker).
# One frame per message, little-endian:
#   uint8   kind
#   uint32  payload byte length
#   bytes   payload (kind-specific, see below)
# ---------------------------------------------------------------------------

FRAME_HEADER = struct.Struct("<BI")      # kind, payload bytes

KIND_PARAMS = 0     # worker -> hub: float32[] flat params contributing
#                     to the round; hub -> worker reply: uint32 round
#                     index + float32[] round mean (the round header
#                     keys the ISSUE 13 drift audit by the hub's own
#                     counter — elastic membership can't skew it)
KIND_DONE = 1       # worker -> hub: partition finished, leaving the job
KIND_HELLO = 2      # uint32 worker id — first frame on every connect, so
#                     the hub's worker labels are the CALLER's ids (a
#                     known id on a fresh connection is a REJOIN)
KIND_SPANCTX = 3    # hub -> worker right after HELLO: the master's span
#                     context header (empty payload = tracing off)
KIND_REJOIN = 4     # hub -> worker after SPANCTX: uint32 current round,
#                     uint32 registered worker id (echoed so a
#                     uniquified duplicate dialer learns its hub-side
#                     identity — the drift audit labels by it),
#                     then float32[] current mean params (absent = no
#                     round completed yet) — a (re)joiner starts from the
#                     job's live state instead of its stale local params
KIND_LEASE_REQ = 5  # worker -> hub: request a partition lease (empty)
KIND_LEASE = 6      # hub -> worker: uint8 grant status (leases.GRANT_*),
#                     then uint32 item id when status == GRANT_OK
KIND_LEASE_DONE = 7  # worker -> hub: uint32 item id completed (no ack —
#                     a completion lost with the connection is re-run,
#                     the at-least-once half of the lease contract)
KIND_FLEET_SUBMIT = 8   # router -> replica: one leased generation request
#                     (see pack_fleet_submit for the payload layout)
KIND_FLEET_RESULT = 9   # replica -> router: the finished request — lease
#                     item id + generated tokens + finish reason; the
#                     router's RequestLeaseTable.complete() decides
#                     whether this result wins (exactly-once) or is a
#                     ghost from a presumed-dead replica (dropped)


# ---------------------------------------------------------------------------
# Fleet frame payloads (ISSUE 18). The FleetRouter's in-process replica
# handles round-trip every submit/result through these packers, so the
# byte layout is exercised in tier-1 today and a socket-backed replica
# host can slot in behind the same boundary later. Little-endian:
#
#   FLEET_SUBMIT: uint32 item, uint32 max_new_tokens, float32 temperature,
#                 int32 top_k (0 = off), int32 eos_id (-1 = none),
#                 uint8 kind (RequestKind wire byte, ISSUE 20),
#                 uint8 beam_width (0 = default, BEAM only),
#                 uint8 pooling (EMBED only: 0 = mean, 1 = last),
#                 uint32 allowlist length, int32[] allowed token ids
#                 (CONSTRAINED only; 0 = no mask),
#                 uint16 session byte length, session bytes (utf-8),
#                 uint32 prompt length, int32[] prompt token ids
#   FLEET_RESULT: uint32 item, uint8 reason byte length, reason (utf-8),
#                 uint8 kind (RequestKind wire byte),
#                 uint32 token count, int32[] generated token ids,
#                 uint32 float count, float32[] kind payload (SCORE:
#                 per-token logprobs; EMBED: the pooled embedding;
#                 BEAM: [best total logprob]; else empty)
# ---------------------------------------------------------------------------

_FLEET_SUBMIT_HDR = struct.Struct("<IIfiiBBB")
_FLEET_RESULT_HDR = struct.Struct("<IB")


def pack_fleet_submit(item: int, prompt_ids, max_new_tokens: int,
                      temperature: float = 0.0, top_k: int = 0,
                      eos_id: Optional[int] = None,
                      session_id: Optional[str] = None,
                      kind: int = 0, beam_width: int = 0,
                      pooling: int = 0, allowed_ids=None) -> bytes:
    sess = (session_id or "").encode()
    if len(sess) > 0xFFFF:
        raise ValueError("session_id too long for wire format")
    ids = np.ascontiguousarray(np.asarray(prompt_ids, np.int32))
    allow = np.ascontiguousarray(np.asarray(
        [] if allowed_ids is None else allowed_ids, np.int32))
    return (_FLEET_SUBMIT_HDR.pack(item, max_new_tokens, float(temperature),
                                   int(top_k or 0),
                                   -1 if eos_id is None else int(eos_id),
                                   int(kind), int(beam_width),
                                   int(pooling))
            + struct.pack("<I", allow.size) + allow.tobytes()
            + struct.pack("<H", len(sess)) + sess
            + struct.pack("<I", ids.size) + ids.tobytes())


def unpack_fleet_submit(payload: bytes) -> dict:
    (item, max_new, temp, top_k, eos, kind, beam_width,
     pooling) = _FLEET_SUBMIT_HDR.unpack_from(payload)
    off = _FLEET_SUBMIT_HDR.size
    (na,) = struct.unpack_from("<I", payload, off)
    off += 4
    allow = np.frombuffer(payload, np.int32, count=na, offset=off).copy()
    off += 4 * na
    (slen,) = struct.unpack_from("<H", payload, off)
    off += 2
    sess = payload[off:off + slen].decode()
    off += slen
    (n,) = struct.unpack_from("<I", payload, off)
    off += 4
    ids = np.frombuffer(payload, np.int32, count=n, offset=off).copy()
    return {"item": item, "prompt_ids": ids, "max_new_tokens": max_new,
            "temperature": temp, "top_k": top_k or None,
            "eos_id": None if eos == -1 else eos,
            "session_id": sess or None,
            "kind": kind, "beam_width": beam_width, "pooling": pooling,
            "allowed_ids": allow if na else None}


def pack_fleet_result(item: int, token_ids, reason: str,
                      kind: int = 0, floats=None) -> bytes:
    rb = reason.encode()
    if len(rb) > 0xFF:
        raise ValueError("finish reason too long for wire format")
    ids = np.ascontiguousarray(np.asarray(token_ids, np.int32))
    fl = np.ascontiguousarray(np.asarray(
        [] if floats is None else floats, np.float32))
    return (_FLEET_RESULT_HDR.pack(item, len(rb)) + rb
            + struct.pack("<B", int(kind))
            + struct.pack("<I", ids.size) + ids.tobytes()
            + struct.pack("<I", fl.size) + fl.tobytes())


def unpack_fleet_result(payload: bytes) -> dict:
    item, rlen = _FLEET_RESULT_HDR.unpack_from(payload)
    off = _FLEET_RESULT_HDR.size
    reason = payload[off:off + rlen].decode()
    off += rlen
    (kind,) = struct.unpack_from("<B", payload, off)
    off += 1
    (n,) = struct.unpack_from("<I", payload, off)
    off += 4
    ids = np.frombuffer(payload, np.int32, count=n, offset=off).copy()
    off += 4 * n
    (nf,) = struct.unpack_from("<I", payload, off)
    off += 4
    floats = np.frombuffer(payload, np.float32, count=nf,
                           offset=off).copy()
    return {"item": item, "token_ids": ids, "reason": reason,
            "kind": kind, "floats": floats}


def send_frame(conn: socket.socket, kind: int, payload: bytes = b""):
    conn.sendall(FRAME_HEADER.pack(kind, len(payload)) + payload)


def recv_frame(conn: socket.socket) -> Tuple[int, bytes]:
    kind, nbytes = FRAME_HEADER.unpack(_recv_exact(conn, FRAME_HEADER.size))
    payload = _recv_exact(conn, nbytes) if nbytes else b""
    return kind, bytes(payload)


def backoff_delays(base: float, cap: float, n: int) -> List[float]:
    """The bounded exponential-backoff schedule used by scaleout's
    ``WorkerClient``: delay before retry i is ``min(cap, base * 2**i)``.
    Pure so the fast suite can pin the schedule."""
    return [min(cap, base * (2 ** i)) for i in range(max(0, n))]


# ---------------------------------------------------------------------------
# Span-context wire encoding — the cross-transport half of obs.spans.
# A SpanContext serializes to its JSON header (empty payload = no trace);
# scaleout's hub sends one frame of this to every worker on connect, so a
# master round and its worker fits share one trace tree.
# ---------------------------------------------------------------------------

def pack_span_context(ctx) -> bytes:
    """``SpanContext | None`` -> wire payload bytes."""
    return b"" if ctx is None else ctx.to_header().encode()


def unpack_span_context(payload: bytes):
    """Wire payload -> ``SpanContext | None`` (tolerates garbage: a trace
    header must never take down a training job)."""
    from ..obs.spans import SpanContext
    if not payload:
        return None
    try:
        return SpanContext.from_header(payload.decode())
    except UnicodeDecodeError:
        return None


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = conn.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("gradient peer closed the connection")
        got += r
    return buf   # bytearray: both consumers (struct.unpack, np.frombuffer)
                 # take buffer objects — bytes(buf) would re-copy the frame


def _recv_frame(conn: socket.socket) -> Tuple[np.ndarray, float]:
    nbytes, threshold = _HDR.unpack(_recv_exact(conn, _HDR.size))
    payload = _recv_exact(conn, nbytes) if nbytes else b""
    return np.frombuffer(payload, np.int64).copy(), threshold


def _send_frame(conn: socket.socket, tokens: np.ndarray, threshold: float):
    payload = np.ascontiguousarray(tokens, np.int64).tobytes()
    conn.sendall(_HDR.pack(len(payload), threshold) + payload)


def _make_socket(address: Address) -> socket.socket:
    if isinstance(address, str):
        return socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    return socket.socket(socket.AF_INET, socket.SOCK_STREAM)


class GradientExchangeServer:
    """All-gather hub: waits for ``n_workers`` connections, then per round
    collects one frame from each worker and broadcasts the full set back.
    Runs in a daemon thread; ``stop()`` (or any worker disconnect after
    training) shuts it down."""

    def __init__(self, n_workers: int, address: Address = ("127.0.0.1", 0)):
        self.n_workers = n_workers
        self._sock = _make_socket(address)
        if not isinstance(address, str):
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(n_workers)
        self.address = self._sock.getsockname()
        self._conns: List[socket.socket] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.rounds = 0

    def start(self) -> "GradientExchangeServer":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self):
        try:
            while len(self._conns) < self.n_workers:
                conn, _ = self._sock.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1) \
                    if conn.family == socket.AF_INET else None
                self._conns.append(conn)
            while not self._stop.is_set():
                frames = [_recv_frame(c) for c in self._conns]
                count = struct.pack("<I", len(frames))
                for c in self._conns:
                    c.sendall(count)
                    for tokens, thr in frames:
                        _send_frame(c, tokens, thr)
                self.rounds += 1
                from ..obs import get_registry
                get_registry().counter(
                    "dl4j_gradex_rounds_total",
                    "Gradient-exchange all-gather rounds served").inc()
        except (ConnectionError, OSError):
            pass  # workers done / stop() closed the socket
        finally:
            for c in self._conns:
                try:
                    c.close()
                except OSError:
                    pass

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # unblock a serve thread parked in _recv_frame on a live worker
        for c in self._conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if isinstance(self.address, (str, bytes)):
            import os
            try:
                os.unlink(self.address)
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)


class SocketGradientTransport:
    """Worker-side connection to a GradientExchangeServer."""

    def __init__(self, address: Address, timeout: Optional[float] = None):
        """``timeout=None`` (default) blocks indefinitely in the all-gather
        — stragglers (per-worker XLA compile, checkpoint pauses) routinely
        exceed any fixed budget in the slow-interconnect regime this
        transport targets; pass a timeout only for fail-fast tests."""
        self._sock = _make_socket(address)
        self._sock.settimeout(timeout)
        self._sock.connect(tuple(address) if not isinstance(address, str)
                           else address)
        if self._sock.family == socket.AF_INET:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def exchange(self, tokens: np.ndarray,
                 threshold: float) -> List[Tuple[np.ndarray, float]]:
        """Send this worker's frame; block until every worker's frame
        arrives (the all-gather round)."""
        _send_frame(self._sock, tokens, threshold)
        (count,) = struct.unpack("<I", _recv_exact(self._sock, 4))
        return [_recv_frame(self._sock) for _ in range(count)]

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class DistributedGradientWorker:
    """Per-PROCESS gradient-sharing worker (reference: one
    EncodedGradientsAccumulator per machine on the Aeron bus).

    ``step`` takes the POST-UPDATER update (e.g. lr·grad or the Adam step)
    — the same contract as upstream, which encodes updates after the
    updater, NOT raw gradients: the threshold lives in update space, so
    the adaptive controller can track the training phase (large quanta
    early, fine quanta near convergence — this is what makes encoded-
    sparse training converge equivalently to dense; a gradient-space
    threshold cannot, because the lr rescaling hides the movement scale).
    Residual error feedback keeps the stream lossless over time.

    The returned mean update is identical on every worker, so identically-
    initialized replicas stay bit-identical — the property the 2-process
    convergence test asserts."""

    def __init__(self, n_params: int, transport: SocketGradientTransport,
                 threshold: float = 1e-3, adaptive: bool = True,
                 target_sparsity: float = 0.1):
        self.n_params = n_params
        self.transport = transport
        self.residual = np.zeros(n_params, np.float32)
        self.adaptive = AdaptiveThreshold(
            threshold, target_sparsity=target_sparsity, decay=1.5,
            max_threshold=10.0) if adaptive else None
        self.threshold = threshold
        self.last_encoded = 0

    def step(self, update: np.ndarray) -> np.ndarray:
        """Encode + exchange this worker's local update; returns the mean
        decoded update across all workers (apply as ``w -= result``)."""
        tokens = threshold_encode(
            np.asarray(update, np.float32).ravel(), self.residual,
            self.threshold)
        self.last_encoded = int(tokens.size)
        frames = self.transport.exchange(tokens, self.threshold)
        out = np.zeros(self.n_params, np.float32)
        for peer_tokens, peer_thr in frames:
            out += threshold_decode(peer_tokens, peer_thr, self.n_params)
        if self.adaptive is not None:
            self.threshold = self.adaptive.update(self.last_encoded,
                                                  self.n_params)
        return out / len(frames)

    def residual_norm(self) -> float:
        return float(np.linalg.norm(self.residual))
