"""Pipeline parallelism — GPipe-style microbatched stages over the 'pp' axis.

Reference counterpart: none in DL4J (its scaleout is data-parallel only);
required by the goal spec. TPU-native design: the transformer's stacked
block params (leading L axis) are sharded over 'pp' (L/P blocks per stage);
inside ``shard_map`` a fill-drain loop streams M microbatches through the
ring, moving activations to the next stage with ``lax.ppermute`` each tick
(neighbor hop = pure ICI). Embedding/head are replicated; stage 0 embeds,
the last stage computes the LM loss, and the scalar is psum-broadcast so
every device returns the same value. ``jax.grad`` differentiates straight
through (ppermute's transpose is the reverse permute), so the SAME fill-
drain program serves forward and backward — no hand-written schedule.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax import lax

from .._jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..zoo import transformer as tfm


def _stage_loss_fn(cfg, n_stages, other_axes=(), aux_weight=1e-2):
    """Builds the per-device pipelined loss, to run inside shard_map."""

    def fn(params, ids_mb, tgt_mb):
        # params['blocks'] leaves: (L/P, ...) local; embed/head replicated
        stage = lax.axis_index("pp")
        n_mb = ids_mb.shape[0]
        mb, t = ids_mb.shape[1], ids_mb.shape[2]
        d = cfg.d_model
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        buf = jnp.zeros((mb, t, d), cfg.dtype)
        total = jnp.zeros((), jnp.float32)
        aux_total = jnp.zeros((), jnp.float32)
        is_first = stage == 0
        is_last = stage == n_stages - 1
        for tick in range(n_mb + n_stages - 1):
            mb_idx = jnp.clip(tick, 0, n_mb - 1)
            fresh = tfm.embed(params, cfg, ids_mb[mb_idx])
            x = jnp.where(is_first & (tick < n_mb), fresh, buf)
            y, aux = tfm.apply_blocks(params["blocks"], cfg, x)
            # this stage does real work on ticks [stage, stage + n_mb)
            real_work = (tick >= stage) & (tick - stage < n_mb)
            aux_total = aux_total + jnp.where(real_work, aux.astype(jnp.float32), 0.0)
            out_idx = tick - (n_stages - 1)
            if 0 <= out_idx:
                logits = tfm.head_logits(params, cfg, y)
                tgt = tgt_mb[jnp.clip(out_idx, 0, n_mb - 1)]
                logp = jax.nn.log_softmax(logits, -1)
                nll = -jnp.take_along_axis(
                    logp, tgt[..., None].astype(jnp.int32), -1)[..., 0].mean()
                use = is_last & (out_idx < n_mb)
                total = total + jnp.where(use, nll, 0.0)
            buf = lax.ppermute(y, "pp", perm)
        # nll lives on the last stage only; MoE aux loss accrues on EVERY
        # stage (each holds L/P routed blocks) — both psum over the ring
        total = lax.psum(jnp.where(is_last, total, 0.0), "pp") / n_mb
        total = total + aux_weight * lax.psum(aux_total, "pp") / n_mb
        # average the data-parallel shards (tp copies identical; pmean no-op)
        for ax in other_axes:
            total = lax.pmean(total, ax)
        return total

    return fn


def make_pipeline_loss(mesh: Mesh, cfg: tfm.TransformerConfig):
    """Pipelined LM loss over mesh axes ('pp' required; 'dp'/'tp' optional).

    Call: loss = fn(params, ids (M, mb, T), targets (M, mb, T)).
    params['blocks'] leaves must have leading dim L divisible by pp size.
    """
    n_stages = mesh.shape["pp"]
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={n_stages}")

    # spec trees: blocks sharded over pp on axis 0; everything else replicated
    def param_specs(params):
        return {
            k: (jax.tree_util.tree_map(lambda _: P("pp"), v) if k == "blocks"
                else jax.tree_util.tree_map(lambda _: P(), v))
            for k, v in params.items()
        }

    data_spec = P(None, "dp" if "dp" in mesh.axis_names else None, None)

    other_axes = tuple(a for a in mesh.axis_names if a != "pp")

    def build(params):
        specs = param_specs(params)
        fn = shard_map(
            _stage_loss_fn(cfg, n_stages, other_axes), mesh=mesh,
            in_specs=(specs, data_spec, data_spec),
            out_specs=P(), check_vma=False)
        return fn

    def loss(params, ids_mb, tgt_mb):
        return build(params)(params, ids_mb, tgt_mb)

    return loss


def make_pipeline_train_step(mesh: Mesh, cfg: tfm.TransformerConfig, optimizer):
    """Jitted pipelined train step: (params, opt_state, ids_mb, tgt_mb) →
    (params, opt_state, loss). Params stay pp-sharded throughout."""
    loss_fn = make_pipeline_loss(mesh, cfg)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, ids_mb, tgt_mb):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids_mb, tgt_mb)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def place_params_for_pipeline(mesh: Mesh, params):
    """Device_put params with blocks sharded over 'pp' (axis 0), rest replicated."""
    def sh(k):
        def inner(leaf):
            if k == "blocks":
                return NamedSharding(mesh, P("pp"))
            return NamedSharding(mesh, P())
        return inner
    return {k: jax.tree_util.tree_map(
        lambda a, _k=k: jax.device_put(a, sh(_k)(a)), v)
        for k, v in params.items()}
