"""Parameter-averaging distributed training.

Reference parity: ``org.deeplearning4j.spark.impl.paramavg
.ParameterAveragingTrainingMaster`` (and ParallelWrapper's
``averagingFrequency`` mode): each worker trains locally for
`averaging_frequency` steps on its own shard of the data stream, then
parameters (and optionally updater state) are averaged across workers.

TPU-first redesign: instead of shipping parameters through a Spark driver,
the whole averaging round is ONE XLA program — `shard_map` over the mesh's
'dp' axis gives every device its own parameter/optimizer replica (stacked
leading device axis), `lax.scan` runs the local steps on-device, and a
`psum`-mean over ICI replaces the driver aggregation. Host code only feeds
batches.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .._jax_compat import shard_map

from .mesh import data_parallel_mesh


class ParameterAveragingTrainer:
    """Train `net` with periodic parameter averaging over the dp mesh axis.

    averaging_frequency=1 with plain SGD is numerically identical to
    synchronous gradient averaging (averaging linear steps == stepping on
    the averaged gradient); larger frequencies trade sync cost for
    staleness exactly like the reference's Spark mode.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 averaging_frequency: int = 5,
                 average_updater_state: bool = True):
        if not net.initialized:
            raise ValueError("initialize the network first (net.init(...))")
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.net = net
        self.mesh = mesh or data_parallel_mesh()
        if "dp" not in self.mesh.axis_names:
            raise ValueError("mesh needs a 'dp' axis")
        self.freq = int(averaging_frequency)
        self.average_updater_state = average_updater_state
        self.n = int(np.prod([s for a, s in zip(self.mesh.axis_names,
                                                self.mesh.devices.shape)
                              if a == "dp"]))
        self._round = None

    # ------------------------------------------------------------------ build
    def _build(self):
        net = self.net
        if net._optimizer is None:
            net._build_optimizer(1)
        optimizer = net._optimizer
        freq, n = self.freq, self.n

        def local_round(params, opt_state, states, xs, ys, rngs, fms, lms):
            """Runs on ONE device's replica. shard_map blocks keep the
            sharded leading axis at local size 1 — strip it, run `freq`
            sequential local steps over the (freq, b, ...) microbatches,
            psum-average, and re-add the axis for the stacked output."""
            unblk = partial(jax.tree_util.tree_map, lambda a: a[0])
            params, opt_state, states = (unblk(params), unblk(opt_state),
                                         unblk(states))
            xs, ys, rngs = xs[0], ys[0], rngs[0]
            fms = None if fms is None else fms[0]
            lms = None if lms is None else lms[0]

            def one(carry, inp):
                p, o, s = carry
                x, y, rng, fm, lm = inp
                (loss, s2), grads = jax.value_and_grad(
                    net._loss, has_aux=True)(p, s, x, y, rng, fm, lm)
                updates, o2 = optimizer.update(grads, o, p)
                p2 = optax.apply_updates(p, updates)
                p2 = net._apply_constraints(p2)
                return (p2, o2, s2), loss

            (params, opt_state, states), losses = lax.scan(
                one, (params, opt_state, states), (xs, ys, rngs, fms, lms))
            # driver aggregation -> psum over ICI
            params = jax.tree_util.tree_map(
                lambda a: lax.psum(a, "dp") / n, params)
            avg_if_float = lambda a: (lax.psum(a, "dp") / n  # noqa: E731
                                      if jnp.issubdtype(jnp.asarray(a).dtype,
                                                        jnp.floating) else a)
            if self.average_updater_state:
                opt_state = jax.tree_util.tree_map(avg_if_float, opt_state)
            states = jax.tree_util.tree_map(avg_if_float, states)
            loss = lax.pmean(jnp.mean(losses), "dp")
            reblk = partial(jax.tree_util.tree_map, lambda a: a[None])
            return reblk(params), reblk(opt_state), reblk(states), loss

        # every leaf is stacked over a leading device axis; batches are
        # (n*freq*b, ...) reshaped to (n, freq, b, ...) and split over dp
        def round_fn(stacked_params, stacked_opt, stacked_states, xs, ys,
                     rngs, fms, lms):
            sm = shard_map(
                local_round, mesh=self.mesh,
                in_specs=(P("dp"),) * 8,
                out_specs=(P("dp"), P("dp"), P("dp"), P()),
                check_vma=False)
            return sm(stacked_params, stacked_opt, stacked_states, xs, ys,
                      rngs, fms, lms)

        self._round = jax.jit(round_fn, donate_argnums=(0, 1, 2))
        return self._round

    # ------------------------------------------------------------------- fit
    def _stack(self, tree):
        """Replicate each leaf to a stacked (n, ...) array sharded over dp —
        device_put with the stacked sharding places one replica per device
        (broadcasting on the default device would transiently hold n full
        replicas of params + optimizer state on one chip)."""
        sh = NamedSharding(self.mesh, P("dp"))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(
                np.broadcast_to(np.asarray(a)[None],
                                (self.n,) + tuple(np.shape(a))), sh), tree)

    def _unstack(self, tree):
        return jax.tree_util.tree_map(lambda a: a[0], tree)

    def fit(self, iterator, *, epochs: int = 1):
        """Feeds rounds of n_workers * averaging_frequency microbatches.
        A tail of fewer microbatches than a full round is trained with
        plain synchronous steps via one `net.fit` call on the averaged
        params (exact, no staleness; epoch_count advances once per epoch
        either way)."""
        net = self.net
        # peek without consuming (lists/tuples only; generator iterators hit
        # the same loud guard in _run_round on the first full round)
        probe = iterator[0] if isinstance(iterator, (list, tuple)) \
            and len(iterator) else None
        if probe is not None and isinstance(probe.features, (list, tuple)):
            raise NotImplementedError(
                "ParameterAveragingTrainer stacks single-arm DataSet "
                "batches; for MultiDataSet (multi-input/multi-output) "
                "training use ParallelWrapper instead")
        round_fn = self._round or self._build()
        if net._optimizer is None:
            net._build_optimizer(1)
        sp = self._stack(net.params)
        so = self._stack(net._opt_state)
        ss = self._stack(net.states)
        last = None
        need = self.n * self.freq
        for _ in range(epochs):
            buf = []
            tail_handled = False
            for ds in iterator:
                if isinstance(ds.features, (list, tuple)):
                    # generators bypass the list peek above; guard every
                    # batch so the tail path never feeds MultiDataSets
                    # into DataSet.merge
                    raise NotImplementedError(
                        "ParameterAveragingTrainer stacks single-arm "
                        "DataSet batches; for MultiDataSet use "
                        "ParallelWrapper instead")
                buf.append(ds)
                if len(buf) == need:
                    sp, so, ss, last = self._run_round(round_fn, sp, so, ss,
                                                       buf)
                    buf = []
            if buf:
                # flush the remainder synchronously on the averaged params,
                # one step PER microbatch (batch_size keeps the source
                # granularity); ONE net.fit call = one epoch_count bump +
                # one on_epoch_end
                from ..data.iterators import ListDataSetIterator
                net.params = self._unstack(sp)
                net._opt_state = self._unstack(so)
                net.states = self._unstack(ss)
                last_f = net.fit(ListDataSetIterator(
                    buf, batch_size=buf[0].num_examples()))
                last = jnp.asarray(last_f if last_f is not None else 0.0)
                tail_handled = True
                sp, so, ss = (self._stack(net.params),
                              self._stack(net._opt_state),
                              self._stack(net.states))
            if hasattr(iterator, "reset"):
                iterator.reset()
            if not tail_handled:
                net.epoch_count += 1
                for listener in net.listeners:
                    if hasattr(listener, "on_epoch_end"):
                        listener.on_epoch_end(net)
        net.params = self._unstack(sp)
        net._opt_state = self._unstack(so)
        net.states = self._unstack(ss)
        net._invalidate()
        return None if last is None else float(last)

    @staticmethod
    def _stack_masks(masks, shaped_like):
        """None-mix handling: all None -> None; else missing masks become
        all-ones of the present mask's per-example shape."""
        if all(m is None for m in masks):
            return None
        proto = next(m for m in masks if m is not None)
        filled = [np.ones_like(proto) if m is None else np.asarray(m)
                  for m in masks]
        return np.stack(filled).reshape(
            shaped_like + filled[0].shape[1:])

    def _run_round(self, round_fn, sp, so, ss, buf):
        net = self.net
        if isinstance(buf[0].features, (list, tuple)):
            raise NotImplementedError(
                "ParameterAveragingTrainer stacks single-arm DataSet "
                "batches; for MultiDataSet (multi-input/multi-output) "
                "training use ParallelWrapper instead")
        buf_x = [np.asarray(ds.features) for ds in buf]
        buf_y = [np.asarray(ds.labels) for ds in buf]
        b = buf_x[0].shape[0]
        if any(x.shape[0] != b for x in buf_x):
            raise ValueError("all microbatches in a round must share a "
                             "batch size (got mixed sizes)")
        lead = (self.n, self.freq, b)
        xs = np.stack(buf_x).reshape(lead + buf_x[0].shape[1:])
        ys = np.stack(buf_y).reshape(lead + buf_y[0].shape[1:])
        fms = self._stack_masks([ds.features_mask for ds in buf], lead)
        lms = self._stack_masks([ds.labels_mask for ds in buf], lead)
        net._host_key, sub = jax.random.split(net._host_key)
        rngs = jax.random.split(sub, self.n * self.freq).reshape(
            self.n, self.freq, 2)
        sp, so, ss, loss = round_fn(
            sp, so, ss, jnp.asarray(xs), jnp.asarray(ys), rngs,
            None if fms is None else jnp.asarray(fms),
            None if lms is None else jnp.asarray(lms))
        net._step_count += self.n * self.freq
        if net.listeners:
            # listeners read model state (checkpoint/eval): expose the
            # just-averaged replica, not the pre-fit params (a[0] makes a
            # fresh buffer, safe across the next round's donation)
            net.params = self._unstack(sp)
            net._opt_state = self._unstack(so)
            net.states = self._unstack(ss)
            lv = float(loss)
            for listener in net.listeners:
                listener.iteration_done(net, net._step_count,
                                        net.epoch_count, lv)
        return sp, so, ss, loss
