"""Ring attention — sequence/context parallelism for long sequences.

Reference counterpart: DL4J has no long-context story; this is the TPU-native
capability the goal spec demands (sequence parallel over the 'sp' mesh axis).

Design (Liu et al. ring attention, blockwise online softmax): queries stay
resident per device; key/value blocks rotate around the 'sp' ring via
``lax.ppermute`` (ICI neighbor exchange), each hop overlapping the local
blockwise attention. Accumulation uses the numerically-stable online-softmax
(running max + running denominator), so the result is EXACT — identical to
full attention, with O(T/n) memory per device.

`ring_attention_inner` is mesh-aware: inside shard_map/jit over a mesh with
'sp', it runs the ring; with no 'sp' axis in scope it falls back to plain
fused attention (so the same model code runs on 1 chip).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def _blockwise_attn(q, k, v, causal_bias):
    """Single block attention returning (num, denom, rowmax) for online merge.

    q (B,Tq,H,D), k/v (B,Tk,H,D); bias (Tq,Tk) additive (0/-inf) or None.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal_bias is not None:
        s = s + causal_bias[None, None, :, :]
    m = jnp.max(s, axis=-1, keepdims=True)                     # (B,H,Tq,1)
    m = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)                  # (B,H,Tq,1)
    num = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)   # (B,Tq,H,D)
    return num.astype(jnp.float32), denom, m


def _merge(acc, new):
    """Merge two online-softmax partials."""
    num_a, den_a, m_a = acc
    num_n, den_n, m_n = new
    m = jnp.maximum(m_a, m_n)
    ca = jnp.exp(m_a - m)
    cn = jnp.exp(m_n - m)
    num = num_a * ca.squeeze(-1).transpose(0, 2, 1)[..., None] \
        + num_n * cn.squeeze(-1).transpose(0, 2, 1)[..., None]
    den = den_a * ca + den_n * cn
    return num, den, m


def ring_attention_sharded(q, k, v, axis_name: str = "sp", causal: bool = True):
    """Runs INSIDE shard_map: q/k/v are the local sequence shard
    (B, T_local, H, D). Exact causal attention across the full sequence."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    t_local = q.shape[1]

    def local_bias(q_block_idx, k_block_idx):
        # causal mask between local q block (global rows) and rotating k block
        if not causal:
            return None
        q_pos = q_block_idx * t_local + jnp.arange(t_local)
        k_pos = k_block_idx * t_local + jnp.arange(t_local)
        mask = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(mask, 0.0, -1e30).astype(jnp.float32)

    # initial block: own k/v
    acc = _blockwise_attn(q, k, v, local_bias(idx, idx))
    kv = (k, v)
    perm = [(i, (i + 1) % n) for i in range(n)]
    for hop in range(1, n):
        kv = jax.tree_util.tree_map(lambda x: lax.ppermute(x, axis_name, perm), kv)
        src = (idx - hop) % n   # whose k/v we now hold
        new = _blockwise_attn(q, kv[0], kv[1], local_bias(idx, src))
        acc = _merge(acc, new)
    num, den, _ = acc
    den_t = den.squeeze(-1).transpose(0, 2, 1)[..., None]       # (B,Tq,H,1)
    return (num / jnp.maximum(den_t, 1e-30)).astype(q.dtype)


def ring_attention_inner(q, k, v, causal: bool = True, axis_name: str = "sp"):
    """Mesh-aware dispatch: ring when 'sp' is an in-scope mapped axis."""
    try:
        lax.axis_index(axis_name)  # raises NameError outside shard_map('sp')
        in_ring = True
    except NameError:
        in_ring = False
    if in_ring:
        return ring_attention_sharded(q, k, v, axis_name, causal)
    return jax.nn.dot_product_attention(q, k, v, is_causal=causal)


def ring_attention(mesh: Mesh, q, k, v, causal: bool = True):
    """Host-callable wrapper: shard q/k/v over ('dp', 'sp') and run the ring.

    q/k/v: (B, T, H, D) global arrays. Returns global (B, T, H, D).
    """
    spec = P("dp" if "dp" in mesh.axis_names else None, "sp", None, None)
    fn = shard_map(
        partial(ring_attention_sharded, axis_name="sp", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)
