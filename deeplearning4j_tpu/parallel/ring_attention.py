"""Ring attention — sequence/context parallelism for long sequences.

Reference counterpart: DL4J has no long-context story; this is the TPU-native
capability the goal spec demands (sequence parallel over the 'sp' mesh axis).

Design (Liu et al. ring attention, blockwise online softmax): queries stay
resident per device; key/value blocks rotate around the 'sp' ring via
``lax.ppermute`` (ICI neighbor exchange), each hop overlapping the local
blockwise attention. Partials are merged in (out, lse) form — numerically
stable log-sum-exp weighting — so the result is EXACT: identical to full
attention, with O(T/n) memory per device.

r4 rework:
- No bias tensors: the only hop that needs masking is the diagonal one
  (own k/v), and there the q/k blocks are ALIGNED, so plain causal
  attention applies. Earlier hops are unmasked; later hops are fully
  masked and are SKIPPED via ``lax.cond`` (an all-zero partial), halving
  the causal ring's compute instead of exp(-1e30)-ing it away.
- The local block attention can run the pallas flash kernel
  (``use_flash="auto"``): ``flash_attention_lse`` streams the block
  through VMEM and returns the lse the merge needs, custom-VJP included,
  so the per-shard score matrix never hits HBM — the composition the
  long-context regime exists for.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from .._jax_compat import shard_map


def _xla_attn_lse(q, k, v, causal):
    """(B,T,H,D) attention returning (out f32, lse (B,H,T) f32)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    m = jnp.maximum(jnp.max(s, axis=-1), -1e30)                 # (B,H,Tq)
    p = jnp.exp(s - m[..., None])
    den = jnp.sum(p, axis=-1)                                   # (B,H,Tq)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    den_safe = jnp.maximum(den, 1e-30)
    out = out.astype(jnp.float32) / den_safe.transpose(0, 2, 1)[..., None]
    return out, m + jnp.log(den_safe)


def _flash_attn_lse(q, k, v, causal, interpret):
    """Flash-kernel local attention in ring layout (B,T,H,D)."""
    from ..kernels.flash_attention import _tuned_blocks, flash_attention_lse
    b, t, h, d = q.shape
    bq, bk = _tuned_blocks(b, h, t, d, q.dtype, causal, interpret)
    out, lse = flash_attention_lse(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), None, causal, bq, bk, interpret)
    return out.transpose(0, 2, 1, 3).astype(jnp.float32), lse


def _merge(acc, new):
    """Merge two (out, lse) online-softmax partials."""
    out_a, lse_a = acc
    out_n, lse_n = new
    lse = jnp.logaddexp(lse_a, lse_n)                            # (B,H,Tq)
    ca = jnp.exp(lse_a - lse).transpose(0, 2, 1)[..., None]      # (B,Tq,H,1)
    cn = jnp.exp(lse_n - lse).transpose(0, 2, 1)[..., None]
    return out_a * ca + out_n * cn, lse


def _use_flash(use_flash, t_local):
    from ..kernels._common import pltpu
    if pltpu is None:     # CPU-only pallas wheel: no kernel to run
        return False
    if use_flash == "auto":
        return jax.default_backend() == "tpu" and t_local >= 1024
    return bool(use_flash)


def ring_attention_sharded(q, k, v, axis_name: str = "sp",
                           causal: bool = True, use_flash="auto",
                           interpret=None):
    """Runs INSIDE shard_map: q/k/v are the local sequence shard
    (B, T_local, H, D). Exact causal attention across the full sequence."""
    from .._jax_compat import axis_size
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    flash = _use_flash(use_flash, t_local)

    def attn(q_, k_, v_, causal_):
        if flash:
            return _flash_attn_lse(q_, k_, v_, causal_, interpret)
        return _xla_attn_lse(q_, k_, v_, causal_)

    # hop 0: own k/v — the diagonal block is ALIGNED, plain causal applies
    acc = attn(q, k, v, causal)
    kv = (k, v)
    perm = [(i, (i + 1) % n) for i in range(n)]
    zero = (jnp.zeros_like(acc[0]),
            jnp.full_like(acc[1], -jnp.inf))
    for hop in range(1, n):
        kv = jax.tree_util.tree_map(lambda x: lax.ppermute(x, axis_name, perm), kv)
        src = (idx - hop) % n   # whose k/v we now hold
        if causal:
            # src < idx: full (unmasked) block; src > idx: entirely above
            # the diagonal — skip the matmuls, contribute a zero partial
            new = lax.cond(src < idx,
                           lambda ops: attn(q, ops[0], ops[1], False),
                           lambda ops: zero, kv)
        else:
            new = attn(q, kv[0], kv[1], False)
        acc = _merge(acc, new)
    out, _ = acc
    return out.astype(q.dtype)


def ring_attention_inner(q, k, v, causal: bool = True, axis_name: str = "sp",
                         use_flash="auto", interpret=None):
    """Mesh-aware dispatch: ring when 'sp' is an in-scope mapped axis."""
    try:
        lax.axis_index(axis_name)  # raises NameError outside shard_map('sp')
        in_ring = True
    except NameError:
        in_ring = False
    if in_ring:
        return ring_attention_sharded(q, k, v, axis_name, causal, use_flash,
                                      interpret)
    return jax.nn.dot_product_attention(q, k, v, is_causal=causal)


def ring_attention(mesh: Mesh, q, k, v, causal: bool = True,
                   use_flash="auto", interpret=None):
    """Host-callable wrapper: shard q/k/v over ('dp', 'sp') and run the ring.

    q/k/v: (B, T, H, D) global arrays. Returns global (B, T, H, D).
    ``use_flash``: True / False / "auto" — run the pallas flash kernel for
    the per-shard local attention (auto: on TPU when the local shard is
    long enough to engage it).
    """
    spec = P("dp" if "dp" in mesh.axis_names else None, "sp", None, None)
    fn = shard_map(
        partial(ring_attention_sharded, axis_name="sp", causal=causal,
                use_flash=use_flash, interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)
