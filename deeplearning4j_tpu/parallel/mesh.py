"""Device mesh abstraction — the substrate for all parallelism.

Reference counterpart: DL4J has no mesh concept; its parallelism is
``ParallelWrapper`` (replicate + average) and gradient sharing over
Aeron UDP. The TPU-native redesign centralizes on ``jax.sharding.Mesh``
with named axes:

  dp    — data parallel (batch split; gradient psum rides ICI)
  fsdp  — fully-sharded data parallel (params/opt-state sharded too)
  tp    — tensor parallel (Megatron column/row within a layer)
  pp    — pipeline parallel (stage-partitioned layers, microbatched)
  sp    — sequence/context parallel (ring attention over long sequences)
  ep    — expert parallel (MoE expert sharding + all_to_all dispatch)

`MeshSpec` builds a mesh from {axis: size} on any device set (real pod or
the virtual 8-CPU test mesh), validating that the product matches the
device count. Multi-host: `bootstrap_distributed()` wires jax.distributed
so the same mesh spans hosts (DCN between hosts, ICI within).
"""

from __future__ import annotations

import math
import os
import warnings
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "pp", "tp", "sp", "ep")


@dataclass
class MeshSpec:
    """{axis_name: size}; axes of size 1 are kept (harmless, simplifies specs)."""

    axes: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        for a in self.axes:
            if a not in AXES:
                raise ValueError(f"unknown mesh axis '{a}'; known: {AXES}")

    @property
    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        if self.size != len(devices):
            raise ValueError(
                f"mesh spec {self.axes} needs {self.size} devices, got {len(devices)}")
        names = tuple(self.axes.keys())
        shape = tuple(self.axes.values())
        arr = np.asarray(devices).reshape(shape)
        return Mesh(arr, names)


def make_mesh(devices=None, **axes) -> Mesh:
    """make_mesh(dp=2, tp=4) → Mesh over the available devices."""
    return MeshSpec(axes).build(devices)


def data_parallel_mesh(devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return make_mesh(devices, dp=len(devices))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *axes_present: str) -> NamedSharding:
    """Shard the leading (batch) dim over dp (and fsdp if present)."""
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names and
                       (not axes_present or a in axes_present))
    return NamedSharding(mesh, P(batch_axes if batch_axes else None))


def shard_params_fsdp(mesh: Mesh, params, min_size: int = 2 ** 14):
    """ZeRO-3 layout: shard each large leaf's LAST axis over 'fsdp' when it
    divides evenly; small leaves stay replicated. Returns matching shardings
    pytree. (Last axis: keeps row-major contiguity for the all-gather.)"""
    if "fsdp" not in mesh.axis_names:
        raise ValueError("mesh has no fsdp axis")
    n = mesh.shape["fsdp"]

    def spec(leaf):
        if leaf.ndim == 0 or leaf.size < min_size:
            return NamedSharding(mesh, P())
        for ax in range(leaf.ndim - 1, -1, -1):
            if leaf.shape[ax] % n == 0:
                parts = [None] * leaf.ndim
                parts[ax] = "fsdp"
                return NamedSharding(mesh, P(*parts))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(spec, params)


def bootstrap_distributed(coordinator: Optional[str] = None,
                          num_processes: Optional[int] = None,
                          process_id: Optional[int] = None) -> None:
    """Multi-host init (reference: the Aeron/Spark cluster bootstrap).

    On TPU pods the args come from the environment; elsewhere pass them
    explicitly. Safe to call when already initialized.

    A failed init RAISES when the caller clearly asked for multi-host
    (explicit args, or cluster env vars present): silently falling back to
    single-process training is exactly the kind of quiet misconfiguration
    the reference's cluster bootstrap rejects. Only a bare, argument-less
    call in a single-process dev environment downgrades to a warning.
    """
    if jax.process_count() > 1:
        return
    kw = {}
    if coordinator:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    multi_host_requested = bool(kw) or any(
        os.environ.get(v) for v in
        ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
         "MEGASCALE_COORDINATOR_ADDRESS"))
    try:
        jax.distributed.initialize(**kw)
    except (RuntimeError, ValueError) as e:
        if multi_host_requested:
            raise RuntimeError(
                f"multi-host bootstrap failed (coordinator={coordinator!r}, "
                f"num_processes={num_processes!r}, process_id={process_id!r})"
                " — refusing to fall back to single-process training"
            ) from e
        warnings.warn(f"jax.distributed.initialize unavailable ({e}); "
                      "continuing single-process", RuntimeWarning,
                      stacklevel=2)


def hybrid_mesh_2d(ici_axes: Dict[str, int], dcn_axes: Dict[str, int]) -> Mesh:
    """DCN×ICI layout: outer axes over hosts (DCN), inner over chips (ICI) —
    mirrors mesh_utils.create_hybrid_device_mesh for explicit control."""
    from jax.experimental import mesh_utils
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    dcn_shape = tuple(dcn_axes.values())
    ici_shape = tuple(ici_axes.values())
    devs = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=jax.devices())
    return Mesh(devs, names)
