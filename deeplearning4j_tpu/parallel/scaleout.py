"""Cluster-level training orchestration — the Spark-scaleout analogue,
now elastic and fault-tolerant (ROADMAP item 4).

Reference parity: ``deeplearning4j-scaleout/spark``'s
``SparkDl4jMultiLayer`` / ``SparkComputationGraph`` +
``ParameterAveragingTrainingMaster``, including the executor
re-provisioning contract: a JOB driver that provisions workers, leases
the data partitions out, runs averaging-frequency-paced
parameter-averaging rounds over a master hub, survives worker AND
master failure mid-job, and checkpoints the averaged model (atomically)
between rounds for resume.

TPU-native positioning: WITHIN one pod, ``ParallelWrapper`` /
``ParameterAveragingTrainer`` compile the whole round as one XLA program
over ICI — always use those. This driver is the layer ABOVE: separate
worker processes/hosts with no shared runtime (the regime Spark
executors occupy), coordinated over TCP/Unix sockets.

The elasticity contract (see docs/ARCHITECTURE.md for the full failure
matrix):

- **Worker rejoin.** The hub's accept thread stays alive for the whole
  job (not just the first ``n_workers`` connections). A HELLO carrying
  a known-or-new worker id mid-job is answered with the master's span
  context AND a REJOIN ack (current round + current mean params), so a
  restarted worker enters the next averaging round from the job's live
  state. ``dl4j_scaleout_rejoins_total`` counts re-attachments.
- **Master restart.** ``SparkDl4jMultiLayer.fit`` resumes from
  ``checkpoint_dir`` when an interrupted job's stamp is present
  (``latest.zip`` + ``round.txt`` + ``leases.json``; a completed job
  deletes the lease stamp): params reload, round numbering continues,
  and only unfinished lease items re-run. ``WorkerClient`` retries
  connect/recv with bounded exponential backoff, so workers survive the
  hub's death and re-attach to the restarted hub instead of hanging
  forever. ``dl4j_scaleout_master_restarts_total`` counts resumes.
- **Partition leasing.** Data is no longer statically partitioned at
  spawn: the hub holds a ``LeaseTable`` of ``(epoch, shard)`` work
  items and workers lease them one at a time (affinity reproduces the
  old round-robin split while everyone is alive). A dead worker's
  unfinished leases return to the pool and are re-granted to a survivor
  or rejoiner (``dl4j_scaleout_leases_reassigned_total``) — job output
  covers every partition regardless of the failure schedule.
- **Concurrent gather.** Each worker connection gets its own hub-side
  handler thread; a round closes as soon as every live worker's frame
  has landed, or at a deadline (``worker_timeout`` after the first
  frame) — one hung straggler times out alone instead of stalling the
  healthy workers' recv loop head-of-line.

Wire protocol: kind-tagged frames, one per message — layouts live in
``parallel/transport.py`` (``KIND_PARAMS/DONE/HELLO/SPANCTX`` plus the
elastic ``KIND_REJOIN/LEASE_REQ/LEASE/LEASE_DONE``).

Telemetry (deeplearning4j_tpu.obs): rounds / drops / rejoins /
reassignments / restarts under ``dl4j_scaleout_*``, and span context
propagates master -> worker over the wire so a master round and its
worker fits stitch into ONE trace tree (round ids derived
deterministically via ``derived_span_id(trace, "round", k)``).
"""

from __future__ import annotations

import contextlib
import os
import socket
import struct
import threading
import time
import uuid
import warnings
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..obs import SpanContext, derived_span_id, get_registry, get_tracer
from ..obs.spans import Span
from .leases import GRANT_NONE, GRANT_OK, GRANT_RETRY, LeaseTable
from .transport import (Address, KIND_DONE, KIND_HELLO, KIND_LEASE,
                        KIND_LEASE_DONE, KIND_LEASE_REQ, KIND_PARAMS,
                        KIND_REJOIN, KIND_SPANCTX, _make_socket,
                        backoff_delays, pack_span_context, recv_frame,
                        send_frame, unpack_span_context)

_SOCK_ERRORS = (ConnectionError, socket.timeout, OSError)


def _drift_source(address) -> str:
    """Drift-audit source key for one scaleout job: scoped by the hub
    address so two jobs in one process (tests run dozens) can't collide
    on round indexes, while BOTH wire ends derive the same key. TCP
    keys by PORT only — the hub sees its bound form (('0.0.0.0', p))
    and a worker its dial form (('localhost', p)); the host string
    differs, the port never does, and two in-process hubs can't share
    a port. AF_UNIX keys by path (identical on both ends). A master
    RESTART on the same address keeps the source, so resumed rounds
    land in the same audit series."""
    if isinstance(address, str):
        return f"scaleout:{address}"
    return "scaleout:port:%s" % tuple(address)[1]


class MasterDiedError(RuntimeError):
    """The master hub died mid-job (fault injection or crash); the job
    is resumable from ``checkpoint_dir``."""


class TrainingMaster:
    """Configuration interface (reference ``TrainingMaster``)."""

    def __init__(self, *, batch_size_per_worker: int = 32,
                 averaging_frequency: int = 5, n_workers: int = 2,
                 epochs_per_fit: int = 1,
                 worker_timeout: float = 120.0,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every_rounds: int = 1,
                 worker_retries: int = 3,
                 worker_backoff: float = 0.5):
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = averaging_frequency
        self.n_workers = n_workers
        self.epochs_per_fit = epochs_per_fit
        self.worker_timeout = worker_timeout
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_rounds = max(1, checkpoint_every_rounds)
        # bounded reconnect-with-backoff budget handed to every worker's
        # WorkerClient — how long a worker survives a master outage
        self.worker_retries = max(0, worker_retries)
        self.worker_backoff = worker_backoff


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Reference ``ParameterAveragingTrainingMaster``: sync param
    averaging every ``averaging_frequency`` worker iterations."""


def atomic_write_text(path, text: str):
    """Write ``text`` to ``path`` via a temp file + ``os.replace`` so a
    crash mid-write can never leave a torn artifact (the between-round
    checkpoint the master-restart path depends on)."""
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


def read_resume_state(ckdir) -> Optional[Tuple[int, str]]:
    """``(round, lease-snapshot-json)`` from a between-round checkpoint,
    or None when there is no interrupted job to resume: a COMPLETED job
    deletes ``leases.json``, and a missing/corrupt stamp means fresh.
    Because ``round.txt`` is written LAST (after ``latest.zip`` and
    ``leases.json``), its presence implies the others are whole."""
    ckdir = Path(ckdir)
    rt, lj = ckdir / "round.txt", ckdir / "leases.json"
    if not (rt.exists() and lj.exists()):
        return None
    try:
        return int(rt.read_text().strip()), lj.read_text()
    except (ValueError, OSError):
        return None


# ---------------------------------------------------------------------------
# Master-side hub
# ---------------------------------------------------------------------------

class ParamAveragingHub:
    """Master-side hub for parameter-averaging rounds with elasticity.

    One accept thread (alive for the whole job — rejoiners welcome) plus
    one handler thread per worker connection. A round gathers
    concurrently: it closes when every live worker's params frame has
    landed, or ``worker_timeout`` after the first frame (stragglers time
    out alone). ``result()`` waits for the job to drain and returns the
    final averaged flat params (None if no round ever completed).
    """

    def __init__(self, n_workers: int, address: Address = ("127.0.0.1", 0),
                 worker_timeout: float = 120.0,
                 on_round: Optional[Callable[[np.ndarray, int], None]] = None,
                 span_ctx=None, lease_table: Optional[LeaseTable] = None,
                 start_round: int = 0,
                 initial_params: Optional[np.ndarray] = None,
                 fail_after_rounds: Optional[int] = None):
        self.n_workers = n_workers
        self.worker_timeout = worker_timeout
        self.on_round = on_round
        self.span_ctx = span_ctx  # master trace context, sent to workers
        self._table = lease_table
        self.start_round = int(start_round)
        self.rounds = int(start_round)      # absolute round counter
        self.fail_after_rounds = fail_after_rounds
        self.fail_injected = False
        self._initial_params = None if initial_params is None else \
            np.asarray(initial_params, np.float32)
        self._sock = _make_socket(address)
        if not isinstance(address, str):
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        else:
            # AF_UNIX restart-same-path: clear a stale socket file here,
            # and NEVER unlink on stop — a dying hub must not tear down
            # the path its restarted successor may have already bound
            with contextlib.suppress(OSError):
                os.unlink(address)
        self._sock.bind(address)
        self._sock.listen(max(n_workers, 8))
        self.address = self._sock.getsockname()
        # drift audit (ISSUE 13): a FRESH job (round counter starting
        # at 0) on a reused address must not be compared against the
        # previous job's checksums for the same round indexes — clear
        # the source. A RESUMED hub (start_round > 0) keeps them: its
        # rounds continue the same series. Decoration only.
        if start_round == 0:
            try:
                from ..obs import numerics as obs_numerics
                obs_numerics.get_auditor().reset_source(
                    _drift_source(self.address))
            except Exception:  # noqa: BLE001 — audit is decoration
                pass
        self.dropped: List[int] = []
        self.rejoins = 0
        self._final: Optional[np.ndarray] = None
        self._last_mean: Optional[np.ndarray] = None
        # --- round barrier state (all guarded by _cv) ---
        self._cv = threading.Condition()
        self._live: Dict[int, socket.socket] = {}
        self._ever: Set[int] = set()
        self._frames: Dict[int, np.ndarray] = {}
        self._means: Dict[int, Tuple[np.ndarray, int]] = {}  # wid ->
        # (round mean, hub round index) — the index rides the PARAMS
        # reply so workers key their drift audit by the hub's counter
        self._deadline: Optional[float] = None
        self._round_t0: Optional[Tuple[float, float]] = None
        self._after_q: List[tuple] = []
        self._draining = False
        self._stopped = False
        self._provisioned = False        # first n_workers all said HELLO
        self._t0 = time.monotonic()
        self._reassigned_seen = 0
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ParamAveragingHub":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dl4j-tpu-param-hub")
        self._accept_thread.start()
        return self

    @property
    def stopped(self) -> bool:
        return self._stopped

    def result(self, timeout: Optional[float] = None) -> Optional[np.ndarray]:
        """Wait for the job to drain (every registered worker done or
        dropped) and return the final averaged params; shuts the hub
        down on the way out."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._stopped or (self._ever and not self._live),
                timeout)
        self.stop()
        return self._final

    def stop(self, join: bool = True):
        with self._cv:
            already = self._stopped
            self._stopped = True
            conns = list(self._live.values())
            self._live.clear()
            self._cv.notify_all()
        if already and not join:
            return
        get_registry().gauge("dl4j_scaleout_live_workers",
                             "Workers currently in the averaging round").set(0)
        try:
            self._sock.close()
        except OSError:
            pass
        for c in conns:
            with contextlib.suppress(OSError):
                c.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                c.close()
        if join:
            cur = threading.current_thread()
            with self._cv:
                threads = list(self._threads)
                if self._accept_thread is not None:
                    threads.append(self._accept_thread)
            for t in threads:
                if t is not cur and t.is_alive():
                    t.join(timeout=5)

    def wait_dropped(self, wid: int, timeout: Optional[float] = None) -> bool:
        """Block until ``wid`` is no longer live (the hub has processed
        its death) — lets a supervisor respawn the worker under the SAME
        id so the fresh HELLO reads as a rejoin, not a live duplicate."""
        with self._cv:
            return self._cv.wait_for(
                lambda: wid not in self._live or self._stopped, timeout)

    # ------------------------------------------------------------ accept
    def _accept_loop(self):
        # short accept timeout: close() from another thread does NOT
        # interrupt a blocked accept() on Linux, so poll the stop flag
        self._sock.settimeout(0.25)
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return                      # stop() closed the socket
            try:
                conn.settimeout(min(self.worker_timeout, 10.0))
                kind, payload = recv_frame(conn)
                wid = struct.unpack("<I", payload)[0] \
                    if kind == KIND_HELLO and len(payload) == 4 \
                    else len(self._ever)
                conn.settimeout(self.worker_timeout)
            except (*_SOCK_ERRORS, struct.error):
                with contextlib.suppress(OSError):
                    conn.close()
                continue
            wid = self._register(wid, conn)
            try:
                # reply with the master's trace context (empty = off) and
                # the REJOIN ack: current round + current mean (empty
                # params = no round yet) — the (re)joiner starts from the
                # job's live state
                send_frame(conn, KIND_SPANCTX,
                           pack_span_context(self.span_ctx))
                with self._cv:
                    rnd = self.rounds
                    mean = self._last_mean if self._last_mean is not None \
                        else self._initial_params
                # the ack echoes the REGISTERED wid: a live-duplicate
                # dialer was uniquified by _register, and its drift
                # audit (ISSUE 13) must label by the hub-side identity
                # or two workers would overwrite one replica's checksums
                ack = struct.pack("<II", rnd, wid) + \
                    (mean.astype(np.float32).tobytes()
                     if mean is not None else b"")
                send_frame(conn, KIND_REJOIN, ack)
            except _SOCK_ERRORS:
                # ALREADY registered: route through _leave so the wid is
                # not leaked in _live (which would hold its lease slot
                # hostage and stall every round to the deadline)
                self._leave(wid, conn, done=False)
                with contextlib.suppress(OSError):
                    conn.close()
                continue
            t = threading.Thread(target=self._handle, args=(wid, conn),
                                 daemon=True, name=f"dl4j-tpu-hub-w{wid}")
            with self._cv:
                self._threads.append(t)
            t.start()

    def _register(self, wid: int, conn: socket.socket) -> int:
        reg = get_registry()
        with self._cv:
            if wid in self._live:        # live duplicate id — uniquify
                step = max(1, self.n_workers)
                while wid in self._live or wid in self._ever:
                    wid += step
            rejoin = wid in self._ever
            self._live[wid] = conn
            self._ever.add(wid)
            if len(self._ever) >= self.n_workers:
                self._provisioned = True
            if rejoin:
                self.rejoins += 1
                reg.counter("dl4j_scaleout_rejoins_total",
                            "Workers that re-attached to a live scaleout "
                            "job").inc()
            reg.gauge("dl4j_scaleout_live_workers",
                      "Workers currently in the averaging round"
                      ).set(len(self._live))
            self._cv.notify_all()
        return wid

    # ------------------------------------------------------------ handlers
    def _handle(self, wid: int, conn: socket.socket):
        try:
            while not self._stopped:
                kind, payload = recv_frame(conn)
                if kind == KIND_DONE:
                    self._leave(wid, conn, done=True)
                    return
                if kind == KIND_PARAMS:
                    res = self._contribute(
                        wid, np.frombuffer(payload, np.float32))
                    if res is None:         # hub stopped mid-round
                        return
                    mean, rnd = res
                    # reply = 4-byte round index + f32 mean: the worker
                    # keys its drift-audit checksum (ISSUE 13) by the
                    # hub's OWN round counter, so elastic membership
                    # (stragglers, rejoins) can never skew the audit
                    # onto the wrong round
                    send_frame(conn, KIND_PARAMS,
                               struct.pack("<I", rnd)
                               + mean.astype(np.float32).tobytes())
                elif kind == KIND_LEASE_REQ:
                    status, item = self._grant(wid)
                    pl = bytes([status]) + (struct.pack("<I", item)
                                            if status == GRANT_OK else b"")
                    send_frame(conn, KIND_LEASE, pl)
                elif kind == KIND_LEASE_DONE and len(payload) == 4:
                    if self._table is not None:
                        self._table.complete(
                            wid, struct.unpack("<I", payload)[0])
                # unknown kinds: ignored (forward compatibility)
        except _SOCK_ERRORS:
            self._leave(wid, conn, done=False)
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def _leave(self, wid: int, conn: socket.socket, done: bool):
        released: List[int] = []
        with self._cv:
            if self._live.get(wid) is not conn:
                return                      # superseded by a rejoin
            del self._live[wid]
            self._frames.pop(wid, None)
            self._means.pop(wid, None)
            if not done:
                self.dropped.append(wid)
                get_registry().counter("dl4j_scaleout_workers_dropped_total",
                                       "Workers dropped mid-job").inc()
                if self._table is not None:
                    released = self._table.release_worker(wid)
            get_registry().gauge("dl4j_scaleout_live_workers",
                                 "Workers currently in the averaging round"
                                 ).set(len(self._live))
            self._maybe_close_locked()
            self._cv.notify_all()
        if not done:
            extra = (f" ({len(released)} lease(s) returned to the pool)"
                     if released else "")
            warnings.warn(f"scaleout: worker {wid} failed mid-job — "
                          f"continuing with the survivors{extra}")
        self._drain_after()

    # ------------------------------------------------------------ rounds
    def _contribute(self, wid: int,
                    vec: np.ndarray) -> Optional[Tuple[np.ndarray, int]]:
        """Deposit ``wid``'s params frame into the current round; block
        until the round containing it closes; return (round mean, round
        index) — None = hub stopped. Rounds close when every live
        worker has contributed, or at the deadline — whichever comes
        first."""
        vec = np.asarray(vec, np.float32)
        with self._cv:
            if self._stopped or self._live.get(wid) is None:
                return None
            self._frames[wid] = vec
            if self._round_t0 is None:
                self._round_t0 = (time.time(), time.perf_counter())
                self._deadline = time.monotonic() + self.worker_timeout
            self._maybe_close_locked()
            while wid not in self._means and not self._stopped:
                rem = (self._deadline - time.monotonic()) \
                    if self._deadline is not None else 0.25
                if rem <= 0:
                    self._close_round_locked()
                    continue
                self._cv.wait(min(rem, 0.25))
            mean = self._means.pop(wid, None)
        self._drain_after()
        return mean

    def _maybe_close_locked(self):
        if not self._frames:
            return
        if not self._provisioned:
            if time.monotonic() - self._t0 < self.worker_timeout:
                return      # provisioning window: wait for the full crew
            self._provisioned = True
        if set(self._frames) >= set(self._live):
            self._close_round_locked()

    def _close_round_locked(self):
        if not self._frames:
            return
        contributors = dict(self._frames)
        self._frames.clear()
        mean = np.mean(list(contributors.values()), axis=0).astype(np.float32)
        self._last_mean = mean
        self._final = mean
        self.rounds += 1
        for w in contributors:
            self._means[w] = (mean, self.rounds)
        self._provisioned = True    # whoever averaged IS the working set
        t0 = self._round_t0
        self._round_t0 = None
        self._deadline = None
        get_registry().counter("dl4j_scaleout_rounds_total",
                               "Parameter-averaging rounds completed").inc()
        self._after_q.append((mean, self.rounds, len(contributors), t0))
        self._cv.notify_all()

    def _drain_after(self):
        """Run queued post-round work (round span, on_round checkpoint)
        OUTSIDE the barrier lock, single-threaded and in round order."""
        while True:
            with self._cv:
                if self._draining or not self._after_q:
                    return
                self._draining = True
                item = self._after_q.pop(0)
            try:
                self._after_round(*item)
            finally:
                with self._cv:
                    self._draining = False

    def _after_round(self, mean: np.ndarray, rnd: int, n_contrib: int,
                     t0: Optional[Tuple[float, float]]):
        # the round span was timed across handler threads (first frame ->
        # close), so it is assembled by hand with the DETERMINISTIC id
        # both wire ends compute — workers parent their fit spans to it
        # without a round-trip
        if self.span_ctx is not None:
            trace, parent = self.span_ctx.trace_id, self.span_ctx.span_id
            sid = derived_span_id(trace, "round", rnd)
        else:
            trace, parent = uuid.uuid4().hex[:16], None
            sid = derived_span_id(trace, "round", rnd)
        start_ts, t0p = t0 if t0 is not None else (time.time(),
                                                  time.perf_counter())
        get_tracer().add_span(Span(
            name="scaleout_round", trace_id=trace, span_id=sid,
            parent_id=parent, start_ts=start_ts,
            time_s=time.perf_counter() - t0p,
            attrs={"round": rnd, "workers": n_contrib}))
        # drift audit (ISSUE 13): record the broadcast mean's checksum
        # under replica "hub" for this round; every worker records the
        # mean IT received after applying it, and the auditor compares —
        # all ends of the wire must enter round rnd+1 from bit-identical
        # state (dl4j_replica_drift_*). Decoration only.
        try:
            from ..obs import numerics as obs_numerics
            obs_numerics.get_auditor().record(
                _drift_source(self.address), "hub", rnd,
                **obs_numerics.checksum_ndarray(mean))
        except Exception:  # noqa: BLE001 — audit is decoration
            pass
        if self.on_round is not None:
            try:
                self.on_round(mean, rnd)
            except Exception as e:  # noqa: BLE001 — checkpointing must
                # never take down the averaging plane
                warnings.warn(f"scaleout: on_round callback failed: {e}")
        if self.fail_after_rounds is not None and \
                rnd - self.start_round >= self.fail_after_rounds:
            # fault injection: the master dies between rounds — workers
            # see dead sockets and retry-reattach; fit raises
            # MasterDiedError and a new fit resumes from the checkpoint
            self.fail_injected = True
            self.stop(join=False)

    # ------------------------------------------------------------ leases
    def _grant(self, wid: int) -> Tuple[int, int]:
        if self._table is None:
            return GRANT_NONE, -1
        nw = self._table.n_workers
        with self._cv:
            live_slots = {w % nw for w in self._live}
            if not self._provisioned and \
                    time.monotonic() - self._t0 >= self.worker_timeout:
                self._provisioned = True
            unsettled = set() if self._provisioned else \
                set(range(nw)) - {w % nw for w in self._ever}
            stealable = set(range(nw)) - live_slots - unsettled
        status, item = self._table.acquire(wid, stealable_slots=stealable,
                                           unsettled_slots=unsettled)
        with self._cv:
            newly = self._table.reassigned - self._reassigned_seen
            if newly > 0:
                get_registry().counter(
                    "dl4j_scaleout_leases_reassigned_total",
                    "Partition leases re-granted after their worker died "
                    "or left").inc(newly)
                self._reassigned_seen = self._table.reassigned
        return status, item


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------

class WorkerClient:
    """Worker-side connection with bounded reconnect-with-backoff.

    ``average(flat)`` every averaging_frequency steps, ``lease()`` /
    ``lease_done(item)`` in lease mode, ``done()`` when finished. With
    ``max_retries > 0``, a dead hub (master restart, network flap) is
    survived transparently: the client re-dials with exponential backoff
    (``backoff_delays``), re-HELLOs under the same worker id (the hub
    counts it as a rejoin), and resends the in-flight frame. Retries
    exhausted -> a clean ``ConnectionError``, never an indefinite hang
    (``timeout`` bounds every socket op; None preserves the legacy
    block-forever behavior for hand-managed deployments)."""

    def __init__(self, address: Address, worker_id: int = 0,
                 timeout: Optional[float] = None, max_retries: int = 0,
                 backoff_base: float = 0.5, backoff_max: float = 8.0):
        self.address = address
        self.worker_id = int(worker_id)
        self.timeout = timeout
        self.max_retries = max(0, int(max_retries))
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.rejoins = 0          # successful re-attaches after a failure
        self.span_ctx: Optional[SpanContext] = None
        self.rejoin_params: Optional[np.ndarray] = None
        self.round_offset = 0     # hub's round counter when we joined
        self.last_round = 0       # hub round of the last average() reply
        self._sock: Optional[socket.socket] = None
        self._connect()

    # ------------------------------------------------------------ dialing
    def _dial(self):
        sock = _make_socket(self.address)
        sock.settimeout(self.timeout)
        try:
            sock.connect(tuple(self.address)
                         if not isinstance(self.address, str)
                         else self.address)
            send_frame(sock, KIND_HELLO, struct.pack("<I", self.worker_id))
            kind, payload = recv_frame(sock)
            span_ctx = unpack_span_context(payload) \
                if kind == KIND_SPANCTX else None
            kind, payload = recv_frame(sock)
            round_offset, rejoin, assigned = 0, None, self.worker_id
            if kind == KIND_REJOIN and len(payload) >= 8:
                round_offset, assigned = struct.unpack("<II", payload[:8])
                if len(payload) > 8:
                    rejoin = np.frombuffer(payload[8:], np.float32).copy()
        except BaseException:
            with contextlib.suppress(OSError):
                sock.close()
            raise
        self._sock = sock
        self.span_ctx = span_ctx
        self.round_offset = int(round_offset)
        # hub-side identity: differs from worker_id when a live
        # duplicate dialer was uniquified at _register — the drift
        # audit labels by THIS id so colliding workers never share a
        # replica series
        self.assigned_id = int(assigned)
        self.rejoin_params = rejoin

    def _connect(self):
        delays = backoff_delays(self.backoff_base, self.backoff_max,
                                self.max_retries)
        last = None
        for attempt in range(self.max_retries + 1):
            try:
                self._dial()
                return
            except _SOCK_ERRORS as e:
                last = e
                if attempt < self.max_retries:
                    time.sleep(delays[attempt])
        raise ConnectionError(
            f"scaleout hub at {self.address!r} unreachable after "
            f"{self.max_retries + 1} attempt(s): {last}")

    def _close_sock(self):
        if self._sock is not None:
            with contextlib.suppress(OSError):
                self._sock.close()
            self._sock = None

    def _ensure(self):
        if self._sock is None:
            raise ConnectionError("not connected")

    def _retrying(self, op, what: str):
        delays = backoff_delays(self.backoff_base, self.backoff_max,
                                self.max_retries)
        last = None
        for attempt in range(self.max_retries + 1):
            try:
                return op()
            except _SOCK_ERRORS as e:
                last = e
                if attempt == self.max_retries:
                    break
                self._close_sock()
                time.sleep(delays[attempt])
                try:
                    self._dial()
                    self.rejoins += 1
                except _SOCK_ERRORS as e2:
                    last = e2       # next loop iteration backs off longer
        self._close_sock()
        raise ConnectionError(
            f"scaleout hub lost during {what} and not recovered after "
            f"{self.max_retries + 1} attempt(s): {last}")

    # ------------------------------------------------------------ ops
    def average(self, flat: np.ndarray) -> np.ndarray:
        """Contribute ``flat`` and return the round mean. The reply's
        4-byte header is the hub's round index — kept on
        ``self.last_round`` so the drift audit (ISSUE 13) keys its
        checksum by the hub's counter, immune to membership skew."""
        blob = np.ascontiguousarray(flat, np.float32).tobytes()

        def op():
            self._ensure()
            send_frame(self._sock, KIND_PARAMS, blob)
            kind, payload = recv_frame(self._sock)
            if kind != KIND_PARAMS or len(payload) < 4:
                raise ConnectionError("hub closed mid-round")
            self.last_round = struct.unpack("<I", payload[:4])[0]
            return np.frombuffer(payload[4:], np.float32).copy()

        return self._retrying(op, "average")

    def lease(self, max_wait: Optional[float] = None) -> Optional[int]:
        """Lease the next work item; None when the pool has nothing
        (now or ever) for this worker. GRANT_RETRY (the provisioning
        window) polls until ``max_wait`` elapses — defaulting to the
        client's socket ``timeout``, which the driver sizes to outlast
        the hub's provisioning grace (``worker_timeout``), so a worker
        never abandons items that are merely held back for an owner the
        hub has not yet given up on."""
        if max_wait is None:
            max_wait = self.timeout if self.timeout else 30.0

        def op():
            self._ensure()
            send_frame(self._sock, KIND_LEASE_REQ)
            kind, payload = recv_frame(self._sock)
            if kind != KIND_LEASE or not payload:
                raise ConnectionError("hub closed during lease grant")
            status = payload[0]
            item = struct.unpack("<I", payload[1:5])[0] \
                if status == GRANT_OK and len(payload) >= 5 else -1
            return status, item

        deadline = time.monotonic() + max_wait
        while True:
            status, item = self._retrying(op, "lease")
            if status == GRANT_OK:
                return item
            if status == GRANT_NONE or time.monotonic() > deadline:
                return None
            time.sleep(0.05)

    def lease_done(self, item: int) -> bool:
        """Best-effort completion report. If the connection died since
        the grant, the hub has already released the lease — do NOT
        resend on a fresh connection (the item may be re-leased); the
        re-run is the at-least-once half of the lease contract."""
        try:
            self._ensure()
            send_frame(self._sock, KIND_LEASE_DONE,
                       struct.pack("<I", int(item)))
            return True
        except _SOCK_ERRORS:
            return False

    def done(self):
        try:
            if self._sock is not None:
                send_frame(self._sock, KIND_DONE)
        finally:
            self._close_sock()

    def abort(self):
        """Crash path: close without DONE so the hub drops us (and
        releases our leases) instead of hanging."""
        self._close_sock()


def worker_main(address: Address, net, datasets: Sequence,
                averaging_frequency: int, epochs: int = 1,
                fail_after_steps: Optional[int] = None,
                worker_id: int = 0, *,
                worker_timeout: Optional[float] = None,
                lease: bool = False, max_retries: int = 0,
                backoff_base: float = 0.5, backoff_max: float = 8.0) -> None:
    """The worker body (reference: the Spark executor's FitWorker). Same
    code for thread, subprocess, or remote-host execution — only
    ``address`` changes.

    Two data modes: ``lease=False`` fits ``datasets`` as this worker's
    static partition (legacy contract); ``lease=True`` treats
    ``datasets`` as the FULL shard list and leases ``(epoch, shard)``
    items from the hub one at a time, so a dead peer's shards flow to
    this worker and this worker's shards outlive it. Either way the
    averaging round joins every ``averaging_frequency`` local steps.

    ``worker_timeout`` bounds every socket wait (None = legacy
    block-forever); ``max_retries``/``backoff_*`` let the worker survive
    a master restart by re-attaching. ``fail_after_steps`` is a
    fault-injection hook for tests."""
    client = WorkerClient(address, worker_id=worker_id,
                          timeout=worker_timeout, max_retries=max_retries,
                          backoff_base=backoff_base, backoff_max=backoff_max)
    if client.rejoin_params is not None and client.rejoin_params.size:
        # enter the job from its live state, not our stale init
        net.set_params_flat(client.rejoin_params)
    tracer = get_tracer()
    state = {"step": 0, "base_step": 0,
             "base_round": client.round_offset, "rejoins": client.rejoins}

    def fit_span():
        """Span for the fit feeding the next averaging round: parented
        to the ROUND's deterministic id, so the exported tree reads
        master job -> round k -> this worker's fits."""
        ctx = client.span_ctx
        if ctx is None:
            return contextlib.nullcontext()
        if client.rejoins != state["rejoins"]:
            # reconnected mid-job: rebase the round arithmetic on the
            # hub's current round so already-fed rounds aren't counted
            # twice (which would orphan the spans on phantom round ids)
            state["rejoins"] = client.rejoins
            state["base_step"] = state["step"]
            state["base_round"] = client.round_offset
        rnd = state["base_round"] + \
            (state["step"] - state["base_step"]) // averaging_frequency + 1
        parent = SpanContext(ctx.trace_id,
                             derived_span_id(ctx.trace_id, "round", rnd))
        return tracer.span("scaleout_worker_fit", parent=parent,
                           attrs={"worker": worker_id, "round": rnd,
                                  "step": state["step"] + 1})

    def audit_mean(mean: np.ndarray):
        """Drift audit (ISSUE 13): checksum the round mean this worker
        just applied, keyed by the hub's OWN round index (carried in
        the PARAMS reply) under this worker's replica id. The hub
        records the same round under "hub" and the auditor compares
        every end of the wire (dl4j_replica_drift_*) — zero drift is
        the proof all replicas entered the next round from identical
        state. Host numpy only; decoration."""
        try:
            from ..obs import numerics as obs_numerics
            obs_numerics.get_auditor().record(
                _drift_source(address),
                str(getattr(client, "assigned_id", worker_id)),
                client.last_round,
                **obs_numerics.checksum_ndarray(
                    np.ascontiguousarray(mean, np.float32)))
        except Exception:  # noqa: BLE001 — audit is decoration
            pass

    def fit_one(ds):
        with fit_span():
            net.fit(ds)
        state["step"] += 1
        get_registry().counter(
            "dl4j_scaleout_worker_steps_total",
            "Fit steps taken by scaleout workers").inc()
        if fail_after_steps is not None and state["step"] >= fail_after_steps:
            raise RuntimeError("injected worker failure")
        if state["step"] % averaging_frequency == 0:
            mean = client.average(np.asarray(net.params_flat(), np.float32))
            net.set_params_flat(mean)
            audit_mean(mean)

    try:
        if lease:
            n_shards = max(1, len(datasets))
            while True:
                item = client.lease()
                if item is None:
                    break
                fit_one(datasets[item % n_shards])
                client.lease_done(item)
        else:
            for _ in range(epochs):
                for ds in datasets:
                    fit_one(ds)
        # one final sync so the master sees this worker's tail steps
        if state["step"] % averaging_frequency:
            mean = client.average(np.asarray(net.params_flat(), np.float32))
            net.set_params_flat(mean)
            audit_mean(mean)
        client.done()
    except BaseException:
        # crash without done(): the hub must drop us (releasing our
        # leases), not hang — this is the fault-tolerance failure path
        client.abort()
        raise


# ---------------------------------------------------------------------------
# Job driver
# ---------------------------------------------------------------------------

class SparkDl4jMultiLayer:
    """Reference ``SparkDl4jMultiLayer``: net + TrainingMaster → job-level
    ``fit``. Workers are provisioned as threads by default (each runs its
    own jitted fit on its leased shards — the single-host multi-executor
    regime); point remote processes at ``hub.address`` + ``worker_main``
    for true multi-host operation. ``fit`` resumes an interrupted job
    from ``checkpoint_dir`` automatically (see ``read_resume_state``)."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.tm = training_master
        self.rounds = 0
        self.dropped_workers: List[int] = []
        self.lease_table: Optional[LeaseTable] = None
        self.resumed = False
        self.rejoins = 0

    # ---------------------------------------------------------- checkpoint
    def _checkpoint(self, template_net, table: LeaseTable):
        tm = self.tm
        if tm.checkpoint_dir is None:
            return None
        ckdir = Path(tm.checkpoint_dir)
        ckdir.mkdir(parents=True, exist_ok=True)

        def on_round(mean: np.ndarray, round_idx: int):
            if round_idx % tm.checkpoint_every_rounds:
                return
            template_net.set_params_flat(mean)
            from ..serde.model_serializer import save_model
            # every artifact lands atomically; the round STAMP is written
            # last, so a stamp present implies the others are whole
            save_model(template_net, ckdir / "latest.zip")
            atomic_write_text(ckdir / "leases.json", table.snapshot())
            atomic_write_text(ckdir / "round.txt", str(round_idx))

        return on_round

    def _load_resume_state(self, n_shards: int,
                           n_workers: int) -> Tuple[int, tuple, bool]:
        """(start_round, completed item ids, resumed?) — reads the
        interrupted-job stamp left by ``_checkpoint`` and reloads the
        averaged params into ``self.net``."""
        tm = self.tm
        if tm.checkpoint_dir is None:
            return 0, (), False
        stamp = read_resume_state(tm.checkpoint_dir)
        if stamp is None:
            return 0, (), False
        rnd, snap = stamp
        table = LeaseTable.restore(snap, n_shards, tm.epochs_per_fit,
                                   n_workers)
        if table is None:        # different job geometry — start fresh
            return 0, (), False
        model_path = Path(tm.checkpoint_dir) / "latest.zip"
        if model_path.exists():
            from ..serde.model_serializer import load_model
            restored = load_model(model_path)
            self.net.set_params_flat(
                np.asarray(restored.params_flat(), np.float32))
        return rnd, table.completed, True

    def _clear_lease_stamp(self):
        """A completed job deletes ``leases.json`` so the next ``fit``
        against the same checkpoint_dir starts a FRESH job (the stamp
        marks interruption, not history)."""
        if self.tm.checkpoint_dir is not None:
            with contextlib.suppress(OSError):
                (Path(self.tm.checkpoint_dir) / "leases.json").unlink()

    # ---------------------------------------------------------- fit
    def fit(self, datasets: Sequence, *,
            fail_worker: Optional[int] = None,
            fail_after_steps: int = 1,
            respawn_failed: bool = False,
            fail_master_after_rounds: Optional[int] = None):
        """Run the job: lease table over (epoch, shard) items → provision
        workers → averaging rounds → final averaged params land in
        ``self.net``. ``fail_worker`` / ``fail_after_steps`` inject a
        worker crash; ``respawn_failed`` re-provisions a crashed worker
        once (the Spark executor-re-provisioning contract — it rejoins
        under the same id); ``fail_master_after_rounds`` injects a
        master death (resume by calling ``fit`` again with the same
        ``checkpoint_dir``)."""
        tm = self.tm
        datasets = list(datasets)
        if not datasets:
            raise ValueError("no datasets to fit")
        n_shards = len(datasets)
        n = max(1, min(tm.n_workers, n_shards))
        start_round, completed, resumed = self._load_resume_state(n_shards, n)
        table = LeaseTable(n_shards, tm.epochs_per_fit, n,
                           completed=completed)
        self.lease_table = table
        self.resumed = resumed
        if resumed:
            get_registry().counter(
                "dl4j_scaleout_master_restarts_total",
                "Scaleout jobs resumed from the between-round "
                "checkpoint").inc()
        if table.all_done():
            # the interrupted job was already fully covered — the
            # checkpoint params (just reloaded) ARE the job's output
            self._clear_lease_stamp()
            self.rounds = start_round
            self.dropped_workers = []
            return self.net
        tracer = get_tracer()
        # the hub closes a round worker_timeout after its first frame;
        # give clients headroom past that so a straggler round cannot be
        # misread as a dead hub
        client_timeout = tm.worker_timeout * 1.25 + 2.0
        with tracer.span("scaleout_job",
                         attrs={"workers": n, "resumed": resumed}) as job_span:
            # the job root span's context rides the hub's KIND_SPANCTX
            # frames to every worker — thread, process, or remote host
            hub = ParamAveragingHub(
                n_workers=n, worker_timeout=tm.worker_timeout,
                on_round=self._checkpoint(self.net.clone(), table),
                span_ctx=job_span.context, lease_table=table,
                start_round=start_round,
                initial_params=(np.asarray(self.net.params_flat(), np.float32)
                                if resumed else None),
                fail_after_rounds=fail_master_after_rounds).start()

            threads: List[threading.Thread] = []
            tlock = threading.Lock()
            errors: List[BaseException] = []
            respawns: Dict[int, int] = {}

            def spawn(wid: int, inject: Optional[int]):
                replica = self.net.clone()

                def run():
                    try:
                        worker_main(hub.address, replica, datasets,
                                    tm.averaging_frequency, tm.epochs_per_fit,
                                    inject, worker_id=wid, lease=True,
                                    worker_timeout=client_timeout,
                                    max_retries=tm.worker_retries,
                                    backoff_base=tm.worker_backoff)
                    except BaseException as e:  # noqa: BLE001 — collected
                        errors.append(e)
                        if respawn_failed and respawns.get(wid, 0) < 1 \
                                and not hub.stopped:
                            respawns[wid] = respawns.get(wid, 0) + 1
                            # wait for the hub to notice the death so the
                            # fresh HELLO reads as a REJOIN, not a live
                            # duplicate id
                            hub.wait_dropped(wid, timeout=tm.worker_timeout)
                            spawn(wid, None)

                t = threading.Thread(target=run, daemon=True,
                                     name=f"dl4j-tpu-worker-{wid}")
                with tlock:
                    threads.append(t)
                t.start()

            for wid in range(n):
                spawn(wid, fail_after_steps if wid == fail_worker else None)
            # join ALL workers, including respawns registered while we join
            joined = 0
            while True:
                with tlock:
                    batch = threads[joined:]
                if not batch:
                    break
                for t in batch:
                    t.join()
                joined += len(batch)
            final = hub.result(timeout=tm.worker_timeout)
            job_span.set_attr("rounds", hub.rounds)
            job_span.set_attr("dropped", list(hub.dropped))
        self.rounds = hub.rounds
        self.dropped_workers = hub.dropped
        self.rejoins = hub.rejoins
        if hub.fail_injected:
            raise MasterDiedError(
                f"scaleout master died (injected) after round {hub.rounds}; "
                "call fit again with the same checkpoint_dir to resume")
        if final is None:
            if not resumed:
                raise RuntimeError(
                    "scaleout job produced no averaged parameters (every "
                    f"worker failed before the first round; errors: {errors})")
            # resumed job needed no further rounds: checkpoint params stand
            final = np.asarray(self.net.params_flat(), np.float32)
        self.net.set_params_flat(final)
        if table.all_done():
            self._clear_lease_stamp()
        else:
            # never report clean success on partial coverage: the stamp
            # (when checkpointing) stays behind so a later fit resumes
            miss = table.n_items - len(table.completed)
            warnings.warn(
                f"scaleout: job drained with {miss} of {table.n_items} "
                "partition item(s) unconsumed" +
                (" — call fit again with the same checkpoint_dir to "
                 "resume" if tm.checkpoint_dir else
                 " and no checkpoint_dir to resume from"))
        return self.net


SparkComputationGraph = SparkDl4jMultiLayer   # CG has the same flat-params
# contract (params_flat/set_params_flat/clone/fit) — one driver serves both
