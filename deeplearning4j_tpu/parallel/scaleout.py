"""Cluster-level training orchestration — the Spark-scaleout analogue.

Reference parity: ``deeplearning4j-scaleout/spark``'s
``SparkDl4jMultiLayer`` / ``SparkComputationGraph`` +
``ParameterAveragingTrainingMaster`` (VERDICT r4 missing item 3): a JOB
driver that provisions workers, partitions the data, runs
averaging-frequency-paced parameter-averaging rounds over a master hub,
tolerates worker failure mid-job (the round averages over the survivors,
like Spark dropping a failed executor's partial result), and checkpoints
the averaged model between rounds for resume.

TPU-native positioning: WITHIN one pod, ``ParallelWrapper`` /
``ParameterAveragingTrainer`` compile the whole round as one XLA program
over ICI — always use those. This driver is the layer ABOVE: separate
worker processes/hosts with no shared runtime (the regime Spark executors
occupy), coordinated over TCP/Unix sockets. Workers run the same
``worker_main`` whether they are threads (tests, single-host), processes
(multi-core hosts), or remote hosts (point them at the master's
address; compose with ``bootstrap_distributed`` when each worker is
itself a multi-chip jax.distributed process).

Wire protocol (little-endian), one frame per message:
  uint8   kind (0 = params, 1 = done, 2 = hello, 3 = span context)
  uint32  payload byte length
  float32[] flat parameter vector (kind 0 only)
Each round the hub averages the params frames of every LIVE worker and
sends the mean back to those workers. Workers that disconnect, error, or
time out are dropped from the job with a warning — training continues
with the survivors.

Telemetry (deeplearning4j_tpu.obs): the hub counts rounds / drops /
live workers under ``dl4j_scaleout_*``, and span context propagates
master -> worker over the wire (the hub answers every HELLO with a
KIND_SPANCTX frame): the job root span, each averaging round's span
(deterministic id ``derived_span_id(trace, "round", k)``), and every
worker's fit spans parented under that round stitch into ONE trace
tree, exportable as JSONL via ``obs.get_tracer().export_jsonl``.
"""

from __future__ import annotations

import contextlib
import socket
import struct
import threading
import warnings
from pathlib import Path
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..obs import SpanContext, derived_span_id, get_registry, get_tracer
from .transport import (Address, _make_socket, _recv_exact,
                        pack_span_context, unpack_span_context)

_FHDR = struct.Struct("<BI")      # kind, payload bytes
KIND_PARAMS = 0
KIND_DONE = 1
KIND_HELLO = 2    # uint32 worker id — sent once on connect, so the hub's
# worker labels are the CALLER's ids, not TCP accept order
KIND_SPANCTX = 3  # hub -> worker right after HELLO: the master's span
# context header (empty payload = tracing off) — workers parent their
# fit spans into the master's trace tree


def _send(conn: socket.socket, kind: int, payload: bytes = b""):
    conn.sendall(_FHDR.pack(kind, len(payload)) + payload)


def _recv(conn: socket.socket):
    kind, nbytes = _FHDR.unpack(_recv_exact(conn, _FHDR.size))
    payload = _recv_exact(conn, nbytes) if nbytes else b""
    return kind, payload


class TrainingMaster:
    """Configuration interface (reference ``TrainingMaster``)."""

    def __init__(self, *, batch_size_per_worker: int = 32,
                 averaging_frequency: int = 5, n_workers: int = 2,
                 epochs_per_fit: int = 1,
                 worker_timeout: float = 120.0,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every_rounds: int = 1):
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = averaging_frequency
        self.n_workers = n_workers
        self.epochs_per_fit = epochs_per_fit
        self.worker_timeout = worker_timeout
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_rounds = max(1, checkpoint_every_rounds)


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Reference ``ParameterAveragingTrainingMaster``: sync param
    averaging every ``averaging_frequency`` worker iterations."""


class ParamAveragingHub:
    """Master-side hub for parameter-averaging rounds with failure
    tolerance. One daemon thread; ``result()`` joins and returns the final
    averaged flat params (or None if every worker failed before round 1).
    """

    def __init__(self, n_workers: int, address: Address = ("127.0.0.1", 0),
                 worker_timeout: float = 120.0,
                 on_round: Optional[Callable[[np.ndarray, int], None]] = None,
                 span_ctx=None):
        self.n_workers = n_workers
        self.worker_timeout = worker_timeout
        self.on_round = on_round
        self.span_ctx = span_ctx  # master trace context, sent to workers
        self._sock = _make_socket(address)
        if not isinstance(address, str):
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(n_workers)
        self.address = self._sock.getsockname()
        self.rounds = 0
        self.dropped: List[int] = []
        self._final: Optional[np.ndarray] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ParamAveragingHub":
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dl4j-tpu-param-hub")
        self._thread.start()
        return self

    def _serve(self):
        reg = get_registry()
        m_rounds = reg.counter("dl4j_scaleout_rounds_total",
                               "Parameter-averaging rounds completed")
        m_dropped = reg.counter("dl4j_scaleout_workers_dropped_total",
                                "Workers dropped mid-job")
        m_live = reg.gauge("dl4j_scaleout_live_workers",
                           "Workers currently in the averaging round")
        conns = {}
        try:
            self._sock.settimeout(self.worker_timeout)
            for i in range(self.n_workers):
                conn, _ = self._sock.accept()
                conn.settimeout(self.worker_timeout)
                kind, payload = _recv(conn)
                wid = struct.unpack("<I", payload)[0] \
                    if kind == KIND_HELLO and len(payload) == 4 else i
                while wid in conns:    # duplicate/defaulted ids stay unique
                    wid += self.n_workers
                conns[wid] = conn
                # reply with the master's trace context (empty = off)
                _send(conn, KIND_SPANCTX, pack_span_context(self.span_ctx))
        except (OSError, socket.timeout, ConnectionError):
            pass      # provision what arrived; 0 workers handled below
        live = dict(conns)
        m_live.set(len(live))
        mean = None
        tracer = get_tracer()
        while live:
            # the round span opens when the hub starts gathering and has
            # the DETERMINISTIC id round k+1 — workers parent the fits
            # feeding round k+1 to the same id without a wire round-trip
            rnd = self.rounds + 1
            span_kw = {"parent": self.span_ctx} if self.span_ctx else {}
            rid = None if self.span_ctx is None else derived_span_id(
                self.span_ctx.trace_id, "round", rnd)
            with tracer.span("scaleout_round", attrs={"round": rnd},
                             span_id=rid, **span_kw) as round_span:
                frames = {}
                for wid, conn in list(live.items()):
                    try:
                        kind, payload = _recv(conn)
                    except (ConnectionError, socket.timeout, OSError):
                        warnings.warn(
                            f"scaleout: worker {wid} failed mid-job — "
                            "continuing with the survivors")
                        self.dropped.append(wid)
                        m_dropped.inc()
                        del live[wid]
                        continue
                    if kind == KIND_DONE:
                        del live[wid]
                    else:
                        frames[wid] = np.frombuffer(payload, np.float32)
                m_live.set(len(live))
                if frames:
                    mean = np.mean(list(frames.values()), axis=0)
                    self._final = mean
                    blob = mean.astype(np.float32).tobytes()
                    for wid in list(frames):
                        try:
                            _send(live[wid], KIND_PARAMS, blob)
                        except (ConnectionError, OSError):
                            warnings.warn(f"scaleout: worker {wid} failed at "
                                          "broadcast — dropping")
                            self.dropped.append(wid)
                            m_dropped.inc()
                            del live[wid]
                    self.rounds += 1
                    m_rounds.inc()
                    m_live.set(len(live))   # broadcast may have dropped
                    round_span.set_attr("workers", len(frames))
                    if self.on_round is not None:
                        self.on_round(mean, self.rounds)
                else:
                    # every worker finished/died before sending params:
                    # not an averaging round — keep it out of the trace
                    round_span.set_attr("empty", True)
        m_live.set(0)
        for conn in conns.values():
            try:
                conn.close()
            except OSError:
                pass

    def result(self, timeout: Optional[float] = None) -> Optional[np.ndarray]:
        if self._thread is not None:
            self._thread.join(timeout)
        try:
            self._sock.close()
        except OSError:
            pass
        return self._final


class WorkerClient:
    """Worker-side connection: call ``average(flat)`` every
    averaging_frequency steps, ``done()`` when the partition is finished."""

    def __init__(self, address: Address, worker_id: int = 0,
                 timeout: Optional[float] = None):
        self._sock = _make_socket(address)
        self._sock.settimeout(timeout)
        self._sock.connect(tuple(address) if not isinstance(address, str)
                           else address)
        _send(self._sock, KIND_HELLO, struct.pack("<I", int(worker_id)))
        # the hub answers every HELLO with the master's span context
        # (empty payload when tracing is off) — adopt it so this
        # worker's fit spans join the master's trace tree
        kind, payload = _recv(self._sock)
        self.span_ctx = unpack_span_context(payload) \
            if kind == KIND_SPANCTX else None

    def average(self, flat: np.ndarray) -> np.ndarray:
        _send(self._sock, KIND_PARAMS,
              np.ascontiguousarray(flat, np.float32).tobytes())
        kind, payload = _recv(self._sock)
        if kind != KIND_PARAMS:
            raise ConnectionError("hub closed mid-round")
        return np.frombuffer(payload, np.float32).copy()

    def done(self):
        try:
            _send(self._sock, KIND_DONE)
        finally:
            self._sock.close()


def worker_main(address: Address, net, datasets: Sequence,
                averaging_frequency: int, epochs: int = 1,
                fail_after_steps: Optional[int] = None,
                worker_id: int = 0) -> None:
    """The worker body (reference: the Spark executor's FitWorker). Runs
    local fit steps on ``datasets`` (this worker's partition), joining the
    averaging round every ``averaging_frequency`` batches. Same code for
    thread, subprocess, or remote-host execution — only ``address``
    changes. ``fail_after_steps`` is a fault-injection hook for tests."""
    client = WorkerClient(address, worker_id=worker_id)
    tracer = get_tracer()
    ctx = client.span_ctx

    def fit_span(step):
        """Span for the fit feeding averaging round step//freq (+1):
        parented to the ROUND's deterministic id, so the exported tree
        reads master job -> round k -> this worker's fits."""
        if ctx is None:
            return contextlib.nullcontext()
        rnd = step // averaging_frequency + 1
        parent = SpanContext(ctx.trace_id,
                             derived_span_id(ctx.trace_id, "round", rnd))
        return tracer.span("scaleout_worker_fit", parent=parent,
                           attrs={"worker": worker_id, "round": rnd,
                                  "step": step + 1})

    step = 0
    try:
        for _ in range(epochs):
            for ds in datasets:
                with fit_span(step):
                    net.fit(ds)
                step += 1
                get_registry().counter(
                    "dl4j_scaleout_worker_steps_total",
                    "Fit steps taken by scaleout workers").inc()
                if fail_after_steps is not None and step >= fail_after_steps:
                    raise RuntimeError("injected worker failure")
                if step % averaging_frequency == 0:
                    mean = client.average(np.asarray(net.params_flat(),
                                                     np.float32))
                    net.set_params_flat(mean)
        # one final sync so the master sees this worker's tail steps
        if step % averaging_frequency:
            mean = client.average(np.asarray(net.params_flat(), np.float32))
            net.set_params_flat(mean)
        client.done()
    except RuntimeError:
        # crash without done(): the hub must drop us, not hang — this is
        # the failure path the fault-tolerance test exercises
        try:
            self_sock = client._sock
            self_sock.close()
        except OSError:
            pass
        raise


class SparkDl4jMultiLayer:
    """Reference ``SparkDl4jMultiLayer``: net + TrainingMaster → job-level
    ``fit``. Workers are provisioned as threads by default (each runs its
    own jitted fit on its partition — the single-host multi-executor
    regime); point remote processes at ``hub.address`` + ``worker_main``
    for true multi-host operation."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.tm = training_master

    def _partition(self, datasets: Sequence) -> List[List]:
        parts: List[List] = [[] for _ in range(self.tm.n_workers)]
        for i, ds in enumerate(datasets):
            parts[i % self.tm.n_workers].append(ds)
        return [p for p in parts if p]

    def _checkpoint(self, template_net):
        tm = self.tm
        if tm.checkpoint_dir is None:
            return None
        ckdir = Path(tm.checkpoint_dir)
        ckdir.mkdir(parents=True, exist_ok=True)

        def on_round(mean: np.ndarray, round_idx: int):
            if round_idx % tm.checkpoint_every_rounds:
                return
            template_net.set_params_flat(mean)
            from ..serde.model_serializer import save_model
            save_model(template_net, ckdir / "latest.zip")
            (ckdir / "round.txt").write_text(str(round_idx))

        return on_round

    def fit(self, datasets: Sequence, *,
            fail_worker: Optional[int] = None,
            fail_after_steps: int = 1):
        """Run the job: partition → provision workers → averaging rounds →
        final averaged params land in ``self.net``. ``fail_worker`` /
        ``fail_after_steps`` inject a worker crash (tests)."""
        tm = self.tm
        parts = self._partition(datasets)
        if not parts:
            raise ValueError("no datasets to fit")
        n = len(parts)
        tracer = get_tracer()
        with tracer.span("scaleout_job", attrs={"workers": n}) as job_span:
            # the job root span's context rides the hub's KIND_SPANCTX
            # frames to every worker — thread, process, or remote host
            hub = ParamAveragingHub(
                n_workers=n, worker_timeout=tm.worker_timeout,
                on_round=self._checkpoint(self.net.clone()),
                span_ctx=job_span.context).start()

            replicas = [self.net.clone() for _ in range(n)]
            threads = []
            errors: List[BaseException] = []

            def run(wid, replica, part):
                try:
                    worker_main(hub.address, replica, part,
                                tm.averaging_frequency, tm.epochs_per_fit,
                                fail_after_steps if wid == fail_worker
                                else None,
                                worker_id=wid)
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)

            for wid, (replica, part) in enumerate(zip(replicas, parts)):
                t = threading.Thread(target=run, args=(wid, replica, part),
                                     daemon=True,
                                     name=f"dl4j-tpu-worker-{wid}")
                t.start()
                threads.append(t)
            for t in threads:
                t.join()
            final = hub.result(timeout=tm.worker_timeout)
            job_span.set_attr("rounds", hub.rounds)
            job_span.set_attr("dropped", list(hub.dropped))
        if final is None:
            raise RuntimeError(
                "scaleout job produced no averaged parameters (every worker "
                f"failed before the first round; errors: {errors})")
        self.net.set_params_flat(final)
        self.rounds = hub.rounds
        self.dropped_workers = hub.dropped
        return self.net


SparkComputationGraph = SparkDl4jMultiLayer   # CG has the same flat-params
# contract (params_flat/set_params_flat/clone/fit) — one driver serves both
