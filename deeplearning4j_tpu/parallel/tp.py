"""Tensor parallelism for user-built networks — reusable column/row-parallel
layers + a sharding resolver for any MultiLayerNetwork / ComputationGraph.

Reference counterpart: none in DL4J (its scaleout is data-parallel only);
Megatron-LM defined the column/row split this module names. TPU-native
design: a layer DECLARES PartitionSpecs for its params (``param_pspecs``),
``network_param_shardings`` assembles the matching NamedSharding pytree for
the whole net, and GSPMD inserts the all-reduces when the ordinary jitted
train step runs over a mesh with a 'tp' axis — no hand-written collectives,
and the same layer runs unsharded on a single device (the specs are just
ignored). ``ParallelWrapper`` picks these shardings up automatically, so
``ParallelWrapper(net, mesh=make_mesh(dp=2, tp=2))`` tensor-parallelizes
any user model built from these layers.

Math note (why no explicit collective appears): Column ⊗ Row is the
Megatron pairing — a column-parallel Dense (W sharded (None, 'tp'))
produces activations sharded on the feature axis; feeding them into a
row-parallel Dense (W sharded ('tp', None)) makes each device compute a
partial product that XLA finishes with one psum, exactly the hand-written
Megatron f/g functions.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.layers.attention import SelfAttentionLayer
from ..nn.layers.conv import ConvolutionLayer
from ..nn.layers.core import (DenseLayer, EmbeddingLayer,
                              EmbeddingSequenceLayer, OutputLayer)


@dataclass
class ColumnParallelDense(DenseLayer):
    """Dense with W sharded over output features: W (nIn, nOut/tp) per
    device; bias sharded likewise. Output activations come out
    feature-sharded — pair with a RowParallelDense downstream."""

    def param_pspecs(self):
        return {"W": P(None, "tp"), "b": P("tp")}


@dataclass
class RowParallelDense(DenseLayer):
    """Dense with W sharded over input features: consumes feature-sharded
    activations; XLA psums the partial products (Megatron 'g')."""

    def param_pspecs(self):
        return {"W": P("tp", None), "b": P()}


@dataclass
class ColumnParallelOutputLayer(OutputLayer):
    """Output layer with a column-parallel projection (e.g. a large
    vocab/classify head sharded over classes)."""

    def param_pspecs(self):
        return {"W": P(None, "tp"), "b": P("tp")}


@dataclass
class RowShardedEmbedding(EmbeddingLayer):
    """Embedding table sharded over the VOCAB axis: W (vocab/tp, nOut) per
    device — vocab is the natural tp axis for LM embeddings (the table
    dominates memory; each id lives on exactly one shard and GSPMD turns
    the jnp.take into a one-hot-partial + psum, Megatron's
    VocabParallelEmbedding). Requires vocab % tp == 0 to shard (degrades
    to replicated otherwise, like every spec here)."""

    def param_pspecs(self):
        return {"W": P("tp", None), "b": P()}


@dataclass
class RowShardedEmbeddingSequence(EmbeddingSequenceLayer):
    """Sequence variant of RowShardedEmbedding ((B, T) ids → (B, T, nOut))."""

    def param_pspecs(self):
        return {"W": P("tp", None), "b": P()}


@dataclass
class ChannelShardedConvolution(ConvolutionLayer):
    """Conv2D with the kernel sharded over OUTPUT channels: W HWIO
    (kh, kw, cin, cout/tp), bias (cout/tp) — the column-parallel split for
    CNNs. Activations come out channel-sharded; stack these and XLA keeps
    the channel sharding flowing through the whole conv trunk (channel-last
    NHWC makes the sharded dim the last one, the TPU-friendly layout)."""

    def param_pspecs(self):
        return {"W": P(None, None, None, "tp"), "b": P("tp")}


@dataclass
class InputChannelShardedConvolution(ConvolutionLayer):
    """Conv2D sharded over INPUT channels: W (kh, kw, cin/tp, cout) — the
    row-parallel pairing; consumes channel-sharded activations, XLA psums
    the partial channel contractions (Megatron 'g' for convs)."""

    def param_pspecs(self):
        return {"W": P(None, None, "tp", None), "b": P()}

    def validate_tp(self, mesh: Mesh):
        if self.groups != 1 and mesh.shape.get("tp", 1) > 1:
            raise ValueError(
                "InputChannelShardedConvolution: grouped/depthwise convs "
                "cannot row-shard input channels (each group's channels "
                "must stay together); use ChannelShardedConvolution")


@dataclass
class ShardedSelfAttention(SelfAttentionLayer):
    """Multi-head attention with Megatron head sharding: Q/K/V projections
    column-parallel (heads split over 'tp'), output projection
    row-parallel. Requires n_heads % tp == 0 for an even head split —
    enforced at sharding resolution (``validate_tp``), since the mesh
    isn't known at construction."""

    def param_pspecs(self):
        return {"Wq": P(None, "tp"), "Wk": P(None, "tp"),
                "Wv": P(None, "tp"), "Wo": P("tp", None)}

    def validate_tp(self, mesh: Mesh):
        tp = mesh.shape.get("tp", 1)
        if tp > 1 and self.n_heads % tp:
            raise ValueError(
                f"ShardedSelfAttention needs n_heads ({self.n_heads}) "
                f"divisible by tp ({tp}); an uneven split cuts through a "
                "head and forces cross-device resharding in every "
                "attention reshape")


def _resolve_spec(mesh: Mesh, spec):
    """Drop axes the mesh doesn't have so specs degrade gracefully."""
    return P(*(a if (a is None or a in mesh.axis_names) else None
               for a in spec))


def layer_param_shardings(mesh: Mesh, layer, params):
    """Sharding pytree for ONE layer's params: declared pspecs where the
    shapes divide, replicated otherwise."""
    specs = getattr(layer, "param_pspecs", lambda: {})() or {}
    validate = getattr(layer, "validate_tp", None)
    if validate is not None:
        validate(mesh)
    rep = NamedSharding(mesh, P())

    def sh(key, leaf):
        spec = specs.get(key)
        if spec is None or not hasattr(leaf, "shape"):
            return rep
        spec = _resolve_spec(mesh, spec)
        for dim, ax in zip(leaf.shape, tuple(spec) + (None,) * leaf.ndim):
            if ax is not None and dim % mesh.shape[ax] != 0:
                return rep   # indivisible — keep replicated rather than fail
        return NamedSharding(mesh, spec)

    return {k: (sh(k, v) if hasattr(v, "shape")
                else jax.tree_util.tree_map(lambda _: rep, v))
            for k, v in params.items()}


def network_param_shardings(mesh: Mesh, net):
    """NamedSharding pytree for a whole MultiLayerNetwork (params keyed
    'layer_i') or ComputationGraph (params keyed by node name)."""
    out = {}
    if hasattr(net, "layers") and isinstance(net.params, dict) \
            and all(k.startswith("layer_") for k in net.params):
        for i, layer in enumerate(net.layers):
            key = f"layer_{i}"
            out[key] = layer_param_shardings(mesh, layer, net.params[key])
        return out
    # ComputationGraph: conf.nodes[name].op is the layer
    for name, p in net.params.items():
        node = net.conf.nodes.get(name)
        op = getattr(node, "op", None)
        out[name] = layer_param_shardings(mesh, op, p) if op is not None \
            else jax.tree_util.tree_map(
                lambda _: NamedSharding(mesh, P()), p)
    return out
