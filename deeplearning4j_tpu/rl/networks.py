"""Small policy/value MLPs built from the framework's own DenseLayer —
the analogue of RL4J's ``DQNFactoryStdDense`` / ``ActorCriticFactory
SeparateStdDense`` (RL4J builds DL4J MultiLayerNetworks; we build a
pure (init, apply) pair over the same layer objects).
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..nn.layers.base import Ctx
from ..nn.layers.core import DenseLayer


def build_mlp(sizes: Sequence[int], activation: str = "relu",
              final_activation: str = "identity"):
    """sizes = (in, h1, ..., out) → (init(key) -> params, apply(params, x) -> y)."""
    layers = []
    for i, (a, b) in enumerate(zip(sizes, sizes[1:])):
        act = activation if i < len(sizes) - 2 else final_activation
        layers.append(DenseLayer(n_in=a, n_out=b, activation=act))

    def init(key):
        params = []
        shape = (sizes[0],)
        for layer in layers:
            key, sub = jax.random.split(key)
            p, _, shape = layer.init(sub, shape)
            params.append(p)
        return params

    def apply(params, x):
        h = x
        ctx = Ctx(train=False, rng=None)
        for layer, p in zip(layers, params):
            h, _ = layer.apply(p, {}, h, ctx)
        return h

    return init, apply


def build_actor_critic(obs_dim: int, n_actions: int,
                       hidden: Sequence[int] = (64, 64)):
    """Shared torso, two heads: (init, policy_logits_fn, value_fn combined).

    apply(params, obs) -> (logits (B, A), value (B,)).
    """
    torso_sizes = (obs_dim, *hidden)
    t_init, t_apply = build_mlp(torso_sizes, final_activation="tanh",
                                activation="tanh")
    p_head = DenseLayer(n_in=hidden[-1], n_out=n_actions, activation="identity")
    v_head = DenseLayer(n_in=hidden[-1], n_out=1, activation="identity")

    def init(key):
        k1, k2, k3 = jax.random.split(key, 3)
        pp, _, _ = p_head.init(k2, (hidden[-1],))
        vp, _, _ = v_head.init(k3, (hidden[-1],))
        return {"torso": t_init(k1), "pi": pp, "v": vp}

    def apply(params, obs):
        h = t_apply(params["torso"], obs)
        ctx = Ctx(train=False, rng=None)
        logits, _ = p_head.apply(params["pi"], {}, h, ctx)
        value, _ = v_head.apply(params["v"], {}, h, ctx)
        return logits, value[..., 0]

    return init, apply
