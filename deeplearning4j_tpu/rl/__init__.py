"""deeplearning4j_tpu.rl — RL4J-lite: DQN/DoubleDQN, A2C, A3C, replay, envs."""

from .a2c import A2C, A2CConfiguration
from .a3c import A3C, A3CConfiguration, A3CDiscrete
from .policies import BoltzmannPolicy, DQNPolicy, EpsGreedy, Policy
from .async_nstep_q import (AsyncNStepQLearning,
                            AsyncNStepQLearningConfiguration,
                            AsyncNStepQLearningDiscrete)
from .dqn import DQN, QLearningConfiguration
from .env import (CartPoleEnv, Environment, VectorizedCartPole, cartpole_init,
                  cartpole_step)
from .networks import build_actor_critic, build_mlp
from .replay import ReplayBuffer
