"""Action-selection policies.

Reference parity: ``org.deeplearning4j.rl4j.policy`` — `Policy` (play),
`DQNPolicy` (greedy), `EpsGreedy` (annealed exploration wrapper),
`BoltzmannPolicy` (softmax over Q with temperature).

Policies wrap any ``q_fn(obs) -> (A,) values`` callable (e.g. a DQN's
network or ``AsyncNStepQLearning.params`` via a lambda) and select
discrete actions; the softmax/argmax math runs through jax so a policy
can also be vmapped inside a jitted rollout.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Policy:
    """Base: next_action(obs) + play(env) rollout scoring."""

    def next_action(self, obs, key=None) -> int:
        raise NotImplementedError

    def play(self, env, max_steps: int = 1000, seed: int = 0) -> float:
        """Run one episode, returning the cumulative reward (reference
        Policy.play)."""
        key = jax.random.PRNGKey(seed)
        obs = env.reset()
        total = 0.0
        for _ in range(max_steps):
            key, sub = jax.random.split(key)
            out = env.step(self.next_action(obs, sub))
            obs, reward, done = out[0], out[1], out[2]   # (+info) gym-style
            total += float(reward)
            if done:
                break
        return total


class DQNPolicy(Policy):
    """Greedy argmax over Q (reference DQNPolicy)."""

    def __init__(self, q_fn: Callable):
        self.q_fn = q_fn

    def next_action(self, obs, key=None) -> int:
        return int(jnp.argmax(self.q_fn(jnp.asarray(obs)), -1))


class EpsGreedy(Policy):
    """Annealed eps-greedy wrapper around another policy (reference
    EpsGreedy: epsilonNbStep linear anneal from eps=1 to min_epsilon)."""

    def __init__(self, inner: Policy, n_actions: int,
                 eps_start: float = 1.0, min_epsilon: float = 0.1,
                 anneal_steps: int = 10000):
        self.inner = inner
        self.n_actions = n_actions
        self.eps_start, self.min_eps = eps_start, min_epsilon
        self.anneal_steps = max(1, anneal_steps)
        self.step_count = 0

    def epsilon(self) -> float:
        frac = min(1.0, self.step_count / self.anneal_steps)
        return self.eps_start + (self.min_eps - self.eps_start) * frac

    def next_action(self, obs, key=None) -> int:
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        k1, k2 = jax.random.split(key)
        eps = self.epsilon()
        self.step_count += 1
        if float(jax.random.uniform(k1)) < eps:
            return int(jax.random.randint(k2, (), 0, self.n_actions))
        return self.inner.next_action(obs, k2)


class BoltzmannPolicy(Policy):
    """Sample actions ∝ softmax(Q / temperature) (reference
    BoltzmannPolicy); temperature → 0 approaches greedy."""

    def __init__(self, q_fn: Callable, temperature: float = 1.0):
        if temperature <= 0:
            raise ValueError("temperature must be > 0")
        self.q_fn = q_fn
        self.temperature = float(temperature)

    def next_action(self, obs, key=None) -> int:
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        logits = self.q_fn(jnp.asarray(obs)) / self.temperature
        return int(jax.random.categorical(key, logits))
