"""Asynchronous Advantage Actor-Critic — parity with RL4J's
``org.deeplearning4j.rl4j.learning.async.a3c.discrete.A3CDiscrete`` /
``AsyncLearning`` (the Hogwild actor-thread pool + shared global network).

TPU-first redesign of the async thread pool. The reference spawns
``numThreads`` CPU workers; each holds a STALE local copy of the global
network, rolls out ``nStep`` transitions in its own env, computes a
gradient against its local copy, pushes it into the shared updater, then
pulls fresh globals. We reproduce exactly that update discipline as one
XLA program per iteration:

1. all W workers roll out and differentiate **in parallel** (``vmap``)
   against their own local (stale) parameter copies — a stacked pytree
   with a leading worker axis;
2. a sequential ``lax.scan`` over workers applies each worker's gradient
   through the SHARED optax optimizer state onto the global params —
   worker k's update sees the globals already moved by workers < k,
   computed from params that did not include those moves (true Hogwild
   gradient staleness, deterministic rather than scheduler-ordered);
3. immediately after pushing, each worker pulls the then-current globals
   as its next local copy (the reference's post-push sync), so worker 0
   runs the next rollout one-to-W updates staler than worker W-1.

The rollout/returns/loss estimator is shared with the synchronous A2C
via :mod:`.actor_critic`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import jax
import jax.numpy as jnp
import optax

from .actor_critic import (DiscretePolicyMixin, actor_critic_loss,
                           make_rollout, nstep_returns)
from .env import cartpole_init, cartpole_step
from .networks import build_actor_critic


@dataclass
class A3CConfiguration:
    gamma: float = 0.99
    learning_rate: float = 7e-4
    n_workers: int = 8              # reference numThreads
    n_envs_per_worker: int = 2      # envs stepped by each worker's rollout
    rollout_length: int = 16        # reference nStep (t_max)
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    max_grad_norm: float = 0.5
    seed: int = 0
    hidden: Sequence[int] = (64, 64)


class A3C(DiscretePolicyMixin):
    """A3CDiscrete analogue: Hogwild workers as a vmapped+scanned XLA program."""

    def __init__(self, config: A3CConfiguration = None,
                 env_init=cartpole_init, env_step=cartpole_step,
                 obs_dim: int = 4, n_actions: int = 2):
        self.cfg = cfg = config or A3CConfiguration()
        init_fn, self._ac_fn = build_actor_critic(obs_dim, n_actions, cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        pkey, self._key = jax.random.split(key)
        self.params = init_fn(pkey)                       # the GLOBAL network
        self._opt = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm),
                                optax.adam(cfg.learning_rate))
        self._opt_state = self._opt.init(self.params)     # the SHARED updater
        # every worker starts in sync with the globals
        self._locals = jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(p, (cfg.n_workers,) + p.shape),
            self.params)

        ac_fn, opt = self._ac_fn, self._opt
        W, E, T = cfg.n_workers, cfg.n_envs_per_worker, cfg.rollout_length
        rollout = make_rollout(ac_fn, env_step, env_init, E, T)
        loss_fn = actor_critic_loss(ac_fn, cfg.value_coef, cfg.entropy_coef)

        def worker_grad(local_params, states, key):
            """One worker: nStep rollout on its own envs with its own stale
            params → (gradient, done count, final env states)."""
            states, key, (obs, actions, rew, done) = rollout(
                local_params, states, key)
            _, boot = ac_fn(local_params, states)                  # V(s_T)
            returns = nstep_returns(cfg.gamma, boot, rew, done)
            flat = lambda a: a.reshape((T * E,) + a.shape[2:])
            grads = jax.grad(
                lambda p: loss_fn(p, flat(obs), flat(actions),
                                  flat(returns))[0])(local_params)
            return grads, done.sum(), states

        @jax.jit
        def iteration(global_params, opt_state, locals_, states, key):
            keys = jax.random.split(key, W + 1)
            # 1. parallel actors: every worker differentiates vs ITS params
            grads, dones, states = jax.vmap(worker_grad)(
                locals_, states, keys[:W])

            # 2+3. async apply: push each worker's (stale) gradient through
            # the shared updater in worker order, then that worker pulls the
            # fresh globals — lax.scan carries (globals, opt_state)
            def push_pull(carry, g):
                gp, os_ = carry
                updates, os_ = opt.update(g, os_, gp)
                gp = optax.apply_updates(gp, updates)
                return (gp, os_), gp
            (global_params, opt_state), new_locals = jax.lax.scan(
                push_pull, (global_params, opt_state), grads)
            return global_params, opt_state, new_locals, states, \
                keys[W], dones.sum()

        self._iteration = iteration
        self._env_init = env_init

    def train(self, iterations: int) -> List[float]:
        """Returns episode terminations per iteration (lower = better: the
        vectorised cartpole pays 1/step, so fewer resets = longer balancing)."""
        cfg = self.cfg
        self._key, rkey = jax.random.split(self._key)
        states = jax.vmap(lambda k: jax.vmap(self._env_init)(
            jax.random.split(k, cfg.n_envs_per_worker)))(
            jax.random.split(rkey, cfg.n_workers))       # (W, E, obs)
        dones = []
        for _ in range(iterations):
            (self.params, self._opt_state, self._locals, states,
             self._key, d) = self._iteration(
                self.params, self._opt_state, self._locals, states, self._key)
            dones.append(float(d))
        return dones


A3CDiscrete = A3C  # reference class-name alias
