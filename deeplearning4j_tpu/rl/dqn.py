"""DQN / Double-DQN — parity with RL4J's
``org.deeplearning4j.rl4j.learning.sync.qlearning.discrete.QLearningDiscrete``
(+ ``QLearningConfiguration``: gamma, epsilon annealing, target-network
sync, replay warmup, reward clipping).

TPU-first: the TD update — forward both nets, build targets, Huber loss,
grads, optimizer — is ONE jitted function with donated params/opt-state.
Action selection jits the Q-forward; the env/replay loop stays on host
(that part is inherently sequential IO, exactly like the reference).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .env import Environment
from .networks import build_mlp
from .replay import ReplayBuffer


@dataclass
class QLearningConfiguration:
    """Reference QLearningConfiguration surface."""

    gamma: float = 0.99
    learning_rate: float = 1e-3
    batch_size: int = 64
    buffer_size: int = 10000
    warmup_steps: int = 500          # reference expRepPlay start size
    target_update_freq: int = 250    # reference targetDqnUpdateFreq
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_steps: int = 3000      # linear anneal (reference epsilonNbStep)
    double_dqn: bool = True
    reward_clip: Optional[float] = None  # reference rewardFactor/clip
    max_episode_steps: int = 500
    seed: int = 0
    hidden: Sequence[int] = (64, 64)


class DQN:
    """Synchronous deep Q-learning over a discrete-action Environment."""

    def __init__(self, env: Environment, config: QLearningConfiguration = None):
        self.env = env
        self.cfg = config or QLearningConfiguration()
        cfg = self.cfg
        obs_dim = int(np.prod(env.observation_shape))
        self._init_fn, self._q_fn = build_mlp(
            (obs_dim, *cfg.hidden, env.action_space_size))
        key = jax.random.PRNGKey(cfg.seed)
        self.params = self._init_fn(key)
        self.target_params = jax.tree_util.tree_map(lambda a: a, self.params)
        self._opt = optax.adam(cfg.learning_rate)
        self._opt_state = self._opt.init(self.params)
        self.buffer = ReplayBuffer(cfg.buffer_size, (obs_dim,), seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._steps = 0
        self.episode_rewards: List[float] = []

        q_fn, opt, gamma, double = self._q_fn, self._opt, cfg.gamma, cfg.double_dqn

        def td_loss(params, target_params, batch):
            q = q_fn(params, batch["obs"])                              # (B, A)
            q_sel = jnp.take_along_axis(q, batch["actions"][:, None], 1)[:, 0]
            q_next_t = q_fn(target_params, batch["next_obs"])           # (B, A)
            if double:
                a_star = jnp.argmax(q_fn(params, batch["next_obs"]), axis=1)
                q_next = jnp.take_along_axis(q_next_t, a_star[:, None], 1)[:, 0]
            else:
                q_next = q_next_t.max(axis=1)
            target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
                jax.lax.stop_gradient(q_next)
            return optax.huber_loss(q_sel, target).mean()

        @jax.jit
        def update(params, target_params, opt_state, batch):
            loss, grads = jax.value_and_grad(td_loss)(params, target_params, batch)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        # jitted TD step (no donation: params and target_params alias the
        # same buffers right after a target sync, and XLA rejects donating
        # a buffer that is also a live input)
        self._update = jax.jit(update)
        self._q_jit = jax.jit(lambda p, x: q_fn(p, x))

    # ------------------------------------------------------------------ api
    def epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self._steps / max(cfg.eps_decay_steps, 1))
        return cfg.eps_start + frac * (cfg.eps_end - cfg.eps_start)

    def q_values(self, obs):
        """Q(obs) -> (A,) values — the hook policies wrap (DQNPolicy(
        agent.q_values))."""
        return self._q_jit(self.params, jnp.asarray(obs)[None, :])[0]

    def act(self, obs, greedy: bool = False) -> int:
        if not greedy and self._rng.random() < self.epsilon():
            return int(self._rng.integers(self.env.action_space_size))
        return int(jnp.argmax(self.q_values(obs)))

    def train(self, episodes: int, callback: Optional[Callable] = None) -> List[float]:
        """Reference QLearningDiscrete.train — returns per-episode rewards."""
        cfg = self.cfg
        for _ in range(episodes):
            obs = self.env.reset().ravel()
            ep_reward, done, t = 0.0, False, 0
            while not done and t < cfg.max_episode_steps:
                a = self.act(obs)
                nxt, r, done, info = self.env.step(a)
                nxt = np.asarray(nxt).ravel()
                ep_reward += r
                if cfg.reward_clip is not None:
                    r = float(np.clip(r, -cfg.reward_clip, cfg.reward_clip))
                # truncation is not failure: don't bootstrap-terminate on it
                store_done = done and not info.get("truncated", False)
                self.buffer.add(obs, a, r, nxt, store_done)
                obs = nxt
                self._steps += 1
                t += 1
                if len(self.buffer) >= cfg.warmup_steps:
                    batch = {k: jnp.asarray(v)
                             for k, v in self.buffer.sample(cfg.batch_size).items()}
                    self.params, self._opt_state, _ = self._update(
                        self.params, self.target_params, self._opt_state, batch)
                if self._steps % cfg.target_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        jnp.copy, self.params)
            self.episode_rewards.append(ep_reward)
            if callback:
                callback(self, ep_reward)
        return self.episode_rewards

    def play(self, max_steps: int = 500) -> float:
        """One greedy episode (reference Policy.play)."""
        obs = self.env.reset().ravel()
        total, done, t = 0.0, False, 0
        while not done and t < max_steps:
            obs, r, done, _ = self.env.step(self.act(obs, greedy=True))
            obs = np.asarray(obs).ravel()
            total += r
            t += 1
        return total
