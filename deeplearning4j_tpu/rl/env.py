"""RL environments — parity with RL4J's ``org.deeplearning4j.rl4j.mdp.MDP``
protocol (reset/step/isDone, discrete action space) and its CartPole family
of toy control tasks.

TPU-first redesign: the physics is a *pure jax function*
``(state, action) -> (state, reward, done)`` so whole rollouts run
on-device under ``lax.scan`` and across ``vmap``-vectorised env batches —
RL4J steps one Java env object per thread; we step N envs per XLA program.
A small gym-like host wrapper keeps the familiar imperative API.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Environment:
    """Gym/RL4J-style protocol: reset() → obs; step(a) → (obs, r, done, info)."""

    observation_shape: Tuple[int, ...] = ()
    action_space_size: int = 0

    def reset(self):
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError


# ------------------------------------------------------------------ cartpole
# Classic control constants (match the canonical CartPole-v1 task RL4J wraps).
_GRAVITY = 9.8
_MASS_CART = 1.0
_MASS_POLE = 0.1
_TOTAL_MASS = _MASS_CART + _MASS_POLE
_LENGTH = 0.5
_POLEMASS_LENGTH = _MASS_POLE * _LENGTH
_FORCE_MAG = 10.0
_TAU = 0.02
_THETA_LIMIT = 12 * 2 * np.pi / 360
_X_LIMIT = 2.4


def cartpole_init(key) -> jnp.ndarray:
    """Uniform(-0.05, 0.05) start state (x, x_dot, theta, theta_dot)."""
    return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)


def cartpole_step(state: jnp.ndarray, action) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pure Euler-integrated cartpole step. action ∈ {0, 1}.

    Returns (next_state, reward, done). Jit/vmap/scan-safe: no Python
    branching, `done` is a bool array the caller folds into its rollout.
    """
    x, x_dot, theta, theta_dot = state[0], state[1], state[2], state[3]
    force = jnp.where(action == 1, _FORCE_MAG, -_FORCE_MAG)
    cos_t, sin_t = jnp.cos(theta), jnp.sin(theta)
    temp = (force + _POLEMASS_LENGTH * theta_dot ** 2 * sin_t) / _TOTAL_MASS
    theta_acc = (_GRAVITY * sin_t - cos_t * temp) / (
        _LENGTH * (4.0 / 3.0 - _MASS_POLE * cos_t ** 2 / _TOTAL_MASS))
    x_acc = temp - _POLEMASS_LENGTH * theta_acc * cos_t / _TOTAL_MASS
    x = x + _TAU * x_dot
    x_dot = x_dot + _TAU * x_acc
    theta = theta + _TAU * theta_dot
    theta_dot = theta_dot + _TAU * theta_acc
    nxt = jnp.stack([x, x_dot, theta, theta_dot])
    done = (jnp.abs(x) > _X_LIMIT) | (jnp.abs(theta) > _THETA_LIMIT)
    return nxt, jnp.asarray(1.0, nxt.dtype), done


class CartPoleEnv(Environment):
    """Host wrapper over the pure physics — RL4J's CartPole MDP analogue."""

    observation_shape = (4,)
    action_space_size = 2

    def __init__(self, seed: int = 0, max_steps: int = 500):
        self._key = jax.random.PRNGKey(seed)
        self.max_steps = max_steps
        self._t = 0
        self._state = None
        self._step_jit = jax.jit(cartpole_step)

    def reset(self):
        self._key, sub = jax.random.split(self._key)
        self._state = cartpole_init(sub)
        self._t = 0
        return np.asarray(self._state)

    def step(self, action):
        nxt, r, done = self._step_jit(self._state, jnp.asarray(action))
        self._state = nxt
        self._t += 1
        # a step that physically terminates is NOT a truncation, even at the cap
        trunc = (not bool(done)) and self._t >= self.max_steps
        return np.asarray(nxt), float(r), bool(done) or trunc, {"truncated": trunc}


@dataclass
class VectorizedCartPole:
    """N independent cartpoles as ONE on-device batch — the TPU-native env.

    ``reset(key) -> states (N,4)``; ``step(states, actions) ->
    (states', rewards, dones)`` with auto-reset of finished envs, all pure,
    so an entire A2C rollout is a single ``lax.scan``.
    """

    n_envs: int = 8

    def reset(self, key):
        return jax.vmap(cartpole_init)(jax.random.split(key, self.n_envs))

    def step(self, states, actions, key):
        nxt, r, done = jax.vmap(cartpole_step)(states, actions)
        fresh = jax.vmap(cartpole_init)(jax.random.split(key, self.n_envs))
        nxt = jnp.where(done[:, None], fresh, nxt)   # auto-reset
        return nxt, r, done
