"""Asynchronous n-step Q-learning — parity with RL4J's
``org.deeplearning4j.rl4j.learning.async.nstep.discrete
.AsyncNStepQLearningDiscrete`` (the Hogwild counterpart of A3C with an
eps-greedy Q policy and a periodically-synced target network).

TPU-first redesign mirrors :mod:`.a3c`: the reference's ``numThreads``
CPU workers become one XLA program per iteration — a ``vmap`` over
workers, each rolling out ``nStep`` transitions in its own envs with its
own STALE local Q-network, then a sequential ``lax.scan`` pushing each
worker's gradient through the SHARED optimizer (true Hogwild staleness,
deterministic order), after which each worker pulls the fresh globals.
The n-step target bootstraps from a TARGET network copied from the
globals every ``target_update_freq`` iterations (reference
``targetDqnUpdateFreq``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import jax
import jax.numpy as jnp
import optax

from .actor_critic import nstep_returns
from .env import cartpole_init, cartpole_step
from .networks import build_mlp


@dataclass
class AsyncNStepQLearningConfiguration:
    gamma: float = 0.99
    learning_rate: float = 1e-3
    n_workers: int = 8              # reference numThreads
    n_envs_per_worker: int = 2
    rollout_length: int = 8         # reference nStep
    eps_start: float = 1.0          # eps-greedy anneal (reference epsilon)
    eps_end: float = 0.05
    eps_anneal_iters: int = 150
    target_update_freq: int = 20    # reference targetDqnUpdateFreq (iters)
    max_grad_norm: float = 1.0
    seed: int = 0
    hidden: Sequence[int] = (64, 64)


class AsyncNStepQLearning:
    """AsyncNStepQLearningDiscrete analogue over vectorized envs."""

    def __init__(self, config: AsyncNStepQLearningConfiguration = None,
                 env_init=cartpole_init, env_step=cartpole_step,
                 obs_dim: int = 4, n_actions: int = 2):
        self.cfg = cfg = config or AsyncNStepQLearningConfiguration()
        self.n_actions = n_actions
        init_fn, self._q_fn = build_mlp((obs_dim, *cfg.hidden, n_actions))
        key = jax.random.PRNGKey(cfg.seed)
        pkey, self._key = jax.random.split(key)
        self.params = init_fn(pkey)                      # global Q network
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self._opt = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm),
                                optax.adam(cfg.learning_rate))
        self._opt_state = self._opt.init(self.params)
        self._locals = jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(p, (cfg.n_workers,) + p.shape),
            self.params)

        q_fn, opt = self._q_fn, self._opt
        W, E, T = cfg.n_workers, cfg.n_envs_per_worker, cfg.rollout_length

        def rollout(local_params, states, key, eps):
            """T eps-greedy steps on E envs. Returns trajectories + final
            states (auto-reset on done, like the vectorized cartpole)."""
            def step(carry, _):
                st, k = carry
                k, ka, kr = jax.random.split(k, 3)
                obs = st
                q = q_fn(local_params, obs)                  # (E, A)
                greedy = jnp.argmax(q, -1)
                rand = jax.random.randint(ka, (E,), 0, n_actions)
                explore = jax.random.bernoulli(kr, eps, (E,))
                act = jnp.where(explore, rand, greedy)
                nxt, rew, done = jax.vmap(env_step)(st, act)
                k, kreset = jax.random.split(k)
                fresh = jax.vmap(env_init)(jax.random.split(kreset, E))
                nxt = jnp.where(done[:, None], fresh, nxt)
                return (nxt, k), (obs, act, rew, done)
            (states, key), traj = jax.lax.scan(step, (states, key),
                                               None, length=T)
            return states, key, traj

        def worker_grad(local_params, target_params, states, key, eps):
            states, key, (obs, act, rew, done) = rollout(
                local_params, states, key, eps)
            boot = jnp.max(q_fn(target_params, states), -1)   # V_target(s_T)
            returns = nstep_returns(cfg.gamma, boot, rew, done)  # (T, E)
            flat_obs = obs.reshape((T * E,) + obs.shape[2:])
            flat_act = act.reshape(T * E)
            flat_ret = returns.reshape(T * E)

            def loss(p):
                q = q_fn(p, flat_obs)
                qa = jnp.take_along_axis(q, flat_act[:, None], 1)[:, 0]
                return jnp.mean(optax.huber_loss(qa, flat_ret))

            l, grads = jax.value_and_grad(loss)(local_params)
            return grads, l, done.sum(), states

        @jax.jit
        def iteration(global_params, target_params, opt_state, locals_,
                      states, key, eps):
            keys = jax.random.split(key, W + 1)
            grads, losses, dones, states = jax.vmap(
                worker_grad, in_axes=(0, None, 0, 0, None))(
                locals_, target_params, states, keys[:W], eps)

            def push_pull(carry, g):
                gp, os_ = carry
                updates, os_ = opt.update(g, os_, gp)
                gp = optax.apply_updates(gp, updates)
                return (gp, os_), gp
            (global_params, opt_state), new_locals = jax.lax.scan(
                push_pull, (global_params, opt_state), grads)
            return (global_params, opt_state, new_locals, states, keys[W],
                    dones.sum(), losses.mean())

        self._iteration = iteration
        self._env_init = env_init
        self._iter_count = 0

    def epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self._iter_count / max(1, cfg.eps_anneal_iters))
        return float(cfg.eps_start + (cfg.eps_end - cfg.eps_start) * frac)

    def choose_action(self, obs) -> int:
        """Greedy policy for play (reference DQNPolicy)."""
        q = self._q_fn(self.params, jnp.asarray(obs)[None])
        return int(jnp.argmax(q, -1)[0])

    def train(self, iterations: int) -> List[float]:
        """Returns episode terminations per iteration (lower = better on
        the vectorized cartpole: fewer resets = longer balancing)."""
        cfg = self.cfg
        self._key, rkey = jax.random.split(self._key)
        states = jax.vmap(lambda k: jax.vmap(self._env_init)(
            jax.random.split(k, cfg.n_envs_per_worker)))(
            jax.random.split(rkey, cfg.n_workers))
        dones = []
        for _ in range(iterations):
            (self.params, self._opt_state, self._locals, states, self._key,
             d, _) = self._iteration(
                self.params, self.target_params, self._opt_state,
                self._locals, states, self._key, self.epsilon())
            self._iter_count += 1
            if self._iter_count % cfg.target_update_freq == 0:
                self.target_params = jax.tree_util.tree_map(
                    jnp.copy, self.params)
            dones.append(float(d))
        return dones


AsyncNStepQLearningDiscrete = AsyncNStepQLearning  # reference alias
