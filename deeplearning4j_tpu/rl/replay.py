"""Experience replay — parity with RL4J's
``org.deeplearning4j.rl4j.learning.sync.ExpReplay`` (circular buffer,
uniform sampling).

Host-side by design: replay is IO/memory plumbing, not compute. The
buffer is pre-allocated numpy; ``sample`` returns device-ready arrays.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_shape: Tuple[int, ...],
                 obs_dtype=np.float32, seed: int = 0):
        self.capacity = int(capacity)
        self.obs = np.zeros((capacity, *obs_shape), obs_dtype)
        self.next_obs = np.zeros((capacity, *obs_shape), obs_dtype)
        self.actions = np.zeros((capacity,), np.int32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self._i = 0
        self._full = False
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self.capacity if self._full else self._i

    def add(self, obs, action, reward, next_obs, done):
        i = self._i
        self.obs[i] = obs
        self.actions[i] = action
        self.rewards[i] = reward
        self.next_obs[i] = next_obs
        self.dones[i] = float(done)
        self._i = (i + 1) % self.capacity
        self._full = self._full or self._i == 0

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, len(self), size=batch_size)
        return {"obs": self.obs[idx], "actions": self.actions[idx],
                "rewards": self.rewards[idx], "next_obs": self.next_obs[idx],
                "dones": self.dones[idx]}
