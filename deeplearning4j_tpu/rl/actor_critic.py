"""Shared actor-critic machinery for A2C (synchronous) and A3C (Hogwild).

The reference splits this the same way: RL4J's ``AdvantageActorCritic``
update rule + ``ActorCriticPolicy`` play surface are shared between the
sync and async learners. Here that shared core is three pure builders —
the vmapped-env n-step rollout, the bootstrapped discounted returns, and
the policy/value/entropy loss — plus the greedy/sampling play mixin.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_rollout(ac_fn, env_step, env_init, n_envs: int, length: int):
    """(params, states, key) → (states, key, (obs, actions, rew, done)):
    ``lax.scan`` over `length` steps of `n_envs` vmapped envs, sampling
    actions from the policy and auto-resetting finished envs."""
    def rollout(params, states, key):
        def body(carry, _):
            states, key = carry
            akey, rkey, key = jax.random.split(key, 3)
            logits, _ = ac_fn(params, states)
            actions = jax.random.categorical(akey, logits)       # (n_envs,)
            nxt, rew, done = jax.vmap(env_step)(states, actions)
            fresh = jax.vmap(env_init)(jax.random.split(rkey, n_envs))
            nxt = jnp.where(done[:, None], fresh, nxt)
            out = (states, actions, rew, done.astype(jnp.float32))
            return (nxt, key), out
        (states, key), traj = jax.lax.scan(body, (states, key), None,
                                           length=length)
        return states, key, traj
    return rollout


def nstep_returns(gamma: float, bootstrap, rew, done):
    """Backward scan of n-step bootstrapped returns; `done` truncates."""
    def disc(carry, xs):
        r, d = xs
        g = r + gamma * (1.0 - d) * carry
        return g, g
    _, returns = jax.lax.scan(disc, bootstrap, (rew, done), reverse=True)
    return returns


def actor_critic_loss(ac_fn, value_coef: float, entropy_coef: float):
    """(params, obs, actions, returns) → (loss, entropy): policy gradient
    with advantage baseline, value regression, entropy bonus."""
    def loss_fn(params, obs, actions, returns):
        logits, values = ac_fn(params, obs)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, actions[:, None], 1)[:, 0]
        adv = returns - values
        policy_loss = -(jax.lax.stop_gradient(adv) * logp_a).mean()
        value_loss = jnp.square(adv).mean()
        entropy = -(jnp.exp(logp) * logp).sum(axis=1).mean()
        return (policy_loss + value_coef * value_loss
                - entropy_coef * entropy), entropy
    return loss_fn


class DiscretePolicyMixin:
    """act()/play() surface (reference ACPolicy): greedy or sampled action
    from `self.params` via `self._ac_fn`, episode playout on a host env."""

    def act(self, obs, greedy: bool = True) -> int:
        logits, _ = self._ac_fn(self.params, jnp.asarray(obs)[None, :])
        if greedy:
            return int(jnp.argmax(logits[0]))
        self._key, sub = jax.random.split(self._key)
        return int(jax.random.categorical(sub, logits[0]))

    def play(self, env, max_steps: int = 500) -> float:
        obs = env.reset()
        total, done, t = 0.0, False, 0
        while not done and t < max_steps:
            obs, r, done, _ = env.step(self.act(obs))
            total += r
            t += 1
        return total
