"""Advantage Actor-Critic — parity with RL4J's
``org.deeplearning4j.rl4j.learning.async.a3c.discrete.A3CDiscrete``.

TPU-first redesign of A3C's async CPU threads: instead of K Hogwild
actor threads each stepping its own Java env, K envs are a single
``vmap``-vectorised batch stepped inside ``lax.scan`` — the whole
n-step rollout AND the policy/value/entropy update is one XLA program
per iteration. Same estimator (n-step returns, advantage baseline,
entropy bonus), deterministic instead of asynchronously stale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import jax
import jax.numpy as jnp
import optax

from .env import cartpole_init, cartpole_step
from .networks import build_actor_critic


@dataclass
class A2CConfiguration:
    gamma: float = 0.99
    learning_rate: float = 7e-4
    n_envs: int = 8                 # reference numThreads
    rollout_length: int = 16        # reference nStep
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    max_grad_norm: float = 0.5
    seed: int = 0
    hidden: Sequence[int] = (64, 64)


class A2C:
    """A2C over the vectorised on-device cartpole (or any pure env pair)."""

    def __init__(self, config: A2CConfiguration = None,
                 env_init=cartpole_init, env_step=cartpole_step,
                 obs_dim: int = 4, n_actions: int = 2):
        self.cfg = cfg = config or A2CConfiguration()
        init_fn, self._ac_fn = build_actor_critic(obs_dim, n_actions, cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        pkey, self._key = jax.random.split(key)
        self.params = init_fn(pkey)
        self._opt = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm),
                                optax.adam(cfg.learning_rate))
        self._opt_state = self._opt.init(self.params)

        ac_fn, opt = self._ac_fn, self._opt
        N, T, gamma = cfg.n_envs, cfg.rollout_length, cfg.gamma

        def rollout(params, states, key):
            """lax.scan over T steps of N vmapped envs → trajectory batch."""
            def body(carry, _):
                states, key = carry
                akey, rkey, key = jax.random.split(key, 3)
                logits, _ = ac_fn(params, states)
                actions = jax.random.categorical(akey, logits)         # (N,)
                nxt, rew, done = jax.vmap(env_step)(states, actions)
                fresh = jax.vmap(env_init)(jax.random.split(rkey, N))
                nxt = jnp.where(done[:, None], fresh, nxt)
                out = (states, actions, rew, done.astype(jnp.float32))
                return (nxt, key), out
            (states, key), traj = jax.lax.scan(body, (states, key), None, length=T)
            return states, key, traj

        def loss_fn(params, obs, actions, returns):
            logits, values = ac_fn(params, obs)                        # (T*N, ...)
            logp = jax.nn.log_softmax(logits)
            logp_a = jnp.take_along_axis(logp, actions[:, None], 1)[:, 0]
            adv = returns - values
            policy_loss = -(jax.lax.stop_gradient(adv) * logp_a).mean()
            value_loss = jnp.square(adv).mean()
            entropy = -(jnp.exp(logp) * logp).sum(axis=1).mean()
            return (policy_loss + cfg.value_coef * value_loss
                    - cfg.entropy_coef * entropy), entropy

        @jax.jit
        def iteration(params, opt_state, states, key):
            states, key, (obs, actions, rew, done) = rollout(
                params, states, key)
            _, boot = ac_fn(params, states)                            # V(s_T)
            def disc(carry, xs):
                r, d, = xs
                g = r + gamma * (1.0 - d) * carry
                return g, g
            _, returns = jax.lax.scan(disc, boot, (rew, done), reverse=True)
            flat = lambda a: a.reshape((T * N,) + a.shape[2:])
            (loss, ent), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, flat(obs), flat(actions), flat(returns))
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # done count is the learning signal: reward is 1/step in cartpole,
            # so fewer terminations per rollout == longer episodes
            return params, opt_state, states, key, loss, done.sum()
        self._iteration = iteration
        self._env_init = env_init

    def train(self, iterations: int) -> List[float]:
        """Returns episode terminations per iteration (lower = better)."""
        cfg = self.cfg
        self._key, rkey = jax.random.split(self._key)
        states = jax.vmap(self._env_init)(jax.random.split(rkey, cfg.n_envs))
        dones = []
        for _ in range(iterations):
            self.params, self._opt_state, states, self._key, loss, d = \
                self._iteration(self.params, self._opt_state, states, self._key)
            dones.append(float(d))
        return dones

    def act(self, obs, greedy: bool = True) -> int:
        logits, _ = self._ac_fn(self.params, jnp.asarray(obs)[None, :])
        if greedy:
            return int(jnp.argmax(logits[0]))
        self._key, sub = jax.random.split(self._key)
        return int(jax.random.categorical(sub, logits[0]))

    def play(self, env, max_steps: int = 500) -> float:
        obs = env.reset()
        total, done, t = 0.0, False, 0
        while not done and t < max_steps:
            obs, r, done, _ = env.step(self.act(obs))
            total += r
            t += 1
        return total
