"""Advantage Actor-Critic, synchronous — parity with RL4J's
``AdvantageActorCritic`` update rule run as the single-learner (sync)
variant; the async Hogwild learner lives in :mod:`.a3c`.

TPU-first shape: K envs are a single ``vmap``-vectorised batch stepped
inside ``lax.scan`` — the whole n-step rollout AND the
policy/value/entropy update is one XLA program per iteration. Same
estimator as the reference (n-step returns, advantage baseline, entropy
bonus), shared with A3C via :mod:`.actor_critic`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import jax
import jax.numpy as jnp
import optax

from .actor_critic import (DiscretePolicyMixin, actor_critic_loss,
                           make_rollout, nstep_returns)
from .env import cartpole_init, cartpole_step
from .networks import build_actor_critic


@dataclass
class A2CConfiguration:
    gamma: float = 0.99
    learning_rate: float = 7e-4
    n_envs: int = 8                 # reference numThreads
    rollout_length: int = 16        # reference nStep
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    max_grad_norm: float = 0.5
    seed: int = 0
    hidden: Sequence[int] = (64, 64)


class A2C(DiscretePolicyMixin):
    """A2C over the vectorised on-device cartpole (or any pure env pair)."""

    def __init__(self, config: A2CConfiguration = None,
                 env_init=cartpole_init, env_step=cartpole_step,
                 obs_dim: int = 4, n_actions: int = 2):
        self.cfg = cfg = config or A2CConfiguration()
        init_fn, self._ac_fn = build_actor_critic(obs_dim, n_actions, cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        pkey, self._key = jax.random.split(key)
        self.params = init_fn(pkey)
        self._opt = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm),
                                optax.adam(cfg.learning_rate))
        self._opt_state = self._opt.init(self.params)

        ac_fn, opt = self._ac_fn, self._opt
        N, T = cfg.n_envs, cfg.rollout_length
        rollout = make_rollout(ac_fn, env_step, env_init, N, T)
        loss_fn = actor_critic_loss(ac_fn, cfg.value_coef, cfg.entropy_coef)

        @jax.jit
        def iteration(params, opt_state, states, key):
            states, key, (obs, actions, rew, done) = rollout(
                params, states, key)
            _, boot = ac_fn(params, states)                        # V(s_T)
            returns = nstep_returns(cfg.gamma, boot, rew, done)
            flat = lambda a: a.reshape((T * N,) + a.shape[2:])
            (loss, ent), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, flat(obs), flat(actions), flat(returns))
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # done count is the learning signal: reward is 1/step in cartpole,
            # so fewer terminations per rollout == longer episodes
            return params, opt_state, states, key, loss, done.sum()
        self._iteration = iteration
        self._env_init = env_init

    def train(self, iterations: int) -> List[float]:
        """Returns episode terminations per iteration (lower = better)."""
        cfg = self.cfg
        self._key, rkey = jax.random.split(self._key)
        states = jax.vmap(self._env_init)(jax.random.split(rkey, cfg.n_envs))
        dones = []
        for _ in range(iterations):
            self.params, self._opt_state, states, self._key, loss, d = \
                self._iteration(self.params, self._opt_state, states, self._key)
            dones.append(float(d))
        return dones
