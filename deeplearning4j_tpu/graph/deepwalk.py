"""DeepWalk — parity with
``org.deeplearning4j.graph.models.deepwalk.DeepWalk`` (random-walk corpus
→ skip-gram vertex embeddings; Builder knobs vectorSize/windowSize/
walkLength/learningRate).

The walk corpus is generated host-side (graph.random_walks) and embedded
by the shared on-device SGNS trainer via SequenceVectors — the upstream
class does exactly this composition (GraphWalkIterator feeding its
SequenceVectors superclass), with per-pair Hogwild replaced by the jitted
batch step.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..nlp.sequencevectors import SequenceVectors
from .graph import Graph, random_walks


@dataclass
class DeepWalk:
    """Vertex embeddings from uniform random walks + skip-gram/NS."""

    layer_size: int = 64          # reference vectorSize
    window_size: int = 5
    walk_length: int = 40
    walks_per_vertex: int = 10
    negative: int = 5
    learning_rate: float = 0.025
    epochs: int = 3
    batch_size: int = 2048
    seed: int = 0

    _sv: Optional[SequenceVectors] = field(default=None, repr=False)

    def fit(self, graph: Graph):
        walks = random_walks(graph, self.walk_length, self.walks_per_vertex,
                             self.seed)
        self._sv = SequenceVectors(
            layer_size=self.layer_size, window_size=self.window_size,
            negative=self.negative, learning_rate=self.learning_rate,
            epochs=self.epochs, batch_size=self.batch_size, seed=self.seed)
        self._sv.fit([list(map(int, w)) for w in walks])
        return self

    # ------------------------------------------------ query surface
    def vertex_vector(self, v: int) -> np.ndarray:
        """Reference getVertexVector."""
        return self._sv.element_vector(v)

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity_elements(a, b)

    def verts_nearest(self, v: int, top_n: int = 10) -> List[int]:
        """Nearest vertices by cosine (reference verticesNearest)."""
        return [int(w) for w in self._sv.elements_nearest(v, top_n=top_n)]

    @property
    def vectors(self) -> SequenceVectors:
        """The underlying SequenceVectors (lookup table access)."""
        return self._sv
