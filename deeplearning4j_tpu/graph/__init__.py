"""deeplearning4j_tpu.graph — graph vertex embeddings.

Parity with the ``deeplearning4j-graph`` module: a lightweight graph
structure (``org.deeplearning4j.graph.graph.Graph``), uniform random
walks (``RandomWalkIterator``), and DeepWalk vertex embeddings
(``org.deeplearning4j.graph.models.deepwalk.DeepWalk``).
"""

from .deepwalk import DeepWalk
from .graph import Graph, random_walks
