"""Graph structure + random walks — parity with
``org.deeplearning4j.graph.graph.Graph`` (adjacency-list graph over int
vertex ids) and ``org.deeplearning4j.graph.iterator.RandomWalkIterator``
(uniform next-neighbor walks of fixed length).

Walks are generated vectorised: the ragged adjacency is padded to a
(V, max_degree) neighbor matrix so ALL walks advance one step per numpy
op — the host-side analogue of stepping every walker in lock-step,
replacing the reference's per-walk iterator loop.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np


class Graph:
    """Undirected-by-default adjacency-list graph over vertices 0..V-1."""

    def __init__(self, n_vertices: int,
                 edges: Optional[Iterable[Tuple[int, int]]] = None,
                 undirected: bool = True):
        if n_vertices <= 0:
            raise ValueError(f"n_vertices must be positive, got {n_vertices}")
        self.n_vertices = n_vertices
        self.undirected = undirected
        self._adj: List[List[int]] = [[] for _ in range(n_vertices)]
        for a, b in (edges or []):
            self.add_edge(a, b)

    def add_edge(self, a: int, b: int):
        if not (0 <= a < self.n_vertices and 0 <= b < self.n_vertices):
            raise ValueError(f"edge ({a}, {b}) out of range 0..{self.n_vertices - 1}")
        self._adj[a].append(b)
        if self.undirected and a != b:
            self._adj[b].append(a)
        return self

    def neighbors(self, v: int) -> List[int]:
        return list(self._adj[v])

    def degree(self, v: int) -> int:
        return len(self._adj[v])

    def num_edges(self) -> int:
        total = sum(len(n) for n in self._adj)
        return total // 2 if self.undirected else total

    def padded_adjacency(self) -> Tuple[np.ndarray, np.ndarray]:
        """(V, max_degree) neighbor matrix padded with self-ids + (V,) degrees.

        Padding with the vertex's own id makes dead-end walks self-loop
        instead of indexing garbage (the reference's NoEdges handling is
        EXCEPTION_ON_DISCONNECTED by default; SELF_LOOP matches its
        PADDING mode and keeps the walk tensor rectangular)."""
        max_deg = max(1, max((len(n) for n in self._adj), default=1))
        nbr = np.tile(np.arange(self.n_vertices, dtype=np.int32)[:, None],
                      (1, max_deg))
        for v, ns in enumerate(self._adj):
            if ns:
                nbr[v, :len(ns)] = np.asarray(ns, np.int32)
        deg = np.asarray([max(1, len(n)) for n in self._adj], np.int32)
        return nbr, deg


def random_walks(graph: Graph, walk_length: int = 40,
                 walks_per_vertex: int = 10, seed: int = 0,
                 starts: Optional[Sequence[int]] = None) -> np.ndarray:
    """Uniform random walks, (n_walks, walk_length) int32 vertex ids.

    Every vertex starts ``walks_per_vertex`` walks (shuffled start order,
    like the reference's GraphWalkIterator epochs) unless ``starts`` is
    given explicitly.
    """
    nbr, deg = graph.padded_adjacency()
    rng = np.random.default_rng(seed)
    if starts is None:
        starts = np.tile(np.arange(graph.n_vertices, dtype=np.int32),
                         walks_per_vertex)
        rng.shuffle(starts)
    else:
        starts = np.asarray(starts, np.int32)
    walks = np.empty((len(starts), walk_length), np.int32)
    cur = starts.copy()
    walks[:, 0] = cur
    for t in range(1, walk_length):
        r = rng.integers(0, deg[cur])
        cur = nbr[cur, r]
        walks[:, t] = cur
    return walks
