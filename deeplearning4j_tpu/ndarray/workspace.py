"""Memory-workspace analogue for TPU/XLA.

Reference parity: ``org.nd4j.linalg.api.memory.MemoryWorkspace`` /
``Nd4j.getWorkspaceManager()`` — libnd4j's arena allocator that reuses
scratch buffers across iterations to avoid GC/alloc churn.

TPU-first redesign: XLA already arena-allocates every intermediate inside a
compiled program, so the workspace concept maps to (a) *buffer donation* —
marking inputs whose HBM may be reused for outputs — and (b) keeping the
whole iteration inside one ``jit`` so nothing round-trips through host
memory. This module provides the donation bookkeeping and a scoped config
object so DL4J-style `with workspace(...)` code has a direct equivalent.
"""

from __future__ import annotations

import contextlib
import functools
from dataclasses import dataclass, field

import jax


@dataclass
class WorkspaceConfig:
    """Mirrors WorkspaceConfiguration: which argnums to donate on the step fn."""

    name: str = "WS_TRAIN"
    donate_argnums: tuple = ()
    donate_argnames: tuple = ()


_active: list = []


@contextlib.contextmanager
def workspace(config: WorkspaceConfig | None = None, name: str = "WS"):
    """Scoped workspace; inside the scope `current()` returns the config."""
    cfg = config or WorkspaceConfig(name=name)
    _active.append(cfg)
    try:
        yield cfg
    finally:
        _active.pop()


def current() -> WorkspaceConfig | None:
    return _active[-1] if _active else None


def jit_in_workspace(fn=None, *, donate_argnums=(), static_argnums=(), **jit_kw):
    """jit with donation — the workspace-enter/exit of the TPU world.

    Donated inputs alias their HBM to outputs (params/opt-state in a train
    step), eliminating the copy the reference's workspace existed to avoid.
    """
    if fn is None:
        return functools.partial(jit_in_workspace, donate_argnums=donate_argnums,
                                 static_argnums=static_argnums, **jit_kw)
    return jax.jit(fn, donate_argnums=donate_argnums, static_argnums=static_argnums, **jit_kw)


def live_buffer_bytes() -> int:
    """Total bytes of live device buffers (workspace occupancy introspection)."""
    total = 0
    for d in jax.live_arrays():
        total += d.nbytes
    return total


def device_memory_stats() -> dict:
    """Per-device memory stats where the backend exposes them."""
    out = {}
    for dev in jax.devices():
        try:
            out[str(dev)] = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend may not support stats
            out[str(dev)] = None
    return out
