"""ND4J factory analogue — TPU-native array creation and core ops.

Reference parity: upstream ``nd4j-api`` ``org.nd4j.linalg.factory.Nd4j`` and
``INDArray`` method surface (creation, arithmetic, reductions, shape ops).
Design departure: arrays ARE ``jax.Array`` — no wrapper object. All functions
are pure and jit-safe; the DL4J method names (``mmul``, ``norm1``, ``normmax``,
``tensorMmul``) are provided as module-level functions so a DL4J user can
translate ``a.mmul(b)`` → ``nd.mmul(a, b)`` mechanically.
"""

from __future__ import annotations

import builtins
from functools import partial

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

# ---------------------------------------------------------------------------
# dtypes — bfloat16 is first-class on TPU
# ---------------------------------------------------------------------------
bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_

_DEFAULT_DTYPE = jnp.float32


def set_default_dtype(dtype) -> None:
    global _DEFAULT_DTYPE
    _DEFAULT_DTYPE = jnp.dtype(dtype)


def default_dtype():
    return _DEFAULT_DTYPE


def _dt(dtype):
    return _DEFAULT_DTYPE if dtype is None else dtype


# ---------------------------------------------------------------------------
# Creation (Nd4j.create / zeros / ones / ...)
# ---------------------------------------------------------------------------

def create(data, dtype=None):
    """Nd4j.create analogue: array from nested lists / numpy / jax array."""
    return jnp.asarray(data, dtype=dtype)


asarray = create


def zeros(*shape, dtype=None):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    return jnp.zeros(shape, dtype=_dt(dtype))


def ones(*shape, dtype=None):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    return jnp.ones(shape, dtype=_dt(dtype))


def full(shape, value, dtype=None):
    return jnp.full(shape, value, dtype=_dt(dtype))


def value_array_of(shape, value, dtype=None):  # Nd4j.valueArrayOf
    return full(shape, value, dtype)


def empty(shape, dtype=None):
    return jnp.empty(shape, dtype=_dt(dtype))


def zeros_like(a):
    return jnp.zeros_like(a)


def ones_like(a):
    return jnp.ones_like(a)


def eye(n, m=None, dtype=None):
    return jnp.eye(n, m, dtype=_dt(dtype))


def arange(*args, dtype=None):
    return jnp.arange(*args, dtype=dtype)


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=_dt(dtype))


def scalar(value, dtype=None):
    return jnp.asarray(value, dtype=dtype)


def diag(v, k=0):
    return jnp.diag(v, k)


def meshgrid(*arrays, indexing="ij"):
    return jnp.meshgrid(*arrays, indexing=indexing)


def tri(n, m=None, k=0, dtype=None):
    return jnp.tri(n, m, k, dtype=_dt(dtype))


def one_hot(indices, depth, dtype=None, axis=-1):
    return jax.nn.one_hot(indices, depth, dtype=_dt(dtype), axis=axis)


# ---------------------------------------------------------------------------
# Arithmetic / linear algebra (INDArray.mmul / tensorMmul / dot ...)
# ---------------------------------------------------------------------------

def mmul(a, b):
    """Matrix multiply (INDArray.mmul) — rides the MXU; prefers bf16 inputs."""
    return jnp.matmul(a, b)


matmul = mmul


def dot(a, b):
    return jnp.dot(a, b)


def tensor_mmul(a, b, axes):
    """INDArray.tensorMmul — tensordot over the given axes."""
    return jnp.tensordot(a, b, axes=axes)


def einsum(subscripts, *operands, precision=None):
    return jnp.einsum(subscripts, *operands, precision=precision)


def outer(a, b):
    return jnp.outer(a, b)


def kron(a, b):
    return jnp.kron(a, b)


def batch_mmul(a, b):
    return jnp.einsum("bij,bjk->bik", a, b)


add = jnp.add
sub = jnp.subtract
mul = jnp.multiply
div = jnp.divide
rdiv = lambda a, b: jnp.divide(b, a)
rsub = lambda a, b: jnp.subtract(b, a)
pow = jnp.power
mod = jnp.mod
floor_div = jnp.floor_divide
neg = jnp.negative
reciprocal = jnp.reciprocal
fmod = jnp.fmod
remainder = jnp.remainder
maximum = jnp.maximum
minimum = jnp.minimum


def squared_difference(a, b):
    d = jnp.subtract(a, b)
    return d * d


# comparison
eq = jnp.equal
neq = jnp.not_equal
gt = jnp.greater
gte = jnp.greater_equal
lt = jnp.less
lte = jnp.less_equal
logical_and = jnp.logical_and
logical_or = jnp.logical_or
logical_not = jnp.logical_not
logical_xor = jnp.logical_xor
isnan = jnp.isnan
isinf = jnp.isinf
isfinite = jnp.isfinite


# ---------------------------------------------------------------------------
# Reductions (INDArray.sum / norm1 / norm2 / normmax / ...)
# ---------------------------------------------------------------------------

def sum(a, axis=None, keepdims=False, dtype=None):
    return jnp.sum(a, axis=axis, keepdims=keepdims, dtype=dtype)


def mean(a, axis=None, keepdims=False):
    return jnp.mean(a, axis=axis, keepdims=keepdims)


def std(a, axis=None, keepdims=False, ddof=0):
    return jnp.std(a, axis=axis, keepdims=keepdims, ddof=ddof)


def var(a, axis=None, keepdims=False, ddof=0):
    return jnp.var(a, axis=axis, keepdims=keepdims, ddof=ddof)


def max(a, axis=None, keepdims=False):
    return jnp.max(a, axis=axis, keepdims=keepdims)


def min(a, axis=None, keepdims=False):
    return jnp.min(a, axis=axis, keepdims=keepdims)


def prod(a, axis=None, keepdims=False):
    return jnp.prod(a, axis=axis, keepdims=keepdims)


def argmax(a, axis=None):
    return jnp.argmax(a, axis=axis)


def argmin(a, axis=None):
    return jnp.argmin(a, axis=axis)


def norm1(a, axis=None, keepdims=False):
    """L1 norm (INDArray.norm1)."""
    return jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdims)


def norm2(a, axis=None, keepdims=False):
    """L2 norm (INDArray.norm2)."""
    return jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=keepdims))


def normmax(a, axis=None, keepdims=False):
    """Max-abs norm (INDArray.normmax)."""
    return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdims)


def squared_norm(a, axis=None, keepdims=False):
    return jnp.sum(jnp.square(a), axis=axis, keepdims=keepdims)


def cumsum(a, axis=None):
    return jnp.cumsum(a, axis=axis)


def cumprod(a, axis=None):
    return jnp.cumprod(a, axis=axis)


def all(a, axis=None, keepdims=False):
    return jnp.all(a, axis=axis, keepdims=keepdims)


def any(a, axis=None, keepdims=False):
    return jnp.any(a, axis=axis, keepdims=keepdims)


def count_nonzero(a, axis=None):
    return jnp.count_nonzero(a, axis=axis)


def entropy(a, axis=None):
    p = a / jnp.sum(a, axis=axis, keepdims=True)
    return -jnp.sum(p * jnp.log(jnp.clip(p, 1e-12, None)), axis=axis)


def log_sum_exp(a, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(a, axis=axis, keepdims=keepdims)


# ---------------------------------------------------------------------------
# Shape ops
# ---------------------------------------------------------------------------

def reshape(a, *shape):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    return jnp.reshape(a, shape)


def ravel(a):
    return jnp.ravel(a)


def flatten(a):
    return jnp.ravel(a)


def transpose(a, axes=None):
    return jnp.transpose(a, axes)


def permute(a, *axes):
    """INDArray.permute — axis permutation."""
    axes = axes[0] if len(axes) == 1 and isinstance(axes[0], (tuple, list)) else axes
    return jnp.transpose(a, axes)


def swap_axes(a, ax1, ax2):
    return jnp.swapaxes(a, ax1, ax2)


def move_axis(a, src, dst):
    return jnp.moveaxis(a, src, dst)


def expand_dims(a, axis):
    return jnp.expand_dims(a, axis)


def squeeze(a, axis=None):
    return jnp.squeeze(a, axis)


def concat(arrays, axis=0):
    return jnp.concatenate(arrays, axis=axis)


concatenate = concat
hstack = jnp.hstack
vstack = jnp.vstack


def stack(arrays, axis=0):
    return jnp.stack(arrays, axis=axis)


def unstack(a, axis=0):
    return [jnp.squeeze(s, axis) for s in jnp.split(a, a.shape[axis], axis)]


def split(a, n_or_sections, axis=0):
    return jnp.split(a, n_or_sections, axis=axis)


def tile(a, reps):
    return jnp.tile(a, reps)


def repeat(a, repeats, axis=None):
    return jnp.repeat(a, repeats, axis=axis)


def pad(a, pad_width, mode="constant", constant_values=0):
    if mode == "constant":
        return jnp.pad(a, pad_width, mode=mode, constant_values=constant_values)
    return jnp.pad(a, pad_width, mode=mode)


def flip(a, axis=None):
    return jnp.flip(a, axis=axis)


def roll(a, shift, axis=None):
    return jnp.roll(a, shift, axis=axis)


def broadcast_to(a, shape):
    return jnp.broadcast_to(a, shape)


def size(a):
    return a.size


def shape(a):
    return a.shape


def rank(a):
    return a.ndim


def length(a):
    return a.size


def dup(a):
    """INDArray.dup — functional copy (a no-op value-wise under XLA)."""
    return jnp.asarray(a).copy()


def cast(a, dtype):
    return a.astype(dtype)


astype = cast


# ---------------------------------------------------------------------------
# Elementwise transforms (org.nd4j.linalg.ops.transforms.Transforms)
# ---------------------------------------------------------------------------
abs = jnp.abs
sign = jnp.sign
exp = jnp.exp
expm1 = jnp.expm1
log = jnp.log
log1p = jnp.log1p
log2 = jnp.log2
log10 = jnp.log10
sqrt = jnp.sqrt
rsqrt = lax.rsqrt
square = jnp.square
cbrt = jnp.cbrt
floor = jnp.floor
ceil = jnp.ceil
round = jnp.round
trunc = jnp.trunc
sin = jnp.sin
cos = jnp.cos
tan = jnp.tan
asin = jnp.arcsin
acos = jnp.arccos
atan = jnp.arctan
atan2 = jnp.arctan2
sinh = jnp.sinh
cosh = jnp.cosh
tanh = jnp.tanh
asinh = jnp.arcsinh
acosh = jnp.arccosh
atanh = jnp.arctanh
erf = jax.scipy.special.erf
erfc = jax.scipy.special.erfc
sigmoid = jax.nn.sigmoid
softplus = jax.nn.softplus
softmax = jax.nn.softmax
log_softmax = jax.nn.log_softmax
relu = jax.nn.relu
relu6 = jax.nn.relu6
leaky_relu = jax.nn.leaky_relu
elu = jax.nn.elu
gelu = jax.nn.gelu
silu = jax.nn.silu
hard_sigmoid = jax.nn.hard_sigmoid
hard_tanh = jax.nn.hard_tanh


def clip(a, min=None, max=None):
    return jnp.clip(a, min, max)


clip_by_value = clip


def clip_by_norm(a, clip_norm, axis=None):
    n = norm2(a, axis=axis, keepdims=True)
    return jnp.where(n > clip_norm, a * (clip_norm / jnp.maximum(n, 1e-12)), a)


def step(a):  # heaviside step used by DL4J Transforms.step
    return (a > 0).astype(a.dtype)


def pow_scalar(a, p):
    return jnp.power(a, p)


# ---------------------------------------------------------------------------
# Sorting / searching / selection
# ---------------------------------------------------------------------------

def sort(a, axis=-1, descending=False):
    out = jnp.sort(a, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def argsort(a, axis=-1, descending=False):
    out = jnp.argsort(a, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def top_k(a, k, axis=-1):
    if axis in (-1, a.ndim - 1):
        return lax.top_k(a, k)
    am = jnp.moveaxis(a, axis, -1)
    v, i = lax.top_k(am, k)
    return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis)


def where(cond, x=None, y=None):
    if x is None and y is None:
        return jnp.where(cond)
    return jnp.where(cond, x, y)


def searchsorted(a, v, side="left"):
    return jnp.searchsorted(a, v, side=side)


def unique(a, size=None, fill_value=None):
    """jnp.unique; pass `size` for a jit-safe static-shape variant."""
    if size is not None:
        return jnp.unique(a, size=size, fill_value=fill_value)
    return jnp.unique(a)


def take(a, indices, axis=None):
    return jnp.take(a, indices, axis=axis)


def take_along_axis(a, indices, axis):
    return jnp.take_along_axis(a, indices, axis=axis)


def gather(a, indices, axis=0):
    return jnp.take(a, indices, axis=axis)


def scatter_update(a, indices, updates):
    return a.at[indices].set(updates)


def scatter_add(a, indices, updates):
    return a.at[indices].add(updates)


def scatter_max(a, indices, updates):
    return a.at[indices].max(updates)


def segment_sum(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


# ---------------------------------------------------------------------------
# Linear algebra (Nd4j.linalg / lapack)
# ---------------------------------------------------------------------------
class linalg:
    cholesky = staticmethod(jnp.linalg.cholesky)
    qr = staticmethod(jnp.linalg.qr)
    svd = staticmethod(jnp.linalg.svd)
    inv = staticmethod(jnp.linalg.inv)
    pinv = staticmethod(jnp.linalg.pinv)
    det = staticmethod(jnp.linalg.det)
    slogdet = staticmethod(jnp.linalg.slogdet)
    solve = staticmethod(jnp.linalg.solve)
    lstsq = staticmethod(jnp.linalg.lstsq)
    eig = staticmethod(jnp.linalg.eig)
    eigh = staticmethod(jnp.linalg.eigh)
    norm = staticmethod(jnp.linalg.norm)
    matrix_rank = staticmethod(jnp.linalg.matrix_rank)
    triangular_solve = staticmethod(jax.scipy.linalg.solve_triangular)


# ---------------------------------------------------------------------------
# Conv primitives (libnd4j conv ops → lax). NHWC is the TPU-native layout.
# ---------------------------------------------------------------------------

def conv2d(x, w, stride=(1, 1), padding="SAME", dilation=(1, 1),
           feature_group_count=1, dimension_numbers=("NHWC", "HWIO", "NHWC")):
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=padding,
        rhs_dilation=tuple(dilation), dimension_numbers=dimension_numbers,
        feature_group_count=feature_group_count,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None)


def max_pool2d(x, window=(2, 2), stride=None, padding="VALID"):
    stride = window if stride is None else stride
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max, (1, *window, 1), (1, *stride, 1), padding)


def avg_pool2d(x, window=(2, 2), stride=None, padding="VALID", count_include_pad=True):
    stride = window if stride is None else stride
    s = lax.reduce_window(x, 0.0, lax.add, (1, *window, 1), (1, *stride, 1), padding)
    if count_include_pad or padding == "VALID":
        return s / (window[0] * window[1])
    ones_ = jnp.ones(x.shape[:3] + (1,), x.dtype)
    cnt = lax.reduce_window(ones_, 0.0, lax.add, (1, *window, 1), (1, *stride, 1), padding)
    return s / cnt


def im2col(x, kernel, stride=(1, 1), padding="VALID"):
    """Extract patches: (N,H,W,C) → (N, OH, OW, kh*kw*C)."""
    kh, kw = kernel
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=tuple(stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return patches


def col2im(cols, x_shape, kernel, stride=(1, 1)):
    """Scatter-add patches back (used by gradient checks for im2col)."""
    n, h, w, c = x_shape
    kh, kw = kernel
    oh = (h - kh) // stride[0] + 1
    ow = (w - kw) // stride[1] + 1
    cols = cols.reshape(n, oh, ow, c, kh, kw)  # patches dim ordering: C major
    out = jnp.zeros(x_shape, cols.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, i:i + oh * stride[0]:stride[0],
                         j:j + ow * stride[1]:stride[1], :].add(cols[:, :, :, :, i, j])
    return out


# host transfer helpers
def to_numpy(a):
    return _np.asarray(a)


def device_put(a, device=None):
    return jax.device_put(a, device)
