"""NDArrayIndex analogue — structured slicing helpers.

Reference parity: ``org.nd4j.linalg.indexing.NDArrayIndex`` (interval, point,
all, newAxis) and ``INDArray.get/put(INDArrayIndex...)``, plus BooleanIndexing.
Arrays are jax.Arrays, so these build standard numpy-style index tuples —
jit-safe when bounds are static; use `dynamic_slice` helpers for traced starts.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


class _All:
    def resolve(self):
        return slice(None)


class _NewAxis:
    def resolve(self):
        return None


class Interval:
    def __init__(self, start, end, step=1):
        self.start, self.end, self.step = start, end, step

    def resolve(self):
        return slice(self.start, self.end, self.step)


class Point:
    def __init__(self, i):
        self.i = i

    def resolve(self):
        return self.i


class Indices:
    """Fancy index by an integer array along one axis."""

    def __init__(self, idx):
        self.idx = idx

    def resolve(self):
        return jnp.asarray(self.idx)


def all():
    return _All()


def new_axis():
    return _NewAxis()


def interval(start, end, step=1):
    return Interval(start, end, step)


def point(i):
    return Point(i)


def indices(idx):
    return Indices(idx)


def _resolve(ixs):
    return tuple(ix.resolve() if hasattr(ix, "resolve") else ix for ix in ixs)


def get(a, *ixs):
    """INDArray.get(NDArrayIndex...)"""
    return a[_resolve(ixs)]


def put(a, *ixs_and_value):
    """INDArray.put(NDArrayIndex..., value) — functional: returns new array."""
    *ixs, value = ixs_and_value
    return jnp.asarray(a).at[_resolve(ixs)].set(value)


def put_scalar(a, idx, value):
    return jnp.asarray(a).at[tuple(idx) if isinstance(idx, (list, tuple)) else idx].set(value)


def get_scalar(a, *idx):
    return a[tuple(idx)]


# --- BooleanIndexing analogue ---------------------------------------------

def replace_where(a, replacement, cond_mask):
    """BooleanIndexing.replaceWhere — functional."""
    return jnp.where(cond_mask, replacement, a)


def apply_where(a, cond_mask, fn):
    return jnp.where(cond_mask, fn(a), a)


def first_index(cond_mask, axis=None):
    """Index of first True (BooleanIndexing.firstIndex); -1 if none."""
    flat = cond_mask if axis is not None else cond_mask.ravel()
    idx = jnp.argmax(flat, axis=axis)
    has = jnp.any(flat, axis=axis)
    return jnp.where(has, idx, -1)


def last_index(cond_mask, axis=None):
    flat = cond_mask if axis is not None else cond_mask.ravel()
    n = flat.shape[axis if axis is not None else 0]
    rev = jnp.flip(flat, axis=axis if axis is not None else 0)
    idx = n - 1 - jnp.argmax(rev, axis=axis)
    has = jnp.any(flat, axis=axis)
    return jnp.where(has, idx, -1)


# --- dynamic (traced-start) slicing ---------------------------------------

def dynamic_slice(a, starts, sizes):
    return lax.dynamic_slice(a, starts, sizes)


def dynamic_update_slice(a, update, starts):
    return lax.dynamic_update_slice(a, update, starts)


def tensor_along_dimension(a, index, dim):
    """INDArray.tensorAlongDimension — slice at `index` along `dim`."""
    return jnp.take(a, index, axis=dim)


def slice_along_first(a, i):
    """INDArray.slice(i)."""
    return a[i]
