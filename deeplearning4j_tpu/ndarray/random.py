"""Nd4j random analogue on JAX's counter-based PRNG.

Reference parity: ``org.nd4j.linalg.factory.Nd4j.rand/randn`` and
``org.nd4j.linalg.api.rng`` (stateful seeded RNG). TPU-first departure:
the canonical API is *explicit keys* (jit-safe, reproducible under SPMD);
a thin stateful facade (`set_seed`, `rand`, `randn`) exists for DL4J-style
host-side use and splits a host-held key per call — never use it inside jit.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

_lock = threading.Lock()
# lazy: creating a PRNGKey at import time would initialize (and on this
# sandbox, claim) the default device backend for EVERY package import
_state = {"key": None}


def set_seed(seed: int) -> None:
    """Nd4j.getRandom().setSeed analogue (host-side only)."""
    with _lock:
        _state["key"] = jax.random.PRNGKey(seed)


def next_key():
    """Split and return a fresh subkey from the host-side stream."""
    with _lock:
        if _state["key"] is None:
            _state["key"] = jax.random.PRNGKey(0)
        _state["key"], sub = jax.random.split(_state["key"])
    return sub


def key(seed: int):
    return jax.random.PRNGKey(seed)


def split(k, num: int = 2):
    return jax.random.split(k, num)


def fold_in(k, data: int):
    return jax.random.fold_in(k, data)


# --- explicit-key distributions (jit-safe canonical API) -------------------

def uniform(k, shape=(), dtype=jnp.float32, minval=0.0, maxval=1.0):
    return jax.random.uniform(k, shape, dtype, minval, maxval)


def normal(k, shape=(), dtype=jnp.float32, mean=0.0, std=1.0):
    return mean + std * jax.random.normal(k, shape, dtype)


def truncated_normal(k, shape=(), dtype=jnp.float32, lower=-2.0, upper=2.0, mean=0.0, std=1.0):
    return mean + std * jax.random.truncated_normal(k, lower, upper, shape, dtype)


def bernoulli(k, p=0.5, shape=()):
    return jax.random.bernoulli(k, p, shape)


def binomial(k, n, p, shape=(), dtype=jnp.int32):
    return jax.random.binomial(k, n, p, shape=shape).astype(dtype)


def gamma(k, alpha, shape=(), dtype=jnp.float32):
    return jax.random.gamma(k, alpha, shape, dtype)


def beta(k, a, b, shape=(), dtype=jnp.float32):
    return jax.random.beta(k, a, b, shape, dtype)


def exponential(k, shape=(), dtype=jnp.float32, rate=1.0):
    return jax.random.exponential(k, shape, dtype) / rate


def poisson(k, lam, shape=(), dtype=jnp.int32):
    return jax.random.poisson(k, lam, shape, dtype)


def randint(k, shape, minval, maxval, dtype=jnp.int32):
    return jax.random.randint(k, shape, minval, maxval, dtype)


def categorical(k, logits, axis=-1, shape=None):
    return jax.random.categorical(k, logits, axis=axis, shape=shape)


def permutation(k, x, axis=0):
    return jax.random.permutation(k, x, axis=axis)


def choice(k, a, shape=(), replace=True, p=None):
    return jax.random.choice(k, a, shape, replace, p)


def gumbel(k, shape=(), dtype=jnp.float32):
    return jax.random.gumbel(k, shape, dtype)


def laplace(k, shape=(), dtype=jnp.float32):
    return jax.random.laplace(k, shape, dtype)


# --- stateful facade (Nd4j.rand/randn; host-side convenience) --------------

def rand(*shape, dtype=jnp.float32, minval=0.0, maxval=1.0):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    return uniform(next_key(), shape, dtype, minval, maxval)


def randn(*shape, dtype=jnp.float32):
    shape = shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list)) else shape
    return jax.random.normal(next_key(), shape, dtype)


def shuffle(x, axis=0):
    return jax.random.permutation(next_key(), x, axis=axis)
