"""`deeplearning4j_tpu.ndarray` — the ND4J analogue (tensor layer).

Usage: ``from deeplearning4j_tpu import nd`` then ``nd.zeros(3, 4)``,
``nd.mmul(a, b)``, ``nd.random.randn(2, 2)``. Arrays are plain jax.Arrays.
"""

from . import indexing, random, workspace
from .factory import *  # noqa: F401,F403 — the Nd4j-style flat namespace
from .factory import linalg  # noqa: F401
