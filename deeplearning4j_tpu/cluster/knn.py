"""Nearest-neighbor search.

Reference parity: ``nearestneighbor-core`` — VPTree-backed
`NearestNeighborsSearch` and `RandomProjectionLSH`.

TPU-first redesign: the reference builds a VP-tree to prune host-side
distance evaluations; on TPU the pruning is the wrong trade — a dense
N×Q distance computation is one MXU matmul and `jax.lax.top_k` finds the
neighbors, so brute force IS the fast path (the same reasoning as the
exact-repulsion t-SNE in `manifold/`). The LSH variant keeps the
reference's signed-random-projection buckets for sublinear candidate
selection over very large corpora, with the final exact ranking still
done on device.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ._distance import l2_normalize, sq_dists as _sq_dists


class NearestNeighborsSearch:
    """Exact k-NN over a fixed corpus (VPTree.search analogue)."""

    def __init__(self, points, distance: str = "euclidean"):
        if distance not in ("euclidean", "cosine"):
            raise ValueError("distance must be 'euclidean' or 'cosine'")
        self.distance = distance
        self._x = jnp.asarray(points, jnp.float32)
        if distance == "cosine":
            self._xn = l2_normalize(self._x)
        self._knn = jax.jit(self._knn_impl, static_argnums=(1,))

    def _knn_impl(self, q, k):
        if self.distance == "cosine":
            d = 1.0 - l2_normalize(q) @ self._xn.T
        else:
            d = _sq_dists(q, self._x)
        neg, idx = jax.lax.top_k(-d, k)
        return idx, -neg

    def search(self, query, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """(Q, D) or (D,) query → (indices (Q, k), distances (Q, k));
        euclidean distances are squared (monotone-equivalent ranking,
        no sqrt on the hot path)."""
        q = jnp.asarray(query, jnp.float32)
        single = q.ndim == 1
        if single:
            q = q[None]
        k = int(min(k, self._x.shape[0]))
        idx, d = self._knn(q, k)
        idx, d = np.asarray(idx), np.asarray(d)
        return (idx[0], d[0]) if single else (idx, d)


class RandomProjectionLSH:
    """Signed random-projection LSH (reference RandomProjectionLSH):
    hash = sign bits of `n_bits` random projections; candidates share a
    bucket in any of `n_tables` tables; exact ranking on the candidates
    happens on device."""

    def __init__(self, points, n_bits: int = 12, n_tables: int = 4,
                 seed: int = 0):
        self._x = np.asarray(points, np.float32)
        n, d = self._x.shape
        key = jax.random.PRNGKey(seed)
        self._proj = np.asarray(
            jax.random.normal(key, (n_tables, d, n_bits), jnp.float32))
        self.n_bits, self.n_tables = n_bits, n_tables
        codes = self._hash(self._x)                      # (T, N)
        self._tables = []
        for t in range(n_tables):
            buckets = {}
            for i, c in enumerate(codes[t]):
                buckets.setdefault(int(c), []).append(i)
            self._tables.append(buckets)

    def _hash(self, pts) -> np.ndarray:
        bits = (np.einsum("nd,tdb->tnb", pts, self._proj) > 0)
        weights = (1 << np.arange(self.n_bits)).astype(np.int64)
        return bits @ weights                            # (T, N)

    def candidates(self, query) -> np.ndarray:
        q = np.asarray(query, np.float32)[None]
        codes = self._hash(q)[:, 0]
        cand = set()
        for t in range(self.n_tables):
            cand.update(self._tables[t].get(int(codes[t]), ()))
        return np.fromiter(cand, np.int64) if cand else np.arange(
            self._x.shape[0])

    def search(self, query, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Approximate k-NN: bucket candidates, exact-ranked on device."""
        q = np.asarray(query, np.float32)
        cand = self.candidates(q)
        sub = jnp.asarray(self._x[cand])
        d = np.asarray(_sq_dists(jnp.asarray(q)[None], sub))[0]
        order = np.argsort(d)[:k]
        return cand[order], d[order]
