"""K-means clustering.

Reference parity: ``org.deeplearning4j.clustering.kmeans.KMeansClustering``
(setup(k, maxIter, distance), applyTo(points) → ClusterSet).

TPU-first redesign: the reference's iterative point-at-a-time cluster
assignment becomes Lloyd iterations as one jitted program — the N×K
distance matrix is a single matmul-shaped computation on the MXU
(||x||² - 2x·cᵀ + ||c||²), assignments one argmin, and the centroid
update a segment-sum. k-means++ seeding runs as a short scan of the same
distance kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


from ._distance import l2_normalize, sq_dists as _sq_dists


def _cosine_dists(x, c):
    return 1.0 - l2_normalize(x) @ l2_normalize(c).T


_DISTANCES = {"euclidean": _sq_dists, "cosine": _cosine_dists,
              "manhattan": lambda x, c: jnp.sum(
                  jnp.abs(x[:, None, :] - c[None, :, :]), -1)}


class KMeansClustering:
    """KMeansClustering.setup(k, maxIter, 'euclidean') analogue.

    fit(points) runs k-means++ seeding then Lloyd iterations until
    assignment convergence or max_iterations; exposes cluster_centers_,
    labels_, inertia_ and predict().
    """

    def __init__(self, k: int, max_iterations: int = 100,
                 distance: str = "euclidean", seed: int = 0,
                 tol: float = 1e-6):
        if distance not in _DISTANCES:
            raise ValueError(f"distance must be one of {sorted(_DISTANCES)}")
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.distance = distance
        self.seed = seed
        self.tol = float(tol)
        self.cluster_centers_: Optional[np.ndarray] = None
        self.labels_: Optional[np.ndarray] = None
        self.inertia_: Optional[float] = None
        self._build_kernels()       # jit ONCE per instance (re-fits and
        # same-shape sweeps reuse the compiled programs)

    def _build_kernels(self):
        dist = _DISTANCES[self.distance]
        K, max_it, tol = self.k, self.max_iterations, self.tol

        @jax.jit
        def seed_pp(key, x):
            """k-means++: iteratively pick centers ∝ distance-squared."""
            n = x.shape[0]
            k0, key = jax.random.split(key)
            first = x[jax.random.randint(k0, (), 0, n)]
            centers0 = jnp.zeros((K, x.shape[1])).at[0].set(first)

            def pick(carry, i):
                centers, key = carry
                d = dist(x, centers)                       # (N, K)
                # distance to the nearest ALREADY-CHOSEN center
                masked = jnp.where(jnp.arange(K)[None, :] < i, d, jnp.inf)
                dmin = jnp.min(masked, -1)
                key, kc = jax.random.split(key)
                idx = jax.random.categorical(
                    kc, jnp.log(jnp.maximum(dmin, 1e-12)))
                return (centers.at[i].set(x[idx]), key), None

            (centers, _), _ = jax.lax.scan(
                pick, (centers0, key), jnp.arange(1, K))
            return centers

        @jax.jit
        def lloyd(centers, x):
            n = x.shape[0]

            def body(state):
                centers, _, it, _ = state
                d = dist(x, centers)
                assign = jnp.argmin(d, -1)
                one_hot = jax.nn.one_hot(assign, K, dtype=x.dtype)
                counts = one_hot.sum(0)
                sums = one_hot.T @ x
                new_centers = jnp.where(
                    counts[:, None] > 0,
                    sums / jnp.maximum(counts, 1)[:, None], centers)
                shift = jnp.max(jnp.sum(jnp.square(new_centers - centers), -1))
                return new_centers, assign, it + 1, shift

            def cond(state):
                _, _, it, shift = state
                return (it < max_it) & (shift > tol)

            init = (centers, jnp.zeros((n,), jnp.int32), 0, jnp.inf)
            centers, assign, _, _ = jax.lax.while_loop(cond, body, init)
            d = dist(x, centers)
            assign = jnp.argmin(d, -1)
            inertia = jnp.sum(jnp.min(d, -1))
            return centers, assign, inertia

        self._seed_pp, self._lloyd = seed_pp, lloyd

    # ------------------------------------------------------------------ setup
    @classmethod
    def setup(cls, k: int, max_iterations: int = 100,
              distance: str = "euclidean", seed: int = 0):
        """Reference factory-method name."""
        return cls(k, max_iterations, distance, seed)

    # -------------------------------------------------------------------- fit
    def fit(self, points) -> "KMeansClustering":
        x = jnp.asarray(points, jnp.float32)
        if x.shape[0] < self.k:
            raise ValueError(
                f"need at least k={self.k} points, got {x.shape[0]}")
        key = jax.random.PRNGKey(self.seed)
        centers, assign, inertia = self._lloyd(self._seed_pp(key, x), x)
        self.cluster_centers_ = np.asarray(centers)
        self.labels_ = np.asarray(assign)
        self.inertia_ = float(inertia)
        return self

    apply_to = fit          # reference applyTo naming

    def predict(self, points) -> np.ndarray:
        if self.cluster_centers_ is None:
            raise ValueError("fit first")
        d = _DISTANCES[self.distance](jnp.asarray(points, jnp.float32),
                                      jnp.asarray(self.cluster_centers_))
        return np.asarray(jnp.argmin(d, -1))
