"""Shared distance kernels for clustering / k-NN."""

from __future__ import annotations

import jax.numpy as jnp


def sq_dists(q, x):
    """(Q, D), (N, D) -> (Q, N) squared euclidean distances as one MXU
    matmul-shaped computation: ||q||^2 - 2 q·x^T + ||x||^2 (clamped at 0 —
    float error can dip slightly negative for near-identical rows)."""
    qq = jnp.sum(jnp.square(q), -1, keepdims=True)
    xx = jnp.sum(jnp.square(x), -1)
    return jnp.maximum(qq - 2.0 * (q @ x.T) + xx, 0.0)


def l2_normalize(x, eps: float = 1e-12):
    return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), eps)
