"""deeplearning4j_tpu.cluster — clustering + nearest-neighbor search.

Reference parity: ``deeplearning4j-nearestneighbors-parent`` —
`clustering/kmeans/KMeansClustering`, `nearestneighbor-core` VPTree
search, and `RandomProjectionLSH`.
"""

from .kmeans import KMeansClustering
from .knn import NearestNeighborsSearch, RandomProjectionLSH

__all__ = ["KMeansClustering", "NearestNeighborsSearch",
           "RandomProjectionLSH"]
