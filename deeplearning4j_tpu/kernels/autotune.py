"""Unified autotune harness — persistent cost records for every tuned
surface (ISSUE 17 tentpole, second half; SURVEY §7 R2 item).

The reference leans on cuDNN's internal autotuner
(cudnnFindConvolutionAlgorithmEx et al., arXiv 1410.0759); XLA has no
equivalent for hand-written pallas kernels, so this is ours — the TVM
cost-record pattern (arXiv 1802.04799), minus the learned model: time
each candidate on the REAL device with the same marginal-chained-steps
discipline bench.py uses, pick the fastest, and persist the verdict as
a cost record in ``~/.deeplearning4j_tpu/autotune.json`` so one
process's sweep pays for every later run on the same chip generation.

One store, one key grammar, three tuned surfaces today:

- ``flash5:...`` — flash-attention (block_q, block_k) per shape
  (``flash_attention._tuned_blocks``);
- ``serving_page_len: / serving_prefill_chunk: / serving_decode_slots:``
  — the serving knobs (``serving/tune.py``);
- ``paged_decode:...`` — the pallas paged-attention decode kernel's
  fidelity-gated kernel-vs-XLA promotion verdicts
  (``kernels/paged_attention.py``).

A key's KIND is everything before the first ``:`` — the public
:func:`records` filter. Every record is::

    {"choice": [...],                 # the winning candidate
     "meta":   {"measured_at": ..., "best_s": ...,
                "measurements": [[cand, seconds|null], ...], ...},
     "sha":    "..." | absent}        # source fingerprint, see below

**Sha auto-invalidation**: a record written with ``sha=`` (the digest
of the kernel source that was measured — :func:`source_sha`) is only
served while the caller presents the SAME sha. A lookup with a
different sha deletes the record, bumps
``dl4j_autotune_invalidations_total`` and falls through to the
re-measure path — editing a kernel can never be served a stale verdict
measured against the old code. Records without a sha (flash blocks,
serving knobs: the measured code is the caller itself) never
invalidate this way.

Public API (ISSUE 17 satellite — ``serving/tune.py`` and every new
consumer go through these, not the private store internals):

- :func:`autotune` — race candidates, cache the winner (sha-aware);
- :func:`records` / :func:`lookup` / :func:`choice` — read records
  back (``kind=`` filters by key kind-prefix);
- :func:`put` / :func:`invalidate` — write/drop one record;
- :func:`source_sha` — fingerprint a kernel's source for ``sha=``;
- :func:`measurement_meta` / :func:`clear_cache` — as before.

``_disk_cache`` / ``_entry_choice`` remain as deprecated shims for the
PR 14 private imports; new code uses :func:`records` / :func:`choice`.
"""

from __future__ import annotations

import hashlib
import inspect
import json
import os
import time
import warnings
from pathlib import Path
from typing import Callable, Dict, Iterable, Optional, Tuple

_memory_cache: Dict[str, Tuple] = {}
_CACHE_PATH = Path(os.environ.get(
    "DL4J_TPU_DATA", Path.home() / ".deeplearning4j_tpu")) / "autotune.json"


# ------------------------------------------------------------- store --

def _load_store() -> dict:
    try:
        return json.loads(_CACHE_PATH.read_text())
    except Exception:  # noqa: BLE001 — absent/corrupt cache = empty
        return {}


def _save_store(store: dict):
    try:
        _CACHE_PATH.parent.mkdir(parents=True, exist_ok=True)
        _CACHE_PATH.write_text(json.dumps(store, indent=1))
    except OSError:
        pass  # read-only home: in-process cache still works


def _normalize(entry) -> dict:
    """Disk entries are either the bare choice list (legacy) or a
    ``{"choice": [...], "meta": {...}, "sha": ...}`` record with
    measurement provenance (TVM cost-record discipline: every cached
    verdict says when and from what measurements it was reached)."""
    if isinstance(entry, dict):
        return {"choice": list(entry.get("choice", [])),
                "meta": entry.get("meta"),
                "sha": entry.get("sha")}
    return {"choice": list(entry), "meta": None, "sha": None}


def _kind(key: str) -> str:
    return key.split(":", 1)[0]


# ------------------------------------------------------ public reads --

def records(kind: Optional[str] = None) -> Dict[str, dict]:
    """Every persisted cost record, normalized to
    ``{key: {choice, meta, sha}}``. ``kind=`` filters by the key's
    kind segment (everything before the first ``:``) — prefix-matched,
    so ``kind="serving"`` returns all three ``serving_*`` knob
    families and ``kind="serving_page_len"`` exactly one."""
    out = {}
    for key, entry in _load_store().items():
        if kind is not None and not _kind(key).startswith(kind):
            continue
        out[key] = _normalize(entry)
    return out


def lookup(key: str, sha: Optional[str] = None) -> Optional[dict]:
    """The record for ``key`` — ``{choice, meta, sha}`` — or None.
    When the caller presents a ``sha`` and the record carries a
    DIFFERENT one, the record is stale against the current kernel
    source: it is deleted (memory + disk), the invalidation counter
    bumps, and None returns — the caller re-measures."""
    store = _load_store()
    if key not in store:
        return None
    rec = _normalize(store[key])
    if sha is not None and rec["sha"] is not None and rec["sha"] != sha:
        invalidate(key, reason="sha")
        return None
    return rec


def choice(key: str, sha: Optional[str] = None) -> Optional[Tuple]:
    """The cached winning candidate for ``key`` as a tuple, or None
    (miss, or sha-invalidated — see :func:`lookup`)."""
    rec = lookup(key, sha=sha)
    return None if rec is None else tuple(rec["choice"])


def measurement_meta(key: str) -> Optional[dict]:
    """The measurement provenance recorded for `key`, or None (cache
    miss / legacy entry)."""
    rec = lookup(key)
    return None if rec is None else rec["meta"]


# ----------------------------------------------------- public writes --

def put(key: str, chosen, meta: Optional[dict] = None,
        sha: Optional[str] = None):
    """Persist one cost record (memory + disk). ``chosen`` is the
    winning candidate (any sequence); ``meta`` the measurement
    provenance; ``sha`` the source fingerprint that gates staleness."""
    store = _load_store()
    entry = {"choice": list(chosen)}
    if meta is not None:
        entry["meta"] = meta
    if sha is not None:
        entry["sha"] = sha
    store[key] = entry
    _memory_cache[key] = tuple(chosen)
    _save_store(store)


def invalidate(key: str, reason: str = "explicit") -> bool:
    """Drop one record from memory and disk; counts into
    ``dl4j_autotune_invalidations_total{kernel,reason}``. Returns True
    if a disk record existed."""
    _memory_cache.pop(key, None)
    store = _load_store()
    existed = store.pop(key, None) is not None
    if existed:
        _save_store(store)
        try:
            from ..obs import get_registry
            get_registry().counter(
                "dl4j_autotune_invalidations_total",
                "Cost records dropped (sha change, explicit reset)",
                labelnames=("kernel", "reason")).inc(
                    kernel=_kind(key), reason=reason)
        except Exception:  # noqa: BLE001 — telemetry is decoration
            pass
    return existed


def clear_cache():
    _memory_cache.clear()
    try:
        _CACHE_PATH.unlink()
    except OSError:
        pass


def source_sha(*objs) -> str:
    """Fingerprint of the given functions'/modules' SOURCE text — the
    ``sha=`` a kernel passes so its cost records auto-invalidate when
    the kernel is edited. Deliberately source-based (not bytecode):
    a comment-only edit re-races too, which is cheap and safe."""
    h = hashlib.sha256()
    for obj in objs:
        h.update(inspect.getsource(obj).encode())
    return h.hexdigest()[:16]


# -------------------------------------------------------- measurement --

def _time_once(run: Callable[[], object], reps: int = 8) -> float:
    """Marginal seconds per call: chained calls ended by one host fetch
    (block_until_ready does not sync through the axon tunnel)."""
    import jax.numpy as jnp

    def fetch(x):
        return float(jnp.asarray(x).reshape(-1)[0])

    fetch(run())  # compile + warmup
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = run()
    fetch(out)
    t_n = time.perf_counter() - t0
    t0 = time.perf_counter()
    fetch(run())
    t_1 = time.perf_counter() - t0
    return max((t_n - t_1) / (reps - 1), 1e-9)


def autotune(key: str, candidates: Iterable[Tuple],
             make_run: Callable[[Tuple], Optional[Callable[[], object]]],
             enabled: bool = True, sha: Optional[str] = None) -> Tuple:
    """Pick the fastest candidate for `key`; cached thereafter.

    make_run(candidate) returns a nullary closure executing the kernel with
    that config (returning a fetchable array), or None if the candidate is
    invalid for the shape. With enabled=False (or when every candidate
    fails) the FIRST valid candidate is returned untimed.

    ``sha=`` stamps the record with the measured kernel's source
    fingerprint: a later call presenting a different sha invalidates the
    record and re-races (see :func:`lookup`).
    """
    from ..obs import get_registry
    reg = get_registry()
    if key in _memory_cache and sha is None:
        reg.counter("dl4j_autotune_cache_hits_total",
                    "Autotune lookups served from cache",
                    labelnames=("level",)).inc(level="memory")
        return _memory_cache[key]
    cached = lookup(key, sha=sha)
    if cached is not None:
        level = "memory" if key in _memory_cache else "disk"
        reg.counter("dl4j_autotune_cache_hits_total",
                    "Autotune lookups served from cache",
                    labelnames=("level",)).inc(level=level)
        chosen = tuple(cached["choice"])
        _memory_cache[key] = chosen
        return chosen

    candidates = [c for c in candidates]
    if not enabled:
        chosen = candidates[0]
        _memory_cache[key] = chosen
        return chosen

    m_measure = reg.counter("dl4j_autotune_measurements_total",
                            "Candidate configs timed on the device")
    m_time = reg.histogram("dl4j_autotune_candidate_seconds",
                           "Marginal per-call seconds of timed candidates")
    best, best_t = None, float("inf")
    measurements = []   # per-candidate provenance for the disk record
    for cand in candidates:
        run = make_run(cand)
        if run is None:                     # invalid for the shape
            measurements.append([list(cand), None])
            continue
        try:
            t = _time_once(run)
        except Exception:  # noqa: BLE001 — config doesn't compile/fit VMEM
            measurements.append([list(cand), None])
            continue
        m_measure.inc()
        m_time.observe(t)
        measurements.append([list(cand), t])
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        best = candidates[0]
    put(key, best,
        meta={"measured_at": time.time(),
              "best_s": None if best_t == float("inf") else best_t,
              "candidates": len(candidates),
              "measurements": measurements},
        sha=sha)
    return best


# ------------------------------------------- deprecated private shims --
# PR 14's serving/tune.py reached into these; kept so external callers
# keep working one more release. New code: records()/choice()/lookup().

def _disk_cache() -> dict:
    """Deprecated: use :func:`records` (normalized) instead."""
    warnings.warn("autotune._disk_cache is deprecated; use "
                  "autotune.records()", DeprecationWarning, stacklevel=2)
    return _load_store()


def _entry_choice(entry):
    """Deprecated: use :func:`choice`/:func:`lookup` instead."""
    warnings.warn("autotune._entry_choice is deprecated; use "
                  "autotune.choice()/lookup()", DeprecationWarning,
                  stacklevel=2)
    return tuple(_normalize(entry)["choice"])
