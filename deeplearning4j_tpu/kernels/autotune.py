"""Block-size autotuning for pallas kernels (SURVEY §7 R2 item).

The reference leans on cuDNN's internal autotuner (cudnnFindConvolution
AlgorithmEx et al.); XLA has no equivalent for hand-written pallas
kernels, so this is ours: time each candidate config on the REAL device
with the same marginal-chained-steps discipline bench.py uses, pick the
fastest, and cache the choice both in-process and on disk
(``~/.deeplearning4j_tpu/autotune.json``) so one process's sweep pays for
every later run on the same chip generation.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Callable, Dict, Iterable, Optional, Tuple

_memory_cache: Dict[str, Tuple] = {}
_CACHE_PATH = Path(os.environ.get(
    "DL4J_TPU_DATA", Path.home() / ".deeplearning4j_tpu")) / "autotune.json"


def _disk_cache() -> dict:
    try:
        return json.loads(_CACHE_PATH.read_text())
    except Exception:  # noqa: BLE001 — absent/corrupt cache = empty
        return {}


def _entry_choice(entry):
    """Disk entries are either the bare choice list (legacy) or a
    ``{"choice": [...], "meta": {...}}`` record with measurement
    provenance (TVM cost-record discipline: every cached verdict says
    when and from what measurements it was reached)."""
    return tuple(entry["choice"]) if isinstance(entry, dict) \
        else tuple(entry)


def measurement_meta(key: str) -> Optional[dict]:
    """The measurement provenance recorded for `key`, or None (cache
    miss / legacy entry)."""
    entry = _disk_cache().get(key)
    return entry.get("meta") if isinstance(entry, dict) else None


def _save_disk_cache(cache: dict):
    try:
        _CACHE_PATH.parent.mkdir(parents=True, exist_ok=True)
        _CACHE_PATH.write_text(json.dumps(cache, indent=1))
    except OSError:
        pass  # read-only home: in-process cache still works


def clear_cache():
    _memory_cache.clear()
    try:
        _CACHE_PATH.unlink()
    except OSError:
        pass


def _time_once(run: Callable[[], object], reps: int = 8) -> float:
    """Marginal seconds per call: chained calls ended by one host fetch
    (block_until_ready does not sync through the axon tunnel)."""
    import jax.numpy as jnp

    def fetch(x):
        return float(jnp.asarray(x).reshape(-1)[0])

    fetch(run())  # compile + warmup
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = run()
    fetch(out)
    t_n = time.perf_counter() - t0
    t0 = time.perf_counter()
    fetch(run())
    t_1 = time.perf_counter() - t0
    return max((t_n - t_1) / (reps - 1), 1e-9)


def autotune(key: str, candidates: Iterable[Tuple],
             make_run: Callable[[Tuple], Optional[Callable[[], object]]],
             enabled: bool = True) -> Tuple:
    """Pick the fastest candidate for `key`; cached thereafter.

    make_run(candidate) returns a nullary closure executing the kernel with
    that config (returning a fetchable array), or None if the candidate is
    invalid for the shape. With enabled=False (or when every candidate
    fails) the FIRST valid candidate is returned untimed.
    """
    from ..obs import get_registry
    reg = get_registry()
    if key in _memory_cache:
        reg.counter("dl4j_autotune_cache_hits_total",
                    "Autotune lookups served from cache",
                    labelnames=("level",)).inc(level="memory")
        return _memory_cache[key]
    disk = _disk_cache()
    if key in disk:
        reg.counter("dl4j_autotune_cache_hits_total",
                    "Autotune lookups served from cache",
                    labelnames=("level",)).inc(level="disk")
        choice = _entry_choice(disk[key])
        _memory_cache[key] = choice
        return choice

    candidates = [c for c in candidates]
    if not enabled:
        choice = candidates[0]
        _memory_cache[key] = choice
        return choice

    m_measure = reg.counter("dl4j_autotune_measurements_total",
                            "Candidate configs timed on the device")
    m_time = reg.histogram("dl4j_autotune_candidate_seconds",
                           "Marginal per-call seconds of timed candidates")
    best, best_t = None, float("inf")
    measurements = []   # per-candidate provenance for the disk record
    for cand in candidates:
        run = make_run(cand)
        if run is None:                     # invalid for the shape
            measurements.append([list(cand), None])
            continue
        try:
            t = _time_once(run)
        except Exception:  # noqa: BLE001 — config doesn't compile/fit VMEM
            measurements.append([list(cand), None])
            continue
        m_measure.inc()
        m_time.observe(t)
        measurements.append([list(cand), t])
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        best = candidates[0]
    _memory_cache[key] = best
    disk[key] = {"choice": list(best),
                 "meta": {"measured_at": time.time(),
                          "best_s": None if best_t == float("inf")
                          else best_t,
                          "candidates": len(candidates),
                          "measurements": measurements}}
    _save_disk_cache(disk)
    return best
