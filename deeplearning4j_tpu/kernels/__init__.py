"""deeplearning4j_tpu.kernels — pallas TPU kernels for the hot ops."""

from .flash_attention import flash_attention, mha_reference
from .paged_attention import paged_attention, paged_attention_reference
