"""deeplearning4j_tpu.kernels — pallas TPU kernels for the hot ops."""

from .flash_attention import flash_attention, mha_reference
