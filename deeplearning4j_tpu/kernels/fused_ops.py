"""Fused batchnorm-activation — pallas TPU kernel (SURVEY §7 R2 kernel).

Reference counterpart: libnd4j's fused ``batchnorm`` + activation epilogue
(cuDNN ``cudnnBatchNormalizationForwardInference`` followed by the fused
activation the reference's conv helpers request). At inference the whole
BN collapses to a per-channel affine y = act(x * scale + shift) with

    scale = gamma / sqrt(var + eps),   shift = beta - mean * scale

precomputed once; the kernel then makes ONE bandwidth-bound pass over x:
rows stream through VMEM in blocks, the (1, C) scale/shift vectors stay
resident, and the activation is applied in-register before the row block
is written back — no (B·H·W, C) intermediate ever round-trips to HBM.

Backward (rarely needed at inference, but required for frozen-BN
fine-tuning) is recompute-based via the jnp reference, like the other
kernels in this package.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ._common import interpret_default
from ._common import pltpu

_VMEM_BUDGET = 8 << 20  # row blocks stay comfortably inside VMEM


def plan_blocks(n: int, c: int, itemsize: int, buffers: int = 2):
    """Row-block size for an (N, C) pass, or None when no clean block fits
    VMEM (callers fall back to the XLA path). `buffers` is how many
    (block, C) tensors the kernel keeps resident per grid step (in + out =
    2 for the forward passes; the backward dx pass streams x, g AND dx =
    3). A non-divisible N is only acceptable when the WHOLE array is one
    small block."""
    for cand in (1024, 512, 256, 128, 8):
        if n % cand == 0 \
                and buffers * cand * c * max(itemsize, 4) <= _VMEM_BUDGET:
            return cand
    if buffers * n * c * max(itemsize, 4) <= _VMEM_BUDGET:
        return n
    return None

_ACTS = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "swish": jax.nn.swish,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "softplus": jax.nn.softplus,
}


def supported_activation(name) -> bool:
    return isinstance(name, str) and name in _ACTS


_interpret_default = interpret_default


def bn_act_reference(x2d, scale, shift, activation: str):
    """jnp oracle AND recompute target: act(x * scale + shift), (N, C)."""
    return _ACTS[activation](x2d * scale[None, :] + shift[None, :])


def _kernel(x_ref, scale_ref, shift_ref, o_ref, *, activation):
    y = (x_ref[...].astype(jnp.float32) * scale_ref[...]
         + shift_ref[...])
    o_ref[...] = _ACTS[activation](y).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_bn_act(x2d, scale, shift, activation: str = "identity",
                 interpret=None):
    """(N, C) rows × per-channel affine + activation, one HBM pass."""
    out, _ = _fwd(x2d, scale, shift, activation, interpret)
    return out


def _fwd(x2d, scale, shift, activation, interpret):
    res = (x2d, scale, shift)
    if pltpu is None:
        return bn_act_reference(x2d, scale, shift, activation
                                ).astype(x2d.dtype), res
    if interpret is None:
        interpret = _interpret_default()
    n, c = x2d.shape
    bn = plan_blocks(n, c, x2d.dtype.itemsize)
    if bn is None:                       # no VMEM-safe blocking: XLA path
        return bn_act_reference(x2d, scale, shift, activation
                                ).astype(x2d.dtype), res
    out = pl.pallas_call(
        functools.partial(_kernel, activation=activation),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2d.dtype),
        interpret=interpret,
    )(x2d, scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32))
    return out, res


def _bwd(activation, interpret, res, g):
    x2d, scale, shift = res
    # cast like the primal does: without it the recompute emits f32 for
    # bf16 x (promotion with the f32 scale/shift) and the VJP then
    # rejects the incoming bf16 cotangent
    _, vjp_fn = jax.vjp(
        lambda x, sc, sh: bn_act_reference(x, sc, sh, activation
                                           ).astype(x2d.dtype),
        x2d, scale, shift)
    return vjp_fn(g)


fused_bn_act.defvjp(_fwd, _bwd)


# ------------------------------------------------------------------ training
# Training-path BN+activation (the cuDNN BatchNormalizationForwardTraining /
# Backward regime, reference org.deeplearning4j.nn.layers.normalization.
# BatchNormalization via its cuDNN helper): batch statistics computed from x
# with the one-pass shifted-moment trick, then ONE normalize+activation
# sweep; the custom VJP implements the standard BN backward (two fused
# sweeps: reductions, then dx) instead of letting autodiff save the
# pre-activation tensor as a residual.

_ACT_GRADS = {
    # act'(z) computed straight from the PRE-activation z, so the backward
    # never needs the activation output as a residual
    "identity": lambda z: jnp.ones_like(z),
    "relu": lambda z: (z > 0).astype(z.dtype),
    "relu6": lambda z: ((z > 0) & (z < 6.0)).astype(z.dtype),
    "sigmoid": lambda z: jax.nn.sigmoid(z) * (1 - jax.nn.sigmoid(z)),
    "tanh": lambda z: 1.0 - jnp.square(jnp.tanh(z)),
    "leakyrelu": lambda z: jnp.where(z > 0, 1.0, 0.01).astype(z.dtype),
    "softplus": lambda z: jax.nn.sigmoid(z),
}


def supported_train_activation(name) -> bool:
    return isinstance(name, str) and name in _ACT_GRADS


def _train_stats(x2d, center):
    """One-pass shifted batch moments (same numerics as the jnp train path):
    mean = c + E[x-c], var = E[(x-c)^2] - E[x-c]^2, clamped at 0."""
    n, c = x2d.shape
    xf = x2d.astype(jnp.float32)
    d = xf - center[None, :]
    s1 = jnp.sum(d, axis=0)
    s2 = jnp.sum(d * d, axis=0)
    mean = center + s1 / n
    var = jnp.maximum(s2 / n - jnp.square(s1 / n), 0.0)
    return mean, var


def _stats_kernel(x_ref, c_ref, s_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)

    d = x_ref[...].astype(jnp.float32) - c_ref[...]
    s_ref[0:1, :] += jnp.sum(d, axis=0, keepdims=True)
    s_ref[1:2, :] += jnp.sum(d * d, axis=0, keepdims=True)


def _bn_bwd_reduce_kernel(x_ref, g_ref, scale_ref, shift_ref, minv_ref,
                          r_ref, *, activation):
    """Accumulate dbeta = sum(dz) and dgamma = sum(dz * xhat) over row
    blocks; z and xhat are recomputed in-register from x."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        r_ref[...] = jnp.zeros_like(r_ref)

    xf = x_ref[...].astype(jnp.float32)
    z = xf * scale_ref[...] + shift_ref[...]
    dz = g_ref[...].astype(jnp.float32) * _ACT_GRADS[activation](z)
    # xhat = (x - mean) * inv = (z - beta_hat) / gamma ... recompute from
    # x directly with (mean, inv) folded into minv rows: [mean; inv]
    xhat = (xf - minv_ref[0:1, :]) * minv_ref[1:2, :]
    r_ref[0:1, :] += jnp.sum(dz, axis=0, keepdims=True)
    r_ref[1:2, :] += jnp.sum(dz * xhat, axis=0, keepdims=True)


def _bn_bwd_dx_kernel(x_ref, g_ref, scale_ref, shift_ref, minv_ref,
                      corr_ref, dx_ref, *, activation):
    """dx = scale * (dz - dbeta/N - xhat * dgamma/N); corr rows hold
    [dbeta/N ; dgamma/N]."""
    xf = x_ref[...].astype(jnp.float32)
    z = xf * scale_ref[...] + shift_ref[...]
    dz = g_ref[...].astype(jnp.float32) * _ACT_GRADS[activation](z)
    xhat = (xf - minv_ref[0:1, :]) * minv_ref[1:2, :]
    dx = scale_ref[...] * (dz - corr_ref[0:1, :] - xhat * corr_ref[1:2, :])
    dx_ref[...] = dx.astype(dx_ref.dtype)


def bn_act_train_reference(x2d, gamma, beta, center, eps, activation):
    """jnp oracle: batch-stats BN + activation, one-pass shifted moments."""
    mean, var = _train_stats(x2d, center)
    inv = lax.rsqrt(var + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean * scale
    y = _ACTS[activation](x2d.astype(jnp.float32) * scale + shift)
    return y.astype(x2d.dtype), mean, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_bn_act_train(x2d, gamma, beta, center, eps: float = 1e-5,
                       activation: str = "identity", interpret=None):
    """(N, C) training BN: batch stats -> act(x*scale+shift).

    Returns ``(y, mean, var)`` — mean/var are the BATCH statistics (f32),
    for the caller's running-average update; their output cotangents are
    treated as zero (they feed stop-gradient EMA state, never the loss).
    ``center`` is the f32 per-channel shift for the one-pass moments
    (callers pass the running mean; in exact arithmetic the moments are
    independent of it, so its cotangent is zero).
    """
    (y, mean, var), _ = _train_fwd(x2d, gamma, beta, center, eps, activation,
                                   interpret)
    # enforce the VJP contract in the primal too: the stats outputs are
    # EMA-only, so differentiating through them must not silently drop terms
    return y, lax.stop_gradient(mean), lax.stop_gradient(var)


def _train_fwd(x2d, gamma, beta, center, eps, activation, interpret):
    n, c = x2d.shape
    if interpret is None:
        interpret = _interpret_default()
    bn = None if pltpu is None else plan_blocks(n, c, x2d.dtype.itemsize)
    if bn is None:
        y, mean, var = bn_act_train_reference(x2d, gamma, beta, center, eps,
                                              activation)
        inv = lax.rsqrt(var + eps)
        return (y, mean, var), (x2d, gamma, beta, mean, inv)
    s = pl.pallas_call(
        _stats_kernel,
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((2, c), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        interpret=interpret,
    )(x2d, center.reshape(1, c).astype(jnp.float32))
    mean = center + s[0] / n
    var = jnp.maximum(s[1] / n - jnp.square(s[0] / n), 0.0)
    inv = lax.rsqrt(var + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean * scale
    y = pl.pallas_call(
        functools.partial(_kernel, activation=activation),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2d.dtype),
        interpret=interpret,
    )(x2d, scale.reshape(1, c), shift.reshape(1, c))
    return (y, mean, var), (x2d, gamma, beta, mean, inv)


def _train_bwd(eps, activation, interpret, res, cotangents):
    g = cotangents[0]  # (dy, dmean, dvar) — stats cotangents are EMA-only
    x2d, gamma, beta, mean, inv = res
    dcenter = jnp.zeros_like(mean)
    n, c = x2d.shape
    if interpret is None:
        interpret = _interpret_default()
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean * scale
    # 3 resident row blocks in the dx pass (x, g, dx)
    bn = None if pltpu is None else plan_blocks(n, c, x2d.dtype.itemsize,
                                                buffers=3)
    if bn is None:
        xf = x2d.astype(jnp.float32)
        z = xf * scale[None, :] + shift[None, :]
        dz = g.astype(jnp.float32) * _ACT_GRADS[activation](z)
        xhat = (xf - mean[None, :]) * inv[None, :]
        dbeta = jnp.sum(dz, axis=0)
        dgamma = jnp.sum(dz * xhat, axis=0)
        dx = scale[None, :] * (dz - dbeta[None, :] / n
                               - xhat * dgamma[None, :] / n)
        return (dx.astype(x2d.dtype), dgamma.astype(gamma.dtype),
                dbeta.astype(beta.dtype), dcenter)
    minv = jnp.stack([mean, inv]).astype(jnp.float32)          # (2, C)
    common = [pl.BlockSpec((bn, c), lambda i: (i, 0)),         # x
              pl.BlockSpec((bn, c), lambda i: (i, 0)),         # g
              pl.BlockSpec((1, c), lambda i: (0, 0)),          # scale
              pl.BlockSpec((1, c), lambda i: (0, 0)),          # shift
              pl.BlockSpec((2, c), lambda i: (0, 0))]          # [mean; inv]
    r = pl.pallas_call(
        functools.partial(_bn_bwd_reduce_kernel, activation=activation),
        grid=(n // bn,),
        in_specs=common,
        out_specs=pl.BlockSpec((2, c), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        interpret=interpret,
    )(x2d, g, scale.reshape(1, c), shift.reshape(1, c), minv)
    dbeta, dgamma = r[0], r[1]
    dx = pl.pallas_call(
        functools.partial(_bn_bwd_dx_kernel, activation=activation),
        grid=(n // bn,),
        in_specs=common + [pl.BlockSpec((2, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2d.dtype),
        interpret=interpret,
    )(x2d, g, scale.reshape(1, c), shift.reshape(1, c), minv,
      (r / n).astype(jnp.float32))
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
            dcenter)


fused_bn_act_train.defvjp(_train_fwd, _train_bwd)
