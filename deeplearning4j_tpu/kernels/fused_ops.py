"""Fused batchnorm-activation — pallas TPU kernel (SURVEY §7 R2 kernel).

Reference counterpart: libnd4j's fused ``batchnorm`` + activation epilogue
(cuDNN ``cudnnBatchNormalizationForwardInference`` followed by the fused
activation the reference's conv helpers request). At inference the whole
BN collapses to a per-channel affine y = act(x * scale + shift) with

    scale = gamma / sqrt(var + eps),   shift = beta - mean * scale

precomputed once; the kernel then makes ONE bandwidth-bound pass over x:
rows stream through VMEM in blocks, the (1, C) scale/shift vectors stay
resident, and the activation is applied in-register before the row block
is written back — no (B·H·W, C) intermediate ever round-trips to HBM.

Backward (rarely needed at inference, but required for frozen-BN
fine-tuning) is recompute-based via the jnp reference, like the other
kernels in this package.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret_default
from ._common import pltpu

_VMEM_BUDGET = 8 << 20  # row blocks stay comfortably inside VMEM


def plan_blocks(n: int, c: int, itemsize: int):
    """Row-block size for an (N, C) pass, or None when no clean block fits
    VMEM (callers fall back to the XLA path). A non-divisible N is only
    acceptable when the WHOLE array is one small block."""
    for cand in (1024, 512, 256, 128, 8):
        if n % cand == 0 and 2 * cand * c * max(itemsize, 4) <= _VMEM_BUDGET:
            return cand
    if 2 * n * c * max(itemsize, 4) <= _VMEM_BUDGET:
        return n
    return None

_ACTS = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "swish": jax.nn.swish,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "softplus": jax.nn.softplus,
}


def supported_activation(name) -> bool:
    return isinstance(name, str) and name in _ACTS


_interpret_default = interpret_default


def bn_act_reference(x2d, scale, shift, activation: str):
    """jnp oracle AND recompute target: act(x * scale + shift), (N, C)."""
    return _ACTS[activation](x2d * scale[None, :] + shift[None, :])


def _kernel(x_ref, scale_ref, shift_ref, o_ref, *, activation):
    y = (x_ref[...].astype(jnp.float32) * scale_ref[...]
         + shift_ref[...])
    o_ref[...] = _ACTS[activation](y).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_bn_act(x2d, scale, shift, activation: str = "identity",
                 interpret=None):
    """(N, C) rows × per-channel affine + activation, one HBM pass."""
    out, _ = _fwd(x2d, scale, shift, activation, interpret)
    return out


def _fwd(x2d, scale, shift, activation, interpret):
    res = (x2d, scale, shift)
    if pltpu is None:
        return bn_act_reference(x2d, scale, shift, activation
                                ).astype(x2d.dtype), res
    if interpret is None:
        interpret = _interpret_default()
    n, c = x2d.shape
    bn = plan_blocks(n, c, x2d.dtype.itemsize)
    if bn is None:                       # no VMEM-safe blocking: XLA path
        return bn_act_reference(x2d, scale, shift, activation
                                ).astype(x2d.dtype), res
    out = pl.pallas_call(
        functools.partial(_kernel, activation=activation),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, c), x2d.dtype),
        interpret=interpret,
    )(x2d, scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32))
    return out, res


def _bwd(activation, interpret, res, g):
    x2d, scale, shift = res
    _, vjp_fn = jax.vjp(
        lambda x, sc, sh: bn_act_reference(x, sc, sh, activation),
        x2d, scale, shift)
    return vjp_fn(g)


fused_bn_act.defvjp(_fwd, _bwd)
