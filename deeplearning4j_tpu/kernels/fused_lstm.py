"""Fused whole-sequence LSTM — pallas TPU kernel (SURVEY §7 R2 kernel).

Replaces the reference's cuDNN RNN helper (libnd4j ``lstmLayer``/cuDNN
``cudnnRNNForward``) for the training/inference forward pass. The
TPU-native design runs the ENTIRE time loop inside one pallas kernel:

- the grid iterates t = 0..T-1 sequentially; the recurrent weights
  (H, 4H), peephole vectors, and the (B, H) h/c state live in VMEM the
  whole time (constant-index blocks are kept resident across grid steps),
  so HBM traffic per step is just the (B, 4H) input-projection block in
  and the (B, H) hidden block out — XLA's `lax.scan` loop re-reads the
  recurrent weights from HBM every iteration;
- the input projection x@W+b for ALL steps is computed OUTSIDE as one
  (B·T, 4H) MXU matmul (hoisted, as in the scan path);
- gate math matches nn.layers.recurrent.LSTM._cell exactly: gate order
  [i, f, o, g], sigmoid gates, tanh candidate/output, optional Graves
  peepholes (pI/pF on c_{t-1}, pO on c_t), f32 accumulation.

Backward is recompute-based (flash-attention-style): the custom VJP
replays the pure-jnp reference scan under jax.vjp, so no per-step gate
activations are saved — O(B·H) residual memory instead of O(B·T·4H),
which is what lets long sequences train at all.

Falls back to interpreter mode off-TPU so the same code path is
unit-testable on the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret_default as _interpret_default
from ._common import pltpu

_VMEM_BUDGET = 12 << 20  # leave headroom of the ~16 MiB per-core VMEM


def fits_vmem(b: int, h: int, itemsize: int) -> bool:
    """Whether the whole-sequence kernel's resident set fits VMEM: the
    (H, 4H) weights + (B, 4H) x-block (double-buffered) + two f32 (B, H)
    state scratches + in/out state blocks. Callers fall back to the
    lax.scan path when this is False — a model that trained fine there
    must never start failing to compile because of an 'auto' kernel."""
    resident = (h * 4 * h * itemsize          # RW, constant block
                + 2 * b * 4 * h * itemsize    # streamed x-proj, dbl-buffered
                + 2 * b * h * 4               # h/c f32 scratch
                + 4 * b * h * itemsize        # h0/c0 in + out block (dbl)
                + 3 * h * 4)                  # peepholes
    return resident <= _VMEM_BUDGET


# ------------------------------------------------------------ reference ----
def lstm_seq_reference(xproj, rw, peep, h0, c0):
    """Pure-jnp oracle AND the recompute target for the backward pass.

    xproj (B, T, 4H) = x@W + b; rw (H, 4H); peep (3, H) [pI, pF, pO]
    (zeros for a plain LSTM); h0/c0 (B, H). Returns hs (B, T, H).
    """
    h = h0.shape[-1]

    def step(carry, xt):
        h_prev, c_prev = carry
        # gate math in f32 regardless of the (possibly bf16) carry dtype —
        # matches the kernel's f32 scratch state
        z = (xt + h_prev @ rw).astype(jnp.float32)
        c32 = c_prev.astype(jnp.float32)
        zi, zf, zo, zg = (z[:, :h], z[:, h:2 * h],
                          z[:, 2 * h:3 * h], z[:, 3 * h:])
        zi = zi + c32 * peep[0]
        zf = zf + c32 * peep[1]
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        g = jnp.tanh(zg)
        c_new = f * c32 + i * g
        zo = zo + c_new * peep[2]
        o = jax.nn.sigmoid(zo)
        h_new = o * jnp.tanh(c_new)
        return (h_new.astype(h_prev.dtype), c_new.astype(c_prev.dtype)), \
            h_new.astype(h_prev.dtype)

    _, hs = jax.lax.scan(step, (h0, c0), xproj.swapaxes(0, 1))
    return hs.swapaxes(0, 1)


# --------------------------------------------------------------- kernel ----
def _lstm_kernel(xproj_ref, rw_ref, peep_ref, h0_ref, c0_ref,
                 out_ref, h_s, c_s):
    t = pl.program_id(0)
    hdim = h_s.shape[-1]

    @pl.when(t == 0)
    def _init():
        h_s[...] = h0_ref[...].astype(jnp.float32)
        c_s[...] = c0_ref[...].astype(jnp.float32)

    h_prev = h_s[...]
    c_prev = c_s[...]
    # matmul in the weights' dtype (bf16 runs at full MXU rate), f32 accum;
    # the h/c state itself stays f32 in scratch across all steps
    z = xproj_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h_prev.astype(rw_ref.dtype), rw_ref[...],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    peep = peep_ref[...].astype(jnp.float32)       # (3, H) resident
    zi = z[:, :hdim] + c_prev * peep[0:1, :]
    zf = z[:, hdim:2 * hdim] + c_prev * peep[1:2, :]
    zo = z[:, 2 * hdim:3 * hdim]
    zg = z[:, 3 * hdim:]
    i = jax.nn.sigmoid(zi)
    f = jax.nn.sigmoid(zf)
    g = jnp.tanh(zg)
    c_new = f * c_prev + i * g
    o = jax.nn.sigmoid(zo + c_new * peep[2:3, :])
    h_new = o * jnp.tanh(c_new)
    h_s[...] = h_new
    c_s[...] = c_new
    out_ref[0] = h_new.astype(out_ref.dtype)


def _lstm_pallas(xproj, rw, peep, h0, c0, interpret):
    b, t, g4 = xproj.shape
    h = g4 // 4
    # time-major so every streamed block is a FULL (B, 4H) slice — pallas
    # TPU requires the last two block dims be (8, 128)-aligned or whole
    hs = pl.pallas_call(
        _lstm_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, g4), lambda i: (i, 0, 0)),  # streamed x-proj
            pl.BlockSpec((h, g4), lambda i: (0, 0)),        # resident weights
            pl.BlockSpec((3, h), lambda i: (0, 0)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((t, b, h), xproj.dtype),
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32),
                        pltpu.VMEM((b, h), jnp.float32)],
        interpret=interpret,
    )(xproj.swapaxes(0, 1), rw, peep, h0, c0)
    return hs.swapaxes(0, 1)


# ------------------------------------------------------------ public -------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_lstm_seq(xproj, rw, peep, h0, c0, interpret=None):
    """Whole-sequence LSTM: (B, T, 4H) projections → (B, T, H) hiddens."""
    out, _ = _fwd(xproj, rw, peep, h0, c0, interpret)
    return out


def _fwd(xproj, rw, peep, h0, c0, interpret):
    if pltpu is None:
        return lstm_seq_reference(xproj, rw, peep, h0, c0), \
            (xproj, rw, peep, h0, c0)
    if interpret is None:
        interpret = _interpret_default()
    out = _lstm_pallas(xproj, rw, peep, h0, c0, interpret)
    return out, (xproj, rw, peep, h0, c0)


def _bwd(interpret, res, g):
    xproj, rw, peep, h0, c0 = res
    # recompute-backward: replay the jnp scan under vjp (no stored gates)
    _, vjp_fn = jax.vjp(lstm_seq_reference, xproj, rw, peep, h0, c0)
    return vjp_fn(g)


fused_lstm_seq.defvjp(_fwd, _bwd)
