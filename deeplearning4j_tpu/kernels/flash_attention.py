"""Flash attention — pallas TPU kernel (FlashAttention-2 schedule).

Replaces the reference's cuDNN/libnd4j fused-attention path
(``org.deeplearning4j.nn.layers.recurrent/attention``, libnd4j
``multiHeadDotProductAttention``) with a TPU-native kernel: online-softmax
tiling keeps the (T, T) score matrix out of HBM, MXU matmuls accumulate in
f32, and the backward pass recomputes probabilities per tile (two passes:
dQ over query tiles, dK/dV over key tiles) instead of materialising them.

Shapes: q, k, v are (B, H, T, D); output (B, H, T, D). ``causal`` applies a
lower-triangular mask. Falls back to interpreter mode off-TPU so the same
code path is unit-testable on the CPU mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(t: int, d: int, block_q: int, block_k: int):
    bq = min(block_q, t)
    bk = min(block_k, t)
    while t % bq:
        bq //= 2
    while t % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


# ---------------------------------------------------------------- forward --

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale           # (bq, d)
    bq, d = q.shape
    t = k_ref.shape[1]
    nk = t // block_k

    def body(kj, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_idx = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_idx > q_idx, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq,), NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    if causal:
        # only key blocks up to (and including) this query block contribute
        nk_eff = ((qi + 1) * bq + block_k - 1) // block_k
        nk_eff = jnp.minimum(nk_eff, nk)
        acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (acc, m, l))
    else:
        acc, m, l = jax.lax.fori_loop(0, nk, body, (acc, m, l))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse broadcast over a small lane dim so the block shape is TPU-tileable
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l_safe))[:, None], (bq, 8))


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    bq, bk = _block_sizes(t, d, block_q, block_k)
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    grid = (b * h, t // bq)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, block_k=bk),
        grid=grid,
        in_specs=[pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
                  pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
                  pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0))],
        out_specs=[pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
                   pl.BlockSpec((1, bq, 8), lambda bh, i: (bh, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, t, 8), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d), lse[:, :, 0].reshape(b, h, t)


# --------------------------------------------------------------- backward --

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]
    bq, d = q.shape
    t = k_ref.shape[1]
    nk = t // block_k

    def body(kj, dq):
        k = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_idx = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_idx > q_idx, NEG_INF, s)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jnp.zeros((bq, d), jnp.float32)
    if causal:  # skip fully-masked key blocks, mirroring the forward
        nk_eff = jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, nk)
        dq = jax.lax.fori_loop(0, nk_eff, body, dq)
    else:
        dq = jax.lax.fori_loop(0, nk, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q):
    kj = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    t = q_ref.shape[1]
    nq = t // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_idx = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_idx > q_idx, NEG_INF, s)
        p = jnp.exp(s - lse[:, None])
        dv_new = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    if causal:  # first query block that can attend to this key block
        qi_start = (kj * bk) // block_q
        dk, dv = jax.lax.fori_loop(qi_start, nq, body, (dk, dv))
    else:
        dk, dv = jax.lax.fori_loop(0, nq, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ------------------------------------------------------------- public api --

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Fused scaled-dot-product attention. q/k/v: (B, H, T, D) → (B, H, T, D)."""
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = _interpret_default()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if interpret is None:
        interpret = _interpret_default()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, t, d = q.shape
    bq, bk = _block_sizes(t, d, block_q, block_k)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    flat = lambda x: x.reshape(b * h, t, -1)
    qf, kf, vf, dof = flat(q), flat(k), flat(v), flat(g)
    lsef = jnp.broadcast_to(lse.reshape(b * h, t)[:, :, None], (b * h, t, 8))
    deltaf = jnp.broadcast_to(delta.reshape(b * h, t)[:, :, None], (b * h, t, 8))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, block_k=bk),
        grid=(b * h, t // bq),
        in_specs=[pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
                  pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
                  pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
                  pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
                  pl.BlockSpec((1, bq, 8), lambda bh, i: (bh, i, 0)),
                  pl.BlockSpec((1, bq, 8), lambda bh, i: (bh, i, 0))],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, block_q=bq),
        grid=(b * h, t // bk),
        in_specs=[pl.BlockSpec((1, t, d), lambda bh, j: (bh, 0, 0)),
                  pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
                  pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
                  pl.BlockSpec((1, t, d), lambda bh, j: (bh, 0, 0)),
                  pl.BlockSpec((1, t, 8), lambda bh, j: (bh, 0, 0)),
                  pl.BlockSpec((1, t, 8), lambda bh, j: (bh, 0, 0))],
        out_specs=[pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
                   pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, t, d), q.dtype)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    shape = (b, h, t, d)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_ntc(q, k, v, causal=False, interpret=None):
    """(B, T, H, D)-layout adapter around :func:`flash_attention` — the
    layout the nn layers and the transformer use."""
    out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), None, causal, 128, 128,
                          interpret)
    return out.transpose(0, 2, 1, 3)


def mha_reference(q, k, v, scale=None, causal=False):
    """Plain-XLA oracle used by tests and as a fallback."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
