"""Flash attention — pallas TPU kernel (FlashAttention-2 schedule).

Replaces the reference's cuDNN/libnd4j fused-attention path
(``org.deeplearning4j.nn.layers.recurrent/attention``, libnd4j
``multiHeadDotProductAttention``) with a TPU-native kernel: online-softmax
tiling keeps the (T, T) score matrix out of HBM, MXU matmuls accumulate in
f32, and the backward pass recomputes probabilities per tile (two passes:
dQ over query tiles, dK/dV over key tiles) instead of materialising them.

VMEM discipline: K/V (and in the backward passes Q/dO/lse/delta) STREAM
through the kernel one block per grid step — the KV/Q block index is the
fastest grid dimension and the online-softmax state lives in VMEM scratch
that persists across it (TPU grids iterate sequentially). Peak VMEM is
O(block_q·d + block_k·d), independent of sequence length, so the kernel
works exactly in the long-context regime flash attention exists for.

Shapes: q, k, v are (B, H, T, D); output (B, H, T, D). ``causal`` applies a
lower-triangular mask (fully-masked blocks are skipped via pl.when).
Falls back to interpreter mode off-TPU so the same code path is
unit-testable on the CPU mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret_default as _interpret_default
from ._common import pltpu

NEG_INF = -1e30


def _block_sizes(t: int, d: int, block_q: int, block_k: int):
    bq = min(block_q, t)
    bk = min(block_k, t)
    while t % bq:
        bq //= 2
    while t % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


# ---------------------------------------------------------------- forward --

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: skip key blocks that lie entirely above the diagonal
    live = (kj * bk <= qi * bq + bq - 1) if causal else (kj >= 0)

    @pl.when(live)
    def _compute():
        # operands stay bf16: the v5e MXU multiplies bf16 natively with
        # f32 accumulation (preferred_element_type); casting to f32 first
        # runs the MXU at a fraction of peak and doubles VMEM traffic
        q = q_ref[0]                                        # (bq, d)
        k = k_ref[0]                                        # (bk, d)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32
                                ) * scale                   # (bq, bk) f32
        if causal:
            q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_idx = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_idx > q_idx, NEG_INF, s)
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse broadcast over a small lane dim so the block is TPU-tileable
        lse_ref[0] = jnp.broadcast_to(
            (m_ref[:, 0] + jnp.log(l_safe))[:, None], lse_ref.shape[1:])


def _causal_kv_map(bq, bk, causal):
    """KV-block index map. For causal grids, dead steps (key block entirely
    above the diagonal) CLAMP to the last live key block: Pallas skips the
    HBM->VMEM fetch when successive steps reference the same block, so the
    ~half of the rectangular grid that pl.when skips stops costing
    bandwidth too. (Compute for dead steps is already skipped; without the
    clamp their DMAs still ran — measured ~2x wasted attention traffic at
    long T.)"""
    if not causal:
        return lambda bh, i, j: (bh, j, 0)
    return lambda bh, i, j: (bh, jnp.minimum(j, (i * bq + bq - 1) // bk), 0)


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    bq, bk = _block_sizes(t, d, block_q, block_k)
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    kv_map = _causal_kv_map(bq, bk, causal)
    grid = (b * h, t // bq, t // bk)      # kv block = fastest dim (streamed)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal),
        grid=grid,
        in_specs=[pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
                  pl.BlockSpec((1, bk, d), kv_map),
                  pl.BlockSpec((1, bk, d), kv_map)],
        out_specs=[pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
                   pl.BlockSpec((1, bq, 8), lambda bh, i, j: (bh, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, t, 8), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq, 8), jnp.float32),
                        pltpu.VMEM((bq, 8), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d), lse[:, :, 0].reshape(b, h, t)


# --------------------------------------------------------------- backward --

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc_ref, *, scale, causal):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    live = (kj * bk <= qi * bq + bq - 1) if causal else (kj >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_idx = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_idx > q_idx, NEG_INF, s)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(k.dtype)
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, scale, causal):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # causal: only query blocks at or below this key block contribute
    live = (qi * bq + bq - 1 >= kj * bk) if causal else (qi >= 0)

    @pl.when(live)
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32
                                ) * scale                    # (bq, bk)
        if causal:
            q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_idx = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_idx > q_idx, NEG_INF, s)
        p = jnp.exp(s - lse[:, None])
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


# ------------------------------------------------------------- public api --

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_pallas(q, k, v, scale: Optional[float] = None,
                            causal: bool = False, block_q: int = 128,
                            block_k: int = 128,
                            interpret: Optional[bool] = None):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def flash_attention(q, k, v, scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Fused scaled-dot-product attention. q/k/v: (B, H, T, D) → (B, H, T, D).

    On jaxlib builds without Pallas-TPU support (``pltpu`` unimportable) this
    transparently falls back to the plain-XLA :func:`mha_reference` path so
    the module stays usable (plain jax autodiff replaces the custom VJP).
    """
    if pltpu is None:
        return mha_reference(q, k, v, scale, causal).astype(q.dtype)
    return _flash_attention_pallas(q, k, v, scale, causal, block_q, block_k,
                                   interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = _interpret_default()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    return _flash_bwd_impl(scale, causal, block_q, block_k, interpret,
                           q, k, v, g, lse, delta)


def _flash_bwd_impl(scale, causal, block_q, block_k, interpret,
                    q, k, v, g, lse, delta):
    """Shared backward. ``delta`` is rowsum(dO·O) for the plain kernel; the
    lse-returning variant passes rowsum(dO·O) − dLSE instead — the ONLY
    difference an lse cotangent makes (ds = p·(dp − delta + dlse), so it
    folds into delta; dv is dlse-independent)."""
    if interpret is None:
        interpret = _interpret_default()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, t, d = q.shape
    bq, bk = _block_sizes(t, d, block_q, block_k)
    flat = lambda x: x.reshape(b * h, t, -1)
    qf, kf, vf, dof = flat(q), flat(k), flat(v), flat(g)
    lsef = jnp.broadcast_to(lse.reshape(b * h, t)[:, :, None], (b * h, t, 8))
    deltaf = jnp.broadcast_to(delta.reshape(b * h, t)[:, :, None], (b * h, t, 8))

    kv_map = _causal_kv_map(bq, bk, causal)
    if causal:
        # dkv grid streams q blocks; dead steps (q block entirely above the
        # diagonal) clamp to the FIRST live q block — same no-refetch trick
        # as _causal_kv_map, mirrored
        q_map = lambda bh, j, i: (bh, jnp.maximum(i, (j * bk) // bq), 0)
    else:
        q_map = lambda bh, j, i: (bh, i, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal),
        grid=(b * h, t // bq, t // bk),   # kv block streamed (fastest dim)
        in_specs=[pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
                  pl.BlockSpec((1, bk, d), kv_map),
                  pl.BlockSpec((1, bk, d), kv_map),
                  pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
                  pl.BlockSpec((1, bq, 8), lambda bh, i, j: (bh, i, 0)),
                  pl.BlockSpec((1, bq, 8), lambda bh, i, j: (bh, i, 0))],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal),
        grid=(b * h, t // bk, t // bq),   # q block streamed (fastest dim)
        in_specs=[pl.BlockSpec((1, bq, d), q_map),
                  pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
                  pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
                  pl.BlockSpec((1, bq, d), q_map),
                  # lse/delta stream with the q block — clamp them too, or
                  # dead causal steps keep fetching these (1, bq, 8) blocks
                  pl.BlockSpec((1, bq, 8), q_map),
                  pl.BlockSpec((1, bq, 8), q_map)],
        out_specs=[pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
                   pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, t, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    shape = (b, h, t, d)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


_flash_attention_pallas.defvjp(_flash_fwd, _flash_bwd)


# ------------------------------------------------------- lse-returning api --

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_lse_pallas(q, k, v, scale: Optional[float] = None,
                                causal: bool = False, block_q: int = 128,
                                block_k: int = 128,
                                interpret: Optional[bool] = None):
    (out, lse), _ = _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k,
                                   interpret)
    return out, lse


def flash_attention_lse(q, k, v, scale: Optional[float] = None,
                        causal: bool = False, block_q: int = 128,
                        block_k: int = 128,
                        interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the per-row
    log-sum-exp, ``lse`` (B, H, T) f32 — the quantity ring attention needs
    to merge partial attention results across sequence shards. The custom
    VJP propagates BOTH cotangents (dLSE folds into the delta term; see
    `_flash_bwd_impl`). Falls back to a plain-XLA computation on jaxlib
    builds without Pallas-TPU support (same policy as flash_attention)."""
    if pltpu is None:
        if scale is None:
            scale = 1.0 / math.sqrt(q.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            t = q.shape[2]
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask, s, NEG_INF)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        p = jnp.exp(s - lse[..., None])
        out = jnp.einsum("bhqk,bhkd->bhqd", p,
                         v.astype(jnp.float32)).astype(q.dtype)
        return out, lse
    return _flash_attention_lse_pallas(q, k, v, scale, causal, block_q,
                                       block_k, interpret)


def _flash_fwd_lse(q, k, v, scale, causal, block_q, block_k, interpret):
    out, res = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return (out, res[4]), res


def _flash_bwd_lse(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    delta = jnp.sum(g_out.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    if g_lse is not None and jnp.issubdtype(
            getattr(g_lse, "dtype", jnp.float32), jnp.floating):
        delta = delta - g_lse.astype(jnp.float32)
    return _flash_bwd_impl(scale, causal, block_q, block_k, interpret,
                           q, k, v, g_out, lse, delta)


_flash_attention_lse_pallas.defvjp(_flash_fwd_lse, _flash_bwd_lse)


def _tuned_blocks(b, h, t, d, dtype, causal, interpret) -> tuple:
    """Autotuned (block_q, block_k) for this attention shape — timed on the
    real chip once, cached to disk (kernels/autotune.py). Off-TPU (or with
    tuning disabled) falls back to the measured v5e sweet spot
    (min(512,T), min(1024,T)) rather than re-timing."""
    import os

    if interpret or jax.default_backend() != "tpu" \
            or os.environ.get("DL4J_TPU_AUTOTUNE", "1") != "1":
        return _block_sizes(t, d, 512, 1024)
    from .autotune import autotune

    def make_run(cand):
        bq, bk = cand
        if t % bq or t % bk:
            return None
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (b, h, t, d), dtype)

        # Time the TRAIN path (fwd + both bwd passes): block-size choice is
        # dominated by the backward kernels, and a fwd-only race mispicks
        # (the flash4 tuner's 128×128 regression).
        def loss(q_, k_, v_):
            return jnp.sum(_flash_attention_pallas(
                q_, k_, v_, None, causal, bq, bk, False
            ).astype(jnp.float32))

        grad_fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def run():
            return grad_fn(q, q, q)[0]
        return run

    chip = jax.devices()[0].device_kind.replace(" ", "_")
    # "flash5" (r5): tune on the GRAD path with large-block candidates.
    # The flash4 tuner timed the forward kernel only and picked 128×128,
    # but the 128-vs-1024 block gap lives in the two backward passes: the
    # diag_t4096 phase-F fwd+bwd sweep (2026-08-01, v5e) measured t4096/b4
    # 34.0 ms at 128×128 vs 6.1 ms at 1024×1024, and t1024/b16 9.9 ms vs
    # 2.1 ms at 512×1024 — the grid is (B·H)(T/bq)(T/bk) SEQUENTIAL steps,
    # and per-step grid+DMA overhead (~1 µs) dominates small blocks.
    # Candidates ≥2048 are dropped: the remote compiler rejects them
    # (HTTP 500, same sweep), and 1024×1024 (s block 4 MB f32 + kv 256 KB)
    # already sits well inside VMEM at d=64.
    return autotune(
        f"flash5:{chip}:{b}x{h}x{t}x{d}:{jnp.dtype(dtype).name}:{causal}",
        [(512, 1024), (1024, 1024), (1024, 512), (512, 512),
         (256, 512), (256, 256), (128, 128)],
        make_run)


def flash_attention_ntc(q, k, v, causal=False, interpret=None):
    """(B, T, H, D)-layout adapter around :func:`flash_attention` — the
    layout the nn layers and the transformer use. Block sizes are
    autotuned per shape on the real chip."""
    b, t, h, d = q.shape
    bq, bk = _tuned_blocks(b, h, t, d, q.dtype, causal, interpret)
    out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), None, causal, bq, bk,
                          interpret)
    return out.transpose(0, 2, 1, 3)


def mha_reference(q, k, v, scale=None, causal=False):
    """Plain-XLA oracle used by tests and as a fallback."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
