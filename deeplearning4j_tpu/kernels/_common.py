"""Shared pallas-kernel plumbing: the jaxlib-compatibility pltpu import
(CPU-only wheels ship pallas without the TPU backend) and backend
detection — one copy for every kernel module."""

from __future__ import annotations

import jax

try:
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
except ImportError:  # pragma: no cover - depends on jaxlib build
    pltpu = None


def interpret_default() -> bool:
    return jax.default_backend() != "tpu"
