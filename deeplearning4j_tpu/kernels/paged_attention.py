"""Paged-attention decode kernel — pallas TPU (ISSUE 17 tentpole).

The paged decode path (ISSUE 14) was XLA gather-attention: every decode
step materializes each slot's WHOLE mapped KV (``kl[table]`` — a
(B, S, H, Dh) gather) in HBM before one un-fused softmax-matvec reads
it once. Correct, but the slowest possible per-step kernel: the 3.0×
concurrency win of PR 14 and the 8.8× prefix-sharing win of PR 16 both
sit on it. This module is the cuDNN move (arXiv 1410.0759) — one fused
primitive instead of composed ops:

- **block-parallel over a slot's mapped pages**: grid ``(B, P)`` with
  the logical-page index fastest (TPU grids iterate sequentially, so
  the online-softmax state lives in VMEM scratch across a slot's
  pages, exactly the FlashAttention-2 schedule
  ``flash_attention.py`` already proves);
- **no materialized gather**: the per-slot page-table row rides in as
  a scalar-prefetch operand (``pltpu.PrefetchScalarGridSpec``) and the
  K/V BlockSpec index maps read THROUGH it — each grid step DMAs one
  (page_len, H, Dh) page straight from the pool, HBM traffic is the
  mapped bytes once, with no (B, S, H, Dh) intermediate;
- **sentinel pages and partial-fill tails masked in-kernel**: an
  unmapped (sentinel ``n_pages``) entry or a page past the slot's
  cursor is dead — ``pl.when`` skips its compute, the index map clamps
  its DMA onto the last live page (the `_causal_kv_map` no-refetch
  trick), and the tail rows of the last live page mask to ``NEG_INF``
  before the running max.

Dispatch is fidelity-gated promotion (:func:`decide`), not faith: per
shape-bucket the kernel RACES the XLA gather path on probe caches of
the live geometry; promotion requires the FidelityProbe
(``paged_kernel_vs_xla``) to hold ``kl_max`` under
:data:`PROMOTION_MAX_KL` AND bit-identical greedy tokens, plus a
measured speed win. Losers fall back silently. The verdict persists as
a unified-harness cost record (``paged_decode:...`` key) stamped with
:func:`kernel_sha` — editing this kernel auto-invalidates every stale
verdict and re-races (``kernels/autotune.py``).

Off-TPU the kernel runs in pallas interpret mode (the CPU CI oracle);
on jaxlib builds without pallas-TPU support entirely, it falls back to
:func:`paged_attention_reference` — the same math the engine's gather
path runs.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ._common import interpret_default as _interpret_default
from ._common import pltpu
from . import autotune

NEG_INF = -1e30

#: promotion fidelity budget: max per-position KL(ref ‖ kernel), nats —
#: the same bound `scripts/fidelity_report.py --max-kl` gates captures
#: with. Greedy tokens must additionally match bitwise.
PROMOTION_MAX_KL = 1e-3

#: env knob for the dispatch mode when the engine doesn't pin one:
#: auto (race on TPU, gather elsewhere) | race | on | off
_MODE_ENV = "DL4J_PAGED_KERNEL"


# ------------------------------------------------------------ kernel --

def _decode_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, page_len, n_pages):
    """One grid step = one (slot b, logical page j). Scratch carries
    the slot's online-softmax state (m/l running stats + f32 acc)
    across its pages; init at j==0, emit at the last page."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    pos = pos_ref[b]
    # dead page: unmapped (sentinel) or entirely past the cursor —
    # compute skipped AND (via the clamped index map) no fresh DMA
    live = (table_ref[b, j] < n_pages) & (j * page_len <= pos)

    @pl.when(live)
    def _compute():
        q = q_ref[0]                                      # (H, Dh)
        k = k_ref[0]                                      # (PL, H, Dh)
        v = v_ref[0]
        # per-head q·k over the page: operands stay in cache dtype, the
        # MXU accumulates f32 (flash-kernel discipline)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale   # (H, PL)
        # partial-fill tail: rows past the slot's cursor mask out
        t_idx = j * page_len + jax.lax.broadcasted_iota(jnp.int32,
                                                        s.shape, 1)
        s = jnp.where(t_idx > pos, NEG_INF, s)
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)           # (H, Dh)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_ref[:, 0]
        # a slot with zero live rows (nothing mapped) emits zeros —
        # garbage-by-contract the scheduler never reads, same as the
        # gather path's clamped-garbage rows
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


# pl imported late so the module stays importable (reference path +
# promotion bookkeeping) even where jax.experimental.pallas is absent
try:
    from jax.experimental import pallas as pl
except ImportError:  # pragma: no cover - depends on jaxlib build
    pl = None


def paged_attention(q, k_pages, v_pages, table, pos,
                    interpret: Optional[bool] = None):
    """Fused single-token attention over a block-paged KV pool.

    q (B, H, Dh); k_pages/v_pages (n_pages, page_len, H, Dh) — ONE
    layer's pool; table (B, P) int32 per-slot page-table rows (sentinel
    ``n_pages`` = unmapped); pos (B,) int32 per-slot cursors (position
    ``pos[b]`` is the row just written — valid rows are
    ``<= pos[b]``, the `_cached_attention` mask contract). Returns
    (B, H, Dh) in q's dtype.

    On jaxlib builds without pallas (or pallas-TPU) support this
    transparently falls back to :func:`paged_attention_reference`.
    """
    if pl is None or pltpu is None:
        return paged_attention_reference(q, k_pages, v_pages, table, pos)
    if interpret is None:
        interpret = _interpret_default()
    b, h, dh = q.shape
    npg, plen = k_pages.shape[0], k_pages.shape[1]
    per_slot = table.shape[1]
    scale = 1.0 / math.sqrt(dh)

    def kv_map(b_, j_, tbl, ps):
        # dead steps clamp onto the slot's LAST live page so pallas
        # skips the HBM->VMEM fetch (same-block no-refetch rule); the
        # sentinel additionally clamps in-bounds for the DMA engine
        jl = jnp.minimum(j_, jnp.maximum(ps[b_], 0) // plen)
        return (jnp.minimum(tbl[b_, jl], npg - 1), 0, 0, 0)

    q_map = lambda b_, j_, tbl, ps: (b_, 0, 0)      # noqa: E731
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, per_slot),                  # page index fastest
        in_specs=[
            pl.BlockSpec((1, h, dh), q_map),
            pl.BlockSpec((1, plen, h, dh), kv_map),
            pl.BlockSpec((1, plen, h, dh), kv_map),
        ],
        out_specs=pl.BlockSpec((1, h, dh), q_map),
        scratch_shapes=[pltpu.VMEM((h, dh), jnp.float32),
                        pltpu.VMEM((h, 8), jnp.float32),
                        pltpu.VMEM((h, 8), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, page_len=plen,
                          n_pages=npg),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, dh), q.dtype),
        interpret=interpret,
    )(table, pos, q, k_pages, v_pages)


def paged_attention_reference(q, k_pages, v_pages, table, pos):
    """The XLA gather oracle — byte-for-byte the math the engine's
    PR 14 paged decode ran: materialize each slot's fixed-width table
    row (sentinel entries CLAMP to the last pool page — garbage the pos
    mask never exposes), f32 softmax over the masked scores."""
    b, h, dh = q.shape
    plen = k_pages.shape[1]
    per_slot = table.shape[1]
    kg = k_pages[table].reshape(b, per_slot * plen, h, dh)
    vg = v_pages[table].reshape(b, per_slot * plen, h, dh)
    scale = 1.0 / math.sqrt(dh)
    scores = jnp.einsum("bhd,bshd->bhs",
                        (q.astype(jnp.float32) * scale),
                        kg.astype(jnp.float32))
    s = kg.shape[1]
    mask = jnp.arange(s)[None, :] <= pos[:, None]          # (B, S)
    scores = jnp.where(mask[:, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", probs, vg.astype(jnp.float32))
    return out.astype(q.dtype)


def kernel_sha() -> str:
    """Source fingerprint of the pallas kernel — the ``sha=`` every
    ``paged_decode:*`` cost record is stamped with. Editing the kernel
    (or its dispatch wrapper) changes this, which auto-invalidates
    stale promotion verdicts on next lookup (tested in
    tests/test_paged_attention.py)."""
    return autotune.source_sha(_decode_kernel, paged_attention)


# --------------------------------------------------------- promotion --

def bucket_key(cfg, cache, backend: Optional[str] = None) -> str:
    """The shape-bucket cost-record key for one engine geometry:
    kernel kind + model shape + pool geometry + dtype + backend."""
    if backend is None:
        backend = jax.default_backend()
    npg, plen = cache["k"].shape[1], cache["k"].shape[2]
    slots, per_slot = cache["pages"].shape
    dt = jnp.dtype(cache["k"].dtype).name
    return (f"paged_decode:L{cfg.n_layers}H{cfg.n_heads}D{cfg.head_dim}"
            f":PL{plen}:P{per_slot}:NP{npg}:S{slots}:{dt}:{backend}")


def _probe_cache(cfg, cache) -> Tuple[Dict, object]:
    """A probe cache with the LIVE cache's exact abstract shapes —
    random k/v content, every slot mapped to ~3/4 of its page-table
    width (partial-fill tail included) with contiguous distinct pages,
    cursors mid-page. Racing on it compiles/times the very signatures
    the live decode sweep will run (the race pre-warms the bucket).
    Returns (cache pytree, probe tokens)."""
    import numpy as np
    rng = np.random.default_rng(0)
    kshape = cache["k"].shape
    dt = cache["k"].dtype
    npg, plen = kshape[1], kshape[2]
    slots, per_slot = cache["pages"].shape
    table = np.full((slots, per_slot), npg, np.int32)
    nxt = 0
    pos = np.zeros((slots,), np.int32)
    for s in range(slots):
        want = max(1, (3 * per_slot) // 4)
        got = min(want, npg - nxt)
        if got < 1:                       # pool exhausted: leave empty
            continue
        table[s, :got] = np.arange(nxt, nxt + got)
        nxt += got
        # cursor mid-way into the last mapped page (partial fill)
        pos[s] = (got - 1) * plen + plen // 2
    probe = {
        "k": jnp.asarray(rng.standard_normal(kshape), dt),
        "v": jnp.asarray(rng.standard_normal(kshape), dt),
        "pos": jnp.asarray(pos),
        "pages": jnp.asarray(table),
    }
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (slots,)),
                       jnp.int32)
    return probe, toks


def _fid_compact(rep: Dict) -> Dict:
    keep = ("max_abs_err", "mean_abs_err", "kl_mean", "kl_max",
            "topk_agreement", "greedy_match_frac", "greedy_prefix_len",
            "positions")
    return {k: rep[k] for k in keep if k in rep}


def race(engine, cache, *, max_kl: float = PROMOTION_MAX_KL) -> Dict:
    """Race the pallas kernel against the XLA gather path on probe
    caches of ``cache``'s geometry; gate on fidelity; persist the
    verdict as a sha-stamped cost record; bump
    ``dl4j_autotune_promotions_total{kernel,verdict}``.

    Returns the record meta: ``{choice, verdict, gather_s, kernel_s,
    speedup, fidelity}``. The verdict vocabulary:

    - ``promoted`` — fidelity holds and the kernel measured faster;
    - ``fallback_slower`` — fidelity holds, gather measured faster;
    - ``fallback_fidelity`` — kl_max or greedy equivalence failed
      (the kernel is silently never dispatched for this bucket).
    """
    import numpy as np
    from ..obs import get_registry
    from ..obs.fidelity import FidelityProbe

    cfg = engine.cfg
    key = bucket_key(cfg, cache)
    sha = kernel_sha()

    # fidelity first: one step from IDENTICAL probe content through
    # both paths, compared in token space and KL
    probe_a, toks = _probe_cache(cfg, cache)
    probe_b = {k: jnp.array(v) for k, v in probe_a.items()}  # own buffers
    ref_logits, _ = engine._decode_paged(engine.params, probe_a, toks)
    cand_logits, _ = engine._decode_paged_kernel(engine.params, probe_b,
                                                 toks)
    fid = FidelityProbe("paged_kernel_vs_xla").compare(
        np.asarray(ref_logits, np.float32),
        np.asarray(cand_logits, np.float32))
    fidelity_ok = (fid["kl_max"] <= max_kl
                   and fid["greedy_match_frac"] == 1.0)

    # time BOTH arms regardless of the fidelity outcome — the A/B
    # numbers belong in the cost record and the bench ledger either
    # way; fidelity gates only the PROMOTION, never the measurement
    timings: Dict[str, float] = {}
    for name, fn in (("gather", engine._decode_paged),
                     ("kernel", engine._decode_paged_kernel)):
        state = {}
        state["cache"], state["toks"] = _probe_cache(cfg, cache)

        def run():
            logits, state["cache"] = fn(engine.params, state["cache"],
                                        state["toks"])
            return logits

        timings[name] = autotune._time_once(run)
    if fidelity_ok:
        chosen = ("kernel" if timings["kernel"] < timings["gather"]
                  else "gather")
        verdict = "promoted" if chosen == "kernel" else "fallback_slower"
    else:
        chosen, verdict = "gather", "fallback_fidelity"

    meta = {
        "verdict": verdict,
        "gather_s": timings.get("gather"),
        "kernel_s": timings.get("kernel"),
        "speedup": (round(timings["gather"] / timings["kernel"], 3)
                    if len(timings) == 2 and timings["kernel"] > 0
                    else None),
        "max_kl": max_kl,
        "fidelity": _fid_compact(fid),
        "backend": jax.default_backend(),
    }
    autotune.put(key, (chosen,), meta=meta, sha=sha)
    get_registry().counter(
        "dl4j_autotune_promotions_total",
        "Fidelity-gated kernel-vs-XLA promotion races, by verdict",
        labelnames=("kernel", "verdict")).inc(
            kernel="paged_decode", verdict=verdict)
    return dict(meta, choice=chosen, key=key)


def decide(engine, cache, mode: Optional[str] = None) -> str:
    """The dispatch decision for one engine × cache geometry:
    ``"kernel"`` or ``"gather"``. Resolution order:

    - ``mode`` (or the engine's pinned mode, or ``$DL4J_PAGED_KERNEL``):
      ``off`` → gather, ``on`` → kernel (no race — bench/debug);
    - ``auto`` (default): off-TPU the gather path wins untimed (the
      interpret-mode kernel exists for CI oracles, not speed); on TPU,
      fall through to the race;
    - ``race``: race regardless of backend (CPU tests/bench A/B).

    Raced verdicts are persistent sha-stamped cost records — a second
    process on the same chip generation gets the verdict for free, and
    an edited kernel invalidates + re-races (``kernels/autotune.py``).
    """
    if mode is None:
        mode = getattr(engine, "paged_kernel_mode", None) \
            or os.environ.get(_MODE_ENV, "auto")
    mode = str(mode).lower()
    if mode in ("off", "0", "gather"):
        return "gather"
    if mode in ("on", "1", "kernel"):
        return "kernel"
    if mode == "auto" and jax.default_backend() != "tpu":
        return "gather"
    # race (or auto-on-TPU): serve the cached verdict when its sha
    # still matches the kernel source, else measure
    rec = autotune.lookup(bucket_key(engine.cfg, cache), sha=kernel_sha())
    if rec is not None and rec["choice"]:
        return str(rec["choice"][0])
    return str(race(engine, cache)["choice"])
