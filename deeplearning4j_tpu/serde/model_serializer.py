"""ModelSerializer — zip checkpoints (config + params + updater + normalizer).

Reference parity: ``org.deeplearning4j.util.ModelSerializer``
(writeModel/restoreMultiLayerNetwork, addNormalizerToModel). Format here:
a zip holding ``conf.pkl`` (config object), ``params.npz`` / ``states.npz``
(flattened pytrees with path-encoded keys), optional ``updater.npz`` and
``normalizer.pkl``. For sharded/distributed checkpoints use
``deeplearning4j_tpu.serde.orbax_ckpt`` instead.
"""

from __future__ import annotations

import io
import os
import pickle
import zipfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

SEP = "|"


def _flatten_with_paths(tree):
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = SEP.join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p):
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return f"#{p.idx}"
    return str(p)


def _save_npz(zf: zipfile.ZipFile, name: str, tree):
    buf = io.BytesIO()
    flat = _flatten_with_paths(tree)
    # bfloat16 isn't a numpy-native dtype for savez; view as uint16 + marker
    packed = {}
    for k, v in flat.items():
        if v.dtype == jnp.bfloat16:
            packed["__bf16__" + k] = v.view(np.uint16)
        else:
            packed[k] = v
    np.savez(buf, **packed)
    zf.writestr(name, buf.getvalue())


def _load_npz(zf: zipfile.ZipFile, name: str):
    with zf.open(name) as f:
        z = np.load(io.BytesIO(f.read()))
        out = {}
        for k in z.files:
            if k.startswith("__bf16__"):
                out[k[len("__bf16__"):]] = z[k].view(jnp.bfloat16)
            else:
                out[k] = z[k]
        return out


def _unflatten_into(template, flat):
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = SEP.join(_path_str(p) for p in path)
        if key not in flat:
            raise KeyError(f"checkpoint missing param {key}")
        leaves.append(jnp.asarray(flat[key]).astype(leaf.dtype).reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_model(model, path, save_updater: bool = False, normalizer=None):
    from ..autodiff.samediff import SameDiff
    if isinstance(model, SameDiff):
        # SameDiff graphs carry their own replay-record format
        if normalizer is not None:
            raise ValueError("normalizers are not part of the SameDiff "
                             "format — save it separately")
        return model.save(path, save_updater=save_updater)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    # write-then-rename: a crash mid-save must never corrupt an existing
    # checkpoint at `path` (scaleout's master-restart resumes from it)
    tmp = path.with_name(path.name + ".tmp")
    with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("conf.pkl", pickle.dumps({
            "kind": type(model).__name__,
            "conf": model.conf,
            "preprocessors": getattr(model, "_preprocessors", {}),
            "epoch_count": getattr(model, "epoch_count", 0),
            "step_count": getattr(model, "_step_count", 0),
        }))
        _save_npz(zf, "params.npz", model.params)
        _save_npz(zf, "states.npz", model.states)
        if save_updater and getattr(model, "_opt_state", None) is not None:
            zf.writestr("updater.pkl", pickle.dumps(
                jax.tree_util.tree_map(lambda a: np.asarray(a), model._opt_state)))
        if normalizer is not None:
            zf.writestr("normalizer.pkl", pickle.dumps(normalizer))
    os.replace(tmp, path)


def load_model(path):
    from ..nn.computation_graph import ComputationGraph
    from ..nn.multi_layer_network import MultiLayerNetwork
    with zipfile.ZipFile(path) as zf:
        if "graph.pkl" in zf.namelist():      # a saved SameDiff graph
            from ..autodiff.samediff import SameDiff
            return SameDiff.load(path)
        if "configuration.json" in zf.namelist():   # upstream DL4J zip
            import json as _json
            from .upstream_dl4j import (
                restore_upstream_computation_graph,
                restore_upstream_multi_layer_network)
            conf = _json.loads(zf.read("configuration.json"))
            if "vertices" in conf:
                return restore_upstream_computation_graph(path)
            return restore_upstream_multi_layer_network(path)
        meta = pickle.loads(zf.read("conf.pkl"))
        cls = {"MultiLayerNetwork": MultiLayerNetwork,
               "ComputationGraph": ComputationGraph}[meta["kind"]]
        model = cls(meta["conf"])
        conf = meta["conf"]
        if getattr(conf, "input_type", None) is not None or \
                getattr(conf, "input_types", None) is not None:
            model.init()
        if not model.initialized:
            # need shapes: rebuild params directly from the file
            model.params = {}
            model.states = {}
        flat_p = _load_npz(zf, "params.npz")
        flat_s = _load_npz(zf, "states.npz")
        if model.initialized:
            model.params = _unflatten_into(model.params, flat_p)
            if jax.tree_util.tree_leaves(model.states):
                model.states = _unflatten_into(model.states, flat_s)
        else:
            model.params = _nest(flat_p)
            model.states = _nest(flat_s)
            # empty per-layer dicts produce no leaves when flattened — restore
            # the containers so forward can index every layer/node
            if hasattr(model, "layers"):
                keys = [f"layer_{i}" for i in range(len(model.layers))]
            else:
                keys = list(conf.nodes)
            for k in keys:
                model.params.setdefault(k, {})
                model.states.setdefault(k, {})
            model.initialized = True
        model._preprocessors = meta.get("preprocessors", {})
        model.epoch_count = meta.get("epoch_count", 0)
        model._step_count = meta.get("step_count", 0)
        if "updater.pkl" in zf.namelist():
            raw = pickle.loads(zf.read("updater.pkl"))
            model._restored_opt_state = jax.tree_util.tree_map(jnp.asarray, raw)
        if "normalizer.pkl" in zf.namelist():
            model.normalizer = pickle.loads(zf.read("normalizer.pkl"))
    return model


def restore_normalizer(path):
    with zipfile.ZipFile(path) as zf:
        if "normalizer.pkl" in zf.namelist():
            return pickle.loads(zf.read("normalizer.pkl"))
        if "normalizer.bin" in zf.namelist():   # upstream DL4J layout
            from .upstream_dl4j import read_normalizer_upstream_format
            return read_normalizer_upstream_format(zf.read("normalizer.bin"))
    return None


def _nest(flat):
    out = {}
    for key, v in flat.items():
        parts = key.split(SEP)
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = jnp.asarray(v)
    return out
