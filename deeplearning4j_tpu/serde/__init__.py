"""Serialization: zip checkpoints + orbax distributed checkpointing.

Reference parity: ``org.deeplearning4j.util.ModelSerializer``.
``ModelSerializer`` is the DL4J-shaped static facade over
:mod:`.model_serializer`'s functions.
"""

from .model_serializer import load_model, restore_normalizer, save_model
from .orbax_ckpt import OrbaxCheckpointer, PreemptionWatchdog
from .upstream_dl4j import (is_upstream_format,
                            restore_upstream_computation_graph,
                            restore_upstream_multi_layer_network,
                            write_computation_graph_upstream_format,
                            write_model_upstream_format)


class ModelSerializer:
    """DL4J-style static facade (``writeModel`` / ``restoreMultiLayerNetwork``).

    ``restore_multi_layer_network`` auto-detects upstream DL4J zips
    (configuration.json + coefficients.bin — the format existing DL4J
    users hold) alongside our native format;
    ``write_model_upstream_format`` exports back to it."""

    write_model = staticmethod(save_model)
    writeModel = staticmethod(save_model)
    write_model_upstream_format = staticmethod(write_model_upstream_format)
    write_computation_graph_upstream_format = staticmethod(
        write_computation_graph_upstream_format)
    restore_multi_layer_network = staticmethod(load_model)
    restoreMultiLayerNetwork = staticmethod(load_model)
    restore_computation_graph = staticmethod(load_model)
    restoreComputationGraph = staticmethod(load_model)
    restore_normalizer = staticmethod(restore_normalizer)
    restoreNormalizer = staticmethod(restore_normalizer)


__all__ = [
    "ModelSerializer", "save_model", "load_model", "restore_normalizer",
    "OrbaxCheckpointer", "PreemptionWatchdog", "is_upstream_format",
    "restore_upstream_multi_layer_network", "write_model_upstream_format",
    "restore_upstream_computation_graph",
    "write_computation_graph_upstream_format",
]
