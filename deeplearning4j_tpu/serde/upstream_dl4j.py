"""Upstream-Deeplearning4j checkpoint interop (VERDICT r4 missing item 1).

Reads and writes the zip layout every existing DL4J user holds
(reference: ``org.deeplearning4j.util.ModelSerializer.writeModel`` /
``restoreMultiLayerNetwork``, ``MultiLayerConfiguration.fromJson``):

    configuration.json   MultiLayerConfiguration JSON (Jackson @class-tagged)
    coefficients.bin     all params as ONE flat row vector, Nd4j.write wire
    updaterState.bin     optional flat updater state (Adam m/v etc.)

Wire layout of an Nd4j.write array (big-endian, java DataOutputStream):

    writeUTF(shape-buffer dtype name)        e.g. "LONG"
    writeInt(shapeInfo length)
    shapeInfo int64s: [rank, *shape, *stride, offset, elemWiseStride, order]
                      (order is the ascii code of 'c' or 'f')
    writeUTF(data dtype name)                "FLOAT" | "DOUBLE" | "HALF"
    writeInt(data length)
    raw big-endian values

Param packing (reference ``MultiLayerNetwork.params()``): layers in order;
per layer the initializer's param keys in order (Dense/Output/Embedding:
W, b; Convolution: W, b; BatchNormalization: gamma, beta, mean, var;
LSTM/GravesLSTM: W, RW, b); each tensor flattened in **'f' (column-major)
order** — DL4J allocates its param views in 'f' order. Upstream tensor
layouts differ from ours in one place: conv kernels are (nOut, nIn, kH, kW)
there, HWIO (kH, kW, nIn, nOut) here — transposed on the way through.

Provenance caveat: ``/root/reference`` is an empty mount, so this layout is
written from knowledge of the public upstream format and proven
self-consistent by synthesized in-repo fixtures
(tests/test_upstream_serde.py builds the zip with raw struct/json calls,
NOT via this module's writer). If the mount ever materializes, validate
against a real zip before trusting cross-version details.
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

_J = "org.deeplearning4j.nn.conf.layers."
_ACT = "org.nd4j.linalg.activations.impl."
_LOSS = "org.nd4j.linalg.lossfunctions.impl."
_UPD = "org.nd4j.linalg.learning.config."

# ------------------------------------------------------------------ nd4j wire

_DTYPES = {"FLOAT": (">f4", np.float32), "DOUBLE": (">f8", np.float64),
           "HALF": (">f2", np.float16), "LONG": (">i8", np.int64),
           "INT": (">i4", np.int32)}


def _read_utf(buf: io.BytesIO) -> str:
    (n,) = struct.unpack(">H", buf.read(2))
    return buf.read(n).decode("utf-8")


def _write_utf(buf: io.BytesIO, s: str):
    raw = s.encode("utf-8")
    buf.write(struct.pack(">H", len(raw)))
    buf.write(raw)


def read_nd4j_array(data) -> np.ndarray:
    """Parse one Nd4j.write()-format array from ``data`` (bytes, or a
    BytesIO stream — the stream is left positioned just past the frame,
    so back-to-back frames parse by repeated calls)."""
    buf = io.BytesIO(data) if isinstance(data, (bytes, bytearray)) else data
    shape_dtype = _read_utf(buf)
    if shape_dtype not in ("LONG", "INT"):
        raise ValueError(f"unexpected shape-buffer dtype {shape_dtype!r}")
    (n_shape,) = struct.unpack(">i", buf.read(4))
    width = 8 if shape_dtype == "LONG" else 4
    fmt = ">%d%s" % (n_shape, "q" if shape_dtype == "LONG" else "i")
    info = struct.unpack(fmt, buf.read(width * n_shape))
    rank = int(info[0])
    shape = tuple(int(s) for s in info[1:1 + rank])
    order = chr(int(info[-1])) if info[-1] in (99, 102) else "c"
    data_dtype = _read_utf(buf)
    if data_dtype not in _DTYPES:
        raise ValueError(f"unsupported data dtype {data_dtype!r}")
    wire, host = _DTYPES[data_dtype]
    (n,) = struct.unpack(">i", buf.read(4))
    arr = np.frombuffer(buf.read(n * np.dtype(wire).itemsize), dtype=wire
                        ).astype(host)
    return arr.reshape(shape, order=order)


def write_nd4j_array(arr: np.ndarray, order: str = "c") -> bytes:
    """Serialize ``arr`` in the Nd4j.write() wire layout."""
    arr = np.asarray(arr)
    if arr.dtype == np.float64:
        name, wire = "DOUBLE", ">f8"
    elif arr.dtype == np.float16:
        name, wire = "HALF", ">f2"
    else:
        name, wire = "FLOAT", ">f4"
        arr = arr.astype(np.float32)
    rank = arr.ndim
    shape = arr.shape
    # strides in elements for the declared order
    strides = []
    acc = 1
    dims = shape if order == "f" else shape[::-1]
    for d in dims:
        strides.append(acc)
        acc *= d
    strides = strides if order == "f" else strides[::-1]
    info = [rank, *shape, *strides, 0, 1, ord(order)]
    buf = io.BytesIO()
    _write_utf(buf, "LONG")
    buf.write(struct.pack(">i", len(info)))
    buf.write(struct.pack(">%dq" % len(info), *info))
    _write_utf(buf, name)
    buf.write(struct.pack(">i", arr.size))
    buf.write(arr.ravel(order=order).astype(wire).tobytes())
    return buf.getvalue()


# ------------------------------------------------------------- config mapping

_ACT_FROM_JAVA = {
    "ActivationReLU": "relu", "ActivationReLU6": "relu6",
    "ActivationIdentity": "identity", "ActivationSoftmax": "softmax",
    "ActivationTanH": "tanh", "ActivationSigmoid": "sigmoid",
    "ActivationLReLU": "leakyrelu", "ActivationELU": "elu",
    "ActivationSELU": "selu", "ActivationGELU": "gelu",
    "ActivationSoftPlus": "softplus", "ActivationSoftSign": "softsign",
    "ActivationHardSigmoid": "hardsigmoid", "ActivationHardTanH": "hardtanh",
    "ActivationSwish": "swish", "ActivationMish": "mish",
    "ActivationCube": "cube", "ActivationRationalTanh": "rationaltanh",
    "ActivationRectifiedTanh": "rectifiedtanh",
}
_ACT_TO_JAVA = {v: k for k, v in _ACT_FROM_JAVA.items()}

_LOSS_FROM_JAVA = {
    "LossMCXENT": "mcxent", "LossNegativeLogLikelihood": "mcxent",
    "LossMSE": "mse", "LossL2": "l2", "LossL1": "l1", "LossMAE": "mae",
    "LossBinaryXENT": "binary_xent", "LossHinge": "hinge",
    "LossSquaredHinge": "squared_hinge", "LossKLD": "kld",
    "LossPoisson": "poisson", "LossCosineProximity": "cosine_proximity",
    "LossMSLE": "msle", "LossMAPE": "mape",
}
_LOSS_TO_JAVA = {
    "mcxent": "LossMCXENT", "mse": "LossMSE", "l2": "LossL2", "l1": "LossL1",
    "mae": "LossMAE", "binary_xent": "LossBinaryXENT", "hinge": "LossHinge",
    "squared_hinge": "LossSquaredHinge", "kld": "LossKLD",
    "poisson": "LossPoisson", "cosine_proximity": "LossCosineProximity",
    "msle": "LossMSLE", "mape": "LossMAPE",
}


def _act_from_json(d):
    if d is None:
        return None
    if isinstance(d, str):
        return d.lower()
    cls = d.get("@class", "").rsplit(".", 1)[-1]
    if cls not in _ACT_FROM_JAVA:
        raise ValueError(f"unsupported upstream activation {cls!r}")
    return _ACT_FROM_JAVA[cls]


def _updater_from_json(d):
    from ..train import updaters as U
    if d is None:
        return None
    cls = d.get("@class", "").rsplit(".", 1)[-1]
    lr = d.get("learningRate", 1e-3)
    table = {
        "Adam": lambda: U.Adam(lr, beta1=d.get("beta1", 0.9),
                               beta2=d.get("beta2", 0.999),
                               epsilon=d.get("epsilon", 1e-8)),
        "AdamW": lambda: U.AdamW(lr, beta1=d.get("beta1", 0.9),
                                 beta2=d.get("beta2", 0.999),
                                 epsilon=d.get("epsilon", 1e-8),
                                 weight_decay=d.get("weightDecay", 1e-2)),
        "Sgd": lambda: U.Sgd(lr),
        "Nesterovs": lambda: U.Nesterovs(lr, momentum=d.get("momentum", 0.9)),
        "RmsProp": lambda: U.RmsProp(lr, epsilon=d.get("epsilon", 1e-8)),
        "AdaGrad": lambda: U.AdaGrad(lr, epsilon=d.get("epsilon", 1e-6)),
        "AdaDelta": lambda: U.AdaDelta(),
        "Nadam": lambda: U.Nadam(lr),
        "AMSGrad": lambda: U.AMSGrad(lr),
        "AdaMax": lambda: U.AdaMax(lr),
        "NoOp": lambda: U.NoOp(),
    }
    if cls not in table:
        raise ValueError(f"unsupported upstream updater {cls!r}")
    return table[cls]()


def _updater_to_json(u):
    name = type(u).__name__
    d = {"@class": _UPD + name}
    if hasattr(u, "learning_rate"):
        lr = u.learning_rate
        if callable(lr):
            try:
                lr = float(lr(0))   # schedule: export its step-0 value
            except Exception as e:  # noqa: BLE001
                raise ValueError(
                    f"learning-rate schedule {type(u.learning_rate).__name__}"
                    " cannot be exported to the upstream format (could not "
                    f"evaluate it at step 0: {e}); set a scalar lr before "
                    "exporting") from e
        d["learningRate"] = float(lr)
    for ours, theirs in (("beta1", "beta1"), ("beta2", "beta2"),
                         ("epsilon", "epsilon"), ("momentum", "momentum"),
                         ("weight_decay", "weightDecay")):
        if hasattr(u, ours):
            d[theirs] = float(getattr(u, ours))
    return d


def _layer_from_json(d):
    """One upstream layer JSON dict → our Layer dataclass."""
    from ..nn.layers import conv as C
    from ..nn.layers import core as K
    from ..nn.layers import norm as N
    from ..nn.layers import recurrent as R

    cls = d.get("@class", "").rsplit(".", 1)[-1]
    act = _act_from_json(d.get("activationFn") or d.get("activation"))
    common = {}
    if act is not None:
        common["activation"] = act

    if cls in ("DenseLayer",):
        return K.DenseLayer(n_in=int(d["nin"]), n_out=int(d["nout"]),
                            has_bias=d.get("hasBias", True), **common)
    if cls in ("OutputLayer", "RnnOutputLayer"):
        loss = d.get("lossFn") or d.get("lossFunction")
        if isinstance(loss, dict):
            lname = loss.get("@class", "").rsplit(".", 1)[-1]
            if lname not in _LOSS_FROM_JAVA:
                raise ValueError(f"unsupported upstream loss {lname!r}")
            loss = _LOSS_FROM_JAVA[lname]
        elif isinstance(loss, str):
            loss = loss.lower()
        else:
            loss = "mcxent"
        klass = K.RnnOutputLayer if cls == "RnnOutputLayer" else K.OutputLayer
        return klass(n_in=int(d["nin"]), n_out=int(d["nout"]), loss=loss,
                     has_bias=d.get("hasBias", True),
                     **(common or {"activation": "softmax"}))
    if cls == "ConvolutionLayer":
        return C.ConvolutionLayer(
            n_in=int(d["nin"]), n_out=int(d["nout"]),
            kernel_size=tuple(d.get("kernelSize", (3, 3))),
            stride=tuple(d.get("stride", (1, 1))),
            padding=tuple(d.get("padding", (0, 0))),
            dilation=tuple(d.get("dilation", (1, 1))),
            convolution_mode=d.get("convolutionMode", "Truncate").lower(),
            has_bias=d.get("hasBias", True), **common)
    if cls == "SubsamplingLayer":
        pt = d.get("poolingType", "MAX")
        pt = pt if isinstance(pt, str) else pt.get("poolingType", "MAX")
        return C.SubsamplingLayer(
            kernel_size=tuple(d.get("kernelSize", (2, 2))),
            stride=tuple(d.get("stride") or d.get("kernelSize", (2, 2))),
            padding=tuple(d.get("padding", (0, 0))),
            convolution_mode=d.get("convolutionMode", "Truncate").lower(),
            pooling_type=pt.lower())
    if cls == "BatchNormalization":
        return N.BatchNormalization(decay=d.get("decay", 0.9),
                                    eps=d.get("eps", 1e-5),
                                    **common)
    if cls in ("LSTM", "GravesLSTM"):
        klass = R.GravesLSTM if cls == "GravesLSTM" else R.LSTM
        gate = _act_from_json(d.get("gateActivationFn")) or "sigmoid"
        return klass(n_in=int(d["nin"]), n_out=int(d["nout"]),
                     forget_gate_bias=d.get("forgetGateBiasInit", 1.0),
                     gate_activation=gate,
                     **(common or {"activation": "tanh"}))
    if cls == "EmbeddingLayer":
        return K.EmbeddingLayer(n_in=int(d["nin"]), n_out=int(d["nout"]),
                                has_bias=d.get("hasBias", False), **common)
    if cls == "ActivationLayer":
        return K.ActivationLayer(**(common or {"activation": "identity"}))
    if cls == "DropoutLayer":
        rate = 1.0 - d.get("idropout", {}).get("p", 0.5) \
            if isinstance(d.get("idropout"), dict) else d.get("dropout", 0.5)
        return K.DropoutLayer(rate=rate)
    raise ValueError(
        f"unsupported upstream layer class {cls!r} — supported: Dense, "
        "Output, RnnOutput, Convolution, Subsampling, BatchNormalization, "
        "LSTM, GravesLSTM, Embedding, Activation, Dropout")


def _layer_to_json(layer):
    from ..nn.layers import conv as C
    from ..nn.layers import core as K
    from ..nn.layers import norm as N
    from ..nn.layers import recurrent as R
    from ..nn.layers.wrappers import unwrap

    lyr = unwrap(layer)
    raw_act = getattr(lyr, "activation", None)
    if raw_act is not None and not isinstance(raw_act, str):
        raise ValueError(
            f"layer {type(lyr).__name__} uses a callable activation "
            f"{raw_act!r} — only named activations can be exported to the "
            "upstream format")
    act_name = raw_act

    def act_json(name):
        if name not in _ACT_TO_JAVA:
            raise ValueError(f"activation {name!r} has no upstream analogue")
        return {"@class": _ACT + _ACT_TO_JAVA[name]}

    if isinstance(lyr, K.RnnOutputLayer) or (type(lyr) is K.OutputLayer):
        loss = str(lyr.loss).lower()
        if loss not in _LOSS_TO_JAVA:
            raise ValueError(f"loss {loss!r} has no upstream analogue")
        cls = "RnnOutputLayer" if isinstance(lyr, K.RnnOutputLayer) \
            else "OutputLayer"
        return {"@class": _J + cls, "nin": int(lyr.n_in), "nout": int(lyr.n_out),
                "hasBias": bool(lyr.has_bias),
                "activationFn": act_json(act_name or "softmax"),
                "lossFn": {"@class": _LOSS + _LOSS_TO_JAVA[loss]}}
    if type(lyr) is K.DenseLayer:
        return {"@class": _J + "DenseLayer", "nin": int(lyr.n_in),
                "nout": int(lyr.n_out), "hasBias": bool(lyr.has_bias),
                "activationFn": act_json(act_name or "identity")}
    if type(lyr) is C.ConvolutionLayer:
        return {"@class": _J + "ConvolutionLayer", "nin": int(lyr.n_in),
                "nout": int(lyr.n_out),
                "kernelSize": list(_pair(lyr.kernel_size)),
                "stride": list(_pair(lyr.stride)),
                "padding": list(_pair(lyr.padding)),
                "dilation": list(_pair(lyr.dilation)),
                "convolutionMode": lyr.convolution_mode.capitalize(),
                "hasBias": bool(lyr.has_bias),
                "activationFn": act_json(act_name or "identity")}
    if type(lyr) is C.SubsamplingLayer:
        return {"@class": _J + "SubsamplingLayer",
                "kernelSize": list(_pair(lyr.kernel_size)),
                "stride": list(_pair(lyr.stride or lyr.kernel_size)),
                "padding": list(_pair(lyr.padding)),
                "convolutionMode": lyr.convolution_mode.capitalize(),
                "poolingType": lyr.pooling_type.upper()}
    if type(lyr) is N.BatchNormalization:
        return {"@class": _J + "BatchNormalization",
                "decay": float(lyr.decay), "eps": float(lyr.eps),
                "activationFn": act_json(act_name or "identity")}
    if isinstance(lyr, R.LSTM):
        cls = "GravesLSTM" if isinstance(lyr, R.GravesLSTM) else "LSTM"
        return {"@class": _J + cls, "nin": int(lyr.n_in),
                "nout": int(lyr.n_out),
                "forgetGateBiasInit": float(lyr.forget_gate_bias),
                "activationFn": act_json(act_name or "tanh"),
                "gateActivationFn": act_json(lyr.gate_activation)}
    if type(lyr) is K.EmbeddingLayer:
        return {"@class": _J + "EmbeddingLayer", "nin": int(lyr.n_in),
                "nout": int(lyr.n_out), "hasBias": bool(lyr.has_bias),
                "activationFn": act_json(act_name or "identity")}
    if type(lyr) is K.ActivationLayer:
        return {"@class": _J + "ActivationLayer",
                "activationFn": act_json(act_name or "identity")}
    if type(lyr) is K.DropoutLayer:
        return {"@class": _J + "DropoutLayer",
                "idropout": {"@class": "org.deeplearning4j.nn.conf.dropout."
                                       "Dropout", "p": 1.0 - lyr.rate}}
    raise ValueError(f"layer {type(lyr).__name__} has no upstream-format "
                     "writer (supported: Dense/Output/RnnOutput/Conv/"
                     "Subsampling/BatchNorm/LSTM/GravesLSTM/Embedding/"
                     "Activation/Dropout)")


def _pair(v):
    if v is None:
        return (1, 1)
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


# ------------------------------------------------------------- param packing

def _upstream_param_entries(layer, params, state):
    """[(key, upstream_np_array)] for one layer, upstream order + layout."""
    from ..nn.layers import conv as C
    from ..nn.layers import norm as N
    from ..nn.layers.wrappers import unwrap

    lyr = unwrap(layer)
    out = []
    if isinstance(lyr, N.BatchNormalization):
        c = state["mean"].shape[0]
        gamma = params.get("gamma", np.ones((c,), np.float32))
        beta = params.get("beta", np.zeros((c,), np.float32))
        return [("gamma", np.asarray(gamma)), ("beta", np.asarray(beta)),
                ("mean", np.asarray(state["mean"])),
                ("var", np.asarray(state["var"]))]
    if isinstance(lyr, C.ConvolutionLayer) and "W" in params:
        w = np.asarray(params["W"]).transpose(3, 2, 0, 1)  # HWIO → OIHW
        out.append(("W", w))
        if "b" in params:
            out.append(("b", np.asarray(params["b"])))
        return out
    for key in ("W", "RW", "b", "pI", "pF", "pO"):
        if key in params:
            out.append((key, np.asarray(params[key])))
    for key in sorted(params):
        if key not in dict(out):
            out.append((key, np.asarray(params[key])))
    return out


def _iter_param_nodes(net):
    """(key, layer, params, states) per param-bearing node, packing order:
    MLN = layer index order; CG = topological node order."""
    if hasattr(net, "layers"):                         # MultiLayerNetwork
        for i, layer in enumerate(net.layers):
            yield (f"layer_{i}", layer, net.params[f"layer_{i}"],
                   net.states[f"layer_{i}"])
    else:                                              # ComputationGraph
        from ..nn.layers.base import Layer
        for name in net.conf.topo_order:
            op = net.conf.nodes[name].op
            if isinstance(op, Layer):
                yield (name, op, net.params.get(name, {}),
                       net.states.get(name, {}))


def _assign_upstream_params(net, flat: np.ndarray):
    """Split the upstream flat row vector back into net.params/states
    (MLN and CG — _iter_param_nodes fixes the packing order)."""
    from ..nn.layers import conv as C
    from ..nn.layers import norm as N
    from ..nn.layers.wrappers import unwrap

    flat = np.asarray(flat).reshape(-1)
    off = 0

    def take(shape):
        nonlocal off
        n = int(np.prod(shape))
        if off + n > flat.size:
            raise ValueError(
                f"coefficients.bin too short: need {off + n} floats, "
                f"have {flat.size}")
        chunk = flat[off:off + n].reshape(shape, order="f")
        off += n
        return chunk

    for _key, layer, p, s in _iter_param_nodes(net):
        lyr = unwrap(layer)
        if isinstance(lyr, N.BatchNormalization):
            c = s["mean"].shape[0]
            gamma = take((c,))
            beta = take((c,))
            mean = take((c,))
            var = take((c,))
            if "gamma" in p:
                p["gamma"] = jnp.asarray(gamma).astype(p["gamma"].dtype)
                p["beta"] = jnp.asarray(beta).astype(p["beta"].dtype)
            s["mean"] = jnp.asarray(mean, jnp.float32)
            s["var"] = jnp.asarray(var, jnp.float32)
            continue
        if isinstance(lyr, C.ConvolutionLayer) and "W" in p:
            kh, kw, cin, cout = p["W"].shape
            w = take((cout, cin, kh, kw)).transpose(2, 3, 1, 0)  # OIHW → HWIO
            p["W"] = jnp.asarray(w).astype(p["W"].dtype)
            if "b" in p:
                p["b"] = jnp.asarray(take(p["b"].shape)).astype(p["b"].dtype)
            continue
        keys = [k for k in ("W", "RW", "b", "pI", "pF", "pO") if k in p]
        keys += [k for k in sorted(p) if k not in keys]
        for k in keys:
            p[k] = jnp.asarray(take(p[k].shape)).astype(p[k].dtype)
    if off != flat.size:
        raise ValueError(f"coefficients.bin has {flat.size} floats but the "
                         f"configuration consumes {off} — config/params "
                         "mismatch")
    net._invalidate()


def _param_order_arrays(net):
    """All upstream param entries of the whole net, packing order."""
    out = []
    for _key, layer, p, s in _iter_param_nodes(net):
        out.extend(a for _, a in _upstream_param_entries(layer, p, s))
    return out


# ------------------------------------------------------------------ zip io

_IT = "org.deeplearning4j.nn.conf.inputs.InputType$"


def _shape_to_input_type_json(shape):
    """A concrete input shape → the upstream InputType JSON (rank decides:
    4=cnn3d DHWC, 3=cnn HWC, 2=recurrent (T, C), 1=feed-forward)."""
    shape = tuple(shape)
    if len(shape) == 4:
        dd, h, w, c = shape
        return {"@class": _IT + "InputTypeConvolutional3D",
                "depth": int(dd), "height": int(h), "width": int(w),
                "channels": int(c)}
    if len(shape) == 3:
        h, w, c = shape
        return {"@class": _IT + "InputTypeConvolutional",
                "height": int(h), "width": int(w), "channels": int(c)}
    if len(shape) == 2:
        t, c = shape
        d = {"@class": _IT + "InputTypeRecurrent", "size": int(c)}
        if t is not None:
            d["timeSeriesLength"] = int(t)
        return d
    return {"@class": _IT + "InputTypeFeedForward", "size": int(shape[-1])}


def _input_type_json(net):
    shape = getattr(net, "_init_input_shape", None)
    return None if shape is None else _shape_to_input_type_json(shape)


def _input_type_from_json(it):
    """Upstream InputType JSON → our (kind, shape) input-type tuple."""
    cls = it.get("@class", "").rsplit("$", 1)[-1]
    if cls == "InputTypeConvolutional3D":
        return ("cnn3d", (int(it["depth"]), int(it["height"]),
                          int(it["width"]), int(it["channels"])))
    if cls == "InputTypeConvolutional":
        return ("cnn", (int(it["height"]), int(it["width"]),
                        int(it["channels"])))
    if cls == "InputTypeRecurrent":
        t = it.get("timeSeriesLength")
        return ("rnn", (int(t) if t else None, int(it["size"])))
    if cls == "InputTypeFeedForward":
        return ("ff", (int(it["size"]),))
    raise ValueError(f"unsupported upstream InputType {cls!r}")


def _input_shape_from_json(d, layers):
    it = d.get("inputType")
    if it:
        return _input_type_from_json(it)[1]
    n_in = getattr(layers[0], "n_in", None)
    if n_in:
        # recurrent first layer needs (T, C); feed-forward needs (C,)
        from ..nn.layers.recurrent import BaseRecurrent
        if isinstance(layers[0], BaseRecurrent):
            return (None, int(n_in))
        return (int(n_in),)
    raise ValueError("configuration.json has no inputType and the first "
                     "layer has no nIn — cannot infer input shape")


def write_model_upstream_format(net, path, save_updater: bool = False,
                                normalizer=None):
    """Write ``net`` in the upstream DL4J zip layout (configuration.json +
    coefficients.bin [+ updaterState.bin] [+ normalizer.bin]).
    ComputationGraph nets route to the CG writer automatically."""
    if not hasattr(net, "layers"):          # a ComputationGraph
        return write_computation_graph_upstream_format(
            net, path, save_updater=save_updater, normalizer=normalizer)
    top = json.loads(mln_conf_to_upstream_json(net.conf))
    top["iterationCount"] = int(getattr(net, "_step_count", 0))
    it = _input_type_json(net)   # net's resolved init shape beats the
    if it:                       # config-level declaration when present
        top["inputType"] = it
    arrays = _param_order_arrays(net)
    flat = np.concatenate([a.ravel(order="f").astype(np.float32)
                           for a in arrays]) if arrays else np.zeros(0, "f4")
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(top, indent=2))
        zf.writestr("coefficients.bin",
                    write_nd4j_array(flat.reshape(1, -1), order="f"))
        if save_updater and getattr(net, "_opt_state", None) is not None:
            m, v = _extract_adam_mv(net)
            if m is not None:
                state = np.concatenate([
                    np.concatenate([mm.ravel(order="f"), vv.ravel(order="f")])
                    for mm, vv in zip(m, v)]) if m else np.zeros(0, "f4")
                zf.writestr("updaterState.bin",
                            write_nd4j_array(
                                state.astype(np.float32).reshape(1, -1),
                                order="f"))
        norm = normalizer or getattr(net, "normalizer", None)
        if norm is not None:
            zf.writestr("normalizer.bin",
                        write_normalizer_upstream_format(norm))


def _extract_adam_mv(net):
    """Per-upstream-param [m], [v] lists from the optax state, or (None,
    None) when the optimizer has no adam-style mu/nu."""
    mu = nu = None
    for part in jax.tree_util.tree_leaves(
            net._opt_state, is_leaf=lambda x: hasattr(x, "mu")):
        if hasattr(part, "mu"):
            mu, nu = part.mu, part.nu
            break
    if mu is None:
        return None, None
    ms, vs = [], []
    for nkey, layer, p, s in _iter_param_nodes(net):
        entries = _upstream_param_entries(layer, p, s)
        mu_i = mu.get(nkey, {})
        nu_i = nu.get(nkey, {})
        for key, arr in entries:
            if key in ("mean", "var", "gamma", "beta"):
                src_m = mu_i.get(key) if key in ("gamma", "beta") else None
                src_v = nu_i.get(key) if key in ("gamma", "beta") else None
                if src_m is None:
                    if key in ("mean", "var"):
                        continue       # BN running stats carry no updater state
                    src_m = np.zeros_like(arr)
                    src_v = np.zeros_like(arr)
            else:
                src_m = mu_i.get(key, np.zeros_like(arr))
                src_v = nu_i.get(key, np.zeros_like(arr))
            from ..nn.layers import conv as C
            from ..nn.layers.wrappers import unwrap
            if isinstance(unwrap(layer), C.ConvolutionLayer) and key == "W":
                src_m = np.asarray(src_m).transpose(3, 2, 0, 1)
                src_v = np.asarray(src_v).transpose(3, 2, 0, 1)
            ms.append(np.asarray(src_m))
            vs.append(np.asarray(src_v))
    return ms, vs


def _adopt_updater_state(net, flat: np.ndarray, iteration_count: int = 0):
    """Map an upstream flat Adam state ([m, v] per param, packing order)
    onto ``net._upstream_adam_state`` = (mu_tree, nu_tree, count); MLN
    grafts it into the optax state when the optimizer is built."""
    from ..nn.layers import conv as C
    from ..nn.layers.wrappers import unwrap

    flat = np.asarray(flat).reshape(-1)
    # the mu/nu trees must MATCH net.params' structure (graft tree_maps
    # them), so start every node key — param-less vertex nodes included —
    # with an empty dict
    mu = {k: {} for k in net.params}
    nu = {k: {} for k in net.params}
    off = 0
    for nkey, layer, p, s in _iter_param_nodes(net):
        lyr = unwrap(layer)
        entries = _upstream_param_entries(layer, p, s)
        mu_i, nu_i = {}, {}
        for key, arr in entries:
            if key in ("mean", "var"):
                continue
            n = arr.size
            if off + 2 * n > flat.size:
                raise ValueError("updaterState.bin too short for the "
                                 "configuration's parameters")
            m = flat[off:off + n].reshape(arr.shape, order="f")
            v = flat[off + n:off + 2 * n].reshape(arr.shape, order="f")
            off += 2 * n
            if key not in p:
                continue               # e.g. locked BN gamma/beta
            if isinstance(lyr, C.ConvolutionLayer) and key == "W":
                m = m.transpose(2, 3, 1, 0)
                v = v.transpose(2, 3, 1, 0)
            mu_i[key] = jnp.asarray(m, jnp.float32)
            nu_i[key] = jnp.asarray(v, jnp.float32)
        mu[nkey] = mu_i
        nu[nkey] = nu_i
    if off != flat.size:
        raise ValueError(f"updaterState.bin has {flat.size} floats; the "
                         f"configuration consumes {off}")
    net._upstream_adam_state = (mu, nu, int(iteration_count))


def graft_adam_state(opt_state, upstream):
    """Replace the mu/nu (and count) of any adam-style component inside an
    optax state tuple with the restored upstream trees."""
    mu, nu, count = upstream

    def rec(s):
        if hasattr(s, "mu") and hasattr(s, "nu"):
            new_mu = jax.tree_util.tree_map(
                lambda old, new: jnp.asarray(new, old.dtype
                                             ).reshape(old.shape), s.mu, mu)
            new_nu = jax.tree_util.tree_map(
                lambda old, new: jnp.asarray(new, old.dtype
                                             ).reshape(old.shape), s.nu, nu)
            kw = {"mu": new_mu, "nu": new_nu}
            if hasattr(s, "count"):
                kw["count"] = jnp.asarray(count, s.count.dtype)
            return s._replace(**kw)
        if type(s) is tuple:
            return tuple(rec(x) for x in s)
        return s

    return rec(opt_state)


def restore_upstream_multi_layer_network(path, load_updater: bool = True):
    """Restore an upstream-format DL4J zip as our MultiLayerNetwork."""
    from ..nn.conf import NeuralNetConfiguration
    from ..nn.multi_layer_network import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError(f"{path} is not an upstream-format DL4J zip "
                             "(no configuration.json)")
        conf_json = json.loads(zf.read("configuration.json"))
        if "confs" not in conf_json:
            if "vertices" in conf_json or "networkInputs" in conf_json:
                raise ValueError(
                    "this is an upstream ComputationGraph zip — use "
                    "restore_upstream_computation_graph (or the "
                    "ModelSerializer facade, which auto-routes)")
            raise ValueError("configuration.json has no 'confs' — not an "
                             "upstream MultiLayerConfiguration")
        if "coefficients.bin" not in names:
            raise ValueError(f"{path} has configuration.json but no "
                             "coefficients.bin — not a complete upstream "
                             "DL4J model zip")
        conf = mln_conf_from_upstream_json(conf_json)
        upd = conf.globals_.updater
        net = MultiLayerNetwork(conf)
        net.init(_input_shape_from_json(conf_json, conf.layers))
        flat = read_nd4j_array(zf.read("coefficients.bin"))
        _assign_upstream_params(net, flat)
        net._step_count = int(conf_json.get("iterationCount", 0))
        if load_updater and "updaterState.bin" in names:
            from ..train import updaters as U
            if isinstance(upd, (U.Adam, U.AdamW)):
                _adopt_updater_state(
                    net, read_nd4j_array(zf.read("updaterState.bin")),
                    conf_json.get("iterationCount", 0))
            else:
                import warnings
                warnings.warn(
                    f"updaterState.bin present but the updater is "
                    f"{type(upd).__name__} — only Adam/AdamW state layouts "
                    "(2 floats per param) are mapped; training resumes "
                    "with fresh optimizer state", stacklevel=2)
        if "normalizer.bin" in names:
            net.normalizer = read_normalizer_upstream_format(
                zf.read("normalizer.bin"))
    return net


def is_upstream_format(path) -> bool:
    try:
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
        return "configuration.json" in names and "coefficients.bin" in names
    except (zipfile.BadZipFile, OSError):
        return False


# -------------------------------------------------- ComputationGraph zips --
# Upstream ComputationGraphConfiguration JSON: networkInputs/networkOutputs,
# "vertices" (@class-tagged GraphVertex configs; LayerVertex wraps a
# NeuralNetConfiguration holding the layer), "vertexInputs". Param packing
# follows the graph's topological order (reference ComputationGraph.params()
# flattens vertex param tables in topo order); our writer emits "vertices"
# in that same order so the round trip is stable, and for foreign JSON the
# packing order is OUR (deterministic) Kahn sort — documented assumption,
# same provenance caveat as the module header.

_GV = "org.deeplearning4j.nn.conf.graph."
_EW_FROM_JAVA = {"Add": "add", "Subtract": "sub", "Product": "mul",
                 "Average": "avg", "Max": "max"}
_EW_TO_JAVA = {v: k for k, v in _EW_FROM_JAVA.items()}


def _vertex_from_json(d):
    from ..nn import vertices as V
    cls = d.get("@class", "").rsplit(".", 1)[-1]
    if cls == "MergeVertex":
        return V.MergeVertex(axis=int(d.get("mergeAxis", -1)))
    if cls == "ElementWiseVertex":
        op = d.get("op", "Add")
        if op not in _EW_FROM_JAVA:
            raise ValueError(f"unsupported ElementWiseVertex op {op!r}")
        return V.ElementWiseVertex(op=_EW_FROM_JAVA[op])
    if cls == "ScaleVertex":
        return V.ScaleVertex(scale=float(d.get("scaleFactor", 1.0)))
    if cls == "ShiftVertex":
        return V.ShiftVertex(shift=float(d.get("shiftFactor", 0.0)))
    if cls == "L2NormalizeVertex":
        kw = {}
        if "eps" in d:
            kw["eps"] = float(d["eps"])
        return V.L2NormalizeVertex(**kw)
    if cls == "StackVertex":
        return V.StackVertex()
    if cls == "SubsetVertex":
        return V.SubsetVertex(lo=int(d["from"]), hi=int(d["to"]))
    raise ValueError(
        f"unsupported upstream graph vertex {cls!r} — supported: "
        "LayerVertex, Merge, ElementWise, Scale, Shift, L2Normalize, "
        "Stack, Subset")


def _vertex_to_json(v):
    from ..nn import vertices as V
    if type(v) is V.MergeVertex:
        return {"@class": _GV + "MergeVertex", "mergeAxis": int(v.axis)}
    if type(v) is V.ElementWiseVertex:
        if v.op not in _EW_TO_JAVA:
            raise ValueError(f"ElementWiseVertex op {v.op!r} has no "
                             "upstream analogue")
        return {"@class": _GV + "ElementWiseVertex", "op": _EW_TO_JAVA[v.op]}
    if type(v) is V.ScaleVertex:
        return {"@class": _GV + "ScaleVertex", "scaleFactor": float(v.scale)}
    if type(v) is V.ShiftVertex:
        return {"@class": _GV + "ShiftVertex", "shiftFactor": float(v.shift)}
    if type(v) is V.L2NormalizeVertex:
        return {"@class": _GV + "L2NormalizeVertex", "eps": float(v.eps)}
    if type(v) is V.StackVertex:
        return {"@class": _GV + "StackVertex"}
    if type(v) is V.SubsetVertex:
        return {"@class": _GV + "SubsetVertex", "from": int(v.lo),
                "to": int(v.hi)}
    raise ValueError(f"vertex {type(v).__name__} has no upstream-format "
                     "writer")


def write_computation_graph_upstream_format(cg, path,
                                            save_updater: bool = False,
                                            normalizer=None):
    """Write a ComputationGraph in the upstream DL4J zip layout."""
    top = json.loads(cg_conf_to_upstream_json(cg.conf))
    top["iterationCount"] = int(getattr(cg, "_step_count", 0))
    # convenience duplicate of the per-LayerVertex iUpdater
    top["iUpdater"] = _updater_to_json(cg.conf.globals_.updater)
    shapes = getattr(cg, "_init_shapes", None)
    if shapes:   # the net's resolved init shapes beat any config-level
        top["inputTypes"] = [_shape_to_input_type_json(s) for s in shapes]
    arrays = _param_order_arrays(cg)
    flat = np.concatenate([a.ravel(order="f").astype(np.float32)
                           for a in arrays]) if arrays else np.zeros(0, "f4")
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(top, indent=2))
        zf.writestr("coefficients.bin",
                    write_nd4j_array(flat.reshape(1, -1), order="f"))
        if save_updater and getattr(cg, "_opt_state", None) is not None:
            m, v = _extract_adam_mv(cg)
            if m is not None:
                state = np.concatenate([
                    np.concatenate([mm.ravel(order="f"), vv.ravel(order="f")])
                    for mm, vv in zip(m, v)]) if m else np.zeros(0, "f4")
                zf.writestr("updaterState.bin",
                            write_nd4j_array(
                                state.astype(np.float32).reshape(1, -1),
                                order="f"))
        norm = normalizer or getattr(cg, "normalizer", None)
        if norm is not None:
            zf.writestr("normalizer.bin",
                        write_normalizer_upstream_format(norm))


def restore_upstream_computation_graph(path, input_shapes=None,
                                       load_updater: bool = True):
    """Restore an upstream-format ComputationGraph zip."""
    from ..nn.conf import NeuralNetConfiguration
    from ..nn.computation_graph import ComputationGraph

    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        conf_json = json.loads(zf.read("configuration.json"))
        if "vertices" not in conf_json:
            raise ValueError("configuration.json has no 'vertices' — use "
                             "restore_upstream_multi_layer_network for "
                             "MultiLayerNetwork zips")
        if "coefficients.bin" not in names:
            raise ValueError(f"{path} has configuration.json but no "
                             "coefficients.bin — not a complete upstream "
                             "DL4J model zip")
        gconf = cg_conf_from_upstream_json(conf_json)
        upd = gconf.globals_.updater
        cg = ComputationGraph(gconf)
        if input_shapes is None:
            if gconf.input_types:
                input_shapes = [tuple(t[1]) for t in gconf.input_types]
            else:
                raise ValueError(
                    "configuration.json has no inputTypes — pass "
                    "input_shapes=[...] to restore_upstream_computation_graph")
        cg.init(list(input_shapes))

        flat = read_nd4j_array(zf.read("coefficients.bin"))
        _assign_upstream_params(cg, flat)   # shared MLN/CG unpacker
        cg._step_count = int(conf_json.get("iterationCount", 0))
        if load_updater and "updaterState.bin" in names:
            from ..train import updaters as U
            if isinstance(upd, (U.Adam, U.AdamW)):
                _adopt_updater_state(
                    cg, read_nd4j_array(zf.read("updaterState.bin")),
                    conf_json.get("iterationCount", 0))
            else:
                import warnings
                warnings.warn(
                    f"updaterState.bin present but the updater is "
                    f"{type(upd).__name__} — only Adam/AdamW state layouts "
                    "are mapped; training resumes with fresh optimizer "
                    "state", stacklevel=2)
        if "normalizer.bin" in names:
            cg.normalizer = read_normalizer_upstream_format(
                zf.read("normalizer.bin"))
    return cg


# ----------------------------------------------------------- normalizer.bin
# Reference: ``NormalizerSerializer`` — ModelSerializer.addNormalizerToModel
# stores the fitted normalizer as a "normalizer.bin" zip entry. Wire spec
# (same provenance caveat as the module header; strategies beyond
# standardize/min-max are rejected loudly):
#   writeUTF(strategy)        "STANDARDIZE" | "MIN_MAX"
#   writeBoolean(fitLabels)   1 byte
#   MIN_MAX only: float64 targetMin, float64 targetMax (big-endian)
#   Nd4j arrays: feature stats pair [, label stats pair when fitLabels]
#     STANDARDIZE: mean, std      MIN_MAX: min, max


def _stats_from_mean_std(mean, std):
    from ..data.normalizers import _Stats
    st = _Stats()
    mean = np.asarray(mean, np.float64).reshape(-1)
    std = np.asarray(std, np.float64).reshape(-1)
    st.n = 1
    st.sum = mean.copy()
    st.sum_sq = std * std + mean * mean   # var = sum_sq/n − mean²
    st.min = mean - std
    st.max = mean + std
    return st


def _stats_from_min_max(mn, mx):
    from ..data.normalizers import _Stats
    st = _Stats()
    mn = np.asarray(mn, np.float64).reshape(-1)
    mx = np.asarray(mx, np.float64).reshape(-1)
    st.n = 1
    st.sum = (mn + mx) / 2
    st.sum_sq = st.sum * st.sum
    st.min = mn
    st.max = mx
    return st


def write_normalizer_upstream_format(norm) -> bytes:
    from ..data.normalizers import (NormalizerMinMaxScaler,
                                    NormalizerStandardize)
    buf = io.BytesIO()
    if isinstance(norm, NormalizerStandardize):
        _write_utf(buf, "STANDARDIZE")
        buf.write(struct.pack(">?", bool(norm.fit_labels)))
        arrays = [norm._f.mean, norm._f.std]
        if norm.fit_labels:
            arrays += [norm._l.mean, norm._l.std]
    elif isinstance(norm, NormalizerMinMaxScaler):
        _write_utf(buf, "MIN_MAX")
        buf.write(struct.pack(">?", bool(norm.fit_labels)))
        buf.write(struct.pack(">dd", float(norm.min_range),
                              float(norm.max_range)))
        arrays = [norm._f.min, norm._f.max]
        if norm.fit_labels:
            arrays += [norm._l.min, norm._l.max]
    else:
        raise ValueError(
            f"{type(norm).__name__} has no upstream normalizer.bin writer "
            "(supported: NormalizerStandardize, NormalizerMinMaxScaler)")
    for a in arrays:
        # stats accumulate in f64 — keep that precision on the wire
        # (large-magnitude means lose up to ~1.0 at f32)
        buf.write(write_nd4j_array(
            np.asarray(a, np.float64).reshape(1, -1), order="f"))
    return buf.getvalue()


def read_normalizer_upstream_format(data: bytes):
    from ..data.normalizers import (NormalizerMinMaxScaler,
                                    NormalizerStandardize)
    buf = io.BytesIO(data)
    strategy = _read_utf(buf)
    (fit_labels,) = struct.unpack(">?", buf.read(1))

    def next_array():
        # read_nd4j_array consumes exactly one frame from the stream
        return np.asarray(read_nd4j_array(buf), np.float64).reshape(-1)

    if strategy == "STANDARDIZE":
        norm = NormalizerStandardize()
        norm.fit_labels = bool(fit_labels)
        norm._f = _stats_from_mean_std(next_array(), next_array())
        if fit_labels:
            norm._l = _stats_from_mean_std(next_array(), next_array())
        return norm
    if strategy == "MIN_MAX":
        lo, hi = struct.unpack(">dd", buf.read(16))
        norm = NormalizerMinMaxScaler(min_range=lo, max_range=hi)
        norm.fit_labels = bool(fit_labels)
        norm._f = _stats_from_min_max(next_array(), next_array())
        if fit_labels:
            norm._l = _stats_from_min_max(next_array(), next_array())
        return norm
    raise ValueError(f"unsupported upstream normalizer strategy "
                     f"{strategy!r} (supported: STANDARDIZE, MIN_MAX)")


# ------------------------------------------------- config-level JSON API --
# Reference: ``MultiLayerConfiguration.toJson()/fromJson()`` and
# ``ComputationGraphConfiguration.toJson()/fromJson()`` — the config-only
# half of the interop (no weights). These power the `to_upstream_json` /
# `from_upstream_json` methods on our configuration classes.


_KIND_TO_RANK = {"ff": 1, "rnn": 2, "cnn": 3, "cnn3d": 4}


def _our_input_type_to_json(it):
    """Our (kind, shape) input-type tuple → upstream InputType JSON,
    dispatching on the KIND tag (not shape-length guessing)."""
    kind, shape = it[0], tuple(it[1])
    if kind not in _KIND_TO_RANK:
        raise ValueError(f"input type kind {kind!r} has no upstream "
                         "InputType analogue")
    if len(shape) != _KIND_TO_RANK[kind]:
        raise ValueError(f"input type {it!r}: kind {kind!r} expects a "
                         f"rank-{_KIND_TO_RANK[kind]} shape")
    return _shape_to_input_type_json(shape)


def mln_conf_to_upstream_json(conf) -> str:
    """Our MultiLayerConfiguration → upstream-format JSON string."""
    confs = []
    for layer in conf.layers:
        confs.append({"layer": _layer_to_json(layer),
                      "seed": int(conf.globals_.seed), "miniBatch": True,
                      "iUpdater": _updater_to_json(conf.globals_.updater)})
    top = {"backpropType": "Standard", "confs": confs}
    if conf.input_type is not None:
        top["inputType"] = _our_input_type_to_json(conf.input_type)
    return json.dumps(top, indent=2)


def mln_conf_from_upstream_json(data):
    """Upstream MultiLayerConfiguration JSON (str or parsed dict) → our
    configuration."""
    from ..nn.conf import NeuralNetConfiguration
    d = json.loads(data) if isinstance(data, (str, bytes)) else data
    if "confs" not in d:
        raise ValueError("not an upstream MultiLayerConfiguration (no "
                         "'confs')")
    layers = [_layer_from_json(c["layer"]) for c in d["confs"]]
    builder = NeuralNetConfiguration.builder()
    if d["confs"]:
        builder = builder.seed(d["confs"][0].get("seed", 12345))
        upd = _updater_from_json(d["confs"][0].get("iUpdater"))
        if upd is not None:
            builder = builder.updater(upd)
    lb = builder.list()
    for lyr in layers:
        lb = lb.layer(lyr)
    it = d.get("inputType")
    if it:
        lb = lb.set_input_type(_input_type_from_json(it))
    return lb.build()


def cg_conf_to_upstream_json(conf) -> str:
    """Our ComputationGraphConfiguration → upstream-format JSON string."""
    from ..nn.layers.base import Layer
    vertices = {}
    vertex_inputs = {}
    for name in conf.topo_order:
        node = conf.nodes[name]
        if isinstance(node.op, Layer):
            vertices[name] = {
                "@class": _GV + "LayerVertex",
                "layerConf": {"layer": _layer_to_json(node.op),
                              "seed": int(conf.globals_.seed),
                              "iUpdater": _updater_to_json(
                                  conf.globals_.updater)}}
        else:
            vertices[name] = _vertex_to_json(node.op)
        vertex_inputs[name] = list(node.inputs)
    top = {"networkInputs": list(conf.inputs),
           "networkOutputs": list(conf.outputs),
           "vertices": vertices,
           "vertexInputs": vertex_inputs}
    if conf.input_types:
        top["inputTypes"] = [_our_input_type_to_json(it)
                             for it in conf.input_types]
    return json.dumps(top, indent=2)


def cg_conf_from_upstream_json(data):
    """Upstream ComputationGraphConfiguration JSON (str or parsed dict) →
    our configuration."""
    from ..nn.conf import NeuralNetConfiguration
    d = json.loads(data) if isinstance(data, (str, bytes)) else data
    if "vertices" not in d:
        raise ValueError("not an upstream ComputationGraphConfiguration "
                         "(no 'vertices')")
    builder = NeuralNetConfiguration.builder()
    upd_json = d.get("iUpdater")
    seed = None
    for vd in d["vertices"].values():
        lc = vd.get("layerConf")
        if lc:
            if upd_json is None and lc.get("iUpdater"):
                upd_json = lc["iUpdater"]   # genuine upstream zips carry
                # the updater inside each LayerVertex's NeuralNetConfiguration
            if seed is None and lc.get("seed") is not None:
                seed = int(lc["seed"])
    if seed is not None:
        builder = builder.seed(seed)
    upd = _updater_from_json(upd_json)
    if upd is not None:
        builder = builder.updater(upd)
    gb = builder.graph_builder()
    gb.add_inputs(*d["networkInputs"])
    vertex_inputs = d.get("vertexInputs", {})
    for name, vd in d["vertices"].items():
        cls = vd.get("@class", "").rsplit(".", 1)[-1]
        ins = vertex_inputs.get(name, [])
        if cls == "LayerVertex":
            gb.add_layer(name, _layer_from_json(vd["layerConf"]["layer"]),
                         *ins)
        else:
            gb.add_vertex(name, _vertex_from_json(vd), *ins)
    gb.set_outputs(*d["networkOutputs"])
    its = d.get("inputTypes")
    if its:
        gb.set_input_types(*[_input_type_from_json(it) for it in its])
    return gb.build()
