"""Orbax-backed distributed checkpointing — sharded, async, with retention.

Reference counterpart: DL4J's CheckpointListener + ModelSerializer write a
zip from host memory on one node. The TPU-native path must checkpoint
SHARDED params (fsdp/tp/pp) without gathering to one host and without
stalling the step loop — exactly what orbax provides (per-shard tensorstore
writes, async commit). This wraps orbax with the framework's param/state
pytrees and a CheckpointListener-compatible retention policy, and powers
preemption resume (SURVEY.md §2.8 elastic/failure handling).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Optional

import jax


class OrbaxCheckpointer:
    def __init__(self, directory, max_to_keep: int = 3, async_: bool = True,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_)
        self.manager = ocp.CheckpointManager(str(self.directory), options=options)

    # ------------------------------------------------------------------ save
    def save(self, step: int, params, states=None, opt_state=None,
             metadata: Optional[dict] = None, force: bool = False) -> bool:
        """Async sharded save; returns False if skipped by save_interval."""
        ocp = self._ocp
        items = {"params": ocp.args.StandardSave(params)}
        if states is not None and jax.tree_util.tree_leaves(states):
            items["states"] = ocp.args.StandardSave(states)
        if opt_state is not None and jax.tree_util.tree_leaves(opt_state):
            items["opt_state"] = ocp.args.StandardSave(opt_state)
        if metadata:
            items["metadata"] = ocp.args.JsonSave(metadata)
        return self.manager.save(step, args=ocp.args.Composite(**items),
                                 force=force)

    def wait(self):
        self.manager.wait_until_finished()

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(self, step: Optional[int] = None, params_like=None,
                states_like=None, opt_state_like=None):
        """Restore (params, states, opt_state, metadata); `*_like` trees give
        target shardings/dtypes so shards land directly on their devices."""
        ocp = self._ocp
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        kw = {}
        if params_like is not None:
            kw["params"] = ocp.args.StandardRestore(params_like)
        else:
            kw["params"] = ocp.args.StandardRestore()
        saved = set()
        try:
            saved = set(self.manager.item_metadata(step).keys())
        except Exception:  # noqa: BLE001 — older orbax
            pass
        if not saved or "states" in saved:
            kw["states"] = ocp.args.StandardRestore(states_like)
        if not saved or "opt_state" in saved:
            kw["opt_state"] = ocp.args.StandardRestore(opt_state_like)
        if not saved or "metadata" in saved:
            kw["metadata"] = ocp.args.JsonRestore()
        try:
            out = self.manager.restore(step, args=ocp.args.Composite(**kw))
        except Exception:
            # retry with params only (checkpoint without optional items)
            out = self.manager.restore(step, args=ocp.args.Composite(
                params=kw["params"]))
        get = lambda k: out.get(k) if hasattr(out, "get") else getattr(out, k, None)
        return get("params"), get("states"), get("opt_state"), get("metadata")

    def close(self):
        self.manager.close()


class PreemptionWatchdog:
    """Elastic/failure handling: checkpoint on a deadline so preemption
    (SIGTERM with grace period, maintenance events) never loses more than
    `interval_s` of work. Reference counterpart: Spark/Aeron trainers
    restarting from the last ModelSerializer write."""

    def __init__(self, checkpointer: OrbaxCheckpointer, interval_s: float = 300.0):
        self.ckpt = checkpointer
        self.interval_s = interval_s
        self._last = time.monotonic()
        self._installed = False

    def maybe_save(self, step: int, params, states=None, opt_state=None) -> bool:
        now = time.monotonic()
        if now - self._last >= self.interval_s:
            self.ckpt.save(step, params, states, opt_state, force=True)
            self._last = now
            return True
        return False

    def install_signal_handler(self, get_state_fn):
        """On SIGTERM: synchronously save `get_state_fn() -> (step, params,
        states, opt_state)` before the process dies."""
        import signal

        def handler(signum, frame):
            step, params, states, opt_state = get_state_fn()
            self.ckpt.save(step, params, states, opt_state, force=True)
            self.ckpt.wait()
            raise SystemExit(143)

        signal.signal(signal.SIGTERM, handler)
        self._installed = True


class CheckpointingTrainerMixin:
    """Glue for MultiLayerNetwork/ComputationGraph: resume_or_init()."""

    @staticmethod
    def resume(net, checkpointer: OrbaxCheckpointer):
        step = checkpointer.latest_step()
        if step is None:
            return 0
        params, states, opt_state, meta = checkpointer.restore(
            params_like=net.params,
            states_like=net.states if jax.tree_util.tree_leaves(net.states) else None,
            opt_state_like=net._opt_state)
        net.params = params
        if states is not None:
            net.states = states
        if opt_state is not None:
            net._opt_state = opt_state
        if meta:
            net._step_count = meta.get("step_count", step)
            net.epoch_count = meta.get("epoch_count", 0)
        return step
