"""Version shims over the moving parts of the jax API surface.

The code in this repo is written against the modern spellings
(``jax.shard_map`` with ``check_vma=``, ``lax.axis_size``); older
runtimes — the pinned container image runs jax 0.4.37 — only have
``jax.experimental.shard_map.shard_map`` with ``check_rep=`` and no
``lax.axis_size`` at all. Every internal user imports through this
module so the mapping lives in exactly one place. Dependency-free and
package-level on purpose: ``parallel``, ``zoo`` and ``serving`` all
reach it as ``from .._jax_compat import shard_map``.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map
    _MODERN = True
except ImportError:  # jax < 0.6: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map
    _MODERN = False


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    """``jax.shard_map`` accepting the modern ``check_vma`` kwarg on any
    jax: on old runtimes it is passed through as ``check_rep`` (same
    meaning — disable the replication/varying-manual-axes check)."""
    if not _MODERN and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def axis_size(axis_name):
    """``lax.axis_size`` (modern) — spelled ``psum(1, axis)`` on runtimes
    that predate it (a python-int psum constant-folds to the STATIC axis
    size, so loop bounds and permutations stay trace-time constants).
    Valid only inside a mapped (shard_map/pmap) region, same as the real
    thing."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
