"""deeplearning4j_tpu.autodiff — SameDiff graph API (whole-graph XLA)."""

from .samediff import SameDiff, SDVariable, TrainingConfig
