"""deeplearning4j_tpu.autodiff — SameDiff graph API (whole-graph XLA)."""

from .samediff import History, SameDiff, SDVariable, TrainingConfig
from .onnx_import import import_onnx
from .tf_import import import_frozen_graph
