"""TF frozen-GraphDef import → SameDiff graph (the reference's BERT path).

Reference parity: ``org.nd4j.imports.graphmapper.tf.TFGraphMapper`` /
``samediff-import-tensorflow`` — DL4J runs BERT by importing a frozen TF
graph into SameDiff. Here the GraphDef is parsed (tensorflow is baked into
the image; only the proto reader is used — no TF execution) and mapped onto
our SameDiff, which then jits the WHOLE graph through XLA instead of the
reference's per-op interpreter.

Supported op subset covers transformer/BERT-style graphs: matmul/bias/
elementwise chains, reshapes/transposes, softmax, layer-norm primitive
chains, gather (embeddings), batched matmul, one_hot, reductions, and the
shape plumbing ops. Unknown ops raise with the op name so coverage gaps are
loud, not silent.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .samediff import SameDiff, SDVariable


def _tensor_to_np(tensor_proto):
    from tensorflow.python.framework import tensor_util
    return tensor_util.MakeNdarray(tensor_proto)


def _axes(v):
    return tuple(int(a) for a in np.asarray(v).ravel())


def _attr_f(node, name, default):
    """Float attr with an explicit-presence check: an attr explicitly set
    to 0.0 must NOT fall back to the default (`.f or default` would)."""
    return node.attr[name].f if name in node.attr else default


class TFImporter:
    def __init__(self):
        self.handlers = {
            "Const": None, "Placeholder": None, "Identity": self._identity,
            "IdentityN": self._identity, "NoOp": None,
            "MatMul": self._matmul, "BatchMatMul": self._batch_matmul,
            "BatchMatMulV2": self._batch_matmul,
            "BiasAdd": lambda i, n: i[0] + i[1],
            "Add": lambda i, n: i[0] + i[1], "AddV2": lambda i, n: i[0] + i[1],
            "AddN": lambda i, n: sum(i),
            "Sub": lambda i, n: i[0] - i[1], "Mul": lambda i, n: i[0] * i[1],
            "RealDiv": lambda i, n: i[0] / i[1], "Div": lambda i, n: i[0] / i[1],
            "Maximum": lambda i, n: jnp.maximum(i[0], i[1]),
            "Minimum": lambda i, n: jnp.minimum(i[0], i[1]),
            "Pow": lambda i, n: jnp.power(i[0], i[1]),
            "SquaredDifference": lambda i, n: jnp.square(i[0] - i[1]),
            "Square": lambda i, n: jnp.square(i[0]),
            "Sqrt": lambda i, n: jnp.sqrt(i[0]),
            "Rsqrt": lambda i, n: lax.rsqrt(i[0]),
            "Exp": lambda i, n: jnp.exp(i[0]), "Log": lambda i, n: jnp.log(i[0]),
            "Neg": lambda i, n: -i[0], "Abs": lambda i, n: jnp.abs(i[0]),
            "Tanh": lambda i, n: jnp.tanh(i[0]),
            "Sigmoid": lambda i, n: jax.nn.sigmoid(i[0]),
            "Relu": lambda i, n: jax.nn.relu(i[0]),
            "Relu6": lambda i, n: jax.nn.relu6(i[0]),
            "Elu": lambda i, n: jax.nn.elu(i[0]),
            "Selu": lambda i, n: jax.nn.selu(i[0]),
            "Softplus": lambda i, n: jax.nn.softplus(i[0]),
            "Erf": lambda i, n: jax.scipy.special.erf(i[0]),
            "Softmax": lambda i, n: jax.nn.softmax(i[0], axis=-1),
            "LogSoftmax": lambda i, n: jax.nn.log_softmax(i[0], axis=-1),
            "Reshape": lambda i, n: jnp.reshape(i[0], _axes(i[1])),
            "Transpose": lambda i, n: jnp.transpose(i[0], _axes(i[1])),
            "ExpandDims": lambda i, n: jnp.expand_dims(i[0], int(np.asarray(i[1]))),
            "Squeeze": self._squeeze,
            "ConcatV2": lambda i, n: jnp.concatenate(i[:-1], axis=int(np.asarray(i[-1]))),
            "Pack": self._pack, "Unpack": self._unpack,
            "Split": self._split, "SplitV": self._splitv,
            "StridedSlice": self._strided_slice,
            "Slice": self._slice,
            "GatherV2": self._gather, "Gather": self._gather,
            "OneHot": self._one_hot,
            "Cast": self._cast,
            "Mean": self._mean, "Sum": self._sum, "Max": self._rmax,
            "Min": self._rmin, "Prod": self._prod,
            "ArgMax": lambda i, n: jnp.argmax(i[0], axis=int(np.asarray(i[1]))),
            "Shape": lambda i, n: jnp.asarray(i[0].shape, jnp.int32),
            "Rank": lambda i, n: jnp.asarray(np.ndim(i[0]), jnp.int32),
            "Fill": lambda i, n: jnp.full(_axes(i[0]), i[1]),
            "ZerosLike": lambda i, n: jnp.zeros_like(i[0]),
            "OnesLike": lambda i, n: jnp.ones_like(i[0]),
            "Tile": lambda i, n: jnp.tile(i[0], _axes(i[1])),
            "StopGradient": lambda i, n: lax.stop_gradient(i[0]),
            "Rsub": lambda i, n: i[1] - i[0],
            "Einsum": lambda i, n: jnp.einsum(
                n.attr["equation"].s.decode(), *i),
            "FusedBatchNorm": self._fused_bn, "FusedBatchNormV3": self._fused_bn,
            "Conv2D": self._conv2d, "MaxPool": self._maxpool,
            "AvgPool": self._avgpool,
            "Greater": lambda i, n: jnp.greater(i[0], i[1]),
            "GreaterEqual": lambda i, n: jnp.greater_equal(i[0], i[1]),
            "Less": lambda i, n: jnp.less(i[0], i[1]),
            "Equal": lambda i, n: jnp.equal(i[0], i[1]),
            "NotEqual": lambda i, n: jnp.not_equal(i[0], i[1]),
            "Select": lambda i, n: jnp.where(i[0], i[1], i[2]),
            "SelectV2": lambda i, n: jnp.where(i[0], i[1], i[2]),
            "Tanh_": lambda i, n: jnp.tanh(i[0]),
            # --- r3 widening: the broad frozen-graph long tail ------------
            "Floor": lambda i, n: jnp.floor(i[0]),
            "Ceil": lambda i, n: jnp.ceil(i[0]),
            "Round": lambda i, n: jnp.round(i[0]),
            "Rint": lambda i, n: jnp.rint(i[0]),
            "Sign": lambda i, n: jnp.sign(i[0]),
            "FloorDiv": lambda i, n: jnp.floor_divide(i[0], i[1]),
            "FloorMod": lambda i, n: jnp.mod(i[0], i[1]),
            "Mod": lambda i, n: jnp.fmod(i[0], i[1]),   # TF Mod truncates
            "Log1p": lambda i, n: jnp.log1p(i[0]),
            "Expm1": lambda i, n: jnp.expm1(i[0]),
            "Sin": lambda i, n: jnp.sin(i[0]),
            "Cos": lambda i, n: jnp.cos(i[0]),
            "Tan": lambda i, n: jnp.tan(i[0]),
            "Asin": lambda i, n: jnp.arcsin(i[0]),
            "Acos": lambda i, n: jnp.arccos(i[0]),
            "Atan": lambda i, n: jnp.arctan(i[0]),
            "Sinh": lambda i, n: jnp.sinh(i[0]),
            "Cosh": lambda i, n: jnp.cosh(i[0]),
            "Asinh": lambda i, n: jnp.arcsinh(i[0]),
            "Acosh": lambda i, n: jnp.arccosh(i[0]),
            "Atanh": lambda i, n: jnp.arctanh(i[0]),
            "Atan2": lambda i, n: jnp.arctan2(i[0], i[1]),
            "Reciprocal": lambda i, n: jnp.reciprocal(i[0]),
            "Inv": lambda i, n: jnp.reciprocal(i[0]),
            "Erfc": lambda i, n: jax.scipy.special.erfc(i[0]),
            "LeakyRelu": lambda i, n: jax.nn.leaky_relu(
                i[0], _attr_f(n, "alpha", 0.2)),
            "Softsign": lambda i, n: jax.nn.soft_sign(i[0]),
            "IsNan": lambda i, n: jnp.isnan(i[0]),
            "IsInf": lambda i, n: jnp.isinf(i[0]),
            "IsFinite": lambda i, n: jnp.isfinite(i[0]),
            "LogicalAnd": lambda i, n: jnp.logical_and(i[0], i[1]),
            "LogicalOr": lambda i, n: jnp.logical_or(i[0], i[1]),
            "LogicalNot": lambda i, n: jnp.logical_not(i[0]),
            "LessEqual": lambda i, n: jnp.less_equal(i[0], i[1]),
            "All": self._rall, "Any": self._rany,
            "ArgMin": lambda i, n: jnp.argmin(i[0], axis=int(np.asarray(i[1]))),
            "Cumsum": self._cumsum, "Cumprod": self._cumprod,
            "Pad": lambda i, n: jnp.pad(i[0], np.asarray(i[1])),
            "PadV2": lambda i, n: jnp.pad(
                i[0], np.asarray(i[1]),
                constant_values=float(np.asarray(i[2]))),
            "MirrorPad": lambda i, n: jnp.pad(
                i[0], np.asarray(i[1]),
                mode=("reflect" if n.attr["mode"].s == b"REFLECT"
                      else "symmetric")),
            "Concat": lambda i, n: jnp.concatenate(
                i[1:], axis=int(np.asarray(i[0]))),   # legacy: axis FIRST
            "ReverseV2": lambda i, n: jnp.flip(i[0], _axes(i[1])),
            "Range": lambda i, n: jnp.arange(
                np.asarray(i[0]).item(), np.asarray(i[1]).item(),
                np.asarray(i[2]).item()),
            "LinSpace": lambda i, n: jnp.linspace(
                np.asarray(i[0]).item(), np.asarray(i[1]).item(),
                int(np.asarray(i[2]))),
            "Size": lambda i, n: jnp.asarray(np.prod(i[0].shape), jnp.int32),
            "BroadcastTo": lambda i, n: jnp.broadcast_to(i[0], _axes(i[1])),
            "GatherNd": self._gather_nd,
            "ScatterNd": lambda i, n: jnp.zeros(
                _axes(i[2]), i[1].dtype).at[
                tuple(jnp.asarray(i[0]).astype(jnp.int32)[..., k]
                      for k in range(i[0].shape[-1]))].add(i[1]),
            "TensorScatterUpdate": lambda i, n: i[0].at[
                tuple(jnp.asarray(i[1]).astype(jnp.int32)[..., k]
                      for k in range(i[1].shape[-1]))].set(i[2]),
            "TensorScatterAdd": lambda i, n: i[0].at[
                tuple(jnp.asarray(i[1]).astype(jnp.int32)[..., k]
                      for k in range(i[1].shape[-1]))].add(i[2]),
            "InvertPermutation": lambda i, n: jnp.argsort(i[0]),
            "MatrixBandPart": self._matrix_band_part,
            "MatrixDiag": lambda i, n: i[0][..., None]
                * jnp.eye(i[0].shape[-1], dtype=i[0].dtype),
            "MatrixDiagPart": lambda i, n: jnp.diagonal(
                i[0], axis1=-2, axis2=-1),
            "L2Loss": lambda i, n: 0.5 * jnp.sum(jnp.square(i[0])),
            "LRN": self._lrn,
            "DepthwiseConv2dNative": self._depthwise_conv2d,
            "Conv2DBackpropInput": self._conv2d_transpose,
            "SpaceToDepth": lambda i, n: self._space_depth(i[0],
                                                           n, to_depth=True),
            "DepthToSpace": lambda i, n: self._space_depth(i[0],
                                                           n, to_depth=False),
            "ResizeBilinear": self._resize_bilinear,
            "ResizeNearestNeighbor": self._resize_nearest,
            # spectral family (rides the new sd_ops FFT work)
            "FFT": lambda i, n: jnp.fft.fft(i[0]),
            "IFFT": lambda i, n: jnp.fft.ifft(i[0]),
            "FFT2D": lambda i, n: jnp.fft.fft2(i[0]),
            "IFFT2D": lambda i, n: jnp.fft.ifft2(i[0]),
            "RFFT": lambda i, n: jnp.fft.rfft(
                i[0], n=int(_axes(i[1])[0]) if len(i) > 1 else None),
            "IRFFT": lambda i, n: jnp.fft.irfft(
                i[0], n=int(_axes(i[1])[0]) if len(i) > 1 else None),
            "ComplexAbs": lambda i, n: jnp.abs(i[0]),
            "Real": lambda i, n: jnp.real(i[0]),
            "Imag": lambda i, n: jnp.imag(i[0]),
            "Conj": lambda i, n: jnp.conj(i[0]),
            "Complex": lambda i, n: lax.complex(i[0], i[1]),
            "Angle": lambda i, n: jnp.angle(i[0]),
            # --- r4 widening: arbitrary-frozen-graph generality -----------
            "ClipByValue": lambda i, n: jnp.clip(i[0], i[1], i[2]),
            "Xlogy": lambda i, n: jax.scipy.special.xlogy(i[0], i[1]),
            "Xlog1py": lambda i, n: jax.scipy.special.xlog1py(i[0], i[1]),
            "Xdivy": lambda i, n: jnp.where(
                i[0] == 0, 0.0, i[0] / jnp.where(i[0] == 0, 1.0, i[1])),
            "Digamma": lambda i, n: jax.scipy.special.digamma(i[0]),
            "Lgamma": lambda i, n: jax.scipy.special.gammaln(i[0]),
            "Igamma": lambda i, n: jax.scipy.special.gammainc(i[0], i[1]),
            "Igammac": lambda i, n: jax.scipy.special.gammaincc(i[0], i[1]),
            "Polygamma": lambda i, n: jax.scipy.special.polygamma(
                jnp.asarray(i[0]).astype(jnp.int32), i[1]),
            "Zeta": lambda i, n: jax.scipy.special.zeta(i[0], i[1]),
            "Betainc": lambda i, n: jax.scipy.special.betainc(
                i[0], i[1], i[2]),
            "Erfinv": lambda i, n: jax.scipy.special.erfinv(i[0]),
            "Ndtri": lambda i, n: jax.scipy.special.ndtri(i[0]),
            "TopKV2": self._topk,
            "SegmentSum": lambda i, n: self._segment(i, "sum"),
            "SegmentMean": lambda i, n: self._segment(i, "mean"),
            "SegmentMax": lambda i, n: self._segment(i, "max"),
            "SegmentMin": lambda i, n: self._segment(i, "min"),
            "SegmentProd": lambda i, n: self._segment(i, "prod"),
            "UnsortedSegmentSum": lambda i, n: self._segment(i, "sum",
                                                             unsorted=True),
            "UnsortedSegmentMax": lambda i, n: self._segment(i, "max",
                                                             unsorted=True),
            "UnsortedSegmentMin": lambda i, n: self._segment(i, "min",
                                                             unsorted=True),
            "UnsortedSegmentProd": lambda i, n: self._segment(i, "prod",
                                                              unsorted=True),
            "Bincount": lambda i, n: jnp.bincount(
                jnp.asarray(i[0]).astype(jnp.int32).ravel(),
                weights=None if np.asarray(i[2]).size == 0 else i[2].ravel(),
                length=int(np.asarray(i[1]))),
            "DynamicPartition": self._dynamic_partition,
            "DynamicStitch": self._dynamic_stitch,
            "ParallelDynamicStitch": self._dynamic_stitch,
            "SpaceToBatchND": self._space_to_batch_nd,
            "BatchToSpaceND": self._batch_to_space_nd,
            "Dilation2D": self._dilation2d,
            "Conv3D": self._conv3d,
            "MaxPool3D": self._maxpool3d,
            "AvgPool3D": self._avgpool3d,
            "FakeQuantWithMinMaxArgs": self._fake_quant_args,
            "CheckNumerics": self._check_numerics,
            "Snapshot": self._identity,
            "PreventGradient": self._identity,
            "EnsureShape": self._identity,
            "NonMaxSuppressionV3": self._nms_v3,
            "NonMaxSuppressionV4": self._nms_v4,
            "CropAndResize": self._crop_and_resize,
            "ResizeBicubic": self._resize_bicubic,
            "DrawBoundingBoxesV2": self._draw_boxes,
            "DrawBoundingBoxes": self._draw_boxes,
            "MatrixDeterminant": lambda i, n: jnp.linalg.det(i[0]),
            "MatrixInverse": lambda i, n: jnp.linalg.inv(i[0]),
            "Cholesky": lambda i, n: jnp.linalg.cholesky(i[0]),
            "LogMatrixDeterminant": lambda i, n: list(
                jnp.linalg.slogdet(i[0])),
            "SoftmaxCrossEntropyWithLogits": self._softmax_xent,
            "SparseSoftmaxCrossEntropyWithLogits": self._sparse_softmax_xent,
            "Roll": lambda i, n: jnp.roll(i[0], _axes(i[1]), _axes(i[2])),
            "Bucketize": lambda i, n: jnp.searchsorted(
                jnp.asarray(list(n.attr["boundaries"].list.f)),
                i[0], side="right").astype(jnp.int32),
            # TF clamps out-of-range values into the edge bins; jnp.histogram
            # would drop them, so clip first
            "HistogramFixedWidth": lambda i, n: jnp.histogram(
                jnp.clip(i[0], float(np.asarray(i[1])[0]),
                         float(np.asarray(i[1])[1])),
                bins=int(np.asarray(i[2])),
                range=(float(np.asarray(i[1])[0]),
                       float(np.asarray(i[1])[1])))[0].astype(jnp.int32),
            "BroadcastArgs": lambda i, n: jnp.asarray(
                np.broadcast_shapes(tuple(_axes(i[0])), tuple(_axes(i[1]))),
                jnp.int32),
            "LeftShift": lambda i, n: jnp.left_shift(i[0], i[1]),
            "RightShift": lambda i, n: jnp.right_shift(i[0], i[1]),
            "BitwiseAnd": lambda i, n: jnp.bitwise_and(i[0], i[1]),
            "BitwiseOr": lambda i, n: jnp.bitwise_or(i[0], i[1]),
            "BitwiseXor": lambda i, n: jnp.bitwise_xor(i[0], i[1]),
            "Invert": lambda i, n: jnp.bitwise_not(i[0]),
            "AccumulateNV2": lambda i, n: sum(i),
            "RandomUniform": lambda i, n: jax.random.uniform(
                self._node_key(n), _axes(i[0])),
            "RandomStandardNormal": lambda i, n: jax.random.normal(
                self._node_key(n), _axes(i[0])),
            "TruncatedNormal": lambda i, n: jax.random.truncated_normal(
                self._node_key(n), -2.0, 2.0, _axes(i[0])),
            "RandomUniformInt": lambda i, n: jax.random.randint(
                self._node_key(n), _axes(i[0]), int(np.asarray(i[1])),
                int(np.asarray(i[2]))),
            "Multinomial": lambda i, n: self._multinomial(i, n),
            # --- control flow: V2 functional ops --------------------------
            "If": self._if, "StatelessIf": self._if,
            "While": self._while, "StatelessWhile": self._while,
            "PartitionedCall": self._call, "StatefulPartitionedCall":
                self._call,
            # V1 Switch/Merge conditionals are wired in import_graph (they
            # need graph-level branch tracking); V1 loop frames are not
            # representable without frame analysis — loud error:
            "Enter": self._v1_loop_err, "Exit": self._v1_loop_err,
            "NextIteration": self._v1_loop_err,
            "LoopCond": self._v1_loop_err,
        }
        # ops with >1 output: op type -> (node -> output count)
        self.multi_output = {
            "Split": lambda n: n.attr["num_split"].i,
            "SplitV": lambda n: n.attr["num_split"].i,
            "Unpack": lambda n: n.attr["num"].i,
            "TopKV2": lambda n: 2,
            "LogMatrixDeterminant": lambda n: 2,
            "SoftmaxCrossEntropyWithLogits": lambda n: 2,
            "SparseSoftmaxCrossEntropyWithLogits": lambda n: 2,
            "NonMaxSuppressionV4": lambda n: 2,
            "DynamicPartition": lambda n: n.attr["num_partitions"].i,
            "If": lambda n: len(n.attr["Tout"].list.type),
            "StatelessIf": lambda n: len(n.attr["Tout"].list.type),
            "While": lambda n: len(n.attr["T"].list.type),
            "StatelessWhile": lambda n: len(n.attr["T"].list.type),
            "PartitionedCall": lambda n: len(n.attr["Tout"].list.type),
            "StatefulPartitionedCall":
                lambda n: len(n.attr["Tout"].list.type),
        }
        self._functions = {}

    # --- handlers needing node attrs ---------------------------------------
    def _identity(self, i, n):
        return i[0]

    def _matmul(self, i, n):
        a, b = i[0], i[1]
        if n.attr["transpose_a"].b:
            a = a.T
        if n.attr["transpose_b"].b:
            b = b.T
        return a @ b

    def _batch_matmul(self, i, n):
        a, b = i[0], i[1]
        if n.attr["adj_x"].b:
            a = jnp.swapaxes(a, -1, -2)
        if n.attr["adj_y"].b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    def _squeeze(self, i, n):
        dims = tuple(n.attr["squeeze_dims"].list.i)
        return jnp.squeeze(i[0], axis=dims if dims else None)

    def _pack(self, i, n):
        return jnp.stack(i, axis=n.attr["axis"].i)

    def _unpack(self, i, n):
        ax = n.attr["axis"].i
        num = n.attr["num"].i
        return [jnp.squeeze(s, ax) for s in jnp.split(i[0], num, axis=ax)]

    def _split(self, i, n):
        ax = int(np.asarray(i[0]))
        return jnp.split(i[1], n.attr["num_split"].i, axis=ax)

    def _splitv(self, i, n):
        sizes = _axes(i[1])
        ax = int(np.asarray(i[2]))
        idx = np.cumsum(sizes)[:-1].tolist()
        return jnp.split(i[0], idx, axis=ax)

    def _strided_slice(self, i, n):
        x, begin, end, strides = i[0], _axes(i[1]), _axes(i[2]), _axes(i[3])
        bm = n.attr["begin_mask"].i
        em = n.attr["end_mask"].i
        sm = n.attr["shrink_axis_mask"].i
        nm = n.attr["new_axis_mask"].i
        em_ellipsis = n.attr["ellipsis_mask"].i
        idx = []
        for d in range(len(begin)):
            if em_ellipsis & (1 << d):
                idx.append(Ellipsis)
            elif nm & (1 << d):
                idx.append(None)
            elif sm & (1 << d):
                idx.append(begin[d])
            else:
                b = None if (bm & (1 << d)) else begin[d]
                e = None if (em & (1 << d)) else end[d]
                idx.append(slice(b, e, strides[d]))
        return x[tuple(idx)]

    def _slice(self, i, n):
        begin = _axes(i[1])
        size = _axes(i[2])
        # TF convention: size -1 → everything from begin to the end of the dim
        size = tuple(d - b if s == -1 else s
                     for b, s, d in zip(begin, size, i[0].shape))
        return lax.dynamic_slice(i[0], begin, size)

    def _gather(self, i, n):
        ax = int(np.asarray(i[2])) if len(i) > 2 else 0
        return jnp.take(i[0], i[1].astype(jnp.int32), axis=ax)

    def _one_hot(self, i, n):
        depth = int(np.asarray(i[1]))
        on = i[2] if len(i) > 2 else 1.0
        off = i[3] if len(i) > 3 else 0.0
        oh = jax.nn.one_hot(i[0].astype(jnp.int32), depth)
        return oh * on + (1 - oh) * off

    _TF_DTYPES = {1: jnp.float32, 2: jnp.float64, 3: jnp.int32, 9: jnp.int64,
                  10: jnp.bool_, 14: jnp.bfloat16, 19: jnp.float16}

    def _cast(self, i, n):
        return i[0].astype(self._TF_DTYPES.get(n.attr["DstT"].type, jnp.float32))

    def _mean(self, i, n):
        return jnp.mean(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _sum(self, i, n):
        return jnp.sum(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rmax(self, i, n):
        return jnp.max(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rmin(self, i, n):
        return jnp.min(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _prod(self, i, n):
        return jnp.prod(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rall(self, i, n):
        return jnp.all(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rany(self, i, n):
        return jnp.any(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _cumsum(self, i, n):
        ax = int(np.asarray(i[1]))
        x = jnp.flip(i[0], ax) if n.attr["reverse"].b else i[0]
        if n.attr["exclusive"].b:
            y = jnp.cumsum(x, axis=ax) - x
        else:
            y = jnp.cumsum(x, axis=ax)
        return jnp.flip(y, ax) if n.attr["reverse"].b else y

    def _cumprod(self, i, n):
        ax = int(np.asarray(i[1]))
        x = jnp.flip(i[0], ax) if n.attr["reverse"].b else i[0]
        y = jnp.cumprod(x, axis=ax)
        if n.attr["exclusive"].b:
            # shift-by-one with a leading 1 (zero-safe, dtype-preserving —
            # dividing out x would be wrong at zeros and float-promote ints)
            lead = list(x.shape)
            lead[ax] = 1
            y = jnp.concatenate(
                [jnp.ones(lead, y.dtype),
                 lax.slice_in_dim(y, 0, x.shape[ax] - 1, axis=ax)], axis=ax)
        return jnp.flip(y, ax) if n.attr["reverse"].b else y

    def _gather_nd(self, i, n):
        idx = jnp.asarray(i[1]).astype(jnp.int32)
        return i[0][tuple(idx[..., k] for k in range(idx.shape[-1]))]

    def _matrix_band_part(self, i, n):
        x = i[0]
        lo, hi = int(np.asarray(i[1])), int(np.asarray(i[2]))
        r = jnp.arange(x.shape[-2])[:, None] - jnp.arange(x.shape[-1])[None, :]
        keep = ((r <= (lo if lo >= 0 else x.shape[-2]))
                & (-r <= (hi if hi >= 0 else x.shape[-1])))
        return x * keep.astype(x.dtype)

    def _lrn(self, i, n):
        r = n.attr["depth_radius"].i if "depth_radius" in n.attr else 5
        bias = _attr_f(n, "bias", 1.0)
        alpha = _attr_f(n, "alpha", 1.0)
        beta = _attr_f(n, "beta", 0.5)
        sq = lax.reduce_window(jnp.square(i[0]), 0.0, lax.add,
                               (1, 1, 1, 2 * r + 1), (1, 1, 1, 1), "SAME")
        return i[0] / jnp.power(bias + alpha * sq, beta)

    def _depthwise_conv2d(self, i, n):
        strides = tuple(n.attr["strides"].list.i)[1:3]
        pad = n.attr["padding"].s.decode()
        w = i[1]  # TF (kh, kw, cin, mult) → lax HWIO (kh, kw, 1, cin*mult)
        kh, kw, cin, mult = w.shape
        w = w.reshape(kh, kw, 1, cin * mult)
        return lax.conv_general_dilated(
            i[0], w, strides, pad, dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=cin)

    def _conv2d_transpose(self, i, n):
        # inputs: output_shape (const), filters (kh,kw,Cout,Cin), dy.
        # True gradient-of-conv: dilate dy by the stride, pad with the
        # TRANSPOSED forward pads (derived from TF's SAME/VALID rule on the
        # requested output size — authoritative, so odd sizes land exact),
        # convolve with the spatially-flipped, io-swapped kernel.
        strides = tuple(n.attr["strides"].list.i)[1:3]
        padding = n.attr["padding"].s.decode()
        dy = i[2]
        oh, ow = (int(v) for v in _axes(i[0])[1:3])
        w = i[1]
        kh, kw = w.shape[:2]
        wf = jnp.transpose(w[::-1, ::-1], (0, 1, 3, 2))

        def grad_pad(out_sz, in_sz, k, s):
            if padding == "SAME":
                fwd_out = -(-out_sz // s)
                total = max(0, (fwd_out - 1) * s + k - out_sz)
                fwd_lo = total // 2
            else:
                fwd_lo = 0
            lo = k - 1 - fwd_lo
            dil = (in_sz - 1) * s + 1
            hi = out_sz + k - 1 - dil - lo   # solves out == requested size
            return lo, hi

        ph = grad_pad(oh, dy.shape[1], kh, strides[0])
        pw_ = grad_pad(ow, dy.shape[2], kw, strides[1])
        return lax.conv_general_dilated(
            dy, wf, (1, 1), (ph, pw_), lhs_dilation=strides,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def _space_depth(self, x, n, to_depth):
        bs = n.attr["block_size"].i
        b, h, w, c = x.shape
        if to_depth:
            return x.reshape(b, h // bs, bs, w // bs, bs, c).transpose(
                0, 1, 3, 2, 4, 5).reshape(b, h // bs, w // bs, bs * bs * c)
        return x.reshape(b, h, w, bs, bs, c // (bs * bs)).transpose(
            0, 1, 3, 2, 4, 5).reshape(b, h * bs, w * bs, c // (bs * bs))

    def _resize_coords(self, n, in_dim, out_dim, clamp_half_pixel=True):
        """Source sample coordinates for the three TF resize conventions.
        Bilinear/nearest clamp the half-pixel coordinate at 0 (TF's
        HalfPixelScalerForNN/legacy behavior); bicubic keeps the negative
        border coordinate and clamps the TAPS instead — pass
        clamp_half_pixel=False there."""
        if n.attr["align_corners"].b and out_dim > 1:
            return jnp.linspace(0.0, in_dim - 1, out_dim)
        if n.attr["half_pixel_centers"].b:
            scale = in_dim / out_dim
            c = (jnp.arange(out_dim) + 0.5) * scale - 0.5
            return jnp.maximum(c, 0.0) if clamp_half_pixel else c
        return jnp.arange(out_dim) * (in_dim / out_dim)   # v1 legacy

    def _resize_bilinear(self, i, n):
        x = i[0]
        oh, ow = (int(v) for v in _axes(i[1]))
        b, h, w, c = x.shape
        ys = self._resize_coords(n, h, oh)
        xs = self._resize_coords(n, w, ow)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[None, :, None, None].astype(x.dtype)
        wx = (xs - x0)[None, None, :, None].astype(x.dtype)
        top = x[:, y0][:, :, x0] * (1 - wx) + x[:, y0][:, :, x1] * wx
        bot = x[:, y1][:, :, x0] * (1 - wx) + x[:, y1][:, :, x1] * wx
        return top * (1 - wy) + bot * wy

    def _resize_nearest(self, i, n):
        x = i[0]
        oh, ow = (int(v) for v in _axes(i[1]))
        b, h, w, c = x.shape
        ys = self._resize_coords(n, h, oh)
        xs = self._resize_coords(n, w, ow)
        # TF rounds half AWAY from zero (coords are >= 0, so floor(x+0.5));
        # jnp.round's half-to-even would shift every .5 coordinate
        round_fn = ((lambda v: jnp.floor(v + 0.5))
                    if (n.attr["align_corners"].b
                        or n.attr["half_pixel_centers"].b)
                    else jnp.floor)
        yi = jnp.clip(round_fn(ys).astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(round_fn(xs).astype(jnp.int32), 0, w - 1)
        return x[:, yi][:, :, xi]

    def _fused_bn(self, i, n):
        x, gamma, beta, mean, var = i[:5]
        eps = n.attr["epsilon"].f or 1e-3
        return (x - mean) * lax.rsqrt(var + eps) * gamma + beta

    def _conv2d(self, i, n):
        strides = tuple(n.attr["strides"].list.i)[1:3]
        pad = n.attr["padding"].s.decode()
        return lax.conv_general_dilated(
            i[0], i[1], strides, pad, dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def _maxpool(self, i, n):
        k = tuple(n.attr["ksize"].list.i)
        s = tuple(n.attr["strides"].list.i)
        pad = n.attr["padding"].s.decode()
        return lax.reduce_window(i[0], -jnp.inf, lax.max, k, s, pad)

    def _avgpool(self, i, n):
        k = tuple(n.attr["ksize"].list.i)
        s = tuple(n.attr["strides"].list.i)
        pad = n.attr["padding"].s.decode()
        total = lax.reduce_window(i[0], 0.0, lax.add, k, s, pad)
        if pad == "SAME":
            # TF excludes padding from the denominator at the borders
            ones = jnp.ones_like(i[0])
            count = lax.reduce_window(ones, 0.0, lax.add, k, s, pad)
            return total / count
        return total / (k[1] * k[2])

    # --------------------------------------------------- r4 handler methods
    def _topk(self, i, n):
        k = int(np.asarray(i[1]))
        vals, idx = lax.top_k(i[0], k)
        if not n.attr["sorted"].b:
            pass  # unsorted=False only loosens the contract; sorted is fine
        return [vals, idx.astype(jnp.int32)]

    def _segment(self, i, mode, unsorted=False):
        data = i[0]
        ids = jnp.asarray(i[1]).astype(jnp.int32)
        if unsorted:
            num = int(np.asarray(i[2]))
        else:
            # sorted segment ops: num_segments = last id + 1, which must be
            # static for XLA — requires a const ids tensor (typical in
            # frozen graphs); a traced ids tensor raises here, loudly
            num = int(np.asarray(ids)[-1]) + 1
        if mode == "mean":
            s = jax.ops.segment_sum(data, ids, num)
            c = jax.ops.segment_sum(jnp.ones_like(data), ids, num)
            return s / jnp.maximum(c, 1)
        return getattr(jax.ops, f"segment_{mode}")(data, ids, num)

    def _dynamic_partition(self, i, n):
        # Frozen graphs virtually always carry CONCRETE partition indices;
        # true ragged parts then compose correctly with DynamicStitch.
        # A traced partition vector is inherently dynamic-shape — loud
        # error, not silently-masked zero rows (which would corrupt a
        # downstream stitch: every masked slot would write index 0).
        num = n.attr["num_partitions"].i
        if isinstance(i[1], jax.core.Tracer):
            raise NotImplementedError(
                f"DynamicPartition '{n.name}': data-dependent partition "
                "indices produce dynamic shapes XLA cannot compile; only "
                "constant partitions import")
        parts = np.asarray(i[1]).astype(np.int32)
        return [i[0][np.nonzero(parts == k)[0]] for k in range(num)]

    def _dynamic_stitch(self, i, n):
        half = len(i) // 2
        indices, data = i[:half], i[half:]
        size = int(max(int(np.asarray(ix).max()) for ix in indices)) + 1
        suffix = data[0].shape[np.ndim(indices[0]):]
        out = jnp.zeros((size,) + suffix, data[0].dtype)
        for ix, d in zip(indices, data):
            # each pair splits at ITS index rank (mixed ranks are the
            # canonical DynamicStitch usage)
            out = out.at[jnp.asarray(ix).astype(jnp.int32).ravel()].set(
                d.reshape((-1,) + d.shape[np.ndim(ix):]))
        return out

    def _space_to_batch_nd(self, i, n):
        from . import sd_ops
        return sd_ops.BASE["space_to_batch_nd"](
            i[0], _axes(i[1]), [tuple(r) for r in np.asarray(i[2])])

    def _batch_to_space_nd(self, i, n):
        from . import sd_ops
        return sd_ops.BASE["batch_to_space_nd"](
            i[0], _axes(i[1]), [tuple(r) for r in np.asarray(i[2])])

    def _dilation2d(self, i, n):
        from . import sd_ops
        strides = tuple(n.attr["strides"].list.i)[1:3]
        rates = tuple(n.attr["rates"].list.i)[1:3]
        return sd_ops.CNN["dilation2d"](i[0], i[1], strides, rates,
                                        n.attr["padding"].s.decode())

    def _conv3d(self, i, n):
        strides = tuple(n.attr["strides"].list.i)[1:4]
        pad = n.attr["padding"].s.decode()
        return lax.conv_general_dilated(
            i[0], i[1], strides, pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))

    def _maxpool3d(self, i, n):
        k = tuple(n.attr["ksize"].list.i)
        s = tuple(n.attr["strides"].list.i)
        return lax.reduce_window(i[0], -jnp.inf, lax.max, k, s,
                                 n.attr["padding"].s.decode())

    def _avgpool3d(self, i, n):
        k = tuple(n.attr["ksize"].list.i)
        s = tuple(n.attr["strides"].list.i)
        pad = n.attr["padding"].s.decode()
        total = lax.reduce_window(i[0], 0.0, lax.add, k, s, pad)
        if pad == "SAME":
            count = lax.reduce_window(jnp.ones_like(i[0]), 0.0, lax.add,
                                      k, s, pad)
            return total / count
        return total / np.prod(k[1:4])

    def _fake_quant_args(self, i, n):
        from . import sd_ops
        return sd_ops.NN_EXT["fake_quant_with_min_max_args"](
            i[0], min=_attr_f(n, "min", -6.0), max=_attr_f(n, "max", 6.0),
            num_bits=(n.attr["num_bits"].i or 8),
            narrow_range=n.attr["narrow_range"].b)

    def _check_numerics(self, i, n):
        from . import sd_ops
        return sd_ops.BASE["check_numerics"](
            i[0], n.attr["message"].s.decode() or "CheckNumerics failed")

    def _nms_v3(self, i, n):
        from . import sd_ops
        idx, _ = sd_ops.IMAGE["non_max_suppression"](
            i[0], i[1], int(np.asarray(i[2])),
            iou_threshold=float(np.asarray(i[3])),
            score_threshold=float(np.asarray(i[4])))
        return idx

    def _nms_v4(self, i, n):
        from . import sd_ops
        idx, count = sd_ops.IMAGE["non_max_suppression"](
            i[0], i[1], int(np.asarray(i[2])),
            iou_threshold=float(np.asarray(i[3])),
            score_threshold=float(np.asarray(i[4])))
        return [idx, count]

    def _crop_and_resize(self, i, n):
        from . import sd_ops
        return sd_ops.IMAGE["crop_and_resize"](
            i[0], i[1], jnp.asarray(i[2]).astype(jnp.int32), _axes(i[3]),
            extrapolation_value=_attr_f(n, "extrapolation_value", 0.0))

    @staticmethod
    def _cubic_weights(frac, A=-0.75):
        """Keys cubic kernel weights for taps [-1, 0, 1, 2] at fractional
        offset ``frac`` (TF uses A=-0.75, unlike jax.image's -0.5)."""
        d = jnp.stack([frac + 1.0, frac, 1.0 - frac, 2.0 - frac], axis=-1)
        ad = jnp.abs(d)
        near = ((A + 2.0) * ad - (A + 3.0)) * ad * ad + 1.0
        far = ((A * ad - 5.0 * A) * ad + 8.0 * A) * ad - 4.0 * A
        return jnp.where(ad <= 1.0, near, jnp.where(ad < 2.0, far, 0.0))

    def _axis_cubic(self, n, in_dim, out_dim, dtype):
        """(indices (out,4), weights (out,4)) for one axis. TF semantics:
        legacy/align_corners use A=-0.75 with border-CLAMPED taps;
        half_pixel_centers uses the Keys kernel (A=-0.5) with out-of-range
        taps ZEROED and the remaining weights renormalized."""
        half = bool(n.attr["half_pixel_centers"].b)
        cs = self._resize_coords(n, in_dim, out_dim,
                                 clamp_half_pixel=False)
        c0 = jnp.floor(cs)
        taps = c0.astype(jnp.int32)[:, None] + jnp.arange(-1, 3)[None, :]
        wts = self._cubic_weights((cs - c0).astype(dtype),
                                  A=-0.5 if half else -0.75)
        if half:
            valid = (taps >= 0) & (taps <= in_dim - 1)
            wts = wts * valid.astype(dtype)
            wts = wts / jnp.sum(wts, axis=-1, keepdims=True)
        return jnp.clip(taps, 0, in_dim - 1), wts

    def _resize_bicubic(self, i, n):
        """Separable bicubic honoring all three TF coordinate conventions
        (align_corners / half_pixel_centers / legacy) — see _axis_cubic."""
        x = i[0]
        oh, ow = (int(v) for v in _axes(i[1]))
        b, h, w, c = x.shape
        yi, wy = self._axis_cubic(n, h, oh, x.dtype)   # (oh, 4) each
        xi, wx = self._axis_cubic(n, w, ow, x.dtype)   # (ow, 4) each
        rows = x[:, yi]                       # (b, oh, 4, w, c)
        rows = jnp.einsum("bykwc,yk->bywc", rows, wy)
        cols = rows[:, :, xi]                 # (b, oh, ow, 4, c)
        return jnp.einsum("bywkc,wk->bywc", cols, wx)

    def _draw_boxes(self, i, n):
        from . import sd_ops
        return sd_ops.IMAGE["draw_bounding_boxes"](
            i[0], i[1], None if len(i) < 3 or np.asarray(i[2]).size == 0
            else i[2])

    def _softmax_xent(self, i, n):
        logits, labels = i[0], i[1]
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.sum(labels * logp, axis=-1)
        return [loss, jax.nn.softmax(logits, axis=-1) - labels]

    def _sparse_softmax_xent(self, i, n):
        logits = i[0]
        labels = jnp.asarray(i[1]).astype(jnp.int32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        grad = jax.nn.softmax(logits, axis=-1) \
            - jax.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype)
        return [loss, grad]

    def _multinomial(self, i, n):
        from . import sd_ops
        return sd_ops.RANDOM["multinomial"](
            self._node_key(n), i[0], int(np.asarray(i[1]))).astype(jnp.int64)

    def _node_key(self, n):
        """Deterministic PRNG key per random node: frozen-graph inference
        has no seed input, so derive one from the node name (stable across
        runs — unlike TF's stateful kernels, deliberately: reproducibility
        is the TPU-native contract). crc32, not hash(): str hash is
        process-salted (same reasoning as samediff's name keying)."""
        import zlib
        return jax.random.PRNGKey(zlib.crc32(n.name.encode()) & 0x7FFFFFFF)

    def _v1_loop_err(self, i, n):
        raise NotImplementedError(
            f"TF v1 control-flow frame op '{n.op}' (node '{n.name}'): v1 "
            "while-loops need frame analysis and are not supported; "
            "re-export the model with TF2 functional control flow "
            "(tf.function produces While/StatelessWhile, which import)")

    # ---------------------------------------------- function-library support
    def _register_functions(self, graph_def):
        for fdef in graph_def.library.function:
            self._functions[fdef.signature.name] = fdef

    @staticmethod
    def _op_output_args(op_name):
        """Output arg names for an op type from TF's registry (proto-side
        only, nothing executes)."""
        try:
            from tensorflow.python.framework import op_def_registry
            od = op_def_registry.get(op_name)
            return [a.name for a in od.output_arg] if od else None
        except Exception:  # noqa: BLE001 — registry is best-effort
            return None

    def _run_function(self, fname, args):
        """Execute a FunctionDef body eagerly over jax values (used inside
        lax.cond / lax.while_loop branches). Reuses the same handler table;
        function-internal tensors live in a local env."""
        fdef = self._functions[fname]
        sig = fdef.signature
        env = {}
        for arg_def, val in zip(sig.input_arg, args):
            env[arg_def.name] = val

        def resolve(ref):
            base, _, rest = ref.partition(":")
            if base.startswith("^"):
                return None
            if base in env and not rest:
                return env[base]
            v = env[base]
            if isinstance(v, dict):       # node with named output args
                arg, _, idx = rest.partition(":")
                slot = v[arg]
                return slot[int(idx)] if isinstance(slot, list) else slot
            return v

        for node in fdef.node_def:
            if node.op == "Const":
                # NUMPY, not jnp.asarray: _run_function executes inside an
                # active jit trace (lax.cond/while_loop branch), where
                # jnp.asarray stages a device_put and returns a TRACER —
                # static-axis handlers (gather, argmax...) then break.
                # numpy values stay concrete and promote on use.
                env[node.name] = _tensor_to_np(node.attr["value"].tensor)
                continue
            if node.op == "NoOp":
                continue            # control-dependency anchors, like main
            handler = self.handlers.get(node.op)
            if handler is None:
                raise NotImplementedError(
                    f"TF op '{node.op}' inside function '{fname}' "
                    f"(node '{node.name}') not mapped")
            ins = [resolve(r) for r in node.input if not r.startswith("^")]
            out = handler(ins, node)
            if isinstance(out, list):
                names = self._op_output_args(node.op)
                if names and len(names) == len(out):
                    env[node.name] = dict(zip(names, out))
                elif names and len(names) == 1:
                    env[node.name] = {names[0]: out}  # one variadic out arg
                else:
                    raise NotImplementedError(
                        f"cannot name the {len(out)} outputs of "
                        f"'{node.op}' in function '{fname}' (op registry "
                        "metadata unavailable)")
            else:
                env[node.name] = out      # plain value; resolve ignores :a:0

        return [resolve(fdef.ret[o.name]) for o in sig.output_arg]

    def _if(self, i, n):
        """Concrete operands (tf.function-lifted constant captures — e.g. a
        gather axis) are CLOSED OVER rather than passed through lax.cond:
        handlers need them static inside the branch trace."""
        pred, args = i[0], list(i[1:])
        then_f = n.attr["then_branch"].func.name
        else_f = n.attr["else_branch"].func.name
        dyn = [k for k, v in enumerate(args)
               if isinstance(v, jax.core.Tracer)]

        def mk(branch):
            def f(*dyn_vals):
                full = list(args)
                for p, k in enumerate(dyn):
                    full[k] = dyn_vals[p]
                return tuple(self._run_function(branch, full))
            return f

        out = lax.cond(jnp.squeeze(jnp.asarray(pred)).astype(bool),
                       mk(then_f), mk(else_f), *[args[k] for k in dyn])
        return list(out)   # always a list: import_graph's view nodes index

    def _while(self, i, n):
        """Loop-INVARIANT args whose incoming value is concrete (lifted
        constant captures) stay out of the carry — inside the body they must
        be static (axes, shapes), and a carried tracer would break that.
        Invariance is read off the body FunctionDef: output k resolves back
        to input k through Identity chains."""
        cond_f = n.attr["cond"].func.name
        body_f = n.attr["body"].func.name
        fb = self._functions[body_f]
        args = list(i)
        id_map = {nd.name: nd.input[0] for nd in fb.node_def
                  if nd.op == "Identity" and nd.input}

        def base_of(ref):
            cur, seen = ref.split(":")[0], set()
            while cur in id_map and cur not in seen:
                seen.add(cur)
                cur = id_map[cur].split(":")[0]
            return cur

        static = []
        for k, (ia, oa) in enumerate(zip(fb.signature.input_arg,
                                         fb.signature.output_arg)):
            invariant = base_of(fb.ret[oa.name]) == ia.name
            static.append(invariant
                          and not isinstance(args[k], jax.core.Tracer))
        carry_idx = [k for k, s in enumerate(static) if not s]

        def full_args(carry):
            full = list(args)
            for p, k in enumerate(carry_idx):
                full[k] = carry[p]
            return full

        def cond(carry):
            return jnp.squeeze(jnp.asarray(self._run_function(
                cond_f, full_args(carry))[0])).astype(bool)

        def body(carry):
            outs = self._run_function(body_f, full_args(carry))
            return tuple(outs[k] for k in carry_idx)

        out_carry = lax.while_loop(cond, body,
                                   tuple(args[k] for k in carry_idx))
        out = list(args)           # invariant slots pass their input through
        for p, k in enumerate(carry_idx):
            out[k] = out_carry[p]
        return out                 # always a list (see _if)

    def _call(self, i, n):
        return self._run_function(n.attr["f"].func.name, list(i))

    # ------------------------------------------------------------------ main
    def import_graph(self, graph_def, sd: SameDiff | None = None) -> SameDiff:
        """Map a tf.compat.v1 GraphDef onto a SameDiff graph. Handles the
        function library (V2 control flow), generalized multi-output ops,
        and V1 Switch/Merge conditionals (both branches compute, Merge
        selects on the predicate — the XLA-native formulation of a dataflow
        cond; V1 loop FRAMES raise, see _v1_loop_err)."""
        sd = sd or SameDiff.create()
        self._register_functions(graph_def)
        produced: Dict[str, Any] = {}   # tf tensor name → SDVariable | list
        # V1 conditionals: tensor names descending from a Switch output →
        # (pred tensor name, branch_is_true); Merge uses it to select.
        branch_of: Dict[str, Any] = {}
        # constant folding (upstream TFGraphMapper does this too): nodes
        # whose transitive inputs are all Const evaluate EAGERLY here, so
        # shape/axis/index plumbing (Range→DynamicPartition chains, sizes)
        # reaches downstream handlers as concrete values — at eval time
        # everything inside the jit is a tracer, which static-arg handlers
        # cannot accept.
        concrete: Dict[str, Any] = {}
        _MISS = object()

        def conc_ref(name):
            base, _, idx = name.partition(":")
            v = concrete.get(base.lstrip("^"), _MISS)
            if v is _MISS:
                return _MISS
            if isinstance(v, list):
                return v[int(idx) if idx else 0]
            return v

        NOFOLD = {"RandomUniform", "RandomStandardNormal", "TruncatedNormal",
                  "RandomUniformInt", "Multinomial", "Switch", "Merge",
                  "If", "StatelessIf", "While", "StatelessWhile",
                  "PartitionedCall", "StatefulPartitionedCall"}

        def tensor_ref(name) -> SDVariable:
            base, _, idx = name.partition(":")
            base = base.lstrip("^")
            v = produced[base]
            if isinstance(v, list):
                return v[int(idx) if idx else 0]
            return v

        for node in graph_def.node:
            op = node.op
            if op == "Const":
                arr = _tensor_to_np(node.attr["value"].tensor)
                concrete[node.name] = arr
                produced[node.name] = sd.constant(node.name, jnp.asarray(arr))
                continue
            if op in ("Placeholder", "PlaceholderWithDefault"):
                shape = None
                if node.attr["shape"].shape.dim:
                    shape = tuple(d.size if d.size > 0 else None
                                  for d in node.attr["shape"].shape.dim)
                produced[node.name] = sd.placeholder(node.name, shape)
                continue
            if op == "NoOp":
                continue
            if op in ("Enter", "Exit", "NextIteration", "LoopCond"):
                self._v1_loop_err(None, node)   # fail at import, not eval
            data_inputs = [i for i in node.input if not i.startswith("^")]
            if op == "Switch":
                # outputs: 0 = false branch, 1 = true branch; both are
                # identity views of the data — selection happens at Merge
                data = tensor_ref(data_inputs[0])
                pred_name = data_inputs[1]
                outs = [sd._op(f"{node.name}_b{j}", lambda t: t, [data])
                        for j in range(2)]
                branch_of[f"{node.name}:0"] = (pred_name, False)
                branch_of[f"{node.name}:1"] = (pred_name, True)
                branch_of[node.name] = (pred_name, False)  # bare = output 0
                produced[node.name] = outs
                continue
            if op == "Merge":
                # pick the true-branch input via the predicate; both branch
                # values exist (computed unconditionally — sound for the
                # side-effect-free graphs XLA compiles anyway)
                infos = [branch_of.get(i) for i in data_inputs]
                if not any(infos):
                    raise NotImplementedError(
                        f"Merge '{node.name}' without Switch ancestry "
                        "(v1 loop?) is not supported")
                pred_name = next(inf[0] for inf in infos if inf)
                pred = tensor_ref(pred_name)
                vals = [tensor_ref(i) for i in data_inputs]
                true_pos = next(
                    (k for k, inf in enumerate(infos) if inf and inf[1]),
                    None)
                if true_pos is None or len(vals) != 2:
                    raise NotImplementedError(
                        f"Merge '{node.name}': cannot identify the "
                        "true-branch input from Switch lineage "
                        f"({len(vals)} inputs, lineage {infos}) — silently "
                        "guessing would invert the conditional")
                t_val = vals[true_pos]
                f_val = vals[1 - true_pos]
                v = sd._op(node.name + "_op",
                           lambda f, t, p: jnp.where(
                               jnp.asarray(p).astype(bool), t, f),
                           [f_val, t_val, pred])
                v.rename(node.name)
                # value_index = POSITION of the chosen input (TF contract),
                # not the predicate value
                vi = sd._op(node.name + "_index",
                            (lambda tp: lambda p: jnp.where(
                                jnp.asarray(p).astype(bool), tp,
                                1 - tp).astype(jnp.int32))(true_pos),
                            [pred])
                produced[node.name] = [v, vi]
                # nested conds: the whole Merge sits inside the OUTER branch
                # iff its predicate does — inherit the pred's lineage
                outer = branch_of.get(pred_name)
                if outer is not None:
                    branch_of[node.name] = outer
                    branch_of[node.name + ":0"] = outer
                continue
            handler = self.handlers.get(op)
            if handler is None:
                raise NotImplementedError(
                    f"TF op '{op}' (node '{node.name}') not mapped; "
                    f"supported: {sorted(k for k, v in self.handlers.items() if v)}")

            conc_ins = [conc_ref(i) for i in data_inputs]
            if op not in NOFOLD and handler is not None \
                    and all(v is not _MISS for v in conc_ins):
                out = handler(list(conc_ins), node)
                if isinstance(out, list):
                    concrete[node.name] = [np.asarray(v) for v in out]
                    produced[node.name] = [
                        sd.constant(f"{node.name}_{j}", jnp.asarray(v))
                        for j, v in enumerate(out)]
                else:
                    concrete[node.name] = np.asarray(out)
                    produced[node.name] = sd.constant(node.name,
                                                      jnp.asarray(out))
                continue
            ins = [tensor_ref(i) for i in data_inputs]

            def make_fn(h=handler, nd=node):
                def fn(*vals):
                    return h(list(vals), nd)
                return fn

            # propagate V1 branch lineage through ordinary ops
            lineage = next((branch_of[i] for i in data_inputs
                            if i in branch_of), None)
            if lineage is not None:
                branch_of[node.name] = lineage
                branch_of[node.name + ":0"] = lineage

            if op in self.multi_output:
                count = int(self.multi_output[op](node))
                tup = sd._op(node.name + "_tuple", make_fn(), ins)
                outs = []
                for j in range(count):
                    outs.append(sd._op(f"{node.name}_{j}",
                                       (lambda jj: lambda t: t[jj])(j), [tup]))
                    if lineage is not None:
                        branch_of[f"{node.name}:{j}"] = lineage
                produced[node.name] = outs
            else:
                v = sd._op(node.name + "_op", make_fn(), ins)
                v.rename(node.name)
                produced[node.name] = v
        return sd


def import_frozen_graph(path_or_graphdef, outputs: List[str] | None = None):
    """Load a frozen .pb (or an in-memory GraphDef) → (SameDiff, outputs)."""
    if isinstance(path_or_graphdef, (str, bytes)):
        from tensorflow.core.framework import graph_pb2
        gd = graph_pb2.GraphDef()
        with open(path_or_graphdef, "rb") as f:
            gd.ParseFromString(f.read())
    else:
        gd = path_or_graphdef
    sd = TFImporter().import_graph(gd)
    outs = [sd.get_variable(o) for o in outputs] if outputs else None
    return sd, outs
