"""TF frozen-GraphDef import → SameDiff graph (the reference's BERT path).

Reference parity: ``org.nd4j.imports.graphmapper.tf.TFGraphMapper`` /
``samediff-import-tensorflow`` — DL4J runs BERT by importing a frozen TF
graph into SameDiff. Here the GraphDef is parsed (tensorflow is baked into
the image; only the proto reader is used — no TF execution) and mapped onto
our SameDiff, which then jits the WHOLE graph through XLA instead of the
reference's per-op interpreter.

Supported op subset covers transformer/BERT-style graphs: matmul/bias/
elementwise chains, reshapes/transposes, softmax, layer-norm primitive
chains, gather (embeddings), batched matmul, one_hot, reductions, and the
shape plumbing ops. Unknown ops raise with the op name so coverage gaps are
loud, not silent.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .samediff import SameDiff, SDVariable


def _tensor_to_np(tensor_proto):
    from tensorflow.python.framework import tensor_util
    return tensor_util.MakeNdarray(tensor_proto)


def _axes(v):
    return tuple(int(a) for a in np.asarray(v).ravel())


def _attr_f(node, name, default):
    """Float attr with an explicit-presence check: an attr explicitly set
    to 0.0 must NOT fall back to the default (`.f or default` would)."""
    return node.attr[name].f if name in node.attr else default


class TFImporter:
    def __init__(self):
        self.handlers = {
            "Const": None, "Placeholder": None, "Identity": self._identity,
            "IdentityN": self._identity, "NoOp": None,
            "MatMul": self._matmul, "BatchMatMul": self._batch_matmul,
            "BatchMatMulV2": self._batch_matmul,
            "BiasAdd": lambda i, n: i[0] + i[1],
            "Add": lambda i, n: i[0] + i[1], "AddV2": lambda i, n: i[0] + i[1],
            "AddN": lambda i, n: sum(i),
            "Sub": lambda i, n: i[0] - i[1], "Mul": lambda i, n: i[0] * i[1],
            "RealDiv": lambda i, n: i[0] / i[1], "Div": lambda i, n: i[0] / i[1],
            "Maximum": lambda i, n: jnp.maximum(i[0], i[1]),
            "Minimum": lambda i, n: jnp.minimum(i[0], i[1]),
            "Pow": lambda i, n: jnp.power(i[0], i[1]),
            "SquaredDifference": lambda i, n: jnp.square(i[0] - i[1]),
            "Square": lambda i, n: jnp.square(i[0]),
            "Sqrt": lambda i, n: jnp.sqrt(i[0]),
            "Rsqrt": lambda i, n: lax.rsqrt(i[0]),
            "Exp": lambda i, n: jnp.exp(i[0]), "Log": lambda i, n: jnp.log(i[0]),
            "Neg": lambda i, n: -i[0], "Abs": lambda i, n: jnp.abs(i[0]),
            "Tanh": lambda i, n: jnp.tanh(i[0]),
            "Sigmoid": lambda i, n: jax.nn.sigmoid(i[0]),
            "Relu": lambda i, n: jax.nn.relu(i[0]),
            "Relu6": lambda i, n: jax.nn.relu6(i[0]),
            "Elu": lambda i, n: jax.nn.elu(i[0]),
            "Selu": lambda i, n: jax.nn.selu(i[0]),
            "Softplus": lambda i, n: jax.nn.softplus(i[0]),
            "Erf": lambda i, n: jax.scipy.special.erf(i[0]),
            "Softmax": lambda i, n: jax.nn.softmax(i[0], axis=-1),
            "LogSoftmax": lambda i, n: jax.nn.log_softmax(i[0], axis=-1),
            "Reshape": lambda i, n: jnp.reshape(i[0], _axes(i[1])),
            "Transpose": lambda i, n: jnp.transpose(i[0], _axes(i[1])),
            "ExpandDims": lambda i, n: jnp.expand_dims(i[0], int(np.asarray(i[1]))),
            "Squeeze": self._squeeze,
            "ConcatV2": lambda i, n: jnp.concatenate(i[:-1], axis=int(np.asarray(i[-1]))),
            "Pack": self._pack, "Unpack": self._unpack,
            "Split": self._split, "SplitV": self._splitv,
            "StridedSlice": self._strided_slice,
            "Slice": self._slice,
            "GatherV2": self._gather, "Gather": self._gather,
            "OneHot": self._one_hot,
            "Cast": self._cast,
            "Mean": self._mean, "Sum": self._sum, "Max": self._rmax,
            "Min": self._rmin, "Prod": self._prod,
            "ArgMax": lambda i, n: jnp.argmax(i[0], axis=int(np.asarray(i[1]))),
            "Shape": lambda i, n: jnp.asarray(i[0].shape, jnp.int32),
            "Rank": lambda i, n: jnp.asarray(np.ndim(i[0]), jnp.int32),
            "Fill": lambda i, n: jnp.full(_axes(i[0]), i[1]),
            "ZerosLike": lambda i, n: jnp.zeros_like(i[0]),
            "OnesLike": lambda i, n: jnp.ones_like(i[0]),
            "Tile": lambda i, n: jnp.tile(i[0], _axes(i[1])),
            "StopGradient": lambda i, n: lax.stop_gradient(i[0]),
            "Rsub": lambda i, n: i[1] - i[0],
            "Einsum": lambda i, n: jnp.einsum(
                n.attr["equation"].s.decode(), *i),
            "FusedBatchNorm": self._fused_bn, "FusedBatchNormV3": self._fused_bn,
            "Conv2D": self._conv2d, "MaxPool": self._maxpool,
            "AvgPool": self._avgpool,
            "Greater": lambda i, n: jnp.greater(i[0], i[1]),
            "GreaterEqual": lambda i, n: jnp.greater_equal(i[0], i[1]),
            "Less": lambda i, n: jnp.less(i[0], i[1]),
            "Equal": lambda i, n: jnp.equal(i[0], i[1]),
            "NotEqual": lambda i, n: jnp.not_equal(i[0], i[1]),
            "Select": lambda i, n: jnp.where(i[0], i[1], i[2]),
            "SelectV2": lambda i, n: jnp.where(i[0], i[1], i[2]),
            "Tanh_": lambda i, n: jnp.tanh(i[0]),
            # --- r3 widening: the broad frozen-graph long tail ------------
            "Floor": lambda i, n: jnp.floor(i[0]),
            "Ceil": lambda i, n: jnp.ceil(i[0]),
            "Round": lambda i, n: jnp.round(i[0]),
            "Rint": lambda i, n: jnp.rint(i[0]),
            "Sign": lambda i, n: jnp.sign(i[0]),
            "FloorDiv": lambda i, n: jnp.floor_divide(i[0], i[1]),
            "FloorMod": lambda i, n: jnp.mod(i[0], i[1]),
            "Mod": lambda i, n: jnp.fmod(i[0], i[1]),   # TF Mod truncates
            "Log1p": lambda i, n: jnp.log1p(i[0]),
            "Expm1": lambda i, n: jnp.expm1(i[0]),
            "Sin": lambda i, n: jnp.sin(i[0]),
            "Cos": lambda i, n: jnp.cos(i[0]),
            "Tan": lambda i, n: jnp.tan(i[0]),
            "Asin": lambda i, n: jnp.arcsin(i[0]),
            "Acos": lambda i, n: jnp.arccos(i[0]),
            "Atan": lambda i, n: jnp.arctan(i[0]),
            "Sinh": lambda i, n: jnp.sinh(i[0]),
            "Cosh": lambda i, n: jnp.cosh(i[0]),
            "Asinh": lambda i, n: jnp.arcsinh(i[0]),
            "Acosh": lambda i, n: jnp.arccosh(i[0]),
            "Atanh": lambda i, n: jnp.arctanh(i[0]),
            "Atan2": lambda i, n: jnp.arctan2(i[0], i[1]),
            "Reciprocal": lambda i, n: jnp.reciprocal(i[0]),
            "Inv": lambda i, n: jnp.reciprocal(i[0]),
            "Erfc": lambda i, n: jax.scipy.special.erfc(i[0]),
            "LeakyRelu": lambda i, n: jax.nn.leaky_relu(
                i[0], _attr_f(n, "alpha", 0.2)),
            "Softsign": lambda i, n: jax.nn.soft_sign(i[0]),
            "IsNan": lambda i, n: jnp.isnan(i[0]),
            "IsInf": lambda i, n: jnp.isinf(i[0]),
            "IsFinite": lambda i, n: jnp.isfinite(i[0]),
            "LogicalAnd": lambda i, n: jnp.logical_and(i[0], i[1]),
            "LogicalOr": lambda i, n: jnp.logical_or(i[0], i[1]),
            "LogicalNot": lambda i, n: jnp.logical_not(i[0]),
            "LessEqual": lambda i, n: jnp.less_equal(i[0], i[1]),
            "All": self._rall, "Any": self._rany,
            "ArgMin": lambda i, n: jnp.argmin(i[0], axis=int(np.asarray(i[1]))),
            "Cumsum": self._cumsum, "Cumprod": self._cumprod,
            "Pad": lambda i, n: jnp.pad(i[0], np.asarray(i[1])),
            "PadV2": lambda i, n: jnp.pad(
                i[0], np.asarray(i[1]),
                constant_values=float(np.asarray(i[2]))),
            "MirrorPad": lambda i, n: jnp.pad(
                i[0], np.asarray(i[1]),
                mode=("reflect" if n.attr["mode"].s == b"REFLECT"
                      else "symmetric")),
            "Concat": lambda i, n: jnp.concatenate(
                i[1:], axis=int(np.asarray(i[0]))),   # legacy: axis FIRST
            "ReverseV2": lambda i, n: jnp.flip(i[0], _axes(i[1])),
            "Range": lambda i, n: jnp.arange(
                np.asarray(i[0]).item(), np.asarray(i[1]).item(),
                np.asarray(i[2]).item()),
            "LinSpace": lambda i, n: jnp.linspace(
                np.asarray(i[0]).item(), np.asarray(i[1]).item(),
                int(np.asarray(i[2]))),
            "Size": lambda i, n: jnp.asarray(np.prod(i[0].shape), jnp.int32),
            "BroadcastTo": lambda i, n: jnp.broadcast_to(i[0], _axes(i[1])),
            "GatherNd": self._gather_nd,
            "ScatterNd": lambda i, n: jnp.zeros(
                _axes(i[2]), i[1].dtype).at[
                tuple(jnp.asarray(i[0]).astype(jnp.int32)[..., k]
                      for k in range(i[0].shape[-1]))].add(i[1]),
            "TensorScatterUpdate": lambda i, n: i[0].at[
                tuple(jnp.asarray(i[1]).astype(jnp.int32)[..., k]
                      for k in range(i[1].shape[-1]))].set(i[2]),
            "TensorScatterAdd": lambda i, n: i[0].at[
                tuple(jnp.asarray(i[1]).astype(jnp.int32)[..., k]
                      for k in range(i[1].shape[-1]))].add(i[2]),
            "InvertPermutation": lambda i, n: jnp.argsort(i[0]),
            "MatrixBandPart": self._matrix_band_part,
            "MatrixDiag": lambda i, n: i[0][..., None]
                * jnp.eye(i[0].shape[-1], dtype=i[0].dtype),
            "MatrixDiagPart": lambda i, n: jnp.diagonal(
                i[0], axis1=-2, axis2=-1),
            "L2Loss": lambda i, n: 0.5 * jnp.sum(jnp.square(i[0])),
            "LRN": self._lrn,
            "DepthwiseConv2dNative": self._depthwise_conv2d,
            "Conv2DBackpropInput": self._conv2d_transpose,
            "SpaceToDepth": lambda i, n: self._space_depth(i[0],
                                                           n, to_depth=True),
            "DepthToSpace": lambda i, n: self._space_depth(i[0],
                                                           n, to_depth=False),
            "ResizeBilinear": self._resize_bilinear,
            "ResizeNearestNeighbor": self._resize_nearest,
            # spectral family (rides the new sd_ops FFT work)
            "FFT": lambda i, n: jnp.fft.fft(i[0]),
            "IFFT": lambda i, n: jnp.fft.ifft(i[0]),
            "FFT2D": lambda i, n: jnp.fft.fft2(i[0]),
            "IFFT2D": lambda i, n: jnp.fft.ifft2(i[0]),
            "RFFT": lambda i, n: jnp.fft.rfft(
                i[0], n=int(_axes(i[1])[0]) if len(i) > 1 else None),
            "IRFFT": lambda i, n: jnp.fft.irfft(
                i[0], n=int(_axes(i[1])[0]) if len(i) > 1 else None),
            "ComplexAbs": lambda i, n: jnp.abs(i[0]),
            "Real": lambda i, n: jnp.real(i[0]),
            "Imag": lambda i, n: jnp.imag(i[0]),
            "Conj": lambda i, n: jnp.conj(i[0]),
            "Complex": lambda i, n: lax.complex(i[0], i[1]),
            "Angle": lambda i, n: jnp.angle(i[0]),
        }

    # --- handlers needing node attrs ---------------------------------------
    def _identity(self, i, n):
        return i[0]

    def _matmul(self, i, n):
        a, b = i[0], i[1]
        if n.attr["transpose_a"].b:
            a = a.T
        if n.attr["transpose_b"].b:
            b = b.T
        return a @ b

    def _batch_matmul(self, i, n):
        a, b = i[0], i[1]
        if n.attr["adj_x"].b:
            a = jnp.swapaxes(a, -1, -2)
        if n.attr["adj_y"].b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    def _squeeze(self, i, n):
        dims = tuple(n.attr["squeeze_dims"].list.i)
        return jnp.squeeze(i[0], axis=dims if dims else None)

    def _pack(self, i, n):
        return jnp.stack(i, axis=n.attr["axis"].i)

    def _unpack(self, i, n):
        ax = n.attr["axis"].i
        num = n.attr["num"].i
        return [jnp.squeeze(s, ax) for s in jnp.split(i[0], num, axis=ax)]

    def _split(self, i, n):
        ax = int(np.asarray(i[0]))
        return jnp.split(i[1], n.attr["num_split"].i, axis=ax)

    def _splitv(self, i, n):
        sizes = _axes(i[1])
        ax = int(np.asarray(i[2]))
        idx = np.cumsum(sizes)[:-1].tolist()
        return jnp.split(i[0], idx, axis=ax)

    def _strided_slice(self, i, n):
        x, begin, end, strides = i[0], _axes(i[1]), _axes(i[2]), _axes(i[3])
        bm = n.attr["begin_mask"].i
        em = n.attr["end_mask"].i
        sm = n.attr["shrink_axis_mask"].i
        nm = n.attr["new_axis_mask"].i
        em_ellipsis = n.attr["ellipsis_mask"].i
        idx = []
        for d in range(len(begin)):
            if em_ellipsis & (1 << d):
                idx.append(Ellipsis)
            elif nm & (1 << d):
                idx.append(None)
            elif sm & (1 << d):
                idx.append(begin[d])
            else:
                b = None if (bm & (1 << d)) else begin[d]
                e = None if (em & (1 << d)) else end[d]
                idx.append(slice(b, e, strides[d]))
        return x[tuple(idx)]

    def _slice(self, i, n):
        begin = _axes(i[1])
        size = _axes(i[2])
        # TF convention: size -1 → everything from begin to the end of the dim
        size = tuple(d - b if s == -1 else s
                     for b, s, d in zip(begin, size, i[0].shape))
        return lax.dynamic_slice(i[0], begin, size)

    def _gather(self, i, n):
        ax = int(np.asarray(i[2])) if len(i) > 2 else 0
        return jnp.take(i[0], i[1].astype(jnp.int32), axis=ax)

    def _one_hot(self, i, n):
        depth = int(np.asarray(i[1]))
        on = i[2] if len(i) > 2 else 1.0
        off = i[3] if len(i) > 3 else 0.0
        oh = jax.nn.one_hot(i[0].astype(jnp.int32), depth)
        return oh * on + (1 - oh) * off

    _TF_DTYPES = {1: jnp.float32, 2: jnp.float64, 3: jnp.int32, 9: jnp.int64,
                  10: jnp.bool_, 14: jnp.bfloat16, 19: jnp.float16}

    def _cast(self, i, n):
        return i[0].astype(self._TF_DTYPES.get(n.attr["DstT"].type, jnp.float32))

    def _mean(self, i, n):
        return jnp.mean(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _sum(self, i, n):
        return jnp.sum(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rmax(self, i, n):
        return jnp.max(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rmin(self, i, n):
        return jnp.min(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _prod(self, i, n):
        return jnp.prod(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rall(self, i, n):
        return jnp.all(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _rany(self, i, n):
        return jnp.any(i[0], axis=_axes(i[1]), keepdims=n.attr["keep_dims"].b)

    def _cumsum(self, i, n):
        ax = int(np.asarray(i[1]))
        x = jnp.flip(i[0], ax) if n.attr["reverse"].b else i[0]
        if n.attr["exclusive"].b:
            y = jnp.cumsum(x, axis=ax) - x
        else:
            y = jnp.cumsum(x, axis=ax)
        return jnp.flip(y, ax) if n.attr["reverse"].b else y

    def _cumprod(self, i, n):
        ax = int(np.asarray(i[1]))
        x = jnp.flip(i[0], ax) if n.attr["reverse"].b else i[0]
        y = jnp.cumprod(x, axis=ax)
        if n.attr["exclusive"].b:
            # shift-by-one with a leading 1 (zero-safe, dtype-preserving —
            # dividing out x would be wrong at zeros and float-promote ints)
            lead = list(x.shape)
            lead[ax] = 1
            y = jnp.concatenate(
                [jnp.ones(lead, y.dtype),
                 lax.slice_in_dim(y, 0, x.shape[ax] - 1, axis=ax)], axis=ax)
        return jnp.flip(y, ax) if n.attr["reverse"].b else y

    def _gather_nd(self, i, n):
        idx = jnp.asarray(i[1]).astype(jnp.int32)
        return i[0][tuple(idx[..., k] for k in range(idx.shape[-1]))]

    def _matrix_band_part(self, i, n):
        x = i[0]
        lo, hi = int(np.asarray(i[1])), int(np.asarray(i[2]))
        r = jnp.arange(x.shape[-2])[:, None] - jnp.arange(x.shape[-1])[None, :]
        keep = ((r <= (lo if lo >= 0 else x.shape[-2]))
                & (-r <= (hi if hi >= 0 else x.shape[-1])))
        return x * keep.astype(x.dtype)

    def _lrn(self, i, n):
        r = n.attr["depth_radius"].i if "depth_radius" in n.attr else 5
        bias = _attr_f(n, "bias", 1.0)
        alpha = _attr_f(n, "alpha", 1.0)
        beta = _attr_f(n, "beta", 0.5)
        sq = lax.reduce_window(jnp.square(i[0]), 0.0, lax.add,
                               (1, 1, 1, 2 * r + 1), (1, 1, 1, 1), "SAME")
        return i[0] / jnp.power(bias + alpha * sq, beta)

    def _depthwise_conv2d(self, i, n):
        strides = tuple(n.attr["strides"].list.i)[1:3]
        pad = n.attr["padding"].s.decode()
        w = i[1]  # TF (kh, kw, cin, mult) → lax HWIO (kh, kw, 1, cin*mult)
        kh, kw, cin, mult = w.shape
        w = w.reshape(kh, kw, 1, cin * mult)
        return lax.conv_general_dilated(
            i[0], w, strides, pad, dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=cin)

    def _conv2d_transpose(self, i, n):
        # inputs: output_shape (const), filters (kh,kw,Cout,Cin), dy.
        # True gradient-of-conv: dilate dy by the stride, pad with the
        # TRANSPOSED forward pads (derived from TF's SAME/VALID rule on the
        # requested output size — authoritative, so odd sizes land exact),
        # convolve with the spatially-flipped, io-swapped kernel.
        strides = tuple(n.attr["strides"].list.i)[1:3]
        padding = n.attr["padding"].s.decode()
        dy = i[2]
        oh, ow = (int(v) for v in _axes(i[0])[1:3])
        w = i[1]
        kh, kw = w.shape[:2]
        wf = jnp.transpose(w[::-1, ::-1], (0, 1, 3, 2))

        def grad_pad(out_sz, in_sz, k, s):
            if padding == "SAME":
                fwd_out = -(-out_sz // s)
                total = max(0, (fwd_out - 1) * s + k - out_sz)
                fwd_lo = total // 2
            else:
                fwd_lo = 0
            lo = k - 1 - fwd_lo
            dil = (in_sz - 1) * s + 1
            hi = out_sz + k - 1 - dil - lo   # solves out == requested size
            return lo, hi

        ph = grad_pad(oh, dy.shape[1], kh, strides[0])
        pw_ = grad_pad(ow, dy.shape[2], kw, strides[1])
        return lax.conv_general_dilated(
            dy, wf, (1, 1), (ph, pw_), lhs_dilation=strides,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def _space_depth(self, x, n, to_depth):
        bs = n.attr["block_size"].i
        b, h, w, c = x.shape
        if to_depth:
            return x.reshape(b, h // bs, bs, w // bs, bs, c).transpose(
                0, 1, 3, 2, 4, 5).reshape(b, h // bs, w // bs, bs * bs * c)
        return x.reshape(b, h, w, bs, bs, c // (bs * bs)).transpose(
            0, 1, 3, 2, 4, 5).reshape(b, h * bs, w * bs, c // (bs * bs))

    def _resize_coords(self, n, in_dim, out_dim):
        """Source sample coordinates for the three TF resize conventions."""
        if n.attr["align_corners"].b and out_dim > 1:
            return jnp.linspace(0.0, in_dim - 1, out_dim)
        if n.attr["half_pixel_centers"].b:
            scale = in_dim / out_dim
            return jnp.maximum((jnp.arange(out_dim) + 0.5) * scale - 0.5, 0.0)
        return jnp.arange(out_dim) * (in_dim / out_dim)   # v1 legacy

    def _resize_bilinear(self, i, n):
        x = i[0]
        oh, ow = (int(v) for v in _axes(i[1]))
        b, h, w, c = x.shape
        ys = self._resize_coords(n, h, oh)
        xs = self._resize_coords(n, w, ow)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[None, :, None, None].astype(x.dtype)
        wx = (xs - x0)[None, None, :, None].astype(x.dtype)
        top = x[:, y0][:, :, x0] * (1 - wx) + x[:, y0][:, :, x1] * wx
        bot = x[:, y1][:, :, x0] * (1 - wx) + x[:, y1][:, :, x1] * wx
        return top * (1 - wy) + bot * wy

    def _resize_nearest(self, i, n):
        x = i[0]
        oh, ow = (int(v) for v in _axes(i[1]))
        b, h, w, c = x.shape
        ys = self._resize_coords(n, h, oh)
        xs = self._resize_coords(n, w, ow)
        # TF rounds half AWAY from zero (coords are >= 0, so floor(x+0.5));
        # jnp.round's half-to-even would shift every .5 coordinate
        round_fn = ((lambda v: jnp.floor(v + 0.5))
                    if (n.attr["align_corners"].b
                        or n.attr["half_pixel_centers"].b)
                    else jnp.floor)
        yi = jnp.clip(round_fn(ys).astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(round_fn(xs).astype(jnp.int32), 0, w - 1)
        return x[:, yi][:, :, xi]

    def _fused_bn(self, i, n):
        x, gamma, beta, mean, var = i[:5]
        eps = n.attr["epsilon"].f or 1e-3
        return (x - mean) * lax.rsqrt(var + eps) * gamma + beta

    def _conv2d(self, i, n):
        strides = tuple(n.attr["strides"].list.i)[1:3]
        pad = n.attr["padding"].s.decode()
        return lax.conv_general_dilated(
            i[0], i[1], strides, pad, dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def _maxpool(self, i, n):
        k = tuple(n.attr["ksize"].list.i)
        s = tuple(n.attr["strides"].list.i)
        pad = n.attr["padding"].s.decode()
        return lax.reduce_window(i[0], -jnp.inf, lax.max, k, s, pad)

    def _avgpool(self, i, n):
        k = tuple(n.attr["ksize"].list.i)
        s = tuple(n.attr["strides"].list.i)
        pad = n.attr["padding"].s.decode()
        total = lax.reduce_window(i[0], 0.0, lax.add, k, s, pad)
        if pad == "SAME":
            # TF excludes padding from the denominator at the borders
            ones = jnp.ones_like(i[0])
            count = lax.reduce_window(ones, 0.0, lax.add, k, s, pad)
            return total / count
        return total / (k[1] * k[2])

    # ------------------------------------------------------------------ main
    def import_graph(self, graph_def, sd: SameDiff | None = None) -> SameDiff:
        """Map a tf.compat.v1.GraphDef onto a SameDiff graph."""
        sd = sd or SameDiff.create()
        produced: Dict[str, Any] = {}   # tf tensor name → SDVariable | list

        def tensor_ref(name) -> SDVariable:
            base, _, idx = name.partition(":")
            base = base.lstrip("^")
            v = produced[base]
            if isinstance(v, list):
                return v[int(idx) if idx else 0]
            return v

        for node in graph_def.node:
            op = node.op
            if op == "Const":
                arr = _tensor_to_np(node.attr["value"].tensor)
                produced[node.name] = sd.constant(node.name, jnp.asarray(arr))
                continue
            if op in ("Placeholder", "PlaceholderWithDefault"):
                shape = None
                if node.attr["shape"].shape.dim:
                    shape = tuple(d.size if d.size > 0 else None
                                  for d in node.attr["shape"].shape.dim)
                produced[node.name] = sd.placeholder(node.name, shape)
                continue
            if op == "NoOp":
                continue
            handler = self.handlers.get(op)
            if handler is None:
                raise NotImplementedError(
                    f"TF op '{op}' (node '{node.name}') not mapped; "
                    f"supported: {sorted(k for k, v in self.handlers.items() if v)}")
            ins = [tensor_ref(i) for i in node.input if not i.startswith("^")]

            def make_fn(h=handler, nd=node, multi=op in ("Split", "SplitV", "Unpack")):
                def fn(*vals):
                    return h(list(vals), nd)
                return fn

            if op in ("Split", "SplitV", "Unpack"):
                # multi-output: materialize as tuple node + index views
                tup = sd._op(node.name + "_tuple", make_fn(), ins)
                count = (node.attr["num_split"].i if op in ("Split", "SplitV")
                         else node.attr["num"].i)
                outs = []
                for j in range(count):
                    outs.append(sd._op(f"{node.name}_{j}",
                                       (lambda jj: lambda t: t[jj])(j), [tup]))
                produced[node.name] = outs
            else:
                v = sd._op(node.name + "_op", make_fn(), ins)
                v.rename(node.name)
                produced[node.name] = v
        return sd


def import_frozen_graph(path_or_graphdef, outputs: List[str] | None = None):
    """Load a frozen .pb (or an in-memory GraphDef) → (SameDiff, outputs)."""
    if isinstance(path_or_graphdef, (str, bytes)):
        from tensorflow.core.framework import graph_pb2
        gd = graph_pb2.GraphDef()
        with open(path_or_graphdef, "rb") as f:
            gd.ParseFromString(f.read())
    else:
        gd = path_or_graphdef
    sd = TFImporter().import_graph(gd)
    outs = [sd.get_variable(o) for o in outputs] if outputs else None
    return sd, outs
