"""SameDiff op registry — the broad namespaces.

Reference parity: upstream nd4j's op namespaces
(`nd4j-api/.../autodiff/samediff/ops/SDBaseOps|SDMath|SDNN|SDCNN|SDRNN|
SDLinalg|SDBitwise|SDRandom|SDImage|SDLoss` — ~O(1000) ops). This module is
the TPU-native registry: every op is a pure jnp/lax function (jit-traceable,
differentiable where the math allows), organized into the same namespace
split. Random ops take an EXPLICIT jax PRNG key first (TPU-idiomatic; the
reference threads global RNG state instead).

Conventions: snake_case names matching the upstream camelCase (upstream
`scatterAdd` → `scatter_add`); static shape/axis arguments are python ints
or tuples (XLA needs them static anyway); segment ops require static
`num_segments` like `jax.ops.segment_sum`.
"""

from __future__ import annotations

import math as _math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy import linalg as jsl
from jax.scipy import special as jsp


def _axes(a):
    return tuple(a) if isinstance(a, (list, tuple)) else a


# ---------------------------------------------------------------- SDBaseOps
def _scatter(op):
    def f(ref, indices, updates):
        idx = jnp.asarray(indices).astype(jnp.int32)
        return getattr(jnp.asarray(ref).at[idx], op)(jnp.asarray(updates))
    return f


def _gather_nd(params, indices):
    idx = jnp.asarray(indices).astype(jnp.int32)
    # last index dim is static: unpack it without iterating a traced array
    return jnp.asarray(params)[tuple(idx[..., i]
                                     for i in range(idx.shape[-1]))]


def _scatter_nd(indices, updates, shape):
    idx = jnp.asarray(indices).astype(jnp.int32)
    out = jnp.zeros(tuple(shape), jnp.asarray(updates).dtype)
    return out.at[tuple(idx[..., i] for i in range(idx.shape[-1]))].add(
        jnp.asarray(updates))


def _dynamic_partition(x, partitions, num_partitions):
    # TPU-native: returns a LIST of same-shaped masked arrays (XLA needs
    # static shapes; the reference returns ragged arrays).
    return [jnp.where((partitions == i).reshape((-1,) + (1,) * (x.ndim - 1)),
                      x, 0) for i in range(num_partitions)]


def _dynamic_stitch(indices, data):
    n = sum(int(jnp.size(i)) for i in indices)
    first = jnp.asarray(data[0])
    out = jnp.zeros((n,) + first.shape[1:], first.dtype)
    for idx, d in zip(indices, data):
        out = out.at[jnp.asarray(idx).reshape(-1).astype(jnp.int32)].set(
            jnp.asarray(d).reshape((-1,) + first.shape[1:]))
    return out


def _sequence_mask(lengths, maxlen=None):
    maxlen = int(maxlen) if maxlen is not None else int(jnp.max(lengths))
    return jnp.arange(maxlen) < jnp.asarray(lengths)[..., None]


def _reverse_sequence(x, seq_lengths, seq_axis=1, batch_axis=0):
    t = x.shape[seq_axis]
    idx = jnp.arange(t)
    lens = jnp.asarray(seq_lengths)
    # per-batch index: reversed inside [0, len), identity beyond
    rev = jnp.where(idx[None, :] < lens[:, None],
                    lens[:, None] - 1 - idx[None, :], idx[None, :])
    x_b = jnp.moveaxis(x, (batch_axis, seq_axis), (0, 1))
    out = jnp.take_along_axis(
        x_b, rev.reshape(rev.shape + (1,) * (x_b.ndim - 2)).astype(jnp.int32),
        axis=1)
    return jnp.moveaxis(out, (0, 1), (batch_axis, seq_axis))


def _confusion_matrix(labels, predictions, num_classes):
    idx = labels.astype(jnp.int32) * num_classes + predictions.astype(jnp.int32)
    return jnp.bincount(idx, length=num_classes * num_classes).reshape(
        num_classes, num_classes)


def _clip_by_norm(x, clip_norm, axes=None):
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=_axes(axes), keepdims=True))
    return jnp.where(n > clip_norm, x * clip_norm / jnp.maximum(n, 1e-12), x)


def _clip_by_global_norm(tensors, clip_norm):
    g = jnp.sqrt(sum(jnp.sum(jnp.square(t)) for t in tensors))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(g, 1e-12))
    return [t * scale for t in tensors]


def _top_k(x, k, sorted=True):  # noqa: A002 — upstream arg name
    return lax.top_k(x, int(k))


def _unique_with_counts(x, size):
    # static-size variant (XLA): returns (values, counts) padded to `size`
    vals, counts = jnp.unique(x, return_counts=True, size=int(size))
    return vals, counts


def _batch_mmul(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


BASE = {
    # shape surgery
    "reshape": lambda x, shape: jnp.reshape(x, _axes(shape)),
    "permute": lambda x, *axes: jnp.transpose(x, axes or None),
    "transpose": lambda x, *axes: jnp.transpose(x, axes or None),
    "expand_dims": lambda x, axis: jnp.expand_dims(x, int(axis)),
    "squeeze": lambda x, axis=None: jnp.squeeze(x, axis),
    "concat": lambda *xs, axis=0: jnp.concatenate(xs, axis=int(axis)),
    "stack": lambda *xs, axis=0: jnp.stack(xs, axis=int(axis)),
    "parallel_stack": lambda *xs: jnp.stack(xs, axis=0),
    "unstack": lambda x, axis=0, num=None: [
        jnp.squeeze(s, axis) for s in jnp.split(
            x, num or x.shape[axis], axis=axis)],
    "split": lambda x, num_or_sections, axis=0: jnp.split(
        x, num_or_sections, axis=int(axis)),
    "tile": lambda x, reps: jnp.tile(x, _axes(reps)),
    "repeat": lambda x, repeats, axis=None: jnp.repeat(x, repeats, axis=axis),
    "pad": lambda x, paddings, mode="constant", value=0.0: jnp.pad(
        x, paddings, mode=mode,
        **({"constant_values": value} if mode == "constant" else {})),
    "reverse": lambda x, *axes: jnp.flip(x, _axes(axes) or None),
    "flip": lambda x, *axes: jnp.flip(x, _axes(axes) or None),
    "roll": lambda x, shift, axis=None: jnp.roll(x, shift, axis),
    "broadcast_to": lambda x, shape: jnp.broadcast_to(x, _axes(shape)),
    "moveaxis": lambda x, src, dst: jnp.moveaxis(x, src, dst),
    "swapaxes": lambda x, a, b: jnp.swapaxes(x, int(a), int(b)),
    "ravel": lambda x: jnp.ravel(x),
    "atleast_2d": lambda x: jnp.atleast_2d(x),
    # creation
    "zeros_like": jnp.zeros_like, "ones_like": jnp.ones_like,
    "full_like": lambda x, v: jnp.full_like(x, v),
    "eye": lambda n, m=None: jnp.eye(int(n), None if m is None else int(m)),
    "fill": lambda shape, value: jnp.full(_axes(shape), value),
    "linspace": lambda start, stop, num: jnp.linspace(start, stop, int(num)),
    "range": lambda start, stop=None, step=1: (
        jnp.arange(start) if stop is None else jnp.arange(start, stop, step)),
    "meshgrid": lambda *xs, indexing="xy": jnp.meshgrid(*xs, indexing=indexing),
    # dtype / identity
    "cast": lambda x, dtype: x.astype(dtype),
    "identity": lambda x: x,
    "shape_of": lambda x: jnp.asarray(x.shape, jnp.int32),
    "size": lambda x: jnp.asarray(jnp.size(x), jnp.int32),
    "size_at": lambda x, dim: jnp.asarray(x.shape[int(dim)], jnp.int32),
    "rank": lambda x: jnp.asarray(jnp.ndim(x), jnp.int32),
    # indexing / gather / scatter
    "gather": lambda x, indices, axis=0: jnp.take(
        x, jnp.asarray(indices).astype(jnp.int32), axis=int(axis)),
    "gather_nd": _gather_nd,
    "scatter_update": _scatter("set"),
    "scatter_add": _scatter("add"),
    "scatter_sub": lambda ref, i, u: _scatter("add")(ref, i, -jnp.asarray(u)),
    "scatter_mul": _scatter("multiply"),
    "scatter_div": _scatter("divide"),
    "scatter_max": _scatter("max"),
    "scatter_min": _scatter("min"),
    "scatter_nd": _scatter_nd,
    "slice": lambda x, begin, size: lax.dynamic_slice(
        x, [int(b) for b in begin], [int(s) for s in size]),
    "strided_slice": lambda x, begin, end, strides=None: x[tuple(
        slice(b, e, s) for b, e, s in zip(
            begin, end, strides or [1] * len(begin)))],
    "where": lambda cond, x=None, y=None: (
        jnp.where(cond) if x is None else jnp.where(cond, x, y)),
    "boolean_mask": lambda x, mask, size: jnp.compress(
        jnp.asarray(mask).reshape(-1),
        x.reshape((-1,) + x.shape[jnp.asarray(mask).ndim:]), axis=0,
        size=int(size), fill_value=0),
    "take_along_axis": lambda x, idx, axis: jnp.take_along_axis(
        x, jnp.asarray(idx).astype(jnp.int32), axis=axis),
    "one_hot": lambda idx, depth, on=1.0, off=0.0: jax.nn.one_hot(
        jnp.asarray(idx).astype(jnp.int32), int(depth)) * (on - off) + off,
    "searchsorted": lambda a, v, side="left": jnp.searchsorted(a, v, side=side),
    "diag": lambda x: jnp.diag(x) if x.ndim <= 1 else jnp.diagflat(x),
    "diag_part": lambda x: jnp.diagonal(x, axis1=-2, axis2=-1),
    "trace": lambda x: jnp.trace(x, axis1=-2, axis2=-1),
    "tril": lambda x, k=0: jnp.tril(x, int(k)),
    "triu": lambda x, k=0: jnp.triu(x, int(k)),
    # reductions
    "sum": lambda x, *axes, keepdims=False: jnp.sum(
        x, axis=_axes(axes) or None, keepdims=keepdims),
    "mean": lambda x, *axes, keepdims=False: jnp.mean(
        x, axis=_axes(axes) or None, keepdims=keepdims),
    "prod": lambda x, *axes, keepdims=False: jnp.prod(
        x, axis=_axes(axes) or None, keepdims=keepdims),
    "max": lambda x, *axes, keepdims=False: jnp.max(
        x, axis=_axes(axes) or None, keepdims=keepdims),
    "min": lambda x, *axes, keepdims=False: jnp.min(
        x, axis=_axes(axes) or None, keepdims=keepdims),
    "std": lambda x, *axes, ddof=0, keepdims=False: jnp.std(
        x, axis=_axes(axes) or None, ddof=ddof, keepdims=keepdims),
    "variance": lambda x, *axes, ddof=0, keepdims=False: jnp.var(
        x, axis=_axes(axes) or None, ddof=ddof, keepdims=keepdims),
    "norm1": lambda x, *axes: jnp.sum(jnp.abs(x), axis=_axes(axes) or None),
    "norm2": lambda x, *axes: jnp.sqrt(
        jnp.sum(jnp.square(x), axis=_axes(axes) or None)),
    "norm_max": lambda x, *axes: jnp.max(jnp.abs(x), axis=_axes(axes) or None),
    "squared_norm": lambda x, *axes: jnp.sum(
        jnp.square(x), axis=_axes(axes) or None),
    "count_nonzero": lambda x, *axes: jnp.count_nonzero(
        x, axis=_axes(axes) or None),
    "count_zero": lambda x, *axes: (
        (_math.prod(x.shape[a] for a in axes) if axes else jnp.size(x))
        - jnp.count_nonzero(x, axis=_axes(axes) or None)),
    "any": lambda x, *axes: jnp.any(x, axis=_axes(axes) or None),
    "all": lambda x, *axes: jnp.all(x, axis=_axes(axes) or None),
    "argmax": lambda x, axis=-1: jnp.argmax(x, axis=axis),
    "argmin": lambda x, axis=-1: jnp.argmin(x, axis=axis),
    "iamax": lambda x: jnp.argmax(jnp.abs(x)),
    "iamin": lambda x: jnp.argmin(jnp.abs(x)),
    "cumsum": lambda x, axis=None: jnp.cumsum(x, axis=axis),
    "cumprod": lambda x, axis=None: jnp.cumprod(x, axis=axis),
    "logsumexp": lambda x, *axes: jsp.logsumexp(x, axis=_axes(axes) or None),
    # segment ops (static num_segments — XLA requirement)
    "segment_sum": lambda data, ids, num_segments: jax.ops.segment_sum(
        data, jnp.asarray(ids).astype(jnp.int32), int(num_segments)),
    "segment_prod": lambda data, ids, num_segments: jax.ops.segment_prod(
        data, jnp.asarray(ids).astype(jnp.int32), int(num_segments)),
    "segment_max": lambda data, ids, num_segments: jax.ops.segment_max(
        data, jnp.asarray(ids).astype(jnp.int32), int(num_segments)),
    "segment_min": lambda data, ids, num_segments: jax.ops.segment_min(
        data, jnp.asarray(ids).astype(jnp.int32), int(num_segments)),
    "segment_mean": lambda data, ids, num_segments: (
        jax.ops.segment_sum(data, jnp.asarray(ids).astype(jnp.int32),
                            int(num_segments))
        / jnp.maximum(jax.ops.segment_sum(
            jnp.ones_like(data), jnp.asarray(ids).astype(jnp.int32),
            int(num_segments)), 1)),
    "unsorted_segment_sum": lambda data, ids, num_segments: jax.ops.segment_sum(
        data, jnp.asarray(ids).astype(jnp.int32), int(num_segments),
        indices_are_sorted=False),
    # sorting & sets
    "sort": lambda x, axis=-1, descending=False: (
        -jnp.sort(-x, axis=axis) if descending else jnp.sort(x, axis=axis)),
    "argsort": lambda x, axis=-1: jnp.argsort(x, axis=axis),
    "top_k": _top_k,
    "unique": lambda x, size: jnp.unique(x, size=int(size)),
    "unique_with_counts": _unique_with_counts,
    "in_top_k": lambda predictions, targets, k: jnp.any(
        lax.top_k(predictions, int(k))[1]
        == jnp.asarray(targets).astype(jnp.int32)[..., None], axis=-1),
    # matmul family
    "mmul": jnp.matmul,
    "matmul": jnp.matmul,
    "batch_mmul": _batch_mmul,
    "tensor_mmul": jnp.tensordot,
    "dot": jnp.dot,
    "vdot": jnp.vdot,
    "outer": jnp.outer,
    "kron": jnp.kron,
    "cross": jnp.cross,
    "einsum": jnp.einsum,
    # batch/space rearrangement
    "space_to_depth": lambda x, bs: lax.reshape(
        x.reshape(x.shape[0], x.shape[1] // bs, bs, x.shape[2] // bs, bs,
                  x.shape[3]).transpose(0, 1, 3, 2, 4, 5),
        (x.shape[0], x.shape[1] // bs, x.shape[2] // bs,
         bs * bs * x.shape[3])),
    "depth_to_space": lambda x, bs: x.reshape(
        x.shape[0], x.shape[1], x.shape[2], bs, bs,
        x.shape[3] // (bs * bs)).transpose(0, 1, 3, 2, 4, 5).reshape(
        x.shape[0], x.shape[1] * bs, x.shape[2] * bs,
        x.shape[3] // (bs * bs)),
    # misc
    "dynamic_partition": _dynamic_partition,
    "dynamic_stitch": _dynamic_stitch,
    "sequence_mask": _sequence_mask,
    "reverse_sequence": _reverse_sequence,
    "confusion_matrix": _confusion_matrix,
    "clip_by_value": jnp.clip,
    "clip_by_norm": _clip_by_norm,
    "clip_by_global_norm": _clip_by_global_norm,
    "stop_gradient": lax.stop_gradient,
    "assign": lambda x, y: jnp.broadcast_to(y, jnp.shape(x)).astype(x.dtype),
    "invert_permutation": lambda p: jnp.argsort(p),
    "bincount": lambda x, length: jnp.bincount(
        jnp.asarray(x).astype(jnp.int32), length=int(length)),
    "nan_to_num": jnp.nan_to_num,
}

# ------------------------------------------------------------------ SDMath
MATH_EXT = {
    # inverse/hyperbolic trig
    "atan2": jnp.arctan2, "asinh": jnp.arcsinh, "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
    # exp/log family
    "expm1": jnp.expm1, "log2": jnp.log2, "log10": jnp.log10,
    "rsqrt": lax.rsqrt, "cbrt": jnp.cbrt, "exp2": jnp.exp2,
    "logaddexp": jnp.logaddexp,
    # special functions
    "erfc": jsp.erfc, "erfinv": jsp.erfinv, "lgamma": jsp.gammaln,
    "digamma": jsp.digamma, "polygamma": lambda n, x: jsp.polygamma(int(n), x),
    "igamma": jsp.gammainc, "igammac": jsp.gammaincc, "zeta": jsp.zeta,
    "betainc": jsp.betainc, "xlogy": jsp.xlogy, "entr": jsp.entr,
    "logit": jsp.logit, "expit": jsp.expit,
    # integer-ish arithmetic
    "mod": jnp.mod, "fmod": jnp.fmod, "floor_div": jnp.floor_divide,
    "floor_mod": jnp.mod, "truncate_div": lambda a, b: jnp.trunc(a / b),
    "rdiv": lambda a, b: b / a, "rsub": lambda a, b: b - a,
    "remainder": jnp.remainder,
    # comparisons & predicates
    "eq": jnp.equal, "neq": jnp.not_equal, "gt": jnp.greater,
    "gte": jnp.greater_equal, "lt": jnp.less, "lte": jnp.less_equal,
    "is_finite": jnp.isfinite, "is_nan": jnp.isnan, "is_inf": jnp.isinf,
    "is_numeric_tensor": lambda x: jnp.asarray(
        jnp.issubdtype(jnp.asarray(x).dtype, jnp.number)),
    "is_close": jnp.isclose,
    "is_max": lambda x: x == jnp.max(x),
    # logical
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor, "logical_not": jnp.logical_not,
    # pairwise distances / similarities (reference SDMath distance ops)
    "cosine_similarity": lambda a, b, axis=-1: jnp.sum(a * b, axis) / (
        jnp.maximum(jnp.linalg.norm(a, axis=axis)
                    * jnp.linalg.norm(b, axis=axis), 1e-12)),
    "cosine_distance": lambda a, b, axis=-1: 1.0 - (
        jnp.sum(a * b, axis) / jnp.maximum(
            jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis),
            1e-12)),
    "euclidean_distance": lambda a, b, axis=-1: jnp.sqrt(
        jnp.sum(jnp.square(a - b), axis)),
    "manhattan_distance": lambda a, b, axis=-1: jnp.sum(jnp.abs(a - b), axis),
    "hamming_distance": lambda a, b, axis=-1: jnp.sum(
        (a != b).astype(jnp.float32), axis),
    "jaccard_distance": lambda a, b, axis=-1: 1.0 - (
        jnp.sum(jnp.minimum(a, b), axis)
        / jnp.maximum(jnp.sum(jnp.maximum(a, b), axis), 1e-12)),
    "squared_difference": lambda a, b: jnp.square(a - b),
    # rounding & manipulation
    "trunc": jnp.trunc, "rint": jnp.rint,
    "copysign": jnp.copysign, "heaviside": jnp.heaviside,
    "deg2rad": jnp.deg2rad, "rad2deg": jnp.rad2deg,
    "hypot": jnp.hypot, "ldexp": jnp.ldexp, "frexp": jnp.frexp,
    "step": lambda x: (x > 0).astype(x.dtype),
    "moving_average": lambda x, n: jnp.convolve(
        x, jnp.ones(int(n)) / int(n), mode="valid"),
    "diff": lambda x, n=1, axis=-1: jnp.diff(x, n=n, axis=axis),
    "interp": jnp.interp,
}

# ---------------------------------------------------------------- SDLinalg
LINALG = {
    "cholesky": jnp.linalg.cholesky,
    "qr": jnp.linalg.qr,
    "svd": jnp.linalg.svd,
    "eigh": jnp.linalg.eigh,
    "eigvalsh": jnp.linalg.eigvalsh,
    "solve": jnp.linalg.solve,
    "lstsq": jnp.linalg.lstsq,
    "inv": jnp.linalg.inv,
    "pinv": jnp.linalg.pinv,
    "det": jnp.linalg.det,
    "slogdet": jnp.linalg.slogdet,
    "matrix_rank": jnp.linalg.matrix_rank,
    "norm": jnp.linalg.norm,
    "matrix_power": jnp.linalg.matrix_power,
    "triangular_solve": lambda a, b, lower=True: jax.scipy.linalg.solve_triangular(
        a, b, lower=lower),
    "expm": jax.scipy.linalg.expm,
    "matrix_transpose": lambda x: jnp.swapaxes(x, -1, -2),
    "matrix_diag": lambda d: d[..., None] * jnp.eye(d.shape[-1], dtype=d.dtype),
    "matrix_diag_part": lambda x: jnp.diagonal(x, axis1=-2, axis2=-1),
    "logdet": lambda x: jnp.linalg.slogdet(x)[1],
    "mmul": jnp.matmul,
    "tri": lambda n, m=None, k=0: jnp.tri(
        int(n), None if m is None else int(m), int(k)),
}

# ---------------------------------------------------------------- SDBitwise
BITWISE = {
    "and_": jnp.bitwise_and, "or_": jnp.bitwise_or, "xor": jnp.bitwise_xor,
    "invert": jnp.bitwise_not,
    "left_shift": jnp.left_shift, "right_shift": jnp.right_shift,
    "bits_hamming_distance": lambda a, b: jnp.sum(_popcount(a ^ b)),
    "bit_count": lambda x: _popcount(x),
    "cyclic_shift_left": lambda x, n, bits=32: (
        (x << n) | lax.shift_right_logical(x, bits - n)),
    "cyclic_shift_right": lambda x, n, bits=32: (
        lax.shift_right_logical(x, n) | (x << (bits - n))),
}


def _popcount(x):
    x = jnp.asarray(x)
    c = jnp.zeros_like(x)
    for i in range(x.dtype.itemsize * 8):
        c = c + ((x >> i) & 1)
    return c


# ----------------------------------------------------------------- SDRandom
# Explicit-key API (TPU-idiomatic Philox): first arg is a jax PRNG key.
RANDOM = {
    "uniform": lambda key, shape, minval=0.0, maxval=1.0: jax.random.uniform(
        key, _axes(shape), minval=minval, maxval=maxval),
    "normal": lambda key, shape, mean=0.0, stddev=1.0: mean + stddev
    * jax.random.normal(key, _axes(shape)),
    "log_normal": lambda key, shape, mean=0.0, stddev=1.0: jnp.exp(
        mean + stddev * jax.random.normal(key, _axes(shape))),
    "truncated_normal": lambda key, shape, mean=0.0, stddev=1.0: mean + stddev
    * jax.random.truncated_normal(key, -2.0, 2.0, _axes(shape)),
    "bernoulli": lambda key, p, shape: jax.random.bernoulli(
        key, p, _axes(shape)),
    "binomial": lambda key, n, p, shape: jnp.sum(
        jax.random.bernoulli(key, p, (int(n),) + _axes(shape)), axis=0),
    "gamma": lambda key, alpha, shape: jax.random.gamma(
        key, alpha, _axes(shape)),
    "beta": lambda key, a, b, shape: jax.random.beta(key, a, b, _axes(shape)),
    "poisson": lambda key, lam, shape: jax.random.poisson(
        key, lam, _axes(shape)),
    "exponential": lambda key, shape, rate=1.0: jax.random.exponential(
        key, _axes(shape)) / rate,
    "laplace": lambda key, shape: jax.random.laplace(key, _axes(shape)),
    "gumbel": lambda key, shape: jax.random.gumbel(key, _axes(shape)),
    "cauchy": lambda key, shape: jax.random.cauchy(key, _axes(shape)),
    "randint": lambda key, shape, minval, maxval: jax.random.randint(
        key, _axes(shape), minval, maxval),
    "shuffle": lambda key, x, axis=0: jax.random.permutation(
        key, x, axis=axis, independent=False),
    "permutation": lambda key, n: jax.random.permutation(key, int(n)),
    "choice": lambda key, x, shape, replace=True: jax.random.choice(
        key, x, _axes(shape), replace=replace),
    "categorical": lambda key, logits, shape=(): jax.random.categorical(
        key, logits, shape=_axes(shape) or None),
}

# -------------------------------------------------------------------- SDCNN
_DN2D = ("NHWC", "HWIO", "NHWC")
_DN1D = ("NWC", "WIO", "NWC")
_DN3D = ("NDHWC", "DHWIO", "NDHWC")


def _pool(reducer, init, rank):
    def f(x, k, s=None, padding="VALID"):
        k = (k,) * rank if isinstance(k, int) else tuple(k)
        s = k if s is None else ((s,) * rank if isinstance(s, int) else tuple(s))
        window = (1, *k, 1)
        strides = (1, *s, 1)
        out = lax.reduce_window(x, init, reducer, window, strides, padding)
        if reducer is lax.add:
            ones = jnp.ones(x.shape[1:-1], x.dtype)[None, ..., None]
            denom = lax.reduce_window(
                jnp.broadcast_to(ones, x.shape), 0.0, lax.add, window,
                strides, padding)
            out = out / denom
        return out
    return f


CNN = {
    "conv1d": lambda x, w, stride=1, padding="SAME", dilation=1:
        lax.conv_general_dilated(x, w, (stride,), padding,
                                 rhs_dilation=(dilation,),
                                 dimension_numbers=_DN1D),
    "conv2d": lambda x, w, stride=(1, 1), padding="SAME", dilation=(1, 1):
        lax.conv_general_dilated(x, w, tuple(stride), padding,
                                 rhs_dilation=tuple(dilation),
                                 dimension_numbers=_DN2D),
    "conv3d": lambda x, w, stride=(1, 1, 1), padding="SAME":
        lax.conv_general_dilated(x, w, tuple(stride), padding,
                                 dimension_numbers=_DN3D),
    "depthwise_conv2d": lambda x, w, stride=(1, 1), padding="SAME":
        lax.conv_general_dilated(
            x, w, tuple(stride), padding, dimension_numbers=_DN2D,
            feature_group_count=x.shape[-1]),
    "separable_conv2d": lambda x, wd, wp, stride=(1, 1), padding="SAME":
        lax.conv_general_dilated(
            lax.conv_general_dilated(
                x, wd, tuple(stride), padding, dimension_numbers=_DN2D,
                feature_group_count=x.shape[-1]),
            wp, (1, 1), "VALID", dimension_numbers=_DN2D),
    "deconv2d": lambda x, w, stride=(2, 2), padding="SAME":
        lax.conv_transpose(x, w, tuple(stride), padding,
                           dimension_numbers=_DN2D),
    "max_pooling1d": _pool(lax.max, -jnp.inf, 1),
    "max_pooling2d": _pool(lax.max, -jnp.inf, 2),
    "max_pooling3d": _pool(lax.max, -jnp.inf, 3),
    "avg_pooling1d": _pool(lax.add, 0.0, 1),
    "avg_pooling2d": _pool(lax.add, 0.0, 2),
    "avg_pooling3d": _pool(lax.add, 0.0, 3),
    "global_avg_pooling": lambda x: jnp.mean(
        x, axis=tuple(range(1, x.ndim - 1))),
    "global_max_pooling": lambda x: jnp.max(
        x, axis=tuple(range(1, x.ndim - 1))),
    "upsampling2d": lambda x, scale=2: jnp.repeat(
        jnp.repeat(x, scale, axis=1), scale, axis=2),
    "local_response_normalization": lambda x, depth_radius=5, bias=1.0,
    alpha=1.0, beta=0.5: x / jnp.power(
        bias + alpha * lax.reduce_window(
            jnp.square(x), 0.0, lax.add,
            (1, 1, 1, 2 * depth_radius + 1), (1, 1, 1, 1), "SAME"), beta),
    "im2col": lambda x, kh, kw: lax.conv_general_dilated_patches(
        x, (kh, kw), (1, 1), "VALID", dimension_numbers=_DN2D),
    "batch_norm": lambda x, mean, var, gamma, beta, eps=1e-5: (
        (x - mean) * lax.rsqrt(var + eps) * gamma + beta),
}

# -------------------------------------------------------------------- SDRNN
def _lstm_cell(x, h, c, w_ih, w_hh, b):
    z = x @ w_ih + h @ w_hh + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
    return h2, c2


def _gru_cell(x, h, w_ih, w_hh, b):
    zr = x @ w_ih[:, :2 * h.shape[-1]] + h @ w_hh[:, :2 * h.shape[-1]] \
        + b[:2 * h.shape[-1]]
    z, r = jnp.split(jax.nn.sigmoid(zr), 2, axis=-1)
    n = jnp.tanh(x @ w_ih[:, 2 * h.shape[-1]:]
                 + (r * h) @ w_hh[:, 2 * h.shape[-1]:]
                 + b[2 * h.shape[-1]:])
    return (1 - z) * n + z * h


def _rnn_layer(cell_has_c, cell=None):
    """Scan a cell over (B, T, ...) time-major-internally. ``cell`` defaults
    to the fused LSTM/GRU cells; any (x_t, h[, c], *args) cell works."""
    def f(x, h0, *args):
        def body(carry, xt):
            if cell_has_c:
                h, c = carry
                h2, c2 = (cell or _lstm_cell)(xt, h, c, *args)
                return (h2, c2), h2
            h2 = (cell or _gru_cell)(xt, carry, *args)
            return h2, h2
        init = h0 if not cell_has_c else (h0, jnp.zeros_like(h0))
        _, hs = lax.scan(body, init, jnp.swapaxes(x, 0, 1))
        return jnp.swapaxes(hs, 0, 1)
    return f


RNN = {
    "lstm_cell": _lstm_cell,
    "gru_cell": _gru_cell,
    "simple_rnn_cell": lambda x, h, w_ih, w_hh, b: jnp.tanh(
        x @ w_ih + h @ w_hh + b),
    "lstm_layer": _rnn_layer(cell_has_c=True),
    "gru_layer": _rnn_layer(cell_has_c=False),
}

# ------------------------------------------------------------------ SDImage
IMAGE = {
    "resize_bilinear": lambda x, h, w: jax.image.resize(
        x, (x.shape[0], int(h), int(w), x.shape[3]), "bilinear"),
    "resize_nearest": lambda x, h, w: jax.image.resize(
        x, (x.shape[0], int(h), int(w), x.shape[3]), "nearest"),
    "resize_bicubic": lambda x, h, w: jax.image.resize(
        x, (x.shape[0], int(h), int(w), x.shape[3]), "cubic"),
    "flip_left_right": lambda x: jnp.flip(x, axis=2),
    "flip_up_down": lambda x: jnp.flip(x, axis=1),
    "rot90": lambda x, k=1: jnp.rot90(x, k, axes=(1, 2)),
    "adjust_brightness": lambda x, delta: x + delta,
    "adjust_contrast": lambda x, factor: (
        x - jnp.mean(x, axis=(1, 2), keepdims=True)) * factor
        + jnp.mean(x, axis=(1, 2), keepdims=True),
    "rgb_to_grayscale": lambda x: jnp.sum(
        x * jnp.asarray([0.2989, 0.587, 0.114], x.dtype), axis=-1,
        keepdims=True),
    "per_image_standardization": lambda x: (
        x - jnp.mean(x, axis=(1, 2, 3), keepdims=True)) / jnp.maximum(
        jnp.std(x, axis=(1, 2, 3), keepdims=True),
        1.0 / _math.sqrt(x[0].size)),
    "central_crop": lambda x, frac: x[
        :, int(x.shape[1] * (1 - frac) / 2):
        int(x.shape[1] * (1 - frac) / 2) + int(x.shape[1] * frac),
        int(x.shape[2] * (1 - frac) / 2):
        int(x.shape[2] * (1 - frac) / 2) + int(x.shape[2] * frac)],
    "extract_patches": lambda x, kh, kw: lax.conv_general_dilated_patches(
        x, (int(kh), int(kw)), (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC")),
    "random_crop": lambda key, x, h, w: lax.dynamic_slice(
        x, (0, jax.random.randint(key, (), 0, x.shape[1] - int(h) + 1),
            jax.random.randint(jax.random.fold_in(key, 1), (), 0,
                               x.shape[2] - int(w) + 1), 0),
        (x.shape[0], int(h), int(w), x.shape[3])),
}

# ------------------------------------------------------------------- SDLoss
LOSS_EXT = {
    "hinge_loss": lambda labels, logits: jnp.mean(
        jax.nn.relu(1.0 - (2.0 * labels - 1.0) * logits)),
    "squared_hinge_loss": lambda labels, logits: jnp.mean(jnp.square(
        jax.nn.relu(1.0 - (2.0 * labels - 1.0) * logits))),
    "poisson_loss": lambda labels, preds, eps=1e-7: jnp.mean(
        preds - labels * jnp.log(preds + eps)),
    "kl_divergence": lambda labels, preds, eps=1e-7: jnp.mean(jnp.sum(
        labels * (jnp.log(labels + eps) - jnp.log(preds + eps)), -1)),
    "smooth_l1_loss": lambda labels, preds, beta=1.0: jnp.mean(jnp.where(
        jnp.abs(preds - labels) < beta,
        0.5 * jnp.square(preds - labels) / beta,
        jnp.abs(preds - labels) - 0.5 * beta)),
    "weighted_cross_entropy_with_logits": lambda labels, logits, weight:
        jnp.mean((1 - labels) * logits + (1 + (weight - 1) * labels)
                 * jnp.log1p(jnp.exp(-jnp.abs(logits)))
                 + jax.nn.relu(-logits) * (1 + (weight - 1) * labels)),
    "focal_loss": lambda labels, logits, gamma=2.0, alpha=0.25: jnp.mean(
        -alpha * labels * jnp.power(1 - jax.nn.sigmoid(logits), gamma)
        * jax.nn.log_sigmoid(logits)
        - (1 - alpha) * (1 - labels) * jnp.power(jax.nn.sigmoid(logits), gamma)
        * jax.nn.log_sigmoid(-logits)),
    "ctc_loss": lambda log_probs, labels, logit_lengths, label_lengths:
        _ctc(log_probs, labels, logit_lengths, label_lengths),
    "l2_loss": lambda x: 0.5 * jnp.sum(jnp.square(x)),
    "log_poisson_loss": lambda labels, log_preds, full=False: jnp.mean(
        jnp.exp(log_preds) - labels * log_preds),
}


def _ctc(log_probs, labels, logit_lengths, label_lengths):
    import optax
    b, t, v = log_probs.shape
    logit_pad = (jnp.arange(t)[None, :]
                 >= jnp.asarray(logit_lengths)[:, None]).astype(jnp.float32)
    label_pad = (jnp.arange(labels.shape[1])[None, :]
                 >= jnp.asarray(label_lengths)[:, None]).astype(jnp.float32)
    return jnp.mean(optax.ctc_loss(log_probs, logit_pad, labels, label_pad))


# ------------------------------------------------------------- NN extensions
NN_EXT = {
    "softsign": jax.nn.soft_sign,
    "hard_tanh": lambda x: jnp.clip(x, -1.0, 1.0),
    "hard_swish": jax.nn.hard_swish,
    "log_sigmoid": jax.nn.log_sigmoid,
    "prelu": lambda x, alpha: jnp.where(x >= 0, x, alpha * x),
    "glu": jax.nn.glu,
    "celu": jax.nn.celu,
    "normalize_moments": lambda counts, means_ss, variance_ss, shift=None: (
        means_ss / counts, variance_ss / counts - jnp.square(means_ss / counts)),
    "moments": lambda x, axes: (jnp.mean(x, _axes(axes)),
                                jnp.var(x, _axes(axes))),
    "l2_normalize": lambda x, axis=-1, eps=1e-12: x / jnp.sqrt(jnp.maximum(
        jnp.sum(jnp.square(x), axis=axis, keepdims=True), eps)),
    "bias_add": lambda x, b: x + b,
    "dot_product_attention": lambda q, k, v, mask=None: jax.nn.dot_product_attention(
        q, k, v, mask=mask),
    "pad": lambda x, paddings, value=0.0: jnp.pad(
        x, paddings, constant_values=value),
    "dropout_train": lambda key, x, rate: x * jax.random.bernoulli(
        key, 1 - rate, x.shape) / (1 - rate),
    "layer_norm_no_bias": lambda x, gain, eps=1e-5: (
        x - jnp.mean(x, -1, keepdims=True)) * lax.rsqrt(
        jnp.var(x, -1, keepdims=True) + eps) * gain,
    "rms_norm": lambda x, gain, eps=1e-6: x * lax.rsqrt(
        jnp.mean(jnp.square(x), -1, keepdims=True) + eps) * gain,
    "softmax_with_temperature": lambda x, t=1.0: jax.nn.softmax(x / t, -1),
    "sparsemax": None,  # intentionally absent upstream-odd op
}
del NN_EXT["sparsemax"]


# -------------------------------------------------------- r2 long tail ----
# Second widening pass toward the upstream registry: absolute-value
# reductions, the matchCondition family, entropy/standardize, unsorted
# segment ops, space/batch, merge vertices, linalg band/LU, attention and
# NMS/crop-and-resize image ops.

_CONDS = {
    "lt": jnp.less, "lte": jnp.less_equal, "gt": jnp.greater,
    "gte": jnp.greater_equal, "eq": jnp.equal, "neq": jnp.not_equal,
}


def _list_diff(x, y, size):
    """Upstream listDiff returns (values, indices): the indices (padded
    with -1 beyond the true count) disambiguate pad slots from a genuine
    element 0 in the values."""
    x = jnp.asarray(x)
    keep = ~jnp.isin(x, jnp.asarray(y))
    (idx,) = jnp.where(keep, size=int(size), fill_value=-1)
    return jnp.where(idx >= 0, x[jnp.maximum(idx, 0)], 0), idx


def _clip_by_avg_norm(x, clip, axes=None):
    rms = jnp.sqrt(jnp.mean(jnp.square(x), _axes(axes), keepdims=True))
    return jnp.where(rms > clip, x * clip / jnp.maximum(rms, 1e-12), x)


def _match_condition(x, cond, value):
    """Upstream matchCondition(in, Conditions.lessThan(v)) — the Condition
    object becomes a static name from {lt, lte, gt, gte, eq, neq}."""
    if cond not in _CONDS:
        raise ValueError(f"unknown condition {cond!r}; one of {sorted(_CONDS)}")
    return _CONDS[cond](x, value)


def _space_to_batch(x, block, paddings=((0, 0), (0, 0))):
    b, h, w, c = x.shape
    x = jnp.pad(x, ((0, 0), tuple(paddings[0]), tuple(paddings[1]), (0, 0)))
    h2, w2 = x.shape[1], x.shape[2]
    x = x.reshape(b, h2 // block, block, w2 // block, block, c)
    return x.transpose(2, 4, 0, 1, 3, 5).reshape(
        b * block * block, h2 // block, w2 // block, c)


def _batch_to_space(x, block, crops=((0, 0), (0, 0))):
    bb, h, w, c = x.shape
    b = bb // (block * block)
    x = x.reshape(block, block, b, h, w, c).transpose(2, 3, 0, 4, 1, 5)
    x = x.reshape(b, h * block, w * block, c)
    (ct, cb), (cl, cr) = crops
    return x[:, ct:h * block - cb, cl:w * block - cr, :]


def _mh_attention(q, k, v, wq, wk, wv, wo, mask=None):
    """Upstream multiHeadDotProductAttention: project with (H, Dp, Din)
    weight stacks, per-head scaled dot attention, output-project with
    (Dout, H*Dp). Inputs are (B, T, Din)."""
    qh = jnp.einsum("btd,hpd->bhtp", q, wq)
    kh = jnp.einsum("btd,hpd->bhtp", k, wk)
    vh = jnp.einsum("btd,hpd->bhtp", v, wv)
    s = jnp.einsum("bhqp,bhkp->bhqk", qh, kh) / _math.sqrt(qh.shape[-1])
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    att = jax.nn.softmax(s, -1)
    out = jnp.einsum("bhqk,bhkp->bhqp", att, vh)
    b, h, t, p = out.shape
    return jnp.einsum("btx,ox->bto",
                      out.transpose(0, 2, 1, 3).reshape(b, t, h * p), wo)


def _nms(boxes, scores, max_out, iou_threshold=0.5, score_threshold=-jnp.inf):
    """Non-max suppression, static max_out (XLA): returns (indices, valid)
    where `indices` is padded with -1 beyond `valid` picks. Boxes are
    (N, 4) [y1, x1, y2, x2]."""
    n = boxes.shape[0]
    y1, x1, y2, x2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)

    def iou(i, j):
        yy1 = jnp.maximum(y1[i], y1[j])
        xx1 = jnp.maximum(x1[i], x1[j])
        yy2 = jnp.minimum(y2[i], y2[j])
        xx2 = jnp.minimum(x2[i], x2[j])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area[i] + area[j] - inter, 1e-9)

    def body(state, _):
        live, picked_count = state
        masked = jnp.where(live, scores, -jnp.inf)
        i = jnp.argmax(masked)
        ok = jnp.logical_and(masked[i] > score_threshold,
                             jnp.isfinite(masked[i]))
        suppress = iou(i, jnp.arange(n)) > iou_threshold
        live = jnp.where(ok, jnp.logical_and(live, ~suppress), live)
        live = live.at[i].set(False)
        return (live, picked_count + ok.astype(jnp.int32)), \
            jnp.where(ok, i, -1).astype(jnp.int32)

    (_, count), idx = lax.scan(body, (jnp.ones(n, bool), jnp.int32(0)),
                               None, length=int(max_out))
    return idx, count


def _crop_and_resize(images, boxes, box_indices, crop_size,
                     extrapolation_value=0.0):
    """tf.image.crop_and_resize semantics: normalized [y1, x1, y2, x2]
    boxes, bilinear sampling on a (ch, cw) grid per box; a crop dimension
    of 1 samples the box CENTER, and samples outside the image take
    ``extrapolation_value`` (both as in TF)."""
    ch, cw = int(crop_size[0]), int(crop_size[1])
    _, h, w, _ = images.shape

    def grid(lo, hi, n, extent):
        if n == 1:
            return (0.5 * (lo + hi) * (extent - 1))[None]
        return lo * (extent - 1) + (jnp.arange(n) / (n - 1)) \
            * (hi - lo) * (extent - 1)

    def one(box, bi):
        y1, x1, y2, x2 = box
        ys = grid(y1, y2, ch, h)
        xs = grid(x1, x2, cw, w)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        img = images[bi]
        top, bot = img[y0], img[y1i]         # one row gather each
        a, b = top[:, x0], top[:, x1i]
        c, d = bot[:, x0], bot[:, x1i]
        out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
               + c * wy * (1 - wx) + d * wy * wx)
        inside = ((ys >= 0) & (ys <= h - 1))[:, None, None] \
            & ((xs >= 0) & (xs <= w - 1))[None, :, None]
        return jnp.where(inside, out, extrapolation_value)

    return jax.vmap(one)(jnp.asarray(boxes),
                         jnp.asarray(box_indices).astype(jnp.int32))


BASE.update({
    "space_to_batch": _space_to_batch,
    "batch_to_space": _batch_to_space,
    "unsorted_segment_min": lambda x, ids, num: jax.ops.segment_min(
        x, jnp.asarray(ids).astype(jnp.int32), int(num)),
    "unsorted_segment_max": lambda x, ids, num: jax.ops.segment_max(
        x, jnp.asarray(ids).astype(jnp.int32), int(num)),
    "unsorted_segment_prod": lambda x, ids, num: jax.ops.segment_prod(
        x, jnp.asarray(ids).astype(jnp.int32), int(num)),
    "unsorted_segment_mean": lambda x, ids, num: jax.ops.segment_sum(
        x, jnp.asarray(ids).astype(jnp.int32), int(num)) / jnp.maximum(
        jax.ops.segment_sum(jnp.ones_like(x, jnp.float32),
                            jnp.asarray(ids).astype(jnp.int32), int(num)), 1),
    "unsorted_segment_sqrt_n": lambda x, ids, num: jax.ops.segment_sum(
        x, jnp.asarray(ids).astype(jnp.int32), int(num)) / jnp.sqrt(
        jnp.maximum(jax.ops.segment_sum(
            jnp.ones_like(x, jnp.float32),
            jnp.asarray(ids).astype(jnp.int32), int(num)), 1)),
    "merge_add": lambda *xs: sum(xs),
    "merge_avg": lambda *xs: sum(xs) / len(xs),
    "merge_max": lambda *xs: jnp.stack(xs).max(0),
    "list_diff": _list_diff,
})

MATH_EXT.update({
    "amax": lambda x, axis=None: jnp.max(jnp.abs(x), _axes(axis)),
    "amin": lambda x, axis=None: jnp.min(jnp.abs(x), _axes(axis)),
    "amean": lambda x, axis=None: jnp.mean(jnp.abs(x), _axes(axis)),
    "asum": lambda x, axis=None: jnp.sum(jnp.abs(x), _axes(axis)),
    "logaddexp2": jnp.logaddexp2,
    "match_condition": _match_condition,
    "match_condition_count": lambda x, cond, value: jnp.sum(
        _match_condition(x, cond, value).astype(jnp.int32)),
    "zero_fraction": lambda x: jnp.mean((x == 0).astype(jnp.float32)),
    "entropy": lambda x, axis=None: -jnp.sum(
        x * jnp.log(jnp.maximum(x, 1e-30)), _axes(axis)),
    "log_entropy": lambda x, axis=None: jnp.log(-jnp.sum(
        x * jnp.log(jnp.maximum(x, 1e-30)), _axes(axis))),
    "shannon_entropy": lambda x, axis=None: -jnp.sum(
        x * jnp.log2(jnp.maximum(x, 1e-30)), _axes(axis)),
    "standardize": lambda x, axis=-1, eps=1e-12: (
        x - jnp.mean(x, _axes(axis), keepdims=True)) / jnp.sqrt(
        jnp.var(x, _axes(axis), keepdims=True) + eps),
    "is_non_decreasing": lambda x: jnp.all(jnp.diff(x.ravel()) >= 0),
    "is_strictly_increasing": lambda x: jnp.all(jnp.diff(x.ravel()) > 0),
    "clip_by_avg_norm": _clip_by_avg_norm,
})

LINALG.update({
    "matrix_band_part": lambda x, lower, upper: x * (
        (jnp.arange(x.shape[-2])[:, None] - jnp.arange(x.shape[-1])[None, :]
         <= (lower if lower >= 0 else x.shape[-2]))
        & (jnp.arange(x.shape[-1])[None, :] - jnp.arange(x.shape[-2])[:, None]
           <= (upper if upper >= 0 else x.shape[-1]))),
    "lu": jax.scipy.linalg.lu,
})

# NOTE: layer_norm/log_softmax/gelu/selu/elu/swish/mish (and square/log1p/
# reciprocal in math) already live in samediff's core _NN/_MATH tables —
# NOT duplicated here (sd.nn merges both dicts; a second copy would shadow
# signatures and double-count the registry).
NN_EXT.update({
    "multi_head_dot_product_attention": _mh_attention,
})

IMAGE.update({
    "non_max_suppression": _nms,
    "crop_and_resize": _crop_and_resize,
})


NAMESPACES = {
    "base": BASE, "math": MATH_EXT, "nn": NN_EXT, "loss": LOSS_EXT,
    "linalg": LINALG, "bitwise": BITWISE, "random": RANDOM, "cnn": CNN,
    "rnn": RNN, "image": IMAGE,
}


def op_count():
    return sum(len(t) for t in NAMESPACES.values())


# -------------------------------------------------------- r2 widening #3 --
# SDImage color-space conversions + hue/saturation, group/instance norm,
# adaptive pooling, col2im. Reference: nd4j-api ops/impl/image (RgbToHsv,
# RgbToYiq, RgbToYuv, AdjustHue, AdjustSaturation), SDCNN, and the keras/
# torch adaptive-pooling semantics DL4J users expect via model import.

_YIQ_M = jnp.array([[0.299, 0.587, 0.114],
                    [0.59590059, -0.27455667, -0.32134392],
                    [0.21153661, -0.52273617, 0.31119955]], jnp.float32)
_YUV_M = jnp.array([[0.299, 0.587, 0.114],
                    [-0.14714119, -0.28886916, 0.43601035],
                    [0.61497538, -0.51496512, -0.10001026]], jnp.float32)
# constant inverses precomputed once at import (not per call/trace)
import numpy  # noqa: E402
_YIQ_INV = jnp.asarray(numpy.linalg.inv(numpy.asarray(_YIQ_M)))
_YUV_INV = jnp.asarray(numpy.linalg.inv(numpy.asarray(_YUV_M)))


def _rgb_to_hsv(rgb):
    """Channel-last float rgb in [0,1] -> hsv (same shape/convention as
    tf.image.rgb_to_hsv)."""
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    mx = jnp.max(rgb, -1)
    mn = jnp.min(rgb, -1)
    d = mx - mn
    safe = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0))
    h = jnp.where(d == 0, 0.0, h) / 6.0
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], -1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0] * 6.0, hsv[..., 1], hsv[..., 2]
    i = jnp.floor(h)
    f = h - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], -1)


def _adjust_hue(img, delta):
    hsv = _rgb_to_hsv(img)
    h = (hsv[..., 0] + delta) % 1.0
    return _hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], -1))


def _adjust_saturation(img, factor):
    hsv = _rgb_to_hsv(img)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return _hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], -1))


def _group_norm(x, gamma, beta, groups, eps=1e-5):
    """Channel-last group norm: normalize over all non-batch dims within
    each channel group (tf-addons/torch GroupNorm semantics)."""
    shp = x.shape
    c = shp[-1]
    g = int(groups)
    xg = x.reshape(shp[0], -1, g, c // g)          # (B, spatial, G, C/G)
    mu = jnp.mean(xg, (1, 3), keepdims=True)
    var = jnp.var(xg, (1, 3), keepdims=True)
    xn = ((xg - mu) * lax.rsqrt(var + eps)).reshape(shp)
    return xn * gamma + beta


def _instance_norm(x, gamma, beta, eps=1e-5):
    """Channel-last instance norm: normalize each (sample, channel) over
    the spatial dims."""
    axes = tuple(range(1, x.ndim - 1))
    mu = jnp.mean(x, axes, keepdims=True)
    var = jnp.var(x, axes, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * gamma + beta


def _adaptive_pool2d(x, out_h, out_w, op):
    """torch adaptive_{avg,max}_pool2d semantics, NHWC: output cell (i,j)
    pools input[floor(i*H/oh):ceil((i+1)*H/oh), ...]. Static out sizes."""
    B, H, W, C = x.shape
    oh, ow = int(out_h), int(out_w)
    rows = []
    for i in range(oh):
        h0, h1 = (i * H) // oh, -((-(i + 1) * H) // oh)
        cols = []
        for j in range(ow):
            w0, w1 = (j * W) // ow, -((-(j + 1) * W) // ow)
            win = x[:, h0:h1, w0:w1, :]
            cols.append(op(win, axis=(1, 2)))
        rows.append(jnp.stack(cols, 1))
    return jnp.stack(rows, 1)


def _sd_col2im(cols, x_shape, kh, kw, sh=1, sw=1):
    from ..ndarray.factory import col2im as _c2i
    return _c2i(cols, tuple(x_shape), (int(kh), int(kw)),
                (int(sh), int(sw)))


IMAGE.update({
    "rgb_to_hsv": _rgb_to_hsv,
    "hsv_to_rgb": _hsv_to_rgb,
    "rgb_to_yiq": lambda x: jnp.einsum("...c,kc->...k", x, _YIQ_M),
    "yiq_to_rgb": lambda x: jnp.einsum("...c,kc->...k", x, _YIQ_INV),
    "rgb_to_yuv": lambda x: jnp.einsum("...c,kc->...k", x, _YUV_M),
    "yuv_to_rgb": lambda x: jnp.einsum("...c,kc->...k", x, _YUV_INV),
    "adjust_hue": _adjust_hue,
    "adjust_saturation": _adjust_saturation,
})

NN_EXT.update({
    "group_norm": _group_norm,
    "instance_norm": _instance_norm,
})

CNN.update({
    "adaptive_avg_pooling2d": lambda x, oh, ow: _adaptive_pool2d(
        x, oh, ow, jnp.mean),
    "adaptive_max_pooling2d": lambda x, oh, ow: _adaptive_pool2d(
        x, oh, ow, jnp.max),
    "col2im": _sd_col2im,
})


# -------------------------------------------------------- r3 widening ------
# Spectral (SDMath.fft/ifft/rfft/... — the family VERDICT r2 flagged absent)
# plus a broad pass over SDBaseOps/SDMath/SDLinalg/SDNN/SDCNN/SDImage/
# SDRandom/SDLoss/SDBitwise long-tail ops. All pure jnp/lax, jit-traceable;
# FFTs lower to the XLA FFT HLO (native on TPU).

FFT = {
    "fft": lambda x, n=None, axis=-1: jnp.fft.fft(x, n, axis),
    "ifft": lambda x, n=None, axis=-1: jnp.fft.ifft(x, n, axis),
    "rfft": lambda x, n=None, axis=-1: jnp.fft.rfft(x, n, axis),
    "irfft": lambda x, n=None, axis=-1: jnp.fft.irfft(x, n, axis),
    "hfft": lambda x, n=None, axis=-1: jnp.fft.hfft(x, n, axis),
    "ihfft": lambda x, n=None, axis=-1: jnp.fft.ihfft(x, n, axis),
    "fft2": lambda x, axes=(-2, -1): jnp.fft.fft2(x, axes=_axes(axes)),
    "ifft2": lambda x, axes=(-2, -1): jnp.fft.ifft2(x, axes=_axes(axes)),
    "rfft2": lambda x, axes=(-2, -1): jnp.fft.rfft2(x, axes=_axes(axes)),
    "irfft2": lambda x, axes=(-2, -1): jnp.fft.irfft2(x, axes=_axes(axes)),
    "fftn": lambda x, axes=None: jnp.fft.fftn(x, axes=_axes(axes)),
    "ifftn": lambda x, axes=None: jnp.fft.ifftn(x, axes=_axes(axes)),
    "rfftn": lambda x, axes=None: jnp.fft.rfftn(x, axes=_axes(axes)),
    "irfftn": lambda x, axes=None: jnp.fft.irfftn(x, axes=_axes(axes)),
    "fftshift": lambda x, axes=None: jnp.fft.fftshift(x, _axes(axes)),
    "ifftshift": lambda x, axes=None: jnp.fft.ifftshift(x, _axes(axes)),
    "fftfreq": lambda n, d=1.0: jnp.fft.fftfreq(int(n), d),
    "rfftfreq": lambda n, d=1.0: jnp.fft.rfftfreq(int(n), d),
}

# complex-number surface the FFT family needs (upstream: CreateComplex /
# RealDivide etc. live in SDMath)
MATH_EXT.update({
    "real": jnp.real, "imag": jnp.imag, "conj": jnp.conj,
    "angle": jnp.angle,
    "complex": lambda re, im: lax.complex(re, im),
    "complex_abs": lambda x: jnp.abs(x),
    "unwrap": lambda p, axis=-1: jnp.unwrap(p, axis=axis),
    # signal-adjacent 1-D ops
    "convolve": lambda a, v, mode="full": jnp.convolve(a, v, mode=mode),
    "correlate": lambda a, v, mode="full": jnp.correlate(a, v, mode=mode),
    "trapz": lambda y, x=None, dx=1.0, axis=-1: jnp.trapezoid(
        y, x, dx=dx, axis=axis),
    # elementwise long tail
    "sinc": jnp.sinc, "signbit": jnp.signbit, "nextafter": jnp.nextafter,
    "fabs": jnp.fabs, "gcd": jnp.gcd, "lcm": jnp.lcm,
    "fmax": jnp.fmax, "fmin": jnp.fmin,
    "float_power": jnp.float_power,
    "divmod": jnp.divmod, "modf": jnp.modf,
    "cummax": lambda x, axis=0: lax.cummax(x, axis=int(axis)),
    "cummin": lambda x, axis=0: lax.cummin(x, axis=int(axis)),
    "relative_error": lambda a, b, eps=1e-12: jnp.abs(a - b) / jnp.maximum(
        jnp.maximum(jnp.abs(a), jnp.abs(b)), eps),
    "polyval": lambda p, x: jnp.polyval(jnp.asarray(p), x),
    "ediff1d": lambda x: jnp.ediff1d(x),
    "select": lambda conds, vals, default=0.0: jnp.select(
        list(conds), list(vals), default),
    # special functions
    "i0": jsp.i0, "i0e": jsp.i0e, "i1": jsp.i1, "i1e": jsp.i1e,
    "betaln": jsp.betaln,
    "gamma_fn": jsp.gamma,
    "factorial": jsp.factorial,
    "ndtr": jsp.ndtr, "ndtri": jsp.ndtri, "log_ndtr": jsp.log_ndtr,
    "rel_entr": jsp.rel_entr, "kl_div_elem": jsp.kl_div,
    "spence": jsp.spence,
})

def _histogram_fixed_width(x, range_, nbins):
    lo, hi = range_
    idx = jnp.clip(((x - lo) / (hi - lo) * nbins).astype(jnp.int32),
                   0, int(nbins) - 1)
    return jnp.bincount(idx.ravel(), length=int(nbins))


def _nonzero(x, size):
    return jnp.nonzero(jnp.asarray(x).ravel(), size=int(size),
                       fill_value=-1)[0]


def _matrix_set_diag(x, diag):
    """Replace the main diagonal of the last two dims with ``diag`` of
    length min(m, n) (tf.linalg.set_diag / upstream MatrixSetDiag);
    rectangular matrices supported."""
    m, nn = x.shape[-2], x.shape[-1]
    k = jnp.arange(min(m, nn))
    return jnp.asarray(x).at[..., k, k].set(jnp.asarray(diag))


def _scatter_nd_onto(op):
    def f(ref, indices, updates):
        idx = jnp.asarray(indices).astype(jnp.int32)
        at = jnp.asarray(ref).at[tuple(idx[..., i]
                                       for i in range(idx.shape[-1]))]
        return getattr(at, op)(jnp.asarray(updates))
    return f


BASE.update({
    # nan-aware reductions (upstream has nan-skipping reduce modes)
    "nanmax": lambda x, *axes: jnp.nanmax(x, _axes(axes) or None),
    "nanmin": lambda x, *axes: jnp.nanmin(x, _axes(axes) or None),
    "nansum": lambda x, *axes: jnp.nansum(x, _axes(axes) or None),
    "nanmean": lambda x, *axes: jnp.nanmean(x, _axes(axes) or None),
    "nanstd": lambda x, *axes: jnp.nanstd(x, _axes(axes) or None),
    "nanvar": lambda x, *axes: jnp.nanvar(x, _axes(axes) or None),
    # order statistics
    "percentile": lambda x, q, axis=None: jnp.percentile(
        x, q, axis=_axes(axis)),
    "quantile": lambda x, q, axis=None: jnp.quantile(x, q, axis=_axes(axis)),
    "median": lambda x, axis=None: jnp.median(x, axis=_axes(axis)),
    "ptp": lambda x, axis=None: jnp.max(x, _axes(axis)) - jnp.min(
        x, _axes(axis)),
    "average": lambda x, weights=None, axis=None: jnp.average(
        x, axis=_axes(axis), weights=weights),
    "histogram_fixed_width": _histogram_fixed_width,
    "digitize": lambda x, bins: jnp.digitize(x, jnp.asarray(bins)),
    # stacking / shaping long tail
    "hstack": lambda *xs: jnp.hstack(xs),
    "vstack": lambda *xs: jnp.vstack(xs),
    "dstack": lambda *xs: jnp.dstack(xs),
    "column_stack": lambda *xs: jnp.column_stack(xs),
    "atleast_1d": jnp.atleast_1d,
    "atleast_3d": jnp.atleast_3d,
    "split_sizes": lambda x, sizes, axis=0: jnp.split(
        x, list(numpy.cumsum(sizes))[:-1], axis=int(axis)),
    "eye_like": lambda x: jnp.eye(x.shape[-2], x.shape[-1], dtype=x.dtype),
    "tril_indices": lambda n, k=0: jnp.tril_indices(int(n), int(k)),
    "triu_indices": lambda n, k=0: jnp.triu_indices(int(n), int(k)),
    "nonzero": _nonzero,
    "take": lambda x, idx, axis=None: jnp.take(
        x, jnp.asarray(idx).astype(jnp.int32), axis=axis),
    "batch_gather": lambda x, idx: jax.vmap(
        lambda p, i: jnp.take(p, i, axis=0))(
        x, jnp.asarray(idx).astype(jnp.int32)),
    "isin": lambda x, test: jnp.isin(x, jnp.asarray(test)),
    # scatter-nd family onto an existing tensor (upstream scatterNdAdd/...)
    "scatter_nd_add": _scatter_nd_onto("add"),
    "scatter_nd_sub": lambda ref, i, u: _scatter_nd_onto("add")(
        ref, i, -jnp.asarray(u)),
    "scatter_nd_update": _scatter_nd_onto("set"),
    "matrix_set_diag": _matrix_set_diag,
})

LINALG.update({
    "block_diag": jsl.block_diag,
    "toeplitz": jsl.toeplitz,
    "sqrtm": jsl.sqrtm,
    "cho_factor": lambda a, lower=True: jsl.cho_factor(a, lower=lower)[0],
    "cho_solve": lambda c, b, lower=True: jsl.cho_solve((c, lower), b),
    "lu_factor": lambda a: jsl.lu_factor(a),   # (LU, piv) — piv is required
                                               # to reconstruct/solve
    "lu_solve": lambda a, b: jsl.lu_solve(jsl.lu_factor(a), b),
    "multi_dot": lambda *ms: jnp.linalg.multi_dot(list(ms)),
    "cond": jnp.linalg.cond,
    "svdvals": lambda a: jnp.linalg.svd(a, compute_uv=False),
    "norm_nuclear": lambda a: jnp.sum(jnp.linalg.svd(a, compute_uv=False),
                                      -1),
    "vander": lambda x, n=None: jnp.vander(x, n),
    "khatri_rao": lambda a, b: jnp.einsum("ik,jk->ijk", a, b).reshape(
        a.shape[0] * b.shape[0], a.shape[1]),
}) 

NN_EXT.update({
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),
    "hard_shrink": lambda x, lambd=0.5: jnp.where(jnp.abs(x) > lambd, x, 0.0),
    "soft_shrink": lambda x, lambd=0.5: jnp.sign(x) * jax.nn.relu(
        jnp.abs(x) - lambd),
    "tanh_shrink": lambda x: x - jnp.tanh(x),
    "threshold": lambda x, threshold, value: jnp.where(
        x > threshold, x, value),
    "lp_normalize": lambda x, p=2, axis=-1, eps=1e-12: x / jnp.maximum(
        jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p), eps),
    "pairwise_distance": lambda a, b, p=2.0, eps=1e-6: jnp.sum(
        jnp.abs(a - b + eps) ** p, -1) ** (1.0 / p),
    "gumbel_softmax": lambda key, logits, tau=1.0: jax.nn.softmax(
        (logits + jax.random.gumbel(key, logits.shape)) / tau, -1),
    "swiglu": lambda x, axis=-1: (lambda a, b: jax.nn.silu(a) * b)(
        *jnp.split(x, 2, axis=axis)),
    "alpha_dropout_train": lambda key, x, rate: _alpha_dropout(key, x, rate),
    "spatial_dropout_train": lambda key, x, rate: x * jax.random.bernoulli(
        key, 1 - rate, (x.shape[0],) + (1,) * (x.ndim - 2)
        + (x.shape[-1],)) / (1 - rate),
})


def _alpha_dropout(key, x, rate):
    """SELU-preserving alpha dropout (Klambauer et al.; torch
    AlphaDropout): dropped units go to alpha' = -scale*alpha, then an
    affine correction restores zero mean / unit variance."""
    keep = 1.0 - rate
    alpha_p = -1.7580993408473766
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * (1 - keep) * alpha_p
    return a * jnp.where(mask, x, alpha_p) + b


def _max_pool_with_argmax(x, k, s=None, padding="VALID"):
    """(values, flat argmax indices within each window) — tf
    MaxPoolWithArgmax-style, NHWC, via extracted patches."""
    kh, kw = (k, k) if isinstance(k, int) else tuple(k)
    s = (kh, kw) if s is None else ((s, s) if isinstance(s, int)
                                    else tuple(s))
    c = x.shape[-1]
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(s), padding, dimension_numbers=_DN2D)
    b, oh, ow, _ = patches.shape
    # patches feature dim is (C, kh*kw) interleaved channel-major
    p = patches.reshape(b, oh, ow, c, kh * kw)
    return p.max(-1), p.argmax(-1).astype(jnp.int32)


def _lp_pool2d(x, k, s=None, p=2.0, padding="VALID"):
    kh, kw = (k, k) if isinstance(k, int) else tuple(k)
    s = (kh, kw) if s is None else ((s, s) if isinstance(s, int)
                                    else tuple(s))
    summed = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add,
                               (1, kh, kw, 1), (1, *s, 1), padding)
    return summed ** (1.0 / p)


CNN.update({
    "deconv1d": lambda x, w, stride=2, padding="SAME": lax.conv_transpose(
        x, w, (stride,), padding, dimension_numbers=_DN1D),
    "deconv3d": lambda x, w, stride=(2, 2, 2), padding="SAME":
        lax.conv_transpose(x, w, tuple(stride), padding,
                           dimension_numbers=_DN3D),
    "max_pool_with_argmax": _max_pool_with_argmax,
    "lp_pool2d": _lp_pool2d,
    "pixel_shuffle": lambda x, r: BASE["depth_to_space"](x, int(r)),
    "pixel_unshuffle": lambda x, r: BASE["space_to_depth"](x, int(r)),
    "upsampling1d": lambda x, scale=2: jnp.repeat(x, int(scale), axis=1),
    "upsampling3d": lambda x, scale=2: jnp.repeat(jnp.repeat(jnp.repeat(
        x, int(scale), axis=1), int(scale), axis=2), int(scale), axis=3),
})


def _sobel_edges(img):
    """(B,H,W,C) -> (B,H,W,C,2) [dy, dx] — tf.image.sobel_edges kernels."""
    ky = jnp.asarray([[-1, -2, -1], [0, 0, 0], [1, 2, 1]], img.dtype)
    kx = jnp.asarray([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]], img.dtype)
    c = img.shape[-1]
    k = jnp.stack([ky, kx], -1)                      # (3,3,2)
    w = jnp.zeros((3, 3, c, 2 * c), img.dtype)
    for ch in range(c):
        w = w.at[:, :, ch, 2 * ch:2 * ch + 2].set(k)
    padded = jnp.pad(img, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="reflect")
    out = lax.conv_general_dilated(padded, w, (1, 1), "VALID",
                                   dimension_numbers=_DN2D)
    return out.reshape(img.shape[:-1] + (c, 2))


def _image_gradients(img):
    dy = jnp.concatenate([img[:, 1:] - img[:, :-1],
                          jnp.zeros_like(img[:, :1])], 1)
    dx = jnp.concatenate([img[:, :, 1:] - img[:, :, :-1],
                          jnp.zeros_like(img[:, :, :1])], 2)
    return dy, dx


IMAGE.update({
    "sobel_edges": _sobel_edges,
    "image_gradients": _image_gradients,
    "adjust_gamma": lambda x, gamma=1.0, gain=1.0: gain * x ** gamma,
    "grayscale_to_rgb": lambda x: jnp.broadcast_to(
        x, x.shape[:-1] + (3,)),
    "rgb_to_bgr": lambda x: x[..., ::-1],
    "total_variation": lambda x: (
        jnp.sum(jnp.abs(x[:, 1:] - x[:, :-1]), axis=(1, 2, 3))
        + jnp.sum(jnp.abs(x[:, :, 1:] - x[:, :, :-1]), axis=(1, 2, 3))),
    "pad_to_bounding_box": lambda x, off_h, off_w, th, tw: jnp.pad(
        x, ((0, 0), (int(off_h), int(th) - x.shape[1] - int(off_h)),
            (int(off_w), int(tw) - x.shape[2] - int(off_w)), (0, 0))),
    "crop_to_bounding_box": lambda x, off_h, off_w, th, tw: x[
        :, int(off_h):int(off_h) + int(th),
        int(off_w):int(off_w) + int(tw), :],
})

RANDOM.update({
    "dirichlet": lambda key, alpha, shape=(): jax.random.dirichlet(
        key, jnp.asarray(alpha), _axes(shape) or ()),
    "multivariate_normal": lambda key, mean, cov, shape=():
        jax.random.multivariate_normal(key, mean, cov, _axes(shape) or None),
    "student_t": lambda key, df, shape: jax.random.t(key, df, _axes(shape)),
    "chisquare": lambda key, df, shape: jax.random.chisquare(
        key, df, shape=_axes(shape)),
    "rayleigh": lambda key, scale, shape: jax.random.rayleigh(
        key, scale, shape=_axes(shape)),
    "logistic": lambda key, shape: jax.random.logistic(key, _axes(shape)),
    "pareto": lambda key, b, shape: jax.random.pareto(key, b, shape=_axes(shape)),
    "geometric": lambda key, p, shape: jax.random.geometric(
        key, p, shape=_axes(shape)),
    "rademacher": lambda key, shape: jax.random.rademacher(
        key, _axes(shape)),
})

LOSS_EXT.update({
    "dice_loss": lambda labels, preds, eps=1e-7: 1.0 - (
        2.0 * jnp.sum(labels * preds) + eps) / (
        jnp.sum(labels) + jnp.sum(preds) + eps),
    "log_cosh_loss": lambda labels, preds: jnp.mean(
        jnp.log(jnp.cosh(preds - labels))),
    "quantile_loss": lambda labels, preds, q=0.5: jnp.mean(jnp.maximum(
        q * (labels - preds), (q - 1.0) * (labels - preds))),
    "triplet_margin_loss": lambda anchor, pos, neg, margin=1.0: jnp.mean(
        jax.nn.relu(jnp.linalg.norm(anchor - pos, axis=-1)
                    - jnp.linalg.norm(anchor - neg, axis=-1) + margin)),
    "margin_ranking_loss": lambda x1, x2, y, margin=0.0: jnp.mean(
        jax.nn.relu(-y * (x1 - x2) + margin)),
    "cosine_embedding_loss": lambda x1, x2, y, margin=0.0: jnp.mean(
        jnp.where(y > 0,
                  1.0 - MATH_EXT["cosine_similarity"](x1, x2),
                  jax.nn.relu(MATH_EXT["cosine_similarity"](x1, x2)
                              - margin))),
})

BITWISE.update({
    "set_bit": lambda x, pos: x | (jnp.ones_like(x) << pos),
    "clear_bit": lambda x, pos: x & ~(jnp.ones_like(x) << pos),
    "toggle_bit": lambda x, pos: x ^ (jnp.ones_like(x) << pos),
    "test_bit": lambda x, pos: (lax.shift_right_logical(x, pos) & 1) != 0,
})

NAMESPACES["fft"] = FFT

# upstream SDMath exposes the 1-D spectral ops directly on math as well
MATH_EXT.update({
    "fft": FFT["fft"], "ifft": FFT["ifft"],
    "rfft": FFT["rfft"], "irfft": FFT["irfft"],
})


# -------------------------------------------------------- r4 widening #4 --
# Upstream name audit vs SDBaseOps/SDMath/SDNN/SDCNN/SDRNN/SDLinalg/SDImage/
# SDLoss (VERDICT r3 item 4): conditional-replace family, all-pairs reduce3
# distances, SRU/LSTM-block recurrences, morphological conv, quantization,
# drawing/NMS-overlaps image ops, the nn.losses catalog exposed as SD loss
# ops, and the SDMath scalar tail (cube, lerp, rationalTanh, firstIndex...).

def _cond_mask(x, cond, value=0.0):
    """Upstream `Condition` objects (EqualsCondition, GreaterThan, ...) as a
    static string + threshold — returns the boolean mask."""
    c = str(cond).lower()
    table = {
        "eq": lambda: x == value, "neq": lambda: x != value,
        "gt": lambda: x > value, "gte": lambda: x >= value,
        "lt": lambda: x < value, "lte": lambda: x <= value,
        "abs_gt": lambda: jnp.abs(x) > value,
        "abs_lt": lambda: jnp.abs(x) < value,
        "is_nan": lambda: jnp.isnan(x), "is_inf": lambda: jnp.isinf(x),
        "not_finite": lambda: ~jnp.isfinite(x),
    }
    if c not in table:
        raise ValueError(f"unknown condition '{cond}' "
                         f"(known: {sorted(table)})")
    return table[c]()


def _replace_where(x, replacement, cond, value=0.0):
    """SDBaseOps.replaceWhere: elements satisfying the condition are taken
    from `replacement` (array or scalar)."""
    return jnp.where(_cond_mask(x, cond, value),
                     jnp.broadcast_to(jnp.asarray(replacement, x.dtype),
                                      x.shape), x)


def _compare_and_set(x, compare, set_value, eps=1e-7):
    """nd4j CompareAndSet: where |x - compare| <= eps, write set_value."""
    return jnp.where(jnp.abs(x - compare) <= eps,
                     jnp.asarray(set_value, x.dtype), x)


def _first_index(x, cond, value=0.0):
    """SDMath.firstIndex: first flat index satisfying condition, -1 if none."""
    m = _cond_mask(jnp.ravel(x), cond, value)
    idx = jnp.argmax(m)
    return jnp.where(jnp.any(m), idx, -1).astype(jnp.int32)


def _last_index(x, cond, value=0.0):
    m = _cond_mask(jnp.ravel(x), cond, value)
    n = m.shape[0]
    idx = n - 1 - jnp.argmax(m[::-1])
    return jnp.where(jnp.any(m), idx, -1).astype(jnp.int32)


def _merge_max_index(*xs):
    """nd4j MergeMaxIndex: elementwise argmax across the input list."""
    return jnp.argmax(jnp.stack(xs), axis=0).astype(jnp.int32)


def _rational_tanh(x):
    """nd4j RationalTanh: 1.7159 * a(2x/3) with the quartic rational
    approximation a(y) = sgn(y) * (1 - 1/(1 + |y| + y^2 + 1.41645 y^4))."""
    y = 2.0 * x / 3.0
    a = 1.0 - 1.0 / (1.0 + jnp.abs(y) + y * y + 1.41645 * y ** 4)
    return 1.7159 * jnp.sign(y) * a


def _check_numerics(x, message="CheckNumerics failed"):
    """nd4j CheckNumerics: pass-through that fails on NaN/Inf. Concrete
    arrays raise immediately; under jit the check rides jax's debug
    machinery (error surfaces on fetch with jax_debug_nans)."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        return x                       # ints/bools are always finite
    if not isinstance(x, jax.core.Tracer):
        if not bool(jnp.isfinite(x).all()):
            raise FloatingPointError(f"{message}: non-finite values present")
        return x
    return jax.lax.cond(jnp.isfinite(x).all(), lambda v: v,
                        lambda v: v * jnp.asarray(jnp.nan, x.dtype),
                        x)  # poison, not silence


def _all_pairs(fn):
    """reduce3 all-distances (upstream allEuclidean/allManhattan/...):
    x (N, D), y (M, D) -> (N, M) via vmap over both sides."""
    return lambda x, y: jax.vmap(
        lambda xi: jax.vmap(lambda yj: fn(xi, yj))(y))(x)


BASE.update({
    "replace_where": _replace_where,
    "compare_and_set": _compare_and_set,
    "standard_deviation": BASE["std"],        # SDBaseOps.standardDeviation
    "histogram": lambda x, nbins, range=None: jnp.histogram(
        x, bins=int(nbins), range=range)[0],
    "check_numerics": _check_numerics,
})

MATH_EXT.update({
    "cube": lambda x: x * x * x,
    "lerp": lambda a, b, w: a + w * (b - a),
    "rational_tanh": _rational_tanh,
    "rectified_tanh": lambda x: jnp.maximum(jnp.tanh(x), 0.0),
    "first_index": _first_index,
    "last_index": _last_index,
    "merge_max_index": _merge_max_index,
    "all_euclidean": _all_pairs(
        lambda a, b: jnp.sqrt(jnp.sum(jnp.square(a - b)))),
    "all_manhattan": _all_pairs(lambda a, b: jnp.sum(jnp.abs(a - b))),
    "all_cosine_similarity": _all_pairs(
        lambda a, b: jnp.dot(a, b)
        / jnp.maximum(jnp.linalg.norm(a) * jnp.linalg.norm(b), 1e-12)),
    "all_cosine_distance": _all_pairs(
        lambda a, b: 1.0 - jnp.dot(a, b)
        / jnp.maximum(jnp.linalg.norm(a) * jnp.linalg.norm(b), 1e-12)),
    "all_dot": _all_pairs(jnp.dot),
    "all_hamming": _all_pairs(lambda a, b: jnp.sum(a != b)),
    "all_jaccard": _all_pairs(lambda a, b: 1.0 - jnp.sum(
        jnp.minimum(a, b)) / jnp.maximum(jnp.sum(jnp.maximum(a, b)), 1e-12)),
})


# ---- quantization (upstream FakeQuantWithMinMax*, tf parity) --------------
def _fake_quant(x, min=-6.0, max=6.0, num_bits=8, narrow_range=False):
    qmin = 1 if narrow_range else 0
    qmax = 2 ** int(num_bits) - 1
    # nudge range so zero is exactly representable (TF semantics)
    scale = (max - min) / (qmax - qmin)
    zero = qmin - min / scale
    nudged_zero = jnp.clip(jnp.round(zero), qmin, qmax)
    nudged_min = (qmin - nudged_zero) * scale
    nudged_max = (qmax - nudged_zero) * scale
    clipped = jnp.clip(x, nudged_min, nudged_max)
    q = jnp.round((clipped - nudged_min) / scale)
    return q * scale + nudged_min


def _quantize(x, scale, zero_point, num_bits=8, signed=False):
    qmin = -(2 ** (num_bits - 1)) if signed else 0
    qmax = 2 ** (num_bits - 1) - 1 if signed else 2 ** num_bits - 1
    return jnp.clip(jnp.round(x / scale) + zero_point, qmin, qmax).astype(
        jnp.int8 if signed and num_bits <= 8 else
        jnp.uint8 if num_bits <= 8 else jnp.int32)


NN_EXT.update({
    "crelu": lambda x, axis=-1: jnp.concatenate(
        [jax.nn.relu(x), jax.nn.relu(-x)], axis=axis),
    "relu_layer": lambda x, w, b: jax.nn.relu(x @ w + b),
    "fake_quant_with_min_max_args": _fake_quant,
    "fake_quant_with_min_max_vars": _fake_quant,   # vars = traced min/max
    "quantize": _quantize,
    "dequantize": lambda q, scale, zero_point: (
        q.astype(jnp.float32) - zero_point) * scale,
})


# ---- SRU / LSTM-block recurrences (upstream SDRNN sru/sruCell/lstmblock) --
def _sru_cell(x, c, w, b):
    """Simple Recurrent Unit cell (Lei et al. 2017; upstream sruCell):
    w packs [W, Wf, Wr] as (D, 3D); b packs [bf, br] as (2D,)."""
    d = c.shape[-1]
    z = x @ w
    xt, f_in, r_in = z[..., :d], z[..., d:2 * d], z[..., 2 * d:]
    f = jax.nn.sigmoid(f_in + b[:d])
    r = jax.nn.sigmoid(r_in + b[d:])
    c2 = f * c + (1.0 - f) * xt
    h = r * jnp.tanh(c2) + (1.0 - r) * x[..., :d]
    return h, c2


def _sru(x, c0, w, b):
    """SRU over a full (B, T, D) sequence; the elementwise recurrence is
    the lax.scan body — the matmuls batch over T in one shot first (the
    property that makes SRU fast: no per-step matmul)."""
    d = c0.shape[-1]
    z = x @ w                                  # (B, T, 3D) in one matmul
    f = jax.nn.sigmoid(z[..., d:2 * d] + b[:d])
    r = jax.nn.sigmoid(z[..., 2 * d:] + b[d:])
    xt = z[..., :d]

    def body(c, inp):
        xt_t, f_t, r_t, x_t = inp
        c2 = f_t * c + (1.0 - f_t) * xt_t
        h = r_t * jnp.tanh(c2) + (1.0 - r_t) * x_t[..., :d]
        return c2, h

    _, hs = lax.scan(body, c0, tuple(
        jnp.swapaxes(v, 0, 1) for v in (xt, f, r, x)))
    return jnp.swapaxes(hs, 0, 1)


RNN.update({
    "sru_cell": _sru_cell,
    "sru": _sru,
    "simple_rnn_layer": _rnn_layer(cell_has_c=False,
                                   cell=RNN["simple_rnn_cell"]),
    "lstm_block_cell": _lstm_cell,   # upstream LSTMBlockCell = fused gates,
    "lstm_block": _rnn_layer(cell_has_c=True),  # which our cell already is
})


# ---- morphological conv (upstream/tf Dilation2D; erosion as its dual) -----
def _dilation2d(x, filt, strides=(1, 1), rates=(1, 1), padding="SAME"):
    """x (B, H, W, C), filt (kh, kw, C): out[b,y,x,c] =
    max_{dy,dx}(in[b, y*s+dy*r, x*s+dx*r, c] + filt[dy, dx, c])."""
    kh, kw = filt.shape[0], filt.shape[1]
    sh, sw = strides
    rh, rw = rates
    if padding.upper() == "SAME":
        # TF SAME formula: pad = max((ceil(in/s) - 1)*s + eff - in, 0) —
        # with stride > 1 this differs from eff-1 and misaligns otherwise
        eff_h, eff_w = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        ph = max((-(-x.shape[1] // sh) - 1) * sh + eff_h - x.shape[1], 0)
        pw = max((-(-x.shape[2] // sw) - 1) * sw + eff_w - x.shape[2], 0)
        x = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                        (pw // 2, pw - pw // 2), (0, 0)),
                    constant_values=-jnp.inf)
    h_out = (x.shape[1] - (kh - 1) * rh - 1) // sh + 1
    w_out = (x.shape[2] - (kw - 1) * rw - 1) // sw + 1
    taps = []
    for dy in range(kh):
        for dx in range(kw):
            sl = x[:, dy * rh:dy * rh + h_out * sh:sh,
                   dx * rw:dx * rw + w_out * sw:sw, :]
            taps.append(sl + filt[dy, dx])
    return jnp.max(jnp.stack(taps), axis=0)


def _erosion2d(x, filt, strides=(1, 1), rates=(1, 1), padding="SAME"):
    """Morphological dual: erosion(x, f) = -dilation(-x, reverse(f))."""
    return -_dilation2d(-x, filt[::-1, ::-1], strides, rates, padding)


CNN.update({
    "dilation2d": _dilation2d,
    "erosion2d": _erosion2d,
    "pnorm_pool2d": CNN["lp_pool2d"],          # upstream pnormpool2d name
})


# ---- image: NMS-with-overlaps, area resize, box drawing -------------------
def _nms_overlaps(overlaps, scores, max_out, overlap_threshold=0.5,
                  score_threshold=-jnp.inf):
    """tf.image.non_max_suppression_overlaps: greedy NMS where the (N, N)
    overlap matrix is supplied by the caller instead of IoU from boxes."""
    n = scores.shape[0]

    def body(state, _):
        live, count = state
        masked = jnp.where(live, scores, -jnp.inf)
        i = jnp.argmax(masked)
        ok = jnp.logical_and(masked[i] > score_threshold,
                             jnp.isfinite(masked[i]))
        suppress = overlaps[i] > overlap_threshold
        live = jnp.where(ok, jnp.logical_and(live, ~suppress), live)
        live = live.at[i].set(False)
        return (live, count + ok.astype(jnp.int32)), \
            jnp.where(ok, i, -1).astype(jnp.int32)

    (_, count), idx = lax.scan(body, (jnp.ones(n, bool), jnp.int32(0)),
                               None, length=int(max_out))
    return idx, count


def _resize_area(x, h, w):
    """Area resize: exact block-mean for integer downscale factors, else
    bilinear (jax.image has no area kernel; integer-factor block mean IS
    the area method, which is the common use — avg-pool downscaling)."""
    b, ih, iw, c = x.shape
    h, w = int(h), int(w)
    if ih % h == 0 and iw % w == 0:
        fh, fw = ih // h, iw // w
        return x.reshape(b, h, fh, w, fw, c).mean(axis=(2, 4))
    return jax.image.resize(x, (b, h, w, c), method="linear")


def _draw_bounding_boxes(images, boxes, colors=None):
    """tf.image.draw_bounding_boxes: boxes (B, N, 4) normalized
    [y1, x1, y2, x2]; draws 1px outlines. Static N (XLA); color cycles
    through `colors` (K, C) or defaults to max-intensity channel 0."""
    b, h, w, c = images.shape
    n = boxes.shape[1]
    if colors is None:
        colors = jnp.zeros((1, c), images.dtype).at[0, 0].set(
            jnp.asarray(1.0, images.dtype))
    colors = jnp.asarray(colors, images.dtype)
    ys = jnp.arange(h)[:, None]                  # (H, 1)
    xs = jnp.arange(w)[None, :]                  # (1, W)

    def draw_one(img, box, color):
        # TF truncates (int cast), not rounds
        y1 = (box[0] * (h - 1)).astype(jnp.int32)
        x1 = (box[1] * (w - 1)).astype(jnp.int32)
        y2 = (box[2] * (h - 1)).astype(jnp.int32)
        x2 = (box[3] * (w - 1)).astype(jnp.int32)
        in_y = (ys >= y1) & (ys <= y2)
        in_x = (xs >= x1) & (xs <= x2)
        edge = (in_y & in_x) & (
            (ys == y1) | (ys == y2) | (xs == x1) | (xs == x2))
        return jnp.where(edge[..., None], color, img)

    def per_image(img, bxs):
        def body(im, i):
            return draw_one(im, bxs[i], colors[i % colors.shape[0]]), None
        out, _ = lax.scan(body, img, jnp.arange(n))
        return out

    return jax.vmap(per_image)(images, boxes)


IMAGE.update({
    "non_max_suppression_overlaps": _nms_overlaps,
    "resize_area": _resize_area,
    "draw_bounding_boxes": _draw_bounding_boxes,
})


# ---- the nn.losses catalog as SD loss ops (upstream exposes both) ---------
def _wrap_loss(name):
    from ..nn import losses as _nnl
    return _nnl.get(name)


LOSS_EXT.update({
    "mean_pairwise_squared_error": lambda labels, preds: jnp.mean(jax.vmap(
        lambda d: (jnp.sum(jnp.square(d[:, None] - d[None, :])) / 2.0)
        / jnp.maximum(d.shape[0] * (d.shape[0] - 1) / 2.0, 1.0))(
        (preds - labels).reshape(labels.shape[0], -1))),
    "multi_label_loss": _wrap_loss("multi_label"),
    "mae_loss": _wrap_loss("mae"),
    "mape_loss": _wrap_loss("mape"),
    "msle_loss": _wrap_loss("msle"),
    "wasserstein_loss": _wrap_loss("wasserstein"),
    "fmeasure_loss": _wrap_loss("fmeasure"),
    "mixture_density_loss": _wrap_loss("mixture_density"),
})

LINALG.update({
    "adjoint": lambda x: jnp.conjugate(jnp.swapaxes(x, -1, -2)),
    "matrix_inverse": LINALG["inv"],           # upstream matrixInverse
    "matrix_determinant": LINALG["det"],       # upstream matrixDeterminant
})

def _multinomial(key, logits, num_samples):
    """tf.multinomial semantics: logits (B, K) + int num_samples ->
    (B, num_samples) draws (vs categorical's shape-tuple argument)."""
    logits = jnp.asarray(logits)
    batch = logits.shape[:-1]
    out = jax.random.categorical(key, logits, axis=-1,
                                 shape=(int(num_samples),) + batch)
    return jnp.moveaxis(out, 0, -1)


RANDOM.update({
    "multinomial": _multinomial,
})

BITWISE.update({
    "bit_rotl": BITWISE["cyclic_shift_left"],
    "bit_rotr": BITWISE["cyclic_shift_right"],
})


def _space_to_batch_nd(x, block_shape, paddings):
    """tf/upstream spaceToBatchNd for NHWC-style inputs (spatial dims are
    axes 1..len(block_shape))."""
    bs = [int(b) for b in block_shape]
    pads = [(0, 0)] + [tuple(int(v) for v in p) for p in paddings] \
        + [(0, 0)] * (x.ndim - 1 - len(bs))
    x = jnp.pad(x, pads)
    b = x.shape[0]
    spatial = x.shape[1:1 + len(bs)]
    rest = x.shape[1 + len(bs):]
    shape = [b]
    for s, blk in zip(spatial, bs):
        shape += [s // blk, blk]
    x = x.reshape(shape + list(rest))
    # (b, s1/b1, b1, s2/b2, b2, ...) -> (b1, b2, ..., b, s1/b1, s2/b2, ...)
    perm = [2 * i + 2 for i in range(len(bs))] + [0] \
        + [2 * i + 1 for i in range(len(bs))] \
        + list(range(1 + 2 * len(bs), x.ndim))
    x = x.transpose(perm)
    return x.reshape([b * _math.prod(bs)] + [s // blk for s, blk in
                                             zip(spatial, bs)] + list(rest))


def _batch_to_space_nd(x, block_shape, crops):
    bs = [int(b) for b in block_shape]
    nb = x.shape[0] // _math.prod(bs)
    spatial = x.shape[1:1 + len(bs)]
    rest = x.shape[1 + len(bs):]
    x = x.reshape(bs + [nb] + list(spatial) + list(rest))
    perm = [len(bs)]
    for i in range(len(bs)):
        perm += [len(bs) + 1 + i, i]
    perm += list(range(1 + 2 * len(bs), x.ndim))
    x = x.transpose(perm)
    x = x.reshape([nb] + [s * blk for s, blk in zip(spatial, bs)]
                  + list(rest))
    sl = [slice(None)]
    for (c0, c1), s in zip(crops, x.shape[1:1 + len(bs)]):
        sl.append(slice(int(c0), s - int(c1)))
    return x[tuple(sl)]


def _image_resize(x, h, w, method="bilinear"):
    """SDImage.imageResize: one dispatcher over the method enum."""
    m = str(method).lower()
    if m in ("area",):
        return _resize_area(x, h, w)
    table = {"bilinear": "linear", "linear": "linear",
             "nearest": "nearest", "neighbor": "nearest",
             "bicubic": "cubic", "cubic": "cubic",
             "lanczos3": "lanczos3", "lanczos5": "lanczos5"}
    if m not in table:
        raise ValueError(f"unknown resize method '{method}'")
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, int(h), int(w), c), method=table[m])


BASE.update({
    "space_to_batch_nd": _space_to_batch_nd,
    "batch_to_space_nd": _batch_to_space_nd,
    "tear": BASE["unstack"],                    # nd4j Tear = unstack
})

MATH_EXT.update({
    "eps": lambda x, y, eps=1e-5: jnp.abs(x - y) < eps,   # nd4j Eps op
    "axpy": lambda a, x, y: a * x + y,                    # nd4j Axpy
    "to_degrees": MATH_EXT["rad2deg"],
    "to_radians": MATH_EXT["deg2rad"],
})

NN_EXT.update({
    "precise_gelu": NN_EXT["gelu_exact"],
    "thresholded_relu": lambda x, theta=1.0: jnp.where(x > theta, x, 0.0),
})

RNN.update({
    "gru": RNN["gru_layer"],                    # upstream GRU (time op)
})

IMAGE.update({
    "image_resize": _image_resize,
    "adjust_contrast_v2": IMAGE["adjust_contrast"],
})

LOSS_EXT.update({
    "log_poisson": LOSS_EXT["log_poisson_loss"],
})


# ------------------------------------------------------- r4 widening #4b --
# VERDICT r3 "missing #1": push the registry toward the upstream ~O(1000)
# catalog. Families: libnd4j updater custom ops (nd4j-api ops/impl/updaters/
# {SgdUpdater, NesterovsUpdater, AdaGradUpdater, RmsPropUpdater,
# AdaDeltaUpdater, AdamUpdater, AdaMaxUpdater, NadamUpdater, AmsGradUpdater}),
# tf.signal-style spectral windows/STFT (upstream audio/spectrogram path),
# Assert-family validation ops (nd4j ops/impl/transforms/Assert et al.),
# random image augmentation + affine sampling (tf.image / DataVec
# ImageTransform parity), and the mechanical long tail (AddN, MirrorPad,
# NthElement, Bitcast, SparseToDense, SufficientStatistics, Mode, ...).
# All pure jnp/lax, jit-traceable; random ops take an explicit PRNG key.

# ---------------------------------------------------------- updater ops --
# Functional form: (grad, *state, hyperparams...) -> (update, *new_state);
# caller applies `params - update`. Iteration `t` is 1-based like upstream.

def _adam_moments(g, m, v, b1, b2):
    m2 = b1 * m + (1 - b1) * g
    v2 = b2 * v + (1 - b2) * jnp.square(g)
    return m2, v2


def _u_sgd(g, lr=0.1):
    return (lr * g,)


def _u_momentum(g, v, lr=0.1, momentum=0.9):
    v2 = momentum * v + g
    return lr * v2, v2


def _u_nesterovs(g, v, lr=0.1, momentum=0.9):
    v2 = momentum * v + g
    return lr * (g + momentum * v2), v2


def _u_adagrad(g, s, lr=0.01, eps=1e-6):
    s2 = s + jnp.square(g)
    return lr * g / (jnp.sqrt(s2) + eps), s2


def _u_rmsprop(g, s, lr=0.001, rho=0.95, eps=1e-8):
    s2 = rho * s + (1 - rho) * jnp.square(g)
    return lr * g / jnp.sqrt(s2 + eps), s2


def _u_adadelta(g, s, d, rho=0.95, eps=1e-6):
    s2 = rho * s + (1 - rho) * jnp.square(g)
    u = g * jnp.sqrt(d + eps) / jnp.sqrt(s2 + eps)
    d2 = rho * d + (1 - rho) * jnp.square(u)
    return u, s2, d2


def _u_adam(g, m, v, t, lr=0.001, beta1=0.9, beta2=0.999, eps=1e-8):
    m2, v2 = _adam_moments(g, m, v, beta1, beta2)
    mhat = m2 / (1 - beta1 ** t)
    vhat = v2 / (1 - beta2 ** t)
    return lr * mhat / (jnp.sqrt(vhat) + eps), m2, v2


def _u_adamax(g, m, u, t, lr=0.002, beta1=0.9, beta2=0.999, eps=1e-8):
    m2 = beta1 * m + (1 - beta1) * g
    u2 = jnp.maximum(beta2 * u, jnp.abs(g))
    return lr / (1 - beta1 ** t) * m2 / (u2 + eps), m2, u2


def _u_nadam(g, m, v, t, lr=0.001, beta1=0.9, beta2=0.999, eps=1e-8):
    m2, v2 = _adam_moments(g, m, v, beta1, beta2)
    mhat = m2 / (1 - beta1 ** t)
    vhat = v2 / (1 - beta2 ** t)
    nud = beta1 * mhat + (1 - beta1) * g / (1 - beta1 ** t)
    return lr * nud / (jnp.sqrt(vhat) + eps), m2, v2


def _u_amsgrad(g, m, v, vmax, t, lr=0.001, beta1=0.9, beta2=0.999,
               eps=1e-8):
    m2, v2 = _adam_moments(g, m, v, beta1, beta2)
    vmax2 = jnp.maximum(vmax, v2)
    mhat = m2 / (1 - beta1 ** t)
    return lr * mhat / (jnp.sqrt(vmax2) + eps), m2, v2, vmax2


UPDATER = {
    "sgd_updater": _u_sgd,
    "momentum_updater": _u_momentum,
    "nesterovs_updater": _u_nesterovs,
    "ada_grad_updater": _u_adagrad,
    "rms_prop_updater": _u_rmsprop,
    "ada_delta_updater": _u_adadelta,
    "adam_updater": _u_adam,
    "ada_max_updater": _u_adamax,
    "nadam_updater": _u_nadam,
    "ams_grad_updater": _u_amsgrad,
}

# ----------------------------------------------------------- signal ops --


def _window(kind, n, periodic=True):
    n = int(n)
    if kind == "kaiser":
        raise ValueError("use kaiser_window(n, beta)")
    fn = {"hann": jnp.hanning, "hamming": jnp.hamming,
          "blackman": jnp.blackman, "bartlett": jnp.bartlett}[kind]
    return fn(n + 1)[:-1] if periodic else fn(n)


def _frame(x, frame_length, frame_step, pad_end=False, pad_value=0.0):
    fl, fs = int(frame_length), int(frame_step)
    n = x.shape[-1]
    if pad_end:
        # tf.signal.frame: one frame per step start within the signal;
        # no padding needed when frame_length < frame_step leaves the
        # last frame already in-bounds
        n_frames = -(-n // fs)
        need = (n_frames - 1) * fs + fl
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, max(0, need - n))],
                    constant_values=pad_value)
    else:
        n_frames = 1 + (n - fl) // fs
    idx = (jnp.arange(n_frames)[:, None] * fs + jnp.arange(fl)[None, :])
    return x[..., idx]                      # (..., frames, frame_length)


def _overlap_and_add(frames, frame_step):
    """tf.signal.overlap_and_add: plain scatter-add of the frames (no
    window-power normalization — that is istft's job)."""
    fs = int(frame_step)
    n_frames, fl = frames.shape[-2], frames.shape[-1]
    out_len = (n_frames - 1) * fs + fl
    idx = jnp.arange(n_frames)[:, None] * fs + jnp.arange(fl)[None, :]
    out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
    return out.at[..., idx].add(frames)


def _stft(x, frame_length=256, frame_step=128, fft_length=None,
          window="hann", pad_end=False):
    fl = int(frame_length)
    nfft = int(fft_length or fl)
    frames = _frame(x, fl, frame_step, pad_end=pad_end)
    if window is not None:
        frames = frames * _window(window, fl, periodic=True)
    return jnp.fft.rfft(frames, n=nfft, axis=-1)


def _istft(spec, frame_length=256, frame_step=128, fft_length=None,
           window="hann"):
    fl, fs = int(frame_length), int(frame_step)
    nfft = int(fft_length or fl)
    frames = jnp.fft.irfft(spec, n=nfft, axis=-1)[..., :fl]
    w = (_window(window, fl, periodic=True) if window is not None
         else jnp.ones((fl,)))
    frames = frames * w
    n_frames = frames.shape[-2]
    out = _overlap_and_add(frames, fs)
    norm = _overlap_and_add(
        jnp.broadcast_to(jnp.square(w), (n_frames, fl)), fs)
    return out / jnp.maximum(norm, 1e-12)


SIGNAL = {
    "stft": _stft,
    "istft": _istft,
    "frame": _frame,
    "overlap_and_add": lambda frames, frame_step: _overlap_and_add(
        frames, frame_step),
    "hann_window": lambda n, periodic=True: _window("hann", n, periodic),
    "hamming_window": lambda n, periodic=True: _window(
        "hamming", n, periodic),
    "blackman_window": lambda n, periodic=True: _window(
        "blackman", n, periodic),
    "bartlett_window": lambda n, periodic=True: _window(
        "bartlett", n, periodic),
    "kaiser_window": lambda n, beta=12.0: jnp.kaiser(int(n), beta),
    "linear_to_mel_weight_matrix": None,    # replaced below
    "mfcc": None,                           # replaced below
}


def _mel_matrix(num_mel_bins=20, num_spectrogram_bins=129,
                sample_rate=8000, lower_edge_hertz=125.0,
                upper_edge_hertz=3800.0):
    def hz_to_mel(f):
        return 2595.0 * jnp.log10(1.0 + f / 700.0)
    nyq = sample_rate / 2.0
    freqs = jnp.linspace(0.0, nyq, int(num_spectrogram_bins))
    mel_f = hz_to_mel(freqs)
    edges = jnp.linspace(hz_to_mel(jnp.asarray(lower_edge_hertz)),
                         hz_to_mel(jnp.asarray(upper_edge_hertz)),
                         int(num_mel_bins) + 2)
    lo, ctr, hi = edges[:-2], edges[1:-1], edges[2:]
    up = (mel_f[:, None] - lo[None, :]) / (ctr - lo)[None, :]
    down = (hi[None, :] - mel_f[:, None]) / (hi - ctr)[None, :]
    return jnp.maximum(0.0, jnp.minimum(up, down))


def _mfcc(log_mel, n_mfcc=13):
    # DCT-II orthonormal over the last axis, keep first n_mfcc coeffs
    n = log_mel.shape[-1]
    k = jnp.arange(n)
    basis = jnp.cos(jnp.pi / n * (k[:, None] + 0.5) * k[None, :])
    scale = jnp.concatenate([jnp.full((1,), 1.0 / jnp.sqrt(jnp.asarray(
        float(n)))), jnp.full((n - 1,), jnp.sqrt(2.0 / n))])
    return (log_mel @ basis * scale)[..., :int(n_mfcc)]


SIGNAL["linear_to_mel_weight_matrix"] = _mel_matrix
SIGNAL["mfcc"] = _mfcc

# ----------------------------------------------------------- assert ops --
# Eager: python-raise on violation. Traced: checkify.check (caller wraps
# with jax.experimental.checkify). Upstream: nd4j Assert / validation ops.
from jax.experimental import checkify as _checkify  # noqa: E402


def _assert_all(ok, msg, ret):
    ok = jnp.all(ok)
    if isinstance(ok, jax.core.Tracer):
        _checkify.check(ok, msg)
        return ret
    if not bool(ok):
        raise AssertionError(msg)
    return ret


def _assert2(name, fn):
    def op(x, y):
        return _assert_all(fn(jnp.asarray(x), jnp.asarray(y)),
                           f"assert_{name} failed", x)
    return op


ASSERT = {
    "assert_true": lambda cond, msg="assertion failed": _assert_all(
        cond, msg, cond),
    "assert_eq": _assert2("eq", jnp.equal),
    "assert_neq": _assert2("neq", jnp.not_equal),
    "assert_gt": _assert2("gt", jnp.greater),
    "assert_gte": _assert2("gte", jnp.greater_equal),
    "assert_lt": _assert2("lt", jnp.less),
    "assert_lte": _assert2("lte", jnp.less_equal),
    "assert_finite": lambda x: _assert_all(
        jnp.isfinite(x), "assert_finite failed", x),
    "assert_positive": lambda x: _assert_all(
        jnp.asarray(x) > 0, "assert_positive failed", x),
    "assert_non_negative": lambda x: _assert_all(
        jnp.asarray(x) >= 0, "assert_non_negative failed", x),
    "assert_rank": lambda x, rank: _assert_all(
        jnp.asarray(jnp.ndim(x) == int(rank)),
        f"assert_rank failed", x),
    "assert_shapes_equal": lambda x, y: _assert_all(
        jnp.asarray(jnp.shape(x) == jnp.shape(y)),
        "assert_shapes_equal failed", x),
}

# ------------------------------------- image augmentation + affine ops --
from jax.scipy import ndimage as _jnd  # noqa: E402


def _affine_sample(img, matrix, order=1, cval=0.0):
    """Sample (H, W, C) or (B, H, W, C) with a 2x3 inverse affine matrix
    mapping OUTPUT pixel coords -> input coords (tf.contrib.image
    convention)."""
    m = jnp.asarray(matrix, jnp.float32).reshape(2, 3)

    def one(im):
        h, w = im.shape[0], im.shape[1]
        ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                              jnp.arange(w, dtype=jnp.float32),
                              indexing="ij")
        xin = m[0, 0] * xs + m[0, 1] * ys + m[0, 2]
        yin = m[1, 0] * xs + m[1, 1] * ys + m[1, 2]

        def chan(c):
            return _jnd.map_coordinates(c, [yin, xin], order=order,
                                        mode="constant", cval=cval)
        return jnp.stack([chan(im[..., i]) for i in range(im.shape[-1])],
                         axis=-1)
    return jax.vmap(one)(img) if img.ndim == 4 else one(img)


def _rotate_img(img, angle, order=1, cval=0.0):
    """Rotate by ``angle`` radians about the center, counter-clockwise in
    the array sense: rotate(img, pi/2) == np.rot90(img, 1)."""
    h, w = (img.shape[-3], img.shape[-2])
    c, s = jnp.cos(angle), jnp.sin(angle)
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    # output->input: rotate by -angle about the center
    m = jnp.asarray([[c, -s, cx - c * cx + s * cy],
                     [s, c, cy - s * cx - c * cy]])
    return _affine_sample(img, m, order=order, cval=cval)


def _translate_img(img, dx, dy, order=1, cval=0.0):
    m = jnp.asarray([[1.0, 0.0, -dx], [0.0, 1.0, -dy]])
    return _affine_sample(img, m, order=order, cval=cval)


def _per_image_mask(key, img, p=0.5):
    if img.ndim == 4:
        return jax.random.bernoulli(key, p, (img.shape[0], 1, 1, 1))
    return jax.random.bernoulli(key, p, ())


IMAGE.update({
    "random_flip_left_right": lambda key, img: jnp.where(
        _per_image_mask(key, img), jnp.flip(img, axis=-2), img),
    "random_flip_up_down": lambda key, img: jnp.where(
        _per_image_mask(key, img), jnp.flip(img, axis=-3), img),
    "random_brightness": lambda key, img, max_delta: img + jax.random.uniform(
        key, (), minval=-max_delta, maxval=max_delta),
    "random_contrast": lambda key, img, lower, upper: IMAGE[
        "adjust_contrast"](img, jax.random.uniform(
            key, (), minval=lower, maxval=upper)),
    "random_hue": lambda key, img, max_delta: _adjust_hue(
        img, jax.random.uniform(key, (), minval=-max_delta,
                                maxval=max_delta)),
    "random_saturation": lambda key, img, lower, upper: _adjust_saturation(
        img, jax.random.uniform(key, (), minval=lower, maxval=upper)),
    "rotate": _rotate_img,
    "translate": _translate_img,
    "affine_transform": _affine_sample,
})

# ------------------------------------------------------ mechanical tail --


def _mirror_pad(x, paddings, mode="REFLECT"):
    return jnp.pad(x, paddings,
                   mode={"REFLECT": "reflect",
                         "SYMMETRIC": "symmetric"}[str(mode).upper()])


def _nth_element(x, n, reverse=False):
    s = jnp.sort(x, axis=-1)
    return s[..., x.shape[-1] - 1 - int(n)] if reverse else s[..., int(n)]


def _sparse_to_dense(indices, output_shape, values, default_value=0):
    idx = jnp.asarray(indices).astype(jnp.int32)
    if idx.ndim == 1:
        idx = idx[:, None]
    out = jnp.full(tuple(int(s) for s in output_shape), default_value,
                   jnp.asarray(values).dtype)
    return out.at[tuple(idx[..., i] for i in range(idx.shape[-1]))].set(
        jnp.asarray(values))


def _sufficient_statistics(x, axes, shift=None):
    axes = tuple(_axes(axes)) if not isinstance(axes, int) else (axes,)
    count = jnp.asarray(
        _math.prod(x.shape[a] for a in axes), jnp.float32)
    xs = x - shift if shift is not None else x
    return count, jnp.sum(xs, axes), jnp.sum(jnp.square(xs), axes), shift


def _mode(x, axis=-1):
    s = jnp.sort(jnp.moveaxis(x, axis, -1), axis=-1)
    counts = jnp.sum(s[..., :, None] == s[..., None, :], axis=-1)
    return jnp.take_along_axis(
        s, jnp.argmax(counts, axis=-1)[..., None], axis=-1)[..., 0]


def _hashcode(x):
    """Deterministic java-style polynomial fold of the raw bits (order-
    dependent like upstream ``hashCode``; uint32 wraparound arithmetic)."""
    b = jnp.asarray(x)
    if b.dtype == jnp.bool_:
        b = b.astype(jnp.int32)
    if b.dtype.itemsize != 4:
        b = b.astype(jnp.float32)
    bits = lax.bitcast_convert_type(b, jnp.int32).ravel().astype(jnp.uint32)
    powers = jnp.cumprod(jnp.full((bits.size,), jnp.uint32(31)))[::-1] \
        // jnp.uint32(31)
    return (bits * powers).sum().astype(jnp.int32)


def _set_fill(dtype):
    return (jnp.inf if jnp.issubdtype(dtype, jnp.floating)
            else jnp.iinfo(dtype).max)


def _array_equal(a, b):
    a, b = jnp.asarray(a), jnp.asarray(b)
    if a.shape != b.shape:      # static shapes: mismatch is a static False
        return jnp.asarray(False)
    return jnp.all(jnp.equal(a, b))


def _intersect1d(a, b, size):
    a = jnp.asarray(a)
    fill = _set_fill(a.dtype)
    av = jnp.unique(a, size=int(size), fill_value=fill)
    mask = jnp.isin(av, b)
    return jnp.where(mask, av, fill)


def _union1d(a, b, size):
    c = jnp.concatenate([jnp.ravel(a), jnp.ravel(b)])
    return jnp.unique(c, size=int(size), fill_value=_set_fill(c.dtype))


BASE.update({
    "add_n": lambda *xs: sum(xs[1:], start=xs[0]),
    "accumulate_n": lambda *xs: sum(xs[1:], start=xs[0]),
    "identity_n": lambda *xs: list(xs),
    "mirror_pad": _mirror_pad,
    "nth_element": _nth_element,
    "bitcast": lambda x, dtype: lax.bitcast_convert_type(x, dtype),
    "broadcast_shapes": lambda *shapes: jnp.asarray(
        jnp.broadcast_shapes(*(tuple(s) for s in shapes)), jnp.int32),
    "broadcast_dynamic_shape": lambda s1, s2: jnp.asarray(
        jnp.broadcast_shapes(tuple(int(v) for v in s1),
                             tuple(int(v) for v in s2)), jnp.int32),
    "sparse_to_dense": _sparse_to_dense,
    "sufficient_statistics": _sufficient_statistics,
    "mode": _mode,
    "hashcode": _hashcode,
    "array_equal": lambda a, b: _array_equal(a, b),
    "setdiff1d": BASE["list_diff"],
    "intersect1d": _intersect1d,
    "union1d": lambda a, b, size: _union1d(a, b, size),
    "unravel_index": lambda flat, shape: jnp.unravel_index(
        jnp.asarray(flat), tuple(int(s) for s in shape)),
    "ravel_multi_index": lambda multi, shape: jnp.ravel_multi_index(
        tuple(jnp.asarray(m) for m in multi),
        tuple(int(s) for s in shape), mode="clip"),
    "put_along_axis": lambda x, idx, vals, axis: jnp.put_along_axis(
        x, jnp.asarray(idx), vals, axis=axis, inplace=False),
    "bucketize": BASE["digitize"],
    "reverse_v2": BASE["reverse"],
    "take_nd": BASE["gather_nd"],
})

MATH_EXT.update({
    "multigammaln": lambda x, d: jsp.multigammaln(x, int(d)),
    "realdiv": lambda x, y: jnp.divide(x, y),
    "truncate_mod": lambda x, y: jnp.fmod(x, y),
    "squared_subtract": MATH_EXT["squared_difference"],
    "floordiv": MATH_EXT["floor_div"],
    "cot": lambda x: 1.0 / jnp.tan(x),
    "sec": lambda x: 1.0 / jnp.cos(x),
    "csc": lambda x: 1.0 / jnp.sin(x),
    "log1mexp": lambda x: jnp.where(
        x > -_math.log(2.0), jnp.log(-jnp.expm1(x)),
        jnp.log1p(-jnp.exp(x))),
})

LINALG.update({
    "log_matrix_determinant": lambda x: jnp.linalg.slogdet(x),
    "tensorinv": lambda x, ind=2: jnp.linalg.tensorinv(x, ind=int(ind)),
    "tensorsolve": lambda a, b: jnp.linalg.tensorsolve(a, b),
    "orth": lambda a, rcond=None: _orth(a, rcond),
    "null_space": lambda a, rcond=None: _null_space(a, rcond),
})


def _orth(a, rcond=None):
    u, s, _ = jnp.linalg.svd(a, full_matrices=False)
    tol = (rcond if rcond is not None
           else jnp.finfo(a.dtype).eps * max(a.shape)) * jnp.max(s)
    return jnp.where((s > tol)[None, :], u, 0.0)


def _null_space(a, rcond=None):
    _, s, vh = jnp.linalg.svd(a, full_matrices=True)
    tol = (rcond if rcond is not None
           else jnp.finfo(a.dtype).eps * max(a.shape)) * jnp.max(s)
    rank_mask = jnp.concatenate(
        [s, jnp.zeros(vh.shape[0] - s.shape[0])]) > tol
    return jnp.where(~rank_mask[None, :], vh.T, 0.0)


RANDOM.update({
    "weibull": lambda key, shape, a=1.0, scale=1.0: scale * jnp.power(
        -jnp.log1p(-jax.random.uniform(key, tuple(shape))), 1.0 / a),
    "triangular": lambda key, shape, left=0.0, mode=0.5, right=1.0:
        _r_triangular(key, tuple(shape), left, mode, right),
    "f": lambda key, shape, dfnum, dfden: _r_f(
        key, tuple(shape), dfnum, dfden),
    "negative_binomial": lambda key, shape, n, p: _r_negbin(
        key, tuple(shape), n, p),
    "standard_t": RANDOM["student_t"],
})


def _r_triangular(key, shape, left, mode, right):
    u = jax.random.uniform(key, shape)
    fc = (mode - left) / (right - left)
    return jnp.where(
        u < fc,
        left + jnp.sqrt(u * (right - left) * (mode - left)),
        right - jnp.sqrt((1 - u) * (right - left) * (right - mode)))


def _r_f(key, shape, dfnum, dfden):
    k1, k2 = jax.random.split(key)
    num = 2.0 * jax.random.gamma(k1, dfnum / 2.0, shape) / dfnum
    den = 2.0 * jax.random.gamma(k2, dfden / 2.0, shape) / dfden
    return num / den


def _r_negbin(key, shape, n, p):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, n, shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape)


CNN.update({
    "conv2d_transpose": CNN["deconv2d"],
    "conv1d_transpose": CNN["deconv1d"],
    "conv3d_transpose": CNN["deconv3d"],
    "atrous_conv2d": lambda x, w, rate, padding="SAME": CNN["conv2d"](
        x, w, stride=(1, 1), padding=padding,
        dilation=(int(rate), int(rate))),
})


def _bidirectional(layer_fn, concat_axis=-1):
    def f(x, h0_fwd, h0_bwd, *args):
        n = len(args) // 2
        fwd = layer_fn(x, h0_fwd, *args[:n])
        bwd = layer_fn(jnp.flip(x, axis=1), h0_bwd, *args[n:])
        return jnp.concatenate([fwd, jnp.flip(bwd, axis=1)],
                               axis=concat_axis)
    return f


RNN.update({
    "bidirectional_lstm_layer": _bidirectional(RNN["lstm_layer"]),
    "bidirectional_gru_layer": _bidirectional(RNN["gru_layer"]),
    "dynamic_rnn": RNN["simple_rnn_layer"],
})

NAMESPACES.update({
    "updater": UPDATER, "signal": SIGNAL, "assert": ASSERT,
})

# ------------------------------------------------------ *_bp op family --
# libnd4j ships an explicit backprop custom op for every layer op
# (conv2d_bp, batchnorm_bp, maxpool2d_bp, relu_bp, reduce_sum_bp, ...;
# nd4j-api ops/impl/layers/convolution/*Bp, ops/impl/transforms/gradient/*).
# TPU-native equivalent: DERIVE them from the forward registry with
# jax.vjp — same contract (primals..., dL/dOut, static kwargs) -> input
# cotangent(s), but guaranteed-consistent with the forward op by
# construction instead of hand-written CUDA.


def _bp_of(fn, n_grads=1):
    """Wrap forward `fn` as its libnd4j-style _bp op.

    Signature: (*primals, grad, **static_kwargs) — returns the cotangent
    of the first primal, or a tuple of the first `n_grads` cotangents."""
    def bp_op(*args, **kwargs):
        *primals, g = args
        out, vjp = jax.vjp(lambda *p: fn(*p, **kwargs), *primals)
        grads = vjp(jnp.asarray(g).astype(out.dtype))
        return grads[0] if n_grads == 1 else tuple(grads[:n_grads])
    return bp_op


def _reduce_bp(fn):
    """Reduction _bp: (x, grad, axis=..., keepdims=...) with the grad
    broadcast back over the reduced axes (upstream reduce_*_bp)."""
    def bp_op(x, g, **kwargs):
        out, vjp = jax.vjp(lambda x_: fn(x_, **kwargs), x)
        return vjp(jnp.asarray(g).astype(out.dtype))[0]
    return bp_op


_ACT_FWD = {
    "relu": jax.nn.relu, "relu6": jax.nn.relu6, "elu": jax.nn.elu,
    "selu": jax.nn.selu, "gelu": jax.nn.gelu, "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh, "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign, "swish": jax.nn.swish,
    "hard_swish": jax.nn.hard_swish, "hard_sigmoid": jax.nn.hard_sigmoid,
    "leaky_relu": jax.nn.leaky_relu, "mish": jax.nn.mish,
    "softmax": jax.nn.softmax, "log_softmax": jax.nn.log_softmax,
    "cube": lambda x: x ** 3,
    "rational_tanh": MATH_EXT["rational_tanh"],
    "rectified_tanh": MATH_EXT["rectified_tanh"],
}

BP = {}
for _n, _f in _ACT_FWD.items():
    BP[f"{_n}_bp"] = _bp_of(_f)

for _n in ("conv1d", "conv2d", "conv3d", "deconv1d", "deconv2d", "deconv3d",
           "depthwise_conv2d", "separable_conv2d"):
    BP[f"{_n}_bp"] = _bp_of(CNN[_n], n_grads=2)     # (dx, dw)

for _n in ("max_pooling1d", "max_pooling2d", "max_pooling3d",
           "avg_pooling1d", "avg_pooling2d", "avg_pooling3d",
           "lp_pool2d", "local_response_normalization", "im2col",
           "upsampling2d", "pixel_shuffle"):
    BP[f"{_n}_bp"] = _bp_of(CNN[_n])

BP["batch_norm_bp"] = _bp_of(CNN["batch_norm"], n_grads=5)  # d(all inputs)
BP["layer_norm_bp"] = _bp_of(NN_EXT["layer_norm_no_bias"], n_grads=1)
BP["bias_add_bp"] = _bp_of(NN_EXT["bias_add"], n_grads=2)
BP["l2_normalize_bp"] = _bp_of(NN_EXT["l2_normalize"])
BP["lstm_layer_bp"] = _bp_of(RNN["lstm_layer"], n_grads=2)  # dx, dh0
BP["gru_layer_bp"] = _bp_of(RNN["gru_layer"], n_grads=2)

for _n, _fn in (("sum", jnp.sum), ("mean", jnp.mean), ("max", jnp.max),
                ("min", jnp.min), ("prod", jnp.prod),
                ("variance", jnp.var), ("std", jnp.std),
                ("norm2", jnp.linalg.norm),
                ("logsumexp", jsp.logsumexp)):
    BP[f"reduce_{_n}_bp"] = _reduce_bp(_fn)

BP["squared_norm_bp"] = _reduce_bp(lambda x, **kw: jnp.sum(x * x, **kw))
BP["matmul_bp"] = _bp_of(jnp.matmul, n_grads=2)
BP["mmul_bp"] = BP["matmul_bp"]

NAMESPACES["bp"] = BP

# --------------------------------------------------- r4 widening tail --
# tf-interop aliases (the TF importer maps these names directly), signal
# conveniences, and a few genuinely-absent ops.


def _sample_distorted_bounding_box(key, image_size, min_object_covered=0.1,
                                   area_range=(0.05, 1.0),
                                   aspect_ratio_range=(0.75, 1.33)):
    """tf.image.sample_distorted_bounding_box (random-crop training
    regime): returns (begin(y,x), size(h,w)) for a random crop window with
    area/aspect constraints. Static image_size; rejection-free sampling
    (area and aspect drawn, then clamped into the image)."""
    h, w = int(image_size[0]), int(image_size[1])
    k1, k2, k3, k4 = jax.random.split(key, 4)
    area = jax.random.uniform(k1, (), minval=area_range[0],
                              maxval=area_range[1]) * (h * w)
    ar = jnp.exp(jax.random.uniform(
        k2, (), minval=jnp.log(jnp.asarray(aspect_ratio_range[0])),
        maxval=jnp.log(jnp.asarray(aspect_ratio_range[1]))))
    ch = jnp.clip(jnp.sqrt(area / ar), 1, h).astype(jnp.int32)
    cw = jnp.clip(jnp.sqrt(area * ar), 1, w).astype(jnp.int32)
    y0 = jax.random.randint(k3, (), 0, jnp.maximum(h - ch, 1))
    x0 = jax.random.randint(k4, (), 0, jnp.maximum(w - cw, 1))
    return jnp.stack([y0, x0]), jnp.stack([ch, cw])


def _nms_with_scores(boxes, scores, max_output_size, iou_threshold=0.5,
                     score_threshold=-jnp.inf):
    idx, valid = IMAGE["non_max_suppression"](boxes, scores,
                                              max_output_size,
                                              iou_threshold,
                                              score_threshold)
    return idx, jnp.take(scores, jnp.maximum(idx, 0)) * (idx >= 0)


IMAGE.update({
    "sample_distorted_bounding_box": _sample_distorted_bounding_box,
    "non_max_suppression_with_scores": _nms_with_scores,
})

SIGNAL.update({
    "spectrogram": lambda x, frame_length=256, frame_step=128, **kw:
        jnp.square(jnp.abs(_stft(x, frame_length, frame_step, **kw))),
    "log_mel_spectrogram": lambda x, frame_length=256, frame_step=128,
        num_mel_bins=40, sample_rate=16000, **kw: jnp.log(
            jnp.square(jnp.abs(_stft(x, frame_length, frame_step, **kw)))
            @ _mel_matrix(num_mel_bins,
                          (int(kw.get("fft_length") or frame_length))
                          // 2 + 1, sample_rate) + 1e-6),
})

# tf reduce_* spellings — same callables, importer-friendly names
BASE.update({
    "reduce_sum": BASE["sum"], "reduce_mean": BASE["mean"],
    "reduce_max": BASE["max"], "reduce_min": BASE["min"],
    "reduce_prod": BASE["prod"], "reduce_any": BASE["any"],
    "reduce_all": BASE["all"], "reduce_logsumexp": BASE["logsumexp"],
})

# key-first random ops already implement the stateless contract
RANDOM.update({
    "stateless_uniform": RANDOM["uniform"],
    "stateless_normal": RANDOM["normal"],
    "stateless_truncated_normal": RANDOM["truncated_normal"],
    "stateless_bernoulli": RANDOM["bernoulli"],
})

LINALG.update({
    "cholesky_solve": LINALG["cho_solve"],
    "matrix_triangular_solve": LINALG["triangular_solve"],
})

RNN.update({
    "static_rnn": RNN["simple_rnn_layer"],
    "bidirectional_dynamic_rnn": RNN["bidirectional_lstm_layer"],
})

NN_EXT.update({
    "scaled_dot_product_attention": NN_EXT["dot_product_attention"],
})


# ------------------------------------------------- r5 straggler closers --
# The r5 exclusion audit (docs/OP_AUDIT.md) surfaced the last
# TPU-representable gaps in the upstream custom-op catalog. Reference:
# libnd4j/include/ops/declarable/generic/{list,parity_ops,blas}, nd4j-api
# TensorArray ops. The upstream list family is a mutable TensorArray; the
# TPU-native form is a FIXED-CAPACITY stacked tensor + element count
# carried functionally (XLA needs static shapes), which is exactly how
# lax.scan carries state.


def _list_create(capacity, element_shape, dtype=jnp.float32):
    """TensorArray analogue: (stack, count). Static capacity + shape."""
    return (jnp.zeros((int(capacity),) + tuple(element_shape), dtype),
            jnp.zeros((), jnp.int32))


def _list_write(tarr, index, value):
    """Out-of-capacity writes are dropped (count pins at capacity) — the
    traced setting cannot raise on a dynamic index, and silent clamping
    would corrupt the LAST slot instead."""
    stack, count = tarr
    cap = stack.shape[0]
    idx = jnp.asarray(index, jnp.int32)
    ok = idx < cap
    new = lax.dynamic_update_index_in_dim(
        stack, jnp.asarray(value, stack.dtype), jnp.minimum(idx, cap - 1), 0)
    stack = jnp.where(ok, new, stack)
    return stack, jnp.minimum(jnp.maximum(count, idx + 1), cap)


def _list_read(tarr, index):
    stack, _ = tarr
    return lax.dynamic_index_in_dim(stack, jnp.asarray(index, jnp.int32),
                                    0, keepdims=False)


def _list_push(tarr, value):
    """Push past capacity is a DROPPED no-op with count pinned at capacity
    (not a clamped overwrite of the last slot)."""
    stack, count = tarr
    cap = stack.shape[0]
    ok = count < cap
    new = lax.dynamic_update_index_in_dim(
        stack, jnp.asarray(value, stack.dtype), jnp.minimum(count, cap - 1), 0)
    return jnp.where(ok, new, stack), jnp.minimum(count + 1, cap)


def _list_stack(tarr):
    """Materialize the written prefix MASKED to zeros past count (static
    shape: the full capacity — slice with count would be dynamic)."""
    stack, count = tarr
    mask = (jnp.arange(stack.shape[0]) < count)
    return jnp.where(mask.reshape((-1,) + (1,) * (stack.ndim - 1)), stack, 0)


def _list_unstack(tarr, values):
    stack, _ = tarr
    v = jnp.asarray(values, stack.dtype)
    n = min(v.shape[0], stack.shape[0])
    stack = lax.dynamic_update_slice_in_dim(stack, v[:n], 0, 0)
    return stack, jnp.asarray(n, jnp.int32)


def _list_gather(tarr, indices):
    stack, _ = tarr
    return jnp.take(stack, jnp.asarray(indices, jnp.int32), axis=0)


def _list_scatter(tarr, indices, values):
    stack, count = tarr
    idx = jnp.asarray(indices, jnp.int32)
    stack = stack.at[idx].set(jnp.asarray(values, stack.dtype))
    # initial=-1 keeps an EMPTY scatter a no-op instead of a zero-size max
    hi = jnp.max(idx, initial=-1) + 1
    return stack, jnp.minimum(jnp.maximum(count, hi), stack.shape[0])


def _list_split(tarr, values, sizes):
    """Upstream split_list: rows of `values` split into count-`sizes`
    chunks written sequentially. Static sizes (XLA); each chunk is padded
    to the widest so the stacked element shape stays static."""
    sizes = [int(s) for s in sizes]
    stack, _ = tarr
    width = stack.shape[1] if stack.ndim > 1 else max(sizes)
    v = jnp.asarray(values, stack.dtype)
    off = 0
    for i, s in enumerate(sizes):
        chunk = v[off:off + s]
        pad = [(0, width - s)] + [(0, 0)] * (chunk.ndim - 1)
        stack = stack.at[i].set(jnp.pad(chunk, pad))
        off += s
    return stack, jnp.asarray(len(sizes), jnp.int32)


def _list_size(tarr):
    return tarr[1]


LIST = {
    "create_list": _list_create,
    "write_list": _list_write,
    "read_list": _list_read,
    "push_list": _list_push,
    "stack_list": _list_stack,
    "unstack_list": _list_unstack,
    "gather_list": _list_gather,
    "scatter_list": _list_scatter,
    "split_list": _list_split,
    "size_list": _list_size,
}
NAMESPACES["list"] = LIST


def _embedding_lookup(params, ids, max_norm=None):
    """tf/upstream embedding_lookup: gather rows; optional L2 clip."""
    out = jnp.take(jnp.asarray(params), jnp.asarray(ids, jnp.int32), axis=0)
    if max_norm is not None:
        norms = jnp.linalg.norm(out, axis=-1, keepdims=True)
        out = out * jnp.minimum(1.0, max_norm / jnp.maximum(norms, 1e-12))
    return out


def _xw_plus_b(x, w, b):
    return jnp.asarray(x) @ jnp.asarray(w) + jnp.asarray(b)


def _compare_and_bitpack(x, threshold):
    """Pack (x > threshold) along the last axis (len divisible by 8) into
    uint8 — upstream compare_and_bitpack. MXU-free: one dot with the bit
    weights per byte."""
    x = jnp.asarray(x)
    bits = (x > threshold).astype(jnp.uint8)
    b8 = bits.reshape(x.shape[:-1] + (x.shape[-1] // 8, 8))
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return jnp.sum(b8 * weights, axis=-1).astype(jnp.uint8)


def _batched_gemm(a, b, transpose_a=False, transpose_b=False,
                  alpha=1.0, beta=0.0, c=None):
    """libnd4j batched_gemm: C = alpha * op(A) @ op(B) + beta * C over
    leading batch dims."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    out = alpha * jnp.matmul(a, b)
    if c is not None and beta != 0.0:
        out = out + beta * jnp.asarray(c)
    return out


def _choose(x, mode, scalar):
    """Legacy nd4j choose op: elements of x satisfying the comparison
    `mode` vs scalar (0:<, 1:<=, 2:==, 3:!=, 4:>, 5:>=), zeros elsewhere,
    plus the match count (static-shape form of the ragged upstream
    return)."""
    x = jnp.asarray(x)
    cmp = [lambda a: a < scalar, lambda a: a <= scalar,
           lambda a: a == scalar, lambda a: a != scalar,
           lambda a: a > scalar, lambda a: a >= scalar][int(mode)]
    m = cmp(x)
    return jnp.where(m, x, 0), jnp.sum(m.astype(jnp.int32))


NN_EXT.update({
    "embedding_lookup": _embedding_lookup,
    "xw_plus_b": _xw_plus_b,
})
BASE.update({
    "compare_and_bitpack": _compare_and_bitpack,
    "choose": _choose,
})
LINALG.update({
    "batched_gemm": _batched_gemm,
})
