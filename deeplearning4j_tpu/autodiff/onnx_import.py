"""ONNX import (opset-13 core subset) → SameDiff graph.

Reference parity: ``org.nd4j.imports`` / ``samediff-import-onnx`` — the
reference maps ONNX NodeProtos onto SameDiff ops. Here the .onnx file is
decoded with a minimal hand-rolled protobuf wire-format reader (the image
has no ``onnx`` package; field numbers below are fixed by the public
onnx.proto3 schema) and each node becomes a lazy jax op in the SameDiff
graph, so the imported model jits into one XLA program.

Covered ops target the models the reference's importer is used for
(MLPs, CNNs, transformer blocks exported from torch/keras): Gemm/MatMul,
Conv/pooling (NCHW), BatchNormalization, activations, elementwise +
logical ops, reshape/transpose/concat/split/slice/gather, reductions,
Cast/Clip/Pad/Expand/Tile/Where, Constant(OfShape), Dropout(identity).
Unknown ops raise with the op name — loud, not silent.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .samediff import SameDiff, SDVariable

# =========================================================== protobuf reader
# wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


class Msg:
    """Decoded protobuf message: field number → list of raw values."""

    __slots__ = ("fields",)

    def __init__(self, buf: bytes):
        self.fields: Dict[int, List[Any]] = {}
        i, n = 0, len(buf)
        while i < n:
            key, i = _read_varint(buf, i)
            fnum, wtype = key >> 3, key & 7
            if wtype == 0:
                v, i = _read_varint(buf, i)
            elif wtype == 1:
                v = struct.unpack_from("<q", buf, i)[0]
                i += 8
            elif wtype == 2:
                ln, i = _read_varint(buf, i)
                v = buf[i:i + ln]
                i += ln
            elif wtype == 5:
                v = struct.unpack_from("<i", buf, i)[0]
                i += 4
            else:  # pragma: no cover — groups unused by onnx
                raise ValueError(f"unsupported wire type {wtype}")
            self.fields.setdefault(fnum, []).append(v)

    # -- typed accessors ----------------------------------------------------
    def ints(self, f) -> List[int]:
        out = []
        for v in self.fields.get(f, []):
            if isinstance(v, bytes):          # packed repeated varint
                i = 0
                while i < len(v):
                    x, i = _read_varint(v, i)
                    out.append(x)
            else:
                out.append(v)
        return [x - (1 << 64) if x >= (1 << 63) else x for x in out]

    def int(self, f, default=0) -> int:
        vals = self.ints(f)
        return vals[0] if vals else default

    def floats(self, f) -> List[float]:
        out = []
        for v in self.fields.get(f, []):
            if isinstance(v, bytes):          # packed repeated fixed32
                out.extend(struct.unpack(f"<{len(v) // 4}f", v))
            else:                             # fixed32 read as int
                out.append(struct.unpack("<f", struct.pack("<i", v))[0])
        return out

    def doubles(self, f) -> List[float]:
        out = []
        for v in self.fields.get(f, []):
            if isinstance(v, bytes):          # packed repeated fixed64
                out.extend(struct.unpack(f"<{len(v) // 8}d", v))
            else:                             # fixed64 read as int (<q)
                out.append(struct.unpack("<d", struct.pack("<q", v))[0])
        return out

    def float(self, f, default=0.0) -> float:
        vals = self.floats(f)
        return vals[0] if vals else default

    def bytes_(self, f, default=b"") -> bytes:
        vals = self.fields.get(f, [])
        return vals[0] if vals else default

    def str_(self, f, default="") -> str:
        return self.bytes_(f).decode("utf-8") if f in self.fields else default

    def strs(self, f) -> List[str]:
        return [v.decode("utf-8") for v in self.fields.get(f, [])]

    def msg(self, f) -> Optional["Msg"]:
        vals = self.fields.get(f, [])
        return Msg(vals[0]) if vals else None

    def msgs(self, f) -> List["Msg"]:
        return [Msg(v) for v in self.fields.get(f, [])]


# onnx.proto3 field numbers (public, fixed):
#   ModelProto.graph = 7
#   GraphProto: node=1 name=2 initializer=5 input=11 output=12
#   NodeProto: input=1 output=2 name=3 op_type=4 attribute=5
#   AttributeProto: name=1 f=2 i=3 s=4 t=5 floats=7 ints=8 strings=9 type=20
#   TensorProto: dims=1 data_type=2 float_data=4 int32_data=5 string_data=6
#                int64_data=7 name=8 raw_data=9 double_data=10 uint64_data=11
#   ValueInfoProto: name=1 type=2 ; TypeProto.tensor_type=1
#   TypeProto.Tensor: elem_type=1 shape=2 ; TensorShapeProto.dim=1
#   TensorShapeProto.Dimension: dim_value=1 dim_param=2

_ONNX_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
                5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
                10: np.float16, 11: np.float64, 12: np.uint32, 13: np.uint64}
_ONNX_JNP_DTYPES = {**{k: jnp.dtype(v) for k, v in _ONNX_DTYPES.items()},
                    16: jnp.bfloat16}


def _tensor_to_np(t: Msg) -> np.ndarray:
    dims = tuple(t.ints(1))
    dtype_code = t.int(2, 1)
    raw = t.bytes_(9)
    if raw:
        if dtype_code == 16:                  # bfloat16: upcast via uint16 view
            u16 = np.frombuffer(raw, np.uint16)
            arr = (u16.astype(np.uint32) << 16).view(np.float32)
        else:
            arr = np.frombuffer(raw, _ONNX_DTYPES.get(dtype_code, np.float32))
    elif t.floats(4):
        arr = np.asarray(t.floats(4), np.float32)
    elif t.ints(7):
        arr = np.asarray(t.ints(7), np.int64)
    elif t.ints(5):
        arr = np.asarray(t.ints(5), _ONNX_DTYPES.get(dtype_code, np.int32))
    elif t.doubles(10):
        arr = np.asarray(t.doubles(10), np.float64)
    else:
        arr = np.zeros(0, _ONNX_DTYPES.get(dtype_code, np.float32))
    return arr.reshape(dims) if dims else arr.reshape(())


class OnnxAttr:
    def __init__(self, m: Msg):
        self.name = m.str_(1)
        self.f = m.float(2)
        self.i = m.int(3)
        self.s = m.bytes_(4)
        self.t = m.msg(5)
        self.floats = m.floats(7)
        self.ints = m.ints(8)
        self.strings = m.strs(9)


class OnnxNode:
    def __init__(self, m: Msg):
        self.inputs = m.strs(1)
        self.outputs = m.strs(2)
        self.name = m.str_(3) or (self.outputs[0] if self.outputs else "?")
        self.op_type = m.str_(4)
        self.attrs = {a.name: a for a in (OnnxAttr(x) for x in m.msgs(5))}

    # attribute helpers with defaults
    def ai(self, name, default=0):
        a = self.attrs.get(name)
        return a.i if a else default

    def af(self, name, default=0.0):
        a = self.attrs.get(name)
        return a.f if a else default

    def aints(self, name, default=()):
        a = self.attrs.get(name)
        return list(a.ints) if a and a.ints else list(default)

    def astr(self, name, default=""):
        a = self.attrs.get(name)
        return a.s.decode() if a and a.s else default


def _vi_shape(vi: Msg):
    """ValueInfoProto → (name, shape tuple with None for dynamic dims)."""
    name = vi.str_(1)
    tt = vi.msg(2)
    tt = tt.msg(1) if tt else None            # TypeProto.tensor_type
    shape = None
    if tt is not None:
        sh = tt.msg(2)
        if sh is not None:
            dims = []
            for d in sh.msgs(1):
                dv = d.int(1, 0)
                dims.append(dv if dv > 0 else None)
            shape = tuple(dims)
    return name, shape


class OnnxGraph:
    def __init__(self, m: Msg):
        self.name = m.str_(2)
        self.nodes = [OnnxNode(x) for x in m.msgs(1)]
        self.initializers: Dict[str, np.ndarray] = {}
        for t in m.msgs(5):
            self.initializers[t.str_(8)] = _tensor_to_np(t)
        self.inputs = [_vi_shape(v) for v in m.msgs(11)]
        self.outputs = [_vi_shape(v)[0] for v in m.msgs(12)]


def parse_onnx(data: bytes) -> OnnxGraph:
    model = Msg(data)
    g = model.msg(7)
    if g is None:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    return OnnxGraph(g)


# ============================================================== op handlers
def _auto_pad(node, spatial_rank):
    """pads attr [b1..bk, e1..ek] → lax ((b1,e1),...); SAME_* handled by caller."""
    pads = node.aints("pads", [0] * 2 * spatial_rank)
    return tuple((pads[d], pads[d + spatial_rank]) for d in range(spatial_rank))


def _conv(i, n):
    x, w = i[0], i[1]                         # NCHW, OIHW (onnx layout)
    rank = x.ndim - 2
    strides = tuple(n.aints("strides", [1] * rank))
    dil = tuple(n.aints("dilations", [1] * rank))
    groups = n.ai("group", 1)
    ap = n.astr("auto_pad", "NOTSET")
    pad = "SAME" if ap.startswith("SAME") else _auto_pad(n, rank)
    spec = ("NCHW", "OIHW", "NCHW") if rank == 2 else \
        (("NCH", "OIH", "NCH") if rank == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    y = lax.conv_general_dilated(x, w, strides, pad, rhs_dilation=dil,
                                 dimension_numbers=spec,
                                 feature_group_count=groups)
    if len(i) > 2 and i[2] is not None:
        y = y + i[2].reshape((1, -1) + (1,) * rank)
    return y


def _pool(i, n, reducer, init, average=False):
    x = i[0]
    rank = x.ndim - 2
    if n.ai("ceil_mode", 0):
        raise NotImplementedError(
            "onnx_import: ceil_mode=1 pooling is not supported (floor-mode "
            "reduce_window would silently change the output shape)")
    if n.aints("dilations", [1] * rank) != [1] * rank:
        raise NotImplementedError("onnx_import: pooling dilations unsupported")
    k = tuple(n.aints("kernel_shape"))
    strides = tuple(n.aints("strides", [1] * rank))
    ap = n.astr("auto_pad", "NOTSET")
    window = (1, 1) + k
    ws = (1, 1) + strides
    if ap.startswith("SAME"):
        pad = "SAME"
    else:
        pad = ((0, 0), (0, 0)) + _auto_pad(n, rank)
    y = lax.reduce_window(x, init, reducer, window, ws, pad)
    if average:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, ws, pad)
        y = y / cnt if n.ai("count_include_pad", 0) == 0 else \
            y / np.prod(k)
    return y


def _static(v):
    """Materialise an op input that must be a compile-time constant.

    Raises a clear error instead of JAX's TracerArrayConversionError when a
    model feeds a dynamic Shape->...->Reshape chain (e.g. torch dynamic_axes
    exports) into a shape-consuming op.
    """
    if isinstance(v, jax.core.Tracer):
        raise NotImplementedError(
            "onnx_import: this op needs a compile-time-constant input, but got "
            "a traced (data-dependent) value — dynamic shape chains like "
            "Shape->Gather->Reshape are not supported; re-export the model "
            "with static shapes")
    return np.asarray(v)


def _gemm(i, n):
    a, b = i[0], i[1]
    if n.ai("transA"):
        a = a.T
    if n.ai("transB"):
        b = b.T
    y = n.af("alpha", 1.0) * (a @ b)
    if len(i) > 2 and i[2] is not None:
        y = y + n.af("beta", 1.0) * i[2]
    return y


def _reshape(i, n):
    x, shape = i[0], _static(i[1]).astype(np.int64).tolist()
    out = []
    for d, s in enumerate(shape):
        out.append(x.shape[d] if s == 0 and n.ai("allowzero", 0) == 0 else s)
    return x.reshape(out)


def _slice_op(i, n):
    x = i[0]
    starts = _static(i[1]).ravel().tolist()
    ends = _static(i[2]).ravel().tolist()
    axes = (_static(i[3]).ravel().tolist() if len(i) > 3
            else list(range(len(starts))))
    steps = _static(i[4]).ravel().tolist() if len(i) > 4 else [1] * len(starts)
    idx = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        a = a % x.ndim
        # onnx uses INT64_MAX/MIN sentinels for "to the end"
        e = None if abs(e) >= (1 << 62) else e
        idx[a] = slice(s, e, st)
    return x[tuple(idx)]


def _bn(i, n):
    x, gamma, beta, mean, var = i[:5]
    eps = n.af("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return ((x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + eps)
            * gamma.reshape(shape) + beta.reshape(shape))


def _cast(i, n):
    return i[0].astype(_ONNX_JNP_DTYPES.get(n.ai("to", 1), jnp.float32))


def _reduce(fn, axes_as_input=False):
    def h(i, n):
        if axes_as_input and len(i) > 1:
            axes = tuple(_static(i[1]).ravel().astype(int).tolist())
        else:
            axes = tuple(n.aints("axes")) or None
        return fn(i[0], axis=axes, keepdims=bool(n.ai("keepdims", 1)))
    return h


def _pad_op(i, n):
    x = i[0]
    pads = _static(i[1]).ravel().astype(int).tolist() if len(i) > 1 \
        else n.aints("pads")
    k = x.ndim
    cfg = tuple((pads[d], pads[d + k]) for d in range(k))
    mode = n.astr("mode", "constant")
    if mode == "constant":
        cval = float(_static(i[2])) if len(i) > 2 and i[2] is not None else 0.0
        return jnp.pad(x, cfg, constant_values=cval)
    return jnp.pad(x, cfg, mode={"reflect": "reflect", "edge": "edge"}[mode])


HANDLERS: Dict[str, Any] = {
    # --- elementwise math
    "Add": lambda i, n: i[0] + i[1], "Sub": lambda i, n: i[0] - i[1],
    "Mul": lambda i, n: i[0] * i[1], "Div": lambda i, n: i[0] / i[1],
    "Pow": lambda i, n: jnp.power(i[0], i[1]),
    "Neg": lambda i, n: -i[0], "Abs": lambda i, n: jnp.abs(i[0]),
    "Exp": lambda i, n: jnp.exp(i[0]), "Log": lambda i, n: jnp.log(i[0]),
    "Sqrt": lambda i, n: jnp.sqrt(i[0]),
    "Reciprocal": lambda i, n: 1.0 / i[0],
    "Floor": lambda i, n: jnp.floor(i[0]), "Ceil": lambda i, n: jnp.ceil(i[0]),
    "Round": lambda i, n: jnp.round(i[0]),
    "Sign": lambda i, n: jnp.sign(i[0]),
    "Erf": lambda i, n: lax.erf(i[0]),
    "Min": lambda i, n: _reduce_variadic(jnp.minimum, i),
    "Max": lambda i, n: _reduce_variadic(jnp.maximum, i),
    "Sum": lambda i, n: sum(i),
    "Clip": lambda i, n: jnp.clip(
        i[0],
        None if len(i) < 2 or i[1] is None else i[1],
        None if len(i) < 3 or i[2] is None else i[2]),
    # --- activations
    "Relu": lambda i, n: jax.nn.relu(i[0]),
    "LeakyRelu": lambda i, n: jax.nn.leaky_relu(i[0], n.af("alpha", 0.01)),
    "Elu": lambda i, n: jax.nn.elu(i[0], n.af("alpha", 1.0)),
    "Selu": lambda i, n: jax.nn.selu(i[0]),
    "Celu": lambda i, n: jax.nn.celu(i[0], n.af("alpha", 1.0)),
    "Sigmoid": lambda i, n: jax.nn.sigmoid(i[0]),
    "HardSigmoid": lambda i, n: jnp.clip(
        n.af("alpha", 0.2) * i[0] + n.af("beta", 0.5), 0, 1),
    "Tanh": lambda i, n: jnp.tanh(i[0]),
    "Softmax": lambda i, n: jax.nn.softmax(i[0], axis=n.ai("axis", -1)),
    "LogSoftmax": lambda i, n: jax.nn.log_softmax(i[0], axis=n.ai("axis", -1)),
    "Softplus": lambda i, n: jax.nn.softplus(i[0]),
    "Softsign": lambda i, n: jax.nn.soft_sign(i[0]),
    "Gelu": lambda i, n: jax.nn.gelu(i[0], approximate=n.astr("approximate", "none") == "tanh"),
    "PRelu": lambda i, n: jnp.where(i[0] >= 0, i[0], i[0] * i[1]),
    "Dropout": lambda i, n: i[0],             # inference: identity
    "Identity": lambda i, n: i[0],
    # --- matmul family
    "MatMul": lambda i, n: i[0] @ i[1],
    "Gemm": _gemm,
    # --- conv/pool/norm (NCHW)
    "Conv": _conv,
    "MaxPool": lambda i, n: _pool(i, n, lax.max, -jnp.inf),
    "AveragePool": lambda i, n: _pool(i, n, lax.add, 0.0, average=True),
    "GlobalAveragePool": lambda i, n: jnp.mean(
        i[0], axis=tuple(range(2, i[0].ndim)), keepdims=True),
    "GlobalMaxPool": lambda i, n: jnp.max(
        i[0], axis=tuple(range(2, i[0].ndim)), keepdims=True),
    "BatchNormalization": _bn,
    "LRN": lambda i, n: _lrn(i, n),
    "InstanceNormalization": lambda i, n: _instance_norm(i, n),
    # --- shape ops
    "Reshape": _reshape,
    "Flatten": lambda i, n: i[0].reshape(
        (int(np.prod(i[0].shape[:n.ai("axis", 1)])) or 1, -1)),
    "Transpose": lambda i, n: jnp.transpose(
        i[0], n.aints("perm") or None),
    "Squeeze": lambda i, n: jnp.squeeze(
        i[0], tuple(_static(i[1]).ravel().astype(int).tolist())
        if len(i) > 1 else None),
    "Unsqueeze": lambda i, n: _unsqueeze(
        i[0], _static(i[1]).ravel().astype(int).tolist()
        if len(i) > 1 else n.aints("axes")),
    "Concat": lambda i, n: jnp.concatenate(i, axis=n.ai("axis", 0)),
    "Split": None,                            # handled specially (multi-output)
    "Slice": _slice_op,
    "Gather": lambda i, n: jnp.take(i[0], i[1].astype(jnp.int32),
                                    axis=n.ai("axis", 0)),
    "GatherElements": lambda i, n: jnp.take_along_axis(
        i[0], i[1].astype(jnp.int32), axis=n.ai("axis", 0)),
    "Expand": lambda i, n: jnp.broadcast_to(
        i[0], np.broadcast_shapes(tuple(_static(i[1]).astype(int).tolist()),
                                  i[0].shape)),
    "Tile": lambda i, n: jnp.tile(i[0], tuple(_static(i[1]).astype(int).tolist())),
    "Shape": lambda i, n: jnp.asarray(i[0].shape, jnp.int64),
    "Size": lambda i, n: jnp.asarray(i[0].size, jnp.int64),
    "Pad": _pad_op,
    "Cast": _cast,
    "Where": lambda i, n: jnp.where(i[0], i[1], i[2]),
    "Equal": lambda i, n: i[0] == i[1],
    "Greater": lambda i, n: i[0] > i[1],
    "GreaterOrEqual": lambda i, n: i[0] >= i[1],
    "Less": lambda i, n: i[0] < i[1],
    "LessOrEqual": lambda i, n: i[0] <= i[1],
    "Not": lambda i, n: ~i[0],
    "And": lambda i, n: i[0] & i[1],
    "Or": lambda i, n: i[0] | i[1],
    # --- reductions
    "ReduceMean": _reduce(jnp.mean),
    "ReduceSum": _reduce(jnp.sum, axes_as_input=True),
    "ReduceMax": _reduce(jnp.max),
    "ReduceMin": _reduce(jnp.min),
    "ReduceProd": _reduce(jnp.prod),
    "ReduceL2": _reduce(lambda x, axis, keepdims: jnp.sqrt(
        jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))),
    "ArgMax": lambda i, n: _argminmax(jnp.argmax, i, n),
    "ArgMin": lambda i, n: _argminmax(jnp.argmin, i, n),
    "ConstantOfShape": lambda i, n: jnp.full(
        tuple(_static(i[0]).astype(int).tolist()),
        _tensor_to_np(n.attrs["value"].t).item() if "value" in n.attrs else 0.0),
    "Range": lambda i, n: jnp.arange(_static(i[0]).item(),
                                     _static(i[1]).item(),
                                     _static(i[2]).item()),
}


def _unsqueeze(x, axes):
    # negative axes are relative to the OUTPUT rank (input rank + len(axes))
    out_rank = x.ndim + len(axes)
    for a in sorted(int(a) % out_rank for a in axes):
        x = jnp.expand_dims(x, a)
    return x


def _reduce_variadic(fn, vals):
    out = vals[0]
    for v in vals[1:]:
        out = fn(out, v)
    return out


def _argminmax(fn, i, n):
    out = fn(i[0], axis=n.ai("axis", 0))
    if n.ai("keepdims", 1):
        out = jnp.expand_dims(out, n.ai("axis", 0))
    return out


def _lrn(i, n):
    x = i[0]
    size, alpha = n.ai("size", 5), n.af("alpha", 1e-4)
    beta, bias = n.af("beta", 0.75), n.af("bias", 1.0)
    half = size // 2
    sq = jnp.square(x)
    pad = jnp.pad(sq, ((0, 0), (half, size - 1 - half), (0, 0), (0, 0)))
    acc = sum(pad[:, j:j + x.shape[1]] for j in range(size))
    return x / jnp.power(bias + alpha / size * acc, beta)


def _instance_norm(i, n):
    x, gamma, beta = i[:3]
    eps = n.af("epsilon", 1e-5)
    ax = tuple(range(2, x.ndim))
    mu = x.mean(axis=ax, keepdims=True)
    var = x.var(axis=ax, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mu) * lax.rsqrt(var + eps) * gamma.reshape(shape) + beta.reshape(shape)


# ================================================================= importer
class OnnxImporter:
    def import_graph(self, graph: OnnxGraph, sd: Optional[SameDiff] = None) -> SameDiff:
        sd = sd or SameDiff.create()
        produced: Dict[str, SDVariable] = {}
        const_np: Dict[str, np.ndarray] = {}   # build-time-known values
        consumed = {name for node in graph.nodes for name in node.inputs}
        for name, arr in graph.initializers.items():
            produced[name] = sd.constant(_safe(name), jnp.asarray(arr))
            const_np[name] = arr
        for name, shape in graph.inputs:
            if name not in produced:          # real inputs only, not weights
                produced[name] = sd.placeholder(_safe(name), shape)

        for node in graph.nodes:
            op = node.op_type
            if op == "Constant":
                if "value" in node.attrs:
                    arr = _tensor_to_np(node.attrs["value"].t)
                elif "value_float" in node.attrs:
                    arr = np.float32(node.attrs["value_float"].f)
                elif "value_int" in node.attrs:
                    arr = np.int64(node.attrs["value_int"].i)
                elif "value_ints" in node.attrs:
                    arr = np.asarray(node.attrs["value_ints"].ints, np.int64)
                elif "value_floats" in node.attrs:
                    arr = np.asarray(node.attrs["value_floats"].floats, np.float32)
                else:
                    raise NotImplementedError("Constant without value attr")
                produced[node.outputs[0]] = sd.constant(
                    _safe(node.outputs[0]), jnp.asarray(arr))
                const_np[node.outputs[0]] = np.asarray(arr)
                continue
            if op == "Split":
                x = produced[node.inputs[0]]
                axis = node.ai("axis", 0)
                if len(node.inputs) > 1:
                    name = node.inputs[1]
                    if name not in const_np:
                        raise NotImplementedError(
                            f"Split sizes '{name}' must be a build-time "
                            "constant (initializer or Constant node)")
                    sizes = const_np[name].astype(int).ravel().tolist()
                else:
                    sizes = node.aints("split") or None
                count = len(node.outputs)

                def mk(jj, sizes=sizes, axis=axis, count=count):
                    def fn(xv):
                        if sizes:
                            parts = jnp.split(xv, np.cumsum(sizes)[:-1].tolist(), axis)
                        else:
                            parts = jnp.split(xv, count, axis)
                        return parts[jj]
                    return fn
                for j, out_name in enumerate(node.outputs):
                    produced[out_name] = sd._op(_safe(out_name) + "_op", mk(j), [x])
                    produced[out_name].rename(_safe(out_name))
                continue
            handler = HANDLERS.get(op)
            if handler is None:
                raise NotImplementedError(
                    f"ONNX op '{op}' (node '{node.name}') not mapped; "
                    f"supported: {sorted(k for k, v in HANDLERS.items() if v)}")
            # secondary outputs (e.g. Dropout mask) must not be consumed
            for extra in node.outputs[1:]:
                if extra in consumed:
                    raise NotImplementedError(
                        f"secondary output '{extra}' of op '{op}' is consumed "
                        "downstream — not supported")
            # '' marks a skipped OPTIONAL input: keep its slot as None so
            # later inputs don't shift position (e.g. Clip('x', '', max))
            present = [bool(i) for i in node.inputs]
            ins = [produced[i] for i in node.inputs if i]

            def make_fn(h=handler, nd=node, mask=tuple(present)):
                def fn(*vals):
                    it = iter(vals)
                    full = [next(it) if m else None for m in mask]
                    return h(full, nd)
                return fn

            v = sd._op(_safe(node.outputs[0]) + "_op", make_fn(), ins)
            v.rename(_safe(node.outputs[0]))
            produced[node.outputs[0]] = v
        self.produced = produced
        return sd


def _safe(name: str) -> str:
    return name.replace("/", "_").replace(":", "_").replace(".", "_")


def import_onnx(path_or_bytes, sd: Optional[SameDiff] = None):
    """Load an .onnx file (path or bytes) → (SameDiff, [output SDVariables]).

    Feed the returned graph via ``outputs[0].eval({input_name: array})``;
    input names are sanitised with '/', ':', '.' → '_'.
    """
    if isinstance(path_or_bytes, bytes):
        data = path_or_bytes
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    graph = parse_onnx(data)
    imp = OnnxImporter()
    sd = imp.import_graph(graph, sd)
    outs = [imp.produced[o] for o in graph.outputs]
    return sd, outs
