"""ONNX import (opset-13 core subset) → SameDiff graph.

Reference parity: ``org.nd4j.imports`` / ``samediff-import-onnx`` — the
reference maps ONNX NodeProtos onto SameDiff ops. Here the .onnx file is
decoded with a minimal hand-rolled protobuf wire-format reader (the image
has no ``onnx`` package; field numbers below are fixed by the public
onnx.proto3 schema) and each node becomes a lazy jax op in the SameDiff
graph, so the imported model jits into one XLA program.

Covered ops target the models the reference's importer is used for
(MLPs, CNNs, transformer blocks exported from torch/keras): Gemm/MatMul,
Conv/pooling (NCHW), BatchNormalization, activations, elementwise +
logical ops, reshape/transpose/concat/split/slice/gather, reductions,
Cast/Clip/Pad/Expand/Tile/Where, Constant(OfShape), Dropout(identity).
Unknown ops raise with the op name — loud, not silent.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .samediff import SameDiff, SDVariable

# =========================================================== protobuf reader
# wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


class Msg:
    """Decoded protobuf message: field number → list of raw values."""

    __slots__ = ("fields",)

    def __init__(self, buf: bytes):
        self.fields: Dict[int, List[Any]] = {}
        i, n = 0, len(buf)
        while i < n:
            key, i = _read_varint(buf, i)
            fnum, wtype = key >> 3, key & 7
            if wtype == 0:
                v, i = _read_varint(buf, i)
            elif wtype == 1:
                v = struct.unpack_from("<q", buf, i)[0]
                i += 8
            elif wtype == 2:
                ln, i = _read_varint(buf, i)
                v = buf[i:i + ln]
                i += ln
            elif wtype == 5:
                v = struct.unpack_from("<i", buf, i)[0]
                i += 4
            else:  # pragma: no cover — groups unused by onnx
                raise ValueError(f"unsupported wire type {wtype}")
            self.fields.setdefault(fnum, []).append(v)

    # -- typed accessors ----------------------------------------------------
    def ints(self, f) -> List[int]:
        out = []
        for v in self.fields.get(f, []):
            if isinstance(v, bytes):          # packed repeated varint
                i = 0
                while i < len(v):
                    x, i = _read_varint(v, i)
                    out.append(x)
            else:
                out.append(v)
        return [x - (1 << 64) if x >= (1 << 63) else x for x in out]

    def int(self, f, default=0) -> int:
        vals = self.ints(f)
        return vals[0] if vals else default

    def floats(self, f) -> List[float]:
        out = []
        for v in self.fields.get(f, []):
            if isinstance(v, bytes):          # packed repeated fixed32
                out.extend(struct.unpack(f"<{len(v) // 4}f", v))
            else:                             # fixed32 read as int
                out.append(struct.unpack("<f", struct.pack("<i", v))[0])
        return out

    def doubles(self, f) -> List[float]:
        out = []
        for v in self.fields.get(f, []):
            if isinstance(v, bytes):          # packed repeated fixed64
                out.extend(struct.unpack(f"<{len(v) // 8}d", v))
            else:                             # fixed64 read as int (<q)
                out.append(struct.unpack("<d", struct.pack("<q", v))[0])
        return out

    def float(self, f, default=0.0) -> float:
        vals = self.floats(f)
        return vals[0] if vals else default

    def bytes_(self, f, default=b"") -> bytes:
        vals = self.fields.get(f, [])
        return vals[0] if vals else default

    def str_(self, f, default="") -> str:
        return self.bytes_(f).decode("utf-8") if f in self.fields else default

    def strs(self, f) -> List[str]:
        return [v.decode("utf-8") for v in self.fields.get(f, [])]

    def msg(self, f) -> Optional["Msg"]:
        vals = self.fields.get(f, [])
        return Msg(vals[0]) if vals else None

    def msgs(self, f) -> List["Msg"]:
        return [Msg(v) for v in self.fields.get(f, [])]


# onnx.proto3 field numbers (public, fixed):
#   ModelProto.graph = 7
#   GraphProto: node=1 name=2 initializer=5 input=11 output=12
#   NodeProto: input=1 output=2 name=3 op_type=4 attribute=5
#   AttributeProto: name=1 f=2 i=3 s=4 t=5 floats=7 ints=8 strings=9 type=20
#   TensorProto: dims=1 data_type=2 float_data=4 int32_data=5 string_data=6
#                int64_data=7 name=8 raw_data=9 double_data=10 uint64_data=11
#   ValueInfoProto: name=1 type=2 ; TypeProto.tensor_type=1
#   TypeProto.Tensor: elem_type=1 shape=2 ; TensorShapeProto.dim=1
#   TensorShapeProto.Dimension: dim_value=1 dim_param=2

_ONNX_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
                5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
                10: np.float16, 11: np.float64, 12: np.uint32, 13: np.uint64}
_ONNX_JNP_DTYPES = {**{k: jnp.dtype(v) for k, v in _ONNX_DTYPES.items()},
                    16: jnp.bfloat16}


def _tensor_to_np(t: Msg) -> np.ndarray:
    dims = tuple(t.ints(1))
    dtype_code = t.int(2, 1)
    raw = t.bytes_(9)
    if raw:
        if dtype_code == 16:                  # bfloat16: upcast via uint16 view
            u16 = np.frombuffer(raw, np.uint16)
            arr = (u16.astype(np.uint32) << 16).view(np.float32)
        else:
            arr = np.frombuffer(raw, _ONNX_DTYPES.get(dtype_code, np.float32))
    elif t.floats(4):
        arr = np.asarray(t.floats(4), np.float32)
    elif t.ints(7):
        arr = np.asarray(t.ints(7), np.int64)
    elif t.ints(5):
        arr = np.asarray(t.ints(5), _ONNX_DTYPES.get(dtype_code, np.int32))
    elif t.doubles(10):
        arr = np.asarray(t.doubles(10), np.float64)
    else:
        arr = np.zeros(0, _ONNX_DTYPES.get(dtype_code, np.float32))
    return arr.reshape(dims) if dims else arr.reshape(())


class OnnxAttr:
    def __init__(self, m: Msg):
        self.name = m.str_(1)
        self.f = m.float(2)
        self.i = m.int(3)
        self.s = m.bytes_(4)
        self.t = m.msg(5)
        self.floats = m.floats(7)
        self.ints = m.ints(8)
        self.strings = m.strs(9)


class OnnxNode:
    def __init__(self, m: Msg):
        self.inputs = m.strs(1)
        self.outputs = m.strs(2)
        self.name = m.str_(3) or (self.outputs[0] if self.outputs else "?")
        self.op_type = m.str_(4)
        self.attrs = {a.name: a for a in (OnnxAttr(x) for x in m.msgs(5))}

    # attribute helpers with defaults
    def ai(self, name, default=0):
        a = self.attrs.get(name)
        return a.i if a else default

    def af(self, name, default=0.0):
        a = self.attrs.get(name)
        return a.f if a else default

    def aints(self, name, default=()):
        a = self.attrs.get(name)
        return list(a.ints) if a and a.ints else list(default)

    def astr(self, name, default=""):
        a = self.attrs.get(name)
        return a.s.decode() if a and a.s else default


def _vi_shape(vi: Msg):
    """ValueInfoProto → (name, shape tuple with None for dynamic dims)."""
    name = vi.str_(1)
    tt = vi.msg(2)
    tt = tt.msg(1) if tt else None            # TypeProto.tensor_type
    shape = None
    if tt is not None:
        sh = tt.msg(2)
        if sh is not None:
            dims = []
            for d in sh.msgs(1):
                dv = d.int(1, 0)
                dims.append(dv if dv > 0 else None)
            shape = tuple(dims)
    return name, shape


class OnnxGraph:
    def __init__(self, m: Msg):
        self.name = m.str_(2)
        self.nodes = [OnnxNode(x) for x in m.msgs(1)]
        self.initializers: Dict[str, np.ndarray] = {}
        for t in m.msgs(5):
            self.initializers[t.str_(8)] = _tensor_to_np(t)
        self.inputs = [_vi_shape(v) for v in m.msgs(11)]
        self.outputs = [_vi_shape(v)[0] for v in m.msgs(12)]


def parse_onnx(data: bytes) -> OnnxGraph:
    model = Msg(data)
    g = model.msg(7)
    if g is None:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    return OnnxGraph(g)


# ============================================================== op handlers
def _auto_pad(node, spatial_rank):
    """pads attr [b1..bk, e1..ek] → lax ((b1,e1),...); SAME_* handled by caller."""
    pads = node.aints("pads", [0] * 2 * spatial_rank)
    return tuple((pads[d], pads[d + spatial_rank]) for d in range(spatial_rank))


def _conv(i, n):
    x, w = i[0], i[1]                         # NCHW, OIHW (onnx layout)
    rank = x.ndim - 2
    strides = tuple(n.aints("strides", [1] * rank))
    dil = tuple(n.aints("dilations", [1] * rank))
    groups = n.ai("group", 1)
    ap = n.astr("auto_pad", "NOTSET")
    pad = "SAME" if ap.startswith("SAME") else _auto_pad(n, rank)
    spec = ("NCHW", "OIHW", "NCHW") if rank == 2 else \
        (("NCH", "OIH", "NCH") if rank == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    y = lax.conv_general_dilated(x, w, strides, pad, rhs_dilation=dil,
                                 dimension_numbers=spec,
                                 feature_group_count=groups)
    if len(i) > 2 and i[2] is not None:
        y = y + i[2].reshape((1, -1) + (1,) * rank)
    return y


def _pool(i, n, reducer, init, average=False):
    x = i[0]
    rank = x.ndim - 2
    if n.ai("ceil_mode", 0):
        raise NotImplementedError(
            "onnx_import: ceil_mode=1 pooling is not supported (floor-mode "
            "reduce_window would silently change the output shape)")
    if n.aints("dilations", [1] * rank) != [1] * rank:
        raise NotImplementedError("onnx_import: pooling dilations unsupported")
    k = tuple(n.aints("kernel_shape"))
    strides = tuple(n.aints("strides", [1] * rank))
    ap = n.astr("auto_pad", "NOTSET")
    window = (1, 1) + k
    ws = (1, 1) + strides
    if ap.startswith("SAME"):
        pad = "SAME"
    else:
        pad = ((0, 0), (0, 0)) + _auto_pad(n, rank)
    y = lax.reduce_window(x, init, reducer, window, ws, pad)
    if average:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, ws, pad)
        y = y / cnt if n.ai("count_include_pad", 0) == 0 else \
            y / np.prod(k)
    return y


def _static(v):
    """Materialise an op input that must be a compile-time constant.

    Raises a clear error instead of JAX's TracerArrayConversionError when a
    model feeds a dynamic Shape->...->Reshape chain (e.g. torch dynamic_axes
    exports) into a shape-consuming op.
    """
    if isinstance(v, jax.core.Tracer):
        raise NotImplementedError(
            "onnx_import: this op needs a compile-time-constant input, but got "
            "a traced (data-dependent) value — dynamic shape chains like "
            "Shape->Gather->Reshape are not supported; re-export the model "
            "with static shapes")
    return np.asarray(v)


def _gemm(i, n):
    a, b = i[0], i[1]
    if n.ai("transA"):
        a = a.T
    if n.ai("transB"):
        b = b.T
    y = n.af("alpha", 1.0) * (a @ b)
    if len(i) > 2 and i[2] is not None:
        y = y + n.af("beta", 1.0) * i[2]
    return y


def _reshape(i, n):
    x, shape = i[0], _static(i[1]).astype(np.int64).tolist()
    out = []
    for d, s in enumerate(shape):
        out.append(x.shape[d] if s == 0 and n.ai("allowzero", 0) == 0 else s)
    return x.reshape(out)


def _slice_op(i, n):
    x = i[0]
    starts = _static(i[1]).ravel().tolist()
    ends = _static(i[2]).ravel().tolist()
    axes = (_static(i[3]).ravel().tolist() if len(i) > 3
            else list(range(len(starts))))
    steps = _static(i[4]).ravel().tolist() if len(i) > 4 else [1] * len(starts)
    idx = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        a = a % x.ndim
        # onnx uses INT64_MAX/MIN sentinels for "to the end"
        e = None if abs(e) >= (1 << 62) else e
        idx[a] = slice(s, e, st)
    return x[tuple(idx)]


def _bn(i, n):
    x, gamma, beta, mean, var = i[:5]
    eps = n.af("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return ((x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + eps)
            * gamma.reshape(shape) + beta.reshape(shape))


def _cast(i, n):
    return i[0].astype(_ONNX_JNP_DTYPES.get(n.ai("to", 1), jnp.float32))


def _reduce(fn, axes_as_input=False):
    def h(i, n):
        if axes_as_input and len(i) > 1:
            axes = tuple(_static(i[1]).ravel().astype(int).tolist())
        else:
            axes = tuple(n.aints("axes")) or None
        return fn(i[0], axis=axes, keepdims=bool(n.ai("keepdims", 1)))
    return h


def _pad_op(i, n):
    x = i[0]
    pads = _static(i[1]).ravel().astype(int).tolist() if len(i) > 1 \
        else n.aints("pads")
    k = x.ndim
    cfg = tuple((pads[d], pads[d + k]) for d in range(k))
    mode = n.astr("mode", "constant")
    if mode == "constant":
        cval = float(_static(i[2])) if len(i) > 2 and i[2] is not None else 0.0
        return jnp.pad(x, cfg, constant_values=cval)
    return jnp.pad(x, cfg, mode={"reflect": "reflect", "edge": "edge"}[mode])


_NEAREST_IDX = {
    # ONNX nearest_mode → index computation on the source coordinate x
    "floor": np.floor,
    "ceil": np.ceil,
    "round_prefer_floor": lambda x: np.ceil(x - 0.5),
    "round_prefer_ceil": lambda x: np.floor(x + 0.5),
}


def _resize(i, n):
    """ONNX Resize / Upsample across opsets: Resize-11+ inputs are
    [X, roi?, scales?, sizes?], Resize-10 and Upsample-9 are [X, scales],
    Upsample-7 carries a `scales` float-list attribute. Supports nearest
    (asymmetric, all four nearest_modes) and linear/cubic (half_pixel via
    jax.image.resize, which implements TF2 half-pixel sampling)."""
    x = i[0]
    sizes = None
    if len(i) > 3 and i[3] is not None:
        sizes = _static(i[3]).ravel().astype(np.int64).tolist()
    else:
        scales = None
        if len(i) > 2 and i[2] is not None and np.size(_static(i[2])):
            scales = _static(i[2]).ravel().tolist()     # Resize-11+ slot
        elif len(i) == 2 and i[1] is not None and np.size(_static(i[1])):
            scales = _static(i[1]).ravel().tolist()     # Resize-10/Upsample-9
        elif "scales" in n.attrs:                       # Upsample-7 attr
            scales = list(n.attrs["scales"].floats)
        if scales is not None:
            # spec: output dim = floor(input_dim * scale)
            sizes = [int(np.floor(d * s)) for d, s in zip(x.shape, scales)]
    if sizes is None:
        raise NotImplementedError("Resize needs constant scales or sizes")
    mode = n.astr("mode", "nearest")
    coord = n.astr("coordinate_transformation_mode", "half_pixel")
    if mode == "nearest":
        if coord not in ("asymmetric", "half_pixel"):
            raise NotImplementedError(
                f"Resize nearest with coordinate mode '{coord}'")
        if coord == "asymmetric":
            nearest = n.astr("nearest_mode", "round_prefer_floor")
            if nearest not in _NEAREST_IDX:
                raise NotImplementedError(f"nearest_mode '{nearest}'")
            to_idx = _NEAREST_IDX[nearest]
            out = x
            for ax, (old, new) in enumerate(zip(x.shape, sizes)):
                if new == old:
                    continue
                src = np.arange(new) * (old / new)
                ix = np.clip(to_idx(src).astype(np.int64), 0, old - 1)
                out = jnp.take(out, jnp.asarray(ix), axis=ax)
            return out
        return jax.image.resize(x, tuple(sizes), method="nearest")
    if mode in ("linear", "cubic"):
        if coord not in ("half_pixel", "pytorch_half_pixel"):
            raise NotImplementedError(
                f"Resize {mode} with coordinate mode '{coord}'")
        method = "linear" if mode == "linear" else "cubic"
        return jax.image.resize(x.astype(jnp.float32), tuple(sizes),
                                method=method).astype(x.dtype)
    raise NotImplementedError(f"Resize mode '{mode}'")


HANDLERS: Dict[str, Any] = {
    "Resize": _resize,
    "Upsample": _resize,   # opset<10 alias (scales input or attribute)
    # --- elementwise math
    "Add": lambda i, n: i[0] + i[1], "Sub": lambda i, n: i[0] - i[1],
    "Mul": lambda i, n: i[0] * i[1], "Div": lambda i, n: i[0] / i[1],
    "Pow": lambda i, n: jnp.power(i[0], i[1]),
    "Neg": lambda i, n: -i[0], "Abs": lambda i, n: jnp.abs(i[0]),
    "Exp": lambda i, n: jnp.exp(i[0]), "Log": lambda i, n: jnp.log(i[0]),
    "Sqrt": lambda i, n: jnp.sqrt(i[0]),
    "Reciprocal": lambda i, n: 1.0 / i[0],
    "Floor": lambda i, n: jnp.floor(i[0]), "Ceil": lambda i, n: jnp.ceil(i[0]),
    "Round": lambda i, n: jnp.round(i[0]),
    "Sign": lambda i, n: jnp.sign(i[0]),
    "Erf": lambda i, n: lax.erf(i[0]),
    "Min": lambda i, n: _reduce_variadic(jnp.minimum, i),
    "Max": lambda i, n: _reduce_variadic(jnp.maximum, i),
    "Sum": lambda i, n: sum(i),
    "Clip": lambda i, n: jnp.clip(
        i[0],
        None if len(i) < 2 or i[1] is None else i[1],
        None if len(i) < 3 or i[2] is None else i[2]),
    # --- activations
    "Relu": lambda i, n: jax.nn.relu(i[0]),
    "LeakyRelu": lambda i, n: jax.nn.leaky_relu(i[0], n.af("alpha", 0.01)),
    "Elu": lambda i, n: jax.nn.elu(i[0], n.af("alpha", 1.0)),
    "Selu": lambda i, n: jax.nn.selu(i[0]),
    "Celu": lambda i, n: jax.nn.celu(i[0], n.af("alpha", 1.0)),
    "Sigmoid": lambda i, n: jax.nn.sigmoid(i[0]),
    "HardSigmoid": lambda i, n: jnp.clip(
        n.af("alpha", 0.2) * i[0] + n.af("beta", 0.5), 0, 1),
    "Tanh": lambda i, n: jnp.tanh(i[0]),
    "Softmax": lambda i, n: jax.nn.softmax(i[0], axis=n.ai("axis", -1)),
    "LogSoftmax": lambda i, n: jax.nn.log_softmax(i[0], axis=n.ai("axis", -1)),
    "Softplus": lambda i, n: jax.nn.softplus(i[0]),
    "Softsign": lambda i, n: jax.nn.soft_sign(i[0]),
    "Gelu": lambda i, n: jax.nn.gelu(i[0], approximate=n.astr("approximate", "none") == "tanh"),
    "PRelu": lambda i, n: jnp.where(i[0] >= 0, i[0], i[0] * i[1]),
    "Dropout": lambda i, n: i[0],             # inference: identity
    "Identity": lambda i, n: i[0],
    # --- matmul family
    "MatMul": lambda i, n: i[0] @ i[1],
    "Gemm": _gemm,
    # --- conv/pool/norm (NCHW)
    "Conv": _conv,
    "MaxPool": lambda i, n: _pool(i, n, lax.max, -jnp.inf),
    "AveragePool": lambda i, n: _pool(i, n, lax.add, 0.0, average=True),
    "GlobalAveragePool": lambda i, n: jnp.mean(
        i[0], axis=tuple(range(2, i[0].ndim)), keepdims=True),
    "GlobalMaxPool": lambda i, n: jnp.max(
        i[0], axis=tuple(range(2, i[0].ndim)), keepdims=True),
    "BatchNormalization": _bn,
    "LRN": lambda i, n: _lrn(i, n),
    "InstanceNormalization": lambda i, n: _instance_norm(i, n),
    # --- shape ops
    "Reshape": _reshape,
    "Flatten": lambda i, n: i[0].reshape(
        (int(np.prod(i[0].shape[:n.ai("axis", 1)])) or 1, -1)),
    "Transpose": lambda i, n: jnp.transpose(
        i[0], n.aints("perm") or None),
    "Squeeze": lambda i, n: jnp.squeeze(
        i[0], tuple(_static(i[1]).ravel().astype(int).tolist())
        if len(i) > 1 else None),
    "Unsqueeze": lambda i, n: _unsqueeze(
        i[0], _static(i[1]).ravel().astype(int).tolist()
        if len(i) > 1 else n.aints("axes")),
    "Concat": lambda i, n: jnp.concatenate(i, axis=n.ai("axis", 0)),
    "Split": None,                            # handled specially (multi-output)
    "Slice": _slice_op,
    "Gather": lambda i, n: jnp.take(i[0], i[1].astype(jnp.int32),
                                    axis=n.ai("axis", 0)),
    "GatherElements": lambda i, n: jnp.take_along_axis(
        i[0], i[1].astype(jnp.int32), axis=n.ai("axis", 0)),
    "Expand": lambda i, n: jnp.broadcast_to(
        i[0], np.broadcast_shapes(tuple(_static(i[1]).astype(int).tolist()),
                                  i[0].shape)),
    "Tile": lambda i, n: jnp.tile(i[0], tuple(_static(i[1]).astype(int).tolist())),
    "Shape": lambda i, n: jnp.asarray(i[0].shape, jnp.int64),
    "Size": lambda i, n: jnp.asarray(i[0].size, jnp.int64),
    "Pad": _pad_op,
    "Cast": _cast,
    "Where": lambda i, n: jnp.where(i[0], i[1], i[2]),
    "Equal": lambda i, n: i[0] == i[1],
    "Greater": lambda i, n: i[0] > i[1],
    "GreaterOrEqual": lambda i, n: i[0] >= i[1],
    "Less": lambda i, n: i[0] < i[1],
    "LessOrEqual": lambda i, n: i[0] <= i[1],
    "Not": lambda i, n: ~i[0],
    "And": lambda i, n: i[0] & i[1],
    "Or": lambda i, n: i[0] | i[1],
    # --- reductions
    "ReduceMean": _reduce(jnp.mean),
    "ReduceSum": _reduce(jnp.sum, axes_as_input=True),
    "ReduceMax": _reduce(jnp.max),
    "ReduceMin": _reduce(jnp.min),
    "ReduceProd": _reduce(jnp.prod),
    "ReduceL2": _reduce(lambda x, axis, keepdims: jnp.sqrt(
        jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))),
    "ArgMax": lambda i, n: _argminmax(jnp.argmax, i, n),
    "ArgMin": lambda i, n: _argminmax(jnp.argmin, i, n),
    "ConstantOfShape": lambda i, n: jnp.full(
        tuple(_static(i[0]).astype(int).tolist()),
        _tensor_to_np(n.attrs["value"].t).item() if "value" in n.attrs else 0.0),
    "Range": lambda i, n: jnp.arange(_static(i[0]).item(),
                                     _static(i[1]).item(),
                                     _static(i[2]).item()),
    # --- opset-13 long tail onto the broadened sd_ops registry
    "Einsum": lambda i, n: jnp.einsum(n.astr("equation"), *i),
    "CumSum": lambda i, n: _onnx_cumsum(i[0], int(_static(i[1]).item()),
                                        n.ai("exclusive", 0),
                                        n.ai("reverse", 0)),
    "Mod": lambda i, n: (jnp.fmod(i[0], i[1]) if n.ai("fmod", 0)
                         else jnp.mod(i[0], i[1])),
    "Trilu": lambda i, n: (jnp.triu if n.ai("upper", 1) else jnp.tril)(
        i[0], int(_static(i[1]).item()) if len(i) > 1 and i[1] is not None else 0),
    "HardSwish": lambda i, n: jax.nn.hard_swish(i[0]),
    "Mish": lambda i, n: jax.nn.mish(i[0]),
    "Xor": lambda i, n: i[0] ^ i[1],
    "BitShift": lambda i, n: (jnp.left_shift(i[0], i[1])
                              if n.astr("direction") == "LEFT"
                              else jnp.right_shift(i[0], i[1])),
    "GatherND": lambda i, n: _onnx_gather_nd(i[0], i[1]),
    "ScatterND": lambda i, n: _onnx_scatter_nd(i[0], i[1], i[2]),
    "ScatterElements": lambda i, n: _onnx_scatter_elements(
        i[0], i[1], i[2], n.ai("axis", 0)),
    "OneHot": lambda i, n: _onnx_one_hot(i, n),
    "DepthToSpace": lambda i, n: _depth_to_space_nchw(i[0], n.ai("blocksize", 2),
                                                      n.astr("mode", "DCR")),
    "SpaceToDepth": lambda i, n: _space_to_depth_nchw(i[0], n.ai("blocksize", 2)),
    "ReduceL1": _reduce(lambda x, axis, keepdims: jnp.sum(
        jnp.abs(x), axis=axis, keepdims=keepdims)),
    "ReduceSumSquare": _reduce(lambda x, axis, keepdims: jnp.sum(
        jnp.square(x), axis=axis, keepdims=keepdims)),
    "ReduceLogSumExp": _reduce(lambda x, axis, keepdims:
                               jax.scipy.special.logsumexp(
                                   x, axis=axis, keepdims=keepdims)),
    "IsNaN": lambda i, n: jnp.isnan(i[0]),
    "IsInf": lambda i, n: jnp.isinf(i[0]),
    # --- opset-17/18 long tail (r3: rides the new sd_ops registry entries)
    "DFT": lambda i, n: _onnx_dft(i, n),
    "Shrink": lambda i, n: jnp.where(
        i[0] > n.af("lambd", 0.5), i[0] - n.af("bias", 0.0),
        jnp.where(i[0] < -n.af("lambd", 0.5), i[0] + n.af("bias", 0.0), 0.0)),
    "ThresholdedRelu": lambda i, n: jnp.where(
        i[0] > n.af("alpha", 1.0), i[0], 0.0),
    "MeanVarianceNormalization": lambda i, n: (
        (i[0] - jnp.mean(i[0], tuple(n.aints("axes", (0, 2, 3))),
                         keepdims=True))
        / jnp.sqrt(jnp.var(i[0], tuple(n.aints("axes", (0, 2, 3))),
                           keepdims=True) + 1e-9)),
    "Det": lambda i, n: jnp.linalg.det(i[0]),
}


def _onnx_dft(i, n):
    """ONNX DFT (opset 17 attrs): input (..., 1|2) with trailing real/imag
    dim, optional dft_length input; axis/inverse/onesided attributes.
    Output keeps the trailing complex-pair dim."""
    x = i[0]
    axis = n.ai("axis", 1)
    if axis < 0:
        # ONNX axis is relative to the FULL rank including the trailing
        # real/imag dim, which the complex view below drops
        axis += x.ndim
    dft_len = (None if len(i) < 2 or i[1] is None
               else int(_static(i[1]).item()))
    if x.shape[-1] == 2:
        xc = lax.complex(x[..., 0], x[..., 1])
    else:
        xc = x[..., 0].astype(jnp.complex64)
    if n.ai("inverse", 0):
        y = jnp.fft.ifft(xc, n=dft_len, axis=axis)
    elif n.ai("onesided", 0):
        y = jnp.fft.rfft(jnp.real(xc), n=dft_len, axis=axis)
    else:
        y = jnp.fft.fft(xc, n=dft_len, axis=axis)
    return jnp.stack([jnp.real(y), jnp.imag(y)], axis=-1)


def _onnx_cumsum(x, axis, exclusive, reverse):
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x           # shift: exclusive prefix sum
    if reverse:
        out = jnp.flip(out, axis)
    return out


def _onnx_gather_nd(params, indices):
    from .sd_ops import _gather_nd
    return _gather_nd(params, indices)


def _onnx_scatter_nd(data, indices, updates):
    idx = indices.astype(jnp.int32)
    return data.at[tuple(idx[..., k] for k in range(idx.shape[-1]))].set(updates)


def _onnx_scatter_elements(data, indices, updates, axis):
    return jnp.put_along_axis(data, indices.astype(jnp.int32), updates,
                              axis=axis, inplace=False)


def _onnx_one_hot(i, n):
    indices, depth, values = i[0], int(_static(i[1]).item()), i[2]
    axis = n.ai("axis", -1)
    off, on = values[0], values[1]
    idx = indices.astype(jnp.int32)
    idx = jnp.where(idx < 0, idx + depth, idx)  # ONNX: negatives wrap
    oh = jax.nn.one_hot(idx, depth, axis=axis)
    return oh * (on - off) + off


def _space_to_depth_nchw(x, bs):
    b, c, h, w = x.shape
    x = x.reshape(b, c, h // bs, bs, w // bs, bs)
    return x.transpose(0, 3, 5, 1, 2, 4).reshape(b, c * bs * bs, h // bs, w // bs)


def _depth_to_space_nchw(x, bs, mode="DCR"):
    b, c, h, w = x.shape
    if mode == "DCR":
        x = x.reshape(b, bs, bs, c // (bs * bs), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
    else:  # CRD
        x = x.reshape(b, c // (bs * bs), bs, bs, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(b, c // (bs * bs), h * bs, w * bs)


# ----------------------------------------------------------- RNN ops (multi-
# output). ONNX gate orders: LSTM iofc, GRU zrh; weights are [num_dir,
# gates*hidden, in]. Implemented as lax.scan over time (TPU-friendly static
# shapes); bidirectional runs a reversed second scan.
def _rnn_unsupported(n, kind, peephole=None):
    """Loud-failure invariant: reject inputs/attrs we'd silently miscompute."""
    acts = n.attrs.get("activations")
    defaults = {"LSTM": ["Sigmoid", "Tanh", "Tanh"],
                "GRU": ["Sigmoid", "Tanh"]}[kind]
    if acts and acts.strings not in ([], defaults, defaults * 2):
        raise NotImplementedError(
            f"ONNX {kind}: non-default activations {acts.strings}")
    if n.af("clip", 0.0):
        raise NotImplementedError(f"ONNX {kind}: cell clip not supported")
    if peephole is not None:
        raise NotImplementedError("ONNX LSTM: peephole weights (P) not supported")


def _onnx_lstm(i, n):
    X, W, R = i[0], i[1], i[2]
    B = i[3] if len(i) > 3 and i[3] is not None else None
    if len(i) > 4 and i[4] is not None:
        raise NotImplementedError(
            "ONNX LSTM: per-example sequence_lens not supported (pad-free "
            "batches only) — would silently miscompute padded examples")
    h0 = i[5] if len(i) > 5 and i[5] is not None else None
    c0 = i[6] if len(i) > 6 and i[6] is not None else None
    _rnn_unsupported(n, "LSTM",
                     peephole=i[7] if len(i) > 7 and i[7] is not None else None)
    hidden = R.shape[-1]
    direction = n.astr("direction", "forward")
    num_dir = W.shape[0]

    def run(d, reverse):
        w, r = W[d].T, R[d].T                       # [in,4h], [h,4h]
        b = (B[d][:4 * hidden] + B[d][4 * hidden:]) if B is not None else 0.0
        hi = h0[d] if h0 is not None else jnp.zeros((X.shape[1], hidden), X.dtype)
        ci = c0[d] if c0 is not None else jnp.zeros((X.shape[1], hidden), X.dtype)

        def cell(carry, xt):
            h, c = carry
            z = xt @ w + h @ r + b
            zi, zo, zf, zg = jnp.split(z, 4, axis=-1)   # iofc
            c2 = jax.nn.sigmoid(zf) * c + jax.nn.sigmoid(zi) * jnp.tanh(zg)
            h2 = jax.nn.sigmoid(zo) * jnp.tanh(c2)
            return (h2, c2), h2

        xs = X[::-1] if reverse else X
        (hT, cT), ys = lax.scan(cell, (hi, ci), xs)
        if reverse:
            ys = ys[::-1]
        return ys, hT, cT

    dirs = [run(0, direction == "reverse")]
    if num_dir == 2:
        dirs.append(run(1, True))
    Y = jnp.stack([d[0] for d in dirs], axis=1)     # [seq, num_dir, B, h]
    Y_h = jnp.stack([d[1] for d in dirs], axis=0)
    Y_c = jnp.stack([d[2] for d in dirs], axis=0)
    return Y, Y_h, Y_c


def _onnx_gru(i, n):
    X, W, R = i[0], i[1], i[2]
    B = i[3] if len(i) > 3 and i[3] is not None else None
    if len(i) > 4 and i[4] is not None:
        raise NotImplementedError(
            "ONNX GRU: per-example sequence_lens not supported")
    h0 = i[5] if len(i) > 5 and i[5] is not None else None
    _rnn_unsupported(n, "GRU")
    hidden = R.shape[-1]
    direction = n.astr("direction", "forward")
    num_dir = W.shape[0]
    lbr = n.ai("linear_before_reset", 0)

    def run(d, reverse):
        w, r = W[d].T, R[d].T                       # [in,3h], [h,3h]
        wb = B[d][:3 * hidden] if B is not None else jnp.zeros(3 * hidden, X.dtype)
        rb = B[d][3 * hidden:] if B is not None else jnp.zeros(3 * hidden, X.dtype)
        hi = h0[d] if h0 is not None else jnp.zeros((X.shape[1], hidden), X.dtype)

        def cell(h, xt):
            xz = xt @ w + wb
            hz = h @ r
            z = jax.nn.sigmoid(xz[..., :hidden] + hz[..., :hidden]
                               + rb[:hidden])
            rr = jax.nn.sigmoid(xz[..., hidden:2 * hidden]
                                + hz[..., hidden:2 * hidden]
                                + rb[hidden:2 * hidden])
            if lbr:
                nh = jnp.tanh(xz[..., 2 * hidden:]
                              + rr * (hz[..., 2 * hidden:]
                                      + rb[2 * hidden:]))
            else:
                nh = jnp.tanh(xz[..., 2 * hidden:]
                              + (rr * h) @ r[:, 2 * hidden:]
                              + rb[2 * hidden:])
            h2 = (1 - z) * nh + z * h
            return h2, h2

        xs = X[::-1] if reverse else X
        hT, ys = lax.scan(cell, hi, xs)
        if reverse:
            ys = ys[::-1]
        return ys, hT

    dirs = [run(0, direction == "reverse")]
    if num_dir == 2:
        dirs.append(run(1, True))
    Y = jnp.stack([d[0] for d in dirs], axis=1)
    Y_h = jnp.stack([d[1] for d in dirs], axis=0)
    return Y, Y_h


def _onnx_topk(i, n):
    k = int(_static(i[1]).item())
    axis = n.ai("axis", -1)
    largest = n.ai("largest", 1)
    x = i[0] if largest else -i[0]
    x_last = jnp.moveaxis(x, axis, -1)
    vals, idxs = lax.top_k(x_last, k)
    if not largest:
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idxs, -1, axis).astype(jnp.int64))


# op -> (handler returning tuple, n_outputs_fixed)
MULTI_OUTPUT = {
    "LSTM": _onnx_lstm,
    "GRU": _onnx_gru,
    "TopK": _onnx_topk,
}


def _unsqueeze(x, axes):
    # negative axes are relative to the OUTPUT rank (input rank + len(axes))
    out_rank = x.ndim + len(axes)
    for a in sorted(int(a) % out_rank for a in axes):
        x = jnp.expand_dims(x, a)
    return x


def _reduce_variadic(fn, vals):
    out = vals[0]
    for v in vals[1:]:
        out = fn(out, v)
    return out


def _argminmax(fn, i, n):
    out = fn(i[0], axis=n.ai("axis", 0))
    if n.ai("keepdims", 1):
        out = jnp.expand_dims(out, n.ai("axis", 0))
    return out


def _lrn(i, n):
    x = i[0]
    size, alpha = n.ai("size", 5), n.af("alpha", 1e-4)
    beta, bias = n.af("beta", 0.75), n.af("bias", 1.0)
    half = size // 2
    sq = jnp.square(x)
    pad = jnp.pad(sq, ((0, 0), (half, size - 1 - half), (0, 0), (0, 0)))
    acc = sum(pad[:, j:j + x.shape[1]] for j in range(size))
    return x / jnp.power(bias + alpha / size * acc, beta)


def _instance_norm(i, n):
    x, gamma, beta = i[:3]
    eps = n.af("epsilon", 1e-5)
    ax = tuple(range(2, x.ndim))
    mu = x.mean(axis=ax, keepdims=True)
    var = x.var(axis=ax, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mu) * lax.rsqrt(var + eps) * gamma.reshape(shape) + beta.reshape(shape)


# ================================================================= importer
class OnnxImporter:
    def import_graph(self, graph: OnnxGraph, sd: Optional[SameDiff] = None) -> SameDiff:
        sd = sd or SameDiff.create()
        produced: Dict[str, SDVariable] = {}
        const_np: Dict[str, np.ndarray] = {}   # build-time-known values
        consumed = {name for node in graph.nodes for name in node.inputs}
        for name, arr in graph.initializers.items():
            produced[name] = sd.constant(_safe(name), jnp.asarray(arr))
            const_np[name] = arr
        for name, shape in graph.inputs:
            if name not in produced:          # real inputs only, not weights
                produced[name] = sd.placeholder(_safe(name), shape)

        for node in graph.nodes:
            op = node.op_type
            if op == "Constant":
                if "value" in node.attrs:
                    arr = _tensor_to_np(node.attrs["value"].t)
                elif "value_float" in node.attrs:
                    arr = np.float32(node.attrs["value_float"].f)
                elif "value_int" in node.attrs:
                    arr = np.int64(node.attrs["value_int"].i)
                elif "value_ints" in node.attrs:
                    arr = np.asarray(node.attrs["value_ints"].ints, np.int64)
                elif "value_floats" in node.attrs:
                    arr = np.asarray(node.attrs["value_floats"].floats, np.float32)
                else:
                    raise NotImplementedError("Constant without value attr")
                produced[node.outputs[0]] = sd.constant(
                    _safe(node.outputs[0]), jnp.asarray(arr))
                const_np[node.outputs[0]] = np.asarray(arr)
                continue
            if op == "Split":
                x = produced[node.inputs[0]]
                axis = node.ai("axis", 0)
                if len(node.inputs) > 1:
                    name = node.inputs[1]
                    if name not in const_np:
                        raise NotImplementedError(
                            f"Split sizes '{name}' must be a build-time "
                            "constant (initializer or Constant node)")
                    sizes = const_np[name].astype(int).ravel().tolist()
                else:
                    sizes = node.aints("split") or None
                count = len(node.outputs)

                def mk(jj, sizes=sizes, axis=axis, count=count):
                    def fn(xv):
                        if sizes:
                            parts = jnp.split(xv, np.cumsum(sizes)[:-1].tolist(), axis)
                        else:
                            parts = jnp.split(xv, count, axis)
                        return parts[jj]
                    return fn
                for j, out_name in enumerate(node.outputs):
                    produced[out_name] = sd._op(_safe(out_name) + "_op", mk(j), [x])
                    produced[out_name].rename(_safe(out_name))
                continue
            # ---- build-time constant folding. torch exports RNNs (and
            # dynamic-ish reshapes) behind Shape->Gather->Concat->
            # ConstantOfShape chains; folding them keeps every downstream
            # shape static, which XLA requires anyway.
            if op == "Shape" and node.inputs[0] in produced:
                src = produced[node.inputs[0]]
                shp = const_np[node.inputs[0]].shape \
                    if node.inputs[0] in const_np else src.shape
                if shp is not None and all(
                        isinstance(d, int) and d >= 0 for d in shp):
                    arr = np.asarray(shp, np.int64)
                    produced[node.outputs[0]] = sd.constant(
                        _safe(node.outputs[0]) + "_shape", jnp.asarray(arr))
                    produced[node.outputs[0]].rename(_safe(node.outputs[0]))
                    const_np[node.outputs[0]] = arr
                    continue
            if (op in HANDLERS and HANDLERS[op] is not None
                    and node.inputs and len(node.outputs) == 1
                    and all((not x) or x in const_np for x in node.inputs)):
                vals = [jnp.asarray(const_np[x]) if x else None
                        for x in node.inputs]
                try:
                    folded = np.asarray(HANDLERS[op](vals, node))
                except Exception:
                    folded = None
                if folded is not None:
                    produced[node.outputs[0]] = sd.constant(
                        _safe(node.outputs[0]) + "_folded", jnp.asarray(folded))
                    produced[node.outputs[0]].rename(_safe(node.outputs[0]))
                    const_np[node.outputs[0]] = folded
                    continue
            if op in MULTI_OUTPUT:
                mh = MULTI_OUTPUT[op]
                present = [bool(x) for x in node.inputs]
                ins = [produced[x] for x in node.inputs if x]

                def make_tup(h=mh, nd=node, mask=tuple(present)):
                    def fn(*vals):
                        it = iter(vals)
                        full = [next(it) if m else None for m in mask]
                        return h(full, nd)
                    return fn

                tup = sd._op(_safe(node.outputs[0]) + "_tuple", make_tup(), ins)
                for j, out_name in enumerate(node.outputs):
                    if not out_name:          # optional output, unconsumed
                        continue
                    view = sd._op(_safe(out_name) + "_op",
                                  (lambda jj: lambda t: t[jj])(j), [tup])
                    view.rename(_safe(out_name))
                    produced[out_name] = view
                continue
            handler = HANDLERS.get(op)
            if handler is None:
                raise NotImplementedError(
                    f"ONNX op '{op}' (node '{node.name}') not mapped; "
                    f"supported: {sorted(k for k, v in HANDLERS.items() if v)}")
            # secondary outputs (e.g. Dropout mask) must not be consumed
            for extra in node.outputs[1:]:
                if extra in consumed:
                    raise NotImplementedError(
                        f"secondary output '{extra}' of op '{op}' is consumed "
                        "downstream — not supported")
            # '' marks a skipped OPTIONAL input: keep its slot as None so
            # later inputs don't shift position (e.g. Clip('x', '', max))
            present = [bool(i) for i in node.inputs]
            ins = [produced[i] for i in node.inputs if i]

            def make_fn(h=handler, nd=node, mask=tuple(present)):
                def fn(*vals):
                    it = iter(vals)
                    full = [next(it) if m else None for m in mask]
                    return h(full, nd)
                return fn

            v = sd._op(_safe(node.outputs[0]) + "_op", make_fn(), ins)
            v.rename(_safe(node.outputs[0]))
            produced[node.outputs[0]] = v
        self.produced = produced
        return sd


def _safe(name: str) -> str:
    return name.replace("/", "_").replace(":", "_").replace(".", "_")


def import_onnx(path_or_bytes, sd: Optional[SameDiff] = None):
    """Load an .onnx file (path or bytes) → (SameDiff, [output SDVariables]).

    Feed the returned graph via ``outputs[0].eval({input_name: array})``;
    input names are sanitised with '/', ':', '.' → '_'.
    """
    if isinstance(path_or_bytes, bytes):
        data = path_or_bytes
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    graph = parse_onnx(data)
    imp = OnnxImporter()
    sd = imp.import_graph(graph, sd)
    outs = [imp.produced[o] for o in graph.outputs]
    return sd, outs
