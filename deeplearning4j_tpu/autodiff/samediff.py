"""SameDiff — declarative autodiff graph API, lowered to XLA whole-graph.

Reference parity: ``org.nd4j.autodiff.samediff.SameDiff`` (SDVariable,
placeholders/variables/constants, op namespaces sd.math/sd.nn/..., reverse-
mode ``grad``, TrainingConfig + fit, exec/output sessions).

TPU-first redesign: the reference interprets its op graph node-by-node
through libnd4j. Here the graph is a lightweight symbolic DAG that TRACES to
one JAX function, so execution is `jit(whole_graph)` — XLA fuses and
schedules; gradients come from `jax.grad` of the traced function instead of
the reference's hand-written backprop graph builder. `to_stablehlo()` exports
the compiled module the way the north star demands (SameDiff → StableHLO).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class SDVariable:
    """Symbolic node. Operator overloads build graph nodes (like SDVariable
    arithmetic in the reference)."""

    def __init__(self, sd: "SameDiff", name: str, kind: str, shape=None,
                 dtype=None, op: Optional[Callable] = None,
                 inputs: Sequence["SDVariable"] = (), meta=None):
        self.sd = sd
        self.name = name
        self.kind = kind            # placeholder | variable | constant | op
        self.shape = shape
        self.dtype = dtype
        self.op = op
        self.inputs = list(inputs)
        self.meta = meta            # replay record for serialization

    # --- arithmetic sugar --------------------------------------------------
    def _bin(self, other, fn, opname):
        other = self.sd._wrap(other)
        return self.sd._op(opname, fn, [self, other],
                           meta=("operator", opname))

    def __add__(self, o):
        return self._bin(o, jnp.add, "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin(o, jnp.subtract, "sub")

    def __rsub__(self, o):
        return self.sd._wrap(o)._bin(self, jnp.subtract, "rsub")

    def __mul__(self, o):
        return self._bin(o, jnp.multiply, "mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin(o, jnp.divide, "div")

    def __rtruediv__(self, o):
        return self.sd._wrap(o)._bin(self, jnp.divide, "rdiv")

    def __pow__(self, o):
        return self._bin(o, jnp.power, "pow")

    def __neg__(self):
        return self.sd._op("neg", jnp.negative, [self],
                           meta=("operator", "neg"))

    def __matmul__(self, o):
        return self._bin(o, jnp.matmul, "mmul")

    # --- common methods (SDVariable surface) -------------------------------
    def add(self, o):
        return self.__add__(o)

    def sub(self, o):
        return self.__sub__(o)

    def mul(self, o):
        return self.__mul__(o)

    def div(self, o):
        return self.__truediv__(o)

    def mmul(self, o):
        return self.__matmul__(o)

    def sum(self, *axes, keepdims=False):
        ax = axes if axes else None
        return self.sd._op("sum", lambda x: jnp.sum(x, axis=ax, keepdims=keepdims), [self],
                           meta=("method", "sum", axes, {"keepdims": keepdims}))

    def mean(self, *axes, keepdims=False):
        ax = axes if axes else None
        return self.sd._op("mean", lambda x: jnp.mean(x, axis=ax, keepdims=keepdims), [self],
                           meta=("method", "mean", axes, {"keepdims": keepdims}))

    def std(self, *axes):
        ax = axes if axes else None
        return self.sd._op("std", lambda x: jnp.std(x, axis=ax), [self],
                           meta=("method", "std", axes, {}))

    def max(self, *axes):
        ax = axes if axes else None
        return self.sd._op("max", lambda x: jnp.max(x, axis=ax), [self],
                           meta=("method", "max", axes, {}))

    def min(self, *axes):
        ax = axes if axes else None
        return self.sd._op("min", lambda x: jnp.min(x, axis=ax), [self],
                           meta=("method", "min", axes, {}))

    def argmax(self, axis=-1):
        return self.sd._op("argmax", lambda x: jnp.argmax(x, axis=axis), [self],
                           meta=("method", "argmax", (axis,), {}))

    def reshape(self, *shape):
        return self.sd._op("reshape", lambda x: jnp.reshape(x, shape), [self],
                           meta=("method", "reshape", shape, {}))

    def transpose(self, *axes):
        ax = axes if axes else None
        return self.sd._op("transpose", lambda x: jnp.transpose(x, ax), [self],
                           meta=("method", "transpose", axes, {}))

    def norm2(self, *axes):
        ax = axes if axes else None
        return self.sd._op("norm2", lambda x: jnp.sqrt(jnp.sum(jnp.square(x), axis=ax)), [self],
                           meta=("method", "norm2", axes, {}))

    def rename(self, new_name):
        self.sd._rename(self, new_name)
        return self

    def eval(self, feeds: Optional[dict] = None):
        return self.sd.eval(self, feeds)

    def __repr__(self):
        return f"SDVariable({self.name!r}, {self.kind}, shape={self.shape})"


class _Namespace:
    """Op namespace (sd.math / sd.nn / sd.loss ...)."""

    def __init__(self, sd, table: Dict[str, Callable], ns_name: str = ""):
        self._sd = sd
        self._table = table
        self._name = ns_name

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        fn = self._table.get(name)
        if fn is None:
            raise AttributeError(f"unknown op '{name}'; known: {sorted(self._table)}")

        def make(*args, **kw):
            vars_ = [a for a in args if isinstance(a, SDVariable)]
            # replay record: args with variable positions marked ("$var", i)
            vi = iter(range(len(vars_)))
            pattern = [("$var", next(vi)) if isinstance(a, SDVariable) else a
                       for a in args]

            def apply_fn(*vals):
                it = iter(vals)
                full = [next(it) if isinstance(a, SDVariable) else a for a in args]
                return fn(*full, **kw)

            return self._sd._op(name, apply_fn, vars_,
                                meta=("ns", self._name, name, pattern, kw))
        return make


_MATH = {
    "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log, "log1p": jnp.log1p,
    "sqrt": jnp.sqrt, "square": jnp.square, "sin": jnp.sin, "cos": jnp.cos,
    "tan": jnp.tan, "tanh": jnp.tanh, "sinh": jnp.sinh, "cosh": jnp.cosh,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "erf": jax.scipy.special.erf, "floor": jnp.floor, "ceil": jnp.ceil,
    "round": jnp.round, "sign": jnp.sign, "reciprocal": jnp.reciprocal,
    "pow": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "clip_by_value": jnp.clip, "cumsum": jnp.cumsum, "cumprod": jnp.cumprod,
    "matmul": jnp.matmul, "tensordot": jnp.tensordot, "einsum": jnp.einsum,
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply, "div": jnp.divide,
    "neg": jnp.negative, "isnan": jnp.isnan, "isinf": jnp.isinf,
    "log_sum_exp": jax.scipy.special.logsumexp,
}

_NN = {
    "relu": jax.nn.relu, "relu6": jax.nn.relu6, "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh, "softmax": jax.nn.softmax, "log_softmax": jax.nn.log_softmax,
    "elu": jax.nn.elu, "selu": jax.nn.selu, "gelu": jax.nn.gelu,
    "leaky_relu": jax.nn.leaky_relu, "softplus": jax.nn.softplus,
    "swish": jax.nn.silu, "silu": jax.nn.silu, "mish": jax.nn.mish,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "linear": lambda x, w, b=None: (x @ w + b) if b is not None else x @ w,
    "layer_norm": lambda x, gain, bias=None, eps=1e-5: (
        (x - jnp.mean(x, -1, keepdims=True))
        / jnp.sqrt(jnp.var(x, -1, keepdims=True) + eps) * gain
        + (0 if bias is None else bias)),
    "dropout": lambda x, rate=0.5: x,  # inference no-op (train uses rng version)
    "batch_norm": lambda x, mean, var, gamma, beta, eps=1e-5: (
        (x - mean) / jnp.sqrt(var + eps) * gamma + beta),
    "conv2d": lambda x, w, stride=(1, 1), padding="SAME": lax.conv_general_dilated(
        x, w, tuple(stride), padding, dimension_numbers=("NHWC", "HWIO", "NHWC")),
    "max_pool2d": lambda x, k=(2, 2), s=None, padding="VALID": lax.reduce_window(
        x, -jnp.inf, lax.max, (1, *k, 1), (1, *(s or k), 1), padding),
    "avg_pool2d": lambda x, k=(2, 2), s=None, padding="VALID": lax.reduce_window(
        x, 0.0, lax.add, (1, *k, 1), (1, *(s or k), 1), padding) / (k[0] * k[1]),
    "embedding_lookup": lambda table, ids: jnp.take(table, ids.astype(jnp.int32), axis=0),
    "multi_head_dot_product_attention": None,  # assigned below
}


def _mhdpa(q, k, v, n_heads=1, causal=False):
    b, t, d = q.shape
    hd = d // n_heads
    qh = q.reshape(b, t, n_heads, hd)
    kh = k.reshape(b, t, n_heads, hd)
    vh = v.reshape(b, t, n_heads, hd)
    return jax.nn.dot_product_attention(qh, kh, vh, is_causal=causal).reshape(b, t, d)


_NN["multi_head_dot_product_attention"] = _mhdpa

_LOSS = {
    "softmax_cross_entropy": lambda labels, logits: -jnp.mean(
        jnp.sum(labels * jax.nn.log_softmax(logits, -1), -1)),
    "sparse_softmax_cross_entropy": lambda labels, logits: -jnp.mean(
        jnp.take_along_axis(jax.nn.log_softmax(logits, -1),
                            labels[..., None].astype(jnp.int32), -1)),
    "sigmoid_cross_entropy": lambda labels, logits: jnp.mean(
        jax.nn.relu(logits) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))),
    "mean_squared_error": lambda labels, preds: jnp.mean(jnp.square(preds - labels)),
    "absolute_difference": lambda labels, preds: jnp.mean(jnp.abs(preds - labels)),
    "cosine_distance": lambda a, b: 1.0 - jnp.mean(jnp.sum(
        a * b, -1) / jnp.maximum(jnp.linalg.norm(a, axis=-1)
                                 * jnp.linalg.norm(b, axis=-1), 1e-9)),
    "log_loss": lambda labels, preds, eps=1e-7: -jnp.mean(
        labels * jnp.log(preds + eps) + (1 - labels) * jnp.log(1 - preds + eps)),
    "huber_loss": lambda labels, preds, delta=1.0: jnp.mean(jnp.where(
        jnp.abs(preds - labels) <= delta,
        0.5 * jnp.square(preds - labels),
        delta * (jnp.abs(preds - labels) - 0.5 * delta))),
}


class History:
    """Training record returned by ``SameDiff.fit`` (reference:
    ``org.nd4j.autodiff.listeners.records.History``): per-iteration loss
    curve, per-epoch means, optional per-epoch validation scores."""

    def __init__(self):
        self.loss_curve: List[float] = []
        self.epoch_losses: List[float] = []
        self.validation: List[float] = []

    def final_loss(self):
        return self.loss_curve[-1] if self.loss_curve else None

    def __repr__(self):
        return (f"History(iterations={len(self.loss_curve)}, "
                f"epochs={len(self.epoch_losses)}, "
                f"final_loss={self.final_loss()})")


class TrainingConfig:
    """Reference parity: org.nd4j.autodiff.samediff.TrainingConfig."""

    def __init__(self, updater=None, data_set_feature_mapping=None,
                 data_set_label_mapping=None, l1=0.0, l2=0.0,
                 loss_variables=None):
        from ..train.updaters import Adam
        self.updater = updater or Adam(1e-3)
        self.feature_mapping = data_set_feature_mapping or []
        self.label_mapping = data_set_label_mapping or []
        self.l1 = l1
        self.l2 = l2
        self.loss_variables = loss_variables or []


class SameDiff:
    def __init__(self):
        self._vars: Dict[str, SDVariable] = {}
        self._values: Dict[str, jnp.ndarray] = {}   # variables + constants
        self._counter = 0
        from . import sd_ops
        self.math = _Namespace(self, {**_MATH, **sd_ops.MATH_EXT}, "math")
        self.nn = _Namespace(self, {**_NN, **sd_ops.NN_EXT}, "nn")
        self.loss = _Namespace(self, {**_LOSS, **sd_ops.LOSS_EXT}, "loss")
        # upstream parity: SDBaseOps methods live on SameDiff itself; here
        # they're both a namespace (sd.base.*) and direct attrs (sd.<op>)
        # via __getattr__ below. SDLinalg/SDBitwise/SDRandom/SDCNN/SDRNN/
        # SDImage mirror nd4j's namespace objects.
        self.base = _Namespace(self, sd_ops.BASE, "base")
        self.linalg = _Namespace(self, sd_ops.LINALG, "linalg")
        self.bitwise = _Namespace(self, sd_ops.BITWISE, "bitwise")
        self.random = _Namespace(self, sd_ops.RANDOM, "random")
        self.cnn = _Namespace(self, sd_ops.CNN, "cnn")
        self.rnn = _Namespace(self, sd_ops.RNN, "rnn")
        self.image = _Namespace(self, sd_ops.IMAGE, "image")
        self.fft = _Namespace(self, sd_ops.FFT, "fft")
        self.signal = _Namespace(self, sd_ops.SIGNAL, "signal")
        # `updater` is the training-config field; `assert` is a keyword —
        # the r4 namespaces surface under non-clashing names
        self.updaters = _Namespace(self, sd_ops.UPDATER, "updater")
        self.assertions = _Namespace(self, sd_ops.ASSERT, "assert")
        self.bp = _Namespace(self, sd_ops.BP, "bp")
        # r5: TensorArray family (upstream list ops). The (stack, count)
        # pair threads through graph ops as a regular tuple value.
        self.list = _Namespace(self, sd_ops.LIST, "list")
        self._training_config: Optional[TrainingConfig] = None
        self._loss_vars: List[str] = []
        self._opt_state = None
        self._optimizer = None
        self._compiled = {}
        # True -> fit()'s loss runs under jax.checkpoint (whole-graph
        # activation remat: backward recomputes the forward instead of
        # storing intermediates — the SameDiff counterpart of the layer
        # API's remat_segments, unsegmented because the graph executes as
        # one recursive trace)
        self.remat = False

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    def __getattr__(self, name):
        # SDBaseOps parity: base ops are callable directly on sd (sd.concat,
        # sd.scatter_add, ...) exactly like the upstream SameDiff class.
        if not name.startswith("_"):
            base = self.__dict__.get("base")
            if base is not None and name in base._table:
                return getattr(base, name)
        raise AttributeError(
            f"'SameDiff' object has no attribute {name!r}")

    # ------------------------------------------------------------ node mgmt
    def _fresh(self, base):
        self._counter += 1
        return f"{base}_{self._counter}"

    def _register(self, v: SDVariable):
        if v.name in self._vars:
            raise ValueError(f"duplicate variable name {v.name}")
        self._vars[v.name] = v
        return v

    def _rename(self, v: SDVariable, new):
        del self._vars[v.name]
        if v.name in self._values:
            self._values[new] = self._values.pop(v.name)
        v.name = new
        self._vars[new] = v

    def _wrap(self, value) -> SDVariable:
        if isinstance(value, SDVariable):
            return value
        return self.constant(self._fresh("const"), jnp.asarray(value))

    def _op(self, opname, fn, inputs, meta=None) -> SDVariable:
        return self._register(SDVariable(self, self._fresh(opname), "op",
                                         op=fn, inputs=inputs, meta=meta))

    # ------------------------------------------------------- public surface
    def placeholder(self, name, shape=None, dtype=jnp.float32) -> SDVariable:
        return self._register(SDVariable(self, name, "placeholder", shape, dtype))

    def var(self, name, shape=None, initializer="xavier", value=None,
            dtype=jnp.float32, seed=0) -> SDVariable:
        """Trainable variable (reference: sd.var)."""
        if value is None:
            import zlib

            from ..nn import weights as _w
            fan_in, fan_out = _w.compute_fans(tuple(shape))
            # stable per-name key (process-randomized hash() would make init
            # non-reproducible across runs)
            key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                     zlib.crc32(name.encode()))
            value = _w.get(initializer)(key, tuple(shape), fan_in, fan_out, dtype)
        self._values[name] = jnp.asarray(value, dtype)
        return self._register(SDVariable(self, name, "variable",
                                         tuple(jnp.shape(value)), dtype))

    def constant(self, name, value) -> SDVariable:
        self._values[name] = jnp.asarray(value)
        return self._register(SDVariable(self, name, "constant",
                                         tuple(jnp.shape(value)),
                                         jnp.asarray(value).dtype))

    def variables(self):
        return {n: v for n, v in self._vars.items() if v.kind == "variable"}

    @property
    def params(self):
        """Trainable values, grouped like a network's param table — the
        surface StatsListener/UIServer ratio reporting reads (upstream's
        SameDiff UIListener role)."""
        return {"variables": self._values_snapshot()}

    def get_variable(self, name):
        return self._vars[name]

    # --------------------------------------------------------------- tracing
    def _trace(self, out: SDVariable, var_values: dict, feeds: dict):
        """Iterative post-order evaluation (deep imported graphs — e.g. BERT —
        would blow Python's recursion limit with a recursive walk)."""
        cache: Dict[str, Any] = {}
        stack: List[tuple] = [(out, False)]
        while stack:
            v, expanded = stack.pop()
            if v.name in cache:
                continue
            if v.kind == "placeholder":
                if v.name not in feeds:
                    raise KeyError(f"missing placeholder feed '{v.name}'")
                cache[v.name] = feeds[v.name]
            elif v.kind == "variable":
                cache[v.name] = var_values[v.name]
            elif v.kind == "constant":
                cache[v.name] = self._values[v.name]
            elif expanded:
                cache[v.name] = v.op(*[cache[i.name] for i in v.inputs])
            else:
                stack.append((v, True))
                for i in v.inputs:
                    if i.name not in cache:
                        stack.append((i, False))
        return cache[out.name]

    def make_function(self, outputs, placeholders: Sequence[str]):
        """Lower the graph to a pure fn(var_values, *feeds) → outputs."""
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        outs = [o if isinstance(o, SDVariable) else self._vars[o] for o in outs]

        def fn(var_values, *feed_vals):
            feeds = dict(zip(placeholders, feed_vals))
            vals = [self._trace(o, var_values, feeds) for o in outs]
            return vals[0] if len(vals) == 1 else vals

        return fn

    # ------------------------------------------------------------- execution
    def eval(self, output, feeds: Optional[dict] = None):
        feeds = feeds or {}
        names = sorted(feeds)
        key = (output.name if isinstance(output, SDVariable) else output,
               tuple(names),
               tuple(jnp.shape(feeds[n]) for n in names))
        if key not in self._compiled:
            fn = self.make_function(output, names)
            self._compiled[key] = jax.jit(fn)
        return self._compiled[key](self._values_snapshot(),
                                   *[jnp.asarray(feeds[n]) for n in names])

    output = eval
    exec = eval

    def _values_snapshot(self):
        return {n: self._values[n] for n, v in self._vars.items()
                if v.kind == "variable"}

    def batch_output(self, outputs, feeds):
        names = sorted(feeds)
        fn = jax.jit(self.make_function(outputs, names))
        return fn(self._values_snapshot(), *[jnp.asarray(feeds[n]) for n in names])

    # ------------------------------------------------------------- gradients
    def grad(self, loss, wrt=None, feeds: Optional[dict] = None):
        """Gradients of `loss` w.r.t. variables (reference: sd.grad / calculateGradients)."""
        feeds = feeds or {}
        names = sorted(feeds)
        fn = self.make_function(loss, names)

        def scalar_fn(var_values):
            return fn(var_values, *[jnp.asarray(feeds[n]) for n in names])

        grads = jax.grad(scalar_fn)(self._values_snapshot())
        if wrt is None:
            return grads
        if isinstance(wrt, (str, SDVariable)):
            wrt = [wrt]
        keys = [w.name if isinstance(w, SDVariable) else w for w in wrt]
        return {k: grads[k] for k in keys}

    # ------------------------------------------------------------- training
    def set_training_config(self, config: TrainingConfig):
        self._training_config = config
        return self

    def set_loss_variables(self, *names):
        self._loss_vars = [n.name if isinstance(n, SDVariable) else n for n in names]
        return self

    def fit(self, dataset=None, epochs: int = 1, iterator=None, feeds_fn=None,
            listeners=None, validation_iterator=None, validation_fn=None):
        """Train on a DataSet/iterator using TrainingConfig mappings.

        Returns a `History` (reference:
        ``org.nd4j.autodiff.listeners.records.History`` from SameDiff.fit).
        `listeners` take the nn TrainingListener protocol
        (iteration_done/on_epoch_end); `validation_fn(sd) -> float` (or a
        validation_iterator scored with the training loss) records a
        per-epoch validation metric in the history.
        """
        cfg = self._training_config
        if cfg is None:
            raise ValueError("call set_training_config first")
        if not self._loss_vars:
            raise ValueError("call set_loss_variables first")
        import optax

        from ..train.updaters import build_optimizer
        if self._optimizer is None:
            self._optimizer = build_optimizer(cfg.updater, l1=cfg.l1, l2=cfg.l2)
            if self._opt_state is None:     # may be restored by load()
                self._opt_state = self._optimizer.init(self._values_snapshot())
        ph_names = cfg.feature_mapping + cfg.label_mapping
        step_key = ("__fit_step__", tuple(ph_names), self._loss_vars[0],
                    bool(self.remat))
        if step_key not in self._compiled:
            loss_var = self._vars[self._loss_vars[0]]
            fn = self.make_function(loss_var, ph_names)
            if self.remat:
                fn = jax.checkpoint(fn)
            optimizer = self._optimizer

            @jax.jit
            def step(var_values, opt_state, *feed_vals):
                def lf(vv):
                    return fn(vv, *feed_vals)
                loss, grads = jax.value_and_grad(lf)(var_values)
                updates, opt_state = optimizer.update(grads, opt_state, var_values)
                var_values = optax.apply_updates(var_values, updates)
                return var_values, opt_state, loss

            self._compiled[step_key] = step
        step = self._compiled[step_key]

        data = iterator if iterator is not None else ([dataset] if dataset is not None else None)
        if data is None:
            raise ValueError("provide dataset or iterator")
        listeners = list(listeners or [])
        history = History()
        # same one-step score-fetch deferral as MultiLayerNetwork.fit: when
        # every listener opts in (deferred_score_ok), fetch step k-1's loss
        # while step k runs so the host never stalls the device pipeline
        defer_ok = all(getattr(l, "deferred_score_ok", False)
                       for l in listeners)
        pending = None

        def flush_pending():
            nonlocal pending
            if pending is not None:
                loss_d, it_i, ep_i = pending
                pending = None
                lv = float(loss_d)
                for l in listeners:
                    l.iteration_done(self, it_i, ep_i, lv)

        val_fn = None
        if validation_iterator is not None and validation_fn is None:
            val_fn = jax.jit(self.make_function(
                self._vars[self._loss_vars[0]], ph_names))
        for epoch in range(epochs):
            epoch_losses = []
            for ds in data:
                arrays = [jnp.asarray(a) for a in
                          ([ds.features] if not isinstance(ds.features, list) else ds.features)]
                labels = [jnp.asarray(a) for a in
                          ([ds.labels] if not isinstance(ds.labels, list) else ds.labels)]
                feed_vals = arrays + labels
                vv = self._values_snapshot()
                vv, self._opt_state, loss = step(vv, self._opt_state, *feed_vals)
                self._values.update(vv)
                epoch_losses.append(loss)      # device value; fetched lazily
                self._iter_count = getattr(self, "_iter_count", 0) + 1
                if listeners:
                    if defer_ok:
                        flush_pending()
                        pending = (loss, self._iter_count, epoch)
                    else:
                        lv = float(loss)
                        for l in listeners:
                            l.iteration_done(self, self._iter_count, epoch, lv)
            if hasattr(data, "reset"):
                data.reset()
            flush_pending()
            history.loss_curve.extend(float(l) for l in epoch_losses)
            if epoch_losses:
                history.epoch_losses.append(
                    sum(history.loss_curve[-len(epoch_losses):])
                    / len(epoch_losses))
            if validation_fn is not None:
                history.validation.append(float(validation_fn(self)))
            elif val_fn is not None:
                vs = []
                for ds in validation_iterator:
                    feats = [jnp.asarray(a) for a in (
                        [ds.features] if not isinstance(ds.features, list)
                        else ds.features)]
                    labs = [jnp.asarray(a) for a in (
                        [ds.labels] if not isinstance(ds.labels, list)
                        else ds.labels)]
                    vs.append(float(val_fn(self._values_snapshot(),
                                           *(feats + labs))))
                if hasattr(validation_iterator, "reset"):
                    validation_iterator.reset()
                if vs:
                    history.validation.append(sum(vs) / len(vs))
            for l in listeners:
                if hasattr(l, "on_epoch_end"):
                    l.on_epoch_end(self)
        return history

    def evaluate(self, iterator, output, label_index: int = 0,
                 evaluation=None):
        """Accumulate an Evaluation over an iterator (reference:
        SameDiff.evaluate(DataSetIterator, outputVariable, Evaluation)).
        Features feed via TrainingConfig.feature_mapping; `output` is the
        prediction variable (name or SDVariable); labels come from the
        DataSet's labels (list index `label_index` for MultiDataSet)."""
        cfg = self._training_config
        if cfg is None:
            raise ValueError("call set_training_config first "
                             "(feature_mapping names the input placeholders)")
        if evaluation is None:
            from ..eval.classification import Evaluation as _Eval
            evaluation = _Eval()
        out = output if isinstance(output, SDVariable) else self._vars[output]
        fn = None
        for ds in iterator:
            feats = ([ds.features] if not isinstance(ds.features, list)
                     else ds.features)
            labs = (ds.labels if not isinstance(ds.labels, list)
                    else ds.labels[label_index])
            if fn is None:
                fn = jax.jit(self.make_function(out, cfg.feature_mapping))
            preds = fn(self._values_snapshot(),
                       *[jnp.asarray(a) for a in feats])
            evaluation.eval(labs, preds)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return evaluation

    # ----------------------------------------------------------- control flow
    def lambda_op(self, name, fn, *inputs) -> SDVariable:
        """Arbitrary traceable fn over SDVariable inputs (escape hatch that
        also carries lax control flow into the graph)."""
        return self._op(name, fn, [self._wrap(i) for i in inputs])

    def while_loop(self, cond_fn, body_fn, init) -> SDVariable:
        """lax.while_loop over the traced value of `init` (reference:
        SameDiff.whileLoop, but compiler-friendly — no interpreter loop)."""
        return self._op("while", lambda v: lax.while_loop(cond_fn, body_fn, v),
                        [self._wrap(init)])

    def cond(self, pred, true_fn, false_fn, operand) -> SDVariable:
        return self._op("cond",
                        lambda p, o: lax.cond(p, true_fn, false_fn, o),
                        [self._wrap(pred), self._wrap(operand)])

    def scan(self, f, init, xs) -> SDVariable:
        """lax.scan carried into the graph; returns (carry, ys) tuple value."""
        return self._op("scan", lambda c0, x: lax.scan(f, c0, x),
                        [self._wrap(init), self._wrap(xs)])

    def stop_gradient(self, v) -> SDVariable:
        return self._op("stop_gradient", lax.stop_gradient, [self._wrap(v)])

    # ------------------------------------------------------------- lowering
    def to_jaxpr(self, output, placeholder_shapes: dict):
        names = sorted(placeholder_shapes)
        fn = self.make_function(output, names)
        args = [jnp.zeros(s, jnp.float32) for s in
                (placeholder_shapes[n] for n in names)]
        return jax.make_jaxpr(fn)(self._values_snapshot(), *args)

    def to_stablehlo(self, output, placeholder_shapes: dict) -> str:
        """Whole-graph compile → StableHLO text (the north-star lowering)."""
        names = sorted(placeholder_shapes)
        fn = self.make_function(output, names)
        args = [jnp.zeros(s, jnp.float32) for s in
                (placeholder_shapes[n] for n in names)]
        return jax.jit(fn).lower(self._values_snapshot(), *args).as_text()

    # ---------------------------------------------------------- serialization
    def save(self, path, save_training_config: bool = True,
             save_updater: bool = False):
        """Serialize graph + values (reference: SameDiff.save / FlatBuffers
        sd format; ours is a zip of replay records + npz values).

        Every op node carries a replay record (namespace, op name, const
        args) captured at build time; ops built from raw Python closures
        (`lambda_op`, `while_loop`, `cond`, `scan`, importer internals)
        have none and raise a clear error — lower those graphs with
        `to_stablehlo()` instead.
        """
        import io
        import pickle
        import zipfile
        from pathlib import Path

        unserializable = [v.name for v in self._vars.values()
                          if v.kind == "op" and v.meta is None]
        if unserializable:
            raise ValueError(
                "graph has op nodes without replay records (built via "
                f"lambda_op/control-flow/closures): {unserializable[:8]} — "
                "use to_stablehlo() for a compiler-level artifact instead")
        # topological order (renames can leave dict order non-topological:
        # _rename reinserts the node at the end); iterative DFS — deep
        # chains would blow Python's recursion limit (same reason _trace
        # is iterative)
        ordered, seen = [], set()
        for root in self._vars.values():
            stack = [(root, False)]
            while stack:
                v, expanded = stack.pop()
                if v.name in seen:
                    continue
                if expanded:
                    seen.add(v.name)
                    ordered.append(v)
                else:
                    stack.append((v, True))
                    stack.extend((i, False) for i in v.inputs
                                 if i.name not in seen)
        records = []
        for v in ordered:
            rec = {"name": v.name, "kind": v.kind}
            if v.kind == "placeholder":
                rec["shape"] = v.shape
                rec["dtype"] = np.dtype(v.dtype).name if v.dtype else None
            elif v.kind == "variable":
                rec["dtype"] = np.dtype(v.dtype).name if v.dtype else None
            elif v.kind == "op":
                rec["meta"] = v.meta
                rec["inputs"] = [i.name for i in v.inputs]
            records.append(rec)
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.pkl", pickle.dumps(
                {"records": records, "loss_vars": self._loss_vars}))
            buf = io.BytesIO()
            np.savez(buf, **{n: np.asarray(val)
                             for n, val in self._values.items()})
            zf.writestr("values.npz", buf.getvalue())
            if save_training_config and self._training_config is not None:
                zf.writestr("training.pkl",
                            pickle.dumps(self._training_config))
            if save_updater and self._opt_state is not None:
                zf.writestr("updater.pkl", pickle.dumps(
                    jax.tree_util.tree_map(lambda a: np.asarray(a),
                                           self._opt_state)))
        return path

    _OPERATOR_REPLAY = {
        "add": lambda a, b: a + b, "sub": lambda a, b: a - b,
        "rsub": lambda a, b: a - b, "mul": lambda a, b: a * b,
        "div": lambda a, b: a / b, "rdiv": lambda a, b: a / b,
        "pow": lambda a, b: a ** b, "mmul": lambda a, b: a @ b,
        "neg": lambda a: -a,
    }

    @classmethod
    def load(cls, path) -> "SameDiff":
        """Rebuild a saved graph by replaying its op records."""
        import io
        import pickle
        import zipfile

        with zipfile.ZipFile(path) as zf:
            graph = pickle.loads(zf.read("graph.pkl"))
            values = dict(np.load(io.BytesIO(zf.read("values.npz")),
                                  allow_pickle=False))
            training = (pickle.loads(zf.read("training.pkl"))
                        if "training.pkl" in zf.namelist() else None)
            updater = (pickle.loads(zf.read("updater.pkl"))
                       if "updater.pkl" in zf.namelist() else None)
        sd = cls.create()
        # replay generates fresh op names; advance the counter past every
        # recorded numeric suffix so they can never collide with recorded
        # names registered by earlier replays
        for rec in graph["records"]:
            tail = rec["name"].rsplit("_", 1)
            if len(tail) == 2 and tail[1].isdigit():
                sd._counter = max(sd._counter, int(tail[1]))
        for rec in graph["records"]:
            name, kind = rec["name"], rec["kind"]
            if kind == "placeholder":
                dt = rec.get("dtype")
                sd.placeholder(name, rec.get("shape"),
                               np.dtype(dt) if dt else jnp.float32)
            elif kind == "variable":
                dt = rec.get("dtype")
                sd.var(name, value=values[name],
                       dtype=np.dtype(dt) if dt else jnp.float32)
            elif kind == "constant":
                sd.constant(name, values[name])
            else:
                ins = [sd._vars[i] for i in rec["inputs"]]
                meta = rec["meta"]
                if meta[0] == "operator":
                    v = cls._OPERATOR_REPLAY[meta[1]](*ins)
                elif meta[0] == "method":
                    _, mname, consts, kw = meta
                    v = getattr(ins[0], mname)(*consts, **kw)
                else:   # ("ns", ns_name, op_name, pattern, kw)
                    _, ns_name, op_name, pattern, kw = meta
                    args = [ins[a[1]] if (isinstance(a, tuple) and len(a) == 2
                                          and a[0] == "$var") else a
                            for a in pattern]
                    v = getattr(getattr(sd, ns_name), op_name)(*args, **kw)
                sd._rename(v, name)
        sd._loss_vars = list(graph.get("loss_vars") or [])
        if training is not None:
            sd._training_config = training
        if updater is not None:
            sd._opt_state = jax.tree_util.tree_map(jnp.asarray, updater)
        return sd

    def summary(self) -> str:
        lines = [f"{'name':<24}{'kind':<12}{'shape'}"]
        for n, v in self._vars.items():
            lines.append(f"{n:<24}{v.kind:<12}{v.shape}")
        return "\n".join(lines)
