"""Learning-rate schedules — parity with ``org.nd4j.linalg.schedule.ISchedule``.

Each schedule is a dataclass with ``value_at(iteration, epoch)`` (the DL4J
contract) and ``__call__(step)`` so it plugs straight into optax as a scalar
schedule. DL4J schedules may key on ITERATION or EPOCH (`ScheduleType`);
`to_optax(iters_per_epoch)` converts epoch-typed schedules to step-based.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp


class ScheduleType:
    ITERATION = "iteration"
    EPOCH = "epoch"


@dataclass
class Schedule:
    schedule_type: str = ScheduleType.ITERATION

    def value_at(self, iteration, epoch):
        t = iteration if self.schedule_type == ScheduleType.ITERATION else epoch
        return self._value(t)

    def _value(self, t):  # pragma: no cover — abstract
        raise NotImplementedError

    def to_optax(self, iters_per_epoch: int = 1):
        if self.schedule_type == ScheduleType.EPOCH:
            return lambda step: self._value(step // iters_per_epoch)
        return lambda step: self._value(step)

    def __call__(self, step):
        return self.to_optax()(step)


@dataclass
class FixedSchedule(Schedule):
    value: float = 1e-3

    def _value(self, t):
        return self.value


@dataclass
class StepSchedule(Schedule):
    """lr * decay^floor(t / step)."""

    initial_value: float = 1e-3
    decay_rate: float = 0.1
    step: float = 1000.0

    def _value(self, t):
        return self.initial_value * self.decay_rate ** jnp.floor(t / self.step)


@dataclass
class ExponentialSchedule(Schedule):
    initial_value: float = 1e-3
    gamma: float = 0.99

    def _value(self, t):
        return self.initial_value * self.gamma ** t


@dataclass
class InverseSchedule(Schedule):
    """lr / (1 + gamma*t)^power."""

    initial_value: float = 1e-3
    gamma: float = 0.001
    power: float = 1.0

    def _value(self, t):
        return self.initial_value / (1.0 + self.gamma * t) ** self.power


@dataclass
class PolySchedule(Schedule):
    """lr * (1 - t/maxIter)^power."""

    initial_value: float = 1e-3
    power: float = 1.0
    max_iter: int = 10000

    def _value(self, t):
        frac = jnp.clip(t / self.max_iter, 0.0, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


@dataclass
class SigmoidSchedule(Schedule):
    initial_value: float = 1e-3
    gamma: float = 0.01
    step_size: int = 1000

    def _value(self, t):
        return self.initial_value / (1.0 + jnp.exp(self.gamma * (t - self.step_size)))


@dataclass
class MapSchedule(Schedule):
    """Piecewise-constant: {t: lr}; value holds from each key onward."""

    values: dict = field(default_factory=dict)

    def _value(self, t):
        keys = sorted(self.values)
        out = jnp.asarray(self.values[keys[0]], jnp.float32)
        for k in keys:
            out = jnp.where(t >= k, self.values[k], out)
        return out


@dataclass
class CycleSchedule(Schedule):
    """1cycle: warmup to max_lr, anneal down, final decay (DL4J CycleSchedule)."""

    initial_value: float = 1e-4
    max_value: float = 1e-2
    cycle_length: int = 1000
    annealing_start_fraction: float = 0.9
    annealing_decay: float = 0.1

    def _value(self, t):
        up = self.cycle_length * (1 - self.annealing_start_fraction) / 2
        ann_start = self.cycle_length * self.annealing_start_fraction
        t = jnp.asarray(t, jnp.float32)
        lr_up = self.initial_value + (self.max_value - self.initial_value) * (t / jnp.maximum(up, 1))
        lr_down = self.max_value - (self.max_value - self.initial_value) * jnp.clip(
            (t - up) / jnp.maximum(ann_start - up, 1), 0, 1)
        lr_ann = self.initial_value * self.annealing_decay ** jnp.clip(
            (t - ann_start) / jnp.maximum(self.cycle_length - ann_start, 1), 0, 1)
        return jnp.where(t < up, lr_up, jnp.where(t < ann_start, lr_down, lr_ann))


@dataclass
class WarmupCosineSchedule(Schedule):
    """TPU-era staple (not in DL4J): linear warmup → cosine decay."""

    peak_value: float = 1e-3
    warmup_steps: int = 1000
    total_steps: int = 10000
    end_value: float = 0.0

    def _value(self, t):
        t = jnp.asarray(t, jnp.float32)
        warm = self.peak_value * t / max(self.warmup_steps, 1)
        frac = jnp.clip((t - self.warmup_steps) / max(self.total_steps - self.warmup_steps, 1), 0, 1)
        cos = self.end_value + 0.5 * (self.peak_value - self.end_value) * (1 + jnp.cos(math.pi * frac))
        return jnp.where(t < self.warmup_steps, warm, cos)


def resolve(lr_or_schedule, iters_per_epoch: int = 1):
    """float → constant; Schedule → optax-compatible callable."""
    if isinstance(lr_or_schedule, Schedule):
        return lr_or_schedule.to_optax(iters_per_epoch)
    return lr_or_schedule
