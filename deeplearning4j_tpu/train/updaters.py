"""Updaters — parity with ``org.nd4j.linalg.learning.config.IUpdater`` family.

Each updater is a config dataclass with ``to_optax(iters_per_epoch)`` that
builds the optax GradientTransformation. The DL4J updater names and default
hyperparameters are preserved (Sgd, Adam, AdamW, AMSGrad, Nadam, AdaMax,
AdaDelta, AdaGrad, RmsProp, Nesterovs, NoOp) plus Lion/Lamb as TPU-era bonuses.
Gradient normalization (``GradientNormalization`` enum) composes in front.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp
import optax

from .schedules import Schedule, resolve


@dataclass
class Updater:
    learning_rate: Any = 1e-3  # float or Schedule

    def _lr(self, iters_per_epoch=1):
        return resolve(self.learning_rate, iters_per_epoch)

    def to_optax(self, iters_per_epoch: int = 1) -> optax.GradientTransformation:
        raise NotImplementedError

    def with_lr(self, lr):
        import dataclasses
        return dataclasses.replace(self, learning_rate=lr)


@dataclass
class Sgd(Updater):
    learning_rate: Any = 1e-1  # DL4J Sgd.DEFAULT_LR

    def to_optax(self, iters_per_epoch=1):
        return optax.sgd(self._lr(iters_per_epoch))


@dataclass
class Nesterovs(Updater):
    learning_rate: Any = 0.1
    momentum: Any = 0.9
    accumulator_dtype: Any = None   # e.g. jnp.bfloat16 halves momentum HBM

    def to_optax(self, iters_per_epoch=1):
        return optax.sgd(self._lr(iters_per_epoch), momentum=self.momentum,
                         nesterov=True,
                         accumulator_dtype=self.accumulator_dtype)


@dataclass
class Momentum(Updater):
    learning_rate: Any = 0.1
    momentum: Any = 0.9
    accumulator_dtype: Any = None   # e.g. jnp.bfloat16 halves momentum HBM

    def to_optax(self, iters_per_epoch=1):
        return optax.sgd(self._lr(iters_per_epoch), momentum=self.momentum,
                         nesterov=False,
                         accumulator_dtype=self.accumulator_dtype)


@dataclass
class Adam(Updater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self, iters_per_epoch=1):
        return optax.adam(self._lr(iters_per_epoch), b1=self.beta1, b2=self.beta2,
                          eps=self.epsilon)


@dataclass
class AdamW(Adam):
    weight_decay: float = 1e-2

    def to_optax(self, iters_per_epoch=1):
        return optax.adamw(self._lr(iters_per_epoch), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon, weight_decay=self.weight_decay)


@dataclass
class AMSGrad(Adam):
    def to_optax(self, iters_per_epoch=1):
        return optax.amsgrad(self._lr(iters_per_epoch), b1=self.beta1, b2=self.beta2,
                             eps=self.epsilon)


@dataclass
class Nadam(Adam):
    def to_optax(self, iters_per_epoch=1):
        return optax.nadam(self._lr(iters_per_epoch), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon)


@dataclass
class AdaMax(Adam):
    learning_rate: Any = 2e-3

    def to_optax(self, iters_per_epoch=1):
        return optax.adamax(self._lr(iters_per_epoch), b1=self.beta1, b2=self.beta2,
                            eps=self.epsilon)


@dataclass
class AdaDelta(Updater):
    learning_rate: Any = 1.0  # AdaDelta ignores lr in DL4J; keep 1.0 scale
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self, iters_per_epoch=1):
        return optax.adadelta(self._lr(iters_per_epoch), rho=self.rho, eps=self.epsilon)


@dataclass
class AdaGrad(Updater):
    learning_rate: Any = 1e-1
    epsilon: float = 1e-6

    def to_optax(self, iters_per_epoch=1):
        return optax.adagrad(self._lr(iters_per_epoch), eps=self.epsilon)


@dataclass
class RmsProp(Updater):
    learning_rate: Any = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self, iters_per_epoch=1):
        return optax.rmsprop(self._lr(iters_per_epoch), decay=self.rms_decay,
                             eps=self.epsilon)


@dataclass
class NoOp(Updater):
    def to_optax(self, iters_per_epoch=1):
        return optax.set_to_zero()


@dataclass
class Lion(Updater):
    learning_rate: Any = 1e-4
    beta1: float = 0.9
    beta2: float = 0.99
    weight_decay: float = 0.0

    def to_optax(self, iters_per_epoch=1):
        return optax.lion(self._lr(iters_per_epoch), b1=self.beta1, b2=self.beta2,
                          weight_decay=self.weight_decay)


@dataclass
class Lamb(Updater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-6
    weight_decay: float = 0.0

    def to_optax(self, iters_per_epoch=1):
        return optax.lamb(self._lr(iters_per_epoch), b1=self.beta1, b2=self.beta2,
                          eps=self.epsilon, weight_decay=self.weight_decay)


# --- gradient normalization (org.deeplearning4j.nn.conf.GradientNormalization)

class GradientNormalization:
    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalize_l2_per_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalize_l2_per_param_type"
    CLIP_ELEMENT_WISE_ABSOLUTE_VALUE = "clip_element_wise_absolute_value"
    CLIP_L2_PER_LAYER = "clip_l2_per_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_per_param_type"


def jax_tree_map(fn, tree):
    import jax
    return jax.tree_util.tree_map(fn, tree)


def gradient_normalization(kind: str, threshold: float = 1.0) -> optax.GradientTransformation:
    """Build the optax transform for a GradientNormalization enum value.

    Per-layer == per-leaf here (our params are one leaf per parameter array,
    grouped by layer), matching DL4J's per-layer semantics closely enough for
    training parity; exact per-param-type uses the same leaf granularity.
    """
    kind = (kind or "none").lower()
    if kind == GradientNormalization.NONE:
        return optax.identity()
    if kind in (GradientNormalization.RENORMALIZE_L2_PER_LAYER,
                GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE):
        def renorm(u):
            n = jnp.sqrt(jnp.sum(jnp.square(u)))
            return u / jnp.maximum(n, 1e-8)
        return _map_transform(renorm)
    if kind == GradientNormalization.CLIP_ELEMENT_WISE_ABSOLUTE_VALUE:
        return optax.clip(threshold)
    if kind in (GradientNormalization.CLIP_L2_PER_LAYER,
                GradientNormalization.CLIP_L2_PER_PARAM_TYPE):
        def clipl2(u):
            n = jnp.sqrt(jnp.sum(jnp.square(u)))
            return jnp.where(n > threshold, u * (threshold / jnp.maximum(n, 1e-8)), u)
        return _map_transform(clipl2)
    raise ValueError(f"Unknown gradient normalization: {kind}")


def _map_transform(fn):
    def init(params):
        return optax.EmptyState()

    def update(updates, state, params=None):
        return jax_tree_map(fn, updates), state

    return optax.GradientTransformation(init, update)


def global_norm_clip(max_norm: float) -> optax.GradientTransformation:
    return optax.clip_by_global_norm(max_norm)


def build_optimizer(updater: Updater, *, grad_norm: str = "none",
                    grad_norm_threshold: float = 1.0,
                    l1: float = 0.0, l2: float = 0.0,
                    weight_decay: float = 0.0,
                    iters_per_epoch: int = 1,
                    param_labels=None, per_label_updaters=None
                    ) -> optax.GradientTransformation:
    """Compose: grad-norm → L1/L2 regularization gradients → updater.

    DL4J applies l1/l2 as loss-gradient additions before the updater — we do
    the same (additive grad), which matches `Regularization.applyStep`.
    `param_labels`/`per_label_updaters` implement per-layer updater overrides
    via optax.multi_transform.
    """
    chain = [gradient_normalization(grad_norm, grad_norm_threshold)]
    if l2:
        chain.append(optax.add_decayed_weights(l2))
    if l1:
        def l1_grad(u, p):
            return u + l1 * jnp.sign(p)

        def init(params):
            return optax.EmptyState()

        def update(updates, state, params=None):
            import jax
            return jax.tree_util.tree_map(l1_grad, updates, params), state
        chain.append(optax.GradientTransformation(init, update))
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay))
    if param_labels is not None and per_label_updaters:
        transforms = {k: u.to_optax(iters_per_epoch) for k, u in per_label_updaters.items()}
        chain.append(optax.multi_transform(transforms, param_labels))
    else:
        chain.append(updater.to_optax(iters_per_epoch))
    return optax.chain(*chain)
