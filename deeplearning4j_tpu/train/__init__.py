"""deeplearning4j_tpu.train — updaters, schedules, gradient handling."""

from .constraints import (MaxNormConstraint, MinMaxNormConstraint,
                          NonNegativeConstraint, UnitNormConstraint,
                          apply_constraints)
from .schedules import (CycleSchedule, ExponentialSchedule, FixedSchedule,
                        InverseSchedule, MapSchedule, PolySchedule, Schedule,
                        ScheduleType, SigmoidSchedule, StepSchedule,
                        WarmupCosineSchedule)
from .updaters import (AMSGrad, AdaDelta, AdaGrad, AdaMax, Adam, AdamW,
                       GradientNormalization, Lamb, Lion, Momentum, Nadam,
                       Nesterovs, NoOp, RmsProp, Sgd, Updater,
                       build_optimizer, gradient_normalization)
